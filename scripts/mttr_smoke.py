"""CI gate for the self-healing control plane (ISSUE 17).

Three fast legs over loopback fixtures:

A. **Stall → hedge**: every seeder stalls each upload past the anomaly
   window (but under the io-timeout floor, so nothing strikes). The
   zero-progress detector must fire within 2x ZEST_ANOMALY_WINDOW_S of
   the first injected fault, the mapped remediation (arm the mid-flight
   hedge) must execute EXACTLY once with outcome=success carrying
   before/after series, the hedge counters must move (shared
   ``hedges``/``hedges_won`` accounting), and the landed files must be
   byte-identical to the fixture.
B. **dcn_reset → abort ladder**: a 2-host cooperative round whose
   exchange channel dies on the first request must abort mid-round and
   degrade the missing units to the CDN — byte-identical recovery from
   a hard collective fault.
C. **Dry-run**: leg A re-run under ZEST_REMEDIATE_DRY=1 — decisions
   are logged (outcome=dry_run) but ZERO actions execute: no hedge
   armed, counters untouched.

Usage: python scripts/mttr_smoke.py [--mb 24]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import tempfile
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                       / "tests"))

WINDOW_S = 0.6
STALL_S = 1.5
os.environ.setdefault("ZEST_TIMELINE_HZ", "10")
os.environ.setdefault("ZEST_ANOMALY_WINDOW_S", str(WINDOW_S))


def fail(msg: str, blob=None) -> int:
    print(f"MTTR SMOKE FAILED: {msg}", file=sys.stderr)
    if blob is not None:
        print(json.dumps(blob, indent=2, default=str), file=sys.stderr)
    return 1


def events(kind: str) -> list[dict]:
    from zest_tpu.telemetry import recorder

    return [e for e in recorder.tail() if e.get("kind") == kind]


def stall_leg(rootp: pathlib.Path, files: dict, repo_id: str, hub,
              ports: list[int], tag: str, dry_run: bool):
    """One policy-on pull against all-stalled seeders; returns
    (PullResult, corrupt_bytes)."""
    from zest_tpu import faults, telemetry
    from zest_tpu.config import Config
    from zest_tpu.transfer.pull import pull_model
    from zest_tpu.transfer.swarm import SwarmDownloader

    os.environ["ZEST_REMEDIATE"] = "1"
    if dry_run:
        os.environ["ZEST_REMEDIATE_DRY"] = "1"
    else:
        os.environ.pop("ZEST_REMEDIATE_DRY", None)
    telemetry.reset_all()
    faults.install(f"seeder_stall:1.0@{STALL_S}", 1337)
    try:
        cfg = Config(hf_home=rootp / f"{tag}/hf",
                     cache_dir=rootp / f"{tag}/zest",
                     hf_token="hf_test", endpoint=hub.url)
        swarm = SwarmDownloader(cfg)
        for p in ports:
            swarm.add_direct_peer("127.0.0.1", p)
        try:
            res = pull_model(cfg, repo_id, swarm=swarm,
                             log=lambda *a, **k: None)
        finally:
            swarm.close()
        bad = sum(1 for name, want in files.items()
                  if (res.snapshot_dir / name).read_bytes() != want)
        return res, bad
    finally:
        faults.install(None)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=float, default=24.0)
    args = ap.parse_args()

    import fixtures
    import zest_tpu.transfer.bridge as bridge_mod
    from zest_tpu import faults, telemetry
    from zest_tpu.bench_scale import llama_checkpoint_files
    from zest_tpu.cas.hub import HubClient
    from zest_tpu.config import Config
    from zest_tpu.transfer.bridge import XetBridge
    from zest_tpu.transfer.coop import coop_round
    from zest_tpu.transfer.dcn import DcnServer
    from zest_tpu.transfer.pull import pull_model
    from zest_tpu.transfer.server import BtServer

    # Keep the hedge's peer head start under the anomaly window:
    # otherwise every hedged wave opens with a window-length zero-rate
    # gap, the stall episode re-arms, and the (idempotent) re-arm
    # decision breaks the exactly-once assertion below.
    bridge_mod._HEDGE_EVIDENCE_WAIT_S = 0.25

    files = llama_checkpoint_files(args.mb / 1024.0, scale=8,
                                   smooth=True,
                                   shard_bytes=8 * 1024 * 1024)
    repo_id = "smoke/mttr"
    repo = fixtures.FixtureRepo(repo_id, files, chunks_per_xorb=8)
    quiet = {"log": lambda *a, **k: None}

    with tempfile.TemporaryDirectory() as root, \
            fixtures.FixtureHub(repo) as hub:
        rootp = pathlib.Path(root)

        # Two warm seeders (faults land only on the measured pulls).
        scfgs = []
        for i in range(2):
            cfg = Config(hf_home=rootp / f"seed{i}/hf",
                         cache_dir=rootp / f"seed{i}/zest",
                         hf_token="hf_test", endpoint=hub.url,
                         listen_port=0)
            pull_model(cfg, repo_id, no_p2p=True, **quiet)
            scfgs.append(cfg)
        servers = [BtServer(cfg) for cfg in scfgs]
        ports = [s.start() for s in servers]

        try:
            # — Leg A: stall → detection → hedge, exactly once. —
            t0 = time.time()
            res, bad = stall_leg(rootp, files, repo_id, hub, ports,
                                 "pullA", dry_run=False)
            if bad:
                return fail(f"leg A: {bad} landed files differ from "
                            "the fixture")
            anomalies = [e for e in events("anomaly")
                         if e.get("anomaly") == "stall"]
            if not anomalies:
                return fail("leg A: injected stall never fired the "
                            "zero-progress detector", events("fault_fired"))
            faults_t = [e["t"] for e in events("fault_fired")]
            detect_lag = anomalies[0]["t"] - (min(faults_t) if faults_t
                                              else t0)
            if detect_lag > 2 * WINDOW_S:
                return fail(f"leg A: detection lag {detect_lag:.2f}s "
                            f"exceeds 2x window ({2 * WINDOW_S}s)",
                            anomalies)
            rems = events("remediation")
            hedges = [e for e in rems if e.get("action") == "hedge"]
            # The ACTION executes exactly once: one arming decision
            # (executed AND already=false); later anomaly episodes may
            # re-decide, but every re-decision must be the idempotent
            # no-op re-arm (already=true) or a rate-limited log line —
            # never a second live action, never a failure.
            arming = [e for e in hedges
                      if e.get("outcome") == "success"
                      and not e.get("detail", {}).get("already")]
            if len(arming) != 1:
                return fail("leg A: expected exactly one ARMING hedge "
                            "remediation with outcome=success", rems)
            if any(e.get("outcome") not in ("success", "rate_limited")
                   for e in hedges):
                return fail("leg A: a hedge re-decision failed", hedges)
            if not isinstance(arming[0].get("before"), dict) \
                    or not isinstance(arming[0].get("after"), dict):
                return fail("leg A: hedge event missing before/after "
                            "series", hedges)
            resil = res.stats.get("fetch", {}).get("resilience", {})
            if not resil.get("hedges") or not resil.get("hedges_won"):
                return fail("leg A: armed hedge moved no "
                            "hedges/hedges_won counters", resil)
            print(f"leg A ok: stall detected {detect_lag:.2f}s after "
                  f"injection, 1 hedge success, "
                  f"hedges={resil['hedges']} won={resil['hedges_won']}")

            # — Leg C: the same faults under dry-run — decisions only. —
            res, bad = stall_leg(rootp, files, repo_id, hub, ports,
                                 "pullC", dry_run=True)
            os.environ.pop("ZEST_REMEDIATE_DRY", None)
            if bad:
                return fail(f"leg C: {bad} landed files differ from "
                            "the fixture")
            rems = events("remediation")
            executed = [e for e in rems
                        if e.get("outcome") in ("success", "failed")]
            dry = [e for e in rems if e.get("outcome") == "dry_run"]
            if executed:
                return fail("leg C: dry-run still EXECUTED actions",
                            executed)
            if not dry:
                return fail("leg C: dry-run logged no decisions", rems)
            resil = res.stats.get("fetch", {}).get("resilience", {})
            if resil.get("hedges"):
                return fail("leg C: dry-run armed a live hedge", resil)
            print(f"leg C ok: {len(dry)} dry-run decision(s), zero "
                  "executed, no hedge armed")
        finally:
            for s in servers:
                s.shutdown()

        # — Leg B: dcn_reset mid-exchange → abort → CDN ladder. —
        telemetry.reset_all()
        faults.install("dcn_reset:1.0", 1337)
        try:
            def mk(i):
                cfg = Config(hf_home=rootp / f"h{i}/hf",
                             cache_dir=rootp / f"h{i}/zest",
                             hf_token="hf_test", endpoint=hub.url,
                             dcn_port=0, coop_collective=True)
                b = XetBridge(cfg)
                b.authenticate(repo_id)
                return b

            b0, b1 = mk(0), mk(1)
            s1 = DcnServer(b1.cfg, b1.cache)
            port1 = s1.start()
            try:
                recs = [b0.get_reconstruction(e.xet_hash)
                        for e in HubClient(b0.cfg).list_files(repo_id)
                        if e.is_xet]
                coop_round(b0, recs, 0, 2, {1: ("127.0.0.1", port1)})
                fired = dict(faults.counters())
                if not fired.get("dcn_reset"):
                    return fail("leg B: dcn_reset never fired", fired)
                out = rootp / "check.bin"
                for e in HubClient(b0.cfg).list_files(repo_id):
                    if e.is_xet:
                        b0.reconstruct_to_file(e.xet_hash, out)
                        if out.read_bytes() != files[e.path]:
                            return fail(f"leg B: {e.path} not "
                                        "byte-identical after the "
                                        "abort ladder")
                print(f"leg B ok: dcn_reset fired "
                      f"{fired['dcn_reset']}x, round degraded and "
                      "landed byte-identical")
            finally:
                s1.shutdown()
                b0.close()
                b1.close()
        finally:
            faults.install(None)
            telemetry.reset_all()

    print("MTTR SMOKE OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
