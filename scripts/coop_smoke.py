"""CI smoke for the cooperative pod-scale pull (ROADMAP item 1).

Runs the 8-device dryrun shape (XLA_FLAGS forces 8 virtual CPU
devices; conftest-style env is set by the CI step): 8 simulated hosts
with isolated caches, loopback DCN servers, one 64 MiB synthetic
Llama-shaped checkpoint. Host 0 runs the REAL ``pull_model`` with
``--device=tpu`` and cooperative mode on; hosts 1..7 run their side of
the round concurrently. Asserts, schema- and content-level:

- ``stats["coop"]["peer_served_ratio"] >= 0.8`` on the pulling host —
  the cooperative win actually happened (7/8 of bytes peer-served by
  construction at 8 hosts);
- the landed HBM param tree is BYTE-IDENTICAL to a solo (non-coop)
  pull of the same repo (models.loader.params_digest) — cooperation
  must never change what lands;
- the exchange carried compressed frames: wire bytes < unpacked bytes
  (the fixture is generated compressible, as real checkpoints are);
- zero exchange fallbacks on the healthy path.

Collective-exchange gates (ISSUE 14) — the 8-host round runs the
plan-derived hypercube schedule by default:

- ``stats["coop"]["collective"]`` shows 3 phases, no abort, and ZERO
  per-unit request round trips, asserted twice: the stats field and
  the wire-tag counter of an injected per-peer DcnPool (every window
  tagged, window count == phases + barrier retries);
- the same ``params_digest`` identity as above covers the collective
  leg (the main pull IS collective now), and the chaos leg asserts a
  ``collective_abort`` flight-recorder event on an injected
  ``dcn_reset`` mid-phase before the CDN fallback heals the round.

Fleet-observability gates (ISSUE 7) — the run is TRACED, and after the
pull the per-host spans merge into ONE Perfetto doc that must show:

- >= 2 host tracks, every host sharing the pull's trace_id;
- cross-host flow links (``dcn.request_many`` -> ``dcn.serve``);
- span coverage >= 90% of each host's root pull/round span;

then an injected ``dcn_reset`` round must leave a NON-EMPTY
flight-recorder dump (fault fired -> fallback, in order).

Exit 0 on success; prints the offending stats block and fails
otherwise.
"""

import json
import pathlib
import sys
import tempfile
import threading

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                       / "tests"))

N_HOSTS = 8
REPO_ID = "smoke/coop-llama"


def main() -> int:
    from fixtures import FixtureHub, FixtureRepo
    from zest_tpu import faults, telemetry
    from zest_tpu.bench_scale import llama_checkpoint_files
    from zest_tpu.cas.hub import HubClient
    from zest_tpu.config import Config
    from zest_tpu.models.loader import params_digest
    from zest_tpu.telemetry import fleet, recorder
    from zest_tpu.telemetry import trace as trace_mod
    from zest_tpu.transfer.bridge import XetBridge
    from zest_tpu.transfer.coop import coop_round
    from zest_tpu.transfer.dcn import DcnPool, DcnServer
    from zest_tpu.transfer.pull import pull_model

    files = llama_checkpoint_files(0.064, shard_bytes=16 * 1024 * 1024,
                                   scale=8, smooth=True)
    repo = FixtureRepo(REPO_ID, files, chunks_per_xorb=32)

    def fail(msg: str, blob=None) -> int:
        print(f"COOP SMOKE FAILED: {msg}", file=sys.stderr)
        if blob is not None:
            print(json.dumps(blob, indent=2, default=str),
                  file=sys.stderr)
        return 1

    with FixtureHub(repo) as hub, tempfile.TemporaryDirectory() as root:
        rootp = pathlib.Path(root)

        def host_cfg(tag: str, i: int) -> Config:
            return Config(hf_home=rootp / f"{tag}{i}/hf",
                          cache_dir=rootp / f"{tag}{i}/zest",
                          hf_token="hf_test", endpoint=hub.url,
                          dcn_port=0)

        # Peer hosts 1..7: bridge + DCN server + their coop_round side.
        peers, servers, addrs = [], [], {}
        for i in range(1, N_HOSTS):
            bridge = XetBridge(host_cfg("coop", i))
            bridge.authenticate(REPO_ID)
            server = DcnServer(bridge.cfg, bridge.cache,
                               span_attrs={"host": i})
            addrs[i] = ("127.0.0.1", server.start())
            peers.append(bridge)
            servers.append(server)
        # Host 0 serves through the DcnServer its own pull starts
        # (coop_round binds one on dcn_port=0 and parks it on the
        # bridge); peers discover it lazily via the retry loop — but a
        # deterministic smoke wants a known addr map up front, so host
        # 0 gets a pre-started server over its cache dir too.
        cfg0 = host_cfg("coop", 0)
        server0 = DcnServer(cfg0, __import__(
            "zest_tpu.storage", fromlist=["XorbCache"]).XorbCache(cfg0),
            span_attrs={"host": 0})
        addrs[0] = ("127.0.0.1", server0.start())
        servers.append(server0)

        # Traced run (ISSUE 7): the pull mints the fleet trace_id from
        # repo@sha (no KV store here, so nonce=""); peers derive the
        # SAME id the same way — the correlation contract under test.
        telemetry.set_enabled(True)
        tracer = trace_mod.install(None)
        sha = HubClient(cfg0).resolve_revision(REPO_ID, "main")
        trace_id = fleet.mint_trace_id(f"{REPO_ID}@{sha}")

        peer_results: list = [None] * N_HOSTS
        peer_errors: list[str] = []
        # Peer 1 runs over an injected pool whose wire-tag counters
        # prove the collective leg's zero-per-unit-round-trip claim.
        tag_pool = DcnPool()

        def run_peer(idx: int, bridge) -> None:
            try:
                recs = [bridge.get_reconstruction(e.xet_hash)
                        for e in HubClient(bridge.cfg).list_files(REPO_ID)
                        if e.is_xet]
                peer_results[idx] = coop_round(
                    bridge, recs, idx, N_HOSTS, addrs,
                    trace_id=trace_id,
                    dcn_pool=tag_pool if idx == 1 else None)
            except Exception as exc:  # noqa: BLE001 - reported below
                peer_errors.append(f"host {idx}: {exc!r}")

        threads = [threading.Thread(target=run_peer, args=(i + 1, b),
                                    daemon=True)
                   for i, b in enumerate(peers)]
        for t in threads:
            t.start()

        res = pull_model(cfg0, REPO_ID, device="tpu", no_p2p=True,
                         coop=True, coop_hosts=N_HOSTS, coop_index=0,
                         coop_addrs=addrs, log=lambda *a, **k: None)
        for t in threads:
            t.join(timeout=180)
        tag_pool.close()
        for s in servers:
            s.shutdown()

        stats = res.stats
        coop = stats.get("coop")
        if peer_errors:
            return fail(f"peer rounds failed: {peer_errors}")
        if not coop or coop.get("skipped"):
            return fail("pull did not run the cooperative round", stats)
        ratio = coop.get("peer_served_ratio", 0.0)
        if ratio < 0.8:
            return fail(f"peer_served_ratio {ratio} < 0.8", coop)
        ex = coop.get("exchange", {})
        if coop.get("fallbacks"):
            return fail(f"{coop['fallbacks']} exchange fallbacks on the "
                        "healthy path", coop)
        if not ex.get("wire_bytes"):
            return fail("no bytes crossed the exchange wire", coop)
        if not ex["wire_bytes"] < ex.get("unpacked_bytes", 0):
            return fail(
                f"exchange wire carried {ex['wire_bytes']} bytes for "
                f"{ex.get('unpacked_bytes')} unpacked — frames were "
                "not compressed on the wire", coop)

        # ── Collective-exchange gates (ISSUE 14) ──
        cx = coop.get("collective")
        if not cx:
            return fail("8-host round did not take the collective "
                        "exchange", coop)
        if cx.get("schedule") != "hypercube" or cx.get("phases") != 3:
            return fail(f"expected a 3-phase hypercube at 8 hosts, got "
                        f"{cx.get('schedule')}/{cx.get('phases')}", cx)
        if cx.get("aborted"):
            return fail(f"collective aborted on the healthy path "
                        f"({cx['aborted']})", cx)
        if cx.get("unit_round_trips") != 0:
            return fail(f"{cx['unit_round_trips']} per-unit round "
                        "trips in the collective leg (want 0)", cx)
        # peer_results is indexed by HOST index (run_peer stores at
        # idx), so enumerate already yields the right host number.
        for i, r in enumerate(peer_results):
            pcx = (r or {}).get("collective") or {}
            if r and (pcx.get("aborted") or not pcx):
                return fail(f"host {i} collective degraded", r)
        # Wire-tag counter: every window peer 1 sent was a tagged
        # batched window — the per-unit request/reply shape never hit
        # the wire — and the window count is exactly phases + barrier
        # retries.
        tc = tag_pool.counters
        pcx = peer_results[1]["collective"]
        if tc["untagged_windows"] != 0:
            return fail(f"{tc['untagged_windows']} untagged windows "
                        "on the collective leg", tc)
        # <= not ==: a phase whose whole block set was already cached
        # (a whole-xorb admit covering sibling units) issues zero
        # windows — fewer windows than phases is fine, more means
        # per-unit round trips crept back.
        if not 0 < tc["windows"] <= pcx["phases"] + pcx["retry_windows"]:
            return fail(f"window count {tc['windows']} outside "
                        f"(0, phases {pcx['phases']} + retries "
                        f"{pcx['retry_windows']}]", tc)
        if not (stats.get("hbm") or {}).get("direct"):
            return fail("coop pull did not take the direct landing",
                        stats.get("hbm"))
        if res.params is None:
            return fail("coop pull landed no params")
        coop_digest = params_digest(res.params)
        res.params = None

        # Solo oracle: same repo, no cooperation, fresh dirs.
        solo = pull_model(host_cfg("solo", 0), REPO_ID, device="tpu",
                          no_p2p=True, coop=False,
                          log=lambda *a, **k: None)
        if solo.params is None:
            return fail("solo pull landed no params")
        solo_digest = params_digest(solo.params)
        solo.params = None
        if coop_digest != solo_digest:
            return fail(f"HBM contents diverge: coop {coop_digest[:16]} "
                        f"vs solo {solo_digest[:16]}")

        # ── Fleet trace gates (ISSUE 7) ──
        if coop.get("trace_id") != trace_id:
            return fail(f"pull trace_id {coop.get('trace_id')} != "
                        f"minted {trace_id}", coop)
        for i, r in enumerate(peer_results):
            if r and r.get("trace_id") != trace_id:
                return fail(f"host {i} trace_id diverged", r)
        doc = tracer.to_chrome()
        per_host = fleet.split_hosts(doc, default_host=0)
        merged = fleet.merge_traces(per_host)
        meta = merged["otherData"]
        if len(meta["merged_hosts"]) < 2:
            return fail(f"merged trace has {meta['merged_hosts']} "
                        "host tracks (< 2)", meta)
        if meta.get("trace_ids") != [trace_id]:
            return fail(f"merged trace_ids {meta.get('trace_ids')} != "
                        f"[{trace_id}]", meta)
        if not meta["flow_links"]:
            return fail("no cross-host dcn.request_many→dcn.serve "
                        "flow links in the merged trace", meta)
        for host in sorted(per_host):
            root_name = "pull" if host == 0 else "coop.round"
            cov, root_s = fleet.host_coverage_s(merged, host, root_name)
            if not root_s or cov < 0.9 * root_s:
                return fail(
                    f"host {host} trace coverage {cov:.2f}s < 90% of "
                    f"its {root_name} span ({root_s:.2f}s)")
        merged_path = rootp / "coop-merged-trace.json"
        merged_path.write_text(json.dumps(merged))

        # ── Flight recorder on an injected dcn_reset round ──
        faults.install("dcn_reset:1.0", seed=1337)
        try:
            chaos, chaos_addrs, chaos_servers = [], {}, []
            for i in range(2):
                b = XetBridge(host_cfg("chaos", i))
                b.authenticate(REPO_ID)
                s = DcnServer(b.cfg, b.cache, span_attrs={"host": i})
                chaos_addrs[i] = ("127.0.0.1", s.start())
                chaos.append(b)
                chaos_servers.append(s)

            def run_chaos(i):
                recs = [chaos[i].get_reconstruction(e.xet_hash)
                        for e in HubClient(chaos[i].cfg)
                        .list_files(REPO_ID) if e.is_xet]
                coop_round(chaos[i], recs, i, 2, chaos_addrs,
                           server=chaos_servers[i])

            ct = [threading.Thread(target=run_chaos, args=(i,),
                                   daemon=True) for i in range(2)]
            for t in ct:
                t.start()
            for t in ct:
                t.join(timeout=120)
            for s in chaos_servers:
                s.shutdown()
        finally:
            faults.reset()
        kinds = [e["kind"] for e in recorder.tail()]
        if "fault_fired" not in kinds or "cdn_fallback" not in kinds:
            return fail(f"flight recorder missed the chaos story: "
                        f"{kinds[-20:]}")
        if "collective_abort" not in kinds:
            return fail("dcn_reset mid-phase left no collective_abort "
                        f"in the flight recorder: {kinds[-20:]}")
        dump_path = recorder.RECORDER.dump(rootp / "recorder.json",
                                           reason="injected dcn_reset")
        dumped = json.loads(pathlib.Path(dump_path).read_text())
        if not dumped["events"]:
            return fail("flight-recorder dump is empty", dumped)

        peer_ratios = [round(r["peer_served_ratio"], 3)
                       for r in peer_results if r]
        print("coop smoke OK: host-0 peer_served_ratio "
              f"{ratio:.3f}, exchange {ex['units']} units / "
              f"{ex['wire_bytes']} wire bytes "
              f"({ex['unpacked_bytes']} unpacked), collective "
              f"{cx['schedule']} x{cx['phases']} phases "
              f"({tc['windows']} tagged windows, 0 per-unit round "
              f"trips), peers "
              f"{peer_ratios}, HBM digest {coop_digest[:16]} == solo; "
              f"merged trace: {len(meta['merged_hosts'])} host tracks, "
              f"{meta['flow_links']} flow links, trace_id {trace_id[:8]}…; "
              f"recorder dump: {len(dumped['events'])} events")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
