"""CI smoke for the push write path + continuous fan-out (ISSUE 19).

A loopback publisher/subscriber pair exercising the full trainer-to-
fleet cycle with zero external network:

- the publisher node pushes checkpoint A (``zest push`` internals:
  gearhash CDC against an empty base, xorbs into its local cache,
  manifest + refs/main), then serves it through its own daemon's
  hub-shaped endpoint surface;
- the subscriber node — an unmodified ``pull_model`` pointed at the
  publisher daemon as its endpoint — cold-pulls A and lands it on the
  (virtual) device mesh;
- the subscriber then subscribes to ``POST /v1/watch``; the publisher
  pushes checkpoint B (1 % of tensors mutated). The push's CDC dedup
  against cached revision A must come out ≥ 0.90, the ``/v1/push``
  notification must reach the watcher, and the watcher's automatic
  delta pull + in-place hot-swap must complete — trainer ``pushed_at``
  → swap-complete is the propagation latency;
- byte identity is asserted file-for-file: the subscriber's rev-B
  snapshot must equal the pushed checkpoint exactly.

Writes ``PUSH_r19.json`` at the repo root (the committed record
``scripts/bench_trend.py`` gates against). Exit 0 on success.
"""

import json
import pathlib
import sys
import tempfile
import threading
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                       / "tests"))

MUTATE_FRACTION = 0.01
DEDUP_GATE = 0.90
PROPAGATION_BOUND_S = 60.0   # loopback; generous for shared CI hosts
REPO = "smoke/push"


def fail(msg: str) -> int:
    print(f"PUSH SMOKE FAILED: {msg}", file=sys.stderr)
    return 1


def write_checkpoint(root: pathlib.Path, name: str,
                     files: dict) -> pathlib.Path:
    d = root / name
    d.mkdir()
    for fname, data in files.items():
        target = d / fname
        target.parent.mkdir(parents=True, exist_ok=True)
        target.write_bytes(data)
    return d


def main() -> int:
    from zest_tpu.api.http_api import HttpApi
    from zest_tpu.bench_scale import llama_checkpoint_files
    from zest_tpu.config import Config
    from zest_tpu.transfer import push as push_mod
    from zest_tpu.transfer.pull import pull_model

    quiet = {"log": lambda *a, **k: None}
    files_a = llama_checkpoint_files(0.032, shard_bytes=8 * 1024 * 1024,
                                     scale=8)
    files_b = llama_checkpoint_files(0.032, shard_bytes=8 * 1024 * 1024,
                                     scale=8,
                                     mutate_fraction=MUTATE_FRACTION)
    total_bytes = sum(len(b) for b in files_b.values())

    with tempfile.TemporaryDirectory() as root:
        rootp = pathlib.Path(root)
        pub_cfg = Config(hf_home=rootp / "hf-pub",
                         cache_dir=rootp / "zest-pub",
                         hf_token="hf_test", http_port=0)
        api = HttpApi(pub_cfg)
        port = api.start()
        url = f"http://127.0.0.1:{port}"
        pub_cfg.http_port_file().parent.mkdir(parents=True, exist_ok=True)
        pub_cfg.http_port_file().write_text(str(port))

        # ── Publish revision A, cold (no base evidence). ──
        ckpt_a = write_checkpoint(rootp, "ckpt_a", files_a)
        res_a = push_mod.push_checkpoint(pub_cfg, REPO, ckpt_a, **quiet)
        print(f"pushed A {res_a.revision[:12]}: {res_a.new_xorbs} xorbs, "
              f"{res_a.new_xorb_bytes:,} bytes")

        # ── Subscriber: unmodified pull against the publisher daemon. ──
        sub_cfg = Config(hf_home=rootp / "hf-sub",
                         cache_dir=rootp / "zest-sub",
                         hf_token="hf_test", endpoint=url)
        res1 = pull_model(sub_cfg, REPO, revision=res_a.revision,
                          device="tpu", no_p2p=True, **quiet)
        for fname, data in files_a.items():
            if (res1.snapshot_dir / fname).read_bytes() != data:
                return fail(f"cold pull of A corrupted {fname}")
        print(f"subscriber cold-pulled A "
              f"({res1.stats.get('total_bytes', total_bytes):,} bytes)")

        # ── Watch + push B; the watcher auto-delta-pulls and swaps. ──
        records: list = []
        errors: list = []

        def watcher():
            try:
                records.extend(push_mod.watch_and_swap(
                    sub_cfg, REPO, publisher_url=url, device="tpu",
                    base_params=res1.params,
                    base_revision=res_a.revision, max_events=1,
                    timeout_s=120.0, no_p2p=True, **quiet))
            except Exception as exc:  # noqa: BLE001 - reported below
                errors.append(exc)

        t = threading.Thread(target=watcher, daemon=True)
        t.start()
        deadline = time.monotonic() + 30
        while api.watch_hub.watchers() == 0:
            if time.monotonic() > deadline:
                return fail("watcher never subscribed")
            time.sleep(0.05)

        ckpt_b = write_checkpoint(rootp, "ckpt_b", files_b)
        res_b = push_mod.push_checkpoint(pub_cfg, REPO, ckpt_b, **quiet)
        print(f"pushed B {res_b.revision[:12]}: dedup "
              f"{res_b.dedup_ratio:.4f}, {res_b.new_xorb_bytes:,} new "
              f"bytes, notified={res_b.notified}")
        t.join(timeout=300)
        if t.is_alive():
            return fail("watcher did not complete its swap in time")
        if errors:
            return fail(f"watcher raised: {errors[0]!r}")

        # ── Gates. ──
        if res_b.parent != res_a.revision:
            return fail("push B did not record A as parent")
        if res_b.reused_bytes <= 0:
            return fail("push B dedup was vacuous (zero reused bytes)")
        if res_b.dedup_ratio < DEDUP_GATE:
            return fail(f"dedup ratio {res_b.dedup_ratio:.4f} < "
                        f"{DEDUP_GATE} at {MUTATE_FRACTION:.0%}-changed")
        if not res_b.notified or res_b.notified.get("delivered") != 1:
            return fail(f"fan-out notification lost: {res_b.notified}")
        if len(records) != 1 or records[0].get("revision") != res_b.revision:
            return fail(f"watcher swap records wrong: {records}")
        rec = records[0]
        propagation = rec.get("propagation_s")
        if propagation is None or propagation > PROPAGATION_BOUND_S:
            return fail(f"propagation {propagation} outside bound "
                        f"{PROPAGATION_BOUND_S}s")
        snap_b = sub_cfg.model_snapshot_dir(REPO, res_b.revision)
        byte_identical = all(
            (snap_b / fname).read_bytes() == data
            for fname, data in files_b.items())
        if not byte_identical:
            return fail("subscriber rev-B snapshot not byte-identical "
                        "to the pushed checkpoint")

        api.close()
        doc = {
            "note": "zest push write path + continuous fan-out "
                    "(ISSUE 19): loopback publisher/subscriber pair; "
                    "regenerate with scripts/push_smoke.py",
            "checkpoint_bytes": total_bytes,
            "mutate_fraction": MUTATE_FRACTION,
            "push": {
                "revision": res_b.revision,
                "parent": res_b.parent,
                "files": res_b.files,
                "new_xorbs": res_b.new_xorbs,
                "new_xorb_bytes": res_b.new_xorb_bytes,
                "reused_bytes": res_b.reused_bytes,
                "dedup_ratio": round(res_b.dedup_ratio, 4),
                "elapsed_s": round(res_b.elapsed_s, 3),
            },
            "fanout": {
                "watchers": 1,
                "propagation_s": round(propagation, 3),
                "time_to_swap_s": rec.get("time_to_swap_s"),
            },
            "gates": {
                "dedup_ratio_ge_0.90": res_b.dedup_ratio >= DEDUP_GATE,
                "byte_identical": byte_identical,
                "watch_delivered": True,
                "propagation_under_bound":
                    propagation <= PROPAGATION_BOUND_S,
                "all_ok": True,
            },
        }
        out = pathlib.Path(__file__).resolve().parent.parent \
            / "PUSH_r19.json"
        out.write_text(json.dumps(doc, indent=2) + "\n")
        print(f"push smoke OK: dedup {res_b.dedup_ratio:.4f}, "
              f"propagation {propagation:.2f}s -> {out.name}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
