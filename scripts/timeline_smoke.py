"""CI gate for the live telemetry timelines (ISSUE 15).

Three properties of the in-process time-series store, proven against a
throttled loopback fixture pull:

1. **Conservation** — the fetch-rate series (one per serving tier,
   derived as counter deltas per sampler tick) must integrate back to
   within 5% of the ``FetchStats`` byte total the pull itself reports:
   the timeline is a *history of the counters*, not an estimate.
2. **Visibility** — an injected mid-pull ``cdn_503`` burst must show up
   as a visible rate dip in the series (the burst window's floor well
   below the clean samples' median): a flapping CDN must be *watchable
   while it happens*, which is the module's reason to exist.
3. **Detection** — a ``seeder_stall`` run (every peer response sleeps
   past the anomaly window) must fire the zero-progress stall detector:
   flight-recorder event + ``zest_anomalies_total{kind=stall}`` +
   session annotation, within 2× ``ZEST_ANOMALY_WINDOW_S``.

Usage: python scripts/timeline_smoke.py [--size BYTES]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import statistics
import sys
import tempfile
import threading
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                       / "tests"))

# Sampler knobs BEFORE any zest import resolves them: 10 Hz ticks and
# a 0.5 s anomaly window keep the smoke's wall clock in seconds.
WINDOW_S = 0.5
os.environ.setdefault("ZEST_TIMELINE_HZ", "10")
os.environ.setdefault("ZEST_ANOMALY_WINDOW_S", str(WINDOW_S))


def fail(msg: str, blob=None) -> int:
    print(f"TIMELINE SMOKE FAILED: {msg}", file=sys.stderr)
    if blob is not None:
        print(json.dumps(blob, indent=2, default=str), file=sys.stderr)
    return 1


def fetch_series(tl_doc: dict) -> dict:
    return {n: s for n, s in tl_doc["series"].items()
            if n.startswith("fetch.")}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=float, default=0.064,
                    help="checkpoint GB (default 0.064 = 64 MiB)")
    args = ap.parse_args()

    from fixtures import FixtureHub, FixtureRepo
    from zest_tpu import faults, telemetry
    from zest_tpu.bench_scale import llama_checkpoint_files
    from zest_tpu.config import Config
    from zest_tpu.telemetry import session as session_mod
    from zest_tpu.telemetry import timeline
    from zest_tpu.transfer.pull import pull_model

    files = llama_checkpoint_files(args.size,
                                   shard_bytes=8 * 1024 * 1024, scale=8)
    repo = FixtureRepo("smoke/timeline", files, chunks_per_xorb=16)
    total_payload = sum(len(v) for v in files.values())

    def settle():
        """Let the sampler take two more ticks so the final counter
        delta lands in the series before we read it."""
        time.sleep(2.5 / timeline.STORE.hz)

    # ── Gate 1: rate series integrate to the FetchStats total ──
    telemetry.reset_all()
    with FixtureHub(repo, throttle_bps=24_000_000) as hub, \
            tempfile.TemporaryDirectory() as root:
        rootp = pathlib.Path(root)
        cfg = Config(hf_home=rootp / "hf", cache_dir=rootp / "zest",
                     hf_token="hf_test", endpoint=hub.url)
        res = pull_model(cfg, "smoke/timeline", no_p2p=True,
                         log=lambda *a, **k: None)
        settle()
        doc = timeline.STORE.payload()
        rates = fetch_series(doc)
        if not rates:
            return fail("no fetch.* rate series sampled",
                        sorted(doc["series"]))
        integrated = sum(timeline.integrate(s["samples"])
                         for s in rates.values())
        fetched = sum(res.stats["fetch"]["bytes"].values())
        if fetched <= 0:
            return fail("pull reports zero fetched bytes", res.stats)
        err = abs(integrated - fetched) / fetched
        if err > 0.05:
            return fail(
                f"rate series integrate to {integrated:.0f} B vs "
                f"FetchStats {fetched} B ({err:.1%} off, gate 5%)",
                {n: len(s["samples"]) for n, s in rates.items()})

    # ── Gate 2: a mid-pull cdn_503 burst is a visible rate dip ──
    telemetry.reset_all()
    burst = {}
    with FixtureHub(repo, throttle_bps=16_000_000) as hub, \
            tempfile.TemporaryDirectory() as root:
        rootp = pathlib.Path(root)
        cfg = Config(hf_home=rootp / "hf", cache_dir=rootp / "zest",
                     hf_token="hf_test", endpoint=hub.url)

        def chaos():
            # Wait for real byte flow, then flap the CDN hard for a
            # bounded window.
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                sessions = session_mod.SESSIONS.active()
                if sessions:
                    f = sessions[0]._fetch
                    if f is not None and f.bytes_from_cdn \
                            > total_payload * 0.15:
                        break
                time.sleep(0.02)
            burst["t0"] = time.time()
            faults.install("cdn_503:0.9", seed=1337)
            time.sleep(1.2)
            faults.reset()
            burst["t1"] = time.time()

        t = threading.Thread(target=chaos, daemon=True)
        t.start()
        pull_model(cfg, "smoke/timeline", no_p2p=True,
                   log=lambda *a, **k: None)
        t.join(timeout=30)
        settle()
        doc = timeline.STORE.payload()
    if "t0" not in burst or "t1" not in burst:
        return fail("chaos thread never saw the pull move bytes")
    cdn = (doc["series"].get("fetch.cdn_bps") or {}).get("samples", [])
    inside = [v for tm, v in cdn if burst["t0"] + 0.2 <= tm
              <= burst["t1"]]
    outside = [v for tm, v in cdn
               if (tm < burst["t0"] or tm > burst["t1"] + 0.3) and v > 0]
    if not inside or len(outside) < 3:
        return fail(f"burst window has {len(inside)} samples, clean "
                    f"window {len(outside)} — pull too fast to judge")
    clean_median = statistics.median(outside)
    dip_floor = min(inside)
    if not dip_floor < 0.5 * clean_median:
        return fail(
            f"cdn_503 burst not visible: burst floor {dip_floor:.0f} "
            f"B/s vs clean median {clean_median:.0f} B/s",
            {"inside": inside, "outside_median": clean_median})

    # ── Gate 3: seeder_stall fires the zero-progress stall detector ──
    telemetry.reset_all()
    from zest_tpu.transfer.server import BtServer
    from zest_tpu.transfer.swarm import SwarmDownloader

    with FixtureHub(repo) as hub, \
            tempfile.TemporaryDirectory() as root:
        rootp = pathlib.Path(root)
        seeder_cfg = Config(hf_home=rootp / "hf-seed",
                            cache_dir=rootp / "zest-seed",
                            hf_token="hf_test", endpoint=hub.url,
                            listen_port=0)
        pull_model(seeder_cfg, "smoke/timeline", no_p2p=True,
                   log=lambda *a, **k: None)
        telemetry.reset_all()  # the warm pull must not be the session
        server = BtServer(seeder_cfg)
        port = server.start()
        faults.install("seeder_stall:1.0@2.0")
        try:
            leech = Config(hf_home=rootp / "hf-leech",
                           cache_dir=rootp / "zest-leech",
                           hf_token="hf_test", endpoint=hub.url,
                           listen_port=0)
            swarm = SwarmDownloader(leech)
            swarm.add_direct_peer("127.0.0.1", port)
            try:
                pull_model(leech, "smoke/timeline", swarm=swarm,
                           log=lambda *a, **k: None)
            finally:
                swarm.close()
            if not faults.counters().get("seeder_stall"):
                return fail("seeder_stall never fired — stall run is "
                            "vacuous", faults.counters())
        finally:
            faults.reset()
            server.shutdown()
        anomalies = timeline.STORE.payload()["anomalies"]
        stalls = [e for e in anomalies if e["kind"] == "stall"]
        if not stalls:
            return fail("stall detector never fired under seeder_stall",
                        anomalies)
        recent = session_mod.payload()["recent"]
        if not recent or stalls[0].get("session") != recent[0]["id"]:
            return fail("stall anomaly not attributed to the pull's "
                        "session", {"anomaly": stalls[0],
                                    "sessions": recent})
        if stalls[0].get("stalled_s", 99) > 2 * WINDOW_S + 0.3:
            return fail(
                f"stall detected too late: {stalls[0]['stalled_s']}s "
                f"vs 2x window {2 * WINDOW_S}s", stalls[0])
        m = [m for m in telemetry.REGISTRY.metrics()
             if m.name == "zest_anomalies_total"]
        if not m or m[0].value(kind="stall") < 1:
            return fail("zest_anomalies_total{kind=stall} not bumped")
        recs = [e for e in telemetry.recorder.tail()
                if e.get("kind") == "anomaly"
                and e.get("anomaly") == "stall"]
        if not recs:
            return fail("no flight-recorder anomaly event")

    print("timeline smoke OK: "
          f"rates integrate to {integrated / fetched:.1%} of "
          f"{fetched} fetched bytes; cdn_503 dip "
          f"{dip_floor / clean_median:.0%} of clean median; "
          f"stall fired at {stalls[0].get('stalled_s')}s "
          f"(window {WINDOW_S}s) on session {stalls[0].get('session')}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
