"""SWARM artifact driver: the fleet-scale chaos capacity model
(ROADMAP item 4, ISSUE 12).

Writes ``SWARM_r12.json``-style artifacts with three sections over one
M-puller x K-seeder loopback swarm served through the production upload
policy (choke/unchoke reciprocity, shaped upload buckets, per-request
deadlines):

- ``clean``        — no faults, unshaped: the ceiling (and the
  solo-pull honesty row: with every seed knob unset the serving path is
  the pre-policy server);
- ``shaped``       — CDN token-bucketed to a WAN-ish shared rate,
  seeders shaped to their upload knob: the asymmetry under which the
  peer tier IS the capacity;
- ``shaped_chaos`` — the same links plus the injected ``ZEST_FAULTS``
  matrix (serving-side corruption, seeder stalls, choke flaps, CDN
  503s): the headline block — swarm-wide peer_served_ratio, p50/p99
  pull latency, upload-fairness skew, and corrupt_bytes_admitted
  (must be 0) under failure.

Usage: python scripts/swarm_bench.py [--out SWARM_r12.json]
       [--mb 64] [--pullers 6] [--seeders 4] [--cdn-mbps 8]
       [--seed-mbps 24]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

FAULT_SPEC = ("upload_corrupt:0.02,seeder_stall:0.05@0.3,"
              "seeder_choke_flap:0.1,cdn_503:0.1")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="SWARM_r12.json")
    ap.add_argument("--mb", type=float, default=64.0)
    ap.add_argument("--pullers", type=int, default=6)
    ap.add_argument("--seeders", type=int, default=4)
    ap.add_argument("--cdn-mbps", type=float, default=8.0,
                    help="shaped CDN rate, MB/s shared across the swarm")
    ap.add_argument("--seed-mbps", type=float, default=24.0,
                    help="per-seeder upload cap (ZEST_SEED_RATE_BPS)")
    ap.add_argument("--faults", default=FAULT_SPEC)
    ap.add_argument("--seed", type=int, default=1337)
    args = ap.parse_args()

    from zest_tpu.bench_scale import bench_swarm

    gb = args.mb / 1024.0
    common = dict(gb=gb, m_pullers=args.pullers, k_seeders=args.seeders,
                  scale=4, chunks_per_xorb=16)
    out: dict = {
        "bench": "swarm_capacity",
        "requested_mb": args.mb,
        "pullers": args.pullers,
        "seeders": args.seeders,
        # Honesty note: pullers, seeders, and the shaped CDN all share
        # ONE machine's cores and loopback, so absolute walls are
        # pessimistic vs a real fleet; the ratio/fairness/corruption
        # numbers are topology-level and transfer.
        "note": "single-box loopback swarm; ratios and fairness are the "
                "signal, absolute walls are not",
    }
    print("clean (unshaped, no faults)...")
    out["clean"] = bench_swarm(**common)
    print(json.dumps(out["clean"], indent=1))
    print("shaped (WAN CDN + shaped seeders, no faults)...")
    out["shaped"] = bench_swarm(
        **common,
        shaped_bps=int(args.cdn_mbps * 1e6),
        seed_rate_bps=int(args.seed_mbps * 1e6))
    print(json.dumps(out["shaped"], indent=1))
    print("shaped_chaos (the capacity headline)...")
    out["shaped_chaos"] = bench_swarm(
        **common,
        shaped_bps=int(args.cdn_mbps * 1e6),
        seed_rate_bps=int(args.seed_mbps * 1e6),
        fault_spec=args.faults, fault_seed=args.seed)
    print(json.dumps(out["shaped_chaos"], indent=1))

    chaos = out["shaped_chaos"]
    out["gates"] = {
        "peer_served_ratio_ge_0.85": (
            chaos["peer_served_ratio"] is not None
            and chaos["peer_served_ratio"] >= 0.85),
        "corrupt_bytes_admitted_eq_0":
            chaos["corrupt_bytes_admitted"] == 0,
        "fairness_skew_le_2.0": (
            chaos["upload_fairness"]["skew"] is not None
            and chaos["upload_fairness"]["skew"] <= 2.0),
        "all_faults_fired": set(
            c.split(":")[0] for c in args.faults.split(",")
        ) <= set(chaos["faults_fired"]),
    }
    pathlib.Path(args.out).write_text(json.dumps(out, indent=1) + "\n")
    print(f"wrote {args.out}; gates: {out['gates']}")
    return 0 if all(out["gates"].values()) else 1


if __name__ == "__main__":
    raise SystemExit(main())
