"""CI smoke for delta pulls + in-place hot-swap (ISSUE 10).

A 64 MiB synthetic checkpoint (revision A) is pulled cold with
``--device``; a seeded 1%-changed revision B is then delta-pulled into
the same cache with the resident rev-A tree hot-swapped in place. The
gates:

- **changed-bytes-only fetch**: the delta pull's network bytes
  (FetchStats CDN tier — no peers in this harness) are ≤ 3% of the
  checkpoint total;
- **digest identity**: ``params_digest`` of the swapped tree equals a
  cold pull of revision B in a fresh cache — the delta moved buffers
  and skipped work, never changed bytes;
- **schema**: the delta pull reports ``stats["delta"]`` (with
  ``fetched_ratio``) and ``time_to_swap_s``; the hbm block carries the
  reused/landed split; the base param dict is fully consumed;
- **knob-off**: a ``ZEST_DELTA=0`` pull of B carries NO delta keys
  (stats schema restored bit-for-bit) and still lands correct bytes.

Exit 0 on success; any broken invariant prints the offending stats
block and fails the step.
"""

import json
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                       / "tests"))


def main() -> int:
    from fixtures import FixtureHub, FixtureRepo
    from zest_tpu.bench_scale import llama_checkpoint_files
    from zest_tpu.config import Config
    from zest_tpu.models.loader import params_digest
    from zest_tpu.transfer.pull import pull_model

    files_a = llama_checkpoint_files(0.064, shard_bytes=16 * 1024 * 1024,
                                     scale=8)
    files_b = llama_checkpoint_files(0.064, shard_bytes=16 * 1024 * 1024,
                                     scale=8, mutate_fraction=0.01)
    total = sum(len(b) for b in files_b.values())
    repo = FixtureRepo("smoke/delta", files_a, chunks_per_xorb=64)
    sha_a = repo.commit_sha
    sha_b = repo.add_revision(files_b)

    quiet = {"log": lambda *a, **k: None}

    def fail(msg: str, stats: dict | None = None) -> int:
        print(f"DELTA SMOKE FAILED: {msg}", file=sys.stderr)
        if stats:
            print(json.dumps({k: stats.get(k) for k in (
                "delta", "time_to_swap_s", "time_to_hbm_s", "fetch",
                "hbm")}, indent=2, default=str), file=sys.stderr)
        return 1

    with FixtureHub(repo) as hub:
        with tempfile.TemporaryDirectory() as root:
            rootp = pathlib.Path(root)
            cfg = Config(hf_home=rootp / "hf", cache_dir=rootp / "zest",
                         hf_token="hf_test", endpoint=hub.url)
            res_a = pull_model(cfg, "smoke/delta", revision=sha_a,
                               device="tpu", no_p2p=True, **quiet)
            base = res_a.params
            res_b = pull_model(cfg, "smoke/delta", revision=sha_b,
                               device="tpu", no_p2p=True,
                               base_params=base, base_revision=sha_a,
                               **quiet)
            stats = res_b.stats
            d = stats.get("delta")
            if not d:
                return fail("no stats['delta'] block on the delta pull",
                            stats)
            fetched = stats["fetch"]["bytes"]["cdn"]
            if fetched > 0.03 * total:
                return fail(
                    f"delta pull fetched {fetched} bytes "
                    f"({fetched / total:.2%} of {total}) — over the "
                    "3% gate for a 1%-changed revision", stats)
            if stats.get("time_to_swap_s") is None:
                return fail("no time_to_swap_s on the hot-swap pull",
                            stats)
            swap = (stats.get("hbm") or {}).get("swap") or {}
            if not swap.get("reused_tensors"):
                return fail("per-tensor short-circuit reused nothing",
                            stats)
            if base:
                return fail(f"base params not consumed ({len(base)} "
                            "left)", stats)
            dig_swap = params_digest(res_b.params)
            res_a.params = None
            res_b.params = None

        # Digest oracle: cold pull of B in a fresh cache.
        with tempfile.TemporaryDirectory() as root:
            rootp = pathlib.Path(root)
            cfg = Config(hf_home=rootp / "hf", cache_dir=rootp / "zest",
                         hf_token="hf_test", endpoint=hub.url)
            res_cold = pull_model(cfg, "smoke/delta", revision=sha_b,
                                  device="tpu", no_p2p=True, **quiet)
            dig_cold = params_digest(res_cold.params)
            cold_stats = res_cold.stats
            res_cold.params = None
            if "delta" in cold_stats:
                # Fresh cache: no rev-A evidence exists, so no plan —
                # and no base was passed, so no degraded event either.
                return fail("cold pull in a fresh cache grew a delta "
                            "block", cold_stats)
        if dig_swap != dig_cold:
            return fail(f"digests differ: swapped {dig_swap} vs cold "
                        f"{dig_cold}")

        # Knob-off: schema restored bit-for-bit.
        with tempfile.TemporaryDirectory() as root:
            rootp = pathlib.Path(root)
            cfg = Config(hf_home=rootp / "hf", cache_dir=rootp / "zest",
                         hf_token="hf_test", endpoint=hub.url,
                         delta_pull=False)
            pull_model(cfg, "smoke/delta", revision=sha_a,
                       device="tpu", no_p2p=True, **quiet).params = None
            res_off = pull_model(cfg, "smoke/delta", revision=sha_b,
                                 device="tpu", no_p2p=True, **quiet)
            off = res_off.stats
            res_off.params = None
            for key in ("delta", "time_to_swap_s"):
                if key in off:
                    return fail(f"knob-off pull leaked {key!r}", off)
            if (rootp / "zest" / "manifests").exists():
                return fail("knob-off pull wrote manifests")

    print("delta smoke OK: "
          f"fetched {fetched} of {total} bytes ({fetched / total:.2%}), "
          f"swap {stats['time_to_swap_s']}s vs cold "
          f"{cold_stats['time_to_hbm_s']}s, "
          f"{swap['reused_tensors']} tensors reused, digest "
          f"{dig_swap[:16]} identical")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
