#!/usr/bin/env bash
# Tier-3 multi-host harness — the reference's 3-node Hetzner test
# (test/hetzner/p2p-test.sh:246-390) with the same lifecycle —
# provision / deploy / test / report / teardown — parameterized over N
# local "hosts" (process sandboxes with isolated caches and ports; swap
# ssh_node in where real machines exist). Measures what the reference
# measures: CDN-only baseline vs P2P with 1 and 2 seeders, wall-clock,
# per-source bytes, P2P ratio, plus the re-pull cache-hit time, into
# results/summary.json.
#
# Usage: scripts/multihost-harness.sh [all|provision|deploy|test|report|teardown]
# Env:   NODES (default 3)  MODEL_BYTES (default 8000000)
#        WORK (default /tmp/zest-multihost)  BASE_PORT (default 27881)
#        CDN_BPS (default unset = unshaped) — token-bucket the fixture
#        hub's CDN data plane to this many bytes/s (shared across all
#        nodes) so the CDN-vs-P2P asymmetry the reference's tier-3
#        scenarios measure is reproduced on one machine (peers stay at
#        loopback speed; VERDICT r5 item 3).
set -euo pipefail

cd "$(dirname "$0")/.."
REPO_ROOT=$PWD
NODES=${NODES:-3}
MODEL_BYTES=${MODEL_BYTES:-8000000}
WORK=${WORK:-/tmp/zest-multihost}
BASE_PORT=${BASE_PORT:-27881}
REPO_ID="acme/multihost-model"
RESULTS="$WORK/results"

log() { printf '[harness] %s\n' "$*"; }
die() { printf '[harness] FATAL: %s\n' "$*" >&2; exit 1; }

node_env() {  # node_env <i> -> env assignments on stdout
    local i=$1
    echo "HF_HOME=$WORK/node$i/hf ZEST_CACHE_DIR=$WORK/node$i/zest" \
         "HF_TOKEN=hf_test HF_ENDPOINT=$(cat "$WORK/hub.url")" \
         "ZEST_HTTP_PORT=$((BASE_PORT + 1000 + i))" \
         "ZEST_LISTEN_PORT=$((BASE_PORT + i))"
}

run_node() {  # run_node <i> <cmd...>
    local i=$1; shift
    env $(node_env "$i") python -m zest_tpu "$@"
}

# ── provision: the "create VMs" analog — sandboxes + the origin server ──
provision() {
    log "provision: $NODES nodes under $WORK"
    rm -rf "$WORK"
    mkdir -p "$RESULTS"
    for i in $(seq 0 $((NODES - 1))); do mkdir -p "$WORK/node$i"; done
    python scripts/fixture_hub.py --url-file "$WORK/hub.url" \
        --repo "$REPO_ID" --size "$MODEL_BYTES" \
        ${CDN_BPS:+--throttle-bps "$CDN_BPS"} &
    echo $! > "$WORK/hub.pid"
    # GB-scale fixtures take the hub a while to generate and encode
    # before it binds — scale the wait with the model size (~0.2 s per
    # 4 MB on top of the 10 s floor).
    local iters=$((50 + MODEL_BYTES / 4000000))
    local hub_pid
    hub_pid=$(cat "$WORK/hub.pid")
    for _ in $(seq 1 "$iters"); do
        [ -s "$WORK/hub.url" ] && break
        # A crashed hub must fail in sub-seconds, not after the full
        # size-scaled wait window.
        kill -0 "$hub_pid" 2>/dev/null || break
        sleep 0.2
    done
    [ -s "$WORK/hub.url" ] || die "fixture hub did not start"
    log "origin (CDN analog): $(cat "$WORK/hub.url")"
}

# ── deploy: the "install binaries" analog — record what's running ──
deploy() {
    [ -s "$WORK/hub.url" ] || die "no state; run provision first"
    python - "$WORK/deploy.json" <<'EOF'
import json, platform, sys
from zest_tpu.version import __version__
json.dump({"zest_tpu": __version__,
           "python": platform.python_version(),
           "platform": platform.platform()},
          open(sys.argv[1], "w"))
EOF
    log "deploy: $(cat "$WORK/deploy.json")"
}

start_serve() {  # start_serve <i>
    local i=$1
    env $(node_env "$i") python -m zest_tpu serve --dcn-port 0 \
        > "$WORK/node$i/serve.log" 2>&1 &
    echo $! >> "$WORK/serve.pids"
    local port=$((BASE_PORT + i))
    python scripts/wait_for_port.py "$port" 10 \
        || die "node $i serve did not come up on :$port"
}

timed_pull() {  # timed_pull <node> <outfile> [extra pull args...]
    local i=$1 out=$2; shift 2
    local t0 t1
    t0=$(python -c 'import time; print(time.monotonic())')
    run_node "$i" pull "$REPO_ID" --no-seed "$@" > "$out" 2>&1 \
        || die "pull failed on node $i (see $out)"
    t1=$(python -c 'import time; print(time.monotonic())')
    python -c "print(f'wall_seconds: {$t1 - $t0:.3f}')" >> "$out"
}

# ── test: baseline, then swarms of growing size ──
test_all() {
    [ -s "$WORK/hub.url" ] || die "no state; run provision first"
    : > "$WORK/serve.pids"

    log "=== Test 1: CDN-only baseline (node 0) ==="
    timed_pull 0 "$RESULTS/test1_cdn_baseline.txt" --no-p2p

    log "=== Test 2: node 0 seeds; node 1 pulls P2P (1 peer) ==="
    start_serve 0
    timed_pull 1 "$RESULTS/test2_p2p_1peer.txt" \
        --peer "127.0.0.1:$((BASE_PORT + 0))"

    log "=== Test 3: nodes 0+1 seed; node 2 pulls P2P (2 peers) ==="
    start_serve 1
    timed_pull 2 "$RESULTS/test3_p2p_2peers.txt" \
        --peer "127.0.0.1:$((BASE_PORT + 0))" \
        --peer "127.0.0.1:$((BASE_PORT + 1))"

    log "=== Test 4: re-pull on node 0 (cache hit) ==="
    timed_pull 0 "$RESULTS/test4_repull.txt" --no-p2p
    log "test phase complete"
}

# ── report: parse + gate + summary.json ──
report() {
    python - "$RESULTS" "$NODES" "$MODEL_BYTES" <<'EOF'
import json, pathlib, re, sys

results = pathlib.Path(sys.argv[1])
n_nodes = int(sys.argv[2])
model_bytes = int(sys.argv[3])

def parse(name):
    text = (results / name).read_text()
    def grab(pat, cast=float):
        m = re.search(pat, text)
        return cast(m.group(1)) if m else None
    out = {
        "wall_seconds": grab(r"wall_seconds: ([\d.]+)"),
        "elapsed_seconds": grab(r"Elapsed:\s+([\d.]+)s"),
        "bytes_from_cache": grab(r"From cache:\s+(\d+)", int),
        "bytes_from_peers": grab(r"From peers:\s+(\d+)", int),
        "bytes_from_cdn": grab(r"From CDN:\s+(\d+)", int),
        "p2p_ratio": grab(r"P2P ratio:\s+([\d.]+)%"),
    }
    # Per-stage decomposition + GB/s/host (reference tier-3 records
    # only wall-clocks, p2p-test.sh:325-390; stages are this build's
    # tracing story surfaced into the harness artifact).
    stages = {}
    m = re.search(r"Stages:\s+(.+)", text)
    if m:
        for sm in re.finditer(r"(\w+) ([\d.]+)s", m.group(1)):
            stages[sm.group(1)] = float(sm.group(2))
    out["stages"] = stages
    el = out["elapsed_seconds"]
    out["gbps_per_host"] = (
        round(model_bytes / el / 1e9, 3) if el else None)
    return out

t1, t2, t3, t4 = (parse(f"test{i}_{n}.txt") for i, n in
                  ((1, "cdn_baseline"), (2, "p2p_1peer"),
                   (3, "p2p_2peers"), (4, "repull")))

def secs(t):
    # the CLI-reported transfer time; wall_seconds includes ~4s of
    # python+jax interpreter startup that a real deployment pays once
    return t["elapsed_seconds"] if t["elapsed_seconds"] is not None \
        else t["wall_seconds"]

def speedup(base, other):
    if base and other and other > 0:
        return round(base / other, 2)
    return None

summary = {
    "nodes": n_nodes,
    "model_bytes": model_bytes,
    "cdn_baseline": t1,
    "p2p_1peer": t2,
    "p2p_2peers": t3,
    "repull_cached": t4,
    "speedup_1peer": speedup(secs(t1), secs(t2)),
    "speedup_2peers": speedup(secs(t1), secs(t3)),
    # In-process elapsed ONLY: a wall-clock repull is dominated by the
    # ~4 s interpreter+jax import and compares apples-to-oranges with
    # BASELINE.md's >300x target (a daemon pays the import once). The
    # elapsed-less case surfaces as null, not a fake wall number.
    "speedup_repull": speedup(t1["elapsed_seconds"],
                              t4["elapsed_seconds"]),
    "speedup_repull_wall": speedup(secs(t1), secs(t4)),
}
json.dump(summary, open(results / "summary.json", "w"), indent=1)
print(json.dumps(summary, indent=1))

# The pass gate (reference: p2p-docker-test.sh:204-218 — fail unless
# bytes arrived from peers; ideal is 100% P2P, zero CDN).
ok = True
for name, t in (("1peer", t2), ("2peers", t3)):
    if not t["bytes_from_peers"]:
        print(f"FAIL: {name}: no bytes from peers"); ok = False
    if t["bytes_from_cdn"]:
        print(f"WARN: {name}: {t['bytes_from_cdn']} bytes leaked to CDN")
# A cache-hit re-pull downloads NOTHING (files already in the snapshot):
# every byte counter must be zero — and parse failure is a failure, not
# a vacuous pass.
if t4["bytes_from_cdn"] is None or t4["bytes_from_peers"] is None:
    print("FAIL: re-pull output unparseable"); ok = False
elif t4["bytes_from_cdn"] or t4["bytes_from_peers"]:
    print("FAIL: re-pull hit the network"); ok = False
if t4["elapsed_seconds"] is None:
    print("FAIL: re-pull in-process elapsed missing"); ok = False
sys.exit(0 if ok else 1)
EOF
}

teardown() {
    log "teardown"
    if [ -f "$WORK/serve.pids" ]; then
        while read -r pid; do kill "$pid" 2>/dev/null || true; done \
            < "$WORK/serve.pids"
    fi
    [ -f "$WORK/hub.pid" ] && kill "$(cat "$WORK/hub.pid")" 2>/dev/null || true
    if [ "${KEEP_RESULTS:-0}" = "1" ]; then
        log "results kept at $RESULTS"
        find "$WORK" -mindepth 1 -maxdepth 1 ! -name results \
            -exec rm -rf {} +
    else
        rm -rf "$WORK"
    fi
}

ACTION=${1:-all}
case "$ACTION" in
    provision) provision ;;
    deploy)    deploy ;;
    test)      test_all ;;
    report)    report ;;
    teardown)  teardown ;;
    all)
        trap teardown EXIT
        provision
        deploy
        test_all
        report
        ;;
    *) die "unknown action '$ACTION' (all|provision|deploy|test|report|teardown)" ;;
esac
