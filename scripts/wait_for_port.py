"""Exit 0 once 127.0.0.1:PORT accepts a TCP connect, 1 after a timeout.

Shared readiness probe for the shell harnesses (p2p-loopback-test.sh,
multihost-harness.sh) — one implementation instead of per-script
heredocs that drift apart.

Usage: python scripts/wait_for_port.py PORT [TIMEOUT_SECONDS]
"""

import socket
import sys
import time


def main() -> int:
    port = int(sys.argv[1])
    timeout = float(sys.argv[2]) if len(sys.argv) > 2 else 10.0
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        s = socket.socket()
        s.settimeout(0.3)
        try:
            s.connect(("127.0.0.1", port))
            return 0
        except OSError:
            time.sleep(0.2)
        finally:
            s.close()
    return 1


if __name__ == "__main__":
    sys.exit(main())
