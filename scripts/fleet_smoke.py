"""CI smoke for the fleet-scale topology tier (ISSUE 16).

Three legs, mirroring the tentpole's three claims:

1. **32-host loopback fleet** (4 pods x 8, real GossipNodes over a
   LoopbackMesh): warm pods announce their xorbs into the epidemic
   digest, the tracker is then DISABLED (bootstrap-seed-only — also
   re-asserted at the swarm layer: an attached gossip node demotes
   every non-first announce), and every host must resolve >= 0.85 of
   the checkpoint bytes from the gossip who-has index alone — the
   announce path whose cost is O(N log N), not every-host-to-tracker.
2. **Cold-pod routing**: pod 3 never announces; after anti-entropy
   spreads the index, each of its hosts must route EVERY warm-held
   xorb to a warm pod over WAN (zero CDN bytes for warm-held keys,
   link-cost table ICI < DCN < WAN < CDN), and once one cold member
   holds a key, its pod-mates must prefer that pod-local copy over
   any WAN holder.
3. **Dead-gateway round** (4 real hosts, 2 pods x 2, loopback DCN +
   fixture hub): pod 1's elected gateway is dead on the wire. The
   federated collective must ABORT (not hang), degrade down the PR-13
   ladder (point-to-point exchange, then per-unit CDN fallback for
   the dead host's share), and still leave every surviving host fully
   cached — while ``elect_gateways`` over a plan that quarantines the
   dead host re-elects the next-lowest member with no round trips.

Exit 0 on success; prints the offending block and fails otherwise.
"""

import json
import pathlib
import sys
import tempfile
import threading

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                       / "tests"))

N_HOSTS = 32
POD_SIZE = 8
N_PODS = 4
WARM_KEYS = 60
UNKNOWN_KEYS = 4  # held by nobody: the honest CDN remainder
REPO_ID = "smoke/fleet-llama"


def fail(msg: str, blob=None) -> int:
    print(f"FLEET SMOKE FAILED: {msg}", file=sys.stderr)
    if blob is not None:
        print(json.dumps(blob, indent=2, default=str), file=sys.stderr)
    return 1


def gossip_fleet_legs() -> int | None:
    """Legs 1 + 2: the 32-host loopback gossip fleet."""
    from zest_tpu.config import Config
    from zest_tpu.transfer.gossip import (GossipNode, LoopbackMesh,
                                          link_cost)
    from zest_tpu.transfer.swarm import SwarmDownloader

    pods = tuple(h // POD_SIZE for h in range(N_HOSTS))
    topo = tuple(2 * (h // POD_SIZE) + (h % POD_SIZE >= POD_SIZE // 2)
                 for h in range(N_HOSTS))
    book = {h: ("127.0.0.1", 7000 + h) for h in range(N_HOSTS)}
    mesh = LoopbackMesh()
    nodes = [GossipNode(h, N_HOSTS, book, topology=topo, pods=pods)
             for h in range(N_HOSTS)]
    for node in nodes:
        mesh.register(node)

    # Bootstrap: tracker-visible announces, counted. Warm pods 0..2
    # announce; pod 3 is cold. After this block the tracker is never
    # consulted again — resolution below is digest-only.
    class Tracker:
        announces = 0

        def announce(self, info_hash, port):
            Tracker.announces += 1

        def find_peers(self, info_hash):
            return []

    tracker = Tracker()
    keys = [bytes([j]) * 32 for j in range(WARM_KEYS)]
    for j, key in enumerate(keys):
        holder = (j % 3) * POD_SIZE + (j % POD_SIZE)
        tracker.announce(key, 6881)  # the bootstrap seed
        nodes[holder].announce(key, 6881)
    bootstrap_announces = Tracker.announces

    # Anti-entropy to convergence (bound: 2 * ceil(log2 N) sweeps).
    import math

    bound = 2 * math.ceil(math.log2(N_HOSTS))
    for sweep in range(bound):
        for node in nodes:
            node.tick(mesh)
        if all(node.who_has(k) for node in nodes for k in keys):
            break
    else:
        return fail(f"gossip did not converge in {bound} sweeps")

    # Leg 1: tracker disabled; resolve everything from the digest.
    if Tracker.announces != bootstrap_announces:
        return fail("gossip rounds leaked tracker announces")
    key_bytes = 1 << 20
    peer = cdn = 0
    for node in nodes:
        for j in range(WARM_KEYS + UNKNOWN_KEYS):
            key = bytes([j]) * 32 if j < WARM_KEYS else bytes(
                [0xF0 + j - WARM_KEYS]) * 32
            if node.who_has(key):
                peer += key_bytes
            else:
                cdn += key_bytes
    ratio = peer / (peer + cdn)
    if ratio < 0.85:
        return fail(f"fleet peer_served_ratio {ratio:.3f} < 0.85 with "
                    "tracker disabled after bootstrap")

    # Swarm-layer re-assertion: with a node attached, the tracker sees
    # exactly ONE announce per swarm regardless of refreshes.
    with tempfile.TemporaryDirectory() as root:
        cfg = Config(hf_home=pathlib.Path(root) / "hf",
                     cache_dir=pathlib.Path(root) / "zest")
        t2 = Tracker()
        before = Tracker.announces
        sw = SwarmDownloader(cfg, peer_sources=[t2])
        sw.attach_gossip(GossipNode(0, 2, {}))
        for _ in range(5):
            sw.announce_available(keys[0], keys[0].hex())
        sw.close()
        if Tracker.announces - before != 1:
            return fail(
                f"attached swarm sent {Tracker.announces - before} "
                "tracker announces for one swarm (want 1: bootstrap)")

    # Leg 2: cold pod 3 routes warm-held keys to warm pods over WAN.
    cold = [nodes[3 * POD_SIZE + i] for i in range(POD_SIZE)]
    cold_cdn = 0
    for node in cold:
        for key in keys:
            holders = node.who_has(key)
            if not holders:
                cold_cdn += key_bytes
                continue
            link = link_cost(node.host_index, holders[0],
                             topology=topo, pods=pods)
            if link != 2:  # COST_WAN — nearest warm copy, not origin
                return fail(
                    f"cold host {node.host_index} routed key to "
                    f"holder {holders[0]} at cost {link} (want WAN=2)")
    if cold_cdn:
        return fail(f"cold pod sent {cold_cdn} bytes to the CDN for "
                    "warm-held xorbs (want 0)")
    # Once a cold member holds a key, pod-mates prefer the pod-local
    # copy (ICI/DCN) over every WAN holder.
    cold[0].announce(keys[0], 6881)
    for node in cold:
        node.tick(mesh)
    local = cold[1].who_has(keys[0])[0]
    if link_cost(cold[1].host_index, local,
                 topology=topo, pods=pods) >= 2:
        return fail(f"pod-mate preferred remote holder {local} over "
                    "the pod-local copy")
    print(f"fleet gossip legs OK: ratio {ratio:.3f} with tracker "
          f"disabled ({bootstrap_announces} bootstrap announces, "
          f"sweeps <= {bound}), cold pod zero-CDN for warm keys")
    return None


def dead_gateway_leg() -> int | None:
    """Leg 3: a 2-pod round whose pod-1 gateway is dead on the wire."""
    from fixtures import FixtureHub, FixtureRepo
    from zest_tpu.bench_scale import llama_checkpoint_files
    from zest_tpu.cas.hub import HubClient
    from zest_tpu.config import Config
    from zest_tpu.transfer.bridge import XetBridge
    from zest_tpu.transfer.collective import elect_gateways
    from zest_tpu.transfer.coop import CoopPlan, coop_round
    from zest_tpu.transfer.dcn import DcnServer

    n = 4
    pods = (0, 0, 1, 1)
    dead = 2  # pod 1's elected gateway (lowest index in the pod)
    files = llama_checkpoint_files(0.016, shard_bytes=8 * 1024 * 1024,
                                   scale=8, smooth=True)
    repo = FixtureRepo(REPO_ID, files, chunks_per_xorb=16)
    with FixtureHub(repo) as hub, tempfile.TemporaryDirectory() as root:
        rootp = pathlib.Path(root)
        hosts, servers, addrs = [], [], {}
        for i in range(n):
            cfg = Config(hf_home=rootp / f"h{i}/hf",
                         cache_dir=rootp / f"h{i}/zest",
                         hf_token="hf_test", endpoint=hub.url,
                         dcn_port=0, coop_pods=pods,
                         coop_topology=pods)
            bridge = XetBridge(cfg)
            bridge.authenticate(REPO_ID)
            if i == dead:
                # In the addr map, dead on the wire: port 1 refuses.
                addrs[i] = ("127.0.0.1", 1)
            else:
                server = DcnServer(bridge.cfg, bridge.cache)
                addrs[i] = ("127.0.0.1", server.start())
                servers.append(server)
            hosts.append(bridge)

        recs_by_host = {}
        for i in (0, 1, 3):
            recs_by_host[i] = [
                hosts[i].get_reconstruction(e.xet_hash)
                for e in HubClient(hosts[i].cfg).list_files(REPO_ID)
                if e.is_xet]
        results: dict[int, dict] = {}
        errors: list[str] = []

        def run(i):
            try:
                results[i] = coop_round(
                    hosts[i], recs_by_host[i], i, n, addrs,
                    deadline_s=20.0)
            except Exception as exc:  # noqa: BLE001 - reported below
                errors.append(f"host {i}: {exc!r}")

        threads = [threading.Thread(target=run, args=(i,), daemon=True)
                   for i in (0, 1, 3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=240)
        for s in servers:
            s.shutdown()
        total = sum(
            fi.url_range_end - fi.url_range_start
            for _k, fi in CoopPlan.build(recs_by_host[0], 1).units)
        if errors:
            return fail(f"surviving rounds crashed: {errors}")
        if sorted(results) != [0, 1, 3]:
            return fail(f"rounds missing: {sorted(results)}")
        # Only hosts whose schedule DIALS the dead gateway abort: host
        # 0 (stage B, gateway-to-gateway) and host 3 (stage A/C, pod
        # mate). Host 1's partners are all pod-local and alive — its
        # collective may finish cleanly, served by host 0's healed
        # ladder through the NOT_FOUND barrier.
        for i in (0, 3):
            cx = results[i].get("collective")
            if cx is not None and not cx.get("aborted"):
                return fail(f"host {i} collective finished cleanly "
                            "against a dead gateway", cx)
        for i, r in results.items():
            fetched = (sum(r["fetch"]["tiers"].values())
                       + r["exchange"]["wire_bytes"]
                       + sum(r["exchange"].get("fallback_tiers",
                                               {}).values()))
            if fetched < total:
                return fail(f"host {i} ended short: {fetched} < "
                            f"{total} bytes", r)
        aborts = sum(1 for r in results.values()
                     if (r.get("collective") or {}).get("aborted"))
        fallbacks = sum(r["fallbacks"] for r in results.values())
        if not fallbacks:
            return fail("no CDN fallbacks — the dead gateway's share "
                        "was never degraded down the ladder", results)

        # Deterministic re-election: quarantining the dead gateway
        # hands pod 1 to the next-lowest member, no round trips.
        plan2 = CoopPlan.build(recs_by_host[0], n,
                               quarantined=frozenset({dead}))
        gw2 = elect_gateways(plan2, pods)
        if gw2 != {0: 0, 1: 3}:
            return fail(f"re-election elected {gw2}, want "
                        "{0: 0, 1: 3}")
        for b in hosts:
            b.close()
    print(f"dead-gateway leg OK: {aborts} collective aborts, "
          f"{fallbacks} CDN-fallback units healed the round, pod 1 "
          f"re-elects host 3")
    return None


def main() -> int:
    for leg in (gossip_fleet_legs, dead_gateway_leg):
        rc = leg()
        if rc is not None:
            return rc
    print("fleet smoke OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
