"""MTTR artifact driver: the self-healing chaos bench (ISSUE 17).

Writes ``MTTR_r17.json``: per fault class, detection-to-recovery with
the remediation policy engine ON vs the identical faults ridden out
hands-off (``ZEST_REMEDIATE=0`` — the detector runs in both arms, only
the actions differ). The ``gates`` block is the acceptance surface:

- ``classes_at_half_ok`` — >=3 distinct fault classes recover in
  <=0.5x the hands-off MTTR (seeder_stall via the mid-flight hedge,
  upload_corrupt via the evidence-driven seeder demote, dcn_reset via
  the patience-1 mid-round abort; choke flaps and CDN 503s are honest
  non-wins — their fast-refusal/retry paths ARE the remedy either way);
- ``corrupt_bytes_admitted`` == 0 across every arm of every class;
- ``all_faults_fired`` — each fault actually fired in its hands-off
  arm (the policy arm may legitimately short-circuit a fault site);
- ``remediations_have_series`` — every executed action is a flight
  event carrying before/after timeline snapshots;
- the healthy-swarm control: ZERO executed actions, peer-served ratio
  no worse than hands-off (over-healing is itself a failure mode).

Usage: python scripts/mttr_bench.py [--out MTTR_r17.json] [--runs 2]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="MTTR_r17.json")
    ap.add_argument("--runs", type=int, default=2)
    ap.add_argument("--mb", type=float, default=20.0)
    ap.add_argument("--stall-s", type=float, default=6.0)
    args = ap.parse_args()

    from zest_tpu.bench_scale import bench_mttr

    out: dict = {
        "bench": "mttr_chaos",
        "requested_mb": args.mb,
        # Honesty note: both arms share one machine's cores and
        # loopback, so absolute MTTRs are optimistic vs a real fleet;
        # the policy-on/hands-off RATIO is the per-class signal.
        "note": "single-box loopback chaos; the hands-off/policy-on "
                "MTTR ratio is the signal, absolute walls are not",
    }
    out.update(bench_mttr(gb=args.mb / 1024.0, runs=args.runs,
                          stall_s=args.stall_s))
    print(json.dumps(out, indent=1))
    ok = out["gates"]["classes_at_half_ok"] \
        and out["gates"]["corrupt_bytes_admitted"] == 0 \
        and out["gates"]["all_faults_fired"]
    pathlib.Path(args.out).write_text(json.dumps(out, indent=1) + "\n")
    print(f"wrote {args.out} (gates "
          f"{'OK' if ok else 'FAILED'}: "
          f"{json.dumps(out['gates'])})")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
