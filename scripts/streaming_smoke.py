"""CI smoke for the streaming landing (ISSUE 8).

Two 64 MiB synthetic ``--device`` pulls against the loopback fixture
hub — one streaming (the default), one with ``ZEST_LAND_STREAM=0``
(the PR-1 shard-level double buffer) — must agree and must prove the
tensor-granularity pipeline actually engaged:

- the streamed pull reports ``time_to_first_layer_s`` and it ends
  strictly inside the first half of ``time_to_hbm_s`` (the acceptance
  bar is 0.25× on the 2 GB warm bench; 0.5× here keeps CI robust to
  runner weather on a pull whose fixed costs are a bigger fraction);
- ``params_digest`` of the streamed HBM tree is byte-identical to the
  non-streaming pull's — the ring moved buffers, never bytes;
- the ring accounting exists (stats["hbm"]["ring"]) and the knob-off
  pull carries NO streaming keys (schema restoration, bit-for-bit);
- both pulls' materialized safetensors bytes are exact.

Exit 0 on success; any broken invariant prints the offending stats
block and fails the step.
"""

import json
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                       / "tests"))


def main() -> int:
    from fixtures import FixtureHub, FixtureRepo
    from zest_tpu.bench_scale import llama_checkpoint_files
    from zest_tpu.config import Config
    from zest_tpu.models.loader import params_digest
    from zest_tpu.transfer.pull import pull_model

    # scale=32 → ~120 tiny layers: the first-layer set is ~2% of the
    # bytes, the realistic deep-model shape (a 70B is 80 layers — the
    # scale=8 alternative has SIX, making "first layer" 16% of the
    # model and the ratio bar mostly a measure of fixed startup cost).
    files = llama_checkpoint_files(0.064, shard_bytes=8 * 1024 * 1024,
                                   scale=32)
    repo = FixtureRepo("smoke/streaming", files, chunks_per_xorb=32)

    runs: dict[bool, dict] = {}
    digests: dict[bool, str] = {}
    with FixtureHub(repo) as hub:
        for stream in (True, False):
            with tempfile.TemporaryDirectory() as root:
                rootp = pathlib.Path(root)
                cfg = Config(hf_home=rootp / "hf",
                             cache_dir=rootp / "zest",
                             hf_token="hf_test", endpoint=hub.url,
                             land_stream=stream)
                res = pull_model(cfg, "smoke/streaming", device="tpu",
                                 no_p2p=True, log=lambda *a, **k: None)
                runs[stream] = res.stats
                digests[stream] = params_digest(res.params)
                for name, data in files.items():
                    got = (res.snapshot_dir / name).read_bytes()
                    if got != data:
                        print(f"STREAMING SMOKE FAILED: {name} "
                              f"materialized inexactly (stream="
                              f"{stream})", file=sys.stderr)
                        return 1
                res.params = None

    stats = runs[True]

    def fail(msg: str) -> int:
        print(f"STREAMING SMOKE FAILED: {msg}", file=sys.stderr)
        print(json.dumps({k: stats.get(k) for k in (
            "time_to_hbm_s", "time_to_first_layer_s", "elapsed_s",
            "stages", "hbm")}, indent=2, default=str), file=sys.stderr)
        return 1

    hbm = stats.get("hbm") or {}
    if not hbm.get("streamed"):
        return fail("default pull did not take the streaming landing")
    if not hbm.get("ring"):
        return fail("no ring accounting in stats['hbm']")
    tfl = stats.get("time_to_first_layer_s")
    tth = stats.get("time_to_hbm_s")
    if tfl is None or tth is None:
        return fail(f"missing headline stats (first_layer={tfl}, "
                    f"hbm={tth})")
    if not tfl < 0.5 * tth:
        return fail(f"time_to_first_layer_s ({tfl}) is not < 0.5 x "
                    f"time_to_hbm_s ({tth}) — the layer-ordered "
                    "pipeline did not engage")
    off = runs[False]
    for key in ("time_to_first_layer_s",):
        if key in off:
            return fail(f"knob-off pull leaked streaming key {key!r}")
    off_hbm = off.get("hbm") or {}
    if off_hbm.get("streamed") or off_hbm.get("ring"):
        return fail("knob-off pull streamed anyway")
    if digests[True] != digests[False]:
        return fail(f"HBM digests differ: streamed {digests[True]} vs "
                    f"non-streaming {digests[False]}")
    print("streaming smoke OK: "
          f"first_layer {tfl}s / hbm {tth}s "
          f"({tfl / tth:.0%}), ring {hbm['ring']}, digest "
          f"{digests[True][:16]} identical both modes")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
