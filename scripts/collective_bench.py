"""COLLECTIVE transport-split artifact driver (ISSUE 20).

Writes ``COLLECTIVE_r20.json``: the shaped 8-host exchange wall for the
same redistribution over the three exchange backends —

- ``wire``  (``ZEST_COLLECTIVE_BACKEND=dcn``): PR-13's pooled
  DcnChannel path, byte-exact, the pre-split reference;
- ``split`` (``backend=jax`` over a registered loopback fabric):
  intra-slice phases ride the ICI uint8 lane-permute backend,
  cross-slice stays on the shaped wire — must reconstruct the same
  digests as the wire leg on every host, from that host's own cache;
- ``lossy`` (``ZEST_COLLECTIVE_LOSSY=dcn``): cross-slice BG4 float
  payloads quantize to the ZQLS int8 tier (HBM staging only, never the
  xorb cache) and the leg must beat the wire leg >=1.2x at equal
  peer-served ratio — the EQuARX-grounded headline,

plus the measured preadv decode delta (stored-scheme blob through
``CachedFileReader`` with the preadv lane on vs off, byte-identity
asserted). The artifact carries a ``gates`` block; this driver exits 1
if any gate reads false, and ``scripts/bench_trend.py`` re-checks the
committed artifact on every CI run.

Usage: python scripts/collective_bench.py [--out COLLECTIVE_r20.json]
       [--mb 24] [--hosts 8] [--dcn-mbps 120] [--dcn-rtt-ms 4]
       [--topology 0,0,0,0,1,1,1,1]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="COLLECTIVE_r20.json")
    ap.add_argument("--mb", type=float, default=24.0,
                    help="fp32 shard megabytes (plus a fixed 8 MiB "
                         "incompressible blob)")
    ap.add_argument("--hosts", type=int, default=8)
    ap.add_argument("--dcn-mbps", type=float, default=1.0,
                    help="shaped cross-slice serve rate per host, MB/s "
                         "(WAN-class: low enough that the cross-slice "
                         "leg, not one machine's shared CPUs, sets the "
                         "wall — the regime the lossy tier targets)")
    ap.add_argument("--dcn-rtt-ms", type=float, default=4.0,
                    help="WAN round trip charged per request window on "
                         "cross-slice links")
    ap.add_argument("--topology", default="0,0,0,0,1,1,1,1",
                    help="ZEST_COOP_TOPOLOGY-grammar slice spec "
                         "classing exchange links ici/dcn")
    args = ap.parse_args()

    from zest_tpu.bench_scale import bench_collective_transports

    print(f"[collective-bench] {args.hosts} hosts, {args.mb} MB fp32, "
          f"topology {args.topology}, DCN {args.dcn_mbps} MB/s + "
          f"{args.dcn_rtt_ms} ms/window ...", flush=True)
    out = bench_collective_transports(
        mb=args.mb, n_hosts=args.hosts,
        dcn_bps=int(args.dcn_mbps * 1e6),
        dcn_rtt_s=args.dcn_rtt_ms / 1000.0,
        topology=args.topology)
    out["bench"] = "collective_transports"
    # Honesty note mirrors coop_bench: all hosts share this machine's
    # cores, so absolute walls under-provision a real pod ~Nx; the
    # RATIO between legs (same machine, same bytes, same schedule) is
    # the defensible number.
    out["note"] = "single-machine simulation; legs share host CPUs"
    print(json.dumps(out, indent=1), flush=True)

    ok = True
    for name, val in sorted(out["gates"].items()):
        if not val:
            print(f"FAIL: gate {name} is false", file=sys.stderr)
            ok = False
    for err in out.get("errors", []):
        print(f"FAIL: {err}", file=sys.stderr)
        ok = False
    pathlib.Path(args.out).write_text(json.dumps(out, indent=1) + "\n")
    print(f"[collective-bench] wrote {args.out}")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
