"""Virtual-mesh collective regression fence (VERDICT r3 item 6).

Runs the three collective-path benches — ``ici_all_gather``,
``ring_attention``, ``pipeline_gpipe`` — on the 8-device virtual CPU
mesh and fails if any is more than 2x slower than the stored budget.
Absolute ICI GB/s needs hardware this environment lacks; what a CPU
mesh CAN catch is a *relative* regression in the collective code path
(an accidental gather-materialize, a broken donation, a shape that
stops fusing), which is exactly what the 2x fence is for.

Usage:
    python scripts/collective_fence.py [--update-budget] [OUT.json]

The budget lives at tests/golden/collective_budget.json (regenerate
with --update-budget on a quiet machine after an intentional change and
commit it alongside). The measured numbers are written to OUT.json
(default: collective_fence.json) for the round record.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

# The fence always measures the virtual 8-device CPU mesh, never the
# relay chip. sitecustomize may have imported jax (and registered the
# axon TPU plugin) before this file runs, so setting the env var is not
# enough — pin the config too, before any backend initializes (with a
# dead chip tunnel, axon init hangs indefinitely).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

BUDGET_PATH = REPO / "tests" / "golden" / "collective_budget.json"
SLOWDOWN_LIMIT = 2.0


def calibrate() -> float:
    """Machine-speed yardstick: single-device f32 matmul GFLOP/s.

    The budget file records the yardstick of the machine that wrote it;
    a different (slower/faster) machine's floors are scaled by the
    yardstick ratio, so the 2x fence keeps firing on CODE regressions
    rather than on hardware differences between the budget machine and
    the CI runner."""
    import time

    import jax
    import jax.numpy as jnp

    n = 1024
    x = jnp.ones((n, n), jnp.float32)
    f = jax.jit(lambda a: a @ a)
    f(x).block_until_ready()
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return round(2 * n**3 / best / 1e9, 1)


def measure() -> dict[str, float]:
    from zest_tpu.bench_suite import (
        bench_ici_all_gather,
        bench_pipeline,
        bench_ring_attention,
    )

    out = {}
    for fn in (bench_ici_all_gather, bench_ring_attention, bench_pipeline):
        r = fn()
        out[r.name] = round(r.mb_per_s, 1)
    return out


def main() -> int:
    argv = [a for a in sys.argv[1:]]
    update = "--update-budget" in argv
    if update:
        argv.remove("--update-budget")
    out_path = pathlib.Path(argv[0]) if argv else REPO / "collective_fence.json"

    import jax

    n = len(jax.devices())
    cal = calibrate()
    measured = measure()
    record = {"devices": n, "calibration_gflops": cal,
              "mb_per_s": measured}

    if update or not BUDGET_PATH.exists():
        BUDGET_PATH.write_text(json.dumps(
            {"_comment": "virtual-8-device-mesh collective throughput "
             "budget (MB/s) + the matmul GFLOP/s yardstick of the "
             "machine that wrote it (floors scale by the yardstick "
             "ratio on other machines). Regenerate: "
             "python scripts/collective_fence.py --update-budget",
             "_calibration_gflops": cal,
             **measured}, indent=1))
        print(f"budget written to {BUDGET_PATH}")

    doc = json.loads(BUDGET_PATH.read_text())
    budget = {k: v for k, v in doc.items() if not k.startswith("_")}
    # Normalize for machine speed: a CI runner half as fast as the
    # budget machine gets floors half as high, so the 2x fence stays a
    # fence on the CODE. Clamped at 1.0 — a faster-looking yardstick
    # never RAISES the floor (matmul speed and collective throughput
    # don't co-vary tightly; on a noisy shared host an unclamped ratio
    # turns yardstick jitter into false failures — observed).
    budget_cal = doc.get("_calibration_gflops") or cal
    machine_ratio = min(1.0, cal / budget_cal) if budget_cal else 1.0
    record["machine_ratio"] = round(machine_ratio, 3)
    failures = []
    for name, mbps in measured.items():
        floor = budget.get(name, 0) * machine_ratio / SLOWDOWN_LIMIT
        record.setdefault("floor_mb_per_s", {})[name] = round(floor, 1)
        if mbps < floor:
            failures.append(f"{name}: {mbps} MB/s < floor {floor:.1f} "
                            f"(budget {budget[name]} x machine "
                            f"{machine_ratio:.2f} / {SLOWDOWN_LIMIT}x)")
    record["ok"] = not failures
    out_path.write_text(json.dumps(record, indent=1))
    print(json.dumps(record))
    if failures:
        print("COLLECTIVE FENCE FAILED:", "; ".join(failures),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
