"""Bench regression sentinel (ISSUE 15 satellite).

The committed bench artifacts (``SWARM_r12.json``, ``TENANT_r13.json``,
``MULTIHOST_r14.json``, ``DELTA_r10.json``, ``FLEET_r16.json``,
``MTTR_r17.json``, ``SERVE_r18.json``, ``PUSH_r19.json``,
``COLLECTIVE_r20.json``) carry the numbers each PR
was accepted on — but nothing re-checked them: a later PR regenerating
an artifact with a worse number (a peer-served ratio under its gate, a
speedup that quietly halved, a duplicate-fetch ratio creeping off zero)
would ship silently. This script is the sentinel: it re-parses every
committed artifact against (a) the artifact's own recorded ``gates``
block (every recorded gate must still read true) and (b) an explicit
tolerance table of floors/ceilings for the headline numbers — so a
regenerated artifact below its gate fails CI loud.

Tolerances are FLOORS, not equality: benches run on weather-grade CI
hosts, so the table pins "never ship worse than the gate the PR was
accepted on", not "reproduce the exact number".

Usage: python scripts/bench_trend.py [--root DIR]
Exit 0 = every artifact within tolerance; 1 = regression or a missing/
malformed artifact (an artifact that vanished is a failure too — the
sentinel must not pass vacuously).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys


def get(doc, path):
    """Slash-path lookup (gate keys themselves may contain dots); None
    when any hop is missing."""
    cur = doc
    for part in path.split("/"):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


# The tolerance table: (dotted path, op, bound, why).
#   ge/le — the recorded headline must stay on the right side of the
#           gate its PR was accepted on;
#   eq   — exact invariants (zero corruption, zero unit round trips);
#   truthy — recorded boolean gates that must still hold.
CHECKS: dict[str, list[tuple[str, str, object, str]]] = {
    "SWARM_r12.json": [
        ("gates/peer_served_ratio_ge_0.85", "truthy", None,
         "recorded swarm gate flipped false"),
        ("gates/corrupt_bytes_admitted_eq_0", "truthy", None,
         "recorded corruption gate flipped false"),
        ("gates/fairness_skew_le_2.0", "truthy", None,
         "recorded fairness gate flipped false"),
        ("gates/all_faults_fired", "truthy", None,
         "chaos run went vacuous (a fault never fired)"),
        ("shaped_chaos/peer_served_ratio", "ge", 0.85,
         "swarm peer-served ratio under chaos fell below the "
         "ISSUE-12 gate"),
        ("shaped_chaos/upload_fairness/skew", "le", 2.0,
         "per-seeder upload skew exceeds the fairness gate"),
        ("shaped_chaos/corrupt_bytes_admitted", "eq", 0,
         "corrupt bytes were admitted past the merkle boundary"),
    ],
    "TENANT_r13.json": [
        ("gates/all_ok", "truthy", None,
         "recorded tenant gate block flipped false"),
        ("gates/duplicate_fetch_ratio", "le", 0.05,
         "singleflight dedupe regressed: duplicate CDN fetches"),
        ("gates/zero_corrupt", "truthy", None,
         "tenant bench admitted corrupt bytes"),
        ("gates/killed_isolated", "truthy", None,
         "a killed tenant damaged its neighbors"),
        ("gates/pinned_never_evicted", "truthy", None,
         "disk pressure evicted a pinned cache entry"),
        ("saturation/dedupe/dedupe_hits", "ge", 1,
         "overlapping tenants shared zero in-flight fetches"),
    ],
    "MULTIHOST_r14.json": [
        ("shaped/speedup", "ge", 3.0,
         "coop speedup over the per-host baseline fell below the "
         "accepted floor (recorded 5.5x)"),
        ("shaped/coop/peer_served_ratio", "ge", 0.8,
         "pod peer-served ratio fell below the north-star floor"),
        ("shaped/coop/collective/unit_round_trips", "eq", 0,
         "the collective re-grew per-unit round trips"),
        ("shaped/coop/collective/aborts", "eq", 0,
         "the shaped collective bench aborted to point-to-point"),
        ("shaped/coop/fallbacks", "eq", 0,
         "coop units fell back to CDN in the clean shaped run"),
    ],
    "FLEET_r16.json": [
        ("gates/all_ok", "truthy", None,
         "recorded fleet gate block flipped false"),
        ("gates/peer_served_ratio_min", "ge", 0.90,
         "fleet peer-served ratio fell below the ISSUE-16 gate"),
        ("gates/peer_served_flat_pm_0.03", "truthy", None,
         "peer-served ratio no longer holds flat 256 -> 1024 hosts"),
        ("gates/cdn_egress_per_host_decreasing", "truthy", None,
         "CDN egress per host stopped decreasing with fleet size"),
        ("gates/federated_speedup_min", "ge", 1.3,
         "the federated 3-level schedule no longer beats the flat "
         "schedule by 1.3x on p99 time-to-HBM"),
        ("gates/gossip_converged_within_bound", "truthy", None,
         "gossip who-has convergence exceeded 2*ceil(log2 N) sweeps"),
        ("gates/digest_memory_bounded", "truthy", None,
         "gossip digest grew past its configured entry bound at "
         "1024 hosts"),
        ("gates/cold_pod_zero_cdn_for_warm", "truthy", None,
         "a cold pod sent CDN bytes for xorbs the fleet holds"),
    ],
    "MTTR_r17.json": [
        ("gates/classes_at_half_ok", "truthy", None,
         "fewer than 3 fault classes recover in <=0.5x the hands-off "
         "MTTR — the self-healing policy stopped paying for itself"),
        ("gates/corrupt_bytes_admitted", "eq", 0,
         "a chaos arm admitted corrupt bytes past the merkle boundary"),
        ("gates/all_faults_fired", "truthy", None,
         "chaos run went vacuous (a fault never fired hands-off)"),
        ("gates/remediations_have_series", "truthy", None,
         "an executed action shipped without before/after series"),
        ("gates/control_actions_executed", "eq", 0,
         "the policy engine healed a HEALTHY swarm (over-healing)"),
        ("gates/peer_ratio_ok", "truthy", None,
         "policy-on control run tanked the peer-served ratio"),
    ],
    "SERVE_r18.json": [
        ("gates/all_ok", "truthy", None,
         "recorded serving-pool gate block flipped false"),
        ("gates/ttft_cold_ratio", "le", 0.5,
         "pool cold TTFT no longer <= 0.5x the full-cold-pull-then-"
         "generate wall"),
        ("gates/digest_identical", "truthy", None,
         "an evict -> re-land round trip stopped being byte-identical"),
        ("gates/pinned_never_evicted", "truthy", None,
         "admission pressure evicted a pinned (decoding) model"),
        ("gates/expert_residency", "le", 0.5,
         "lazy MoE paging stopped bounding expert residency under 50%"),
        ("moe_experts/verified", "ge", 1,
         "expert page-ins shipped without digest verification"),
    ],
    "DELTA_r10.json": [
        ("delta_bytes_ratio", "le", 0.03,
         "a 1%-changed delta pull fetched more than the 3% gate"),
        ("swap_ratio", "le", 0.3,
         "hot-swap wall exceeded 0.3x the cold pull gate"),
        ("digest_identical", "truthy", None,
         "the hot-swapped tree is no longer byte-identical to cold"),
        ("tensors_reused", "ge", 1,
         "the per-tensor short-circuit reused nothing"),
    ],
    "COLLECTIVE_r20.json": [
        ("gates/all_ok", "truthy", None,
         "recorded transport-split gate block flipped false"),
        ("gates/digest_identical", "truthy", None,
         "a byte-exact backend (wire/split) stopped reconstructing "
         "source-identical digests on every host"),
        ("lossy/speedup_vs_wire", "ge", 1.2,
         "the lossy cross-slice tier no longer beats the byte-exact "
         "wire >=1.2x under WAN-class DCN shaping (recorded 1.4x)"),
        ("lossy/bits_saved_ratio", "ge", 0.5,
         "the ZQLS int8 tier stopped saving at least half the bytes "
         "on the payloads it quantizes (recorded 0.73)"),
        ("gates/lossy_cache_untouched", "truthy", None,
         "lossy units stopped landing in the HBM staging overlay"),
        ("gates/peer_served_ratio_equal", "truthy", None,
         "the lossy leg's peer-served ratio diverged from the wire "
         "leg — the speedup is no longer like-for-like"),
        ("gates/split_used_ici_lane", "truthy", None,
         "the jax backend moved zero intra-slice bytes through the "
         "ICI lane — the split quietly degraded to all-wire"),
        ("gates/preadv_identity", "truthy", None,
         "the preadv decode lane stopped being byte-identical"),
        ("gates/preadv_engaged", "truthy", None,
         "the preadv lane disengaged (zero stored-scheme terms)"),
        ("legs/lossy/fallbacks", "eq", 0,
         "lossy-leg units fell back to CDN in the clean shaped run"),
    ],
    "PUSH_r19.json": [
        ("gates/all_ok", "truthy", None,
         "recorded push/fan-out gate block flipped false"),
        ("push/dedup_ratio", "ge", 0.90,
         "a 1%-changed push re-uploaded more than 10% of checkpoint "
         "bytes — CDC dedup against the base regressed"),
        ("gates/byte_identical", "truthy", None,
         "the subscriber's pulled revision stopped being "
         "byte-identical to the pushed checkpoint"),
        ("gates/watch_delivered", "truthy", None,
         "the /v1/push notification no longer reaches /v1/watch "
         "subscribers"),
        ("fanout/propagation_s", "le", 60.0,
         "trainer-to-resident propagation exceeded the loopback "
         "bound"),
    ],
}


def check(op: str, value, bound) -> bool:
    if value is None:
        return False
    if op == "truthy":
        return bool(value)
    if op == "ge":
        return value >= bound
    if op == "le":
        return value <= bound
    if op == "eq":
        return value == bound
    raise ValueError(f"unknown op {op!r}")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default=None,
                    help="repo root holding the artifacts "
                         "(default: this script's parent's parent)")
    args = ap.parse_args()
    root = pathlib.Path(args.root) if args.root else \
        pathlib.Path(__file__).resolve().parent.parent

    failures: list[str] = []
    checked = 0
    for name, rules in sorted(CHECKS.items()):
        path = root / name
        try:
            doc = json.loads(path.read_text())
        except (OSError, ValueError) as exc:
            failures.append(f"{name}: unreadable artifact ({exc})")
            continue
        if doc.get("partial"):
            failures.append(
                f"{name}: artifact is marked partial — a crashed bench "
                "must be regenerated, not shipped as the record")
            continue
        for rule_path, op, bound, why in rules:
            value = get(doc, rule_path)
            checked += 1
            if not check(op, value, bound):
                bound_s = "" if op == "truthy" else f" (bound {bound})"
                failures.append(
                    f"{name}: {rule_path} = {value!r}{bound_s} — {why}")

    if failures:
        print("BENCH TREND FAILED — committed artifacts regressed "
              "below their recorded gates:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(f"bench trend OK: {checked} gates across "
          f"{len(CHECKS)} artifacts within tolerance")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
