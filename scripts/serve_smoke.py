"""CI smoke for the HBM serving pool (ISSUE 18).

Two models against the loopback fixture hub — a ~64 MiB llama
(scale=8: nine layers, the largest decode-consistent scale the
synthetic generator emits — deeper scales shrink kv_dim past
num_kv_heads * head_dim) and a small second tenant — driven through
the scale-to-zero serving cycle:

- the classic cold serve (full pull + family generator first token) is
  the baseline wall; the pool's re-land of the SAME model after an
  eviction must produce its first token in < 0.5x that wall, with the
  decode provably starting before the landing finished;
- while model A is pinned (an active decode holds it), model B's
  admission under a one-byte-slack budget must NOT evict A — the pool
  runs over budget instead of breaking a live decode;
- after a real eviction, the re-landed tree's ``params_digest`` is
  byte-identical to the original landing, and the re-served tokens
  match the pre-eviction tokens exactly.

Exit 0 on success; any broken invariant prints the pool summary and
fails the step.
"""

import json
import pathlib
import sys
import tempfile
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                       / "tests"))


def main() -> int:
    import numpy as np

    from fixtures import FixtureHub, FixtureRepo
    from zest_tpu.bench_scale import llama_checkpoint_files
    from zest_tpu.config import Config
    from zest_tpu.models import hbm_pool
    from zest_tpu.models.generate import load_generator
    from zest_tpu.transfer.pull import pull_model

    files_a = llama_checkpoint_files(0.064, shard_bytes=8 * 1024 * 1024,
                                     scale=8)
    files_b = llama_checkpoint_files(0.008, seed=1, scale=8)
    repo_a = FixtureRepo("smoke/serve-a", files_a, chunks_per_xorb=32)
    repo_b = FixtureRepo("smoke/serve-b", files_b, chunks_per_xorb=32)

    prompt = [1, 2, 3]
    steps = 4
    quiet = {"log": lambda *a, **k: None}
    with FixtureHub(repo_a, repo_b) as hub, \
            tempfile.TemporaryDirectory() as root:
        rootp = pathlib.Path(root)
        cfg = Config(hf_home=rootp / "hf", cache_dir=rootp / "zest",
                     hf_token="hf_test", endpoint=hub.url)

        # Baseline: the classic cold serve, request -> first token.
        t0 = time.perf_counter()
        res_a = pull_model(cfg, "smoke/serve-a", no_p2p=True, **quiet)
        snap_a = res_a.snapshot_dir
        first: dict = {}
        _mt, family = load_generator(snap_a)
        family(prompt, steps,
               on_token=lambda _p, _t: first.setdefault(
                   "t", time.perf_counter()))
        full_cold = first["t"] - t0

        pool = hbm_pool.HbmPool(cfg)

        def fail(msg: str) -> int:
            print(f"SERVE SMOKE FAILED: {msg}", file=sys.stderr)
            print(json.dumps(pool.summary(), indent=2, default=str),
                  file=sys.stderr)
            return 1

        try:
            out_first, _info = pool.generate_for(
                snap_a, "smoke/serve-a", prompt, steps)
            d0 = pool.digest(snap_a)
            if not d0:
                return fail("no digest for the resident tree")

            # Pinned A + one-byte-slack budget: B's admission must
            # leave A resident (over budget beats broken decodes).
            res_b = pull_model(cfg, "smoke/serve-b", no_p2p=True,
                               **quiet)
            entry_a, hot = pool.acquire(snap_a, "smoke/serve-a")
            if not hot:
                return fail("model A went cold while still resident")
            pool.budget = entry_a.reserved + 1
            pool.generate_for(res_b.snapshot_dir, "smoke/serve-b",
                              prompt, 2)
            if entry_a.state != "resident":
                return fail("admission pressure evicted a PINNED "
                            f"model (state={entry_a.state!r})")
            if pool.pinned_survivals < 1:
                return fail("the pinned-survival path never engaged")
            pool.release(entry_a)

            # Scale to zero, then the measured re-land serve.
            pool.budget = cfg.hbm_pool_bytes
            if not pool.evict(snap_a, "scale_to_zero"):
                return fail("could not evict the unpinned model A")
            if pool.digest(snap_a) is not None:
                return fail("evicted model still reports a digest")
            out_again, info = pool.generate_for(
                snap_a, "smoke/serve-a", prompt, steps)
            ttft = info["ttft_s"]
            if info["temp"] != "cold":
                return fail(f"re-land served {info['temp']}, not cold")
            if not info["decode_start_before_land_end"]:
                return fail("the gated decode waited for the full "
                            "land — first-layer-commit start did not "
                            "engage")
            if not ttft < 0.5 * full_cold:
                return fail(f"pool cold TTFT ({ttft:.3f}s) is not "
                            f"< 0.5 x the full cold serve wall "
                            f"({full_cold:.3f}s)")
            d1 = pool.digest(snap_a)
            if d1 != d0:
                return fail(f"re-landed digest {d1} != original {d0}")
            if not np.array_equal(np.asarray(out_again),
                                  np.asarray(out_first)):
                return fail("re-served tokens differ from the "
                            "pre-eviction serve")
            print(f"serve smoke OK: pool cold TTFT {ttft:.3f}s vs "
                  f"full cold serve {full_cold:.3f}s "
                  f"({ttft / full_cold:.0%}), gate stall "
                  f"{info['gate_stall_s']:.3f}s, digest {d0[:16]} "
                  "identical across evict -> re-land, pinned "
                  "survived pressure")
            return 0
        finally:
            pool.close()


if __name__ == "__main__":
    raise SystemExit(main())
