#!/usr/bin/env bash
# Two-process P2P test on one machine — the reference's Docker 2-node
# harness (test/local/p2p-docker-test.sh) without Docker: a seeder pulls
# CDN-only from a loopback fixture hub and serves its cache; a leecher
# with a separate cache pulls with --peer pointed at the seeder. PASS
# requires >0 bytes from peers (the reference's gate, p2p-docker-test.sh:
# 204-218); the fixture CDN stays reachable so the waterfall's fallback
# is honest.
set -euo pipefail

cd "$(dirname "$0")/.."
ROOT=$(mktemp -d)
REPO_ID="acme/loopback-model"
LISTEN_PORT=${LISTEN_PORT:-16881}
PIDS=()

cleanup() {
  for pid in "${PIDS[@]:-}"; do kill "$pid" 2>/dev/null || true; done
  rm -rf "$ROOT"
}
trap cleanup EXIT

say() { printf '\n=== %s ===\n' "$*"; }

say "start fixture hub"
python scripts/fixture_hub.py --url-file "$ROOT/hub.url" &
PIDS+=($!)
for _ in $(seq 1 50); do [ -s "$ROOT/hub.url" ] && break; sleep 0.2; done
[ -s "$ROOT/hub.url" ] || { echo "hub did not start"; exit 1; }
HUB_URL=$(cat "$ROOT/hub.url")
echo "hub: $HUB_URL"

common_env=(HF_ENDPOINT="$HUB_URL" HF_TOKEN=hf_test ZEST_NATIVE="${ZEST_NATIVE:-1}")

say "seeder: CDN-only pull"
env "${common_env[@]}" \
    HF_HOME="$ROOT/seeder/hf" ZEST_CACHE_DIR="$ROOT/seeder/zest" \
    python -m zest_tpu pull "$REPO_ID" --no-p2p --no-seed

say "seeder: serve"
env "${common_env[@]}" \
    HF_HOME="$ROOT/seeder/hf" ZEST_CACHE_DIR="$ROOT/seeder/zest" \
    ZEST_LISTEN_PORT="$LISTEN_PORT" ZEST_HTTP_PORT=19847 \
    python -m zest_tpu serve --listen-port "$LISTEN_PORT" --http-port 19847 \
        --dcn-port 0 &
PIDS+=($!)
python scripts/wait_for_port.py "$LISTEN_PORT" 10 \
    || { echo "seeder serve did not come up"; exit 1; }

say "leecher: pull with --peer"
env "${common_env[@]}" \
    HF_HOME="$ROOT/leecher/hf" ZEST_CACHE_DIR="$ROOT/leecher/zest" \
    python -m zest_tpu pull "$REPO_ID" \
      --peer "127.0.0.1:$LISTEN_PORT" --no-dht --no-seed \
  | tee "$ROOT/leecher.out"

say "verify"
PEER_BYTES=$(sed -n 's/.*From peers: \([0-9]*\) bytes.*/\1/p' "$ROOT/leecher.out")
CDN_BYTES=$(sed -n 's/.*From CDN: *\([0-9]*\) bytes.*/\1/p' "$ROOT/leecher.out")
echo "peer bytes: ${PEER_BYTES:-0}, cdn bytes: ${CDN_BYTES:-0}"
if [ -z "${PEER_BYTES:-}" ] || [ "$PEER_BYTES" -eq 0 ]; then
  echo "FAIL: no bytes served by the peer"
  exit 1
fi
# byte-identical files on both sides
python - "$ROOT" <<'EOF'
import sys
from pathlib import Path

root = Path(sys.argv[1])
def snapshot_file(side):
    hits = sorted((root / side / "hf").rglob("model.safetensors"))
    assert hits, f"no snapshot for {side}"
    return hits[0].read_bytes()

assert snapshot_file("seeder") == snapshot_file("leecher"), "payload mismatch"
print("payloads byte-identical")
EOF
echo "PASS: leecher fetched ${PEER_BYTES} bytes from the peer"
