"""MULTIHOST artifact driver: cooperative pull vs per-host CDN, and
the collective vs point-to-point exchange race (ROADMAP items 1+3,
ISSUE 14).

Writes ``MULTIHOST_r14.json``-style artifacts with two sections:

- ``unshaped`` — CDN at loopback speed (the honesty rows: on one
  machine everything is CPU/disk-bound and cooperation's win is
  modest);
- ``shaped``  — the hub's CDN data plane token-bucketed to a WAN-ish
  shared rate AND the DCN hub shaped (per-host serve-rate token bucket
  + one WAN round trip charged per request *window*, keyed on the v2
  wire tag): the asymmetry the reference's tier-3 scenario table
  measures. Under it the per-host baseline pays N x model_bytes
  through the shaped CDN, the cooperative pull pays ~1x + an exchange
  of *compressed* frames, and the exchange block races the PR-6
  point-to-point windows (per-owner windows + NOT_FOUND retry rounds,
  each paying the window RTT) against the collective's O(log N)
  pre-sized phase windows — same bytes, same peer_served_ratio, fewer
  round trips.

Usage: python scripts/coop_bench.py [--out MULTIHOST_r14.json]
       [--mb 64] [--hosts 8] [--cdn-mbps 16] [--dcn-rtt-ms 150]
       [--dcn-mbps 0] [--topology 0,0,0,0,1,1,1,1]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="MULTIHOST_r14.json")
    ap.add_argument("--mb", type=float, default=32.0,
                    help="checkpoint megabytes")
    ap.add_argument("--hosts", type=int, default=8)
    ap.add_argument("--cdn-mbps", type=float, default=4.0,
                    help="shaped CDN rate, MB/s shared across hosts "
                         "(~32 Mbps: a WAN-class origin allocation)")
    ap.add_argument("--dcn-rtt-ms", type=float, default=150.0,
                    help="shaped DCN: one WAN round trip charged per "
                         "request window (v2 wire-tag boundary)")
    ap.add_argument("--dcn-mbps", type=float, default=3.0,
                    help="shaped DCN: per-host cross-slice serve "
                         "rate, MB/s (0 = rate-unshaped, RTT only); "
                         "with --topology, intra-slice links stay "
                         "unshaped (the ICI-vs-DCN asymmetry)")
    ap.add_argument("--topology", default=None,
                    help="ZEST_COOP_TOPOLOGY-grammar slice spec "
                         "(e.g. 0,0,0,0,1,1,1,1) for ici/dcn link "
                         "classes")
    ap.add_argument("--skip-unshaped", action="store_true")
    args = ap.parse_args()

    from zest_tpu.bench_scale import bench_coop_pull

    out: dict = {
        "bench": "coop_pull",
        "hosts": args.hosts,
        "requested_mb": args.mb,
        # Honesty note: all N hosts share this machine's cores, so the
        # exchange (aggregate N*(N-1)/N x model bytes of loopback DCN +
        # verify in ONE process) is ~Nx under-provisioned vs a real pod
        # where each host brings its own CPUs and NIC; the shaped
        # speedup below is therefore a LOWER bound on the pod-scale
        # win, while the baseline is faithfully (N x bytes)/(CDN rate).
        "note": "single-machine simulation; exchange shares host CPUs",
    }
    if not args.skip_unshaped:
        print(f"[coop-bench] unshaped: {args.hosts} hosts, "
              f"{args.mb} MB ...", flush=True)
        out["unshaped"] = bench_coop_pull(gb=args.mb / 1000.0,
                                          n_hosts=args.hosts,
                                          topology=args.topology)
        print(json.dumps(out["unshaped"], indent=1), flush=True)
    rate = int(args.cdn_mbps * 1e6)
    print(f"[coop-bench] shaped: CDN {args.cdn_mbps} MB/s shared, "
          f"DCN rtt {args.dcn_rtt_ms} ms/window"
          + (f" @ {args.dcn_mbps} MB/s/host" if args.dcn_mbps else "")
          + " ...", flush=True)
    out["shaped"] = bench_coop_pull(
        gb=args.mb / 1000.0, n_hosts=args.hosts, shaped_bps=rate,
        dcn_rtt_s=args.dcn_rtt_ms / 1000.0,
        dcn_bps=int(args.dcn_mbps * 1e6) or None,
        topology=args.topology)
    print(json.dumps(out["shaped"], indent=1), flush=True)

    sh = out["shaped"]
    ok = True
    if (sh.get("speedup") or 0) < 2.0:
        print(f"FAIL: shaped cooperative speedup {sh.get('speedup')} "
              "< 2.0 — cooperation did not beat the per-host baseline",
              file=sys.stderr)
        ok = False
    wire = (sh.get("coop") or {}).get("wire") or {}
    if not (wire.get("compressed_ratio") or 1.0) < 1.0:
        print("FAIL: exchange wire bytes not smaller than unpacked — "
              "compressed frames did not cross the wire",
              file=sys.stderr)
        ok = False
    # ISSUE 14 acceptance: the collective exchange beats the
    # point-to-point exchange wall >=1.3x on the shaped sim, at equal
    # peer_served_ratio and with zero per-unit request round trips.
    xch = sh.get("exchange") or {}
    if (xch.get("collective_speedup") or 0) < 1.3:
        print(f"FAIL: collective exchange speedup "
              f"{xch.get('collective_speedup')} < 1.3 over "
              "point-to-point", file=sys.stderr)
        ok = False
    cxb = (xch.get("collective") or {}).get("collective") or {}
    if cxb.get("unit_round_trips", -1) != 0:
        print(f"FAIL: collective leg made "
              f"{cxb.get('unit_round_trips')} per-unit round trips "
              "(want 0)", file=sys.stderr)
        ok = False
    p_ratio = (xch.get("p2p") or {}).get("peer_served_ratio")
    c_ratio = (xch.get("collective") or {}).get("peer_served_ratio")
    if p_ratio != c_ratio:
        print(f"FAIL: peer_served_ratio diverged between exchange "
              f"legs (p2p {p_ratio} vs collective {c_ratio})",
              file=sys.stderr)
        ok = False
    pathlib.Path(args.out).write_text(json.dumps(out, indent=1) + "\n")
    print(f"[coop-bench] wrote {args.out}")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
