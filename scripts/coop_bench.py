"""MULTIHOST artifact driver: cooperative pull vs per-host CDN,
unshaped AND WAN-shaped (VERDICT r5 item 3 + ROADMAP item 1).

Writes ``MULTIHOST_r06.json``-style artifacts with two sections:

- ``unshaped`` — CDN at loopback speed (the honesty rows: on one
  machine everything is CPU/disk-bound and cooperation's win is
  modest);
- ``shaped``  — the hub's CDN data plane token-bucketed to a WAN-ish
  shared rate while the DCN exchange stays at loopback speed: the
  asymmetry the reference's tier-3 scenario table measures, under
  which the per-host baseline pays N x model_bytes through the shaped
  pipe and the cooperative pull pays ~1x + a loopback exchange of
  *compressed* frames.

Usage: python scripts/coop_bench.py [--out MULTIHOST_r06.json]
       [--mb 64] [--hosts 8] [--cdn-mbps 16]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="MULTIHOST_r06.json")
    ap.add_argument("--mb", type=float, default=64.0,
                    help="checkpoint megabytes")
    ap.add_argument("--hosts", type=int, default=8)
    ap.add_argument("--cdn-mbps", type=float, default=4.0,
                    help="shaped CDN rate, MB/s shared across hosts "
                         "(~32 Mbps: a WAN-class origin allocation)")
    ap.add_argument("--skip-unshaped", action="store_true")
    args = ap.parse_args()

    from zest_tpu.bench_scale import bench_coop_pull

    out: dict = {
        "bench": "coop_pull",
        "hosts": args.hosts,
        "requested_mb": args.mb,
        # Honesty note: all N hosts share this machine's cores, so the
        # exchange (aggregate N*(N-1)/N x model bytes of loopback DCN +
        # verify in ONE process) is ~Nx under-provisioned vs a real pod
        # where each host brings its own CPUs and NIC; the shaped
        # speedup below is therefore a LOWER bound on the pod-scale
        # win, while the baseline is faithfully (N x bytes)/(CDN rate).
        "note": "single-machine simulation; exchange shares host CPUs",
    }
    if not args.skip_unshaped:
        print(f"[coop-bench] unshaped: {args.hosts} hosts, "
              f"{args.mb} MB ...", flush=True)
        out["unshaped"] = bench_coop_pull(gb=args.mb / 1000.0,
                                          n_hosts=args.hosts)
        print(json.dumps(out["unshaped"], indent=1), flush=True)
    rate = int(args.cdn_mbps * 1e6)
    print(f"[coop-bench] shaped: CDN {args.cdn_mbps} MB/s shared ...",
          flush=True)
    out["shaped"] = bench_coop_pull(gb=args.mb / 1000.0,
                                    n_hosts=args.hosts,
                                    shaped_bps=rate)
    print(json.dumps(out["shaped"], indent=1), flush=True)

    sh = out["shaped"]
    ok = True
    if (sh.get("speedup") or 0) < 2.0:
        print(f"FAIL: shaped cooperative speedup {sh.get('speedup')} "
              "< 2.0 — cooperation did not beat the per-host baseline",
              file=sys.stderr)
        ok = False
    wire = (sh.get("coop") or {}).get("wire") or {}
    if not (wire.get("compressed_ratio") or 1.0) < 1.0:
        print("FAIL: exchange wire bytes not smaller than unpacked — "
              "compressed frames did not cross the wire",
              file=sys.stderr)
        ok = False
    pathlib.Path(args.out).write_text(json.dumps(out, indent=1) + "\n")
    print(f"[coop-bench] wrote {args.out}")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
