"""Generate the frozen golden fixtures under tests/golden/.

The fixtures pin the XETBLOB xorb layout, the LZ4 frame encoder output,
and the BG4/bitslice transforms against regression: once generated they
are CHECKED IN and must never be regenerated casually — a diff in the
frozen bytes means the on-disk/on-wire format changed, which breaks
interop with every previously-cached xorb (and, for the layouts shared
with production Xet, with HF's CAS). Regenerate only on a deliberate,
versioned format change.

Provenance: chunk payloads are deterministic (numpy PCG64 seed 42 +
fixed literals), so reviewers can confirm the .bin is exactly what
XorbBuilder emits for reproducible inputs — no opaque blobs.

Run: python scripts/gen_golden_fixtures.py
"""

from __future__ import annotations

import json
import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from zest_tpu.cas import compression as comp
from zest_tpu.cas.hashing import chunk_hash, file_hash, hash_to_hex
from zest_tpu.cas.xorb import XorbBuilder, parse_footer

GOLDEN = pathlib.Path(__file__).resolve().parent.parent / "tests" / "golden"


def golden_chunk_payloads() -> list[bytes]:
    """Deterministic chunk payloads covering every auto-selected scheme:
    incompressible (NONE), repetitive text (LZ4), smooth fp32 tensor
    bytes (BG4_LZ4), plus a second incompressible and a structured ramp."""
    rng = np.random.default_rng(42)
    return [
        rng.integers(0, 256, 12 * 1024, dtype=np.uint8).tobytes(),
        (b"the quick brown fox jumps over the lazy dog. " * 512)[: 20 * 1024],
        np.sin(np.linspace(0, 20, 4096)).astype(np.float32).tobytes(),
        rng.integers(0, 256, 9 * 1024, dtype=np.uint8).tobytes(),
        bytes(bytearray((i // 64) % 256 for i in range(16 * 1024))),
    ]


def gen_xorb() -> None:
    payloads = golden_chunk_payloads()
    builder = XorbBuilder()
    for p in payloads:
        builder.add_chunk(p)
    full = builder.serialize_full()
    (GOLDEN / "xorb_mixed.bin").write_bytes(full)

    frames_end, _xh, footer_hashes = parse_footer(full)
    assert frames_end == len(builder.serialize())
    n = len(footer_hashes)
    chunks = []
    for p, frame_off in zip(payloads, builder.frame_offsets()):
        scheme = comp.compress_auto(p)[0]
        chunks.append(
            {
                "chunk_hash": hash_to_hex(chunk_hash(p)),
                "scheme": int(scheme),
                "scheme_name": comp.Scheme(scheme).name,
                "uncompressed_len": len(p),
                "frame_offset": frame_off,
            }
        )
    meta = {
        "comment": "frozen XETBLOB layout fixture; see gen_golden_fixtures.py",
        "n_chunks": n,
        "xorb_hash": hash_to_hex(builder.xorb_hash()),
        "file_hash": hash_to_hex(file_hash(builder.chunk_hashes())),
        "frames_len": len(builder.serialize()),
        "full_len": len(full),
        "chunks": chunks,
    }
    (GOLDEN / "xorb_mixed.json").write_text(json.dumps(meta, indent=1))


def gen_lz4() -> None:
    cases = {
        "empty": b"",
        "hello": b"hello world, golden frame",
        "run": b"A" * 1000,
        "text": (b"the quick brown fox jumps over the lazy dog. " * 40),
        "ramp256": bytes(range(256)) * 8,
    }
    out = {}
    for name, payload in cases.items():
        frame = comp.lz4_frame_compress(payload)
        assert comp.lz4_frame_decompress(frame, len(payload)) == payload
        out[name] = {"payload_len": len(payload), "frame_hex": frame.hex()}
    fixed = bytes(range(32))
    out["_transforms"] = {
        "input_hex": fixed.hex(),
        "bg4_hex": comp._bg4(fixed).hex(),
        "bitslice_hex": comp._bitslice(fixed).hex(),
    }
    (GOLDEN / "lz4_frames.json").write_text(json.dumps(out, indent=1))


if __name__ == "__main__":
    GOLDEN.mkdir(parents=True, exist_ok=True)
    gen_xorb()
    gen_lz4()
    print("golden fixtures written to", GOLDEN)
