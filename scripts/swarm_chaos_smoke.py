"""CI smoke for the seeding tier + swarm capacity model (ISSUE 12).

Two gates, both on one loopback box:

1. **Chaos swarm** — ``bench_scale.bench_swarm`` at M=4 pullers x K=3
   seeders with an injected fault mix (serving-side corruption, seeder
   stalls, choke flaps, CDN 503s) over the production upload policy:

   - swarm-wide ``peer_served_ratio >= 0.8`` — the seeding tier carries
     the fleet even under faults;
   - ``corrupt_bytes_admitted == 0`` — every pulled file byte-compared
     against the fixture source (faults may slow the swarm, never
     poison it);
   - every fault named in the injected spec actually FIRED (a chaos
     gate that never provokes anything passes for the wrong reason);
   - at least one pull was answered (pulls_completed == M).

2. **Rate enforcement** — a seeder configured via the real
   ``ZEST_SEED_RATE_BPS`` env knob (through ``Config.load``, proving
   the wiring, not just the field) serves a ~1.5 MB xorb to a direct
   BT-wire fetch; the transfer must take at least 80% of the
   token-bucket floor (bytes minus burst, over rate) and the bytes
   must be exact — the knob is provably enforced within +-20%.

Exit 0 on success; prints the offending block and fails otherwise.
"""

import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                       / "tests"))

FAULT_SPEC = ("upload_corrupt:0.02,seeder_stall:0.05@0.3,"
              "seeder_choke_flap:0.1,cdn_503:0.1")
RATE_BPS = 1_500_000


def check_swarm() -> None:
    from zest_tpu.bench_scale import bench_swarm

    r = bench_swarm(gb=0.032, m_pullers=4, k_seeders=3, scale=4,
                    chunks_per_xorb=16, fault_spec=FAULT_SPEC,
                    fault_seed=1337)
    print(json.dumps(r, indent=1))
    assert r["pulls_completed"] == 4, f"pulls failed: {r.get('errors')}"
    assert r["corrupt_bytes_admitted"] == 0, (
        f"CORRUPT BYTES ADMITTED: {r['corrupt_bytes_admitted']}")
    ratio = r["peer_served_ratio"]
    assert ratio is not None and ratio >= 0.8, (
        f"peer_served_ratio {ratio} < 0.8 under the fault mix")
    wanted = {clause.split(":")[0] for clause in FAULT_SPEC.split(",")}
    fired = set(r["faults_fired"])
    assert wanted <= fired, (
        f"faults never fired: {sorted(wanted - fired)} "
        f"(a chaos gate that provokes nothing proves nothing)")
    skew = r["upload_fairness"]["skew"]
    assert skew is not None and skew <= 2.0, (
        f"upload fairness skew {skew} — one seeder is carrying the swarm")
    print(f"swarm gate OK: ratio={ratio} skew={skew} "
          f"faults={sorted(fired)}")


def check_rate_enforced() -> None:
    import os

    from fixtures import FixtureHub, FixtureRepo
    from zest_tpu import storage
    from zest_tpu.cas import hashing
    from zest_tpu.cas.xorb import XorbReader
    from zest_tpu.config import Config
    from zest_tpu.p2p import peer_id as peer_id_mod
    from zest_tpu.p2p.peer import BtPeer
    from zest_tpu.transfer.pull import pull_model
    from zest_tpu.transfer.server import BtServer

    import tempfile

    files = {"config.json": b"{}",
             "model.safetensors": os.urandom(1_500_000)}
    repo = FixtureRepo("smoke/seed-rate", files, chunks_per_xorb=64)
    with FixtureHub(repo) as hub, tempfile.TemporaryDirectory() as root:
        rootp = pathlib.Path(root)
        env = dict(os.environ)
        env.update({
            "HF_HOME": str(rootp / "hf"),
            "ZEST_CACHE_DIR": str(rootp / "zest"),
            "HF_ENDPOINT": hub.url,
            "HF_TOKEN": "hf_test",
            "ZEST_LISTEN_PORT": "0",
            "ZEST_SEED_RATE_BPS": str(RATE_BPS),
        })
        cfg = Config.load(env)
        assert cfg.seed_rate_bps == RATE_BPS, "env knob not wired"
        pull_model(cfg, "smoke/seed-rate", no_p2p=True,
                   log=lambda *a, **k: None)
        server = BtServer(cfg)
        port = server.start()
        try:
            cache = storage.XorbCache(cfg)
            key = max(storage.list_cached_xorbs(cfg),
                      key=lambda k: len(cache.get(k) or b""))
            blob = cache.get(key)
            n = len(XorbReader(blob))
            xorb_hash = hashing.hex_to_hash(key)
            peer = BtPeer.connect(
                "127.0.0.1", port,
                peer_id_mod.compute_info_hash(xorb_hash),
                peer_id_mod.generate())
            try:
                t0 = time.monotonic()
                result = peer.request_chunk(xorb_hash, 0, n)
                elapsed = time.monotonic() - t0
            finally:
                peer.close()
        finally:
            server.shutdown()
        assert result.data == blob, "shaped transfer corrupted bytes"
        floor = (len(blob) - RATE_BPS / 4) / RATE_BPS
        assert elapsed >= 0.8 * floor, (
            f"ZEST_SEED_RATE_BPS not enforced: {len(blob)}B in "
            f"{elapsed:.3f}s (floor {floor:.3f}s)")
        observed = len(blob) / elapsed
        print(f"rate gate OK: {len(blob)}B in {elapsed:.3f}s = "
              f"{observed / 1e6:.2f} MB/s vs knob {RATE_BPS / 1e6:.2f} "
              f"MB/s (floor {floor:.3f}s)")


def main() -> int:
    check_swarm()
    check_rate_enforced()
    print("swarm chaos smoke OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
