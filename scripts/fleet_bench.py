"""Fleet-scale topology bench driver (ISSUE 16 tentpole d).

Runs ``bench_scale.bench_fleet`` — the 256/512/1024-host simulation
over the 3-level ICI < DCN < WAN < CDN link matrix, driving the real
CoopPlan / CollectiveSchedule / GossipNode components through an
analytic clock — and writes ``FLEET_r16.json`` at the repo root. The
artifact's in-recorded ``gates`` block is what scripts/bench_trend.py
re-checks on every CI run:

- peer_served_ratio >= 0.90 and flat (+-0.03) from 256 to 1024 hosts;
- CDN egress bytes per host strictly decreasing with fleet size;
- the federated 3-stage schedule >= 1.3x the pod-blind flat schedule
  on p99 time-to-HBM in the WAN-bottlenecked regime;
- gossip who-has convergence within 2*ceil(log2 N) sweeps and digest
  memory under its configured bound at 1024 hosts;
- a cold pod's fetch fully served by warm pods (zero CDN bytes for
  warm-held xorbs).

Usage: python scripts/fleet_bench.py [--out FLEET_r16.json]
       [--sizes 256,512,1024] [--pod-size 64] [--gb 8.0]
Exit 0 when every gate holds; 1 otherwise (the artifact is still
written so the failure is inspectable).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=str(
        pathlib.Path(__file__).resolve().parent.parent
        / "FLEET_r16.json"))
    ap.add_argument("--sizes", default="256,512,1024")
    ap.add_argument("--pod-size", type=int, default=64)
    ap.add_argument("--gb", type=float, default=8.0)
    args = ap.parse_args()

    from zest_tpu.bench_scale import bench_fleet

    sizes = tuple(int(s) for s in args.sizes.split(","))
    t0 = time.perf_counter()
    out = bench_fleet(fleet_sizes=sizes, pod_size=args.pod_size,
                      model_gb=args.gb, out_path=args.out)
    out["bench_wall_s"] = round(time.perf_counter() - t0, 1)
    pathlib.Path(args.out).write_text(json.dumps(out, indent=2) + "\n")

    print(f"{'hosts':>6} {'pods':>5} {'peer_ratio':>10} "
          f"{'cdn/host MB':>11} {'flat p99 s':>10} {'fed p99 s':>9} "
          f"{'speedup':>7} {'gossip sweeps':>13}")
    for s in sizes:
        f = out["fleets"][str(s)]
        print(f"{f['hosts']:>6} {f['pods']:>5} "
              f"{f['peer_served_ratio']:>10.4f} "
              f"{f['cdn_egress_bytes_per_host'] / 1e6:>11.1f} "
              f"{f['flat']['p99_time_to_hbm_s']:>10.2f} "
              f"{f['federated']['p99_time_to_hbm_s']:>9.2f} "
              f"{f['federated_speedup']:>7.2f} "
              f"{f['gossip']['sweeps_to_converge']:>6}/"
              f"{f['gossip']['sweep_bound']}")
    gates = out["gates"]
    bad = [k for k, v in gates.items() if isinstance(v, bool) and not v]
    if bad:
        print(f"FLEET BENCH GATES FAILED: {bad}", file=sys.stderr)
        print(json.dumps(gates, indent=2), file=sys.stderr)
        return 1
    print(f"fleet bench OK in {out['bench_wall_s']}s -> {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
