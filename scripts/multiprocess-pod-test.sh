#!/usr/bin/env bash
# True multi-process distribution gate — the reference's Docker 2-node
# harness (test/local/p2p-docker-test.sh) upgraded to jax.distributed:
# two real jax processes on one machine (CPU backend, 4 virtual devices
# each) form one 8-device mesh, discover each other through the
# coordinator KV store (CoordinatorRegistry), move bytes over BT wire,
# and run a distributed pod_round with cross-process collectives.
#
# The heavy lifting lives in tests/test_multiprocess.py (launcher) +
# tests/_mp_pod_worker.py (per-process worker); this wrapper is the CI
# entry point, mirroring `zig build p2p-test` (build.zig:69-72).
set -euo pipefail

cd "$(dirname "$0")/.."
exec python -m pytest tests/test_multiprocess.py -q -m slow "$@"
