"""Standalone fixture Hub/CAS/CDN server for shell harnesses.

Serves a synthetic content-addressed repo over loopback HTTP so the shell
tests (scripts/p2p-loopback-test.sh) can drive the *real* CLI end-to-end
in a zero-egress environment — the role huggingface.co plays in the
reference's test/local/p2p-docker-test.sh.

Usage: python scripts/fixture_hub.py --url-file /tmp/hub.url [--size N]
Writes the base URL to --url-file once listening, then serves until
SIGTERM/SIGINT.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tests.fixtures import FixtureHub, FixtureRepo  # noqa: E402


def _gpt2_files() -> dict[str, bytes]:
    """A tiny but *valid* GPT-2 checkpoint (HF tensor names + config), so
    examples/pull_to_tpu_mesh.py can land and run it after pulling."""
    import io
    import json

    import numpy as np

    from zest_tpu.models import gpt2
    from zest_tpu.models.safetensors_io import write_safetensors

    cfg = dict(model_type="gpt2", vocab_size=256, n_positions=64, n_ctx=64,
               n_embd=64, n_layer=2, n_head=4, layer_norm_epsilon=1e-5)
    rng = np.random.default_rng(0)
    E, L = cfg["n_embd"], cfg["n_layer"]
    t = {
        "wte.weight": rng.normal(0, 0.02, (cfg["vocab_size"], E)),
        "wpe.weight": rng.normal(0, 0.01, (cfg["n_ctx"], E)),
        "ln_f.weight": np.ones(E), "ln_f.bias": np.zeros(E),
    }
    shapes = {
        "ln_1.weight": (E,), "ln_1.bias": (E,),
        "ln_2.weight": (E,), "ln_2.bias": (E,),
        "attn.c_attn.weight": (E, 3 * E), "attn.c_attn.bias": (3 * E,),
        "attn.c_proj.weight": (E, E), "attn.c_proj.bias": (E,),
        "mlp.c_fc.weight": (E, 4 * E), "mlp.c_fc.bias": (4 * E,),
        "mlp.c_proj.weight": (4 * E, E), "mlp.c_proj.bias": (E,),
    }
    for layer in range(L):
        for leaf, shape in shapes.items():
            init = (np.ones if leaf.endswith("ln_1.weight")
                    or leaf.endswith("ln_2.weight") else
                    lambda s: rng.normal(0, 0.02, s))
            t[f"h.{layer}.{leaf}"] = np.asarray(init(shape))
    tensors = {k: v.astype(np.float32) for k, v in t.items()}
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".safetensors") as f:
        write_safetensors(f.name, tensors)
        blob = Path(f.name).read_bytes()
    return {
        "config.json": json.dumps(cfg).encode(),
        "model.safetensors": blob,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--url-file", required=True)
    ap.add_argument("--repo", default="acme/loopback-model")
    ap.add_argument("--size", type=int, default=1_000_000,
                    help="safetensors payload bytes")
    ap.add_argument("--gpt2", action="store_true",
                    help="serve a tiny valid GPT-2 checkpoint instead of "
                         "random bytes (for the TPU landing example)")
    args = ap.parse_args()

    files = _gpt2_files() if args.gpt2 else {
        "config.json": b'{"model_type": "loopback"}',
        "model.safetensors": os.urandom(args.size),
    }
    repo = FixtureRepo(args.repo, files, chunks_per_xorb=2)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    with FixtureHub(repo) as hub:
        Path(args.url_file).write_text(hub.url)
        print(f"fixture hub for {args.repo} at {hub.url}", flush=True)
        stop.wait()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
