"""Standalone fixture Hub/CAS/CDN server for shell harnesses.

Serves a synthetic content-addressed repo over loopback HTTP so the shell
tests (scripts/p2p-loopback-test.sh) can drive the *real* CLI end-to-end
in a zero-egress environment — the role huggingface.co plays in the
reference's test/local/p2p-docker-test.sh.

Usage: python scripts/fixture_hub.py --url-file /tmp/hub.url [--size N]
Writes the base URL to --url-file once listening, then serves until
SIGTERM/SIGINT.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tests.fixtures import FixtureHub, FixtureRepo  # noqa: E402


def _gpt2_files() -> dict[str, bytes]:
    """Tiny valid GPT-2 checkpoint (shared generator in tests/fixtures)."""
    from tests.fixtures import gpt2_checkpoint_files

    return gpt2_checkpoint_files()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--url-file", required=True)
    ap.add_argument("--repo", default="acme/loopback-model")
    ap.add_argument("--size", type=int, default=1_000_000,
                    help="safetensors payload bytes")
    ap.add_argument("--throttle-bps", type=int, default=None,
                    help="shape the CDN data plane (/xorbs, /resolve "
                         "bodies) to this many bytes/s, shared across "
                         "all connections — the WAN-asymmetry knob for "
                         "the multihost harness")
    kind = ap.add_mutually_exclusive_group()
    kind.add_argument("--gpt2", action="store_true",
                      help="serve a tiny valid GPT-2 checkpoint instead of "
                           "random bytes (for the TPU landing example)")
    kind.add_argument("--llama", action="store_true",
                      help="serve a tiny valid Llama checkpoint (for the "
                           "finetune/export lifecycle example)")
    args = ap.parse_args()

    if args.llama:
        from tests.fixtures import llama_checkpoint_files

        files = llama_checkpoint_files()
    elif args.gpt2:
        files = _gpt2_files()
    else:
        files = {
            "config.json": b'{"model_type": "loopback"}',
            "model.safetensors": os.urandom(args.size),
        }
    repo = FixtureRepo(args.repo, files, chunks_per_xorb=2)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    with FixtureHub(repo, throttle_bps=args.throttle_bps) as hub:
        Path(args.url_file).write_text(hub.url)
        shaped = (f" (CDN shaped to {args.throttle_bps} B/s)"
                  if args.throttle_bps else "")
        print(f"fixture hub for {args.repo} at {hub.url}{shaped}",
              flush=True)
        stop.wait()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
