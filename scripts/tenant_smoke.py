"""CI smoke for the multi-tenant pull service (ISSUE 13).

4 tenants x 2 overlapping ~64 MiB revisions (revision B chunk-dedups
against A) pulled concurrently through ONE process' shared pools over
a shaped loopback CDN, with one tenant killed mid-pull. The gates:

- **duplicate-fetch ratio ~0**: every (xorb, byte-range) unit crosses
  the CDN exactly once across all tenants (singleflight dedupe + the
  shared verified cache). The gate allows the acceptance criterion's
  0.02 — a transport-level timeout under the shaped link can
  legitimately retry one unit — and most runs measure exactly 0.0;
- **digest identity**: every surviving tenant's snapshot is
  byte-identical to a solo pull of the same revision — concurrency
  admitted no corrupt byte;
- **tenant fault isolation**: the killed tenant finishes with the
  ``cancelled`` terminal status (not ``error``) and every other
  tenant's pull succeeds unharmed;
- **pinned survival**: the induced disk-pressure phase evicts under
  live pins without touching a single pinned entry.

(The ``ZEST_TENANCY=0`` knob-off byte/schema identity is pinned by
``tests/test_tenancy.py``, which runs in the test job — not here.)

Exit 0 on success; any broken invariant prints the offending block
and fails the step.
"""

import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from zest_tpu.bench_scale import bench_tenants  # noqa: E402


def main() -> int:
    out = bench_tenants(
        gb=0.064,
        k_tenants=4,
        n_models=2,
        max_pulls=3,
        shaped_bps=64_000_000,
        fault_spec=None,      # chaos coverage lives in the full bench
        disk_pressure=True,
        kill_tenant=True,
        chunks_per_xorb=16,
        scale=8,
    )
    gates = out["gates"]
    checks = {
        "duplicate_fetch_ratio_ok":
            gates["duplicate_fetch_ratio"] <= 0.02,
        "all_digests_identical": gates["zero_corrupt"],
        "killed_tenant_isolated": gates["killed_isolated"],
        "pinned_never_evicted": gates["pinned_never_evicted"],
        "dedupe_hits_nonzero":
            out["saturation"]["dedupe"]["dedupe_hits"] > 0,
    }
    print(json.dumps({"gates": gates,
                      "saturation": {
                          k: out["saturation"][k]
                          for k in ("p50_pull_s", "p99_pull_s",
                                    "cdn_fetches", "distinct_units",
                                    "dedupe", "statuses")},
                      "checks": checks}, indent=2))
    failed = [name for name, ok in checks.items() if not ok]
    if failed:
        print(f"FAILED gates: {failed}", file=sys.stderr)
        print(json.dumps(out, indent=2), file=sys.stderr)
        return 1
    print("tenant smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
