#!/usr/bin/env bash
# Real-network end-to-end integrity gate — the reference's
# test/local/verify-model.sh analog (reference: :90-147): pull a real
# xet-backed repo from huggingface.co through the full CAS client into an
# isolated HF_HOME, then load it with transformers OFFLINE and assert
# parameter count + greedy generation. Records wall-clock and per-source
# byte stats to a JSON report.
#
# Requires network egress to huggingface.co — this is exactly the check
# that CAN'T run against loopback fixtures: it proves the chunking/
# hashing/xorb/reconstruction stack speaks to the production CAS. Run it
# wherever egress exists:
#
#   scripts/verify-model.sh [repo_id] [report.json]
#
# Defaults: openai-community/gpt2 → E2E_REAL.json. HF_TOKEN is optional
# (gpt2 is public). The pytest twin is tests/test_real_e2e.py
# (ZEST_E2E_REAL=1).
set -euo pipefail

cd "$(dirname "$0")/.."
REPO_ID="${1:-openai-community/gpt2}"
REPORT="${2:-E2E_REAL.json}"
ROOT=$(mktemp -d)
trap 'rm -rf "$ROOT"' EXIT

say() { printf '\n=== %s ===\n' "$*"; }

say "preflight: egress to huggingface.co"
python - <<'EOF' || { echo "NO NETWORK EGRESS — cannot run the real-e2e gate"; exit 2; }
import urllib.request
urllib.request.urlopen("https://huggingface.co/api/models/gpt2", timeout=10)
EOF

say "pull $REPO_ID (CDN waterfall tier; P2P off — single node)"
START=$(python -c 'import time; print(time.monotonic())')
env HF_HOME="$ROOT/hf" ZEST_CACHE_DIR="$ROOT/zest" \
    python -m zest_tpu pull "$REPO_ID" --no-p2p --no-seed | tee "$ROOT/pull.log"
END=$(python -c 'import time; print(time.monotonic())')

say "verify: offline transformers load + generation"
env HF_HOME="$ROOT/hf" HF_HUB_OFFLINE=1 TRANSFORMERS_OFFLINE=1 \
    REPO_ID="$REPO_ID" PULL_SECONDS="$(python -c "print($END-$START)")" \
    PULL_LOG="$ROOT/pull.log" REPORT="$REPORT" \
    python - <<'EOF'
import json, os, re, sys

from transformers import AutoModelForCausalLM, AutoTokenizer

repo = os.environ["REPO_ID"]
model = AutoModelForCausalLM.from_pretrained(repo)
tok = AutoTokenizer.from_pretrained(repo)
n_params = sum(p.numel() for p in model.parameters())
assert n_params > 100_000_000, f"only {n_params} params"
prompt = "The quick brown fox"
ids = tok(prompt, return_tensors="pt").input_ids
out = model.generate(ids, max_new_tokens=8, do_sample=False)
text = tok.decode(out[0], skip_special_tokens=True)
assert text.startswith(prompt), text
print(f"OK: {n_params:,} params; generated: {text!r}")

log = open(os.environ["PULL_LOG"]).read()
def grab(pat):
    m = re.search(pat, log)
    return int(m.group(1)) if m else None
report = {
    "repo": repo,
    "wall_clock_seconds": float(os.environ["PULL_SECONDS"]),
    "n_params": n_params,
    "generated": text,
    "bytes_from_peers": grab(r"From peers:\s*(\d+)"),
    "bytes_from_cdn": grab(r"From CDN:\s*(\d+)"),
}
json.dump(report, open(os.environ["REPORT"], "w"), indent=1)
print("report ->", os.environ["REPORT"])
EOF

say "PASS"
