"""CI smoke for the transport/schedule split (ISSUE 20).

A 4-host, two-slice (``0,0,1,1``) in-process exchange — every host's
plan share pre-warmed, loopback DCN servers AND the loopback fabric
registered under the same addresses — runs once per exchange backend
(``ZEST_COLLECTIVE_BACKEND`` = ``dcn`` / ``loopback`` / ``jax``) and
asserts, per backend:

- the round completes collectively: no abort, zero exchange fallbacks,
  zero per-unit round trips;
- **digest identity in byte-exact mode**: every file reconstructs on
  every host, from that host's own cache with NO bridge (a missing
  unit fails loudly instead of healing from the CDN), to the same
  sha256 the fixture was generated with — the transport swap must
  never change a byte;
- the stats schema keeps the restore-pre-split pin: ``backend`` only
  appears off the default, never ``lossy``.

Then the degradation leg: with ``dcn_reset:1.0`` installed, the SAME
round on the jax backend must abort the collective mid-phase and walk
the PR-6 ladder (point-to-point also resets, the CDN waterfall heals)
— the fault fires, the collective stats record the abort, and every
file STILL lands byte-identical.

Exit 0 on success; prints the offending stats block otherwise.
"""

from __future__ import annotations

import hashlib
import json
import pathlib
import sys
import tempfile
import threading

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                       / "tests"))

N_HOSTS = 4
TOPOLOGY = "0,0,1,1"
REPO_ID = "smoke/transport-split"
BACKENDS = ("dcn", "loopback", "jax")


def main() -> int:
    import numpy as np

    from fixtures import FixtureHub, FixtureRepo
    from zest_tpu import faults
    from zest_tpu.cas.hub import HubClient
    from zest_tpu.config import Config, parse_topology
    from zest_tpu.models.direct import CachedFileReader
    from zest_tpu.transfer import transport
    from zest_tpu.transfer.bridge import XetBridge
    from zest_tpu.transfer.coop import CoopPlan, coop_round
    from zest_tpu.transfer.dcn import DcnServer
    from zest_tpu.transfer.federated import warm_units_parallel

    rng = np.random.default_rng(21)
    files = {
        "shard0.f32.bin":
            rng.standard_normal(1_000_000).astype("<f4").tobytes(),
        "blob.bin": rng.bytes(2_000_000),
    }
    source_sha = {k: hashlib.sha256(v).hexdigest()
                  for k, v in files.items()}
    repo = FixtureRepo(REPO_ID, files, chunks_per_xorb=4)
    topo = parse_topology(TOPOLOGY)

    def fail(msg: str, blob=None) -> int:
        print(f"TRANSPORT SMOKE FAILED: {msg}", file=sys.stderr)
        if blob is not None:
            print(json.dumps(blob, indent=2, default=str),
                  file=sys.stderr)
        return 1

    def run_round(hub, rootp, tag: str, backend: str):
        """One prewarmed 4-host collective round on ``backend``;
        returns (per-host stats, per-host digest-ok, hosts)."""
        transport.reset_loopback()
        hosts = []
        for i in range(N_HOSTS):
            cfg = Config(hf_home=rootp / f"{tag}{i}/hf",
                         cache_dir=rootp / f"{tag}{i}/zest",
                         hf_token="hf_test", endpoint=hub.url,
                         dcn_port=0, coop_collective=True,
                         coop_topology=topo,
                         collective_backend=backend)
            bridge = XetBridge(cfg)
            bridge.authenticate(REPO_ID)
            recs = [bridge.get_reconstruction(e.xet_hash)
                    for e in HubClient(cfg).list_files(REPO_ID)
                    if e.is_xet]
            hosts.append((bridge, recs))
        servers, addrs = [], {}
        for i, (bridge, _recs) in enumerate(hosts):
            s = DcnServer(bridge.cfg, bridge.cache)
            addrs[i] = ("127.0.0.1", s.start())
            servers.append(s)
            transport.register_loopback(addrs[i], bridge.cfg,
                                        bridge.cache)
        try:
            def warm(i):
                bridge, recs = hosts[i]
                plan = CoopPlan.build(recs, N_HOSTS)
                warm_units_parallel(bridge, recs,
                                    units=plan.for_host(i))

            ws = [threading.Thread(target=warm, args=(i,))
                  for i in range(N_HOSTS)]
            for t in ws:
                t.start()
            for t in ws:
                t.join()

            results: list[dict | None] = [None] * N_HOSTS
            errs: list[str] = []

            def run(i):
                bridge, recs = hosts[i]
                try:
                    results[i] = coop_round(bridge, recs, i, N_HOSTS,
                                            addrs, server=servers[i])
                except Exception as exc:  # noqa: BLE001
                    errs.append(f"host {i}: {exc!r}")

            threads = [threading.Thread(target=run, args=(i,))
                       for i in range(N_HOSTS)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errs:
                return None, errs, hosts

            digests_ok = True
            for i, (bridge, recs) in enumerate(hosts):
                entries = [e for e in
                           HubClient(bridge.cfg).list_files(REPO_ID)
                           if e.is_xet]
                for e in entries:
                    rec = bridge.get_reconstruction(e.xet_hash)
                    reader = CachedFileReader(bridge.cache, rec)
                    sha = hashlib.sha256(
                        reader.read(0, reader.size)).hexdigest()
                    if sha != source_sha[e.path]:
                        digests_ok = False
                        errs.append(f"host {i}: {e.path} digest "
                                    "mismatch from own cache")
            return results, (digests_ok, errs), hosts
        finally:
            for s in servers:
                s.shutdown()
            transport.reset_loopback()

    with FixtureHub(repo) as hub, tempfile.TemporaryDirectory() as root:
        rootp = pathlib.Path(root)

        # — Per-backend conformance: same round, three transports. —
        for backend in BACKENDS:
            faults.install(None)
            results, (digests_ok, errs), hosts = run_round(
                hub, rootp, f"b_{backend}", backend)
            for b, _r in hosts:
                b.close()
            if results is None:
                return fail(f"[{backend}] round raised", errs)
            done = [r for r in results if r]
            if len(done) != N_HOSTS:
                return fail(f"[{backend}] only {len(done)}/{N_HOSTS} "
                            "hosts completed", results)
            for i, r in enumerate(done):
                cx = r.get("collective")
                if not cx:
                    return fail(f"[{backend}] host {i} ran without "
                                "the collective schedule", r)
                if cx.get("aborted"):
                    return fail(f"[{backend}] host {i} aborted the "
                                "clean round", cx)
                if cx["unit_round_trips"] != 0:
                    return fail(f"[{backend}] host {i} re-grew "
                                "per-unit round trips", cx)
                if r["fallbacks"] != 0:
                    return fail(f"[{backend}] host {i} fell back on "
                                "the healthy path", r)
                if "lossy" in cx:
                    return fail(f"[{backend}] lossy armed without "
                                "opt-in", cx)
                want = None if backend == "dcn" else backend
                if cx.get("backend") != want:
                    return fail(f"[{backend}] stats backend pin "
                                f"broken (got {cx.get('backend')!r}, "
                                f"want {want!r})", cx)
            if not digests_ok:
                return fail(f"[{backend}] digest identity broken",
                            errs)
            ratio = min(r["peer_served_ratio"] for r in done)
            print(f"[{backend}] ok: 4-host round collective, "
                  f"peer_served_ratio>={ratio}, digests identical "
                  "on every host from its own cache")

        # — Degradation: dcn_reset:1.0 on the jax backend must abort
        #   the collective and heal down the PR-6 ladder to CDN. —
        faults.install("dcn_reset:1.0", 1337)
        try:
            results, (digests_ok, errs), hosts = run_round(
                hub, rootp, "chaos", "jax")
            fired = dict(faults.counters())
        finally:
            faults.install(None)
        for b, _r in hosts:
            b.close()
        if results is None:
            return fail("chaos leg raised instead of degrading", errs)
        if not fired.get("dcn_reset"):
            return fail("chaos leg: dcn_reset never fired", fired)
        aborted = sum(1 for r in results
                      if r and r.get("collective", {}).get("aborted"))
        healed = sum(r["fallbacks"] for r in results if r)
        if not aborted:
            return fail("chaos leg: no host recorded a collective "
                        "abort", results)
        if not healed:
            return fail("chaos leg: nothing walked the fallback "
                        "ladder", results)
        if not digests_ok:
            return fail("chaos leg: ladder healed to wrong bytes",
                        errs)
        print(f"chaos ok: dcn_reset fired {fired['dcn_reset']}x, "
              f"{aborted} host(s) aborted the collective, "
              f"{healed} unit(s) healed down the ladder, digests "
              "identical")

    print("transport smoke OK: dcn/loopback/jax rounds digest-"
          "identical; jax degrades down the PR-6 ladder under "
          "dcn_reset")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
