"""Serving-pool artifact driver: HBM as a managed multi-model cache
(ISSUE 18).

Writes ``SERVE_r18.json``: the scale-to-zero serving bench. Baseline
arm is a classic cold serve — full throttled-network pull plus family
generator first token; pool arm re-lands the evicted model from its
local snapshot with the decode gated on per-layer commits. The
``gates`` block is the acceptance surface:

- ``ttft_ok`` — pool cold TTFT <= 0.5x the full-cold-pull-then-
  generate wall;
- ``digest_identical`` — the re-landed tree's ``params_digest`` is
  byte-identical to the original landing;
- ``pinned_never_evicted`` — a pinned (actively decoding) tree
  survives admission pressure with a one-byte-slack budget;
- ``experts_ok`` — the MoE serve's expert residency stays under 50%
  with every page-in digest-verified.

Usage: python scripts/serve_bench.py [--out SERVE_r18.json]
       [--runs 3] [--mb 20] [--throttle-mbps 200]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="SERVE_r18.json")
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--mb", type=float, default=20.0)
    ap.add_argument("--throttle-mbps", type=float, default=200.0)
    ap.add_argument("--budget-s", type=float, default=None)
    args = ap.parse_args()

    from zest_tpu.bench_scale import bench_serve_pool

    out: dict = {
        # Honesty note: one box, loopback hub — the baseline's network
        # share is synthetic (token-bucket throttle). The pull_s field
        # makes that share visible; the local re-land beating a real
        # WAN pull would only widen the ratio.
        "note": "single-box loopback; baseline network is a "
                "token-bucket throttle — pull_s shows its share",
    }
    out.update(bench_serve_pool(gb=args.mb / 1024.0, runs=args.runs,
                                throttle_mbps=args.throttle_mbps,
                                budget_s=args.budget_s))
    print(json.dumps(out, indent=1))
    ok = out["gates"]["all_ok"]
    pathlib.Path(args.out).write_text(json.dumps(out, indent=1) + "\n")
    print(f"wrote {args.out} (gates {'OK' if ok else 'FAILED'}: "
          f"{json.dumps(out['gates'])})")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
