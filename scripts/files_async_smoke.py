"""CI smoke for the background file-materialization contract (ISSUE 5).

A 64 MiB synthetic ``--device`` pull against the loopback fixture hub
must report, schema-level (no wall-clock thresholds — CI runners are
weather):

- ``time_to_hbm_s < elapsed_s`` — the pull was *usable* (params
  resident, verified) strictly before it finished: file materialization
  ran past the landing instead of serializing into it;
- ``files_after_hbm_s > 0`` — the files span overlaps the post-commit
  window (the durability barrier alone guarantees a non-empty overlap
  when the write-behind lane engaged);
- the lane accounting exists and the safetensors bytes on disk are
  exact.

Exit code 0 on success; any broken invariant prints the offending
stats block and fails the step.
"""

import json
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                       / "tests"))


def main() -> int:
    from fixtures import FixtureHub, FixtureRepo
    from zest_tpu.bench_scale import llama_checkpoint_files
    from zest_tpu.config import Config
    from zest_tpu.transfer.pull import pull_model

    files = llama_checkpoint_files(0.064, shard_bytes=16 * 1024 * 1024,
                                   scale=8)
    repo = FixtureRepo("smoke/files-async", files, chunks_per_xorb=32)
    with FixtureHub(repo) as hub, tempfile.TemporaryDirectory() as root:
        rootp = pathlib.Path(root)
        cfg = Config(hf_home=rootp / "hf", cache_dir=rootp / "zest",
                     hf_token="hf_test", endpoint=hub.url)
        res = pull_model(cfg, "smoke/files-async", device="tpu",
                         no_p2p=True, log=lambda *a, **k: None)
        stats = res.stats

        def fail(msg: str) -> int:
            print(f"FILES-ASYNC SMOKE FAILED: {msg}", file=sys.stderr)
            print(json.dumps({k: stats.get(k) for k in (
                "time_to_hbm_s", "elapsed_s", "files_after_hbm_s",
                "stages", "files_pipeline", "hbm")}, indent=2,
                default=str), file=sys.stderr)
            return 1

        hbm = stats.get("hbm") or {}
        if not hbm.get("direct"):
            return fail("pull did not take the direct landing")
        if "time_to_hbm_s" not in stats:
            return fail("no time_to_hbm_s recorded")
        if not stats["time_to_hbm_s"] < stats["elapsed_s"]:
            return fail(
                f"time_to_hbm_s ({stats['time_to_hbm_s']}) did not end "
                f"before the pull ({stats['elapsed_s']}) — "
                "materialization is back on the critical path")
        if not stats.get("files_after_hbm_s", 0) > 0:
            return fail("files span does not overlap the post-commit "
                        f"window (files_after_hbm_s="
                        f"{stats.get('files_after_hbm_s')})")
        lanes = (stats.get("files_pipeline") or {}).get("lane_bytes") or {}
        if not lanes:
            return fail("no lane accounting in files_pipeline")
        for name, data in files.items():
            got = (res.snapshot_dir / name).read_bytes()
            if got != data:
                return fail(f"{name} materialized inexactly")
        print("files-async smoke OK: "
              f"time_to_hbm {stats['time_to_hbm_s']}s < total "
              f"{stats['elapsed_s']}s, files_after_hbm "
              f"{stats['files_after_hbm_s']}s, lanes {lanes}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
