"""CI gate for the critical-path analyzer (ISSUE 11).

A traced 64 MiB synthetic ``--device`` pull against the loopback
fixture hub must produce a ``stats["critical_path"]`` report that

- covers >=90% of ``time_to_hbm_s`` (the attribution is the pull, not
  a sliver of it),
- has a stage split that sums to the path length (the blame tiles the
  wall — no double counting, no dropped segments),
- is reproduced by the analyzer run over the *exported* trace doc
  (``zest analyze`` path): same stages within tolerance,

and an injected ``cdn_503`` chaos run must shift blame toward the
fetch stage — the analyzer's whole point is that a degraded CDN shows
up as fetch blame without a human reading the trace.

Usage: python scripts/critpath_smoke.py [--size BYTES]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                       / "tests"))


def traced_pull(hub, repo_id: str, files: dict, fault_spec=None):
    from zest_tpu import faults, telemetry
    from zest_tpu.config import Config
    from zest_tpu.telemetry import trace as trace_mod
    from zest_tpu.transfer.pull import pull_model

    telemetry.reset_all()
    telemetry.set_enabled(True)
    tracer = trace_mod.install(None)
    if fault_spec:
        faults.install(fault_spec, seed=1337)
    else:
        faults.reset()
    try:
        with tempfile.TemporaryDirectory() as root:
            rootp = pathlib.Path(root)
            cfg = Config(hf_home=rootp / "hf", cache_dir=rootp / "zest",
                         hf_token="hf_test", endpoint=hub.url)
            res = pull_model(cfg, repo_id, device="tpu", no_p2p=True,
                             log=lambda *a, **k: None)
            return res.stats, tracer
    finally:
        faults.reset()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=float, default=0.064,
                    help="checkpoint GB (default 0.064 = 64 MiB)")
    args = ap.parse_args()

    from fixtures import FixtureHub, FixtureRepo
    from zest_tpu.bench_scale import llama_checkpoint_files
    from zest_tpu.telemetry import critpath

    files = llama_checkpoint_files(args.size,
                                   shard_bytes=8 * 1024 * 1024, scale=8)
    repo = FixtureRepo("smoke/critpath", files, chunks_per_xorb=16)

    def fail(msg: str, blob=None) -> int:
        print(f"CRITPATH SMOKE FAILED: {msg}", file=sys.stderr)
        if blob is not None:
            print(json.dumps(blob, indent=2, default=str),
                  file=sys.stderr)
        return 1

    with FixtureHub(repo) as hub:
        stats, tracer = traced_pull(hub, "smoke/critpath", files)
        cp = stats.get("critical_path")
        if not cp:
            return fail("traced pull carried no stats['critical_path']",
                        sorted(stats))
        tth = stats.get("time_to_hbm_s")
        if tth is None:
            return fail("no time_to_hbm_s on a --device pull", stats)
        # Gate 1: the attributed path covers >=90% of the landing wall.
        if cp["path_s"] < 0.9 * tth:
            return fail(f"path {cp['path_s']}s < 90% of "
                        f"time_to_hbm_s {tth}s", cp)
        # Gate 2: the stage split sums to the path length (the blame
        # tiles the wall; rounding tolerance only).
        split_sum = sum(cp["stages"].values())
        if abs(split_sum - cp["path_s"]) > 0.01 + 1e-4 * len(cp["stages"]):
            return fail(f"stage split sums to {split_sum:.4f}s, path is "
                        f"{cp['path_s']}s", cp)
        # Gate 3: the exported-doc analyzer (the `zest analyze` path)
        # reproduces the live split.
        doc = tracer.to_chrome()
        offline = critpath.analyze_doc(doc)
        for stage, sec in cp["stages"].items():
            got = offline["stages"].get(stage, 0.0)
            if abs(got - sec) > 0.02 + 0.02 * sec:
                return fail(
                    f"offline analyzer disagrees on {stage}: live "
                    f"{sec}s vs exported {got}s",
                    {"live": cp["stages"], "offline": offline["stages"]})
        clean_fetch = cp["stages"].get("fetch", 0.0) / cp["path_s"]

        # Gate 4: chaos attribution — a flapping CDN must shift blame
        # toward fetch (503s burn retry+backoff wall inside the fetch
        # spans; everything else is unchanged).
        chaos_stats, _ = traced_pull(hub, "smoke/critpath", files,
                                     fault_spec="cdn_503:0.35")
        ccp = chaos_stats.get("critical_path")
        if not ccp:
            return fail("chaos pull carried no critical_path")
        if not chaos_stats.get("faults", {}).get("cdn_503"):
            return fail("cdn_503 never fired — chaos run is vacuous",
                        chaos_stats.get("faults"))
        chaos_fetch = ccp["stages"].get("fetch", 0.0) / ccp["path_s"]
        if not chaos_fetch > clean_fetch:
            return fail(
                f"injected cdn_503 did not shift blame to fetch: "
                f"clean {clean_fetch:.1%} vs chaos {chaos_fetch:.1%}",
                {"clean": cp["stages"], "chaos": ccp["stages"]})

    print("critpath smoke OK: "
          f"path {cp['path_s']}s covers {cp['path_s'] / tth:.0%} of "
          f"time_to_hbm {tth}s; split {cp['stages']}; "
          f"fetch share {clean_fetch:.1%} -> {chaos_fetch:.1%} under "
          "cdn_503")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
