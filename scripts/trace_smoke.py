"""Telemetry rot guard: a synthetic pull with ``ZEST_TRACE`` on must
produce a valid, non-trivial Chrome trace (ISSUE 4 CI satellite).

Spins the in-process fixture hub with a 64 MiB safetensors payload,
runs a CDN-only pull with the span tracer armed, then fails loudly if
the exported trace is empty, malformed, or covers less than 90% of the
pull's wall time — the acceptance bar. Silent telemetry regressions
(a span() call site dropped, export format broken, the env knob dead)
all land here instead of in a fleet dashboard weeks later.

Usage: python scripts/trace_smoke.py [--size BYTES] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tests"))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=64 * 1024 * 1024,
                    help="safetensors payload bytes (default 64 MiB)")
    ap.add_argument("--out", default=None,
                    help="trace path (default: tempdir/trace.json)")
    args = ap.parse_args()

    work = Path(tempfile.mkdtemp(prefix="zest-trace-smoke-"))
    trace_path = Path(args.out) if args.out else work / "trace.json"
    # The satellite's contract is the ENV knob, not the API: arm the
    # tracer exactly the way an operator would.
    os.environ["ZEST_TRACE"] = str(trace_path)
    os.environ.pop("ZEST_TELEMETRY", None)

    from zest_tpu import telemetry
    from zest_tpu.config import Config
    from zest_tpu.transfer.pull import pull_model
    from fixtures import FixtureHub, FixtureRepo

    files = {
        "config.json": b'{"model_type": "smoke"}',
        "model.safetensors": os.urandom(args.size),
    }
    repo = FixtureRepo("acme/trace-smoke", files, chunks_per_xorb=4)
    with FixtureHub(repo) as hub:
        cfg = Config(hf_home=work / "hf", cache_dir=work / "zest",
                     hf_token="hf_test", endpoint=hub.url)
        result = pull_model(cfg, "acme/trace-smoke", no_p2p=True)

    tracer = telemetry.trace.active()
    if tracer is None:
        print("FAIL: ZEST_TRACE did not arm the tracer", file=sys.stderr)
        return 1
    telemetry.trace.export(trace_path)  # atexit would too; validate now

    try:
        doc = json.loads(trace_path.read_text())
    except (OSError, ValueError) as exc:
        print(f"FAIL: trace unreadable/malformed: {exc}", file=sys.stderr)
        return 1
    events = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    problems = []
    if not events:
        problems.append("trace has no spans")
    names = {e.get("name", "") for e in events}
    if "pull" not in names:
        problems.append("no root 'pull' span")
    if not any(n.startswith("stage.") for n in names):
        problems.append("no stage.* spans")
    for e in events:
        if not (isinstance(e.get("ts"), (int, float))
                and isinstance(e.get("dur"), (int, float))
                and e.get("dur") >= 0):
            problems.append(f"malformed event: {e}")
            break
    elapsed = result.stats["elapsed_s"]
    coverage = tracer.coverage_s()
    if coverage < 0.9 * elapsed:
        problems.append(
            f"span coverage {coverage:.2f}s < 90% of {elapsed:.2f}s wall")
    if problems:
        for p in problems:
            print(f"FAIL: {p}", file=sys.stderr)
        return 1
    print(f"OK: {len(events)} spans, coverage {coverage:.2f}s / "
          f"{elapsed:.2f}s wall, {result.stats['fetch']['bytes']['cdn']} "
          f"CDN bytes -> {trace_path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
