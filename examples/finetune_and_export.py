"""The full model lifecycle: pull → finetune → checkpoint → export.

Pull a Llama-family checkpoint through the swarm, fine-tune it with the
optax loop (AdamW, warmup+cosine, donated steps), checkpoint the
TrainState with orbax, and export the result back to HF safetensors —
which loads with ``transformers.from_pretrained`` unchanged.

Run against a real repo (network required), or point HF_ENDPOINT at the
fixture hub's Llama-shaped repo for a no-network demo:

    python examples/finetune_and_export.py meta-llama/Llama-3.2-1B

    # offline (JAX_PLATFORMS=cpu keeps a dead TPU tunnel from hanging
    # backend init — the guard below pins it):
    python scripts/fixture_hub.py --url-file /tmp/hub.url --llama &
    while [ ! -s /tmp/hub.url ]; do sleep 0.2; done
    HF_ENDPOINT=$(cat /tmp/hub.url) HF_TOKEN=hf_test JAX_PLATFORMS=cpu \
        python examples/finetune_and_export.py acme/loopback-model
"""

import functools
import json
import sys
from pathlib import Path

import os

import jax

if os.environ.get("JAX_PLATFORMS"):
    # Belt-and-braces (see bench.py / the verify notes): sitecustomize
    # registers the axon TPU plugin before this script runs, and with a
    # dead chip tunnel the plugin can hang backend init even when
    # JAX_PLATFORMS requests cpu — pinning the config makes the env var
    # reliably win.
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp
import numpy as np

import zest_tpu as zest
from zest_tpu.models import llama
from zest_tpu.models.checkpoint import (
    export_hf_safetensors,
    restore_train_state,
    save_train_state,
)
from zest_tpu.models.generate import snapshot_tensors
from zest_tpu.models.training import adamw, create_state, make_train_step


def main() -> int:
    repo = sys.argv[1] if len(sys.argv) > 1 else "meta-llama/Llama-3.2-1B"
    snapshot = Path(zest.pull(repo))
    print(f"pulled {repo} -> {snapshot}")

    cfg = llama.LlamaConfig.from_hf(
        json.loads((snapshot / "config.json").read_text())
    )
    params = llama.params_from_hf(snapshot_tensors(snapshot), cfg)

    tx = adamw(lr=1e-4, warmup_steps=10, total_steps=1000)
    step = make_train_step(tx, functools.partial(llama.loss_fn, cfg=cfg))
    state = create_state(params, tx)

    # Stand-in data: random tokens. Real training swaps in a data loader.
    rng = np.random.default_rng(0)
    batch = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (2, 129)), jnp.int32
    )
    for _ in range(5):
        state, loss = step(state, batch)
        print(f"step {int(state.step)}: loss {float(loss):.4f}")

    # Outputs live in the model's cache dir but OUTSIDE snapshots/ —
    # entries there are HF revisions, and cache introspection treats the
    # newest snapshots/ dir as the current revision.
    out_dir = snapshot.parent.parent
    ckpt = out_dir / f"trainstate_step{int(state.step)}"
    save_train_state(ckpt, state)
    state = restore_train_state(ckpt, state)
    print(f"checkpointed + restored at step {int(state.step)} -> {ckpt}")

    out = out_dir / "finetuned.safetensors"
    export_hf_safetensors(out, state.params, cfg)
    print(f"exported HF-format weights -> {out}")
    print("load with: transformers.LlamaForCausalLM + load_state_dict")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
