"""Pull a model through the swarm, then verify it loads.

The reference's example (examples/download_model.py) pulls gpt2 via
zest.pull() and checks it with transformers; this is the same flow on
zest-tpu. Run against the real Hub (needs network + HF_TOKEN for Xet
repos), or against the loopback fixture hub for an offline demo:

    python scripts/fixture_hub.py --url-file /tmp/hub.url &
    while [ ! -s /tmp/hub.url ]; do sleep 0.2; done
    HF_ENDPOINT=$(cat /tmp/hub.url) HF_TOKEN=hf_test \
        python examples/download_model.py acme/loopback-model
"""

import sys

import zest_tpu as zest


def main() -> int:
    repo = sys.argv[1] if len(sys.argv) > 1 else "openai-community/gpt2"
    path = zest.pull(repo)
    print(f"pulled {repo} -> {path}")

    import json
    from pathlib import Path

    cfg = json.loads((Path(path) / "config.json").read_text())
    if cfg.get("model_type") == "loopback":
        print("fixture repo pulled OK (synthetic weights; skipping load)")
        return 0
    try:
        from transformers import AutoModelForCausalLM, AutoTokenizer
    except ImportError:
        print("transformers not installed; skipping load check")
        return 0
    model = AutoModelForCausalLM.from_pretrained(path)
    tok = AutoTokenizer.from_pretrained(path)
    n_params = sum(p.numel() for p in model.parameters())
    print(f"loaded: {n_params / 1e6:.1f}M parameters")
    out = model.generate(
        **tok("The quick brown", return_tensors="pt"), max_new_tokens=8
    )
    print("generate:", tok.decode(out[0]))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
