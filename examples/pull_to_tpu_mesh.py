"""The north-star flow: pull a checkpoint and land it sharded in HBM.

``pull --device=tpu`` ends with the weights already resident where the
model runs: safetensors tensors are committed straight into jax.Arrays
laid out for a pjit mesh (zest_tpu.models.loader), then the pure-JAX
GPT-2 consumes them in place — no torch, no disk round-trip after the
cache write, forward jitted onto the MXU.

Run on a TPU host (or CPU with a virtual mesh):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/pull_to_tpu_mesh.py openai-community/gpt2
"""

import json
import sys
from pathlib import Path

import os

import jax

if os.environ.get("JAX_PLATFORMS"):
    # Belt-and-braces (see bench.py / the verify notes): sitecustomize
    # registers the axon TPU plugin before this script runs, and with a
    # dead chip tunnel the plugin can hang backend init even when
    # JAX_PLATFORMS requests cpu — pinning the config makes the env var
    # reliably win.
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp

import zest_tpu as zest
from zest_tpu.models import gpt2, loader
from zest_tpu.parallel.mesh import model_mesh


def main() -> int:
    repo = sys.argv[1] if len(sys.argv) > 1 else "openai-community/gpt2"
    snapshot = Path(zest.pull(repo))
    print(f"pulled {repo} -> {snapshot}")

    n = len(jax.devices())
    mesh = model_mesh({"data": max(1, n // 4), "model": min(4, n)})
    print(f"mesh: {dict(mesh.shape)}")

    cfg = gpt2.GPT2Config.from_hf(
        json.loads((snapshot / "config.json").read_text())
    )
    # Land the raw checkpoint sharded (Megatron-style rules), then map it
    # onto the stacked param tree the scan-based forward wants.
    tensors = loader.load_checkpoint(
        snapshot, mesh=mesh, rules=gpt2.checkpoint_shard_rules()
    )
    params = gpt2.params_from_hf(tensors, cfg, dtype=jnp.bfloat16)

    ids = jnp.zeros((1, 16), jnp.int32)
    logits = jax.jit(lambda p, i: gpt2.forward(p, i, cfg))(params, ids)
    print(f"forward OK: logits {logits.shape} {logits.dtype} on "
          f"{jax.devices()[0].platform}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
