"""Serving flow: pull once, then stream tokens over the REST API.

The daemon's ``POST /v1/generate`` (zest_tpu.api.http_api — the working
replacement for the reference's stubbed ``POST /v1/pull``,
src/http_api.zig:138-142) pulls the repo if needed and decodes with the
family's KV-cached path: batched prompt prefill, then one sampled token
per step, each emitted as its own SSE event the moment the compiled
scan produces it (``"stream": true``). The decode is one cached jitted
program per request signature, so the first request compiles and
repeats run at device speed.

Run against a real server:

    zest-tpu serve &                  # REST on :9847
    python examples/serve_and_stream.py openai-community/gpt2

or self-contained against the loopback fixture hub (fixture repos carry
no tokenizer, so pass raw prompt ids as the second argument; the while
loop waits for the hub to write its url file):

    python scripts/fixture_hub.py --url-file /tmp/hub.url --gpt2 &
    while [ ! -s /tmp/hub.url ]; do sleep 0.2; done
    HF_ENDPOINT=$(cat /tmp/hub.url) HF_TOKEN=hf_test \
        python examples/serve_and_stream.py acme/loopback-model 1,2,3
"""

import json
import sys

import requests

import zest_tpu as zest


def main() -> int:
    repo = sys.argv[1] if len(sys.argv) > 1 else "openai-community/gpt2"
    zest.enable()  # start the daemon if it isn't running
    # The daemon records its BOUND http port (ZEST_HTTP_PORT=0 binds an
    # ephemeral one); effective_http_port resolves it either way.
    from zest_tpu.config import Config

    port = Config.load().effective_http_port()

    body = {
        "repo_id": repo,
        "steps": 24,
        "temperature": 0.8,
        "top_p": 0.95,
        "stream": True,
    }
    if len(sys.argv) > 2:
        # Raw token ids (fixture repos carry no tokenizer files).
        body["ids"] = [int(t) for t in sys.argv[2].split(",")]
    else:
        body["prompt"] = "The pod woke up and"
    r = requests.post(f"http://127.0.0.1:{port}/v1/generate",
                      json=body, stream=True, timeout=600)
    r.raise_for_status()
    pending_ids: list[int] = []
    for line in r.iter_lines(decode_unicode=True):
        if not line.startswith("data: "):
            continue
        ev = json.loads(line[len("data: "):])
        if ev["event"] == "token":
            # A token event may omit "text" while the server holds back
            # an incomplete UTF-8/BPE sequence — those characters arrive
            # merged into a LATER event's diff, so printing a
            # placeholder would interleave spurious '<id>' markers with
            # real text. Buffer id-only events instead: a text event
            # clears the buffer (the held characters arrived merged into
            # its diff), and whatever is still pending at 'done' — the
            # no-tokenizer case, or a stream truncated mid-sequence —
            # is flushed as trailing '<id>' markers.
            if "text" in ev:
                pending_ids.clear()  # their text arrived merged here
                print(ev["text"], end="", flush=True)
            else:
                pending_ids.append(ev["id"])
        elif ev["event"] == "done":
            print("".join(f"<{t}>" for t in pending_ids), end="")
            print()
            print(f"[done: {len(ev['ids'])} ids]")
        elif ev["event"] == "error":
            print(f"\nerror: {ev['message']}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
