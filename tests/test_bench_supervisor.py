"""bench.py supervisor: the one-JSON-line contract under backend death.

Round 4's driver artifact was lost because the bench process touched a
dead TPU backend before printing anything (BENCH_r04.json: rc=1,
parsed:null). The supervisor redesign makes that structurally
impossible; these tests pin it by running the REAL bench.py as the
driver does, with the backend forced into each failure mode. The
reference analog is bench.zig's unconditional JSON emission
(src/bench.zig:273-287).
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
BENCH = REPO / "bench.py"

SKIP_ALL = "pull_gb,host_to_hbm,decode,http_warm,ici_all_gather"


def run_bench(platform: str, probe_timeout: str = "120") -> dict:
    env = dict(os.environ, JAX_PLATFORMS=platform, ZEST_BENCH_SMOKE="1",
               ZEST_BENCH_SKIP=SKIP_ALL,
               ZEST_BENCH_PROBE_TIMEOUT_S=probe_timeout,
               ZEST_BENCH_CHILD_TIMEOUT_S="600")
    env.pop("ZEST_BENCH_CHILD", None)
    out = subprocess.run([sys.executable, str(BENCH)], env=env,
                         capture_output=True, text=True, timeout=900,
                         cwd=str(REPO))
    assert out.returncode == 0, out.stderr[-800:]
    line = out.stdout.strip().splitlines()[-1]
    return json.loads(line)


@pytest.mark.slow
def test_supervisor_healthy_backend():
    """Happy path: CPU backend up, JSON carries the primary metric."""
    r = run_bench("cpu")
    assert r["metric"] == "blake3_64kb_device"
    assert r["value"] > 0
    assert r["device"] == "cpu"
    assert "tpu_error" not in r


@pytest.mark.slow
def test_supervisor_survives_dead_backend():
    """The r04 regression: a backend that cannot initialize must cost a
    fallback, never the JSON line. `bogus` makes jax's backend init
    raise exactly where axon's did (xla_bridge.backends)."""
    r = run_bench("bogus")
    assert r["metric"] == "blake3_64kb_device"
    assert r["value"] > 0
    assert r["device"] == "cpu"  # fell back
    assert "bogus" in r["tpu_error"]


def _load_bench_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_under_test", BENCH)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


def test_probe_retry_recovers_transient_outage(monkeypatch):
    """The chip tunnel hiccups transiently (observed: a probe hanging
    >180s minutes after the same chip answered). One failed probe must
    cost a retry, not the round's on-chip artifact — and a SUCCESSFUL
    retry must clear the failure, run the child on the chip, and leave
    no tpu_error in the JSON."""
    import contextlib
    import io

    m = _load_bench_module()
    probes: list = []

    def fake_probe(platform, timeout):
        probes.append(platform)
        if platform is None and probes.count(None) == 1:
            return None, "backend init hung >1s"  # first attempt: outage
        return ("tpu" if platform is None else platform), None

    children: list = []

    def fake_child(platform, timeout):
        children.append(platform)
        return {"metric": "x", "device": "tpu", "extra": {}}, None

    monkeypatch.setattr(m, "_probe_backend", fake_probe)
    monkeypatch.setattr(m, "_run_child", fake_child)
    monkeypatch.setattr(m.time, "sleep", lambda s: None)
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        m.main()
    out = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert children == [None]  # the chip-capable attempt ran the child
    assert out["device"] == "tpu"
    assert "tpu_error" not in out


@pytest.mark.slow
def test_mid_set_death_leaves_finished_rows():
    """VERDICT r5 item 1 (first half): the child checkpoints the
    artifact after every metric, so a death mid-set must still emit the
    finished rows. ZEST_BENCH_DIE_AFTER is the child's test hook — it
    hard-exits right after persisting the named metric."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", ZEST_BENCH_SMOKE="1",
               ZEST_BENCH_SKIP=("pull_gb,host_to_hbm,decode,http_warm,"
                                "ici_all_gather,mfu,decode_batch,"
                                "http_warm_device"),
               ZEST_BENCH_DIE_AFTER="host_synthetics",
               ZEST_BENCH_PROBE_TIMEOUT_S="120",
               ZEST_BENCH_CHILD_TIMEOUT_S="600")
    env.pop("ZEST_BENCH_CHILD", None)
    out = subprocess.run([sys.executable, str(BENCH)], env=env,
                         capture_output=True, text=True, timeout=900,
                         cwd=str(REPO))
    assert out.returncode == 0, out.stderr[-800:]
    r = json.loads(out.stdout.strip().splitlines()[-1])
    # The recovered artifact: primary metric + the one finished extra,
    # flagged partial with the death recorded.
    assert r["partial"] is True
    assert r["metric"] == "blake3_64kb_device"
    assert r["value"] > 0
    assert "host_synthetics" in r["extra"]
    assert "rc=86" in r["partial_error"]
    assert "rc=86" in r.get("backend_errors", r.get("tpu_error", ""))


def test_partial_tpu_artifact_beats_cpu_fallback(monkeypatch):
    """A TPU child that dies mid-set but leaves recovered rows must be
    EMITTED (partial on-chip rows beat a complete CPU artifact), with
    the death recorded — not silently replaced by the cpu attempt."""
    import contextlib
    import io

    m = _load_bench_module()
    children: list = []

    def fake_probe(platform, timeout):
        return ("tpu" if platform is None else platform), None

    def fake_child(platform, timeout):
        children.append(platform)
        return {"metric": "x", "device": "tpu", "extra": {"mfu": {}},
                "partial": True, "partial_error": "child died rc=9"}, None

    monkeypatch.setattr(m, "_probe_backend", fake_probe)
    monkeypatch.setattr(m, "_run_child", fake_child)
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        m.main()
    out = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert children == [None], "cpu fallback ran despite recovered rows"
    assert out["device"] == "tpu"
    assert out["partial"] is True
    assert "child died rc=9" in out["tpu_error"]


def test_load_partial_rejects_junk(tmp_path):
    m = _load_bench_module()
    p = tmp_path / "partial.json"
    assert m._load_partial(str(p)) is None  # missing
    p.write_text("{not json")
    assert m._load_partial(str(p)) is None  # malformed
    p.write_text('{"no_metric": 1}')
    assert m._load_partial(str(p)) is None  # never reached the primary
    p.write_text('{"metric": "blake3_64kb_device", "value": 1}')
    assert m._load_partial(str(p))["value"] == 1


def test_probe_retry_exhausted_falls_back(monkeypatch):
    """Both probes of the chip-capable attempt fail -> the cpu attempt
    runs instead and the JSON records both probe failures."""
    import contextlib
    import io

    m = _load_bench_module()

    def fake_probe(platform, timeout):
        if platform is None:
            return None, "backend init hung >1s"
        return platform, None

    def fake_child(platform, timeout):
        return {"metric": "x", "device": "cpu", "extra": {}}, None

    monkeypatch.setattr(m, "_probe_backend", fake_probe)
    monkeypatch.setattr(m, "_run_child", fake_child)
    monkeypatch.setattr(m.time, "sleep", lambda s: None)
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        m.main()
    out = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert out["device"] == "cpu"
    assert "retry" in out["tpu_error"]
