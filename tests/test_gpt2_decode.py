"""GPT-2 KV-cached incremental decode: cache correctness at every
position, token parity with the full-recompute path, and with torch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zest_tpu.models import gpt2


def test_decode_step_matches_full_forward():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(1, 10)),
                      jnp.int32)
    full = np.asarray(gpt2.forward(params, ids, cfg))
    cache = gpt2.init_kv_cache(cfg, 1, 10)
    for pos in range(10):
        logits, cache = gpt2.decode_step(
            params, cache, ids[:, pos], pos, cfg
        )
        np.testing.assert_allclose(np.asarray(logits[0]), full[0, pos],
                                   atol=1e-4, rtol=1e-4)


def test_generate_cached_matches_greedy():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(jax.random.key(2), cfg)
    prompt = [4, 9, 30]
    want = gpt2.generate_greedy(params, cfg, prompt, steps=10)
    got = gpt2.generate_cached(params, cfg, prompt, steps=10)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generate_cached_matches_torch_greedy():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    torch.manual_seed(0)
    hf_cfg = transformers.GPT2Config(
        vocab_size=256, n_positions=64, n_embd=64, n_layer=2, n_head=4,
    )
    model = transformers.GPT2LMHeadModel(hf_cfg)
    model.eval()
    cfg = gpt2.GPT2Config.from_hf(hf_cfg.to_dict())
    state = {k: v.detach().numpy() for k, v in model.state_dict().items()
             if not k.endswith(".attn.bias")}
    params = gpt2.params_from_hf(state, cfg)
    prompt = [3, 14, 15]
    got = gpt2.generate_cached(params, cfg, prompt, steps=8)
    with torch.no_grad():
        want = model.generate(torch.tensor([prompt]), max_new_tokens=8,
                              do_sample=False)
    np.testing.assert_array_equal(np.asarray(got), want[0].numpy())


def test_generate_cached_rejects_overflow():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(jax.random.key(3), cfg)
    with pytest.raises(ValueError, match="exceeds"):
        gpt2.generate_cached(params, cfg, [1] * 60, steps=10)


def test_batched_generate_matches_per_row():
    """(B, T0) prompts decode row-independently: batched greedy output
    equals B separate single-prompt decodes (gpt2 and llama)."""
    from zest_tpu.models import llama

    prompts = np.asarray([[3, 14, 15], [9, 2, 6], [40, 41, 1]])
    for mod, cfg in (
        (gpt2, gpt2.GPT2Config.tiny()),
        (llama, llama.LlamaConfig.tiny()),
    ):
        params = mod.init_params(jax.random.key(7), cfg)
        batched = mod.generate_cached(params, cfg, prompts, steps=6)
        assert batched.shape == (3, 9)
        for i in range(3):
            single = mod.generate_cached(params, cfg, prompts[i], steps=6)
            np.testing.assert_array_equal(np.asarray(batched[i]),
                                          np.asarray(single),
                                          err_msg=mod.__name__)


def test_legacy_prng_key_accepted():
    """jax.random.PRNGKey (raw uint32) still works as the rng arg."""
    from zest_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(9), cfg)
    legacy = llama.generate_cached(params, cfg, [1, 2], steps=4,
                                   temperature=1.0,
                                   rng=jax.random.PRNGKey(3))
    typed = llama.generate_cached(params, cfg, [1, 2], steps=4,
                                  temperature=1.0, rng=jax.random.key(3))
    np.testing.assert_array_equal(np.asarray(legacy), np.asarray(typed))


def test_batched_sampling_rows_are_independent():
    from zest_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(8), cfg)
    prompts = np.asarray([[1, 2], [1, 2], [1, 2]])
    out = llama.generate_cached(params, cfg, prompts, steps=10,
                                temperature=2.0,
                                rng=jax.random.key(5))
    # Same prompt, different per-row keys → at least two rows differ.
    rows = {tuple(np.asarray(r)) for r in out}
    assert len(rows) > 1


# ── MoE (Mixtral) cached decode ──


def test_moe_decode_step_matches_full_forward():
    """Per-position cache correctness. capacity_factor is raised so the
    full forward drops no tokens — decode is per-token (capacity ≥
    top_k per token, the serving semantics), so parity only holds when
    batch-capacity contention is out of the picture."""
    from zest_tpu.models import moe

    cfg = moe.MoEConfig.tiny(capacity_factor=8.0)
    params = moe.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(1, 8)),
                      jnp.int32)
    full, _aux = moe.forward(params, ids, cfg)
    full = np.asarray(full)
    cache = moe.init_kv_cache(cfg, 1, 8)
    for pos in range(8):
        logits, cache = moe.decode_step(
            params, cache, ids[:, pos], pos, cfg
        )
        np.testing.assert_allclose(np.asarray(logits[0]), full[0, pos],
                                   atol=1e-4, rtol=1e-4)


def test_moe_batched_decode_has_per_token_capacity():
    """Batched decode must equal independent per-row decodes: each token
    dispatches with its own expert capacity, so B tokens crowding one
    expert can't drop anyone to the residual path (regression: shared
    batch capacity C = f(B) silently zeroed contributions)."""
    from zest_tpu.models import moe

    cfg = moe.MoEConfig.tiny(capacity_factor=0.1)  # tight on purpose
    params = moe.init_params(jax.random.key(4), cfg)
    # Bias the router hard toward expert 0 so all tokens collide.
    params["blocks"]["moe"]["router_w"] = (
        params["blocks"]["moe"]["router_w"].at[..., 0].set(10.0)
    )
    tokens = jnp.asarray([5, 9, 13], jnp.int32)
    cache3 = moe.init_kv_cache(cfg, 3, 4)
    batched, _ = moe.decode_step(params, cache3, tokens, 0, cfg)
    for i in range(3):
        cache1 = moe.init_kv_cache(cfg, 1, 4)
        single, _ = moe.decode_step(params, cache1, tokens[i:i + 1], 0, cfg)
        np.testing.assert_allclose(np.asarray(batched[i]),
                                   np.asarray(single[0]),
                                   atol=1e-5, rtol=1e-5)


def test_moe_generate_cached_runs_and_is_deterministic():
    from zest_tpu.models import moe

    cfg = moe.MoEConfig.tiny()
    params = moe.init_params(jax.random.key(1), cfg)
    a = moe.generate_cached(params, cfg, [3, 5], steps=6)
    b = moe.generate_cached(params, cfg, [3, 5], steps=6)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (8,)
    assert list(np.asarray(a[:2])) == [3, 5]


def test_mixtral_generate_via_registry(tmp_path):
    """load_generator dispatches mixtral to the MoE cached decode."""
    import json

    from zest_tpu.models import moe
    from zest_tpu.models.generate import load_generator
    from zest_tpu.models.safetensors_io import write_safetensors
    from tests.test_moe import _hf_mixtral_tensors

    cfg = moe.MoEConfig.tiny(n_layer=1, n_experts=4)
    snap = tmp_path / "snap"
    snap.mkdir()
    write_safetensors(snap / "model.safetensors", _hf_mixtral_tensors(cfg))
    (snap / "config.json").write_text(json.dumps(dict(
        model_type="mixtral", vocab_size=cfg.vocab_size,
        hidden_size=cfg.n_embd, intermediate_size=cfg.d_ff,
        num_hidden_layers=cfg.n_layer,
        num_attention_heads=cfg.n_head,
        num_key_value_heads=cfg.n_kv_head,
        num_local_experts=cfg.n_experts,
        num_experts_per_tok=cfg.top_k,
        max_position_embeddings=cfg.n_ctx,
    )))
    model_type, generate = load_generator(snap)
    assert model_type == "mixtral"
    out = generate([1, 2], 5)
    assert out.shape == (7,) and list(out[:2]) == [1, 2]
