"""GPT-2 KV-cached incremental decode: cache correctness at every
position, token parity with the full-recompute path, and with torch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zest_tpu.models import gpt2


def test_decode_step_matches_full_forward():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(1, 10)),
                      jnp.int32)
    full = np.asarray(gpt2.forward(params, ids, cfg))
    cache = gpt2.init_kv_cache(cfg, 1, 10)
    for pos in range(10):
        logits, cache = gpt2.decode_step(
            params, cache, ids[:, pos], pos, cfg
        )
        np.testing.assert_allclose(np.asarray(logits[0]), full[0, pos],
                                   atol=1e-4, rtol=1e-4)


def test_generate_cached_matches_greedy():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(jax.random.key(2), cfg)
    prompt = [4, 9, 30]
    want = gpt2.generate_greedy(params, cfg, prompt, steps=10)
    got = gpt2.generate_cached(params, cfg, prompt, steps=10)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generate_cached_matches_torch_greedy():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    torch.manual_seed(0)
    hf_cfg = transformers.GPT2Config(
        vocab_size=256, n_positions=64, n_embd=64, n_layer=2, n_head=4,
    )
    model = transformers.GPT2LMHeadModel(hf_cfg)
    model.eval()
    cfg = gpt2.GPT2Config.from_hf(hf_cfg.to_dict())
    state = {k: v.detach().numpy() for k, v in model.state_dict().items()
             if not k.endswith(".attn.bias")}
    params = gpt2.params_from_hf(state, cfg)
    prompt = [3, 14, 15]
    got = gpt2.generate_cached(params, cfg, prompt, steps=8)
    with torch.no_grad():
        want = model.generate(torch.tensor([prompt]), max_new_tokens=8,
                              do_sample=False)
    np.testing.assert_array_equal(np.asarray(got), want[0].numpy())


def test_generate_cached_rejects_overflow():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(jax.random.key(3), cfg)
    with pytest.raises(ValueError, match="exceeds"):
        gpt2.generate_cached(params, cfg, [1] * 60, steps=10)
