"""Fleet observability (ISSUE 7): cross-host trace correlation, pod
metrics aggregation, and the flight recorder.

- merged-trace round-trip: two simulated hosts run a real cooperative
  round over loopback DCN; the single process trace splits into
  per-host docs, merges into ONE Perfetto doc with per-host tracks,
  a shared trace_id, client→server flow links, and the clock-offset
  normalization metadata;
- DCN hello negotiation: new↔new exchanges the v2 trace block (and a
  clock-offset estimate), old↔new in BOTH directions degrades to v1
  with the chunk RPC fully functional;
- flight recorder: ring bound, event capture from injected faults
  (reusing ZEST_FAULTS), dump-on-pull-failure crash report;
- pod-scope metrics: counters summed, gauges host-labeled, histograms
  re-summed, derived straggler/skew/ratio gauges, and the live
  ``/v1/metrics?scope=pod`` endpoint with a dead-peer scrape error;
- the knob-off contract: a ``ZEST_TELEMETRY=0`` cooperative pull is
  byte-identical with zero spans and zero recorder events.
"""

from __future__ import annotations

import json
import socket
import struct
import threading

import numpy as np
import pytest

from fixtures import FixtureHub, FixtureRepo

from zest_tpu import faults, telemetry
from zest_tpu.cas import hashing
from zest_tpu.cas.hub import HubClient
from zest_tpu.config import Config
from zest_tpu.telemetry import fleet, recorder as recorder_mod
from zest_tpu.telemetry import trace as trace_mod
from zest_tpu.transfer import dcn
from zest_tpu.transfer.bridge import XetBridge
from zest_tpu.transfer.coop import coop_round
from zest_tpu.transfer.dcn import DcnChannel, DcnPool, DcnServer

REPO_ID = "acme/fleet-model"

_PAYLOAD = np.random.default_rng(11).integers(
    0, 4, 1_200_000, dtype=np.uint8).tobytes()
FILES = {
    "config.json": b'{"model_type": "fleet"}',
    "model.safetensors": _PAYLOAD,
}


@pytest.fixture(scope="module")
def hub():
    repo = FixtureRepo(REPO_ID, FILES, chunks_per_xorb=2)
    with FixtureHub(repo) as h:
        yield h


@pytest.fixture(autouse=True)
def clean_telemetry():
    telemetry.reset_all()
    faults.reset()
    yield
    telemetry.reset_all()
    faults.reset()


def _bridge(hub, root):
    cfg = Config(hf_home=root / "hf", cache_dir=root / "zest",
                 hf_token="hf_test", endpoint=hub.url, dcn_port=0)
    b = XetBridge(cfg)
    b.authenticate(REPO_ID)
    return b


def _recs(bridge):
    return [bridge.get_reconstruction(e.xet_hash)
            for e in HubClient(bridge.cfg).list_files(REPO_ID)
            if e.is_xet]


def _run_coop_hosts(hub, tmp_path, n):
    """n concurrent simulated hosts with per-host DCN servers, each
    round under its own thread trace context (the server's serve spans
    get the host via span_attrs)."""
    bridges, servers, addrs = [], [], {}
    for i in range(n):
        b = _bridge(hub, tmp_path / f"h{i}")
        bridges.append(b)
        s = DcnServer(b.cfg, b.cache, span_attrs={"host": i})
        addrs[i] = ("127.0.0.1", s.start())
        servers.append(s)
    results: list = [None] * n
    errors: list = []

    def run(i):
        try:
            results[i] = coop_round(bridges[i], _recs(bridges[i]), i, n,
                                    addrs, server=servers[i])
        except Exception as exc:  # noqa: BLE001 - surfaced below
            errors.append((i, exc))

    threads = [threading.Thread(target=run, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for s in servers:
        s.shutdown()
    assert not errors, errors
    return results


# ── Trace identity ──


def test_mint_trace_id_deterministic_and_nonce_scoped():
    a = fleet.mint_trace_id("acme/m@sha1")
    assert a == fleet.mint_trace_id("acme/m@sha1")
    assert len(a) == 32 and bytes.fromhex(a)
    assert a != fleet.mint_trace_id("acme/m@sha2")
    assert a != fleet.mint_trace_id("acme/m@sha1", nonce="n1")


# ── Merged-trace round-trip over a real cooperative round ──


def test_merged_trace_round_trip_two_hosts(hub, tmp_path):
    tracer = trace_mod.install(None)
    results = _run_coop_hosts(hub, tmp_path, 2)

    # Both hosts minted the SAME trace id with zero coordination.
    assert results[0]["trace_id"] == results[1]["trace_id"]
    trace_id = results[0]["trace_id"]
    # ...and every host measured a clock offset from its peer's hello.
    for i, r in enumerate(results):
        peer = 1 - i
        assert peer in r["clock_offsets"], r
        off = r["clock_offsets"][peer]
        assert abs(off["offset_s"]) < 2.0  # same machine: ~0, ±rtt/2
        assert off["rtt_s"] >= 0.0

    doc = tracer.to_chrome()
    per_host = fleet.split_hosts(doc, default_host=0)
    assert set(per_host) >= {0, 1}
    merged = fleet.merge_traces(per_host)

    meta = merged["otherData"]
    assert set(meta["merged_hosts"]) >= {"0", "1"}
    assert meta["trace_ids"] == [trace_id]
    assert meta["flow_links"] > 0, "no dcn.request_many↔dcn.serve links"
    assert set(meta["clock_normalization"]) >= {"0", "1"}
    # Per-host tracks: one distinct synthetic pid per host, named.
    names = {e["args"]["name"] for e in merged["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert any("host 0" in n for n in names)
    assert any("host 1" in n for n in names)
    pids = {e["pid"] for e in merged["traceEvents"] if e.get("ph") == "X"}
    assert len(pids) >= 2

    # Every host's spans carry the shared trace_id; flow events bind
    # client windows to serve spans via matching ids.
    rounds = [e for e in merged["traceEvents"]
              if e.get("ph") == "X" and e["name"] == "coop.round"]
    assert len(rounds) == 2
    assert all(e["args"]["trace_id"] == trace_id for e in rounds)
    starts = [e for e in merged["traceEvents"] if e.get("ph") == "s"]
    finishes = [e for e in merged["traceEvents"] if e.get("ph") == "f"]
    assert starts and finishes
    assert {e["id"] for e in starts} >= {e["id"] for e in finishes}

    # Coverage per host: the round span dominates its track.
    for host in (0, 1):
        cov, root = fleet.host_coverage_s(merged, host, "coop.round")
        assert root > 0 and cov >= 0.9 * root

    # The merged doc is valid JSON and survives a file round trip.
    out = tmp_path / "merged.json"
    out.write_text(json.dumps(merged))
    assert json.loads(out.read_text())["otherData"]["flow_links"] > 0


def test_cli_merge_offline(tmp_path, capsys):
    """``zest trace --merge a.json b.json``: offline merge of exported
    per-host traces, host keys recovered from each doc's context."""
    from zest_tpu import cli

    docs = []
    for host in (0, 1):
        trace_mod.clear_context()
        trace_mod.set_context(host=host, trace_id="cd" * 16)
        t = trace_mod.install(None)
        with telemetry.span("coop.round"):
            pass
        docs.append(t.to_chrome())
        trace_mod.uninstall()
    trace_mod.clear_context()
    paths = []
    for i, doc in enumerate(docs):
        p = tmp_path / f"host{i}.json"
        p.write_text(json.dumps(doc))
        paths.append(str(p))
    out = tmp_path / "merged.json"
    assert cli.main(["trace", "--merge", *paths, "--out", str(out)]) == 0
    assert "2 host tracks" in capsys.readouterr().out
    merged = json.loads(out.read_text())
    assert merged["otherData"]["merged_hosts"] == ["0", "1"]
    assert merged["otherData"]["trace_ids"] == ["cd" * 16]


# ── DCN hello negotiation (old ↔ new) ──


@pytest.fixture
def dcn_server(tmp_config):
    from zest_tpu.storage import XorbCache

    tmp_config.dcn_port = 0
    server = DcnServer(tmp_config, XorbCache(tmp_config))
    port = server.start()
    yield server, port
    server.shutdown()


def test_hello_new_to_new_negotiates_v2(dcn_server):
    _server, port = dcn_server
    trace_mod.set_context(host=3, trace_id="ef" * 16)
    try:
        ch = DcnChannel("127.0.0.1", port, timeout=5.0)
    finally:
        trace_mod.clear_context()
    try:
        assert ch.hello.subversion == 2
        assert ch.hello.clock_offset_s is not None
        assert ch.hello.rtt_s is not None and ch.hello.rtt_s < 5.0
        assert abs(ch.hello.clock_offset_s) < 2.0  # same clock
        # The RPC still works over the negotiated stream.
        reply = ch.request(b"\x01" * 32, 0, 1)
        assert isinstance(reply, dcn.DcnNotFound)
    finally:
        ch.close()


def test_hello_old_client_to_new_server(dcn_server):
    """A v1 peer (version byte 1, reserved u16 zero, no trace block)
    must be served exactly as before: the server's v2 advert lands in
    bytes v1 never validated, and no extra block bytes follow."""
    _server, port = dcn_server
    with socket.create_connection(("127.0.0.1", port), timeout=5.0) as s:
        s.sendall(b"ZDCN" + bytes([1, 0, 0, 0]))  # the v1 hello, verbatim
        theirs = dcn._recv_exact(s, 8)
        assert theirs[:4] == b"ZDCN"
        assert theirs[4] == 1  # version byte still satisfies v1's check
        # Negotiated down: the very next bytes are the RPC reply header,
        # not a 32-byte trace block.
        req = dcn.encode_message(dcn.DcnRequest(7, b"\x02" * 32, 0, 1))
        s.sendall(req)
        msg = dcn._recv_message(s)
        assert isinstance(msg, dcn.DcnNotFound)
        assert msg.request_id == 7


def test_hello_new_client_to_old_server(tmp_path):
    """A v1 server (sends the legacy 8-byte hello, expects none of the
    v2 block) still serves a new client: the client reads rsvd=0 and
    never sends its block."""
    lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    port = lsock.getsockname()[1]
    seen: dict = {}

    def old_server():
        conn, _ = lsock.accept()
        with conn:
            conn.sendall(b"ZDCN" + bytes([1, 0, 0, 0]))
            hello = dcn._recv_exact(conn, 8)
            seen["hello"] = hello
            msg = dcn._recv_message(conn)  # v1 decode path
            seen["request"] = msg
            conn.sendall(dcn.encode_message(
                dcn.DcnNotFound(msg.request_id, msg.chunk_hash)))

    t = threading.Thread(target=old_server, daemon=True)
    t.start()
    try:
        ch = DcnChannel("127.0.0.1", port, timeout=5.0)
        try:
            assert ch.hello.subversion == 1
            assert ch.hello.clock_offset_s is None
            reply = ch.request(b"\x03" * 32, 0, 2)
            assert isinstance(reply, dcn.DcnNotFound)
        finally:
            ch.close()
        t.join(timeout=5)
        assert seen["hello"][:5] == b"ZDCN" + bytes([1])
        # Our advert rides the bytes v1 reserved (and ignored).
        assert struct.unpack("<H", seen["hello"][6:8])[0] == 2
        assert isinstance(seen["request"], dcn.DcnRequest)
    finally:
        lsock.close()


def test_request_tag_reaches_server_spans(dcn_server):
    """A traced pool tags its windows; the server's dcn.serve spans
    carry the tag + the client's host identity — the flow-link key."""
    server, port = dcn_server
    tracer = trace_mod.install(None)
    pool = DcnPool(timeout=5.0)
    trace_mod.set_context(host=5, trace_id="aa" * 16)
    try:
        pool.request_many("127.0.0.1", port, [(b"\x04" * 32, 0, 1)])
    finally:
        trace_mod.clear_context()
        pool.close()
    spans = {s.name: s for s in tracer.spans()}
    client = spans["dcn.request_many"]
    assert client.attrs["flow_tag"] >= 1
    serve = spans["dcn.serve"]
    assert serve.attrs["tag"] == client.attrs["flow_tag"]
    assert serve.attrs["client_host"] == 5
    assert serve.attrs["trace_id"] == "aa" * 16


def test_untraced_requests_stay_untagged(dcn_server):
    """No tracer armed → no tag allocation: wire bytes and the
    request shape match the pre-v2 path (the knob-off contract at the
    transport layer)."""
    _server, port = dcn_server
    pool = DcnPool(timeout=5.0)
    try:
        ch = pool.channel("127.0.0.1", port)
        sent = []
        orig = ch.send_request

        def spy(*a, **kw):
            sent.append((a, kw))
            return orig(*a, **kw)

        ch.send_request = spy
        pool.request_many("127.0.0.1", port, [(b"\x05" * 32, 0, 1)])
        assert sent and sent[0][1].get("tag", 0) == 0
    finally:
        pool.close()


# ── Flight recorder ──


def test_recorder_ring_bound_and_dump(tmp_path):
    rec = recorder_mod.FlightRecorder(capacity=8)
    for i in range(20):
        rec.record("fault_fired", fault=f"f{i}")
    events = rec.tail()
    assert len(events) == 8, "ring must stay bounded"
    assert events[0]["fault"] == "f12" and events[-1]["fault"] == "f19"
    assert rec.recorded == 20
    out = tmp_path / "crash" / "report.json"
    rec.dump(out, reason="test")
    doc = json.loads(out.read_text())
    assert doc["reason"] == "test"
    assert doc["recorded_total"] == 20 and len(doc["events"]) == 8
    assert not list(out.parent.glob("*.tmp.*"))


def test_recorder_env_capacity(monkeypatch):
    monkeypatch.setenv(recorder_mod.ENV_EVENTS, "3")
    rec = recorder_mod.FlightRecorder()
    assert rec.capacity == 3


def test_recorder_tail_zero_is_empty():
    rec = recorder_mod.FlightRecorder(capacity=4)
    rec.record("fault_fired", fault="x")
    assert rec.tail(0) == []      # [-0:] would be the WHOLE ring
    assert rec.tail(-1) == []
    assert len(rec.tail(1)) == 1


def test_recorder_captures_chaos_round(hub, tmp_path):
    """An injected dcn_reset exchange (reusing ZEST_FAULTS) leaves an
    ordered story in the ring: the fault fired, then the fallbacks —
    and the dump is a valid non-empty crash report."""
    faults.install("dcn_reset:1.0", seed=1337)
    _run_coop_hosts(hub, tmp_path, 2)
    kinds = [e["kind"] for e in recorder_mod.tail()]
    assert "fault_fired" in kinds
    assert "exchange_dead_host" in kinds
    assert "cdn_fallback" in kinds
    assert kinds.index("fault_fired") < kinds.index("cdn_fallback")
    path = recorder_mod.dump_crash_report(tmp_path, "chaos round")
    assert path is not None
    doc = json.loads((tmp_path / "crash").joinpath(
        path.rsplit("/", 1)[-1]).read_text())
    assert doc["events"]


def test_pull_failure_dumps_crash_report(tmp_path):
    """pull_model failure → crash-report JSON under cache_dir/crash."""
    from zest_tpu.transfer.pull import pull_model

    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                 endpoint="http://127.0.0.1:9")  # nothing listens
    with pytest.raises(Exception):
        pull_model(cfg, "acme/nope", no_p2p=True,
                   log=lambda *a, **k: None)
    crashes = list((tmp_path / "zest" / "crash").glob("zest-crash-*.json"))
    assert crashes, "no crash report written"
    doc = json.loads(crashes[0].read_text())
    assert any(e["kind"] == "pull_failed" for e in doc["events"])


def test_recorder_off_with_telemetry_knob():
    telemetry.set_enabled(False)
    try:
        telemetry.record("fault_fired", fault="x")
        assert recorder_mod.tail() == []
    finally:
        telemetry.set_enabled(None)


# ── Pod metrics aggregation ──

_H0 = """\
# HELP zest_coop_bytes_total coop bytes
# TYPE zest_coop_bytes_total counter
zest_coop_bytes_total{tier="cdn"} 100
zest_coop_bytes_total{tier="dcn"} 700
# HELP zest_coop_exchange_wall_seconds wall
# TYPE zest_coop_exchange_wall_seconds gauge
zest_coop_exchange_wall_seconds 2.0
# HELP zest_coop_fetch_bytes fetch
# TYPE zest_coop_fetch_bytes gauge
zest_coop_fetch_bytes 400
# HELP zest_pull_seconds lat
# TYPE zest_pull_seconds histogram
zest_pull_seconds_bucket{le="1"} 1
zest_pull_seconds_bucket{le="+Inf"} 2
zest_pull_seconds_sum 3.5
zest_pull_seconds_count 2
"""

_H1 = """\
# HELP zest_coop_bytes_total coop bytes
# TYPE zest_coop_bytes_total counter
zest_coop_bytes_total{tier="cdn"} 100
zest_coop_bytes_total{tier="dcn"} 500
# HELP zest_coop_exchange_wall_seconds wall
# TYPE zest_coop_exchange_wall_seconds gauge
zest_coop_exchange_wall_seconds 8.0
# HELP zest_coop_fetch_bytes fetch
# TYPE zest_coop_fetch_bytes gauge
zest_coop_fetch_bytes 600
# HELP zest_pull_seconds lat
# TYPE zest_pull_seconds histogram
zest_pull_seconds_bucket{le="1"} 0
zest_pull_seconds_bucket{le="+Inf"} 1
zest_pull_seconds_sum 4.5
zest_pull_seconds_count 1
"""


def test_aggregate_counters_summed_gauges_labeled():
    text = fleet.aggregate_prometheus({"0": _H0, "1": _H1})
    parsed = fleet.parse_prometheus(text)
    # Counters: summed across hosts per labelset.
    assert parsed["zest_coop_bytes_total"]["samples"][
        (("tier", "cdn"),)] == 200
    assert parsed["zest_coop_bytes_total"]["samples"][
        (("tier", "dcn"),)] == 1200
    # Gauges: one sample per host, host-labeled.
    walls = parsed["zest_coop_exchange_wall_seconds"]["samples"]
    assert walls[(("host", "0"),)] == 2.0
    assert walls[(("host", "1"),)] == 8.0
    # Histograms: additive series re-summed.
    assert parsed["zest_pull_seconds_count"]["samples"][()] == 3
    assert parsed["zest_pull_seconds_sum"]["samples"][()] == 8.0
    assert parsed["zest_pull_seconds_bucket"]["samples"][
        (("le", "+Inf"),)] == 3


def test_aggregate_derives_pod_gauges():
    text = fleet.aggregate_prometheus({"0": _H0, "1": _H1})
    parsed = fleet.parse_prometheus(text)
    # Straggler: slowest (8.0) minus median (median(2,8)=5.0) = 3.0.
    assert parsed["zest_coop_straggler_seconds"]["samples"][()] == \
        pytest.approx(3.0)
    # Fetch-share skew: max(600)/mean(500) = 1.2.
    assert parsed["zest_coop_fetch_share_skew"]["samples"][()] == \
        pytest.approx(1.2)
    # Swarm-wide ratio: peerish 1200 / (1200 + 200) cdn.
    assert parsed["zest_pod_peer_served_ratio"]["samples"][()] == \
        pytest.approx(1200 / 1400)
    assert parsed["zest_pod_hosts"]["samples"][()] == 2


def test_aggregate_reports_scrape_errors():
    text = fleet.aggregate_prometheus({"0": _H0}, errors={"1": "down"})
    parsed = fleet.parse_prometheus(text)
    assert parsed["zest_pod_scrape_errors"]["samples"][
        (("host", "1"),)] == 1


def test_aggregate_demotes_unparseable_host_to_scrape_error():
    """A proxy's HTML error page behind a 200 must cost one host, not
    the whole pod surface."""
    text = fleet.aggregate_prometheus(
        {"0": _H0, "1": "<html>502 Bad Gateway</html>"})
    parsed = fleet.parse_prometheus(text)
    assert parsed["zest_pod_hosts"]["samples"][()] == 1
    assert parsed["zest_pod_scrape_errors"]["samples"][
        (("host", "1"),)] == 1
    assert parsed["zest_coop_bytes_total"]["samples"][
        (("tier", "cdn"),)] == 100  # host 0 still aggregated


def test_parse_rejects_malformed():
    with pytest.raises(ValueError):
        fleet.parse_prometheus("what even is this line\n")


# ── HTTP surfaces ──


@pytest.fixture
def api(tmp_config):
    from zest_tpu.api.http_api import HttpApi

    requests = pytest.importorskip("requests")
    tmp_config.http_port = 0
    a = HttpApi(tmp_config)
    port = a.start()
    yield a, requests, f"http://127.0.0.1:{port}"
    a.close()


def test_v1_trace_endpoint(api):
    a, requests, base = api
    doc = requests.get(f"{base}/v1/trace", timeout=5).json()
    assert doc["traceEvents"] == [] and "note" in doc["otherData"]
    tracer = trace_mod.install(None)
    with telemetry.span("pull", repo="x"):
        pass
    doc = requests.get(f"{base}/v1/trace", timeout=5).json()
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert "pull" in names
    assert doc["otherData"]["spans"] == len(tracer.spans())


def test_v1_debug_endpoint(api):
    _a, requests, base = api
    telemetry.record("cdn_fallback", unit="abc", tier="cdn", bytes=5)
    telemetry.counter("zest_coop_bytes_total", "", ("tier",)) \
        .inc(900, tier="dcn")
    telemetry.counter("zest_coop_bytes_total", "", ("tier",)) \
        .inc(100, tier="cdn")
    d = requests.get(f"{base}/v1/debug?tail=5", timeout=5).json()
    assert d["recorder"]["events"][-1]["kind"] == "cdn_fallback"
    assert d["coop"]["peer_served_ratio"] == pytest.approx(0.9)
    assert d["coop"]["tier_bytes"] == {"dcn": 900, "cdn": 100}


def test_v1_metrics_pod_scope_scrapes_peers(tmp_config):
    """The coordinator aggregates a live peer and reports a dead one."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from zest_tpu.api.http_api import HttpApi

    requests = pytest.importorskip("requests")

    peer_text = ("# HELP zest_coop_bytes_total b\n"
                 "# TYPE zest_coop_bytes_total counter\n"
                 'zest_coop_bytes_total{tier="dcn"} 11\n')

    class PeerHandler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):  # noqa: N802
            body = peer_text.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    peer_httpd = ThreadingHTTPServer(("127.0.0.1", 0), PeerHandler)
    threading.Thread(target=peer_httpd.serve_forever, daemon=True).start()
    peer_port = peer_httpd.server_address[1]

    telemetry.counter("zest_coop_bytes_total", "", ("tier",)) \
        .inc(9, tier="dcn")
    tmp_config.http_port = 0
    tmp_config.coop_index = 0
    a = HttpApi(tmp_config, pod_peers={
        1: ("127.0.0.1", peer_port),
        2: ("127.0.0.1", 1),  # nothing listens: scrape error
    })
    port = a.start()
    try:
        r = requests.get(
            f"http://127.0.0.1:{port}/v1/metrics?scope=pod", timeout=10)
        assert r.status_code == 200
        parsed = fleet.parse_prometheus(r.text)
        assert parsed["zest_coop_bytes_total"]["samples"][
            (("tier", "dcn"),)] == 20  # 9 local + 11 scraped
        assert parsed["zest_pod_hosts"]["samples"][()] == 2
        assert parsed["zest_pod_scrape_errors"]["samples"][
            (("host", "2"),)] == 1
        # Plain scope is untouched: local counters only.
        local = fleet.parse_prometheus(requests.get(
            f"http://127.0.0.1:{port}/v1/metrics", timeout=5).text)
        assert local["zest_coop_bytes_total"]["samples"][
            (("tier", "dcn"),)] == 9
    finally:
        a.close()
        peer_httpd.shutdown()
        peer_httpd.server_close()


def test_pod_scrape_fanout_bounded_at_64_peers(tmp_config, monkeypatch):
    """ISSUE 16 satellite: the pod-scope scrape fan-out rides ONE
    shared bounded pool — with 64 peers and 4 workers at most 4
    scrapes are ever in flight, and every peer still gets scraped
    (the pre-fix behavior built a fresh 8-worker executor per
    request, an unbounded burst across concurrent requests)."""
    import time as _time
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from zest_tpu.api.http_api import HttpApi

    lk = threading.Lock()
    in_flight, peak, served = [0], [0], [0]

    class PeerHandler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):  # noqa: N802
            # Count the worker-held window ONLY: decrement before the
            # response bytes go out — a worker can't start its next
            # scrape until it has read this response, so peak is a true
            # concurrent-worker reading, not racy by one against a
            # handler still between write-return and decrement.
            with lk:
                in_flight[0] += 1
                peak[0] = max(peak[0], in_flight[0])
            _time.sleep(0.02)
            with lk:
                in_flight[0] -= 1
                served[0] += 1
            body = (b"# TYPE zest_x_total counter\n"
                    b"zest_x_total 1\n")
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), PeerHandler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]

    monkeypatch.setattr(fleet, "_SCRAPE_POOL", None)
    tmp_config.coop_index = 0
    tmp_config.pod_scrape_workers = 4
    a = HttpApi(tmp_config, pod_peers={
        i: ("127.0.0.1", port) for i in range(1, 65)})
    try:
        text = a.pod_metrics_text()
    finally:
        httpd.shutdown()
        httpd.server_close()
        pool = fleet._SCRAPE_POOL
        monkeypatch.setattr(fleet, "_SCRAPE_POOL", None)
        if pool is not None:
            pool.shutdown(wait=False)
    assert served[0] == 64
    assert peak[0] <= 4, f"scrape fan-out burst to {peak[0]} threads"
    parsed = fleet.parse_prometheus(text)
    assert parsed["zest_pod_hosts"]["samples"][()] == 65  # local + 64


def test_cmd_debug_writes_report(api, tmp_path, monkeypatch):
    from zest_tpu import cli

    _a, _requests, base = api
    port = base.rsplit(":", 1)[1]
    monkeypatch.setenv("ZEST_HTTP_PORT", port)
    telemetry.record("fault_fired", fault="cdn_503")
    out = tmp_path / "report.json"
    assert cli.main(["debug", "--out", str(out)]) == 0
    doc = json.loads(out.read_text())
    assert any(e["kind"] == "fault_fired"
               for e in doc["recorder"]["events"])


def test_cmd_stats_watch_renders_one_frame(api, monkeypatch, capsys):
    from zest_tpu import cli

    _a, _requests, base = api
    monkeypatch.setenv("ZEST_HTTP_PORT", base.rsplit(":", 1)[1])
    telemetry.counter("zest_coop_bytes_total", "", ("tier",)) \
        .inc(42, tier="dcn")
    telemetry.record("peer_strike", peer="10.0.0.9:7001", strike="corrupt")
    assert cli.main(["stats", "--watch", "--count", "1",
                     "--interval", "0.1"]) == 0
    out = capsys.readouterr().out
    assert "coop: peer_served=" in out
    assert "peer_strike" in out


# ── Knob-off contract: byte-identical coop pull, zero telemetry ──


def test_knob_off_coop_pull_byte_identical(hub, tmp_path):
    from zest_tpu.transfer.coop import CoopPlan
    from zest_tpu.transfer.federated import warm_units_parallel
    from zest_tpu.transfer.pull import pull_model

    def coop_pull(root):
        peer = _bridge(hub, root / "peer")
        recs = _recs(peer)
        warm_units_parallel(peer, recs,
                            units=CoopPlan.build(recs, 2).for_host(1))
        server = DcnServer(peer.cfg, peer.cache)
        port = server.start()
        try:
            cfg = Config(hf_home=root / "p0/hf",
                         cache_dir=root / "p0/zest",
                         hf_token="hf_test", endpoint=hub.url,
                         dcn_port=0)
            return pull_model(cfg, REPO_ID, no_p2p=True, coop=True,
                              coop_hosts=2, coop_index=0,
                              coop_addrs={1: ("127.0.0.1", port)},
                              log=lambda *a, **k: None)
        finally:
            server.shutdown()

    tracer_on = trace_mod.install(None)
    on = coop_pull(tmp_path / "on")
    assert len(tracer_on) > 0
    assert recorder_mod.RECORDER.recorded == 0 or True  # events optional
    trace_mod.uninstall()
    telemetry.reset_all()

    tracer_off = trace_mod.install(None)
    telemetry.set_enabled(False)
    try:
        off = coop_pull(tmp_path / "off")
    finally:
        telemetry.set_enabled(None)

    for name, data in FILES.items():
        assert (on.snapshot_dir / name).read_bytes() == data
        assert (off.snapshot_dir / name).read_bytes() == data
    assert len(tracer_off) == 0, "knob-off pull recorded spans"
    assert recorder_mod.RECORDER.recorded == 0, \
        "knob-off pull recorded flight-recorder events"
    assert on.stats["coop"]["exchange"]["units"] == \
        off.stats["coop"]["exchange"]["units"]
    assert sorted(on.stats["coop"]) == sorted(off.stats["coop"])
    # The pull restored the process trace context: a daemon's NEXT
    # pull must not inherit this one's trace_id.
    assert trace_mod.base_context() == {}
