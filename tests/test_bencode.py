"""Bencode codec tests — strictness parity with reference src/bencode.zig:269-345."""

import pytest

from zest_tpu.p2p import bencode
from zest_tpu.p2p.bencode import BencodeError


class TestEncode:
    def test_int(self):
        assert bencode.encode(42) == b"i42e"
        assert bencode.encode(0) == b"i0e"
        assert bencode.encode(-7) == b"i-7e"

    def test_string(self):
        assert bencode.encode(b"spam") == b"4:spam"
        assert bencode.encode("spam") == b"4:spam"
        assert bencode.encode(b"") == b"0:"

    def test_list(self):
        assert bencode.encode([b"spam", 42]) == b"l4:spami42ee"
        assert bencode.encode([]) == b"le"

    def test_dict_keys_sorted(self):
        assert bencode.encode({b"b": 2, b"a": 1}) == b"d1:ai1e1:bi2ee"

    def test_nested(self):
        assert (
            bencode.encode({b"m": {b"ut_xet": 3}, b"p": 6881})
            == b"d1:md6:ut_xeti3ee1:pi6881ee"
        )

    def test_bool_rejected(self):
        with pytest.raises(BencodeError):
            bencode.encode(True)


class TestDecode:
    def test_roundtrip(self):
        for v in [0, -123, b"hello", [b"a", [1, 2]], {b"k": {b"n": [b"x"]}}]:
            assert bencode.decode(bencode.encode(v)) == v

    def test_leading_zero_int_rejected(self):
        with pytest.raises(BencodeError):
            bencode.decode(b"i042e")

    def test_negative_zero_rejected(self):
        with pytest.raises(BencodeError):
            bencode.decode(b"i-0e")

    def test_zero_ok(self):
        assert bencode.decode(b"i0e") == 0

    def test_unsorted_dict_keys_rejected(self):
        with pytest.raises(BencodeError):
            bencode.decode(b"d1:bi1e1:ai2ee")

    def test_duplicate_dict_keys_rejected(self):
        with pytest.raises(BencodeError):
            bencode.decode(b"d1:ai1e1:ai2ee")

    def test_trailing_bytes_rejected(self):
        with pytest.raises(BencodeError):
            bencode.decode(b"i1eX")

    def test_truncated_string_rejected(self):
        with pytest.raises(BencodeError):
            bencode.decode(b"10:short")

    def test_leading_zero_strlen_rejected(self):
        with pytest.raises(BencodeError):
            bencode.decode(b"04:spam")

    def test_unterminated_rejected(self):
        for bad in [b"i42", b"l1:a", b"d1:ai1e", b""]:
            with pytest.raises(BencodeError):
                bencode.decode(bad)

    def test_hostile_deep_nesting_rejected(self):
        # Untrusted DHT/tracker input must never escape BencodeError
        # (a RecursionError would crash the packet handler).
        with pytest.raises(BencodeError):
            bencode.decode(b"l" * 10_000)
        with pytest.raises(BencodeError):
            bencode.decode(b"d" * 10_000)

    def test_nondigit_string_length_rejected(self):
        with pytest.raises(BencodeError):
            bencode.decode(b"1a:x")

    def test_prefix_decode(self):
        value, n = bencode.decode_prefix(b"i42eTRAILER")
        assert value == 42 and n == 4


class TestDictHelpers:
    def test_typed_lookups(self):
        d = bencode.decode(b"d1:ii7e1:ll1:xe1:s3:abce")
        assert bencode.dict_get_int(d, b"i") == 7
        assert bencode.dict_get_bytes(d, b"s") == b"abc"
        assert bencode.dict_get_list(d, b"l") == [b"x"]
        assert bencode.dict_get_int(d, b"s") is None
        assert bencode.dict_get_dict(d, b"missing") is None
