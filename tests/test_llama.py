"""Llama-family model: numerical parity with the HF torch implementation,
TP/CP sharded train steps, checkpoint mapping, and generation.

The parity test is the strongest correctness anchor available: transformers
(torch, CPU) is the production implementation the pulled checkpoints were
trained against — mirroring the reference's verify-model gate
(test/local/verify-model.sh:103-147), which loads pulled weights with
transformers and asserts generation."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zest_tpu.models import llama

TINY = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            rms_norm_eps=1e-5, rope_theta=10000.0)


def hf_tiny_model(tie=False):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    torch.manual_seed(0)
    cfg = transformers.LlamaConfig(
        **TINY, tie_word_embeddings=tie, attention_bias=False,
        mlp_bias=False,
    )
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    return model, cfg


def to_numpy_state(model):
    return {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}


@pytest.mark.parametrize("tie", [False, True])
def test_forward_matches_transformers(tie):
    torch = pytest.importorskip("torch")
    model, hf_cfg = hf_tiny_model(tie)
    cfg = llama.LlamaConfig.from_hf(hf_cfg.to_dict())
    assert cfg.tie_embeddings == tie
    params = llama.params_from_hf(to_numpy_state(model), cfg)
    assert ("lm_head" in params) == (not tie)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 17))
    got = np.asarray(llama.forward(params, jnp.asarray(ids, jnp.int32), cfg))
    with torch.no_grad():
        want = model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)


def test_forward_matches_transformers_with_llama3_rope_scaling():
    """Llama-3.1-style rope_scaling (the real 8B/70B/405B configs carry it)
    must reproduce transformers' scaled rotary phases exactly."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    torch.manual_seed(1)
    hf_cfg = transformers.LlamaConfig(
        **TINY, tie_word_embeddings=False, attention_bias=False,
        mlp_bias=False,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 16},
    )
    model = transformers.LlamaForCausalLM(hf_cfg)
    model.eval()
    cfg = llama.LlamaConfig.from_hf(hf_cfg.to_dict())
    assert cfg.rope_scaling_factor == 8.0
    assert cfg.rope_original_ctx == 16
    params = llama.params_from_hf(to_numpy_state(model), cfg)
    rng = np.random.default_rng(2)
    # Positions past original_max_position_embeddings exercise scaling.
    ids = rng.integers(0, cfg.vocab_size, size=(2, 48))
    got = np.asarray(llama.forward(params, jnp.asarray(ids, jnp.int32), cfg))
    with torch.no_grad():
        want = model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)


def test_forward_matches_transformers_with_head_dim_override():
    """Mistral-Nemo-style configs decouple head_dim from n_embd/n_head;
    the tree shapes and forward must follow the explicit value."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    torch.manual_seed(2)
    hf_cfg = transformers.LlamaConfig(
        **TINY, tie_word_embeddings=False, attention_bias=False,
        mlp_bias=False, head_dim=24,
    )
    model = transformers.LlamaForCausalLM(hf_cfg)
    model.eval()
    cfg = llama.LlamaConfig.from_hf(hf_cfg.to_dict())
    assert cfg.head_dim == 24
    params = llama.params_from_hf(to_numpy_state(model), cfg)
    assert params["blocks"]["attn"]["q_w"].shape == (2, 64, 4 * 24)
    rng = np.random.default_rng(4)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 11))
    got = np.asarray(llama.forward(params, jnp.asarray(ids, jnp.int32), cfg))
    with torch.no_grad():
        want = model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)


def test_from_hf_rejects_unsupported_rope_scaling():
    cfg_json = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    rope_scaling={"rope_type": "yarn", "factor": 4.0})
    with pytest.raises(ValueError, match="yarn"):
        llama.LlamaConfig.from_hf(cfg_json)


def test_from_hf_rejects_mlp_bias_configs():
    cfg_json = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    mlp_bias=True)
    with pytest.raises(ValueError, match="mlp_bias"):
        llama.LlamaConfig.from_hf(cfg_json)


def test_forward_matches_transformers_attention_bias():
    """Explicit attention_bias=True (HF LlamaAttention) biases o_proj as
    well as q/k/v; all four must map and apply."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    torch.manual_seed(8)
    hf_cfg = transformers.LlamaConfig(
        **TINY, tie_word_embeddings=False, attention_bias=True,
        mlp_bias=False,
    )
    model = transformers.LlamaForCausalLM(hf_cfg)
    model.eval()
    with torch.no_grad():  # transformers zero-inits biases
        for layer in model.model.layers:
            for proj in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                         layer.self_attn.v_proj, layer.self_attn.o_proj):
                proj.bias.normal_(std=0.5)
    cfg = llama.LlamaConfig.from_hf(hf_cfg.to_dict())
    assert cfg.attn_bias and cfg.o_bias
    params = llama.params_from_hf(to_numpy_state(model), cfg)
    assert params["blocks"]["attn"]["o_b"].shape == (2, 64)
    rng = np.random.default_rng(9)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 11))
    got = np.asarray(llama.forward(params, jnp.asarray(ids, jnp.int32), cfg))
    with torch.no_grad():
        want = model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)
    # KV-cached decode carries the biases too.
    full = llama.generate_greedy(params, cfg, [1, 2, 3], steps=6)
    cached = llama.generate_cached(params, cfg, [1, 2, 3], steps=6)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(cached))


def test_qwen2_has_no_o_bias():
    cfg = llama.LlamaConfig.from_hf(dict(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, model_type="qwen2"))
    assert cfg.attn_bias and not cfg.o_bias


def test_forward_matches_transformers_qwen2():
    """Qwen2 hardcodes q/k/v biases (no attention_bias config key); the
    tree must carry and apply them — parity against the HF torch Qwen2."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    torch.manual_seed(6)
    hf_cfg = transformers.Qwen2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    model = transformers.Qwen2ForCausalLM(hf_cfg)
    model.eval()
    # transformers zero-inits biases; randomize so parity exercises them.
    with torch.no_grad():
        for layer in model.model.layers:
            for proj in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                         layer.self_attn.v_proj):
                proj.bias.normal_(std=0.5)
    cfg = llama.LlamaConfig.from_hf(
        dict(hf_cfg.to_dict(), model_type="qwen2")
    )
    assert cfg.attn_bias
    params = llama.params_from_hf(to_numpy_state(model), cfg)
    assert params["blocks"]["attn"]["q_b"].shape == (2, 64)
    # Bias tensors must actually be nonzero for this test to mean much.
    assert float(np.abs(np.asarray(
        params["blocks"]["attn"]["q_b"])).max()) > 0
    rng = np.random.default_rng(7)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 13))
    got = np.asarray(llama.forward(params, jnp.asarray(ids, jnp.int32), cfg))
    with torch.no_grad():
        want = model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)


def test_from_hf_fallbacks_are_hf_defaults():
    """A Llama-2-era config.json omitting rope_theta/rms_norm_eps must get
    transformers.LlamaConfig defaults, not 3.1 preset values."""
    cfg = llama.LlamaConfig.from_hf(dict(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4))
    assert cfg.rope_theta == 10000.0
    assert cfg.rms_eps == 1e-6
    assert cfg.n_ctx == 2048
    assert cfg.rope_scaling_factor is None
    assert cfg.n_kv_head == 4


def test_default_config_is_llama31():
    """The 8B preset must carry the 3.1 scaling (its config.json does)."""
    cfg = llama.LlamaConfig.llama3_8b()
    assert cfg.rope_scaling_factor == 8.0
    assert cfg.rope_original_ctx == 8192
    assert llama.LlamaConfig.tiny().rope_scaling_factor is None


def test_params_from_hf_untied_requires_lm_head():
    """An untied config with no lm_head.weight must raise, not silently
    fall back to tied embeddings (wrong logits)."""
    model, hf_cfg = hf_tiny_model(tie=False)
    cfg = llama.LlamaConfig.from_hf(hf_cfg.to_dict())
    state = to_numpy_state(model)
    del state["lm_head.weight"]
    with pytest.raises(ValueError, match="lm_head"):
        llama.params_from_hf(state, cfg)


def test_params_from_hf_missing_tensor_raises():
    model, hf_cfg = hf_tiny_model()
    cfg = llama.LlamaConfig.from_hf(hf_cfg.to_dict())
    state = to_numpy_state(model)
    del state["model.layers.1.mlp.down_proj.weight"]
    with pytest.raises(ValueError, match="down_proj"):
        llama.params_from_hf(state, cfg)


def test_param_specs_cover_tree():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    specs = llama.param_specs(cfg)
    # Same tree structure: zipping must succeed and yield a spec per leaf.
    zipped = jax.tree.map(lambda a, s: isinstance(s, P), params, specs,
                          is_leaf=lambda v: isinstance(v, P))
    assert all(jax.tree.leaves(zipped))


def test_presets_match_hf_configs():
    assert llama.LlamaConfig.llama3_8b().d_ff == 14336
    c70 = llama.LlamaConfig.llama3_70b()
    assert (c70.n_embd, c70.n_layer, c70.n_kv_head) == (8192, 80, 8)
    assert c70.head_dim == 128


def tp_mesh():
    return Mesh(np.asarray(jax.devices()).reshape(2, 4), ("data", "model"))


def test_tp_train_step_matches_single_device():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(1)
    batch = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(4, 18)), jnp.int32
    )

    ref_params, ref_loss = jax.jit(
        functools.partial(llama.train_step, cfg=cfg)
    )(params, batch)

    mesh = tp_mesh()
    specs = llama.param_specs(cfg)
    sharded = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda v: isinstance(v, P),
    )
    sbatch = jax.device_put(batch, NamedSharding(mesh, P("data", None)))
    tp_params, tp_loss = jax.jit(
        functools.partial(llama.train_step, cfg=cfg)
    )(sharded, sbatch)

    np.testing.assert_allclose(float(tp_loss), float(ref_loss),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(tp_params["blocks"]["attn"]["q_w"]),
        np.asarray(ref_params["blocks"]["attn"]["q_w"]),
        atol=1e-5, rtol=1e-4,
    )


def cp_mesh():
    return Mesh(np.asarray(jax.devices()).reshape(2, 4), ("data", "seq"))


def test_cp_forward_matches_dense():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(2), cfg)
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(2, 32)),
                      jnp.int32)
    mesh = cp_mesh()
    got = llama.cp_forward(params, ids, cfg, mesh)
    want = llama.forward(params, ids, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_cp_train_step_matches_dense():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(4), cfg)
    rng = np.random.default_rng(5)
    batch = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(2, 33)), jnp.int32
    )
    mesh = cp_mesh()
    cp_params, cp_loss = jax.jit(
        functools.partial(llama.cp_train_step, cfg=cfg, mesh=mesh)
    )(params, batch)
    ref_params, ref_loss = llama.train_step(params, batch, cfg)
    np.testing.assert_allclose(float(cp_loss), float(ref_loss),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(cp_params["wte"]), np.asarray(ref_params["wte"]),
        atol=1e-5, rtol=1e-4,
    )


@pytest.mark.slow
def test_cp_tp_train_step_matches_dense():
    """TP×CP composition: a {data, seq, model} mesh runs dp+sp+tp in one
    step — params Megatron-sharded, ring attention on local heads,
    explicit psums — and must still match the dense step exactly."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(11), cfg)
    rng = np.random.default_rng(12)
    batch = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(2, 17)), jnp.int32
    )
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 2, 2),
                ("data", "seq", "model"))
    cp_params, cp_loss = jax.jit(
        functools.partial(llama.cp_train_step, cfg=cfg, mesh=mesh)
    )(params, batch)
    ref_params, ref_loss = llama.train_step(params, batch, cfg)
    np.testing.assert_allclose(float(cp_loss), float(ref_loss),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(cp_params["blocks"]["mlp"]["down_w"]),
        np.asarray(ref_params["blocks"]["mlp"]["down_w"]),
        atol=1e-5, rtol=1e-4,
    )


def test_cp_tp_forward_tied_embeddings():
    """TP×CP with a tied-embedding tree: the head stays replicated (full
    vocab out), attention/MLP still TP-sharded."""
    cfg = llama.LlamaConfig.tiny(tie_embeddings=True)
    params = llama.init_params(jax.random.key(13), cfg)
    assert "lm_head" not in params
    rng = np.random.default_rng(14)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(2, 16)),
                      jnp.int32)
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 2, 2),
                ("data", "seq", "model"))
    got = llama.cp_forward(params, ids, cfg, mesh)
    want = llama.forward(params, ids, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_remat_train_step_matches_exact():
    """jax.checkpoint must change memory, not math: identical loss and
    gradients with remat on, for all three model families."""
    import functools as ft

    from zest_tpu.models import gpt2, moe

    rng = np.random.default_rng(20)
    # Llama
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(20), cfg)
    batch = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 17)), jnp.int32)
    p0, l0 = llama.train_step(params, batch, cfg)
    p1, l1 = llama.train_step(params, batch, cfg, remat=True)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    # The updated params compare gradients — the only thing remat touches
    # is the backward pass, so loss equality alone proves nothing.
    for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(p1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)
    # GPT-2
    gcfg = gpt2.GPT2Config.tiny()
    gparams = gpt2.init_params(jax.random.key(21), gcfg)
    gbatch = jnp.asarray(rng.integers(0, gcfg.vocab_size, (2, 17)),
                         jnp.int32)
    gp0, g0 = gpt2.train_step(gparams, gbatch, gcfg)
    gp1, g1 = gpt2.train_step(gparams, gbatch, gcfg, remat=True)
    np.testing.assert_allclose(float(g0), float(g1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(gp0), jax.tree.leaves(gp1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)
    # MoE
    mcfg = moe.MoEConfig.tiny()
    mparams = moe.init_params(jax.random.key(22), mcfg)
    mbatch = jnp.asarray(rng.integers(0, mcfg.vocab_size, (2, 17)),
                         jnp.int32)
    step = ft.partial(moe.train_step, cfg=mcfg)
    mp0, m0 = step(mparams, mbatch)
    mp1, m1 = step(mparams, mbatch, remat=True)
    np.testing.assert_allclose(float(m0), float(m1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(mp0), jax.tree.leaves(mp1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)


@pytest.mark.slow
def test_cp_remat_matches_exact():
    """Remat through the shard_mapped ring: same loss and params."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(30), cfg)
    rng = np.random.default_rng(31)
    batch = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 33)), jnp.int32)
    mesh = cp_mesh()
    # remat inside shard_map requires jit (eager closed_call is
    # unimplemented in JAX) — which is how the step deploys anyway.
    step = jax.jit(functools.partial(llama.cp_train_step, cfg=cfg,
                                     mesh=mesh),
                   static_argnames=("remat",))
    p0, l0 = step(params, batch)
    p1, l1 = step(params, batch, remat=True)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(p1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)


def test_generate_cached_matches_greedy():
    """KV-cached incremental decode must be token-identical to the full
    recompute path — same argmax at every step."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(8), cfg)
    prompt = [5, 9, 2, 40]
    want = llama.generate_greedy(params, cfg, prompt, steps=12)
    got = llama.generate_cached(params, cfg, prompt, steps=12)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generate_cached_matches_torch_greedy():
    torch = pytest.importorskip("torch")
    model, hf_cfg = hf_tiny_model(tie=False)
    cfg = llama.LlamaConfig.from_hf(hf_cfg.to_dict())
    params = llama.params_from_hf(to_numpy_state(model), cfg)
    prompt = [3, 14, 15, 9, 2, 6]
    got = llama.generate_cached(params, cfg, prompt, steps=9)
    with torch.no_grad():
        want = model.generate(torch.tensor([prompt]), max_new_tokens=9,
                              do_sample=False)
    np.testing.assert_array_equal(np.asarray(got), want[0].numpy())


@pytest.mark.slow
def test_decode_step_single_token_positions():
    """decode_step at position p must reproduce column p of the full
    forward (cache correctness at every position)."""
    import jax.numpy as jnp

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(9), cfg)
    rng = np.random.default_rng(10)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(1, 10)),
                      jnp.int32)
    full = np.asarray(llama.forward(params, ids, cfg))
    cache = llama.init_kv_cache(cfg, 1, 10)
    for pos in range(10):
        logits, cache = llama.decode_step(
            params, cache, ids[:, pos], pos, cfg
        )
        np.testing.assert_allclose(np.asarray(logits[0]), full[0, pos],
                                   atol=1e-4, rtol=1e-4)


def test_generate_greedy_is_deterministic():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(6), cfg)
    out1 = llama.generate_greedy(params, cfg, [1, 2, 3], steps=5)
    out2 = llama.generate_greedy(params, cfg, [1, 2, 3], steps=5)
    assert out1.shape == (8,)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert np.array_equal(np.asarray(out1[:3]), [1, 2, 3])


def test_generate_rejects_overflow():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(7), cfg)
    with pytest.raises(ValueError, match="exceeds"):
        llama.generate_greedy(params, cfg, [1] * 60, steps=10)


def test_checkpoint_shard_rules_match_hf_names():
    import re

    rules = llama.checkpoint_shard_rules()
    names = {
        "model.layers.0.self_attn.q_proj.weight": P("model", None),
        "model.layers.3.self_attn.o_proj.weight": P(None, "model"),
        "model.layers.1.mlp.gate_proj.weight": P("model", None),
        "model.layers.1.mlp.up_proj.weight": P("model", None),
        "model.layers.2.mlp.down_proj.weight": P(None, "model"),
        "lm_head.weight": P("model", None),
    }
    for name, want in names.items():
        got = next(
            (spec for pat, spec in rules if re.search(pat, name)), None
        )
        assert got == want, name
    assert not any(
        re.search(pat, "model.embed_tokens.weight") for pat, _ in rules
    )


def test_fixture_llama_checkpoint_loads_everywhere(tmp_path):
    """The fixture-hub Llama checkpoint (fixtures.llama_checkpoint_files,
    the offline lifecycle demo's input) must stay loadable by BOTH
    consumers: this package's params_from_hf -> forward, and
    transformers.LlamaForCausalLM.load_state_dict (strict)."""
    import json

    from fixtures import llama_checkpoint_files
    from zest_tpu.models.generate import snapshot_tensors

    files = llama_checkpoint_files()
    for name, blob in files.items():
        (tmp_path / name).write_bytes(blob)
    cfg_json = json.loads(files["config.json"])

    cfg = llama.LlamaConfig.from_hf(cfg_json)
    tensors = snapshot_tensors(tmp_path)
    params = llama.params_from_hf(tensors, cfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    logits = llama.forward(params, ids, cfg)
    assert logits.shape == (1, 8, cfg.vocab_size)

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    hf = transformers.LlamaForCausalLM(
        transformers.LlamaConfig(**{k: v for k, v in cfg_json.items()
                                    if k not in ("model_type",
                                                 "architectures",
                                                 "torch_dtype")}))
    state = {k: torch.from_numpy(v.copy()) for k, v in tensors.items()}
    hf.load_state_dict(state, strict=True)
