"""Optax training loop: convergence, family-model composition, and
sharding inheritance of the optimizer state."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zest_tpu.models import gpt2, llama, moe
import pytest

from zest_tpu.models.training import TrainState, adamw, create_state, \
    make_train_step


def test_decay_mask_excludes_norms_and_biases():
    """The stacked-layer trees make norm gains 2-D, so the mask must key
    on leaf names — norm g/b and *_b excluded, weights/embeddings in."""
    from zest_tpu.models.training import decay_mask

    cfg = llama.LlamaConfig.tiny(attn_bias=True)
    mask = decay_mask(llama.init_params(jax.random.key(0), cfg))
    assert mask["blocks"]["ln_attn"]["g"] is False
    assert mask["ln_f"]["g"] is False
    assert mask["blocks"]["attn"]["q_b"] is False
    assert mask["blocks"]["attn"]["q_w"] is True
    assert mask["wte"] is True

    gmask = decay_mask(gpt2.init_params(jax.random.key(1),
                                        gpt2.GPT2Config.tiny()))
    assert gmask["blocks"]["ln_1"]["g"] is False
    assert gmask["blocks"]["ln_1"]["b"] is False
    assert gmask["blocks"]["attn"]["qkv_b"] is False
    assert gmask["blocks"]["attn"]["qkv_w"] is True


def test_loss_decreases_overfitting_one_batch():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    batch = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 17)), jnp.int32)
    tx = adamw(lr=1e-2, warmup_steps=2, total_steps=100)
    step = make_train_step(tx, functools.partial(llama.loss_fn, cfg=cfg))
    state = create_state(params, tx)
    first = None
    for _ in range(15):
        state, loss = step(state, batch)
        first = float(loss) if first is None else first
    assert int(state.step) == 15
    assert float(loss) < first * 0.7, (first, float(loss))


@pytest.mark.slow
def test_composes_with_all_families():
    rng = np.random.default_rng(1)
    cases = [
        (gpt2, gpt2.GPT2Config.tiny(), gpt2.init_params),
        (llama, llama.LlamaConfig.tiny(), llama.init_params),
        (moe, moe.MoEConfig.tiny(), moe.init_params),
    ]
    tx = adamw(warmup_steps=1, total_steps=10)
    for mod, cfg, init in cases:
        params = init(jax.random.key(2), cfg)
        batch = jnp.asarray(
            rng.integers(0, cfg.vocab_size, (2, 9)), jnp.int32
        )
        step = make_train_step(tx, functools.partial(mod.loss_fn, cfg=cfg))
        state, loss = step(create_state(params, tx), batch)
        assert np.isfinite(float(loss)), mod.__name__
        assert isinstance(state, TrainState)


def test_opt_state_inherits_param_sharding():
    """Moments created via zeros_like must carry each param's
    NamedSharding — no spec plumbing."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(3), cfg)
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("data", "model"))
    specs = llama.param_specs(cfg)
    sharded = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda v: isinstance(v, P),
    )
    tx = adamw()
    state = create_state(sharded, tx)  # eager on purpose — see docstring

    # Find the AdamW mu tree and check a TP-sharded leaf kept its spec.
    def find_mu(s):
        if hasattr(s, "mu"):
            return s.mu
        if isinstance(s, (tuple, list)):
            for inner in s:
                found = find_mu(inner)
                if found is not None:
                    return found
        return None

    mu = find_mu(state.opt_state)
    assert mu is not None, "no AdamW moment tree found"
    mu_qw = mu["blocks"]["attn"]["q_w"]
    assert mu_qw.sharding.spec == P(None, None, "model")


def test_sharded_step_matches_unsharded():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(4), cfg)
    rng = np.random.default_rng(5)
    batch = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 17)), jnp.int32)
    tx = adamw(lr=1e-3, warmup_steps=1, total_steps=10)
    loss_fn = functools.partial(llama.loss_fn, cfg=cfg)
    step = make_train_step(tx, loss_fn)

    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("data", "model"))
    specs = llama.param_specs(cfg)
    sharded = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda v: isinstance(v, P),
    )
    # The step DONATES its input state, and device_put with a replicated
    # spec can alias the source buffer — give the donating unsharded run
    # its own deep copy so `sharded` survives.
    params_copy = jax.tree.map(lambda x: jnp.array(x, copy=True), params)
    _, ref_loss = step(create_state(params_copy, tx), batch)
    sbatch = jax.device_put(batch, NamedSharding(mesh, P("data", None)))
    sstate, s_loss = step(create_state(sharded, tx), sbatch)
    np.testing.assert_allclose(float(s_loss), float(ref_loss),
                               atol=1e-6, rtol=1e-6)
    assert int(sstate.step) == 1
