"""Background file-materialization contracts (ISSUE 5 tentpole).

The restructured pull finishes the HBM landing before HF-cache files
finish writing: the write-behind lane is a true background stage
(non-blocking handoff, ``ZEST_FILES_WORKERS``-wide pool), temp files
commit (fsync + atomic rename) only at the pull-exit durability
barrier, and the materialization byte movement itself rides
``posix_fallocate`` + ``pwritev`` with a ``copy_file_range`` zero-copy
lane for stored-scheme cache runs. These tests pin:

- the crash contract — a pull killed after the HBM commit but before
  file writes complete leaves NO complete-named partial file, and the
  re-pull converges byte-identical from the warm cache;
- byte identity of every materialization lane (tensors write-behind,
  copy_file_range, cache decode, waterfall) against the fixture bytes;
- the schema evidence the CI smoke gates on — ``time_to_hbm_s <
  elapsed_s`` with the files span overlapping the post-commit window;
- chaos: a corrupt-serving peer pulled *through the copy lane* still
  attributes the corruption and self-heals (the zero-copy tier never
  weakens the trust boundary).
"""

import threading

import pytest

from zest_tpu.bench_scale import llama_checkpoint_files
from zest_tpu.config import Config
from zest_tpu.transfer.pull import pull_model

from fixtures import FixtureHub, FixtureRepo

# Multi-shard llama-shaped repo, bf16-random (incompressible → the
# stored-scheme frames the copy_file_range lane exists for).
FILES = llama_checkpoint_files(0.012, shard_bytes=3 * 1024 * 1024,
                               scale=8)
SHARDS = sorted(n for n in FILES if n.endswith(".safetensors"))


@pytest.fixture(scope="module")
def hub():
    repo = FixtureRepo("acme/files-async", FILES, chunks_per_xorb=8)
    with FixtureHub(repo) as h:
        yield h


def _cfg(hub, root, **kw):
    return Config(hf_home=root / "hf", cache_dir=root / "zest",
                  hf_token="hf_test", endpoint=hub.url, **kw)


def _quiet(*a, **k):
    pass


# ── Schema: materialization is off the time-to-HBM span ──


def test_device_pull_schema_files_after_hbm(hub, tmp_path):
    res = pull_model(_cfg(hub, tmp_path), "acme/files-async",
                     no_p2p=True, device="tpu", log=_quiet)
    stats = res.stats
    assert stats["hbm"]["direct"] is True
    # The landing finished strictly before the pull did (the durability
    # barrier runs after), and files-stage work ran in the post-commit
    # window — the background-lane evidence, schema-level.
    assert stats["time_to_hbm_s"] < stats["elapsed_s"]
    assert stats["files_after_hbm_s"] > 0
    pipe = stats["files_pipeline"]
    assert pipe["async"] is True
    assert pipe["materialize_workers"] >= 2
    # Every shard rode the write-behind lane (nothing forced a decline
    # at the default 2 GiB budget), and lane bytes cover the shards.
    shard_bytes = sum(len(FILES[n]) for n in SHARDS)
    assert pipe["lane_bytes"].get("tensors", 0) == shard_bytes
    for name, data in FILES.items():
        assert (res.snapshot_dir / name).read_bytes() == data


def test_blocking_handoff_knob_restores_pr1_contract(hub, tmp_path):
    res = pull_model(_cfg(hub, tmp_path, files_async=False),
                     "acme/files-async", no_p2p=True, device="tpu",
                     log=_quiet)
    assert res.stats["files_pipeline"]["async"] is False
    for name, data in FILES.items():
        assert (res.snapshot_dir / name).read_bytes() == data


# ── Crash contract: killed after commit, before files complete ──


def test_kill_after_hbm_commit_leaves_no_complete_partials(
        hub, tmp_path, monkeypatch):
    """Kill the pull at the durability barrier — HBM params are
    resident, every write-behind temp file is written but none is
    renamed. The snapshot must hold NO complete-named safetensors, and
    the re-pull (same warm cache) must converge byte-identical."""
    import zest_tpu.transfer.pull as pull_mod

    barrier_hits = threading.Event()
    orig_barrier = pull_mod._FilePipeline._commit_barrier

    def killed_barrier(self):
        barrier_hits.set()
        raise KeyboardInterrupt("killed before the durability barrier")

    monkeypatch.setattr(pull_mod._FilePipeline, "_commit_barrier",
                        killed_barrier)
    cfg = _cfg(hub, tmp_path)
    with pytest.raises(KeyboardInterrupt):
        pull_model(cfg, "acme/files-async", no_p2p=True, device="tpu",
                   log=_quiet)
    assert barrier_hits.is_set(), "pull died before reaching the barrier"

    snap_root = cfg.model_cache_dir("acme/files-async") / "snapshots"
    snap = next(snap_root.iterdir())
    for name in SHARDS:
        assert not (snap / name).exists(), (
            f"{name} committed despite the kill — the partial-file "
            "contract is broken")

    monkeypatch.setattr(pull_mod._FilePipeline, "_commit_barrier",
                        orig_barrier)
    res = pull_model(cfg, "acme/files-async", no_p2p=True, device="tpu",
                     log=_quiet)
    # Convergence is from the warm xorb cache, not a refetch.
    assert res.stats["fetch"]["bytes"]["cache"] > 0
    for name, data in FILES.items():
        assert (res.snapshot_dir / name).read_bytes() == data
    # Crash leftovers (unrenamed temps from the killed pull) must not
    # shadow the converged snapshot's completeness.
    for name in FILES:
        assert (snap / name).stat().st_size == len(FILES[name])


# ── Byte identity across lanes ──


def test_declined_handoff_materializes_from_cache_byte_identical(
        hub, tmp_path, monkeypatch):
    """Force every write-behind handoff to decline (tensors lane off):
    shards must then materialize post-commit through the cache lane
    (copy_file_range / pread-pwrite + decode) — byte-identical, with
    the lane accounting showing zero tensor-lane bytes."""
    import zest_tpu.transfer.pull as pull_mod

    monkeypatch.setattr(pull_mod, "_write_file_from_tensors",
                        lambda *a, **k: None)
    res = pull_model(_cfg(hub, tmp_path), "acme/files-async",
                     no_p2p=True, device="tpu", log=_quiet)
    lanes = res.stats["files_pipeline"]["lane_bytes"]
    assert lanes.get("tensors", 0) == 0
    # bf16-random shards are stored-scheme: the zero-copy tier moved
    # real bytes (kernel copy_file_range or its pread/pwrite fallback).
    assert lanes.get("copy", 0) > 0
    for name, data in FILES.items():
        assert (res.snapshot_dir / name).read_bytes() == data


def test_async_and_sequential_pulls_byte_identical(hub, tmp_path):
    """The acceptance bit: the async background materialization and the
    fully serialized path (blocking handoff, width 1, single writer)
    produce byte-identical HF-cache trees."""
    seq = pull_model(
        _cfg(hub, tmp_path / "seq", files_async=False,
             pull_pipeline_width=1, files_workers=1),
        "acme/files-async", no_p2p=True, device="tpu", log=_quiet)
    par = pull_model(
        _cfg(hub, tmp_path / "par"),
        "acme/files-async", no_p2p=True, device="tpu", log=_quiet)
    for name, data in FILES.items():
        a = (seq.snapshot_dir / name).read_bytes()
        b = (par.snapshot_dir / name).read_bytes()
        assert a == data and b == data, f"{name} corrupt"


def test_copy_plan_covers_stored_runs_and_decodes_rest(hub, tmp_path):
    """CachedFileReader.copy_plan on a warmed cache: stored-scheme
    terms plan as per-chunk payload copies, the plan tiles the file
    with the decode leftovers, and executing it reproduces the exact
    bytes (the unit-level identity under the pull-level tests above)."""
    import os
    import tempfile

    from zest_tpu.cas.hub import HubClient
    from zest_tpu.models.direct import CachedFileReader
    from zest_tpu.transfer.bridge import XetBridge
    from zest_tpu.transfer.pull import _execute_copy_plan

    cfg = _cfg(hub, tmp_path)
    # Warm the cache first (a plain pull caches every fetched unit).
    pull_model(cfg, "acme/files-async", no_p2p=True, log=_quiet)
    hubc = HubClient(cfg)
    bridge = XetBridge(cfg)
    bridge.authenticate("acme/files-async", "main", hub=hubc)
    entry = next(e for e in hubc.list_files("acme/files-async", "main")
                 if e.path == SHARDS[0])
    rec = bridge.get_reconstruction(entry.xet_hash)
    reader = CachedFileReader(bridge.cache, rec, workers=1)
    size = reader.size
    copies, leftovers = reader.copy_plan(0, size)
    assert copies, "warm bf16 shard planned no zero-copy runs"
    planned = sum(int(lens.sum()) for _p, _s, _d, lens in copies)
    leftover_bytes = sum(hi - lo for lo, hi in leftovers)
    assert planned + leftover_bytes == size

    fd, tmp = tempfile.mkstemp(dir=tmp_path)
    try:
        os.ftruncate(fd, size)
        moved = _execute_copy_plan(copies, fd)
        assert moved == planned
        for d_lo, d_hi in leftovers:
            os.pwrite(fd, reader.read(d_lo, d_hi), d_lo)
        assert os.pread(fd, size, 0) == FILES[SHARDS[0]]
    finally:
        os.close(fd)
        os.unlink(tmp)
    bridge.close()


# ── Chaos: corruption through the copy lane ──


@pytest.mark.chaos
def test_chunk_corrupt_attributed_and_healed_through_copy_lane(tmp_path):
    """A peer serving flipped bytes, with the tensors lane disabled so
    every shard materializes through the copy_file_range tier: the
    corruption must be attributed to the peer (trust-boundary verify),
    healed from CDN, and the materialized files byte-exact — the
    zero-copy tier changed no trust boundary.

    chunks_per_xorb=1 matches the chaos suite's trust geometry: every
    peer blob is a whole xorb, so the merkle-root check at the trust
    boundary is provable for each one (partial footerless blobs are
    outside that proof by the documented model — SCALING.md §4 — on
    the decode lane exactly as on this copy lane). This test is what
    caught the unit-path trust gap `XetBridge._unit_blob_verifies` now
    closes: the warm-fetch peer tier checked only blob structure, so a
    stored-chunk byte flip used to reach the cache, the HBM commit,
    and the materialized file silently."""
    import zest_tpu.transfer.pull as pull_mod
    from zest_tpu import faults
    from zest_tpu.transfer.server import BtServer
    from zest_tpu.transfer.swarm import SwarmDownloader

    # Small single-chunk-xorb repo: every corrupt unit costs a peer
    # round + strike + CDN heal, so xorb count is the test's wall time.
    chaos_files = llama_checkpoint_files(0.003,
                                         shard_bytes=1024 * 1024, scale=8)
    repo = FixtureRepo("acme/files-async-chaos", chaos_files,
                       chunks_per_xorb=1)
    faults.reset()
    with FixtureHub(repo) as hub:
        def cfg_for(name):
            return Config(hf_home=tmp_path / name / "hf",
                          cache_dir=tmp_path / name / "zest",
                          hf_token="hf_test", endpoint=hub.url,
                          listen_port=0)

        seed_cfg = cfg_for("seeder")
        pull_model(seed_cfg, "acme/files-async-chaos", no_p2p=True,
                   log=_quiet)
        server = BtServer(seed_cfg)
        port = server.start()
        orig_wfft = pull_mod._write_file_from_tensors
        try:
            faults.install(f"chunk_corrupt:1.0@127.0.0.1:{port}",
                           seed=1337)
            pull_mod._write_file_from_tensors = lambda *a, **k: None
            cfg = cfg_for("leecher")
            swarm = SwarmDownloader(cfg)
            swarm.add_direct_peer("127.0.0.1", port)
            try:
                # pod=False: the collective pre-pass over the virtual
                # 8-device mesh costs minutes at 228 single-chunk xorbs
                # and is orthogonal to the materialization contract
                # under test — the warm fetch still rides the corrupt
                # peer and the copy lane still materializes every file.
                result = pull_model(cfg, "acme/files-async-chaos",
                                    swarm=swarm, device="tpu", pod=False,
                                    log=_quiet)
            finally:
                swarm.close()
        finally:
            pull_mod._write_file_from_tensors = orig_wfft
            server.shutdown()
            faults.reset()

    for name, data in chaos_files.items():
        assert (result.snapshot_dir / name).read_bytes() == data
    # The fault fired, was attributed to the serving peer, and healed.
    assert result.stats["faults"]["chunk_corrupt"] >= 1
    assert result.stats["swarm"]["corrupt_from_peer"] >= 1
    assert result.stats["fetch"]["bytes"]["cdn"] > 0
    # And the bytes really moved through the zero-copy tier.
    assert result.stats["files_pipeline"]["lane_bytes"].get("copy", 0) > 0
