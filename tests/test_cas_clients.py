"""Hub + CAS client tests against the loopback fixture server.

This is the reference's integration-tier-1 analog (verify-model.sh) scoped
to the metadata layer: real HTTP, real API shapes, zero egress.
"""

import os

import pytest

from zest_tpu.cas import hashing
from zest_tpu.cas.client import CasClient, CasError
from zest_tpu.cas.hub import HubClient, HubError
from zest_tpu.cas.xorb import XorbReader
from zest_tpu.config import Config

from fixtures import FixtureHub, FixtureRepo


@pytest.fixture(scope="module")
def rng_files():
    rng = os.urandom
    return {
        "config.json": b'{"model_type": "test"}',
        "model.safetensors": os.urandom(300_000),
        "tokenizer.json": b'{"version": "1.0"}' * 100,
    }


@pytest.fixture(scope="module")
def hub(rng_files):
    repo = FixtureRepo("test-org/tiny-model", rng_files, chunks_per_xorb=2)
    with FixtureHub(repo) as h:
        yield h


@pytest.fixture
def cfg(hub, tmp_path):
    return Config(
        hf_home=tmp_path / "hf",
        cache_dir=tmp_path / "zest",
        hf_token="hf_test",
        endpoint=hub.url,
    )


class TestHubClient:
    def test_resolve_revision(self, cfg):
        client = HubClient(cfg)
        sha = client.resolve_revision("test-org/tiny-model", "main")
        assert sha.startswith("f1x7ure5ha")

    def test_resolve_unknown_repo(self, cfg):
        with pytest.raises(HubError):
            HubClient(cfg).resolve_revision("nope/missing", "main")

    def test_list_files_with_xet_detection(self, cfg, rng_files):
        entries = {e.path: e for e in HubClient(cfg).list_files(
            "test-org/tiny-model"
        )}
        assert set(entries) == set(rng_files)
        assert entries["model.safetensors"].is_xet
        assert not entries["config.json"].is_xet
        assert entries["model.safetensors"].size == 300_000

    def test_download_regular_file(self, cfg, tmp_path, rng_files):
        dest = tmp_path / "out" / "config.json"
        n = HubClient(cfg).download_regular_file(
            "test-org/tiny-model", "main", "config.json", dest
        )
        assert dest.read_bytes() == rng_files["config.json"]
        assert n == len(rng_files["config.json"])

    def test_xet_token_exchange(self, cfg, hub):
        cas_url, token = HubClient(cfg).xet_read_token("test-org/tiny-model")
        assert cas_url == hub.url and token == "fixture-access-token"


class TestCasClient:
    def _cas(self, cfg):
        cas_url, token = HubClient(cfg).xet_read_token("test-org/tiny-model")
        return CasClient(cas_url, token)

    def test_reconstruction_matches_file(self, cfg, hub, rng_files):
        cas = self._cas(cfg)
        entries = HubClient(cfg).list_files("test-org/tiny-model")
        xet_file = next(e for e in entries if e.is_xet)
        rec = cas.get_reconstruction(xet_file.xet_hash)
        assert rec.total_bytes == len(rng_files["model.safetensors"])
        # chunks_per_xorb=2 on a 300KB file must force multiple terms
        assert len(rec.terms) > 1

    def test_full_fetch_and_reassembly(self, cfg, hub, rng_files):
        cas = self._cas(cfg)
        entries = HubClient(cfg).list_files("test-org/tiny-model")
        xet_file = next(e for e in entries if e.is_xet)
        rec = cas.get_reconstruction(xet_file.xet_hash)
        out = bytearray()
        for term in rec.terms:
            fi = rec.find_fetch_info(term)
            assert fi is not None, "every term must have covering fetch info"
            # fetch_info URLs are served absolute (production behavior);
            # pass through untouched, mirroring bridge._absolute_url.
            blob = cas.fetch_xorb_from_url(
                fi.url, (fi.url_range_start, fi.url_range_end)
            )
            reader = XorbReader(blob)
            local_start = term.range.start - fi.range.start
            local_end = term.range.end - fi.range.start
            out += reader.extract_chunk_range(local_start, local_end)
        assert bytes(out) == rng_files["model.safetensors"]

    def test_byte_range_fetch_is_subset(self, cfg, hub):
        cas = self._cas(cfg)
        xh_hex = next(iter(hub.repos["test-org/tiny-model"].xorbs))
        xf = hub.repos["test-org/tiny-model"].xorbs[xh_hex]
        full = cas.fetch_xorb_from_url(hub.url + f"/xorbs/{xh_hex}")
        assert full == xf.full  # unranged GET returns the footered artifact
        part = cas.fetch_xorb_from_url(
            hub.url + f"/xorbs/{xh_hex}", (0, xf.frame_offsets[1])
        )
        assert part == xf.blob[: xf.frame_offsets[1]]
        assert len(XorbReader(part)) == 1

    def test_unauthorized_reconstruction_rejected(self, cfg, hub):
        cas = CasClient(hub.url, "wrong-token")
        with pytest.raises(CasError):
            cas.get_reconstruction("0" * 64)

    def test_missing_reconstruction_404(self, cfg):
        cas = self._cas(cfg)
        with pytest.raises(CasError, match="no reconstruction"):
            cas.get_reconstruction("f" * 64)

    def test_invalid_byte_range_rejected(self, cfg, hub):
        cas = self._cas(cfg)
        with pytest.raises(CasError, match="invalid byte range"):
            cas.fetch_xorb_from_url(hub.url + "/xorbs/xx", (5, 5))


def test_fixture_dedup_across_files():
    """Two files sharing content must share chunk hashes (CDC dedup)."""
    shared = os.urandom(200_000)
    repo = FixtureRepo(
        "o/r",
        {"a.safetensors": shared, "b.safetensors": shared + os.urandom(50_000)},
    )
    recs = list(repo.reconstructions.values())
    assert len(recs) == 2
    h0 = {hashing.hash_to_hex(t.xorb_hash) for t in recs[0].terms}
    h1 = {hashing.hash_to_hex(t.xorb_hash) for t in recs[1].terms}
    # Same leading content -> at least the first xorb content overlaps via
    # shared chunks; verify chunk-level sharing through the xorb store.
    chunk_sets = []
    for hexes in (h0, h1):
        s = set()
        for xh in hexes:
            s |= {h for h, _ in XorbReader(repo.xorbs[xh].blob).chunk_hashes()}
        chunk_sets.append(s)
    assert chunk_sets[0] & chunk_sets[1], "no shared chunks despite shared content"


class TestStreamingFetch:
    """fetch_xorb_iter — the streaming shape the GB-scale warm path
    writes straight into cache files (one memory pass fewer)."""

    def test_iter_matches_bulk(self, cfg, hub):
        from zest_tpu.cas.client import CasClient

        cas = CasClient(hub.url, "hf_test")
        xh_hex = next(iter(hub.repos["test-org/tiny-model"].xorbs))
        xf = hub.repos["test-org/tiny-model"].xorbs[xh_hex]
        url = hub.url + f"/xorbs/{xh_hex}"
        assert b"".join(cas.fetch_xorb_iter(url)) == xf.full
        rng = (2, xf.frame_offsets[1])
        assert (b"".join(cas.fetch_xorb_iter(url, rng))
                == xf.full[rng[0]:rng[1]])

    def test_trims_when_origin_ignores_range(self):
        """A 200 response to a ranged request must stream out exactly
        the window (the old bulk path sliced locally; the iterator
        trims as chunks pass)."""
        import http.server
        import threading

        from zest_tpu.cas.client import CasClient

        body = bytes(range(256)) * 8192  # 2 MiB, crosses chunk bounds

        class NoRange(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                self.send_response(200)  # ignores Range on purpose
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), NoRange)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            url = f"http://127.0.0.1:{srv.server_address[1]}/x"
            cas = CasClient(url, "hf_test")
            for lo, hi in [(0, 10), (1000, 1_500_000), (2 * 1024 * 1024 - 7,
                                                        2 * 1024 * 1024)]:
                got = b"".join(cas.fetch_xorb_iter(url, (lo, hi)))
                assert got == body[lo:hi], (lo, hi)
        finally:
            srv.shutdown()
