"""Kademlia DHT: metric/routing logic, KRPC codecs, 2-node loopback.

Mirrors the reference's pure-logic + codec-roundtrip test style
(dht.zig:475-671) and goes one further: a real two-node UDP loopback
exchange (the reference has no live DHT test at all).
"""

import pytest

from zest_tpu.p2p import bencode
from zest_tpu.p2p.dht import (
    Dht,
    DhtError,
    KBucket,
    Node,
    RoutingTable,
    bucket_index,
    build_announce_peer,
    build_find_node,
    build_get_peers,
    build_ping,
    encode_compact_nodes,
    encode_compact_peers,
    parse_compact_nodes,
    parse_compact_peers,
    xor_distance,
)


def _id(prefix: bytes) -> bytes:
    return prefix + bytes(20 - len(prefix))


# ── Metric (dht.zig:475-520) ──


def test_xor_distance_symmetry_and_identity():
    a, b = _id(b"\x01"), _id(b"\xff")
    assert xor_distance(a, a) == bytes(20)
    assert xor_distance(a, b) == xor_distance(b, a)


def test_bucket_index_msb_rule():
    assert bucket_index(bytes(20)) == -1
    assert bucket_index(_id(b"\x80")) == 0
    assert bucket_index(_id(b"\x01")) == 7
    assert bucket_index(b"\x00" + _id(b"\x80")[:-1]) == 8
    last = bytes(19) + b"\x01"
    assert bucket_index(last) == 159


def test_kbucket_lru_eviction_keeps_responsive_nodes():
    """Unlike the reference (drops newcomers, dht.zig:81-97), a full bucket
    evicts the least-recently-seen entry."""
    b = KBucket(k=2)
    n1, n2, n3 = (Node(_id(bytes([i])), ("127.0.0.1", i)) for i in (1, 2, 3))
    b.update(n1)
    b.update(n2)
    b.update(n1)          # refresh n1: n2 becomes LRU
    b.update(n3)          # full: evict n2
    ids = [n.node_id for n in b.nodes]
    assert n1.node_id in ids and n3.node_id in ids
    assert n2.node_id not in ids


def test_routing_table_closest_sorted_by_xor():
    table = RoutingTable(_id(b"\x00"))
    for i in range(1, 30):
        table.update(_id(bytes([i])), ("127.0.0.1", i))
    target = _id(b"\x05")
    closest = table.closest(target, 4)
    dists = [xor_distance(n.node_id, target) for n in closest]
    assert dists == sorted(dists)
    assert closest[0].node_id == _id(b"\x05")


def test_routing_table_never_inserts_self():
    me = _id(b"\xaa")
    table = RoutingTable(me)
    table.update(me, ("127.0.0.1", 1))
    assert len(table) == 0


# ── KRPC codecs (dht.zig:578-671) ──


def test_krpc_queries_are_valid_bencode():
    sid, ih, tid = _id(b"s"), _id(b"i"), b"\x00\x01"
    for raw in (
        build_ping(sid, tid),
        build_find_node(sid, ih, tid),
        build_get_peers(sid, ih, tid),
        build_announce_peer(sid, ih, 6881, b"tok", tid),
    ):
        doc = bencode.decode(raw)
        assert doc[b"t"] == tid and doc[b"y"] == b"q"
        assert bencode.dict_get_dict(doc, b"a")[b"id"] == sid


def test_compact_node_roundtrip():
    nodes = [
        Node(_id(b"\x01"), ("10.0.0.1", 6881)),
        Node(_id(b"\x02"), ("192.168.1.9", 51413)),
    ]
    raw = encode_compact_nodes(nodes)
    assert len(raw) == 52
    back = parse_compact_nodes(raw)
    assert back == [(n.node_id, n.addr) for n in nodes]


def test_compact_peer_roundtrip_and_garbage_tolerance():
    peers = [("10.1.2.3", 6881), ("127.0.0.1", 80)]
    vals = encode_compact_peers(peers)
    assert parse_compact_peers(vals) == peers
    assert parse_compact_peers([b"short", 42, b"x" * 7]) == []


def test_parse_compact_nodes_rejects_misaligned():
    with pytest.raises(DhtError):
        parse_compact_nodes(b"x" * 27)


# ── Live loopback (no reference counterpart — improves on its shallow
#    connection tests, SURVEY.md §4 "limitation worth not repeating") ──


@pytest.fixture
def two_nodes():
    a = Dht(bind=("127.0.0.1", 0), request_timeout=2.0)
    b = Dht(bind=("127.0.0.1", 0), request_timeout=2.0)
    yield a, b
    a.close()
    b.close()


def test_ping_updates_routing_tables(two_nodes):
    a, b = two_nodes
    assert a.ping(("127.0.0.1", b.port))
    assert len(a.table) == 1       # from b's response
    assert len(b.table) == 1       # from a's query


def test_announce_and_get_peers_roundtrip(two_nodes):
    a, b = two_nodes
    a.bootstrap([("127.0.0.1", b.port)])
    info_hash = _id(b"\xfe")
    assert a.announce_peer(info_hash, 7001) == 1
    peers, _tokens = b.get_peers(info_hash)  # b holds the store locally
    assert ("127.0.0.1", 7001) in list(b.peer_store[info_hash])
    # and a third node discovers through b
    c = Dht(bind=("127.0.0.1", 0), request_timeout=2.0)
    try:
        c.bootstrap([("127.0.0.1", b.port)])
        found = c.find_peers(info_hash)
        assert ("127.0.0.1", 7001) in found
    finally:
        c.close()


def test_announce_with_invalid_token_is_dropped(two_nodes):
    a, b = two_nodes
    info_hash = _id(b"\xee")
    resp = a._request(
        lambda tid: build_announce_peer(
            a.node_id, info_hash, 7002, b"badtoken", tid
        ),
        ("127.0.0.1", b.port),
    )
    assert resp is None            # silently dropped
    assert info_hash not in b.peer_store
