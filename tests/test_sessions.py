"""Pull-session observability (ISSUE 11): the session table lifecycle,
the live ``/v1/pulls`` + SSE surfaces, critical-path attribution, SLO
breach detection, and the concurrent-pull gauge-clobber fix.

The contract under test: every pull is a first-class observable
session — registered at entry, live phase/progress while running,
terminal status + stats after — with bounded memory (active + recent
ring), zero behavior change with ``ZEST_TELEMETRY=0`` (empty table,
byte-identical pull), and per-session values immune to the
process-global ``zest_last_pull_*`` gauge clobber two concurrent pulls
used to suffer.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from zest_tpu import telemetry
from zest_tpu.telemetry import critpath, session as session_mod
from zest_tpu.telemetry import trace as trace_mod
from zest_tpu.transfer.pull import pull_model

from fixtures import FixtureHub, FixtureRepo, gpt2_checkpoint_files


@pytest.fixture(autouse=True)
def clean_telemetry():
    telemetry.REGISTRY.reset()
    trace_mod.uninstall()
    telemetry.set_enabled(None)
    telemetry.recorder.reset()
    session_mod.reset()
    yield
    telemetry.REGISTRY.reset()
    trace_mod.uninstall()
    telemetry.set_enabled(None)
    telemetry.recorder.reset()
    session_mod.reset()


FILES = {
    "config.json": b'{"model_type": "test"}',
    "model.safetensors": bytes(range(256)) * 2048,  # 512 KiB
    "tokenizer.json": b'{"tok": 1}' * 40,
}


@pytest.fixture(scope="module")
def hub():
    repo = FixtureRepo("acme/session-model", FILES, chunks_per_xorb=3)
    # A valid (landable) checkpoint for the --device tests: the SLO
    # budgets and the hbm-wall assertions need a real time_to_hbm_s.
    ckpt = FixtureRepo("acme/session-ckpt", gpt2_checkpoint_files(),
                       chunks_per_xorb=3)
    with FixtureHub(repo, ckpt) as h:
        yield h


def _cfg(hub, root, **kw):
    from zest_tpu.config import Config

    return Config(hf_home=root / "hf", cache_dir=root / "zest",
                  hf_token="hf_test", endpoint=hub.url, **kw)


# ── Session table ──


class TestSessionTable:
    def test_lifecycle_active_then_recent(self):
        sess = session_mod.begin("a/b", "main", tenant="t1", device="tpu")
        assert sess is not None
        assert session_mod.SESSIONS.active_ids() == [sess.id]
        snap = sess.snapshot()
        assert snap["status"] == "running"
        assert snap["tenant"] == "t1" and snap["device"] == "tpu"
        session_mod.finish(sess, "ok", stats={"elapsed_s": 1.0})
        assert session_mod.SESSIONS.active_ids() == []
        recent = session_mod.SESSIONS.recent()
        assert [s.id for s in recent] == [sess.id]
        snap = recent[0].snapshot(detail=True)
        assert snap["status"] == "ok" and snap["phase"] == "done"
        assert snap["stats"] == {"elapsed_s": 1.0}
        # get() resolves terminal sessions from the ring too.
        assert session_mod.get(sess.id) is sess

    def test_recent_ring_is_bounded(self):
        table = session_mod.SessionTable(capacity=3)
        ids = []
        for i in range(5):
            s = table.begin(f"a/r{i}")
            table.finish(s, "ok")
            ids.append(s.id)
        recent = [s.id for s in table.recent()]
        assert recent == ids[-1:-4:-1]  # newest first, oldest 2 evicted
        assert table.get(ids[0]) is None

    def test_capacity_env_knob(self, monkeypatch):
        monkeypatch.setenv(session_mod.ENV_RECENT, "2")
        table = session_mod.SessionTable()
        assert table.capacity == 2

    def test_disabled_registers_nothing(self):
        telemetry.set_enabled(False)
        assert session_mod.begin("a/b") is None
        session_mod.finish(None, "ok")  # no-op contract
        assert session_mod.payload()["active"] == []
        assert session_mod.payload()["recent"] == []

    def test_error_terminal_state(self):
        sess = session_mod.begin("a/b")
        session_mod.finish(sess, "error", error="ValueError: boom")
        snap = session_mod.SESSIONS.recent()[0].snapshot()
        assert snap["status"] == "error"
        assert snap["error"] == "ValueError: boom"

    def test_errored_session_keeps_progress_but_never_an_eta(self):
        class Stats:
            bytes_from_cache = 0
            bytes_from_peer = 0
            bytes_from_cdn = 400

        sess = session_mod.begin("a/b")
        sess.attach(fetch_stats=Stats())
        sess.set_total_bytes(1000)
        time.sleep(0.06)  # past the ETA warm-up floor
        assert "eta_s" in sess.snapshot()
        session_mod.finish(sess, "error", error="boom")
        snap = sess.snapshot()
        # Partial progress is honest; an ETA for a pull that will
        # never finish is not.
        assert snap["progress"] == 0.4
        assert "eta_s" not in snap

    def test_current_id_binding_and_sole_active_fallback(self):
        sess = session_mod.begin("a/b")
        # Sole active session: unbound threads resolve to it.
        assert session_mod.current_id() == sess.id
        other = session_mod.begin("a/c")
        # Two active: an unbound thread must NOT guess.
        assert session_mod.current_id() is None
        with session_mod.bind(other.id):
            assert session_mod.current_id() == other.id
        assert session_mod.current_id() is None
        session_mod.finish(sess, "ok")
        session_mod.finish(other, "ok")

    def test_recorder_events_carry_session_id(self):
        sess = session_mod.begin("a/b")
        with session_mod.bind(sess.id):
            telemetry.record("fault_fired", fault="cdn_503")
        (ev,) = telemetry.recorder.tail(1)
        assert ev["session"] == sess.id
        # The crash-report envelope carries it too.
        with session_mod.bind(sess.id):
            assert telemetry.recorder.RECORDER.report()["session"] \
                == sess.id
        session_mod.finish(sess, "ok")


# ── Pull integration ──


class TestPullSessions:
    def test_pull_registers_terminal_session(self, hub, tmp_path):
        res = pull_model(_cfg(hub, tmp_path), "acme/session-model",
                         no_p2p=True, tenant="team-a",
                         log=lambda *a, **k: None)
        payload = session_mod.payload()
        assert payload["active"] == []
        (snap,) = payload["recent"]
        assert snap["repo"] == "acme/session-model"
        assert snap["revision"] == res.stats["revision"]
        assert snap["tenant"] == "team-a"
        assert snap["status"] == "ok" and snap["progress"] == 1.0
        assert snap["bytes"]["cdn"] > 0
        assert snap["bytes"]["total"] == sum(
            len(v) for v in FILES.values())
        # Detail view carries the pull's full stats + live stage walls.
        detail = session_mod.get(snap["id"]).snapshot(detail=True)
        assert detail["stats"] is res.stats
        assert detail["stages"].keys() == res.stats["stages"].keys()

    def test_knob_off_pull_byte_identical_with_empty_table(
            self, hub, tmp_path):
        on = pull_model(_cfg(hub, tmp_path / "on"), "acme/session-model",
                        no_p2p=True, log=lambda *a, **k: None)
        assert len(session_mod.payload()["recent"]) == 1
        session_mod.reset()
        telemetry.set_enabled(False)
        try:
            off = pull_model(_cfg(hub, tmp_path / "off"),
                             "acme/session-model", no_p2p=True,
                             log=lambda *a, **k: None)
        finally:
            telemetry.set_enabled(None)
        for name, data in FILES.items():
            assert (on.snapshot_dir / name).read_bytes() == data
            assert (off.snapshot_dir / name).read_bytes() == data
        assert sorted(on.stats) == sorted(off.stats)
        p = session_mod.payload()
        assert p["active"] == [] and p["recent"] == []

    def test_two_concurrent_pulls_distinct_correct_sessions(self, tmp_path):
        """The gauge-clobber regression test (ISSUE 11 satellite): two
        concurrent --device pulls must yield two sessions whose
        recorded walls each match their OWN pull's stats — while the
        process-global zest_last_pull_hbm_seconds gauge, by
        construction, kept only one of them."""
        repos = {
            "acme/cc-small": gpt2_checkpoint_files(n_embd=32, seed=1),
            "acme/cc-large": gpt2_checkpoint_files(n_embd=96, n_layer=3,
                                                   seed=2),
        }
        results: dict = {}

        def pull(repo_id, hub, root):
            results[repo_id] = pull_model(
                _cfg(hub, root), repo_id, device="tpu", no_p2p=True,
                log=lambda *a, **k: None)

        fixtures = [FixtureRepo(rid, f, chunks_per_xorb=3)
                    for rid, f in repos.items()]
        with FixtureHub(*fixtures) as hub:
            threads = [
                threading.Thread(target=pull, args=(rid, hub,
                                                    tmp_path / str(i)))
                for i, rid in enumerate(repos)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

        recent = {s.repo: s for s in session_mod.SESSIONS.recent()}
        assert set(recent) == set(repos)
        ids = {s.id for s in recent.values()}
        assert len(ids) == 2
        for rid, res in results.items():
            sess = recent[rid]
            assert sess.stats is res.stats
            # The session's landing values are the pull's own.
            assert sess.snapshot()["time_to_hbm_s"] == \
                res.stats["time_to_hbm_s"]
            block = sess.landing_block()
            assert block["time_to_hbm_s"] == res.stats["time_to_hbm_s"]
            assert block["session"] == sess.id
        # The process gauge kept exactly ONE of the two walls — the
        # clobber the session table exists to fix.
        gauge = telemetry.REGISTRY.gauge(
            "zest_last_pull_hbm_seconds", "").value()
        # (1e-3: the gauge holds the unrounded wall, stats round to ms.)
        assert any(abs(gauge - r.stats["time_to_hbm_s"]) < 1e-3
                   for r in results.values())

    def test_slo_breach_detection(self, hub, tmp_path):
        cfg = _cfg(hub, tmp_path, slo_tthbm_s=1e-6)
        res = pull_model(cfg, "acme/session-ckpt", device="tpu",
                         no_p2p=True, log=lambda *a, **k: None)
        assert res.stats["time_to_hbm_s"] > 1e-6  # budget is absurd
        assert telemetry.REGISTRY.counter(
            "zest_slo_breaches_total", "", ("slo",)).value(slo="tthbm") \
            == 1
        breaches = [e for e in telemetry.recorder.tail()
                    if e["kind"] == "slo_breach"]
        assert len(breaches) == 1
        (snap,) = session_mod.payload()["recent"]
        assert breaches[0]["session"] == snap["id"]
        assert breaches[0]["actual_s"] == res.stats["time_to_hbm_s"]
        assert snap["slo"]["tthbm"]["breached"] is True
        burn = session_mod.SESSIONS.slo_burn()
        assert burn["tthbm"] == {"pulls": 1, "breaches": 1, "burn": 1.0}

    def test_slo_within_budget_counts_pull_not_breach(self, hub, tmp_path):
        cfg = _cfg(hub, tmp_path, slo_tthbm_s=3600.0)
        pull_model(cfg, "acme/session-ckpt", device="tpu", no_p2p=True,
                   log=lambda *a, **k: None)
        assert telemetry.REGISTRY.counter(
            "zest_slo_breaches_total", "", ("slo",)).value(slo="tthbm") \
            == 0
        assert session_mod.SESSIONS.slo_burn()["tthbm"] == \
            {"pulls": 1, "breaches": 0, "burn": 0.0}

    def test_slo_env_knob_parses_strictly(self):
        from zest_tpu.config import Config

        cfg = Config.load({"ZEST_SLO_TTHBM_S": "12.5",
                           "ZEST_SLO_TTFL_S": ""})
        assert cfg.slo_tthbm_s == 12.5 and cfg.slo_ttfl_s is None
        with pytest.raises(ValueError):
            Config.load({"ZEST_SLO_TTHBM_S": "fast"})
        # A sign slip is a typo, not "off": it must not silently disarm
        # — and neither may a templating artifact writing NaN/inf.
        with pytest.raises(ValueError):
            Config.load({"ZEST_SLO_TTFL_S": "-30"})
        with pytest.raises(ValueError):
            Config.load({"ZEST_SLO_TTHBM_S": "nan"})
        assert Config.load({"ZEST_SLO_TTHBM_S": "0"}).slo_tthbm_s is None
        assert Config.load({"ZEST_TENANT": "t9"}).tenant == "t9"


# ── Critical-path analyzer ──


class TestCritpath:
    def _iv(self, name, t0, t1, **attrs):
        return critpath._Iv(name, t0, t1, attrs)

    def test_hand_built_dag_ground_truth(self):
        """Known-blame DAG: every exclusive second is hand-checkable.

        pull 0..10 ─ resolve 0..1; fetch stage 1..4 with a cdn span
        1.5..3.5; landing 4..9 with decode 4..6 and commit 6..8.5;
        nothing 9..10 (idle)."""
        spans = [
            self._iv("pull", 0, 10, repo="a/b"),
            self._iv("stage.resolve", 0, 1),
            self._iv("stage.fetch", 1, 4),
            self._iv("cdn.fetch", 1.5, 3.5),
            self._iv("stage.hbm_commit", 4, 9),
            self._iv("land.decode", 4, 6),
            self._iv("hbm.commit", 6, 8.5),
        ]
        rep = critpath._analyze(spans)
        assert rep["root"]["wall_s"] == 10
        assert rep["path_s"] == 9.0 and rep["idle_s"] == 1.0
        assert rep["coverage"] == 0.9
        assert rep["stages"] == {"fetch": 3.0, "commit": 3.0,
                                 "decode": 2.0, "metadata": 1.0}
        assert sum(rep["stages"].values()) == pytest.approx(rep["path_s"])
        assert rep["tiers"] == {"cdn": 2.0}
        # Deepest-active blame: the cdn span owns 1.5..3.5; the stage
        # span keeps only its exclusive 1..1.5 + 3.5..4.
        assert rep["by_name"]["cdn.fetch"] == 2.0
        assert rep["by_name"]["stage.fetch"] == 1.0
        # Top blocking span is the biggest exclusive contributor.
        assert rep["top_spans"][0]["blamed_s"] == 2.5
        assert rep["top_spans"][0]["name"] == "hbm.commit"

    def test_no_root_raises(self):
        with pytest.raises(critpath.AnalyzeError):
            critpath._analyze([self._iv("stage.fetch", 0, 1)])

    def test_newest_root_selects_last_pull(self):
        spans = [
            self._iv("pull", 0, 10),
            self._iv("stage.fetch", 0, 10),
            self._iv("pull", 20, 22),
            self._iv("stage.resolve", 20, 22),
        ]
        rep = critpath._analyze(spans, newest_root=True)
        # Only the second pull's window is analyzed.
        assert rep["root"]["wall_s"] == 2
        assert rep["stages"] == {"metadata": 2.0}

    def test_explicit_root_pins_window_over_newest(self):
        """pull_model passes its OWN root span: even when another pull
        finished later in the shared tracer, the analysis windows to
        the caller's root (the concurrent-daemon correctness fix)."""
        spans = [
            self._iv("pull", 0, 10),
            self._iv("stage.fetch", 0, 10),
            self._iv("pull", 20, 22),
            self._iv("stage.resolve", 20, 22),
        ]
        rep = critpath._analyze(spans, newest_root=True,
                                root=self._iv("pull", 0, 10))
        assert rep["root"]["wall_s"] == 10
        assert rep["stages"] == {"fetch": 10.0}

    def test_doc_round_trip_matches_live(self, hub, tmp_path):
        tracer = trace_mod.install(None)
        res = pull_model(_cfg(hub, tmp_path), "acme/session-ckpt",
                         device="tpu", no_p2p=True,
                         log=lambda *a, **k: None)
        cp = res.stats["critical_path"]
        # The acceptance bar: the attributed path covers >=90% of the
        # landing wall (the 64 MiB CI smoke holds the same gate at
        # realistic scale).
        assert cp["path_s"] >= 0.9 * res.stats["time_to_hbm_s"]
        assert sum(cp["stages"].values()) == \
            pytest.approx(cp["path_s"], abs=0.01)
        out = tmp_path / "t.json"
        tracer.export(out)
        offline = critpath.analyze_doc(json.loads(out.read_text()))
        for stage, sec in cp["stages"].items():
            assert offline["stages"].get(stage, 0.0) == \
                pytest.approx(sec, abs=0.02 + 0.02 * sec)

    def test_untraced_pull_has_no_critical_path(self, hub, tmp_path):
        res = pull_model(_cfg(hub, tmp_path), "acme/session-model",
                         no_p2p=True, log=lambda *a, **k: None)
        assert "critical_path" not in res.stats

    def test_merged_doc_host_filter(self):
        # Two hosts' spans in one doc: analysis confines to one host.
        def ev(name, ts, dur, host):
            return {"name": name, "ph": "X", "ts": ts * 1e6,
                    "dur": dur * 1e6, "pid": 1, "tid": host,
                    "args": {"host": host}}

        doc = {"traceEvents": [
            ev("pull", 0, 10, 0), ev("stage.fetch", 0, 10, 0),
            ev("pull", 0, 4, 1), ev("stage.files", 0, 4, 1),
        ]}
        rep = critpath.analyze_doc(doc)  # dominant root → host 0
        assert rep["root"]["host"] == 0
        assert rep["stages"] == {"fetch": 10.0}
        rep1 = critpath.analyze_doc(doc, host=1)
        assert rep1["stages"] == {"files": 4.0}

    def test_analyze_cli(self, hub, tmp_path, capsys):
        from zest_tpu import cli

        tracer = trace_mod.install(None)
        pull_model(_cfg(hub, tmp_path), "acme/session-model",
                   no_p2p=True, log=lambda *a, **k: None)
        out = tmp_path / "t.json"
        tracer.export(out)
        assert cli.main(["analyze", str(out), "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["root"]["name"] == "pull"
        # Loose floor: a ~50 ms fixture pull's fixed setup costs are a
        # visible idle fraction; the 90%-of-time_to_hbm acceptance gate
        # runs at realistic scale in scripts/critpath_smoke.py.
        assert doc["coverage"] >= 0.8
        assert cli.main(["analyze", str(out)]) == 0
        text = capsys.readouterr().out
        assert "critical path" in text and "stage split" in text
        assert cli.main(["analyze", str(tmp_path / "missing.json")]) == 1


# ── HTTP + CLI surfaces ──


@pytest.fixture
def api(tmp_config):
    from zest_tpu.api.http_api import HttpApi

    requests = pytest.importorskip("requests")
    tmp_config.http_port = 0
    a = HttpApi(tmp_config)
    port = a.start()
    yield a, requests, f"http://127.0.0.1:{port}"
    a.close()


def test_v1_pulls_endpoints(api):
    _a, requests, base = api
    sess = session_mod.begin("a/b", tenant="t")
    doc = requests.get(f"{base}/v1/pulls", timeout=5).json()
    assert [s["id"] for s in doc["active"]] == [sess.id]
    detail = requests.get(f"{base}/v1/pulls/{sess.id}", timeout=5)
    assert detail.json()["repo"] == "a/b"
    assert requests.get(f"{base}/v1/pulls/nope", timeout=5) \
        .status_code == 404
    assert requests.get(f"{base}/v1/pulls/nope/events", timeout=5) \
        .status_code == 404
    session_mod.finish(sess, "ok", stats={"elapsed_s": 0.1})
    doc = requests.get(f"{base}/v1/pulls", timeout=5).json()
    assert doc["active"] == [] and len(doc["recent"]) == 1
    # /v1/status counts the table.
    st = requests.get(f"{base}/v1/status", timeout=5).json()
    assert st["pulls"] == {"active": 0, "recent": 1}


def test_sse_stream_against_real_pull(api, tmp_path):
    """The live progress stream (ISSUE 11 acceptance): open the SSE
    stream while a real fixture pull runs; events must go start →
    progress… → done with the terminal event carrying the stats."""
    _a, requests, base = api
    files = {"config.json": b'{"model_type": "test"}',
             "model.safetensors": bytes(range(256)) * 8192}  # 2 MiB
    repo = FixtureRepo("acme/sse-model", files, chunks_per_xorb=3)
    with FixtureHub(repo, throttle_bps=8_000_000) as hub:
        done: dict = {}

        def work():
            done["res"] = pull_model(_cfg(hub, tmp_path),
                                     "acme/sse-model", no_p2p=True,
                                     log=lambda *a, **k: None)

        t = threading.Thread(target=work, daemon=True)
        t.start()
        deadline = time.monotonic() + 10
        sid = None
        while time.monotonic() < deadline and sid is None:
            active = requests.get(f"{base}/v1/pulls", timeout=5) \
                .json()["active"]
            if active:
                sid = active[0]["id"]
            else:
                time.sleep(0.01)
        assert sid is not None, "pull never registered a live session"
        events = []
        with requests.get(f"{base}/v1/pulls/{sid}/events", stream=True,
                          timeout=30) as resp:
            for line in resp.iter_lines():
                if line and line.startswith(b"data: "):
                    events.append(json.loads(line[6:]))
                    if events[-1]["event"] in ("done", "error"):
                        break
        t.join(timeout=30)
    kinds = [e["event"] for e in events]
    assert kinds[0] == "start" and kinds[-1] == "done"
    final = events[-1]
    assert final["status"] == "ok"
    assert final["stats"]["files_downloaded"] == \
        done["res"].stats["files_downloaded"]
    assert all(e["id"] == sid for e in events)


def test_debug_landing_block_routed_through_sessions(api):
    """The /v1/debug landing block must come from the session table —
    the gauges are set to junk first to prove they are no longer the
    source under a populated table."""
    _a, requests, base = api
    telemetry.REGISTRY.gauge("zest_last_pull_hbm_seconds", "").set(999.0)
    telemetry.REGISTRY.gauge(
        "zest_last_pull_first_layer_seconds", "").set(888.0)
    sess = session_mod.begin("a/b", device="tpu")
    session_mod.finish(sess, "ok", stats={
        "time_to_hbm_s": 6.0, "time_to_first_layer_s": 1.2,
        "time_to_swap_s": 0.8, "hbm": {"ring": {"stalls": 2}},
        "delta": {"fetched_ratio": 0.021, "delta_bytes_ratio": 0.02}})
    d = requests.get(f"{base}/v1/debug", timeout=5).json()
    assert d["landing"] == {
        "session": sess.id, "time_to_hbm_s": 6.0, "first_layer_s": 1.2,
        "first_layer_ratio": 0.2, "ring_stalls": 2,
        "delta_ratio": 0.021, "swap_s": 0.8}
    # Empty table → gauge fallback (older-daemon compatibility).
    session_mod.reset()
    d = requests.get(f"{base}/v1/debug", timeout=5).json()
    assert d["landing"]["time_to_hbm_s"] == 999.0


def test_cmd_ps(api, monkeypatch, capsys):
    from zest_tpu import cli

    _a, _requests, base = api
    monkeypatch.setenv("ZEST_HTTP_PORT", base.rsplit(":", 1)[1])
    sess = session_mod.begin("a/b", tenant="team-x")
    assert cli.main(["ps"]) == 0
    out = capsys.readouterr().out
    assert sess.id in out and "a/b@main" in out and "team-x" in out
    assert cli.main(["ps", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["active"][0]["id"] == sess.id
    session_mod.finish(sess, "ok")


def test_ps_lines_pure():
    from zest_tpu.cli import _ps_lines

    lines = _ps_lines({
        "active": [{"id": "p1", "repo": "a/b", "revision": "deadbeef",
                    "status": "running", "phase": "fetch",
                    "progress": 0.42, "eta_s": 3.0, "elapsed_s": 2.1,
                    "tenant": "t"}],
        "recent": [{"id": "p0", "repo": "a/b", "revision": "deadbeef",
                    "status": "ok", "phase": "done", "progress": 1.0,
                    "elapsed_s": 5.0,
                    "slo": {"tthbm": {"breached": True}}}],
        "slo": {"tthbm": {"pulls": 4, "breaches": 1, "burn": 0.25}},
    })
    joined = "\n".join(lines)
    assert "42%" in joined and "eta 3.0s" in joined
    assert "ok!slo" in joined
    assert "slo burn: tthbm=1/4 (25.0%)" in joined
