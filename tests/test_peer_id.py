"""Peer identity tests (parity: reference src/peer_id.zig:47-63)."""

import hashlib

from zest_tpu.p2p import peer_id


def test_peer_id_prefix_and_length():
    pid = peer_id.generate()
    assert len(pid) == 20
    assert pid.startswith(b"-ZT0100-")


def test_peer_ids_differ():
    assert peer_id.generate() != peer_id.generate()


def test_info_hash_deterministic():
    h = bytes(range(32))
    a = peer_id.compute_info_hash(h)
    b = peer_id.compute_info_hash(h)
    assert a == b and len(a) == 20


def test_info_hash_domain_separation():
    # Must equal SHA-1("zest-xet-v1:" || hash) byte-for-byte for swarm
    # interop with the reference (src/peer_id.zig:28-33).
    h = b"\xab" * 32
    expected = hashlib.sha1(b"zest-xet-v1:" + h).digest()
    assert peer_id.compute_info_hash(h) == expected


def test_info_hash_rejects_bad_length():
    import pytest

    with pytest.raises(ValueError):
        peer_id.compute_info_hash(b"short")
