"""Live telemetry timelines (ISSUE 15): the bounded time-series store,
counter-rate derivation, streaming anomaly detection with session
attribution, the ``/v1/timeline`` surface, ``zest top``, and the
tenancy-metrics satellites.

The contract under test: bounded memory by construction (per-series
ring × series cap), rate series that integrate exactly back to the
counters they were derived from, anomalies that fire once per episode
with the right kind and session, and ``ZEST_TIMELINE=0`` restoring the
timeline-less process bit-for-bit (no sampler thread, empty store,
byte-identical pull)."""

from __future__ import annotations

import json
import threading
import time

import pytest

from zest_tpu import telemetry
from zest_tpu.telemetry import critpath
from zest_tpu.telemetry import session as session_mod
from zest_tpu.telemetry import timeline
from zest_tpu.transfer import tenancy
from zest_tpu.transfer.pull import pull_model

from fixtures import FixtureHub, FixtureRepo


@pytest.fixture(autouse=True)
def clean_telemetry():
    telemetry.reset_all()
    tenancy.reset()
    yield
    telemetry.reset_all()
    tenancy.reset()


def _cfg(hub, root, **kw):
    from zest_tpu.config import Config

    return Config(hf_home=root / "hf", cache_dir=root / "zest",
                  hf_token="hf_test", endpoint=hub.url, **kw)


class _FakeFetch:
    """Scripted FetchStats double: the detector only reads the three
    byte counters."""

    def __init__(self):
        self.bytes_from_cache = 0
        self.bytes_from_peer = 0
        self.bytes_from_cdn = 0


# ── Store: ring bound, series cap, cursor paging ──


class TestStore:
    def test_series_ring_is_bounded(self):
        store = timeline.TimelineStore(capacity=4)
        for i in range(10):
            store._append("s", float(i), "gauge", float(i))
        doc = store.payload()
        vals = [v for _t, v in doc["series"]["s"]["samples"]]
        assert vals == [6.0, 7.0, 8.0, 9.0]  # oldest evicted

    def test_series_count_is_capped_lru(self):
        store = timeline.TimelineStore(capacity=4, max_series=3)
        for i in range(5):
            store._append(f"s{i}", 1.0, "gauge", float(i))
        store._append("s2", 2.0, "gauge", 9.0)  # touch s2
        store._append("brand.new", 1.0, "gauge", 10.0)
        names = set(store.payload()["series"])
        assert len(names) == 3
        assert "s2" in names and "brand.new" in names
        assert "s0" not in names and "s1" not in names

    def test_cursor_paging(self):
        store = timeline.TimelineStore(capacity=16)
        store._append("a", 1.0, "gauge", 1.0)
        store._append("b", 2.0, "gauge", 1.0)
        doc = store.payload()
        cursor = doc["cursor"]
        assert {n for n in doc["series"]} == {"a", "b"}
        # Nothing new past the cursor.
        assert store.payload(since=cursor)["series"] == {}
        store._append("a", 3.0, "gauge", 2.0)
        page = store.payload(since=cursor)
        assert list(page["series"]) == ["a"]
        assert page["series"]["a"]["samples"] == [[2.0, 3.0]]
        assert page["cursor"] == cursor + 1

    def test_prefix_filter(self):
        store = timeline.TimelineStore(capacity=4)
        store._append("fetch.cdn_bps", 1.0, "rate", 1.0)
        store._append("ring.stalls", 0.0, "gauge", 1.0)
        assert list(store.payload(prefix="fetch.")["series"]) \
            == ["fetch.cdn_bps"]


# ── Rate derivation from registry counters ──


class TestRates:
    def test_rate_matches_hand_computed_counter_deltas(self):
        store = timeline.TimelineStore(capacity=32)
        c = telemetry.counter("zest_fetch_bytes_total", "", ("source",))
        c.inc(1000, source="cdn")
        store.tick(now=100.0, wall=100.0)  # baseline → 0.0 sample
        c.inc(4000, source="cdn")
        store.tick(now=102.0, wall=102.0)  # 4000 B / 2 s
        c.inc(1000, source="cdn")
        c.inc(600, source="peer")
        store.tick(now=103.0, wall=103.0)
        c.inc(300, source="peer")
        store.tick(now=104.0, wall=104.0)
        doc = store.payload()
        cdn = doc["series"]["fetch.cdn_bps"]["samples"]
        assert cdn == [[100.0, 0.0], [102.0, 2000.0], [103.0, 1000.0],
                       [104.0, 0.0]]
        # A labelset first seen mid-run credits its whole value over
        # the preceding tick interval (zero-anchored so integration
        # stays exact), then rates normally.
        peer = doc["series"]["fetch.peer_bps"]["samples"]
        assert peer == [[102.0, 0.0], [103.0, 600.0], [104.0, 300.0]]
        assert timeline.integrate(peer) == pytest.approx(900.0)
        assert cdn == sorted(cdn)  # monotonic timestamps

    def test_integration_reproduces_counter_total(self):
        store = timeline.TimelineStore(capacity=64)
        c = telemetry.counter("zest_files_bytes_total", "", ("lane",))
        t, total = 10.0, 0
        c.inc(0, lane="copy")  # materialize the labelset at zero
        store.tick(now=t, wall=t)
        for i, (dt, nbytes) in enumerate(
                [(1.0, 5000), (0.5, 0), (2.0, 12345), (1.0, 777)]):
            t += dt
            c.inc(nbytes, lane="copy")
            total += nbytes
            store.tick(now=t, wall=t)
        samples = store.payload()["series"]["files.copy_bps"]["samples"]
        assert timeline.integrate(samples) == pytest.approx(total)

    def test_unlabeled_source_sums_to_one_series(self):
        store = timeline.TimelineStore(capacity=8)
        c = telemetry.counter("zest_seed_bytes_total", "",
                              ("peer_state",))
        c.inc(100, peer_state="reciprocal")
        store.tick(now=1.0, wall=1.0)
        c.inc(100, peer_state="reciprocal")
        c.inc(300, peer_state="optimistic")
        store.tick(now=2.0, wall=2.0)
        samples = store.payload()["series"]["seed.bps"]["samples"]
        assert samples[-1] == [2.0, 400.0]


# ── Probes + cells ──


class TestProbes:
    def test_probe_sampled_each_tick_and_replace_semantics(self):
        store = timeline.TimelineStore(capacity=8)
        store._probes["g"] = lambda: 7
        store.tick(now=1.0, wall=1.0)
        store._probes["g"] = lambda: 9  # replacement wins
        store.tick(now=2.0, wall=2.0)
        assert store.payload()["series"]["g"]["samples"] \
            == [[1.0, 7.0], [2.0, 9.0]]

    def test_failing_or_none_probe_drops_sample(self):
        store = timeline.TimelineStore(capacity=8)

        def boom():
            raise RuntimeError("probe died")

        store._probes["bad"] = boom
        store._probes["idle"] = lambda: None
        store.tick(now=1.0, wall=1.0)
        assert store.payload()["series"] == {}

    def test_conditional_unregister_keeps_replacement(self):
        old, new = (lambda: 1), (lambda: 2)
        timeline.register_probe("ring.test", old)
        timeline.register_probe("ring.test", new)
        timeline.unregister_probe("ring.test", old)  # stale teardown
        assert timeline.STORE._probes["ring.test"] is new
        timeline.unregister_probe("ring.test", new)
        assert "ring.test" not in timeline.STORE._probes

    def test_host_ring_close_unregisters_its_probes(self):
        """Regression: bound methods mint a fresh object per attribute
        access, so close() must unregister with the SAME objects it
        registered — and an old ring's late close must not drop a
        newer ring's probes."""
        from zest_tpu.models.loader import HostRing

        ring = HostRing(1024, 4)
        assert "ring.in_use_bytes" in timeline.STORE._probes
        ring.close()
        assert "ring.in_use_bytes" not in timeline.STORE._probes
        assert "ring.stalls" not in timeline.STORE._probes
        r1 = HostRing(1024, 4)
        r2 = HostRing(2048, 4)
        r1.close()  # replaced before closing: must be a no-op
        assert timeline.STORE._probes["ring.in_use_bytes"] \
            is r2._probe_in_use
        r2.close()
        assert "ring.in_use_bytes" not in timeline.STORE._probes

    def test_posted_cells_recorded_until_cleared(self):
        store = timeline.TimelineStore(capacity=8)
        store._cells["collective.phase"] = 2.0
        store.tick(now=1.0, wall=1.0)
        store._cells.pop("collective.phase")
        store.tick(now=2.0, wall=2.0)
        samples = store.payload()["series"]["collective.phase"]["samples"]
        assert samples == [[1.0, 2.0]]


# ── Anomaly detection (synthetic ground truth) ──


def _session_with_fetch(total=10_000, phase="fetch"):
    sess = session_mod.begin("acme/anom", "main")
    f = _FakeFetch()
    sess._fetch = f
    sess.set_total_bytes(total)
    sess.phase = phase
    return sess, f


class TestAnomalies:
    def test_stall_fires_within_two_windows_with_session_attribution(
            self):
        store = timeline.TimelineStore(capacity=64, window_s=2.0)
        sess, f = _session_with_fetch()
        t = 0.0
        f.bytes_from_cdn = 2000
        store.tick(now=t, wall=t)
        # Progress for two ticks, then a dead stop.
        for delta in (1000, 1000):
            t += 1.0
            f.bytes_from_cdn += delta
            store.tick(now=t, wall=t)
        stall_start = t
        fired_at = None
        for _ in range(8):
            t += 1.0
            store.tick(now=t, wall=t)
            if store.payload()["anomalies"]:
                fired_at = t
                break
        assert fired_at is not None, "stall never fired"
        assert fired_at - stall_start <= 2 * store.window_s
        (ev,) = store.payload()["anomalies"]
        assert ev["kind"] == timeline.ANOMALY_STALL
        assert ev["session"] == sess.id
        # Metric + flight event + session annotation all fired.
        assert telemetry.REGISTRY.metrics()
        m = [m for m in telemetry.REGISTRY.metrics()
             if m.name == "zest_anomalies_total"][0]
        assert m.value(kind=timeline.ANOMALY_STALL) == 1
        recs = [e for e in telemetry.recorder.tail()
                if e["kind"] == "anomaly"]
        assert recs and recs[0]["anomaly"] == timeline.ANOMALY_STALL
        assert recs[0]["session"] == sess.id
        assert timeline.ANOMALY_STALL in sess.snapshot()["anomalies"]
        # One firing per episode: more stalled ticks add nothing.
        for _ in range(4):
            t += 1.0
            store.tick(now=t, wall=t)
        assert m.value(kind=timeline.ANOMALY_STALL) == 1
        session_mod.finish(sess, "ok")

    def test_stall_gated_on_byte_moving_phase(self):
        store = timeline.TimelineStore(capacity=64, window_s=1.0)
        sess, f = _session_with_fetch(phase="hbm_commit")
        f.bytes_from_cdn = 5000
        for i in range(6):
            store.tick(now=float(i), wall=float(i))
        assert store.payload()["anomalies"] == []
        session_mod.finish(sess, "ok")

    def test_stall_fires_during_direct_landing_with_open_fetch(self):
        """Regression: the display phase during a direct landing is
        hbm_commit (outranks fetch) while fetch workers still pull
        bytes inside it — the stall rule judges the OPEN stage
        multiset, so a mid-landing fetch stall still fires."""
        store = timeline.TimelineStore(capacity=64, window_s=1.0)
        sess, f = _session_with_fetch(phase="hbm_commit")
        sess._open = {"hbm_commit": 1, "fetch": 1}
        f.bytes_from_cdn = 5000
        for i in range(6):
            store.tick(now=float(i), wall=float(i))
        kinds = [e["kind"] for e in store.payload()["anomalies"]]
        assert kinds == [timeline.ANOMALY_STALL]
        session_mod.finish(sess, "ok")

    def test_throughput_collapse_vs_own_ewma(self):
        store = timeline.TimelineStore(capacity=128, window_s=2.0)
        sess, f = _session_with_fetch(total=100_000_000)
        t = 0.0
        store.tick(now=t, wall=t)
        # 10 healthy seconds at ~2 MB/s build the EWMA baseline...
        for _ in range(10):
            t += 1.0
            f.bytes_from_cdn += 2_000_000
            store.tick(now=t, wall=t)
        # ...then a trickle: nonzero (not a stall) but far below 25%.
        for _ in range(6):
            t += 1.0
            f.bytes_from_cdn += 10_000
            store.tick(now=t, wall=t)
        kinds = [e["kind"] for e in store.payload()["anomalies"]]
        assert kinds == [timeline.ANOMALY_COLLAPSE]
        (ev,) = store.payload()["anomalies"]
        assert ev["session"] == sess.id
        assert ev["rate_bps"] < ev["ewma_bps"] * timeline.COLLAPSE_FRACTION
        session_mod.finish(sess, "ok")

    def test_queue_growth_without_admission(self):
        store = timeline.TimelineStore(capacity=32, window_s=2.0)
        det = store.detector
        # Queue sits at 3 while admitted_total never moves → fires.
        for i in range(5):
            det.observe_queue(3, 10, float(i))
        assert [e["kind"] for e in store.payload()["anomalies"]] \
            == [timeline.ANOMALY_QUEUE]
        # An admission re-arms the episode; a fresh hold re-fires.
        det.observe_queue(3, 11, 6.0)
        for i in range(7, 12):
            det.observe_queue(3, 11, float(i))
        kinds = [e["kind"] for e in store.payload()["anomalies"]]
        assert kinds == [timeline.ANOMALY_QUEUE] * 2

    def test_queue_draining_never_fires(self):
        store = timeline.TimelineStore(capacity=32, window_s=1.0)
        det = store.detector
        for i, depth in enumerate([5, 4, 3, 2, 1, 0]):
            det.observe_queue(depth, 10 + i, float(i))
        assert store.payload()["anomalies"] == []

    def test_collective_straggler_per_phase(self):
        store = timeline.TimelineStore(capacity=32, window_s=1.0)
        det = store.detector
        cells = {"collective.phase": 0, "collective.barrier_s": 0.0,
                 "collective.partner": 3}
        det.observe_collective(cells, 0.0)
        cells["collective.barrier_s"] = 1.5  # waited past the window
        det.observe_collective(cells, 1.5)
        (ev,) = store.payload()["anomalies"]
        assert ev["kind"] == timeline.ANOMALY_STRAGGLER
        assert ev["phase"] == 0 and ev["partner"] == 3
        # Same phase: fired once. New phase: fresh baseline, no fire.
        cells["collective.barrier_s"] = 3.0
        det.observe_collective(cells, 3.0)
        cells["collective.phase"] = 1
        det.observe_collective(cells, 4.0)
        assert len(store.payload()["anomalies"]) == 1


# ── Knob-off identity ──


FILES = {
    "config.json": b'{"model_type": "test"}',
    "model.safetensors": bytes(range(256)) * 2048,  # 512 KiB
    "tokenizer.json": b'{"tok": 1}' * 20,
}


class TestKnobOff:
    def test_off_means_no_thread_no_samples_no_probes(self):
        timeline.set_enabled(False)
        assert timeline.ensure_started() is False
        assert timeline._sampler is None
        timeline.register_probe("x", lambda: 1)
        timeline.post("y", 2.0)
        assert timeline.STORE._probes == {}
        assert timeline.STORE._cells == {}
        doc = timeline.payload()
        assert doc == {"enabled": False, "series": {}, "anomalies": [],
                       "cursor": 0}
        assert timeline.status_block() == {"enabled": False}

    def test_telemetry_off_implies_timeline_off(self):
        telemetry.set_enabled(False)
        timeline.set_enabled(True)
        assert timeline.enabled() is False

    def test_knob_off_pull_byte_identical_with_empty_store(
            self, tmp_path, monkeypatch):
        repo = FixtureRepo("acme/tl-model", FILES, chunks_per_xorb=3)
        with FixtureHub(repo) as hub:
            on = pull_model(_cfg(hub, tmp_path / "on"), "acme/tl-model",
                            no_p2p=True, log=lambda *a, **k: None)
            assert timeline._sampler is not None  # pull started it
            telemetry.reset_all()
            tenancy.reset()
            monkeypatch.setenv(timeline.ENV_TIMELINE, "0")
            off = pull_model(_cfg(hub, tmp_path / "off"),
                             "acme/tl-model", no_p2p=True,
                             log=lambda *a, **k: None)
            # Hard-off: no sampler thread, empty store, and the pull's
            # stats schema identical — the timeline adds no keys either
            # way, which is exactly the point.
            assert timeline._sampler is None
            assert timeline.STORE.payload()["series"] == {}
            assert sorted(on.stats) == sorted(off.stats)
            for name in FILES:
                assert (on.snapshot_dir / name).read_bytes() \
                    == (off.snapshot_dir / name).read_bytes()


# ── Chaos: a stalled seeder fires the stall anomaly on a real pull ──


class TestChaosStall:
    def test_seeder_stall_pull_fires_stall_with_session(
            self, tmp_path, monkeypatch):
        from zest_tpu import faults, storage
        from zest_tpu.transfer.server import BtServer
        from zest_tpu.transfer.swarm import SwarmDownloader

        files = {"config.json": b'{"model_type": "stall"}',
                 "model.safetensors": bytes(range(256)) * 6000}
        repo = FixtureRepo("acme/stall-model", files, chunks_per_xorb=64)
        window_s = 0.4
        monkeypatch.setenv(timeline.ENV_WINDOW, str(window_s))
        monkeypatch.setenv(timeline.ENV_HZ, "20")
        timeline.reset()
        with FixtureHub(repo) as hub:
            seeder_cfg = _cfg(hub, tmp_path / "seeder")
            pull_model(seeder_cfg, "acme/stall-model", no_p2p=True,
                       log=lambda *a, **k: None)
            telemetry.reset_all()  # drop the seeder warm pull's session
            server = BtServer(seeder_cfg)
            port = server.start()
            # Every peer response sleeps well past 2× the window: the
            # pull's fetch phase makes zero byte progress meanwhile.
            faults.install("seeder_stall:1.0@2.0")
            try:
                leech = _cfg(hub, tmp_path / "leech")
                swarm = SwarmDownloader(leech)
                swarm.add_direct_peer("127.0.0.1", port)
                try:
                    res = pull_model(leech, "acme/stall-model",
                                     swarm=swarm,
                                     log=lambda *a, **k: None)
                finally:
                    swarm.close()
                assert faults.counters().get("seeder_stall", 0) >= 1
            finally:
                faults.install(None)
                server.shutdown()
            # The pull completed (the stall elapsed / CDN healed it)...
            for name, want in files.items():
                assert (res.snapshot_dir / name).read_bytes() == want
            # ...and the detector fired the stall DURING it, attributed
            # to the pull's session (flight event + metric + session
            # annotation — the acceptance triple).
            anomalies = timeline.STORE.payload()["anomalies"]
            stalls = [e for e in anomalies
                      if e["kind"] == timeline.ANOMALY_STALL]
            assert stalls, f"no stall anomaly; got {anomalies}"
            (recent,) = session_mod.payload()["recent"][:1]
            assert stalls[0]["session"] == recent["id"]
            assert stalls[0].get("stalled_s", 0) <= 2 * window_s + 0.5
            m = [m for m in telemetry.REGISTRY.metrics()
                 if m.name == "zest_anomalies_total"][0]
            assert m.value(kind=timeline.ANOMALY_STALL) >= 1
            recs = [e for e in telemetry.recorder.tail()
                    if e.get("kind") == "anomaly"
                    and e.get("anomaly") == timeline.ANOMALY_STALL]
            assert recs and recs[0]["session"] == recent["id"]
            sess = session_mod.get(recent["id"])
            assert timeline.ANOMALY_STALL \
                in sess.snapshot().get("anomalies", {})


# ── HTTP surface + pod merge ──


@pytest.fixture
def api(tmp_config, monkeypatch):
    from zest_tpu.api.http_api import HttpApi

    requests = pytest.importorskip("requests")
    # Slow the live sampler to one tick per 50 s: the endpoint tests
    # drive the store with injected clocks, which a concurrent
    # wall-clock tick would interleave with.
    monkeypatch.setenv(timeline.ENV_HZ, "0.02")
    timeline.reset()
    tmp_config.http_port = 0
    a = HttpApi(tmp_config)
    port = a.start()
    yield a, requests, f"http://127.0.0.1:{port}"
    a.close()


class TestHttp:
    def test_v1_timeline_cursor_paging(self, api):
        _a, requests, base = api
        c = telemetry.counter("zest_fetch_bytes_total", "", ("source",))
        c.inc(1000, source="cdn")
        timeline.STORE.tick(now=1.0, wall=1.0)
        c.inc(2000, source="cdn")
        timeline.STORE.tick(now=2.0, wall=2.0)
        doc = requests.get(f"{base}/v1/timeline", timeout=5).json()
        assert doc["enabled"] is True
        assert doc["series"]["fetch.cdn_bps"]["kind"] == "rate"
        assert len(doc["series"]["fetch.cdn_bps"]["samples"]) == 2
        cursor = doc["cursor"]
        page = requests.get(f"{base}/v1/timeline?since={cursor}",
                            timeout=5).json()
        assert page["series"] == {}
        c.inc(500, source="cdn")
        timeline.STORE.tick(now=3.0, wall=3.0)
        page = requests.get(f"{base}/v1/timeline?since={cursor}",
                            timeout=5).json()
        assert list(page["series"]) == ["fetch.cdn_bps"]
        assert len(page["series"]["fetch.cdn_bps"]["samples"]) == 1
        # Series prefix filter.
        filt = requests.get(f"{base}/v1/timeline?series=ring.",
                            timeout=5).json()
        assert filt["series"] == {}
        # /v1/status carries the store block when on.
        st = requests.get(f"{base}/v1/status", timeout=5).json()
        assert st["timeline"]["enabled"] is True
        assert st["timeline"]["cursor"] >= 3

    def test_merge_timelines_normalizes_clocks(self):
        doc0 = {
            "series": {"fetch.cdn_bps": {
                "kind": "rate", "samples": [[100.0, 5.0]]}},
            "anomalies": [{"t": 100.5, "kind": "stall"}],
            "clock_offsets": {"1": {"offset_s": 2.0, "rtt_s": 0.01}},
        }
        doc1 = {
            "series": {"fetch.cdn_bps": {
                "kind": "rate", "samples": [[102.0, 7.0]]}},
            "anomalies": [],
        }
        merged = timeline.merge_timelines({"0": doc0, "1": doc1})
        assert merged["reference"] == "0"
        assert merged["series"]["h0.fetch.cdn_bps"]["samples"] \
            == [[100.0, 5.0]]
        # Host 1's clock runs 2 s ahead → samples shift back by 2.
        assert merged["series"]["h1.fetch.cdn_bps"]["samples"] \
            == [[100.0, 7.0]]
        assert merged["clock_normalization"]["1"]["applied_offset_s"] \
            == 2.0
        assert merged["anomalies"][0]["host"] == "0"

    def test_merge_without_offsets_is_honest_null(self):
        merged = timeline.merge_timelines({
            "0": {"series": {}, "anomalies": []},
            "1": {"series": {"x": {"kind": "gauge",
                                   "samples": [[5.0, 1.0]]}},
                  "anomalies": []},
        })
        assert merged["clock_normalization"]["1"]["applied_offset_s"] \
            is None
        assert merged["series"]["h1.x"]["samples"] == [[5.0, 1.0]]


# ── zest top ──


class TestTop:
    def _payloads(self):
        status = {"version": "1.0"}
        pulls = {
            "active": [{"id": "p0001-ab", "repo": "a/b",
                        "phase": "fetch", "status": "running",
                        "progress": 0.5, "eta_s": 12.0,
                        "anomalies": {"stall": {"t": 1.0}}}],
            "recent": [],
            "tenancy": {"active": 1, "queued": 2, "max_pulls": 4,
                        "queue_cap": 16},
        }
        tl = {
            "enabled": True,
            "series": {
                "session.p0001-ab.bytes": {
                    "kind": "gauge",
                    "samples": [[1.0, 0.0], [2.0, 4_000_000.0]]},
                "fetch.cdn_bps": {"kind": "rate",
                                  "samples": [[2.0, 2_500_000.0]]},
                "fetch.peer_bps": {"kind": "rate",
                                   "samples": [[2.0, 1_500_000.0]]},
                "ring.in_use_bytes": {"kind": "gauge",
                                      "samples": [[2.0, 1024.0]]},
                "ring.stalls": {"kind": "gauge",
                                "samples": [[2.0, 3.0]]},
                "tenancy.queue_depth": {"kind": "gauge",
                                        "samples": [[2.0, 2.0]]},
                "tenancy.active_pulls": {"kind": "gauge",
                                         "samples": [[2.0, 1.0]]},
                "tenancy.inflight_fetches": {"kind": "gauge",
                                             "samples": [[2.0, 5.0]]},
            },
            "anomalies": [{"t": 1.5, "kind": "stall",
                           "session": "p0001-ab"}],
        }
        return status, pulls, tl

    def test_top_lines_render_frame(self):
        from zest_tpu.cli import _top_lines

        lines = _top_lines(*self._payloads())
        frame = "\n".join(lines)
        assert "active 1" in lines[0] and "queued 2" in lines[0]
        assert "p0001-ab" in frame and "a/b" in frame
        assert "[############------------]" in frame  # 50% of 24
        assert "50%" in frame and "eta 12.0s" in frame
        assert "4.0 MB/s" in frame      # live session byte rate
        assert "cdn=2.5 MB/s" in frame and "peer=1.5 MB/s" in frame
        assert "stalls=3" in frame
        assert "queue: depth=2  active=1  inflight_fetches=5" in frame
        assert "anomaly: stall  session=p0001-ab" in frame
        assert "!stall" in frame        # inline session annotation

    def test_top_lines_idle_and_disabled(self):
        from zest_tpu.cli import _top_lines

        lines = _top_lines({"version": "1.0"}, {}, {"enabled": False})
        frame = "\n".join(lines)
        assert "(no active pulls)" in frame
        assert "ZEST_TIMELINE=0" in frame

    def test_cmd_top(self, api, monkeypatch, capsys):
        from zest_tpu import cli

        _a, _requests, base = api
        monkeypatch.setenv("ZEST_HTTP_PORT", base.rsplit(":", 1)[1])
        sess = session_mod.begin("a/b", tenant="t")
        assert cli.main(["top", "--count", "1"]) == 0
        out = capsys.readouterr().out
        assert "zest top" in out and sess.id in out
        assert cli.main(["top", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["timeline"]["enabled"] is True
        session_mod.finish(sess, "ok")


# ── Tenancy metric satellites ──


class TestTenancySatellites:
    def test_singleflight_outcomes(self):
        flights = tenancy.Singleflight()
        mode, flight = flights.join("k")
        assert mode == "lead"
        results = []

        def wait():
            results.append(flights.wait(flight))

        threads = [threading.Thread(target=wait) for _ in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        flights.resolve(flight)
        for t in threads:
            t.join(timeout=5)
        assert results == ["done", "done"]
        assert flights.summary()["outcomes"] \
            == {"leader": 1, "waiter": 2, "handoff": 0}
        m = [m for m in telemetry.REGISTRY.metrics()
             if m.name == "zest_singleflight_total"][0]
        assert m.value(outcome="leader") == 1
        assert m.value(outcome="waiter") == 2

    def test_singleflight_handoff_outcome(self):
        flights = tenancy.Singleflight()
        _mode, flight = flights.join("k")
        results = []

        def wait():
            results.append(flights.wait(flight))

        t = threading.Thread(target=wait)
        t.start()
        time.sleep(0.05)
        flights.abdicate(flight)  # cancelled leader hands off
        t.join(timeout=5)
        assert results == ["lead"]
        assert flights.summary()["outcomes"]["handoff"] == 1

    def test_admission_wait_histogram_observed(self):
        ctrl = tenancy.AdmissionController(max_pulls=1, max_queue=4)
        ctrl.acquire("a")          # instant
        done = threading.Event()

        def queued():
            ctrl.acquire("b")      # parks until the release below
            done.set()

        t = threading.Thread(target=queued)
        t.start()
        time.sleep(0.15)
        ctrl.release()
        assert done.wait(5)
        h = [m for m in telemetry.REGISTRY.metrics()
             if m.name == "zest_admission_wait_seconds"][0]
        ((_labels, count),) = h.samples()
        assert count == 2          # fast path + queued path
        (_key, row) = h.rows()[0]
        assert row[-1] >= 0.1      # the queued session's wait is in sum

    def test_pinned_skip_flight_event(self, tmp_path):
        pins = tenancy.PinBook()
        cache = tmp_path / "xorbs"
        sub = cache / "aa"
        sub.mkdir(parents=True)
        pinned_hash = "aa" + "1" * 62
        loose_hash = "aa" + "2" * 62
        (sub / pinned_hash).write_bytes(b"x" * 1000)
        (sub / loose_hash).write_bytes(b"y" * 1000)
        pins.pin("tree:a", [pinned_hash])
        ev = tenancy.CacheEvictor(cache, high_bytes=500, low_bytes=100,
                                  pins=pins)
        freed = ev.maybe_evict(force=True)
        assert freed == 1000
        assert ev.pinned_survivals == 1
        events = {e["kind"] for e in telemetry.recorder.tail()}
        assert "cache_evict" in events
        skip = [e for e in telemetry.recorder.tail()
                if e["kind"] == "cache_evict_pinned_skip"]
        assert skip and skip[0]["entries"] == 1
        assert skip[0]["bytes"] == 1000

    def test_status_tenancy_block_gains_outcomes(self, api, tmp_config):
        _a, requests, base = api
        tn = requests.get(f"{base}/v1/status", timeout=5) \
            .json().get("tenancy")
        if tn is None:
            pytest.skip("tenancy off in this config")
        assert tn["dedupe"]["outcomes"] \
            == {"leader": 0, "waiter": 0, "handoff": 0}


# ── Critical-path prefix-table extension (hand-built DAG) ──


class TestCritpathExtension:
    def _iv(self, name, t0, t1, **attrs):
        return critpath._Iv(name, t0, t1, attrs)

    def test_queued_and_collective_attribution(self):
        """Hand-built DAG: 3 s parked in admission is a "queued" stage
        (not `other`, not idle), collective phase spans are fetch work
        split per link class, and barriers stay their own category."""
        spans = [
            self._iv("pull", 0.0, 10.0),
            self._iv("tenancy.queued", 0.0, 3.0, tenant="t"),
            self._iv("stage.fetch", 3.0, 4.0),
            self._iv("coop.collective.phase0", 4.0, 6.0, link="ici"),
            self._iv("coop.collective.phase1", 6.0, 9.0, link="dcn"),
            self._iv("coop.collective.barrier", 8.0, 9.0, phase=1),
            self._iv("hbm.commit", 9.0, 10.0),
        ]
        rep = critpath._analyze(spans)
        assert rep["stages"]["queued"] == pytest.approx(3.0)
        # Phases blame as fetch (minus the nested barrier's second).
        assert rep["stages"]["fetch"] == pytest.approx(1.0 + 2.0 + 2.0)
        assert rep["stages"]["barrier"] == pytest.approx(1.0)
        assert "exchange" not in rep["stages"]
        assert "other" not in rep["stages"]
        # Per-link tier split: the collective's wire seconds land under
        # ici/dcn next to the waterfall tiers.
        assert rep["tiers"]["ici"] == pytest.approx(2.0)
        assert rep["tiers"]["dcn"] == pytest.approx(2.0)
        assert rep["path_s"] == pytest.approx(10.0)

    def test_categorize_rules(self):
        assert critpath.categorize("tenancy.queued") == "queued"
        assert critpath.categorize("coop.collective.phase2") == "fetch"
        assert critpath.categorize("coop.collective.barrier") \
            == "barrier"
        assert critpath.categorize("coop.exchange") == "exchange"
        assert critpath._tier_of("coop.collective.phase2",
                                 {"link": "ici"}) == "ici"
        assert critpath._tier_of("coop.collective.phase2", {}) == "dcn"

    def test_real_queued_pull_blames_queued_stage(self, tmp_path):
        """A traced pull that parks in the admission queue carries a
        `queued` stage in stats["critical_path"]."""
        from zest_tpu.telemetry import trace as trace_mod

        repo = FixtureRepo("acme/q-model", FILES, chunks_per_xorb=3)
        with FixtureHub(repo) as hub:
            cfg = _cfg(hub, tmp_path, tenant_max_pulls=1)
            tracer = trace_mod.install(None)
            st = tenancy.state(cfg)
            st.controller.acquire("hog")   # hold the only slot
            release = threading.Timer(
                0.4, lambda: st.controller.release())
            release.start()
            try:
                res = pull_model(cfg, "acme/q-model", no_p2p=True,
                                 log=lambda *a, **k: None)
            finally:
                release.cancel()
            assert len(tracer) > 0
            cp = res.stats.get("critical_path")
            assert cp is not None
            assert cp["stages"].get("queued", 0) >= 0.3
