"""Loopback peer-to-peer integration tests.

The reference's 2-node Docker harness (p2p-docker-test.sh) in-process: a
seeder (BtServer over a warm cache) and a leecher (pull with a direct
peer), asserting bytes actually came from the peer and not the CDN. This
is deeper than the reference's unit tier, which had no loopback peer test
(SURVEY.md §4 limitation).
"""

import os

import pytest

from zest_tpu import storage
from zest_tpu.cas import hashing
from zest_tpu.config import Config
from zest_tpu.p2p import peer_id as peer_id_mod
from zest_tpu.p2p.peer import BtPeer, ChunkNotFoundError
from zest_tpu.transfer.pull import pull_model
from zest_tpu.transfer.server import BtServer
from zest_tpu.transfer.swarm import SwarmDownloader

from fixtures import FixtureHub, FixtureRepo

FILES = {
    "config.json": b'{"model_type": "loopback"}',
    "model.safetensors": os.urandom(500_000),
}


@pytest.fixture(scope="module")
def hub():
    repo = FixtureRepo("acme/p2p-model", FILES, chunks_per_xorb=2)
    with FixtureHub(repo) as h:
        yield h


def _cfg(hub, root, listen_port=0):
    return Config(
        hf_home=root / "hf",
        cache_dir=root / "zest",
        hf_token="hf_test",
        endpoint=hub.url,
        listen_port=listen_port,
    )


@pytest.fixture
def seeder(hub, tmp_path):
    """A host that pulled via CDN and now serves its cache."""
    cfg = _cfg(hub, tmp_path / "seeder")
    pull_model(cfg, "acme/p2p-model", no_p2p=True)
    server = BtServer(cfg)
    port = server.start()
    yield cfg, port
    server.shutdown()


class TestRawPeerProtocol:
    def test_handshake_and_chunk_fetch(self, hub, seeder, tmp_path):
        seeder_cfg, port = seeder
        cached = storage.list_cached_xorbs(seeder_cfg)
        assert cached
        xorb_hash = hashing.hex_to_hash(cached[0])
        info_hash = peer_id_mod.compute_info_hash(xorb_hash)

        peer = BtPeer.connect(
            "127.0.0.1", port, info_hash, peer_id_mod.generate()
        )
        try:
            blob = storage.XorbCache(seeder_cfg).get(cached[0])
            from zest_tpu.cas.xorb import XorbReader

            n = len(XorbReader(blob))
            result = peer.request_chunk(xorb_hash, 0, n)
            assert result.chunk_offset == 0
            assert result.data == blob
        finally:
            peer.close()

    def test_range_request_gets_sliced_frames(self, hub, seeder):
        seeder_cfg, port = seeder
        from zest_tpu.cas.xorb import XorbReader

        cached = storage.list_cached_xorbs(seeder_cfg)
        key = next(
            k for k in cached
            if len(XorbReader(storage.XorbCache(seeder_cfg).get(k))) >= 2
        )
        xorb_hash = hashing.hex_to_hash(key)
        peer = BtPeer.connect(
            "127.0.0.1", port,
            peer_id_mod.compute_info_hash(xorb_hash), peer_id_mod.generate(),
        )
        try:
            result = peer.request_chunk(xorb_hash, 1, 2)
            assert result.chunk_offset == 1
            reader = XorbReader(result.data)
            assert len(reader) == 1
            full = XorbReader(storage.XorbCache(seeder_cfg).get(key))
            assert reader.extract_chunk(0) == full.extract_chunk(1)
        finally:
            peer.close()

    def test_unknown_chunk_not_found(self, hub, seeder):
        _, port = seeder
        missing = os.urandom(32)
        peer = BtPeer.connect(
            "127.0.0.1", port,
            peer_id_mod.compute_info_hash(missing), peer_id_mod.generate(),
        )
        try:
            with pytest.raises(ChunkNotFoundError):
                peer.request_chunk(missing, 0, 1)
        finally:
            peer.close()

    def test_chunk_cache_tier_served_as_frame_stream(self, hub, seeder):
        """Tier-1 (chunk cache) responses must be parseable frame streams,
        same shape as every other waterfall tier."""
        from zest_tpu.cas.xorb import XorbReader

        seeder_cfg, port = seeder
        chunk = os.urandom(4000)
        h = hashing.chunk_hash(chunk)
        storage.write_chunk(seeder_cfg, h, chunk)
        peer = BtPeer.connect(
            "127.0.0.1", port,
            peer_id_mod.compute_info_hash(h), peer_id_mod.generate(),
        )
        try:
            result = peer.request_chunk(h, 0, 1)
            reader = XorbReader(result.data)
            assert len(reader) == 1
            assert reader.extract_chunk(0) == chunk
        finally:
            peer.close()

    def test_pipelined_requests(self, hub, seeder):
        seeder_cfg, port = seeder
        from zest_tpu.cas.xorb import XorbReader

        cached = storage.list_cached_xorbs(seeder_cfg)
        xorb_hash = hashing.hex_to_hash(cached[0])
        blob = storage.XorbCache(seeder_cfg).get(cached[0])
        n = len(XorbReader(blob))
        peer = BtPeer.connect(
            "127.0.0.1", port,
            peer_id_mod.compute_info_hash(xorb_hash), peer_id_mod.generate(),
        )
        try:
            results = peer.request_chunks_pipelined(
                [(xorb_hash, 0, n), (os.urandom(32), 0, 1), (xorb_hash, 0, n)]
            )
            assert results[0].data == blob
            assert isinstance(results[1], ChunkNotFoundError)
            assert results[2].data == blob
        finally:
            peer.close()


class TestLeecherPull:
    def test_pull_via_peer_not_cdn(self, hub, seeder, tmp_path):
        """The docker-test pass criterion: >0 xorbs from peers; ideal 100%
        P2P (reference: p2p-docker-test.sh:204-218). We assert the ideal:
        all xorb bytes from the peer, zero CDN xorb fetches."""
        _, seeder_port = seeder
        leecher_cfg = _cfg(hub, tmp_path / "leecher")
        swarm = SwarmDownloader(leecher_cfg)
        swarm.add_direct_peer("127.0.0.1", seeder_port)
        try:
            result = pull_model(leecher_cfg, "acme/p2p-model", swarm=swarm)
        finally:
            swarm.close()

        snap = result.snapshot_dir
        for name, data in FILES.items():
            assert (snap / name).read_bytes() == data, f"{name} corrupt"

        fetch = result.stats["fetch"]
        assert fetch["bytes"]["peer"] > 0, "no bytes from peers"
        assert fetch["xorbs"]["cdn"] == 0, (
            f"leecher hit CDN despite warm seeder: {fetch}"
        )
        assert result.stats["swarm"]["chunks_from_peers"] > 0

    def test_leecher_becomes_seeder(self, hub, seeder, tmp_path):
        """Seed-while-downloading: after a P2P pull, the leecher's cache
        must serve a second leecher (swarm.zig:426-429 semantics)."""
        _, seeder_port = seeder
        l1_cfg = _cfg(hub, tmp_path / "l1")
        swarm1 = SwarmDownloader(l1_cfg)
        swarm1.add_direct_peer("127.0.0.1", seeder_port)
        pull_model(l1_cfg, "acme/p2p-model", swarm=swarm1)
        swarm1.close()

        l1_server = BtServer(l1_cfg)
        l1_port = l1_server.start()
        try:
            l2_cfg = _cfg(hub, tmp_path / "l2")
            swarm2 = SwarmDownloader(l2_cfg)
            swarm2.add_direct_peer("127.0.0.1", l1_port)
            result = pull_model(l2_cfg, "acme/p2p-model", swarm=swarm2)
            swarm2.close()
            assert result.stats["fetch"]["xorbs"]["cdn"] == 0
            assert (result.snapshot_dir / "model.safetensors").read_bytes() \
                == FILES["model.safetensors"]
        finally:
            l1_server.shutdown()

    def test_dead_peer_falls_back_to_cdn(self, hub, tmp_path):
        """Waterfall resilience: unreachable peer must not break the pull
        (never-slower-than-CDN guarantee, BASELINE.md scenario 1)."""
        cfg = _cfg(hub, tmp_path / "orphan")
        swarm = SwarmDownloader(cfg)
        swarm.add_direct_peer("127.0.0.1", 1)  # nothing listens there
        try:
            result = pull_model(cfg, "acme/p2p-model", swarm=swarm)
        finally:
            swarm.close()
        assert (result.snapshot_dir / "model.safetensors").read_bytes() == \
            FILES["model.safetensors"]
        assert result.stats["fetch"]["bytes"]["cdn"] > 0
        assert result.stats["swarm"]["peer_failures"] > 0
