"""Tests for the sparse-MoE flagship and expert-sharded distribution
(BASELINE config #4: Mixtral-8x7B expert-sharded).

Model tests verify routing/capacity semantics directly; plan tests build a
real safetensors file with Mixtral-named tensors, content-address it with
the fixture encoder, and assert every chunk lands on the host whose expert
shard consumes it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tests.fixtures import FixtureRepo
from zest_tpu.models import moe
from zest_tpu.models.safetensors_io import parse_header_prefix, write_safetensors
from zest_tpu.parallel.expert import (
    ExpertPlacement,
    ExpertRoutedPlan,
    classify_file,
)
from zest_tpu.parallel.mesh import model_mesh
from zest_tpu.parallel.plan import DistributionPlan


# ── model: routing + capacity semantics ──


def test_forward_shapes_and_aux_loss():
    cfg = moe.MoEConfig.tiny()
    params = moe.init_params(jax.random.key(0), cfg)
    ids = jnp.zeros((2, 16), jnp.int32)
    logits, aux = jax.jit(lambda p, i: moe.forward(p, i, cfg))(params, ids)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()
    assert float(aux) > 0  # load-balance loss is X·Σ f·p ≥ 1 at balance


def _moe_params(cfg, rng_seed=1):
    full = moe.init_params(jax.random.key(rng_seed), cfg)
    # one layer's slice of the stacked moe leaves
    return jax.tree.map(lambda a: a[0], full["blocks"]["moe"])


def test_router_sends_tokens_to_forced_expert():
    cfg = moe.MoEConfig.tiny(n_experts=4, top_k=1, capacity_factor=4.0)
    p = _moe_params(cfg)
    # Router hard-prefers expert 2 for every token.
    router = np.zeros((cfg.n_embd, cfg.n_experts), np.float32)
    router[:, 2] = 1.0
    p["router_w"] = jnp.asarray(router)
    # positive activations so the forced column's logit Σx is the max
    x = jax.random.uniform(
        jax.random.key(3), (1, 8, cfg.n_embd), minval=0.1, maxval=1.0
    )
    out, _ = moe._moe_block(x, p, cfg)
    # Expected: every token through expert 2's SwiGLU with gate weight 1.
    flat = x.reshape(-1, cfg.n_embd)
    h = jax.nn.silu(flat @ p["w1"][2]) * (flat @ p["w3"][2])
    want = (h @ p["w2"][2]).reshape(x.shape)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)


def test_capacity_overflow_drops_to_residual():
    # capacity_factor tiny → C = top_k = 1 slot per expert; with all 8
    # tokens forced onto one expert, 7 must contribute nothing.
    cfg = moe.MoEConfig.tiny(n_experts=4, top_k=1, capacity_factor=0.01)
    p = _moe_params(cfg)
    router = np.zeros((cfg.n_embd, cfg.n_experts), np.float32)
    router[:, 1] = 1.0
    p["router_w"] = jnp.asarray(router)
    x = jax.random.uniform(
        jax.random.key(4), (1, 8, cfg.n_embd), minval=0.1, maxval=1.0
    )
    out, _ = moe._moe_block(x, p, cfg)
    rows = np.abs(np.asarray(out)).sum(-1)[0]
    assert (rows > 0).sum() == 1  # only the token that won the slot


def test_gqa_and_generate_shapes():
    cfg = moe.MoEConfig.tiny(n_head=4, n_kv_head=2)
    params = moe.init_params(jax.random.key(0), cfg)
    logits, _ = moe.forward(params, jnp.zeros((1, 8), jnp.int32), cfg)
    assert logits.shape == (1, 8, cfg.vocab_size)


# ── model: HF checkpoint mapping ──


def _hf_mixtral_tensors(cfg: moe.MoEConfig) -> dict[str, np.ndarray]:
    rng = np.random.default_rng(0)
    E, F, X = cfg.n_embd, cfg.d_ff, cfg.n_experts
    kvE = cfg.n_kv_head * cfg.head_dim
    t = {
        "model.embed_tokens.weight": rng.normal(
            size=(cfg.vocab_size, E)).astype(np.float32),
        "model.norm.weight": np.ones(E, np.float32),
        "lm_head.weight": rng.normal(
            size=(cfg.vocab_size, E)).astype(np.float32),
    }
    for l in range(cfg.n_layer):
        pre = f"model.layers.{l}."
        t[f"{pre}input_layernorm.weight"] = np.ones(E, np.float32)
        t[f"{pre}post_attention_layernorm.weight"] = np.ones(E, np.float32)
        t[f"{pre}self_attn.q_proj.weight"] = rng.normal(
            size=(E, E)).astype(np.float32)
        t[f"{pre}self_attn.k_proj.weight"] = rng.normal(
            size=(kvE, E)).astype(np.float32)
        t[f"{pre}self_attn.v_proj.weight"] = rng.normal(
            size=(kvE, E)).astype(np.float32)
        t[f"{pre}self_attn.o_proj.weight"] = rng.normal(
            size=(E, E)).astype(np.float32)
        t[f"{pre}block_sparse_moe.gate.weight"] = rng.normal(
            size=(X, E)).astype(np.float32)
        for x in range(X):
            t[f"{pre}block_sparse_moe.experts.{x}.w1.weight"] = rng.normal(
                size=(F, E)).astype(np.float32)
            t[f"{pre}block_sparse_moe.experts.{x}.w3.weight"] = rng.normal(
                size=(F, E)).astype(np.float32)
            t[f"{pre}block_sparse_moe.experts.{x}.w2.weight"] = rng.normal(
                size=(E, F)).astype(np.float32)
    return t


def test_params_from_hf_shapes_and_transpose():
    cfg = moe.MoEConfig.tiny(n_layer=2, n_experts=4)
    hf = _hf_mixtral_tensors(cfg)
    params = moe.params_from_hf(hf, cfg)
    w1 = params["blocks"]["moe"]["w1"]
    assert w1.shape == (2, 4, cfg.n_embd, cfg.d_ff)
    np.testing.assert_allclose(
        np.asarray(w1[1, 3]),
        hf["model.layers.1.block_sparse_moe.experts.3.w1.weight"].T,
    )
    logits, _ = moe.forward(params, jnp.zeros((1, 4), jnp.int32), cfg)
    assert np.isfinite(np.asarray(logits)).all()


def test_params_from_hf_missing_tensor_raises():
    cfg = moe.MoEConfig.tiny(n_layer=1, n_experts=2)
    hf = _hf_mixtral_tensors(cfg)
    del hf["model.layers.0.block_sparse_moe.experts.1.w2.weight"]
    with pytest.raises(ValueError, match="experts.1.w2"):
        moe.params_from_hf(hf, cfg)


def test_expert_of_tensor():
    assert moe.expert_of_tensor(
        "model.layers.3.block_sparse_moe.experts.5.w1.weight") == 5
    assert moe.expert_of_tensor(
        "model.layers.3.self_attn.q_proj.weight") is None
    assert moe.expert_of_tensor("model.embed_tokens.weight") is None


# ── model: expert-parallel train step on the virtual mesh ──


def test_train_step_on_data_expert_mesh():
    cfg = moe.MoEConfig.tiny(n_experts=8, top_k=2)
    mesh = model_mesh({"data": 2, "expert": 4})
    params = moe.init_params(jax.random.key(0), cfg)
    specs = moe.param_specs(cfg)
    params = jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda v: isinstance(v, P),
    )
    batch = jax.device_put(
        jnp.zeros((4, 17), jnp.int32), NamedSharding(mesh, P("data"))
    )
    step = jax.jit(lambda p, b: moe.train_step(p, b, cfg))
    with mesh:
        new_params, loss = step(params, batch)
    assert np.isfinite(float(loss))
    # params actually moved (gradient applied)
    delta = np.abs(
        np.asarray(new_params["blocks"]["moe"]["w2"])
        - np.asarray(params["blocks"]["moe"]["w2"])
    ).max()
    assert delta > 0


# ── placement ──


def test_placement_contiguous_blocks_match_gspmd_slicing():
    pl = ExpertPlacement(n_experts=8, num_hosts=4)
    assert [pl.host_of_expert(x) for x in range(8)] == [
        0, 0, 1, 1, 2, 2, 3, 3
    ]
    assert pl.experts_of_host(2) == [4, 5]
    # every expert assigned exactly once across hosts
    seen = [x for h in range(4) for x in pl.experts_of_host(h)]
    assert sorted(seen) == list(range(8))


def test_placement_more_hosts_than_experts():
    pl = ExpertPlacement(n_experts=2, num_hosts=8)
    assert pl.host_of_expert(0) == 0
    assert pl.host_of_expert(1) == 4
    with pytest.raises(ValueError):
        pl.host_of_expert(2)


# ── expert-routed plan over a real content-addressed checkpoint ──


def _moe_checkpoint(tmp_path, cfg):
    path = tmp_path / "model.safetensors"
    write_safetensors(path, _hf_mixtral_tensors(cfg))
    return path.read_bytes()


def _routed_plan(tmp_path, num_hosts=4, chunks_per_xorb=2):
    # Expert tensors (64×512 f32 = 128 KB) are larger than the 64 KB CDC
    # target chunk, like real Mixtral weights — so most chunks fall wholly
    # inside one expert's tensor and can be privately routed.
    cfg = moe.MoEConfig.tiny(n_layer=1, n_experts=4, n_embd=64, d_ff=512,
                             vocab_size=64)
    data = _moe_checkpoint(tmp_path, cfg)
    repo = FixtureRepo("acme/moe", {"model.safetensors": data},
                       chunks_per_xorb=chunks_per_xorb)
    rec = repo.reconstructions[repo.files["model.safetensors"].xet_hash]
    header = parse_header_prefix(data[: 1 << 20])
    placement = ExpertPlacement(cfg.n_experts, num_hosts)
    fm = classify_file(rec, header, moe.expert_of_tensor)
    return cfg, rec, placement, ExpertRoutedPlan.build([fm], placement)


def test_routed_plan_partitions_all_units(tmp_path):
    _cfg, rec, placement, routed = _routed_plan(tmp_path)
    baseline = DistributionPlan.build([rec], placement.num_hosts)
    base_keys = {
        (a.hash_hex, a.fetch_info.range.start) for a in baseline.assignments
    }
    shared_keys = {
        (a.hash_hex, a.fetch_info.range.start)
        for a in routed.shared.assignments
    }
    expert_keys = {
        (a.hash_hex, a.fetch_info.range.start)
        for units in routed.expert_units.values() for a in units
    }
    assert shared_keys | expert_keys == base_keys
    assert not (shared_keys & expert_keys)
    assert routed.expert_units, "expert tensors must yield private units"


def test_routed_plan_expert_units_on_consuming_host(tmp_path):
    """Every expert-only unit is owned by a host whose expert's tensor
    bytes the unit carries."""
    _cfg, rec, placement, routed = _routed_plan(tmp_path)
    for host, units in routed.expert_units.items():
        owned_experts = set(placement.experts_of_host(host))
        assert owned_experts, f"host {host} owns units but no experts"
        for a in units:
            assert a.owner == host


def test_units_for_host_cover_everything_once(tmp_path):
    _cfg, rec, placement, routed = _routed_plan(tmp_path)
    seen = []
    for h in range(placement.num_hosts):
        seen += [
            (a.hash_hex, a.fetch_info.range.start)
            for a in routed.units_for_host(h)
        ]
    assert len(seen) == len(set(seen))
    baseline = DistributionPlan.build([rec], placement.num_hosts)
    assert len(seen) == len(baseline.assignments)


def test_routed_plan_saves_ici_bytes(tmp_path):
    _cfg, _rec, _placement, routed = _routed_plan(tmp_path)
    s = routed.summary()
    assert s["expert_bytes"] > 0
    assert s["ici_bytes_saved"] == s["expert_bytes"] * 3
    # most checkpoint bytes are expert weights in an MoE: the private
    # share should dominate the shared share for this checkpoint
    assert s["expert_bytes"] > s["shared"]["total_bytes"]


def test_single_host_routed_plan_degenerates(tmp_path):
    """num_hosts=1: everything (shared + expert) lands on host 0."""
    _cfg, rec, placement, routed = _routed_plan(tmp_path, num_hosts=1)
    baseline = DistributionPlan.build([rec], 1)
    assert len(routed.units_for_host(0)) == len(baseline.assignments)
