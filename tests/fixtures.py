"""Local HF Hub + CAS + CDN fixture server.

The environment has zero network egress, so every integration test runs
against this loopback server, which speaks the exact API shapes the real
Hub/CAS do (see zest_tpu/cas/hub.py docstring). It plays the role the real
network plays in the reference's shell harnesses (SURVEY.md §4):
`verify-model.sh` equivalent tests pull from here instead of huggingface.co.

``FixtureRepo`` content-addresses a dict of files exactly the way the
framework itself does (CDC chunking -> xorbs -> merkle file hashes), so the
client-side pipeline is verified against an independent server-side
encoding path.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from zest_tpu.cas import hashing, reconstruction as recon
from zest_tpu.cas.publish import XET_SUFFIXES as _XET_SUFFIXES, Publisher


@dataclass
class _XorbFixture:
    hash_hex: str
    blob: bytes               # frame stream (the in-pipeline blob shape)
    frame_offsets: list[int]  # len = num_chunks + 1
    full: bytes = b""         # frames + XETBLOB footer (the S3 artifact)


@dataclass
class _FileFixture:
    path: str
    data: bytes
    xet_hash: str | None = None           # LE-u64 hex of file hash
    terms: list[recon.Term] = field(default_factory=list)


# File extensions stored in Xet CAS (everything else is a "regular" file
# served via /resolve/) — the production list, re-exported for older
# call sites; the CDC-dedup encode itself now lives in
# zest_tpu.cas.publish (ISSUE 19 promoted it out of this fixture, the
# same way _TokenBucket moved to zest_tpu.shaping).


class FixtureRepo:
    """Content-addressed fixture repository.

    ``chunks_per_xorb`` forces files to split across several xorbs so tests
    exercise multi-term reconstruction and cross-xorb fetch planning.

    :meth:`add_revision` adds a second (third, ...) revision whose files
    chunk-dedup against every xorb the repo already holds — the real
    Xet upload semantics: unchanged chunks are *referenced* (terms
    pointing at existing xorbs' chunk ranges), only new chunks enter
    new xorbs. That is what makes revision-to-revision deltas
    structurally cheap at the CAS layer, and what the delta-pull tests
    measure against.
    """

    def __init__(
        self,
        repo_id: str,
        files: dict[str, bytes],
        commit_sha: str = "f1x7ure5ha" + "0" * 30,
        chunks_per_xorb: int = 0,  # 0 = unlimited (one xorb per file)
    ):
        self.repo_id = repo_id
        self.commit_sha = commit_sha
        self.chunks_per_xorb = chunks_per_xorb
        self.files: dict[str, _FileFixture] = {}
        self.xorbs: dict[str, _XorbFixture] = {}
        self.reconstructions: dict[str, recon.Reconstruction] = {}
        # The production CDC-dedup encoder (zest_tpu.cas.publish): owns
        # the chunk index add_revision dedups against — tests and `zest
        # push` share one implementation.
        self._publisher = Publisher(chunks_per_xorb=chunks_per_xorb)
        for path, data in files.items():
            if path.endswith(_XET_SUFFIXES):
                # dedup=False: the base revision packs every chunk into
                # its own xorbs exactly as it always did (fixture
                # geometry is pinned by existing tests); only LATER
                # revisions reference across.
                self._add_xet_file(path, data, chunks_per_xorb,
                                   self.files, dedup=False)
            else:
                self.files[path] = _FileFixture(path, data)
        # Revision order matters: "main" (and any unknown ref) resolves
        # to the LATEST revision, like the real hub.
        self.revisions: dict[str, dict[str, _FileFixture]] = {
            commit_sha: self.files}
        self._rev_order: list[str] = [commit_sha]

    @property
    def latest_sha(self) -> str:
        return self._rev_order[-1]

    def files_for(self, revision: str | None) -> dict[str, _FileFixture]:
        """The file set a revision spec resolves to: an exact sha wins,
        anything else ("main", None, a branch name) is the latest."""
        if revision in self.revisions:
            return self.revisions[revision]
        return self.revisions[self.latest_sha]

    def sha_for(self, revision: str | None) -> str:
        return revision if revision in self.revisions else self.latest_sha

    def add_revision(self, files: dict[str, bytes],
                     commit_sha: str | None = None) -> str:
        """Commit a new revision, chunk-deduped against the existing
        xorb set; returns its sha. ``self.files`` moves to the new
        revision (it is now what "main" resolves to)."""
        if commit_sha is None:
            commit_sha = hashing.blake3_hash(
                (self.latest_sha + str(len(self._rev_order))).encode()
            ).hex()[:40]
        fileset: dict[str, _FileFixture] = {}
        for path, data in files.items():
            if path.endswith(_XET_SUFFIXES):
                self._add_xet_file(path, data, self.chunks_per_xorb,
                                   fileset, dedup=True)
            else:
                fileset[path] = _FileFixture(path, data)
        self.revisions[commit_sha] = fileset
        self._rev_order.append(commit_sha)
        self.files = fileset
        return commit_sha

    def _add_xet_file(self, path: str, data: bytes,
                      chunks_per_xorb: int, fileset: dict,
                      dedup: bool = False) -> None:
        pf = self._publisher.publish_file(path, data, dedup=dedup,
                                          chunks_per_xorb=chunks_per_xorb)
        for px in self._publisher.drain_new_xorbs():
            self.xorbs[px.hash_hex] = _XorbFixture(
                px.hash_hex, px.blob, px.frame_offsets, px.full)
        fileset[path] = _FileFixture(path, data, pf.xet_hash, pf.terms)
        self.reconstructions[pf.xet_hash] = pf.reconstruction


# The hub's CDN shaper, promoted to production code (zest_tpu.shaping)
# so the seeding server's upload policy, bench_scale, and the chaos
# bench share one implementation; kept as a thin re-export for older
# call sites.
from zest_tpu.shaping import TokenBucket as _TokenBucket  # noqa: E402


class FixtureHub:
    """Threaded loopback server for one or more FixtureRepos.

    ``throttle_bps`` shapes the CDN data plane (``/xorbs/`` blob and
    ``/resolve/`` file bodies) through one shared :class:`_TokenBucket`
    — the link-shaping knob the multihost harness and the cooperative
    bench use to measure P2P against a WAN-rate origin while peers stay
    at loopback speed. Metadata (API JSON, reconstructions) stays
    unshaped: CDN control planes are never the bottleneck being
    modeled."""

    def __init__(self, *repos: FixtureRepo, throttle_bps: int | None = None):
        self.repos = {r.repo_id: r for r in repos}
        self.requests_seen: list[str] = []
        # (path, Range header) per /xorbs/ data-plane fetch: the
        # duplicate-fetch evidence at UNIT granularity — two requests
        # for different chunk ranges of one xorb are distinct fetch
        # units, not duplicates (the tenancy dedupe gate counts these).
        self.xorb_fetches: list[tuple[str, str]] = []
        self.throttle = _TokenBucket(throttle_bps) if throttle_bps else None
        fixture = self

        class Handler(BaseHTTPRequestHandler):
            # Keep-alive like a real CDN: HTTP/1.0 (the default) forces a
            # fresh TCP connection per ranged xorb fetch, which dominates
            # loopback pull timings and under-measures the client's
            # session reuse. Every _send sets Content-Length, so 1.1
            # framing is already correct. The timeout bounds how long an
            # idle keep-alive connection pins its handler thread after
            # the hub shuts down (threads are daemonic either way) — but
            # it is a SOCKET timeout, so it also fires mid-transfer when
            # a blocked send stalls: with 16 concurrent ~32 MB unit
            # fetches on one contended core, 5 s truncated over half the
            # responses (observed at the GB-scale bench). 120 s keeps the
            # idle-reap property without strangling large transfers.
            protocol_version = "HTTP/1.1"
            timeout = 120

            def log_message(self, *args):  # quiet
                pass

            def _send(self, code: int, body: bytes,
                      content_type: str = "application/octet-stream"):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, doc, code: int = 200):
                self._send(code, json.dumps(doc).encode(), "application/json")

            def do_GET(self):
                fixture.requests_seen.append(f"GET {self.path}")
                if self.path.startswith("/xorbs/"):
                    fixture.xorb_fetches.append(
                        (self.path, self.headers.get("Range") or ""))
                fixture._handle_get(self)

            def do_POST(self):
                fixture.requests_seen.append(f"POST {self.path}")
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n) if n else b""
                fixture._handle_post(self, body)

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )

    # ── lifecycle ──

    def __enter__(self) -> "FixtureHub":
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._server.shutdown()
        self._server.server_close()

    @property
    def url(self) -> str:
        host, port = self._server.server_address
        return f"http://{host}:{port}"

    # ── request handling ──

    def _repo_for(self, handler, parts):
        repo_id = "/".join(parts[:2])
        repo = self.repos.get(repo_id)
        if repo is None:
            handler._send_json({"error": "RepoNotFound"}, 404)
        return repo

    def _handle_get(self, handler) -> None:
        path = handler.path
        if path.startswith("/api/models/"):
            rest = path[len("/api/models/"):].split("/")
            repo = self._repo_for(handler, rest)
            if repo is None:
                return
            action = rest[2] if len(rest) > 2 else ""
            if action == "revision":
                rev = rest[3] if len(rest) > 3 else None
                handler._send_json({
                    "sha": repo.sha_for(rev),
                    "siblings": [
                        {"rfilename": p}
                        for p in sorted(repo.files_for(rev))
                    ],
                })
            elif action == "xet-read-token":
                handler._send_json({
                    "casUrl": self.url,
                    "accessToken": "fixture-access-token",
                    "exp": 4102444800,
                })
            else:
                handler._send_json({"error": "unknown api"}, 404)
            return

        if path.startswith("/v1/reconstructions/"):
            if handler.headers.get("Authorization") != "Bearer fixture-access-token":
                handler._send_json({"error": "unauthorized"}, 401)
                return
            file_hex = path.rsplit("/", 1)[-1]
            for repo in self.repos.values():
                rec = repo.reconstructions.get(file_hex)
                if rec is not None:
                    doc = self._reconstruction_doc(
                        rec, handler.headers.get("Range")
                    )
                    if doc is None:  # range starts past EOF
                        handler._send_json({"error": "range"}, 416)
                        return
                    handler._send_json(doc)
                    return
            handler._send_json({"error": "not found"}, 404)
            return

        if path.startswith("/xorbs/"):
            xh_hex = path.rsplit("/", 1)[-1]
            for repo in self.repos.values():
                xf = repo.xorbs.get(xh_hex)
                if xf is not None:
                    # Serve the full XETBLOB artifact (frames + footer),
                    # as S3 does; fetch_info url_ranges only ever address
                    # the frame region.
                    self._send_ranged(handler, xf.full or xf.blob)
                    return
            handler._send(404, b"not found")
            return

        # /{org}/{name}/resolve/{rev}/{filename...}
        parts = path.lstrip("/").split("/")
        if len(parts) >= 5 and parts[2] == "resolve":
            repo = self._repo_for(handler, parts)
            if repo is None:
                return
            filename = "/".join(parts[4:])
            f = repo.files_for(parts[3]).get(filename)
            if f is None:
                handler._send(404, b"no such file")
            else:
                self._send_ranged(handler, f.data)
            return

        handler._send(404, b"unknown path")

    def _handle_post(self, handler, body: bytes) -> None:
        path = handler.path
        if path.startswith("/api/models/") and "/paths-info/" in path:
            rest = path[len("/api/models/"):].split("/")
            repo = self._repo_for(handler, rest)
            if repo is None:
                return
            rev = rest[3] if len(rest) > 3 else None
            requested = json.loads(body or b"{}").get("paths", [])
            out = []
            for p in requested:
                f = repo.files_for(rev).get(p)
                if f is None:
                    continue
                item = {"path": p, "size": len(f.data), "type": "file"}
                if f.xet_hash:
                    item["xetHash"] = f.xet_hash
                out.append(item)
            handler._send_json(out)
            return
        handler._send(404, b"unknown path")

    def _reconstruction_doc(self, rec, range_header):
        """Production reconstruction semantics: an optional HTTP ``Range``
        header selects a byte window of the *file*; the response holds only
        the terms overlapping it plus ``offset_into_first_range`` (bytes to
        skip inside the first term). A window starting past EOF is 416 —
        this is how the real client paginates huge files (it walks 256 MB
        windows until the server says 416)."""
        total = sum(t.unpacked_length for t in rec.terms)
        lo, hi = 0, total
        if range_header:
            spec = range_header.split("=", 1)[-1]
            start_s, _, end_s = spec.partition("-")
            lo = int(start_s or 0)
            hi = min(int(end_s) + 1 if end_s else total, total)
            if lo >= total and total > 0:
                return None
        doc = recon.to_json(rec)
        if lo > 0 or hi < total:
            terms, off = [], 0
            offset_into_first = 0
            for t, tj in zip(rec.terms, doc["terms"]):
                t_lo, t_hi = off, off + t.unpacked_length
                if t_hi > lo and t_lo < hi:
                    if not terms:
                        offset_into_first = lo - t_lo
                    terms.append(tj)
                off = t_hi
            doc["terms"] = terms
            doc["offset_into_first_range"] = offset_into_first
            keep = {t["hash"] for t in terms}
            doc["fetch_info"] = {
                h: v for h, v in doc["fetch_info"].items() if h in keep
            }
        # Production fetch_info URLs are absolute presigned links;
        # absolutize at serve time (the port isn't known when the repo
        # fixture is built).
        for entries in doc["fetch_info"].values():
            for fi in entries:
                if fi["url"].startswith("/"):
                    fi["url"] = self.url + fi["url"]
        return doc

    def _send_ranged(self, handler, blob: bytes) -> None:
        """Serve with HTTP Range support (bytes=a-b inclusive), like a CDN."""
        range_header = handler.headers.get("Range")
        if range_header and range_header.startswith("bytes="):
            spec = range_header[len("bytes="):]
            start_s, _, end_s = spec.partition("-")
            start = int(start_s) if start_s else 0
            end = int(end_s) if end_s else len(blob) - 1
            if start >= len(blob):
                handler._send(416, b"range not satisfiable")
                return
            # Zero-copy slice: a real CDN's sendfile path costs no
            # origin CPU per byte; this server shares the bench host's
            # one core with the client, so a bytes-slice copy here
            # would tax the measured client throughput.
            piece = memoryview(blob)[start : end + 1]
            handler.send_response(206)
            handler.send_header("Content-Type", "application/octet-stream")
            handler.send_header(
                "Content-Range", f"bytes {start}-{start+len(piece)-1}/{len(blob)}"
            )
            handler.send_header("Content-Length", str(len(piece)))
            handler.end_headers()
            self._write_shaped(handler, piece)
        else:
            if self.throttle is None:
                handler._send(200, blob)
                return
            handler.send_response(200)
            handler.send_header("Content-Type", "application/octet-stream")
            handler.send_header("Content-Length", str(len(blob)))
            handler.end_headers()
            self._write_shaped(handler, memoryview(blob))

    def _write_shaped(self, handler, piece) -> None:
        """Write a response body, paced by the shared token bucket when
        shaping is on (64 KiB quanta: coarse enough to keep syscall
        overhead negligible, fine enough that a shaped multi-MB body
        releases the GIL regularly for the peers being measured)."""
        if self.throttle is None:
            handler.wfile.write(piece)
            return
        mv = memoryview(piece)
        step = 64 * 1024
        for off in range(0, mv.nbytes, step):
            part = mv[off:off + step]
            self.throttle.acquire(part.nbytes)
            handler.wfile.write(part)


def _safetensors_blob(tensors) -> bytes:
    """Serialize a tensor dict to in-memory safetensors bytes (shared by
    the checkpoint-fixture generators)."""
    import pathlib
    import tempfile

    from zest_tpu.models.safetensors_io import write_safetensors

    with tempfile.NamedTemporaryFile(suffix=".safetensors") as f:
        write_safetensors(f.name, tensors)
        return pathlib.Path(f.name).read_bytes()


def gpt2_checkpoint_files(
    n_embd: int = 64,
    n_layer: int = 2,
    vocab_size: int = 256,
    n_ctx: int = 64,
    seed: int = 0,
) -> dict[str, bytes]:
    """A small but *valid* GPT-2 checkpoint (HF tensor names + config):
    config.json + model.safetensors bytes, sized by the dims — shared by
    the fixture hub CLI, the bench driver's end-to-end pull, and the TPU
    landing example. ~12·n_layer·n_embd² fp32 parameter bytes."""
    import json as _json

    import numpy as np

    cfg = dict(model_type="gpt2", vocab_size=vocab_size,
               n_positions=n_ctx, n_ctx=n_ctx, n_embd=n_embd,
               n_layer=n_layer, n_head=4, layer_norm_epsilon=1e-5)
    rng = np.random.default_rng(seed)
    E, L = n_embd, n_layer
    t = {
        "wte.weight": rng.normal(0, 0.02, (vocab_size, E)),
        "wpe.weight": rng.normal(0, 0.01, (n_ctx, E)),
        "ln_f.weight": np.ones(E), "ln_f.bias": np.zeros(E),
    }
    shapes = {
        "ln_1.weight": (E,), "ln_1.bias": (E,),
        "ln_2.weight": (E,), "ln_2.bias": (E,),
        "attn.c_attn.weight": (E, 3 * E), "attn.c_attn.bias": (3 * E,),
        "attn.c_proj.weight": (E, E), "attn.c_proj.bias": (E,),
        "mlp.c_fc.weight": (E, 4 * E), "mlp.c_fc.bias": (4 * E,),
        "mlp.c_proj.weight": (4 * E, E), "mlp.c_proj.bias": (E,),
    }
    for layer in range(L):
        for leaf, shape in shapes.items():
            init = (np.ones if leaf.endswith("ln_1.weight")
                    or leaf.endswith("ln_2.weight") else
                    lambda s: rng.normal(0, 0.02, s))
            t[f"h.{layer}.{leaf}"] = np.asarray(init(shape))
    tensors = {k: v.astype(np.float32) for k, v in t.items()}
    return {
        "config.json": _json.dumps(cfg).encode(),
        "model.safetensors": _safetensors_blob(tensors),
    }


def llama_checkpoint_files(
    hidden_size: int = 64,
    n_layer: int = 2,
    vocab_size: int = 256,
    n_ctx: int = 64,
    seed: int = 0,
    mutate_fraction: float | None = None,
    mutate_seed: int = 1,
) -> dict[str, bytes]:
    """A small but *valid* HF Llama checkpoint (HF tensor names + config),
    the Llama-family counterpart of :func:`gpt2_checkpoint_files` —
    feeds the no-network lifecycle demo (examples/finetune_and_export.py
    via ``scripts/fixture_hub.py --llama``). GQA 4:2 heads, untied
    embeddings, no attention/mlp biases (the Llama-3.x layout).

    ``mutate_fraction`` derives the deterministic "revision B" of the
    same checkpoint (ISSUE 10): identical base tensors from ``seed``,
    then ~that fraction of the bytes XOR-flipped in seeded contiguous
    runs (``zest_tpu.bench_scale.mutate_tensors``; same shapes) — the
    ~1%-changed revision the delta-pull tests diff against the base."""
    import json as _json

    import numpy as np

    E, L, V = hidden_size, n_layer, vocab_size
    n_head, n_kv = 4, 2
    head_dim = E // n_head
    inter = 2 * E
    cfg = dict(model_type="llama", architectures=["LlamaForCausalLM"],
               vocab_size=V, hidden_size=E, intermediate_size=inter,
               num_hidden_layers=L, num_attention_heads=n_head,
               num_key_value_heads=n_kv, max_position_embeddings=n_ctx,
               rms_norm_eps=1e-5, rope_theta=10000.0,
               tie_word_embeddings=False, torch_dtype="float32")
    rng = np.random.default_rng(seed)

    def w(*shape):
        return rng.normal(0, 0.02, shape).astype(np.float32)

    t = {
        "model.embed_tokens.weight": w(V, E),
        "model.norm.weight": np.ones(E, np.float32),
        "lm_head.weight": w(V, E),
    }
    for i in range(L):
        p = f"model.layers.{i}."
        t[p + "input_layernorm.weight"] = np.ones(E, np.float32)
        t[p + "post_attention_layernorm.weight"] = np.ones(E, np.float32)
        t[p + "self_attn.q_proj.weight"] = w(n_head * head_dim, E)
        t[p + "self_attn.k_proj.weight"] = w(n_kv * head_dim, E)
        t[p + "self_attn.v_proj.weight"] = w(n_kv * head_dim, E)
        t[p + "self_attn.o_proj.weight"] = w(E, n_head * head_dim)
        t[p + "mlp.gate_proj.weight"] = w(inter, E)
        t[p + "mlp.up_proj.weight"] = w(inter, E)
        t[p + "mlp.down_proj.weight"] = w(E, inter)
    if mutate_fraction:
        from zest_tpu.bench_scale import mutate_tensors

        mutate_tensors(t, mutate_fraction, seed=mutate_seed)
    return {
        "config.json": _json.dumps(cfg).encode(),
        "model.safetensors": _safetensors_blob(t),
    }


def mixtral_checkpoint_files(
    hidden_size: int = 64,
    n_layer: int = 2,
    vocab_size: int = 256,
    n_ctx: int = 64,
    n_experts: int = 8,
    top_k: int = 2,
    seed: int = 0,
) -> dict[str, bytes]:
    """A small but *valid* HF Mixtral checkpoint (HF tensor names +
    config) — the MoE counterpart of :func:`llama_checkpoint_files`.
    Expert tensors dominate the byte count (the real Mixtral shape of
    the problem), which is what the HBM pool's lazy expert paging
    (ISSUE 18) needs a fixture for: a dense core worth a small fraction
    of the checkpoint plus ``n_experts`` per-layer SwiGLU expert
    groups."""
    import json as _json

    import numpy as np

    E, L, V, X = hidden_size, n_layer, vocab_size, n_experts
    n_head, n_kv = 4, 2
    head_dim = E // n_head
    inter = 2 * E
    cfg = dict(model_type="mixtral",
               architectures=["MixtralForCausalLM"],
               vocab_size=V, hidden_size=E, intermediate_size=inter,
               num_hidden_layers=L, num_attention_heads=n_head,
               num_key_value_heads=n_kv, max_position_embeddings=n_ctx,
               num_local_experts=X, num_experts_per_tok=top_k,
               rms_norm_eps=1e-5, rope_theta=10000.0,
               torch_dtype="float32")
    rng = np.random.default_rng(seed)

    def w(*shape):
        return rng.normal(0, 0.02, shape).astype(np.float32)

    t = {
        "model.embed_tokens.weight": w(V, E),
        "model.norm.weight": np.ones(E, np.float32),
        "lm_head.weight": w(V, E),
    }
    for i in range(L):
        p = f"model.layers.{i}."
        t[p + "input_layernorm.weight"] = np.ones(E, np.float32)
        t[p + "post_attention_layernorm.weight"] = np.ones(E, np.float32)
        t[p + "self_attn.q_proj.weight"] = w(n_head * head_dim, E)
        t[p + "self_attn.k_proj.weight"] = w(n_kv * head_dim, E)
        t[p + "self_attn.v_proj.weight"] = w(n_kv * head_dim, E)
        t[p + "self_attn.o_proj.weight"] = w(E, n_head * head_dim)
        t[p + "block_sparse_moe.gate.weight"] = w(X, E)
        for x in range(X):
            ep = f"{p}block_sparse_moe.experts.{x}."
            t[ep + "w1.weight"] = w(inter, E)
            t[ep + "w2.weight"] = w(E, inter)
            t[ep + "w3.weight"] = w(inter, E)
    return {
        "config.json": _json.dumps(cfg).encode(),
        "model.safetensors": _safetensors_blob(t),
    }
