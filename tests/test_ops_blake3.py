"""On-device BLAKE3 vs the pure reference implementation (bit-exactness).

The device hasher is the post-gather integrity gate (SURVEY.md §6.4 "on-
device BLAKE3"); a single bit of drift silently corrupts every pulled
model, so parity with cas.blake3 across tree shapes is the whole game.
Sizes chosen to hit: empty input, sub-block, block boundaries, single-leaf
(<=1024B), two-leaf, odd-leaf counts (promotion), and multi-level trees.
"""

import numpy as np
import pytest

from zest_tpu.cas import blake3 as ref
from zest_tpu.ops.blake3 import DeviceHasher, verify_chunks_device

_RNG = np.random.default_rng(42)
_SIZES = [0, 1, 3, 63, 64, 65, 1023, 1024, 1025, 2048, 3000, 5000]


@pytest.fixture(scope="module")
def hasher():
    return DeviceHasher()


def test_plain_matches_reference(hasher):
    chunks = [_RNG.bytes(n) for n in _SIZES]
    got = hasher.hash_batch(chunks)
    for c, g in zip(chunks, got):
        assert g == ref.blake3(c), f"mismatch at len {len(c)}"


def test_keyed_matches_reference():
    key = bytes(range(32))
    hk = DeviceHasher(key)
    chunks = [_RNG.bytes(n) for n in _SIZES]
    for c, g in zip(chunks, hk.hash_batch(chunks)):
        assert g == ref.blake3_keyed(key, c), f"mismatch at len {len(c)}"


def test_every_leaf_count_through_promotion(hasher):
    """1..9 leaves exercises each tree shape the masked pairwise merge can
    take at small scale (odd tails, multi-level promotion)."""
    chunks = [_RNG.bytes(1024 * n + 17) for n in range(9)]
    for c, g in zip(chunks, hasher.hash_batch(chunks)):
        assert g == ref.blake3(c), f"mismatch at len {len(c)}"


def test_device_side_masking(hasher):
    """Garbage bytes beyond `length` must not affect the digest — gathered
    pool rows are reused buffers."""
    import jax.numpy as jnp

    buf = np.frombuffer(_RNG.bytes(2048), dtype=np.uint8).copy()
    words = jnp.asarray(buf.view("<u4")[None, :])
    d = hasher.hash_device(words, jnp.asarray([1500]))
    assert (
        np.asarray(d)[0].astype("<u4").tobytes() == ref.blake3(buf[:1500].tobytes())
    )


def test_verify_chunks_device(hasher):
    import jax.numpy as jnp

    good = _RNG.bytes(1700)
    bad = _RNG.bytes(1700)
    buf = np.zeros((2, 2048), dtype=np.uint8)
    buf[0, :1700] = np.frombuffer(good, dtype=np.uint8)
    buf[1, :1700] = np.frombuffer(bad, dtype=np.uint8)
    expected = np.stack([
        np.frombuffer(ref.blake3(good), dtype="<u4"),
        np.frombuffer(ref.blake3(good), dtype="<u4"),  # wrong for row 1
    ])
    ok = verify_chunks_device(
        jnp.asarray(buf.view("<u4")), jnp.asarray([1700, 1700]),
        jnp.asarray(expected),
    )
    assert bool(ok[0]) and not bool(ok[1])


def test_keyed_chunk_hash_convention(hasher):
    """Device hashing with the CHUNK_KEY matches cas.hashing.chunk_hash —
    the convention the whole CAS layer keys on."""
    from zest_tpu.cas import hashing

    hk = DeviceHasher(hashing.CHUNK_KEY)
    data = _RNG.bytes(3333)
    assert hk.hash_batch([data])[0] == hashing.chunk_hash(data)


def test_capacity_validation(hasher):
    import jax.numpy as jnp

    with pytest.raises(ValueError):
        hasher.hash_device(jnp.zeros((1, 100), jnp.uint32), jnp.asarray([0]))
