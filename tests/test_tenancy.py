"""Multi-tenant pull service (ISSUE 13): the concurrent-daemon suite.

The contract under test: concurrent pulls of overlapping models run
over shared, globally-budgeted pools — ONE network fetch per xorb
range process-wide (singleflight; losers read the winner's cache
entry), fair per-tenant admission with typed backpressure, LRU cache
eviction that never touches pinned entries, and tenant fault
isolation (a cancelled session releases its slot/pins and detaches
from shared flights without poisoning them) — while ``ZEST_TENANCY=0``
restores fully independent pulls bit-for-bit.
"""

from __future__ import annotations

import errno
import hashlib
import os
import threading
import time

import pytest

from zest_tpu import storage, telemetry
from zest_tpu.config import Config
from zest_tpu.telemetry import session as session_mod
from zest_tpu.transfer import tenancy
from zest_tpu.transfer.pull import pull_model
from zest_tpu.transfer.tenancy import (
    AdmissionController,
    AdmissionRejected,
    CacheEvictor,
    CancelToken,
    PinBook,
    PullCancelled,
    Singleflight,
)

from fixtures import FixtureHub, FixtureRepo


@pytest.fixture(autouse=True)
def clean_state():
    telemetry.reset_all()
    tenancy.reset()
    yield
    telemetry.reset_all()
    tenancy.reset()


# Two revisions sharing most content: the "overlapping model sets"
# shape (IOTA) — rev B chunk-dedups against rev A's xorbs, so the two
# pulls contend for the same fetch units. Payloads are seeded random
# bytes: incompressible, so a shaped (throttle_bps) hub actually
# bounds the wire rate — compressible fixtures would LZ4 down to
# nothing and finish before a mid-pull cancel can land.
import random as _random

_MODEL_A = _random.Random(7).randbytes(768 * 1024)
BASE_FILES = {
    "config.json": b'{"model_type": "test"}',
    "model.safetensors": _MODEL_A,
    "tokenizer.json": b'{"tok": 1}' * 64,
}
REV_B_FILES = dict(BASE_FILES)
REV_B_FILES["model.safetensors"] = (
    _MODEL_A[:-65536] + _random.Random(8).randbytes(65536)
)


def _cfg(hub, root, **kw):
    return Config(hf_home=root / "hf", cache_dir=root / "zest",
                  hf_token="hf_test", endpoint=hub.url, **kw)


def _digests(snapshot_dir) -> dict:
    out = {}
    for f in sorted(snapshot_dir.rglob("*")):
        if f.is_file():
            out[str(f.relative_to(snapshot_dir))] = hashlib.sha256(
                f.read_bytes()).hexdigest()
    return out


def _xorb_gets(hub) -> list[tuple[str, str]]:
    """Data-plane fetches at UNIT granularity: (path, byte range)."""
    return list(hub.xorb_fetches)


# ── Tentpole (a): singleflight fetch dedupe ──


class TestSingleflightUnit:
    def test_leader_then_waiter_done(self):
        sf = Singleflight()
        role, flight = sf.join("k")
        assert role == "lead"
        got = []
        t = threading.Thread(
            target=lambda: got.append(sf.wait(sf.join("k")[1])))
        t.start()
        time.sleep(0.05)
        sf.resolve(flight)
        t.join(2)
        assert got == ["done"]
        # The table is empty again: a later miss starts a fresh flight.
        assert sf.join("k")[0] == "lead"

    def test_failed_flight_propagates_one_typed_error(self):
        sf = Singleflight()
        _role, flight = sf.join("k")
        outcomes = []
        t = threading.Thread(
            target=lambda: outcomes.append(sf.wait(sf.join("k")[1])))
        t.start()
        time.sleep(0.05)
        boom = RuntimeError("cdn exploded")
        sf.fail(flight, boom)
        t.join(2)
        assert outcomes == ["failed"]
        assert flight.error is boom

    def test_cancelled_leader_hands_off_to_live_waiter(self):
        sf = Singleflight()
        _role, flight = sf.join("k")
        outcomes = []

        def waiter():
            _r, f = sf.join("k")
            outcomes.append(sf.wait(f))

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        sf.abdicate(flight)  # the leader's session was cancelled
        t.join(2)
        assert outcomes == ["lead"]  # the waiter now owns the fetch

    def test_abdicate_with_no_waiters_dissolves(self):
        sf = Singleflight()
        _role, flight = sf.join("k")
        sf.abdicate(flight)
        assert sf.join("k")[0] == "lead"  # fresh flight, not poisoned

    def test_cancelled_waiter_detaches_without_poisoning(self):
        sf = Singleflight()
        _role, flight = sf.join("k")
        token = CancelToken()
        outcomes = []
        t = threading.Thread(
            target=lambda: outcomes.append(
                sf.wait(sf.join("k")[1], cancel=token)))
        t.start()
        time.sleep(0.05)
        token.cancel()
        t.join(2)
        assert outcomes == ["cancelled"]
        # The flight is untouched: a new waiter still resolves normally.
        got = []
        t2 = threading.Thread(
            target=lambda: got.append(sf.wait(sf.join("k")[1])))
        t2.start()
        sf.resolve(flight)
        t2.join(2)
        assert got == ["done"]


class TestConcurrentOverlappingPulls:
    def test_one_fetch_per_shared_xorb_and_identical_digests(self, tmp_path):
        repo = FixtureRepo("acme/tenants", dict(BASE_FILES),
                           chunks_per_xorb=2)
        rev_b = repo.add_revision(dict(REV_B_FILES))
        rev_a = repo._rev_order[0]
        with FixtureHub(repo) as hub:
            # Solo reference digests, one fresh cfg per revision.
            solo = {}
            for i, rev in enumerate((rev_a, rev_b)):
                cfg = _cfg(hub, tmp_path / f"solo{i}")
                res = pull_model(cfg, "acme/tenants", revision=rev,
                                 no_p2p=True, log=lambda *a, **k: None)
                solo[rev] = _digests(res.snapshot_dir)
            hub.requests_seen.clear()
            hub.xorb_fetches.clear()

            # Concurrent overlapping pulls, one shared cfg/cache.
            cfg = _cfg(hub, tmp_path / "shared")
            results: dict = {}
            barrier = threading.Barrier(2)

            def pull(rev, tenant):
                barrier.wait()
                res = pull_model(cfg, "acme/tenants", revision=rev,
                                 no_p2p=True, tenant=tenant,
                                 log=lambda *a, **k: None)
                results[rev] = res

            ts = [threading.Thread(target=pull, args=(rev_a, "t-a")),
                  threading.Thread(target=pull, args=(rev_b, "t-b"))]
            for t in ts:
                t.start()
            for t in ts:
                t.join(60)
            assert set(results) == {rev_a, rev_b}

            # Byte-identical to the solo pulls.
            for rev, res in results.items():
                assert _digests(res.snapshot_dir) == solo[rev]

            # Exactly one fetch per distinct xorb GET: the overlapping
            # units were either deduped in flight or served from the
            # other pull's cache entry — never fetched twice.
            gets = _xorb_gets(hub)
            assert len(gets) == len(set(gets)), (
                f"duplicate xorb fetches: {sorted(gets)}")

    def test_knob_off_pulls_are_independent_and_schema_identical(
            self, tmp_path):
        repo = FixtureRepo("acme/knoboff", dict(BASE_FILES),
                           chunks_per_xorb=2)
        with FixtureHub(repo) as hub:
            on = pull_model(_cfg(hub, tmp_path / "on"), "acme/knoboff",
                            no_p2p=True, log=lambda *a, **k: None)
            off_cfg = _cfg(hub, tmp_path / "off", tenancy_enabled=False)
            off = pull_model(off_cfg, "acme/knoboff", no_p2p=True,
                             log=lambda *a, **k: None)
        # Byte identity.
        assert _digests(on.snapshot_dir) == _digests(off.snapshot_dir)
        # Stats schema identity: tenancy adds NO keys to pull stats.
        assert set(on.stats) == set(off.stats)
        # files_pipeline reports the same (per-pull) budget bound.
        assert (on.stats["files_pipeline"]["budget_bytes"]
                == off.stats["files_pipeline"]["budget_bytes"])

    def test_knob_off_status_has_no_tenancy_block(self, tmp_path):
        cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "z",
                     tenancy_enabled=False)
        assert tenancy.summary(cfg) is None
        # And even after another (knob-on) cfg configured the state,
        # a knob-off caller still sees None.
        on_cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "z")
        tenancy.state(on_cfg)
        assert tenancy.summary(cfg) is None
        assert tenancy.summary(on_cfg) is not None


# ── Tentpole (b): admission control ──


class TestAdmission:
    def test_immediate_admit_within_budget(self):
        c = AdmissionController(max_pulls=2, max_queue=4)
        c.acquire("a")
        c.acquire("b")
        assert c.summary()["active"] == 2

    def test_fair_queue_deficit_round_robin(self):
        c = AdmissionController(max_pulls=1, max_queue=8)
        c.acquire("warm")  # hold the only slot
        order: list[str] = []
        lock = threading.Lock()

        def enter(name, tenant):
            c.acquire(tenant)
            with lock:
                order.append(name)

        # Tenant A queues three sessions BEFORE tenant B's single one:
        # DRR must still alternate — B's pull cannot starve behind A's
        # queue depth.
        threads = []
        for name, tenant in (("a1", "a"), ("a2", "a"), ("a3", "a"),
                             ("b1", "b")):
            t = threading.Thread(target=enter, args=(name, tenant))
            t.start()
            threads.append(t)
            # Deterministic queue order: wait until this waiter is
            # actually parked before starting the next.
            deadline = time.monotonic() + 2
            want = len(threads)
            while c.summary()["queued"] < want \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
        for i in range(4):
            c.release()
            deadline = time.monotonic() + 2
            while len(order) < i + 1 and time.monotonic() < deadline:
                time.sleep(0.005)
        for t in threads:
            t.join(2)
        assert order == ["a1", "b1", "a2", "a3"]

    def test_queue_full_rejects_typed_with_retry_after(self):
        c = AdmissionController(max_pulls=1, max_queue=0)
        c.acquire("a")
        with pytest.raises(AdmissionRejected) as ei:
            c.acquire("b")
        assert ei.value.retry_after_s >= 1.0
        assert c.summary()["rejected_total"] == 1

    def test_cancel_while_queued_leaves_the_queue(self):
        c = AdmissionController(max_pulls=1, max_queue=4)
        c.acquire("a")
        token = CancelToken()
        errs = []

        def waiter():
            try:
                c.acquire("b", cancel=token)
            except PullCancelled as exc:
                errs.append(exc)

        t = threading.Thread(target=waiter)
        t.start()
        deadline = time.monotonic() + 2
        while c.summary()["queued"] < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        token.cancel("test abort")
        t.join(2)
        assert len(errs) == 1
        assert c.summary()["queued"] == 0
        # The slot was never consumed: release + re-acquire still works.
        c.release()
        c.acquire("c")

    def test_queued_phase_visible_on_session(self):
        c = AdmissionController(max_pulls=1, max_queue=4)
        c.acquire("a")
        sess = session_mod.begin("x/y", "main", tenant="b")
        done = threading.Event()

        def waiter():
            c.acquire("b", session=sess)
            done.set()

        t = threading.Thread(target=waiter)
        t.start()
        deadline = time.monotonic() + 2
        while sess.snapshot()["phase"] != "queued" \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        assert sess.snapshot()["phase"] == "queued"
        c.release()
        assert done.wait(2)
        assert sess.snapshot()["phase"] == "starting"
        t.join(2)

    def test_pull_model_rejects_when_saturated(self, tmp_path):
        repo = FixtureRepo("acme/reject", dict(BASE_FILES),
                           chunks_per_xorb=2)
        with FixtureHub(repo) as hub:
            cfg = _cfg(hub, tmp_path, tenant_max_pulls=1, tenant_queue=0)
            st = tenancy.state(cfg)
            st.controller.acquire("hog")  # saturate the only slot
            try:
                with pytest.raises(AdmissionRejected):
                    pull_model(cfg, "acme/reject", no_p2p=True,
                               log=lambda *a, **k: None)
            finally:
                st.controller.release()
        # The rejected session is terminal "rejected" — typed
        # backpressure, distinct from error (alerts must not fire for
        # the 429 contract working) — never stranded running.
        recent = session_mod.SESSIONS.recent()
        assert recent and recent[0].snapshot()["status"] == "rejected"


# ── Tentpole (c): eviction + pinning ──


def _fake_entry(cache_dir, hash_hex, size, age_s):
    p = cache_dir / hash_hex[:2] / hash_hex
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_bytes(b"x" * size)
    old = time.time() - age_s
    os.utime(p, (old, old))
    return p


class TestEviction:
    def test_lru_eviction_never_evicts_pinned(self, tmp_path):
        cache = tmp_path / "xorbs"
        pinned_hash = "aa" + "0" * 62
        old_hash = "bb" + "1" * 62
        new_hash = "cc" + "2" * 62
        p_pin = _fake_entry(cache, pinned_hash, 4096, age_s=300)
        p_old = _fake_entry(cache, old_hash, 4096, age_s=200)
        p_new = _fake_entry(cache, new_hash, 4096, age_s=10)
        pins = PinBook()
        pins.pin("sess:1", [pinned_hash])
        ev = CacheEvictor(cache, high_bytes=10000, low_bytes=8192,
                          pins=pins)
        freed = ev.maybe_evict()
        assert freed > 0
        assert p_pin.exists(), "pinned entry was evicted"
        assert not p_old.exists(), "LRU victim survived"
        assert ev.pinned_survivals >= 1
        assert p_new.exists()  # newest entry untouched at the low mark
        assert ev.usage_bytes() <= 8192

    def test_partial_entries_pin_under_their_xorb_hash(self, tmp_path):
        cache = tmp_path / "xorbs"
        h = "dd" + "3" * 62
        part = cache / h[:2] / f"{h}.4"
        part.parent.mkdir(parents=True)
        part.write_bytes(b"y" * 2048)
        os.utime(part, (time.time() - 100, time.time() - 100))
        pins = PinBook()
        pins.pin("sess:1", [h])
        ev = CacheEvictor(cache, high_bytes=1024, low_bytes=512,
                          pins=pins)
        ev.maybe_evict()
        assert part.exists()

    def test_enospc_trigger_evicts_unconditionally(self, tmp_path):
        cache = tmp_path / "xorbs"
        _fake_entry(cache, "ee" + "4" * 62, 1024, age_s=50)
        ev = CacheEvictor(cache, high_bytes=1 << 30, low_bytes=0,
                          pins=PinBook())
        assert ev.maybe_evict() == 0       # well under the watermark
        # ENOSPC overrides the watermark: frees down to half usage.
        assert ev.on_enospc() is True
        assert ev.usage_bytes() == 0

    def test_eviction_events_reach_the_flight_recorder(self, tmp_path):
        cache = tmp_path / "xorbs"
        _fake_entry(cache, "ff" + "5" * 62, 2048, age_s=50)
        ev = CacheEvictor(cache, high_bytes=1024, low_bytes=0,
                          pins=PinBook())
        ev.maybe_evict()
        kinds = [e["kind"] for e in telemetry.recorder.tail()]
        assert "cache_evict" in kinds

    def test_reads_touch_mtime_so_eviction_is_lru_not_fifo(
            self, tmp_path):
        # A recently-READ entry must outlive a cold entry written
        # later: cache reads freshen mtime (storage._touch_for_lru),
        # so the evictor's oldest-mtime-first pass is true LRU.
        cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "z")
        cache = storage.XorbCache(cfg)
        hot_hash = "aa" + "6" * 62
        cold_hash = "bb" + "7" * 62
        cache.put(hot_hash, b"h" * 2048)
        p_hot = cfg.xorb_cache_path(hot_hash)
        old = time.time() - 500
        os.utime(p_hot, (old, old))
        cache.put(cold_hash, b"c" * 2048)
        p_cold = cfg.xorb_cache_path(cold_hash)
        os.utime(p_cold, (time.time() - 100,) * 2)
        assert cache.get(hot_hash) is not None  # the READ freshens it
        ev = CacheEvictor(cfg.xorb_cache_dir(), high_bytes=2048,
                          low_bytes=2048, pins=PinBook())
        ev.maybe_evict()
        assert p_hot.exists(), "recently-read entry was evicted (FIFO)"
        assert not p_cold.exists()

    def test_release_unpins(self):
        pins = PinBook()
        pins.pin("sess:1", ["h1", "h2"])
        pins.pin("sess:2", ["h2"])
        pins.release("sess:1")
        assert not pins.pinned("h1")
        assert pins.pinned("h2")  # still held by sess:2
        pins.release("sess:2")
        assert not pins.pinned("h2")

    def test_eviction_mid_pull_degrades_to_refetch(self, tmp_path):
        # Pull once (cache warm), delete every cache entry (the
        # eviction), pull into a fresh hf_home with the SAME zest
        # cache: the pull must refetch, not fail or corrupt.
        repo = FixtureRepo("acme/evict", dict(BASE_FILES),
                           chunks_per_xorb=2)
        with FixtureHub(repo) as hub:
            cfg = _cfg(hub, tmp_path)
            res1 = pull_model(cfg, "acme/evict", no_p2p=True,
                              log=lambda *a, **k: None)
            d1 = _digests(res1.snapshot_dir)
            for sub in cfg.xorb_cache_dir().iterdir():
                for f in sub.iterdir():
                    f.unlink()
            cfg2 = Config(hf_home=tmp_path / "hf2",
                          cache_dir=cfg.cache_dir,
                          hf_token="hf_test", endpoint=hub.url)
            res2 = pull_model(cfg2, "acme/evict", no_p2p=True,
                              log=lambda *a, **k: None)
            assert _digests(res2.snapshot_dir) == d1


# ── Tentpole (d) + satellite: cancellation / fault isolation ──


class TestCancellation:
    def test_cancel_mid_pull_terminal_status_cancelled(self, tmp_path):
        repo = FixtureRepo("acme/cancel", dict(BASE_FILES),
                           chunks_per_xorb=2)
        # Shaped CDN so the pull is slow enough to cancel mid-flight;
        # narrow fetch width so later terms enter the bridge (and its
        # per-term cancellation point) AFTER the token fires — at the
        # default 16-wide pool this small fixture would have every term
        # already in flight before the cancel lands.
        with FixtureHub(repo, throttle_bps=200_000) as hub:
            cfg = _cfg(hub, tmp_path, max_concurrent_downloads=2)
            token = CancelToken()
            errs: list = []

            def run():
                try:
                    pull_model(cfg, "acme/cancel", no_p2p=True,
                               cancel=token, log=lambda *a, **k: None)
                except PullCancelled as exc:
                    errs.append(exc)

            t = threading.Thread(target=run)
            t.start()
            deadline = time.monotonic() + 10
            while not session_mod.SESSIONS.active_ids() \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            time.sleep(0.3)  # let it get into the transfer
            token.cancel("test kill")
            t.join(30)
        assert len(errs) == 1
        recent = session_mod.SESSIONS.recent()
        assert recent and recent[0].snapshot()["status"] == "cancelled"
        # Fault isolation: the admission slot was released.
        assert tenancy.state(cfg).controller.summary()["active"] == 0
        # No half-written complete-named files: only .tmp- temps are
        # ever partial, and those are discarded on abort.
        snap_root = cfg.hub_dir()
        leftovers = [p for p in snap_root.rglob("*.safetensors")
                     if p.is_file()
                     and p.stat().st_size
                     != len(BASE_FILES["model.safetensors"])]
        assert leftovers == []

    def test_cancelled_tenant_leaves_concurrent_tenant_unharmed(
            self, tmp_path):
        repo = FixtureRepo("acme/iso", dict(BASE_FILES),
                           chunks_per_xorb=2)
        rev_b = repo.add_revision(dict(REV_B_FILES))
        rev_a = repo._rev_order[0]
        with FixtureHub(repo) as hub:
            solo_cfg = _cfg(hub, tmp_path / "solo")
            solo = _digests(pull_model(
                solo_cfg, "acme/iso", revision=rev_b, no_p2p=True,
                log=lambda *a, **k: None).snapshot_dir)

            cfg = _cfg(hub, tmp_path / "shared")
            token = CancelToken()
            token.cancel("pre-cancelled tenant")  # dies at first boundary
            survivor: dict = {}

            def victim():
                with pytest.raises(PullCancelled):
                    pull_model(cfg, "acme/iso", revision=rev_a,
                               no_p2p=True, tenant="victim",
                               cancel=token, log=lambda *a, **k: None)

            def healthy():
                survivor["res"] = pull_model(
                    cfg, "acme/iso", revision=rev_b, no_p2p=True,
                    tenant="healthy", log=lambda *a, **k: None)

            ts = [threading.Thread(target=victim),
                  threading.Thread(target=healthy)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(60)
            assert _digests(survivor["res"].snapshot_dir) == solo

    def test_delete_endpoint_fires_token(self, tmp_path):
        from zest_tpu.api.http_api import HttpApi

        cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "z")
        api = HttpApi(cfg)
        try:
            sess = session_mod.begin("a/b", "main")
            sess.cancel_token = CancelToken()
            payload, code = api.cancel_pull(sess.id)
            assert code == 202 and payload["status"] == "cancelling"
            assert sess.cancel_token.fired
            # Unknown id.
            assert api.cancel_pull("nope")[1] == 404
            # Terminal session: 409.
            session_mod.finish(sess, "cancelled", error="test")
            assert api.cancel_pull(sess.id)[1] == 409
        finally:
            api.close()


# ── Satellite: ENOSPC → CacheFullError ──


class _FlakyDisk:
    """Monkeypatched os.fdopen whose first ``failures`` write attempts
    raise ENOSPC — the deterministic stand-in for a full disk."""

    def __init__(self, failures: int):
        self.left = failures

    def install(self, monkeypatch):
        real = os.fdopen
        flaky = self

        def fake(fd, *a, **kw):
            f = real(fd, *a, **kw)
            if flaky.left > 0:
                flaky.left -= 1

                class _Full:
                    def __enter__(self_inner):
                        return self_inner

                    def __exit__(self_inner, *exc):
                        f.close()
                        return False

                    def write(self_inner, b):
                        raise OSError(errno.ENOSPC,
                                      "No space left on device")

                return _Full()
            return f

        monkeypatch.setattr(storage.os, "fdopen", fake)


class TestCacheFull:
    def test_typed_error_cleans_temps_and_fires_event(
            self, tmp_path, monkeypatch):
        _FlakyDisk(failures=10).install(monkeypatch)
        dest = tmp_path / "cache" / "aa" / "entry"
        with pytest.raises(storage.CacheFullError):
            storage.atomic_write(dest, b"payload")
        assert not dest.exists()
        assert list(dest.parent.glob(".tmp-*")) == []
        kinds = [e["kind"] for e in telemetry.recorder.tail()]
        assert "disk_pressure" in kinds

    def test_eviction_hook_earns_one_retry(self, tmp_path, monkeypatch):
        _FlakyDisk(failures=1).install(monkeypatch)
        calls = []
        storage.set_disk_full_hook(lambda: calls.append(1) or True)
        dest = tmp_path / "cache" / "aa" / "entry"
        storage.atomic_write(dest, b"payload")  # retry succeeds
        assert dest.read_bytes() == b"payload"
        assert calls == [1]

    def test_stream_write_is_typed_but_not_retried(
            self, tmp_path, monkeypatch):
        _FlakyDisk(failures=1).install(monkeypatch)
        dest = tmp_path / "cache" / "aa" / "entry"
        with pytest.raises(storage.CacheFullError):
            storage.atomic_write_stream(dest, iter([b"chunk"]))
        assert not dest.exists()

    def test_bridge_fetch_survives_cache_full(self, tmp_path,
                                              monkeypatch):
        # ENOSPC on the xorb-cache write must NOT fail the pull: the
        # fetched bytes are served uncached (graceful degradation).
        repo = FixtureRepo("acme/full", dict(BASE_FILES),
                           chunks_per_xorb=2)
        with FixtureHub(repo) as hub:
            cfg = _cfg(hub, tmp_path)

            real_put = storage.XorbCache.put

            def full_put(self, hash_hex, data):
                raise storage.CacheFullError("disk full (test)", None)

            monkeypatch.setattr(storage.XorbCache, "put", full_put)
            monkeypatch.setattr(storage.XorbCache, "put_partial",
                                lambda *a, **k: (_ for _ in ()).throw(
                                    storage.CacheFullError("full", None)))
            res = pull_model(cfg, "acme/full", no_p2p=True,
                             log=lambda *a, **k: None)
            monkeypatch.setattr(storage.XorbCache, "put", real_put)
            repo_files = repo.files_for(None)
            for path, fx in repo_files.items():
                assert (res.snapshot_dir / path).read_bytes() == fx.data


# ── Satellite: strict env parsing ──


class TestEnvParsing:
    def test_defaults(self):
        cfg = Config.load({})
        assert cfg.tenancy_enabled is True
        assert cfg.tenant_max_pulls == 4
        assert cfg.tenant_queue == 16
        assert cfg.tenant_inflight_bytes == 4 << 30
        assert cfg.tenant_disk_high == 0
        assert cfg.tenant_disk_low == 0

    def test_knob_off(self):
        assert Config.load({"ZEST_TENANCY": "0"}).tenancy_enabled is False

    @pytest.mark.parametrize("env", [
        {"ZEST_TENANCY": "false"},
        {"ZEST_TENANCY": "yes"},
        {"ZEST_TENANT_MAX_PULLS": "-1"},
        {"ZEST_TENANT_MAX_PULLS": "0"},
        {"ZEST_TENANT_QUEUE": "-2"},
        {"ZEST_TENANT_INFLIGHT": "0"},
        {"ZEST_TENANT_INFLIGHT": "-5"},
        {"ZEST_TENANT_DISK_HIGH": "-1"},
        {"ZEST_TENANT_DISK_LOW": "-1"},
        {"ZEST_TENANT_MAX_PULLS": "two"},
        # Cross-validation: LOW alone silently disarms; LOW >= HIGH
        # would trigger eviction passes that free nothing.
        {"ZEST_TENANT_DISK_LOW": "1024"},
        {"ZEST_TENANT_DISK_HIGH": "1024",
         "ZEST_TENANT_DISK_LOW": "2048"},
        {"ZEST_TENANT_DISK_HIGH": "1024",
         "ZEST_TENANT_DISK_LOW": "1024"},
    ])
    def test_malformed_values_raise(self, env):
        with pytest.raises(ValueError):
            Config.load(env)

    def test_explicit_values(self):
        cfg = Config.load({
            "ZEST_TENANT_MAX_PULLS": "2",
            "ZEST_TENANT_QUEUE": "0",
            "ZEST_TENANT_INFLIGHT": str(1 << 20),
            "ZEST_TENANT_DISK_HIGH": str(1 << 30),
            "ZEST_TENANT_DISK_LOW": str(1 << 29),
        })
        assert cfg.tenant_max_pulls == 2
        assert cfg.tenant_queue == 0
        assert cfg.tenant_inflight_bytes == 1 << 20
        assert cfg.tenant_disk_high == 1 << 30
        assert cfg.tenant_disk_low == 1 << 29


# ── Satellite: _pull_memo snapshot pinning ──


class TestPullMemoPinning:
    def _api(self, hub, tmp_path):
        from zest_tpu.api.http_api import HttpApi

        cfg = _cfg(hub, tmp_path)
        return HttpApi(cfg)

    def test_pinned_key_never_expires_under_a_reader(
            self, tmp_path, monkeypatch):
        repo = FixtureRepo("acme/memo", dict(BASE_FILES),
                           chunks_per_xorb=2)
        with FixtureHub(repo) as hub:
            api = self._api(hub, tmp_path)
            try:
                calls = []
                import zest_tpu.transfer.pull as pull_mod

                real = pull_mod.pull_model

                def counting(*a, **kw):
                    calls.append(1)
                    return real(*a, **kw)

                monkeypatch.setattr(pull_mod, "pull_model", counting)
                key = ("acme/memo", "main")
                d1 = api._pull_memo(*key)
                assert len(calls) == 1
                # Reader active + TTL expired: must NOT re-pull.
                api._pin_snapshot(key)
                api._pulled[key] = (api._pulled[key][0], 0.0)
                assert api._pull_memo(*key) == d1
                assert len(calls) == 1
                # Reader gone: the expired entry re-pulls again.
                api._unpin_snapshot(key)
                assert api._pull_memo(*key) == d1
                assert len(calls) == 2
            finally:
                api.close()

    def test_concurrent_misses_share_one_pull(self, tmp_path,
                                              monkeypatch):
        repo = FixtureRepo("acme/memo2", dict(BASE_FILES),
                           chunks_per_xorb=2)
        with FixtureHub(repo) as hub:
            api = self._api(hub, tmp_path)
            try:
                calls = []
                import zest_tpu.transfer.pull as pull_mod

                real = pull_mod.pull_model

                def slow(*a, **kw):
                    calls.append(1)
                    time.sleep(0.2)
                    return real(*a, **kw)

                monkeypatch.setattr(pull_mod, "pull_model", slow)
                got = []
                ts = [threading.Thread(
                    target=lambda: got.append(
                        api._pull_memo("acme/memo2", "main")))
                    for _ in range(3)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(30)
                assert len(calls) == 1
                assert len(set(map(str, got))) == 1
            finally:
                api.close()
