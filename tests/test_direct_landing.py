"""Direct-to-HBM landing: cache → tensors, no reassembled file.

SURVEY.md §7 hard part #2 end-to-end on the virtual mesh: a Mixtral-named
checkpoint is content-addressed by the fixture encoder, distributed via
the expert-sharded round (shared units gathered, expert units private),
and landed straight from the cache into expert-placed device arrays —
asserting bit-equality with the original tensors and that no reassembled
safetensors file was ever written.
"""

import numpy as np
import pytest

from tests.fixtures import FixtureHub, FixtureRepo
from zest_tpu.config import Config
from zest_tpu.models import moe
from zest_tpu.models.direct import (
    CachedFileReader,
    DirectLandingError,
    land_moe_expert_sharded,
    land_tensors,
)
from zest_tpu.models.safetensors_io import parse_header
from zest_tpu.parallel.expert import ExpertPlacement, classify_file
from zest_tpu.parallel.mesh import model_mesh
from zest_tpu.transfer.bridge import XetBridge
from zest_tpu.transfer.pod import (
    expert_pod_round,
    fetch_file_header,
    pod_round,
)

CFG = moe.MoEConfig.tiny(n_layer=1, n_experts=4, n_embd=64, d_ff=512,
                         vocab_size=64)


def _hf_tensors():
    from tests.test_moe import _hf_mixtral_tensors

    return _hf_mixtral_tensors(CFG)


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    from zest_tpu.models.safetensors_io import write_safetensors

    path = tmp_path_factory.mktemp("ckpt") / "model.safetensors"
    write_safetensors(path, _hf_tensors())
    return path.read_bytes()


@pytest.fixture(scope="module")
def hub(ckpt):
    repo = FixtureRepo(
        "acme/tiny-moe",
        {"config.json": b'{"model_type": "mixtral"}',
         "model.safetensors": ckpt},
        chunks_per_xorb=2,
    )
    with FixtureHub(repo) as h:
        yield h


def _bridge(hub, root):
    cfg = Config(hf_home=root / "hf", cache_dir=root / "zest",
                 hf_token="hf_test", endpoint=hub.url)
    bridge = XetBridge(cfg)
    bridge.authenticate("acme/tiny-moe")
    return bridge


def _rec(hub):
    repo = hub.repos["acme/tiny-moe"]
    return repo.reconstructions[repo.files["model.safetensors"].xet_hash]


@pytest.mark.slow
def test_cached_file_reader_random_access(hub, tmp_path, ckpt):
    bridge = _bridge(hub, tmp_path)
    rec = _rec(hub)
    pod_round(bridge, [rec])
    reader = CachedFileReader(bridge.cache, rec)
    assert reader.size == len(ckpt)
    for lo, hi in [(0, 100), (0, len(ckpt)), (131_000, 197_123),
                   (len(ckpt) - 10, len(ckpt)), (5000, 5000)]:
        assert reader.read(lo, hi) == ckpt[lo:hi], (lo, hi)
    with pytest.raises(DirectLandingError):
        reader.read(0, len(ckpt) + 1)


def test_reader_requires_cached_units(hub, tmp_path):
    bridge = _bridge(hub, tmp_path)  # cache empty: no round ran
    reader = CachedFileReader(bridge.cache, _rec(hub))
    with pytest.raises(DirectLandingError, match="not in cache"):
        reader.read(0, 100)


def test_reader_reports_corrupt_cache_with_cause(hub, tmp_path):
    """A corrupt cached unit + no bridge must surface the decode failure
    (with the underlying exception chained), not claim a cache miss."""
    import os

    bridge = _bridge(hub, tmp_path)
    rec = _rec(hub)
    pod_round(bridge, [rec])
    for root, _dirs, files in os.walk(tmp_path / "zest"):
        for name in files:
            path = os.path.join(root, name)
            blob = bytearray(open(path, "rb").read())
            blob[8 : min(len(blob), 64)] = b"\xff" * (min(len(blob), 64) - 8)
            open(path, "wb").write(bytes(blob))
    reader = CachedFileReader(bridge.cache, rec)  # no bridge
    with pytest.raises(DirectLandingError, match="failed to decode") as ei:
        reader.read(0, 100)
    assert isinstance(ei.value.__cause__, ValueError)


def test_land_tensors_bit_exact(hub, tmp_path, ckpt):
    bridge = _bridge(hub, tmp_path)
    rec = _rec(hub)
    pod_round(bridge, [rec])
    header = parse_header(ckpt)
    want = _hf_tensors()
    got = land_tensors(bridge.cache, rec, header)
    assert set(got) == set(want)
    for name in want:
        np.testing.assert_array_equal(got[name], want[name])


def test_land_tensors_predicate_filters(hub, tmp_path, ckpt):
    bridge = _bridge(hub, tmp_path)
    rec = _rec(hub)
    pod_round(bridge, [rec])
    header = parse_header(ckpt)
    got = land_tensors(
        bridge.cache, rec, header,
        predicate=lambda n: moe.expert_of_tensor(n) == 2,
    )
    assert got and all(moe.expert_of_tensor(n) == 2 for n in got)


def test_fetch_file_header_from_head_terms(hub, tmp_path, ckpt):
    bridge = _bridge(hub, tmp_path)
    header = fetch_file_header(bridge, _rec(hub))
    assert set(header.tensors) == set(parse_header(ckpt).tensors)
    # header came from the head of the file, not a full fetch
    assert bridge.stats.bytes_from_cdn < len(ckpt)


def _pull_cfg(hub, root):
    return Config(hf_home=root / "hf", cache_dir=root / "zest",
                  hf_token="hf_test", endpoint=hub.url)


def test_pull_device_tpu_lands_direct(hub, tmp_path, ckpt, monkeypatch):
    """``pull --device=tpu`` lands tensors straight from cached units —
    bit-identical to the written file, zero reassembled-file reads on the
    landing path, ``stats["hbm"]["direct"] is True`` — and the result
    owns the staged tree (VERDICT round-1 items #3 and weak #5)."""
    import zest_tpu.models.loader as loader_mod
    from zest_tpu.transfer.pull import pull_model

    disk_loads = []
    orig = loader_mod.load_checkpoint
    monkeypatch.setattr(
        loader_mod, "load_checkpoint",
        lambda *a, **k: disk_loads.append(a) or orig(*a, **k),
    )
    res = pull_model(_pull_cfg(hub, tmp_path), "acme/tiny-moe",
                     no_p2p=True, device="tpu")
    assert res.stats["hbm"]["direct"] is True
    assert not disk_loads  # the disk staging path never ran
    # The TPU path decomposes into the SURVEY §5 tracing stages. The
    # pipelined pull overlaps `files` with `hbm_commit`, so the stage
    # walls no longer sum below elapsed_s — but each stage's wall is
    # union coverage and individually bounded by it, and busy time
    # (thread-seconds) is reported alongside for attribution.
    stages = res.stats["stages"]
    for stage in ("resolve", "cas_metadata", "fetch", "hbm_commit",
                  "files"):
        assert stages[stage] >= 0, stages
        assert stages[stage] <= res.stats["elapsed_s"] + 0.05
    busy = res.stats["stages_busy"]
    assert set(busy) == set(stages)
    for stage, wall in stages.items():
        assert busy[stage] >= wall - 0.05, (stage, busy, stages)
    assert res.stats["time_to_hbm_s"] <= res.stats["elapsed_s"] + 0.05
    assert res.stats["files_hbm_span_s"] >= 0
    want = _hf_tensors()
    assert set(res.params) == set(want)
    for name, arr in want.items():
        np.testing.assert_array_equal(np.asarray(res.params[name]), arr)
    # the HF-cache file is still written afterwards, byte-identical
    assert (res.snapshot_dir / "model.safetensors").read_bytes() == ckpt


def test_pull_device_tpu_direct_without_pod_round(hub, tmp_path):
    """Cold cache and no collective round (single-slot case): the reader
    pulls missing units through the waterfall — direct landing still
    avoids the disk round-trip."""
    from zest_tpu.transfer.pull import pull_model

    res = pull_model(_pull_cfg(hub, tmp_path), "acme/tiny-moe",
                     no_p2p=True, device="tpu", pod=False)
    assert res.stats["hbm"]["direct"] is True
    want = _hf_tensors()
    for name, arr in want.items():
        np.testing.assert_array_equal(np.asarray(res.params[name]), arr)


def test_pull_device_tpu_resume_stages_from_disk(hub, tmp_path):
    """Files already on disk (resume): reading them beats refetching, so
    the disk path runs and reports direct=False."""
    from zest_tpu.transfer.pull import pull_model

    cfg = _pull_cfg(hub, tmp_path)
    pull_model(cfg, "acme/tiny-moe", no_p2p=True)
    res = pull_model(cfg, "acme/tiny-moe", no_p2p=True, device="tpu")
    assert res.stats["hbm"]["direct"] is False
    # The late (disk-fallback) hbm_commit runs after the files barrier
    # (no overlap on this path), so the old additive invariant still
    # holds; elapsed_s and time_to_hbm_s are refreshed with it.
    stages = res.stats["stages"]
    assert stages["hbm_commit"] >= 0
    assert sum(stages.values()) <= res.stats["elapsed_s"] + 0.05
    assert res.stats["time_to_hbm_s"] == res.stats["elapsed_s"]
    want = _hf_tensors()
    assert set(res.params) == set(want)


def test_expert_round_multiprocess_maps_slots_not_process_index(
    hub, tmp_path, ckpt, monkeypatch
):
    """Under multi-process, expert units route by the mesh slots this
    process's devices occupy (PodDistributor.local_slots), not by
    process_index — one process normally drives several slots, and the
    old equation silently starved every slot but one."""
    import jax

    bridge = _bridge(hub, tmp_path)
    rec = _rec(hub)
    placement = ExpertPlacement(CFG.n_experts, num_hosts=8)
    header = fetch_file_header(bridge, rec)
    fm = classify_file(rec, header, moe.expert_of_tensor)

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    stats = expert_pod_round(bridge, [fm], placement)
    # This process addresses every slot's devices, so it must fetch every
    # host's expert units — with the process_index mapping only host 0's
    # units would have been fetched.
    from zest_tpu.parallel.expert import ExpertRoutedPlan

    routed = ExpertRoutedPlan.build([fm], placement)
    want = sum(len(u) for u in routed.expert_units.values())
    assert len(routed.expert_units) > 1  # units spread over several hosts
    assert stats["expert_units_fetched"] == want


def test_expert_round_plus_direct_landing_end_to_end(hub, tmp_path, ckpt):
    """The flagship config #4 flow: header prefetch → expert-routed round
    → direct landing into a {data, expert} mesh → train step."""
    import jax

    bridge = _bridge(hub, tmp_path)
    rec = _rec(hub)
    placement = ExpertPlacement(CFG.n_experts, num_hosts=8)
    header = fetch_file_header(bridge, rec)
    fm = classify_file(rec, header, moe.expert_of_tensor)
    stats = expert_pod_round(bridge, [fm], placement)
    assert stats["expert_units_fetched"] > 0
    assert stats["expert_units_failed"] == 0
    assert stats["ici_bytes_saved"] > 0

    mesh = model_mesh({"data": 2, "expert": 4})
    params = land_moe_expert_sharded(
        bridge.cache, [(rec, header)], CFG, mesh,
        ExpertPlacement(CFG.n_experts, num_hosts=4),
    )
    # expert leaves really are sharded over the expert axis
    w1 = params["blocks"]["moe"]["w1"]
    assert w1.sharding.spec[1] == "expert"
    # bit-exact against the original checkpoint
    want = moe.params_from_hf(_hf_tensors(), CFG)
    np.testing.assert_array_equal(np.asarray(w1), np.asarray(
        want["blocks"]["moe"]["w1"]
    ))
    # no reassembled safetensors anywhere under the caches
    root = tmp_path
    stray = [p for p in root.rglob("*.safetensors")
             if "zest" in str(p) or "hf" in str(p)]
    assert not stray, stray

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    batch = jax.device_put(
        jnp.zeros((4, 9), jnp.int32), NamedSharding(mesh, P("data"))
    )
    with mesh:
        _new, loss = jax.jit(
            lambda p, b: moe.train_step(p, b, CFG)
        )(params, batch)
    assert np.isfinite(float(loss))


def test_expert_round_mismatched_placement_raises(hub, tmp_path, ckpt):
    bridge = _bridge(hub, tmp_path)
    rec = _rec(hub)
    pod_round(bridge, [rec])
    header = parse_header(ckpt)
    with pytest.raises(DirectLandingError, match="experts"):
        land_moe_expert_sharded(
            bridge.cache, [(rec, header)], CFG,
            model_mesh({"data": 2, "expert": 4}),
            ExpertPlacement(n_experts=16, num_hosts=4),
        )


def test_read_into_matches_read(hub, tmp_path, ckpt):
    """read_into is the one-copy primitive under land_tensors: byte-equal
    to read() across term boundaries, and strict about buffer size."""
    bridge = _bridge(hub, tmp_path)
    rec = _rec(hub)
    pod_round(bridge, [rec])
    reader = CachedFileReader(bridge.cache, rec)
    for lo, hi in [(0, 100), (0, len(ckpt)), (131_000, 197_123),
                   (len(ckpt) - 10, len(ckpt)), (5000, 5000)]:
        buf = bytearray(hi - lo)
        n = reader.read_into(lo, hi, memoryview(buf))
        assert n == hi - lo
        assert bytes(buf) == ckpt[lo:hi], (lo, hi)
    with pytest.raises(DirectLandingError, match="out buffer"):
        reader.read_into(0, 100, memoryview(bytearray(99)))
    with pytest.raises(DirectLandingError):
        reader.read_into(0, len(ckpt) + 1,
                         memoryview(bytearray(len(ckpt) + 1)))
