"""Collective-native coop exchange (transfer.collective; ISSUE 14).

Covers the ISSUE-14 acceptance surface:

- schedule/matrix determinism: every host derives the same phase
  schedule and N×N byte matrix purely from the plan (rec reorder and
  repeated builds agree), every foreign unit is requested exactly once
  per host, and per-owner received bytes equal the plan's ownership
  rows — including under quarantine re-shard;
- topology awareness: ``ZEST_COOP_TOPOLOGY`` slice ids class each
  phase link ici (intra-slice) vs dcn (cross-slice), strictly parsed;
- the round end-to-end over real loopback DCN sockets at hypercube
  (4, 8 hosts) and ring (3 hosts) shapes: fully cached everywhere,
  compressed frames on the wire, zero per-unit round trips (wire-tag
  counters), byte-identical reconstruction;
- degradation: a dead host mid-phase aborts the collective into the
  point-to-point ladder (the round still completes everywhere), and a
  corrupt frame is rejected at the receive-side verify boundary then
  healed;
- ``ZEST_COOP_COLLECTIVE=0`` schema equality with the PR-6 exchange;
- the exchange stats ledger: tier attribution exactly tiles delivered
  bytes, including the mid-round re-delivery race (ISSUE 14 satellite).
"""

from __future__ import annotations

import socket
import threading
import time

import numpy as np
import pytest

from fixtures import FixtureHub, FixtureRepo

from zest_tpu import faults
from zest_tpu.cas.hub import HubClient
from zest_tpu.config import Config, parse_topology
from zest_tpu.transfer.collective import (
    CollectiveSchedule,
    CollectiveUnavailable,
    matrix_skew,
    slice_topology,
    transfer_matrix,
    units_by_owner,
)
from zest_tpu.transfer.coop import (
    CoopPlan,
    _collect_clock_offsets,
    _ExchangeStats,
    coop_round,
)
from zest_tpu.transfer.dcn import DcnPool, DcnServer

REPO_ID = "acme/collective-model"

# Compressible payload: the compressed-through-the-collective evidence
# (wire < unpacked) must be visible, as on real checkpoints.
_PAYLOAD = np.random.default_rng(7).integers(
    0, 4, 1_500_000, dtype=np.uint8).tobytes()
FILES = {
    "config.json": b'{"model_type": "collective"}',
    "model.safetensors": _PAYLOAD,
}


@pytest.fixture(scope="module")
def hub():
    repo = FixtureRepo(REPO_ID, FILES, chunks_per_xorb=2)
    with FixtureHub(repo) as h:
        yield h


@pytest.fixture(autouse=True)
def _no_faults():
    faults.reset()
    yield
    faults.reset()


def _bridge(hub, root, collective=True):
    from zest_tpu.transfer.bridge import XetBridge

    cfg = Config(hf_home=root / "hf", cache_dir=root / "zest",
                 hf_token="hf_test", endpoint=hub.url, dcn_port=0,
                 coop_collective=collective)
    b = XetBridge(cfg)
    b.authenticate(REPO_ID)
    return b


def _recs(bridge):
    return [bridge.get_reconstruction(e.xet_hash)
            for e in HubClient(bridge.cfg).list_files(REPO_ID)
            if e.is_xet]


def _run_hosts(hub, tmp_path, n, round_kwargs=None, skip=(),
               collective=True, pools=None):
    """n concurrent in-process hosts (own cache + DCN server each);
    ``pools`` maps host index → an injected DcnPool whose wire-tag
    counters the test inspects afterwards."""
    bridges, servers, addrs = [], [], {}
    for i in range(n):
        b = _bridge(hub, tmp_path / f"h{i}", collective=collective)
        bridges.append(b)
        if i in skip:
            addrs[i] = ("127.0.0.1", 1)  # nothing listens
            servers.append(None)
        else:
            s = DcnServer(b.cfg, b.cache)
            addrs[i] = ("127.0.0.1", s.start())
            servers.append(s)
    results: list = [None] * n
    errors: list = []

    def run(i):
        try:
            kwargs = dict(round_kwargs or {})
            if pools and i in pools:
                kwargs["dcn_pool"] = pools[i]
            results[i] = coop_round(bridges[i], _recs(bridges[i]), i, n,
                                    addrs, server=servers[i], **kwargs)
        except Exception as exc:  # noqa: BLE001 - surfaced below
            errors.append((i, exc))

    threads = [threading.Thread(target=run, args=(i,), daemon=True)
               for i in range(n) if i not in skip]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for s in servers:
        if s is not None:
            s.shutdown()
    assert not errors, errors
    return bridges, results


def _assert_fully_cached(bridge, root):
    """Every xet file reconstructs byte-exactly with zero CDN traffic —
    the params-identity proof at cache level (the TPU-landed digest
    identity rides the same bytes; coop_smoke pins it end-to-end)."""
    before = bridge.stats.bytes_from_cdn
    for e in HubClient(bridge.cfg).list_files(REPO_ID):
        if e.is_xet:
            out = root / "check.bin"
            bridge.reconstruct_to_file(e.xet_hash, out)
            assert out.read_bytes() == FILES[e.path]
    assert bridge.stats.bytes_from_cdn == before, \
        "reconstruction hit CDN: cache incomplete after the round"


def _requested_keys(plan, host, topology):
    """Unit keys host ``host`` requests across its whole schedule."""
    sched = CollectiveSchedule.build(plan, host, topology)
    blocks = units_by_owner(plan)
    keys = []
    for ph in sched.phases:
        for o in ph.owners:
            keys.extend((hh, fi.range.start) for hh, fi in blocks[o])
    return keys


# ── Schedule + matrix determinism ──


@pytest.mark.parametrize("n", [2, 3, 4, 6, 8])
def test_every_unit_requested_exactly_once(hub, tmp_path, n):
    """Per host: the union of phase request sets is exactly the foreign
    unit set, each unit once — the "every byte sent exactly once"
    invariant, and per-owner received bytes therefore equal the plan's
    ownership rows by construction."""
    b = _bridge(hub, tmp_path)
    plan = CoopPlan.build(_recs(b), n)
    topo = (0,) * n
    for host in plan.alive:
        keys = _requested_keys(plan, host, topo)
        foreign = sorted(k for k, _fi in plan.units
                         if plan.owners[k] != host)
        assert sorted(keys) == foreign
        assert len(keys) == len(set(keys))


def test_matrix_deterministic_under_rec_reorder(hub, tmp_path):
    b = _bridge(hub, tmp_path)
    recs = _recs(b)
    topo = (0, 0, 1, 1)
    m1 = transfer_matrix(CoopPlan.build(recs, 4), topo)
    m2 = transfer_matrix(CoopPlan.build(list(reversed(recs)), 4), topo)
    assert m1 == m2
    assert len(m1) == 4 and all(len(row) == 4 for row in m1)
    assert all(m1[h][h] == 0 for h in range(4)), "no self-traffic"
    assert matrix_skew(m1) >= 1.0


def test_matrix_quarantine_reshard(hub, tmp_path):
    """A quarantined host leaves the schedule entirely (zero row AND
    column) and every unit is still requested exactly once by every
    alive host — the re-shard covers 100% of the plan."""
    b = _bridge(hub, tmp_path)
    recs = _recs(b)
    plan = CoopPlan.build(recs, 4, quarantined={2})
    topo = (0,) * 4
    m = transfer_matrix(plan, topo)
    assert all(v == 0 for v in m[2]), "quarantined host sends nothing"
    assert all(row[2] == 0 for row in m), "nobody sends to it"
    for host in plan.alive:
        keys = _requested_keys(plan, host, topo)
        foreign = sorted(k for k, _fi in plan.units
                         if plan.owners[k] != host)
        assert sorted(keys) == foreign
    # kind flips to ring at 3 alive hosts (not a power of two)
    assert CollectiveSchedule.build(plan, 0, topo).kind == "ring"


def test_schedule_shapes_and_links(hub, tmp_path):
    b = _bridge(hub, tmp_path)
    plan = CoopPlan.build(_recs(b), 4)
    # flat topology → plain hypercube
    s_flat = CollectiveSchedule.build(plan, 0, (0,) * 4)
    assert s_flat.kind == "hypercube"
    assert len(s_flat.phases) == 2
    assert all(ph.link == "ici" for ph in s_flat.phases)
    # 2 slices x 2 hosts → hierarchical: one cross-slice counterpart
    # phase (DCN), then one intra-slice spread phase (ICI)
    topo = (0, 0, 1, 1)
    s0 = CollectiveSchedule.build(plan, 0, topo)
    assert s0.kind == "hierarchical"
    assert len(s0.phases) == 2
    assert s0.phases[0].partner == 2 and s0.phases[0].link == "dcn"
    assert s0.phases[0].owners == (2,), \
        "cross phase imports only the counterpart's OWN block"
    assert s0.phases[1].partner == 1 and s0.phases[1].link == "ici"
    assert sorted(s0.phases[1].owners) == [1, 3], \
        "intra phase spreads the partner's whole counterpart group"
    s_ring = CollectiveSchedule.build(CoopPlan.build(_recs(b), 3), 1,
                                      (0, 0, 0))
    assert s_ring.kind == "ring"
    assert len(s_ring.phases) == 2
    assert all(ph.partner == 0 for ph in s_ring.phases), \
        "ring pulls from the constant left neighbor"


def test_hierarchical_schedule_minimizes_cross_slice_bytes(hub,
                                                           tmp_path):
    """The topology preference rule in byte form: at 2 slices x 4
    hosts, a host's cross-slice (DCN-class) receive bytes are ~1/7 of
    its foreign bytes (its counterpart's block only) — vs 4/7 for the
    flat point-to-point/hypercube exchange — and the aggregate DCN
    traffic is ONE copy of each slice's data."""
    b = _bridge(hub, tmp_path)
    plan = CoopPlan.build(_recs(b), 8)
    topo = (0, 0, 0, 0, 1, 1, 1, 1)
    blocks = units_by_owner(plan)
    bb = {h: sum(fi.url_range_end - fi.url_range_start
                 for _hh, fi in us) for h, us in blocks.items()}
    total = sum(bb.values())
    for host in plan.alive:
        sched = CollectiveSchedule.build(plan, host, topo)
        assert sched.kind == "hierarchical"
        assert len(sched.phases) == 3  # 1 cross + 2 intra
        dcn = sum(bb[o] for ph in sched.phases if ph.link == "dcn"
                  for o in ph.owners)
        counterpart = sched.phases[0].owners[0]
        assert dcn == bb[counterpart], \
            "cross-slice receive = exactly the counterpart's block"
        assert dcn < total / 4
        # every foreign unit still arrives exactly once
        keys = _requested_keys(plan, host, topo)
        foreign = sorted(k for k, _fi in plan.units
                        if plan.owners[k] != host)
        assert sorted(keys) == foreign
    m = transfer_matrix(plan, topo)
    cross = sum(m[s][d] for s in range(8) for d in range(8)
                if topo[s] != topo[d])
    assert cross == total, \
        "aggregate DCN traffic is one copy of each slice's data"


def test_schedule_unavailable_cases(hub, tmp_path):
    b = _bridge(hub, tmp_path)
    plan = CoopPlan.build(_recs(b), 4, quarantined={1, 2, 3})
    with pytest.raises(CollectiveUnavailable):
        CollectiveSchedule.build(plan, 0, (0,) * 4)  # alone
    with pytest.raises(CollectiveUnavailable):
        CollectiveSchedule.build(CoopPlan.build(_recs(b), 4), 9,
                                 (0,) * 4)  # not in the plan


# ── Topology resolution (strict knobs) ──


def test_topology_env_override_and_strictness():
    assert slice_topology(4, env={"ZEST_COOP_TOPOLOGY": "0,0,1,1"}) \
        == (0, 0, 1, 1)
    assert slice_topology(3, env={}) == (0, 0, 0)  # flat default
    with pytest.raises(ValueError):
        slice_topology(4, env={"ZEST_COOP_TOPOLOGY": "0,0,nope,1"})
    with pytest.raises(ValueError):
        # length disagreement is a config error, not a guess
        slice_topology(4, env={"ZEST_COOP_TOPOLOGY": "0,0,1"})
    cfg = Config(hf_home="/tmp/x", cache_dir="/tmp/y",
                 coop_topology=(0, 1))
    assert slice_topology(2, cfg=cfg, env={}) == (0, 1)
    with pytest.raises(ValueError):
        parse_topology("0,-1")
    with pytest.raises(ValueError):
        parse_topology("")


def test_config_collective_env_parsing():
    base = {"HF_HOME": "/tmp/x", "ZEST_CACHE_DIR": "/tmp/y"}
    cfg = Config.load(base)
    assert cfg.coop_collective is True and cfg.coop_topology is None
    off = Config.load({**base, "ZEST_COOP_COLLECTIVE": "0"})
    assert off.coop_collective is False
    topo = Config.load({**base, "ZEST_COOP_TOPOLOGY": "0, 0, 1, 1"})
    assert topo.coop_topology == (0, 0, 1, 1)
    for bad in ("false", "yes", "2", " "):
        with pytest.raises(ValueError):
            Config.load({**base, "ZEST_COOP_COLLECTIVE": bad})
    with pytest.raises(ValueError):
        Config.load({**base, "ZEST_COOP_TOPOLOGY": "a,b"})


# ── The round, end to end ──


@pytest.mark.parametrize("n,kind,phases", [(3, "ring", 2),
                                           (4, "hypercube", 2)])
def test_collective_round_end_to_end(hub, tmp_path, n, kind, phases):
    pools = {i: DcnPool() for i in range(n)}
    try:
        bridges, results = _run_hosts(hub, tmp_path, n, pools=pools)
        for i, (b, r) in enumerate(zip(bridges, results)):
            cx = r.get("collective")
            assert cx, r
            assert cx["schedule"] == kind
            assert cx["phases"] == phases
            assert len(cx["phase_wall_s"]) == phases
            assert "aborted" not in cx, cx
            assert cx["unit_round_trips"] == 0
            assert r["fallbacks"] == 0, r
            assert r["exchange"]["units"] > 0
            assert 0 < r["exchange"]["wire_bytes"] \
                < r["exchange"]["unpacked_bytes"]
            assert sum(cx["link_bytes"].values()) \
                == r["exchange"]["wire_bytes"], \
                "link-class bytes must tile the exchange wire"
            assert r["peer_served_ratio"] >= 0.6, r
            _assert_fully_cached(b, tmp_path / f"h{i}")
        # Zero per-unit request round trips: every window the round's
        # pool sent carried a wire tag (the batched-window shape), and
        # the healthy path needed no more windows than phases plus
        # barrier retries.
        for i, pool in pools.items():
            if results[i] is None:
                continue
            c = pool.counters
            assert c["untagged_windows"] == 0, (i, c)
            assert c["windows"] == c["tagged_windows"]
            cx = results[i]["collective"]
            assert c["windows"] == cx["windows"], (i, c, cx)
            # <= not ==: a phase fully covered by earlier whole-xorb
            # admits issues zero windows; more windows than
            # phases + barrier retries would mean per-unit round
            # trips crept back.
            assert 0 < cx["windows"] \
                <= cx["phases"] + cx["retry_windows"], (i, cx)
        # disjoint fetch shares: ~1 copy total left the CDN
        total_cdn = sum(b.stats.bytes_from_cdn for b in bridges)
        assert total_cdn <= results[0]["plan"]["total_bytes"] * 1.05
    finally:
        for pool in pools.values():
            pool.close()


def test_collective_eight_host_hypercube(hub, tmp_path):
    bridges, results = _run_hosts(hub, tmp_path, 8)
    for i, (b, r) in enumerate(zip(bridges, results)):
        cx = r.get("collective")
        assert cx and cx["schedule"] == "hypercube"
        assert cx["phases"] == 3 and "aborted" not in cx, cx
        assert r["fallbacks"] == 0, r
        _assert_fully_cached(b, tmp_path / f"h{i}")


def test_collective_matches_p2p_and_solo_bytes(hub, tmp_path):
    """Identity across strategies: collective round, point-to-point
    round, and a solo full-waterfall warm all end with byte-identical
    reconstructions (the cache-level params_digest identity; the smoke
    pins the TPU-landed digest on top of the same bytes)."""
    from zest_tpu.transfer.federated import warm_units_parallel

    _bridges, _results = _run_hosts(hub, tmp_path / "cx", 2)
    _bridges2, _results2 = _run_hosts(hub, tmp_path / "p2p", 2,
                                      collective=False)
    solo = _bridge(hub, tmp_path / "solo")
    warm_units_parallel(solo, _recs(solo))
    _assert_fully_cached(solo, tmp_path / "solo")
    _assert_fully_cached(_bridges[0], tmp_path / "cx" / "h0")
    _assert_fully_cached(_bridges2[0], tmp_path / "p2p" / "h0")
    assert _results[0].get("collective")
    assert "collective" not in _results2[0]


def test_collective_dead_host_degrades_to_p2p_ladder(hub, tmp_path):
    """A dead partner mid-phase aborts the collective into the
    point-to-point exchange, which degrades the dead host's units to
    CDN — every live host still completes, and a live host may even
    receive the dead share FORWARDED by a peer that healed it first."""
    n = 4
    bridges, results = _run_hosts(hub, tmp_path, n, skip={3})
    aborted = [r for r in results if r and
               (r.get("collective") or {}).get("aborted")]
    assert aborted, "no host observed the dead partner"
    assert any(3 in (r["exchange"].get("dead_hosts") or [])
               for r in results if r)
    assert sum(r["fallbacks"] for r in results if r) > 0, \
        "the dead share never healed through the ladder"
    for i in range(3):
        _assert_fully_cached(bridges[i], tmp_path / f"h{i}")


def test_collective_corrupt_frame_rejected_and_healed(hub, tmp_path):
    """A byte-flipped frame crossing the collective fails the
    receive-side whole-xorb verification (the fused device pass on
    TPU), is never cached, and heals from CDN."""
    from zest_tpu.transfer.federated import warm_units_parallel

    b0 = _bridge(hub, tmp_path / "owner")
    recs0 = _recs(b0)
    plan = CoopPlan.build(recs0, 2)
    owned = plan.for_host(0)
    assert owned
    warm_units_parallel(b0, recs0, units=owned)
    hh, fi = owned[0]
    entry = b0.cache.get_with_range(hh, fi.range.start)
    bad = bytearray(entry.data)
    bad[len(bad) // 2] ^= 0xFF
    b0.cache.put(hh, bytes(bad))

    server = DcnServer(b0.cfg, b0.cache)
    port = server.start()
    try:
        b1 = _bridge(hub, tmp_path / "puller")
        r = coop_round(b1, _recs(b1), 1, 2, {0: ("127.0.0.1", port)})
        assert r.get("collective"), r
        assert r["exchange"]["verify_rejected"] >= 1, r
        assert r["fallbacks"] >= 1, r
        _assert_fully_cached(b1, tmp_path / "puller")
    finally:
        server.shutdown()


@pytest.mark.chaos
def test_collective_chaos_dcn_reset_mid_phase(hub, tmp_path):
    """An injected ``dcn_reset`` inside a phase window aborts the
    collective and the full ladder completes the round from CDN —
    counted, never a hang, never corruption."""
    faults.install("dcn_reset:1.0", seed=1337)
    bridges, results = _run_hosts(hub, tmp_path, 2)
    assert faults.counters().get("dcn_reset", 0) > 0
    for i, (b, r) in enumerate(zip(bridges, results)):
        assert (r.get("collective") or {}).get("aborted"), r
        assert r["fallbacks"] > 0, r
        assert r["exchange"]["units"] == 0, r
        _assert_fully_cached(b, tmp_path / f"h{i}")


# ── Knob-off schema equality (the PR-6 pin) ──


def test_knob_off_schema_identical_to_p2p(hub, tmp_path):
    """ZEST_COOP_COLLECTIVE=0: the round stats schema is byte-identical
    to the PR-6 point-to-point exchange — exact top-level and exchange
    key sets, no "collective" block anywhere."""
    _bridges, results = _run_hosts(hub, tmp_path, 2, collective=False)
    for r in results:
        assert set(r) == {"host", "hosts", "trace_id", "plan", "fetch",
                          "exchange", "fallbacks", "own_server",
                          "peer_served_ratio", "elapsed_s",
                          "clock_offsets"}, sorted(r)
        assert set(r["exchange"]) == {
            "units", "wire_bytes", "unpacked_bytes", "fallback_units",
            "fallback_bytes", "verify_rejected", "retries",
            "budget_bytes", "inflight_peak_bytes"}, sorted(r["exchange"])


# ── Exchange-stats ledger (ISSUE 14 satellite: exact tier tiling) ──


def test_exchange_ledger_tiles_on_redelivery():
    """A unit re-delivered by the fallback after the exchange already
    booked it (the mid-round eviction race) must REPLACE its booking:
    wire + fallback bytes tile the delivered total instead of
    double-counting the aborted delivery."""
    ex = _ExchangeStats()
    ex.book_exchange(("aa", 0), 100, 400)
    ex.book_exchange(("bb", 0), 50, 200, link="ici")
    assert (ex.units, ex.wire_bytes, ex.unpacked_bytes) == (2, 150, 600)
    # the race: unit aa evicted, fallback refetches it from CDN
    ex.book_fallback(("aa", 0), "cdn", 110)
    assert (ex.units, ex.wire_bytes, ex.unpacked_bytes) == (1, 50, 200)
    assert (ex.fallback_units, ex.fallback_bytes) == (1, 110)
    assert ex.fallback_tiers == {"cdn": 110}
    s = ex.summary()
    assert s["reattributed"] == 1
    assert s["wire_bytes"] + s["fallback_bytes"] == 50 + 110
    # and the other direction: an exchange delivery superseding a
    # fallback booking (a later phase re-serves an evicted unit)
    ex.book_exchange(("aa", 0), 100, 400)
    assert ex.fallback_tiers == {}
    assert (ex.fallback_units, ex.fallback_bytes) == (0, 0)
    assert ex.summary()["reattributed"] == 2
    assert ex.wire_bytes + ex.fallback_bytes == 150


def test_exchange_ledger_absent_without_race(hub, tmp_path):
    """Schema guard: healthy rounds never grow a "reattributed" key —
    the ledger is invisible unless the race actually happened."""
    _bridges, results = _run_hosts(hub, tmp_path, 2)
    for r in results:
        assert "reattributed" not in r["exchange"], r["exchange"]


# ── Clock-offset collection (ISSUE 14 satellite) ──


def test_clock_offsets_dial_undialed_peers_named_and_bounded(tmp_path):
    """Peers the exchange never opened a channel to get a hello dialed
    by named ``zest-coop-clk-*`` workers, joined under one bounded
    deadline — and a hung hello (a listener that never speaks) cannot
    hold the round past the bound."""
    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                 dcn_port=0)
    servers = [DcnServer(cfg), DcnServer(cfg)]
    peers = {i: ("127.0.0.1", s.start()) for i, s in enumerate(servers)}
    names: list[str] = []
    orig_init = threading.Thread.__init__

    def spy_init(self, *args, **kwargs):
        if str(kwargs.get("name", "")).startswith("zest-coop-clk-"):
            names.append(kwargs["name"])
        orig_init(self, *args, **kwargs)

    pool = DcnPool(timeout=5.0)
    out: dict = {}
    threading.Thread.__init__ = spy_init
    try:
        _collect_clock_offsets(pool, peers, out)
    finally:
        threading.Thread.__init__ = orig_init
        pool.close()
        for s in servers:
            s.shutdown()
    assert sorted(out) == [0, 1], out
    assert sorted(names) == ["zest-coop-clk-0", "zest-coop-clk-1"]
    for row in out.values():
        assert "offset_s" in row and "rtt_s" in row

    # hung hello: accepts the TCP connect but never answers the hello
    mute = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    mute.bind(("127.0.0.1", 0))
    mute.listen(1)
    try:
        pool2 = DcnPool(timeout=30.0)
        t0 = time.monotonic()
        out2: dict = {}
        _collect_clock_offsets(
            pool2, {0: ("127.0.0.1", mute.getsockname()[1])}, out2,
            timeout_s=0.5)
        assert time.monotonic() - t0 < 5.0, "hung hello held the round"
        assert out2 == {}
        pool2.close()
    finally:
        mute.close()


# ── Federated 3-level schedule (ISSUE 16 tentpole b) ──


def _fed_plan(hub, tmp_path, n):
    b = _bridge(hub, tmp_path / "fedplan")
    plan = CoopPlan.build(_recs(b), n)
    b.close()
    return plan


def _pod_maps(n, pod_size):
    pods = tuple(h // pod_size for h in range(n))
    topo = tuple(2 * (h // pod_size) + (h % pod_size >= pod_size // 2)
                 for h in range(n))
    return topo, pods


@pytest.mark.parametrize("n,pod_size", [(8, 4), (8, 2), (12, 4)])
def test_federated_coverage_exactly_once(hub, tmp_path, n, pod_size):
    """Every host's federated schedule requests exactly the foreign
    unit set, each unit once — across pow2 pods (hypercube stage B)
    and 3 pods (WAN ring over gateways)."""
    from zest_tpu.transfer.collective import elect_gateways

    plan = _fed_plan(hub, tmp_path, n)
    topo, pods = _pod_maps(n, pod_size)
    blocks = units_by_owner(plan)
    for h in plan.alive:
        sched = CollectiveSchedule.build(plan, h, topo, pods=pods)
        assert sched.kind == "federated"
        keys = []
        for ph in sched.phases:
            for o in ph.owners:
                keys.extend((hh, fi.range.start)
                            for hh, fi in blocks[o])
        want = sorted(k for k, _fi in plan.units
                      if plan.owners[k] != h)
        assert sorted(keys) == want, f"host {h} coverage broken"
    # Election is lowest alive index per pod.
    gws = elect_gateways(plan, pods)
    assert gws == {p: min(h for h in plan.alive if pods[h] == p)
                   for p in set(pods)}


def test_federated_wan_pairs_are_gateways_only(hub, tmp_path):
    """Cross-pod wire pairs occur ONLY between elected gateways, and
    aggregate WAN bytes equal one copy of each pod's data per
    receiving pod — the (P-1)/P-per-gateway property the ISSUE-16
    speedup gate rests on."""
    from zest_tpu.transfer.collective import elect_gateways

    n, pod_size = 8, 4
    plan = _fed_plan(hub, tmp_path, n)
    topo, pods = _pod_maps(n, pod_size)
    gws = set(elect_gateways(plan, pods).values())
    blocks = units_by_owner(plan)
    bb = {h: sum(fi.url_range_end - fi.url_range_start
                 for _hh, fi in us) for h, us in blocks.items()}
    wan_bytes = 0
    for h in plan.alive:
        sched = CollectiveSchedule.build(plan, h, topo, pods=pods)
        for ph in sched.phases:
            if pods[h] != pods[ph.partner]:
                assert ph.link == "wan"
                assert h in gws and ph.partner in gws, \
                    f"non-gateway WAN pair {h}<-{ph.partner}"
                wan_bytes += sum(bb[o] for o in ph.owners)
    n_pods = len(set(pods))
    total = plan.total_bytes
    assert wan_bytes == (n_pods - 1) * total


def test_federated_gateway_reelection_on_quarantine(hub, tmp_path):
    """A quarantined gateway is absent from plan.alive, so the
    next-lowest pod member inherits deterministically — and the
    schedule still covers every unit exactly once."""
    from zest_tpu.transfer.collective import elect_gateways

    n = 8
    plan = _fed_plan(hub, tmp_path, n)
    topo, pods = _pod_maps(n, 4)
    b = _bridge(hub, tmp_path / "fedq")
    plan_q = CoopPlan.build(_recs(b), n, quarantined=frozenset({4}))
    b.close()
    assert elect_gateways(plan, pods) == {0: 0, 1: 4}
    assert elect_gateways(plan_q, pods) == {0: 0, 1: 5}
    blocks = units_by_owner(plan_q)
    for h in plan_q.alive:
        sched = CollectiveSchedule.build(plan_q, h, topo, pods=pods)
        keys = []
        for ph in sched.phases:
            assert ph.partner != 4, "schedule dials the quarantined host"
            for o in ph.owners:
                keys.extend((hh, fi.range.start)
                            for hh, fi in blocks[o])
        want = sorted(k for k, _fi in plan_q.units
                      if plan_q.owners[k] != h)
        assert sorted(keys) == want


def test_pods_env_and_single_pod_degenerate(hub, tmp_path):
    """ZEST_COOP_PODS resolution (env > cfg > None), strict length
    check, and the single-pod degenerate: a pod map naming one pod
    yields the pre-federation schedule bit-for-bit."""
    from zest_tpu.transfer.collective import pod_topology

    assert pod_topology(4) is None
    assert pod_topology(4, env={"ZEST_COOP_PODS": "0,0,1,1"}) == \
        (0, 0, 1, 1)
    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                 coop_pods=(0, 1))
    assert pod_topology(2, cfg=cfg) == (0, 1)
    with pytest.raises(ValueError):
        pod_topology(3, env={"ZEST_COOP_PODS": "0,1"})

    plan = _fed_plan(hub, tmp_path, 4)
    topo = (0, 0, 1, 1)
    for h in plan.alive:
        base = CollectiveSchedule.build(plan, h, topo)
        one_pod = CollectiveSchedule.build(plan, h, topo,
                                           pods=(0, 0, 0, 0))
        assert one_pod == base


def test_federated_round_end_to_end(hub, tmp_path):
    """4 live hosts, 2 pods x 2 over real loopback DCN: every round
    takes the federated schedule, completes with zero fallbacks, and
    the link ledger carries the pinned 'wan' key (schema: present iff
    a pod map is configured)."""
    from zest_tpu.transfer.bridge import XetBridge

    n, pods = 4, (0, 0, 1, 1)
    bridges, servers, addrs = [], [], {}
    for i in range(n):
        cfg = Config(hf_home=tmp_path / f"fed{i}/hf",
                     cache_dir=tmp_path / f"fed{i}/zest",
                     hf_token="hf_test", endpoint=hub.url, dcn_port=0,
                     coop_pods=pods, coop_topology=pods)
        b = XetBridge(cfg)
        b.authenticate(REPO_ID)
        s = DcnServer(b.cfg, b.cache)
        addrs[i] = ("127.0.0.1", s.start())
        bridges.append(b)
        servers.append(s)
    results: list = [None] * n
    errors: list = []

    def run(i):
        try:
            results[i] = coop_round(bridges[i], _recs(bridges[i]), i,
                                    n, addrs, server=servers[i])
        except Exception as exc:  # noqa: BLE001 - surfaced below
            errors.append((i, exc))

    threads = [threading.Thread(target=run, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for s in servers:
        s.shutdown()
    assert not errors, errors
    for i, r in enumerate(results):
        cx = r["collective"]
        assert cx["schedule"] == "federated", cx
        assert not cx.get("aborted"), cx
        assert r["fallbacks"] == 0, r
        assert "wan" in cx["link_bytes"], cx
        _assert_fully_cached(bridges[i], tmp_path / f"fed{i}")
    # Cross-pod bytes actually crossed: the two gateways (0 and 2)
    # carry WAN traffic; non-gateways carry none.
    assert any(r["collective"]["link_bytes"]["wan"] > 0
               for r in results), results
    for i in (1, 3):
        assert results[i]["collective"]["link_bytes"]["wan"] == 0, \
            results[i]["collective"]
    for b in bridges:
        b.close()
