"""Telemetry subsystem tests (ISSUE 4).

Covers the three tentpole pieces and the test satellites:

- span tracer: nesting, export round-trip (the JSON loads and the
  child's interval sits inside the parent's, same thread track);
- metrics registry: Prometheus text schema incl. label escaping,
  served live over ``GET /v1/metrics``;
- the knob-off contract: ``ZEST_TELEMETRY=0`` leaves pulled bytes and
  the stats schema identical, and records zero spans;
- the allowlisted-counter merge warning (satellite 3) and the
  ``stats["faults"]`` exposure (satellite 1).
"""

import json
import re
import threading

import pytest

from zest_tpu import faults, telemetry
from zest_tpu.telemetry import metrics as metrics_mod, trace as trace_mod
from zest_tpu.transfer.pull import pull_model

from fixtures import FixtureHub, FixtureRepo


@pytest.fixture(autouse=True)
def clean_telemetry():
    """Each test gets a zeroed registry, no tracer, env-free enable
    flag — and leaves the process the same way (other test modules
    share the process-global registry)."""
    telemetry.REGISTRY.reset()
    trace_mod.uninstall()
    telemetry.set_enabled(None)
    faults.reset()
    yield
    telemetry.REGISTRY.reset()
    trace_mod.uninstall()
    telemetry.set_enabled(None)
    faults.reset()


# ── Span tracer ──


class TestTracer:
    def test_nested_spans_record_containment(self):
        tracer = trace_mod.install(None)
        with telemetry.span("outer", k="v"):
            with telemetry.span("inner") as sp:
                sp.add_bytes(100)
                sp.add_bytes(28)
        spans = {s.name: s for s in tracer.spans()}
        assert set(spans) == {"outer", "inner"}
        inner, outer = spans["inner"], spans["outer"]
        assert outer.t0 <= inner.t0 and inner.t1 <= outer.t1
        assert inner.tid == outer.tid == threading.get_ident()
        assert inner.attrs["bytes"] == 128
        assert outer.attrs == {"k": "v"}

    def test_exception_tags_error_class_only(self):
        tracer = trace_mod.install(None)
        with pytest.raises(ValueError):
            with telemetry.span("boom"):
                raise ValueError("secret path /etc/passwd")
        (span,) = tracer.spans()
        assert span.attrs["error"] == "ValueError"
        assert "passwd" not in json.dumps(tracer.to_chrome())

    def test_export_round_trip_loads_and_nests(self, tmp_path):
        tracer = trace_mod.install(None)
        with telemetry.span("pull", repo="acme/model"):
            with telemetry.span("stage.fetch"):
                pass
            with telemetry.span("stage.hbm_commit"):
                pass
        out = tmp_path / "trace.json"
        n = tracer.export(out)
        assert n == 3
        doc = json.loads(out.read_text())
        events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in events} == \
            {"pull", "stage.fetch", "stage.hbm_commit"}
        by_name = {e["name"]: e for e in events}
        root = by_name["pull"]
        for child in ("stage.fetch", "stage.hbm_commit"):
            ev = by_name[child]
            assert ev["tid"] == root["tid"]
            assert root["ts"] <= ev["ts"]
            assert ev["ts"] + ev["dur"] <= root["ts"] + root["dur"] + 1e-6
        assert root["args"] == {"repo": "acme/model"}
        # Metadata event marks the process track.
        assert any(e.get("ph") == "M" for e in doc["traceEvents"])

    def test_export_is_atomic_and_idempotent(self, tmp_path):
        tracer = trace_mod.install(None)
        with telemetry.span("a"):
            pass
        out = tmp_path / "t.json"
        tracer.export(out)
        first = out.read_text()
        tracer.export(out)
        assert json.loads(out.read_text()) == json.loads(first)
        assert not list(tmp_path.glob("*.tmp.*"))

    def test_coverage_unions_overlapping_spans(self):
        tracer = trace_mod.install(None)
        s1 = tracer.span("a")
        s1.t0, s1.t1 = 0.0, 1.0
        tracer._record(s1)
        s2 = tracer.span("b")
        s2.t0, s2.t1 = 0.5, 2.0
        tracer._record(s2)
        assert tracer.coverage_s() == pytest.approx(2.0)
        assert tracer.coverage_s(prefix="a") == pytest.approx(1.0)

    def test_span_cap_counts_drops(self):
        tracer = trace_mod.install(None)
        old = trace_mod.MAX_SPANS
        trace_mod.MAX_SPANS = 2
        try:
            for _ in range(4):
                with telemetry.span("x"):
                    pass
        finally:
            trace_mod.MAX_SPANS = old
        assert len(tracer) == 2
        assert tracer.to_chrome()["otherData"]["dropped_spans"] == 2

    def test_env_arms_tracer_lazily(self, monkeypatch, tmp_path):
        out = tmp_path / "env-trace.json"
        monkeypatch.setenv("ZEST_TRACE", str(out))
        trace_mod.reset()
        try:
            with telemetry.span("via-env"):
                pass
            tracer = trace_mod.active()
            assert tracer is not None and len(tracer) == 1
            assert trace_mod.trace_path() == str(out)
        finally:
            trace_mod.uninstall()

    def test_no_tracer_means_null_span(self):
        # autouse fixture uninstalled the tracer: shared no-op object.
        sp = telemetry.span("anything", k=1)
        assert sp is telemetry.NULL_SPAN


# ── Metrics registry + Prometheus exposition ──

# One sample line: name{labels} value  |  name value
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})? (-?[0-9.e+-]+|\+Inf|NaN)$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_prometheus(text: str) -> dict:
    """Minimal exposition-format parser: {name: {labeltuple: value}}.
    Raises on any malformed line — the schema test's teeth."""
    out: dict = {}
    types: dict = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert kind in ("counter", "gauge", "histogram", "untyped")
            types[name] = kind
            continue
        assert not line.startswith("#"), f"unknown comment: {line!r}"
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name, _, labelstr, value = m.groups()
        labels = {}
        if labelstr:
            consumed = _LABEL_RE.sub("", labelstr).strip(", ")
            assert not consumed, f"malformed labels: {labelstr!r}"
            for lm in _LABEL_RE.finditer(labelstr):
                raw = lm.group(2)
                labels[lm.group(1)] = (
                    raw.replace("\\n", "\n").replace('\\"', '"')
                    .replace("\\\\", "\\"))
        out.setdefault(name, {})[tuple(sorted(labels.items()))] = \
            float(value) if value not in ("+Inf", "NaN") else value
    return out


class TestMetrics:
    def test_counter_gauge_histogram_render_and_parse(self):
        telemetry.counter("t_requests_total", "reqs", ("source",)) \
            .inc(3, source="cdn")
        telemetry.gauge("t_occupancy_bytes", "occ").set(12.5)
        h = telemetry.histogram("t_latency_seconds", "lat", ("op",),
                                buckets=(0.1, 1.0))
        h.observe(0.05, op="get")
        h.observe(2.0, op="get")
        parsed = _parse_prometheus(telemetry.render_prometheus())
        assert parsed["t_requests_total"][(("source", "cdn"),)] == 3
        assert parsed["t_occupancy_bytes"][()] == 12.5
        key = (("le", "0.1"), ("op", "get"))
        assert parsed["t_latency_seconds_bucket"][key] == 1
        assert parsed["t_latency_seconds_count"][(("op", "get"),)] == 2
        assert parsed["t_latency_seconds_sum"][(("op", "get"),)] == \
            pytest.approx(2.05)

    def test_label_escaping_round_trips(self):
        nasty = 'a"b\\c\nd'
        telemetry.counter("t_nasty_total", "", ("path",)).inc(path=nasty)
        parsed = _parse_prometheus(telemetry.render_prometheus())
        assert parsed["t_nasty_total"][(("path", nasty),)] == 1

    def test_kind_conflict_fails_loud(self):
        telemetry.counter("t_conflict_total")
        with pytest.raises(telemetry.MetricError):
            telemetry.gauge("t_conflict_total")
        with pytest.raises(telemetry.MetricError):
            telemetry.counter("t_conflict_total", labelnames=("x",))

    def test_unknown_label_fails_loud(self):
        c = telemetry.counter("t_lbl_total", "", ("a",))
        with pytest.raises(telemetry.MetricError):
            c.inc(b=1)

    def test_disabled_ops_are_noops(self):
        c = telemetry.counter("t_off_total")
        telemetry.set_enabled(False)
        c.inc()
        telemetry.set_enabled(None)
        assert c.value() == 0

    def test_collector_runs_at_render_time(self):
        state = {"v": 1}
        telemetry.REGISTRY.add_collector(
            lambda reg: reg.gauge("t_live_gauge", "live").set(state["v"]))
        assert _parse_prometheus(
            telemetry.render_prometheus())["t_live_gauge"][()] == 1
        state["v"] = 7
        assert _parse_prometheus(
            telemetry.render_prometheus())["t_live_gauge"][()] == 7

    def test_sum_allowlisted_warns_once_and_counts(self):
        dicts = [{"units": 1, "rate": 0.5}, {"units": 2, "rate": 0.7}]
        with pytest.warns(RuntimeWarning, match="'rate'"):
            sums, unsummed = telemetry.sum_allowlisted(
                dicts, allow={"units"}, context="test.ctx")
        assert sums == {"units": 3} and unsummed == ["rate"]
        # Second merge of the same key: silent (one-time warning).
        import warnings as _w

        with _w.catch_warnings():
            _w.simplefilter("error")
            telemetry.sum_allowlisted(dicts, allow={"units"},
                                      context="test.ctx")
        c = telemetry.REGISTRY.counter("zest_unsummed_counter_keys_total",
                                       "", ("context", "key"))
        assert c.value(context="test.ctx", key="rate") == 1


# ── Export surfaces: /v1/metrics and /v1/status ──


@pytest.fixture
def api(tmp_config):
    from zest_tpu.api.http_api import HttpApi

    requests = pytest.importorskip("requests")
    tmp_config.http_port = 0
    a = HttpApi(tmp_config)
    port = a.start()
    yield requests, f"http://127.0.0.1:{port}"
    a.close()


class TestHttpSurfaces:
    def test_metrics_endpoint_serves_parseable_prometheus(self, api):
        requests, base = api
        telemetry.counter("t_http_total", "via http", ("q",)) \
            .inc(q='with"quote')
        r = requests.get(f"{base}/v1/metrics", timeout=5)
        assert r.status_code == 200
        assert r.headers["Content-Type"].startswith("text/plain")
        parsed = _parse_prometheus(r.text)
        assert parsed["t_http_total"][(("q", 'with"quote'),)] == 1

    def test_status_reports_telemetry_and_faults(self, api):
        requests, base = api
        faults.install("dcn_reset:1.0", seed=7)
        assert faults.fire("dcn_reset", key="x") is not None
        status = requests.get(f"{base}/v1/status", timeout=5).json()
        tele = status["telemetry"]
        assert tele["enabled"] is True and tele["trace_active"] is False
        assert status["faults"] == {"dcn_reset": 1}

    def test_status_exposes_peer_health_detail(self, tmp_config):
        from zest_tpu.api.http_api import HttpApi
        from zest_tpu.p2p.health import HealthRegistry
        from zest_tpu.transfer.swarm import SwarmDownloader

        swarm = SwarmDownloader(tmp_config, peer_sources=[],
                                health=HealthRegistry(
                                    strikes_to_quarantine=1))
        swarm.health.record_success(("10.0.0.1", 7001), rtt_s=0.05)
        swarm.health.record_failure(("10.0.0.2", 7002), kind="corrupt")
        api = HttpApi(tmp_config, swarm=swarm)
        try:
            payload = api.status_payload()
        finally:
            api.close()
        rows = {r["peer"]: r for r in payload["peers"]}
        assert rows["10.0.0.1:7001"]["ewma_rtt_ms"] == pytest.approx(50.0)
        assert rows["10.0.0.2:7002"]["corruptions"] == 1
        assert rows["10.0.0.2:7002"]["quarantined_for_s"] > 0
        assert payload["swarm"]["health"]["quarantine_events"] == 1

    def test_collector_removed_on_close(self, tmp_config):
        from zest_tpu.api.http_api import HttpApi

        before = len(telemetry.REGISTRY._collectors)
        a = HttpApi(tmp_config)
        assert len(telemetry.REGISTRY._collectors) == before + 1
        a.close()
        assert len(telemetry.REGISTRY._collectors) == before


# ── End-to-end: traced pull + the knob-off contract ──

FILES = {
    "config.json": b'{"model_type": "test"}',
    "model.safetensors": bytes(range(256)) * 2048,  # 512 KiB
    "tokenizer.json": b'{"tok": 1}' * 40,
}


@pytest.fixture(scope="module")
def hub():
    repo = FixtureRepo("acme/telemetry-model", FILES, chunks_per_xorb=3)
    with FixtureHub(repo) as h:
        yield h


def _cfg(hub, root):
    from zest_tpu.config import Config

    return Config(hf_home=root / "hf", cache_dir=root / "zest",
                  hf_token="hf_test", endpoint=hub.url)


def _schema(obj):
    """Nested key structure (values stripped) for schema comparison."""
    if isinstance(obj, dict):
        return {k: _schema(v) for k, v in sorted(obj.items())}
    if isinstance(obj, list):
        return ["list"]
    return type(obj).__name__


class TestPullTelemetry:
    def test_traced_pull_covers_wall_time(self, hub, tmp_path):
        tracer = trace_mod.install(None)
        result = pull_model(_cfg(hub, tmp_path), "acme/telemetry-model",
                            no_p2p=True)
        names = {s.name for s in tracer.spans()}
        # The root span plus per-stage and per-tier spans all recorded.
        assert "pull" in names
        assert any(n.startswith("stage.") for n in names)
        assert any(n.startswith("fetch.") or n.startswith("cdn.")
                   for n in names)
        # Acceptance shape: span coverage ~= the pull's whole wall time
        # (the root span guarantees it; 90% is the criterion's floor).
        assert tracer.coverage_s() >= 0.9 * result.stats["elapsed_s"]
        out = tmp_path / "pull-trace.json"
        tracer.export(out)
        doc = json.loads(out.read_text())
        assert len([e for e in doc["traceEvents"] if e["ph"] == "X"]) \
            == len(tracer.spans())
        # Registry mirrored the session stats: CDN bytes flowed.
        assert telemetry.REGISTRY.counter(
            "zest_fetch_bytes_total", "", ("source",)).value(source="cdn") \
            == result.stats["fetch"]["bytes"]["cdn"]
        assert telemetry.REGISTRY.counter(
            "zest_pulls_total", "", ("outcome",)).value(outcome="ok") == 1

    def test_knob_off_pull_is_byte_identical_and_spanless(
            self, hub, tmp_path):
        # ON: default enablement, tracer armed.
        tracer = trace_mod.install(None)
        on = pull_model(_cfg(hub, tmp_path / "on"), "acme/telemetry-model",
                        no_p2p=True)
        assert len(tracer) > 0
        # OFF: ZEST_TELEMETRY=0 semantics via the test override.
        trace_mod.uninstall()
        tracer_off = trace_mod.install(None)
        telemetry.set_enabled(False)
        try:
            off = pull_model(_cfg(hub, tmp_path / "off"),
                             "acme/telemetry-model", no_p2p=True)
        finally:
            telemetry.set_enabled(None)
        # Hot-path behavior identical: same bytes on disk...
        for name, data in FILES.items():
            assert (on.snapshot_dir / name).read_bytes() == data
            assert (off.snapshot_dir / name).read_bytes() == data
        # ...same stats schema (keys and value types, not timings).
        # stats["critical_path"] is traced-only by design (ISSUE 11):
        # present on the armed pull, absent knob-off — strip it before
        # the comparison after asserting exactly that.
        assert "critical_path" in on.stats
        assert "critical_path" not in off.stats
        on_stats = {k: v for k, v in on.stats.items()
                    if k != "critical_path"}
        assert _schema(on_stats) == _schema(off.stats)
        assert off.stats["files_downloaded"] == on.stats["files_downloaded"]
        assert off.stats["fetch"]["bytes"] == on.stats["fetch"]["bytes"]
        # ...and the disabled pull recorded nothing.
        assert len(tracer_off) == 0

    def test_env_knob_disables_via_state(self, monkeypatch):
        monkeypatch.setenv("ZEST_TELEMETRY", "0")
        telemetry.set_enabled(None)  # force re-read
        assert telemetry.enabled() is False
        monkeypatch.setenv("ZEST_TELEMETRY", "1")
        telemetry.set_enabled(None)
        assert telemetry.enabled() is True

    def test_stage_clock_emits_stage_spans_with_identical_walls(self):
        from zest_tpu.transfer.pull import StageClock

        tracer = trace_mod.install(None)
        clock = StageClock()
        with clock("fetch"):
            pass
        with clock("fetch"):
            pass
        with clock("hbm_commit"):
            pass
        spans = [s for s in tracer.spans() if s.name.startswith("stage.")]
        assert sorted(s.name for s in spans) == \
            ["stage.fetch", "stage.fetch", "stage.hbm_commit"]
        # The adapter preserves the schema: summary keys and coverage
        # math are computed from the same intervals the spans show.
        summary = clock.summary()
        assert set(summary) == {"fetch", "hbm_commit"}
        fetch_spans = [s for s in spans if s.name == "stage.fetch"]
        # Tolerance = the summary's own rounding resolution (1e-4) plus
        # headroom for the clock interval enclosing the span's enter/
        # exit bookkeeping: near-zero stages can round up to 0.0001
        # while the raw span walls stay in the µs range.
        assert summary["fetch"] <= sum(s.t1 - s.t0 for s in fetch_spans) \
            + 1e-3

    def test_faults_fired_lands_in_pull_stats(self, hub, tmp_path):
        faults.install("dcn_reset:1.0", seed=3)
        assert faults.fire("dcn_reset", key="pod0") is not None
        result = pull_model(_cfg(hub, tmp_path), "acme/telemetry-model",
                            no_p2p=True)
        assert result.stats["faults"] == {"dcn_reset": 1}
        assert telemetry.REGISTRY.counter(
            "zest_faults_fired_total", "", ("fault",)
        ).value(fault="dcn_reset") == 1
