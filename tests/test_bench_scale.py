"""bench_scale: the GB-scale pull benchmark, exercised at MB scale.

The driver runs zest_tpu.bench_scale.bench_gb_pull at >=2 GB; these
tests pin its machinery (llama-geometry checkpoint generation, cold-run
isolation, stage medians, spread math) at a size the suite can afford,
so a driver-bench failure is a regression caught here, not a round lost.
"""

import json

import numpy as np

from zest_tpu.bench_scale import bench_gb_pull, llama_checkpoint_files


def test_llama_checkpoint_files_geometry():
    files = llama_checkpoint_files(0.03, shard_bytes=8 * 1024 * 1024,
                                   scale=8)
    cfg = json.loads(files["config.json"])
    assert cfg["model_type"] == "llama"
    assert cfg["hidden_size"] == 512  # scale=8 of the 8B geometry
    shards = [n for n in files if n.endswith(".safetensors")]
    # sharded naming once over one shard
    assert all("-of-" in n for n in shards) or len(shards) == 1
    total = sum(len(b) for b in files.values())
    # sized to order: within 2x of the request (1 layer minimum floors
    # small requests)
    assert total > 0.02e9
    # real tensor names — the landing registry must dispatch to llama
    from zest_tpu.models.safetensors_io import parse_header

    header = parse_header(files[sorted(shards)[0]])
    assert any("self_attn.q_proj" in n or "embed_tokens" in n
               for n in header.tensors)


def test_bench_gb_pull_small():
    """The full bench loop at 30 MB, 2 runs: stages present, spread
    computed, direct landing taken, throughput fields populated."""
    r = bench_gb_pull(gb=0.03, runs=2, chunks_per_xorb=64, scale=8)
    assert r["runs"] == 2
    assert r["time_to_hbm_s"] > 0
    assert r["pull_gbps"] > 0
    assert isinstance(r["stable"], bool) and "spread" in r
    for stage in ("resolve", "cas_metadata", "fetch", "hbm_commit",
                  "files"):
        assert stage in r["stages"], r["stages"]
    assert r["direct"] is True
    assert r["xorbs"] > 1
    # time_to_hbm is the pull's wall-clock to params-resident, so it is
    # bounded by the full pull wall. Stages may OVERLAP under the
    # pipelined pull (files ∥ hbm_commit), so their sum no longer
    # decomposes the wall — but each stage's union-coverage wall is
    # individually bounded by it, and busy >= wall per stage.
    assert r["time_to_hbm_s"] <= r["total_pull_s"] + 0.1
    for v in r["stages"].values():
        assert v["s"] <= r["total_pull_s"] * 1.1 + 0.1
        assert v["busy_s"] >= v["s"] - 0.05
    ov = r["overlap"]
    assert ov["files_hbm_span_s"] >= 0
    assert ov["overlap_s"] >= 0
    assert isinstance(ov["overlapped"], bool)
    assert len(r["time_to_hbm_runs_s"]) == 2
    assert np.isfinite(r["hbm_gbps"])


def test_bench_gb_pull_budget_trims_runs():
    """An exhausted budget still records exactly one timed run (never
    zero), skips the warmup when the fixture build already spent the
    budget, and refuses to call a single run stable."""
    r = bench_gb_pull(gb=0.03, runs=3, chunks_per_xorb=64, scale=8,
                      budget_s=0.01)
    assert r["runs"] == 1
    assert r["warmup_skipped"] is True
    assert r["stable"] is False
    assert r["time_to_hbm_s"] > 0
    # A generous budget keeps the warmup and all runs.
    r2 = bench_gb_pull(gb=0.03, runs=2, chunks_per_xorb=64, scale=8,
                       budget_s=600)
    assert r2["runs"] == 2
    assert r2["warmup_skipped"] is False


def test_bench_gb_pull_budget_dying_mid_warmup(monkeypatch):
    """Fast fixture build + slow pulls: when the budget dies DURING the
    warmup pull, the warmup is promoted to the one recorded run — the
    overshoot stays bounded at a single pull either way, and the output
    discloses it (runs=1, warmup_skipped=true, stable=false)."""
    import time as _time

    import zest_tpu.transfer.pull as pull_mod

    orig = pull_mod.pull_model
    calls = []

    def slow_pull(*args, **kwargs):
        calls.append(1)
        res = orig(*args, **kwargs)
        # Sleep LONGER than the whole budget: any single pull exhausts
        # it, so the budget provably dies during (or before) the warmup
        # no matter how fast or slow this host builds the fixture.
        _time.sleep(3.2)
        return res

    monkeypatch.setattr(pull_mod, "pull_model", slow_pull)
    r = bench_gb_pull(gb=0.005, runs=3, chunks_per_xorb=64, scale=8,
                      budget_s=3.0)
    assert r["runs"] == 1
    assert r["warmup_skipped"] is True
    assert r["stable"] is False
    # Bounded overshoot: exactly ONE pull ran — the promoted warmup (or
    # the single mandatory timed run if the build pre-skipped it) —
    # never warmup + timed. Counted, not wall-clocked: this shared host
    # swings 10x, so absolute-time assertions would be noise.
    assert len(calls) == 1
