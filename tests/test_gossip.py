"""Fleet gossip (transfer.gossip; ISSUE 16): digest CRDT semantics,
O(log N) anti-entropy convergence, bounded eviction, partition healing,
the DCN piggyback wire path, and the ZEST_GOSSIP=0 wiring gate.

The convergence sims are fully seeded (node RNGs are deterministic per
host index) so every run replays identically — a flaky O(log N) bound
would be worse than none.
"""

from __future__ import annotations

import math

import pytest

from zest_tpu.config import Config
from zest_tpu.transfer import gossip as gossip_mod
from zest_tpu.transfer.gossip import (
    COST_DCN,
    COST_ICI,
    COST_WAN,
    KIND_SEEDER,
    KIND_XORB,
    MAX_DELTA_ENTRIES,
    DcnGossipTransport,
    GossipDigest,
    GossipNode,
    LoopbackMesh,
    link_cost,
    node_from_config,
)


def _xh(i: int) -> bytes:
    return i.to_bytes(2, "big") * 16


def _fleet(n: int, **kw) -> tuple[LoopbackMesh, list[GossipNode]]:
    mesh = LoopbackMesh()
    book = {i: (f"host{i}", 7000 + i) for i in range(n)}
    nodes = [GossipNode(i, n, book, **kw) for i in range(n)]
    for node in nodes:
        mesh.register(node)
    return mesh, nodes


def _sweep(mesh: LoopbackMesh, nodes: list[GossipNode]) -> None:
    for node in nodes:
        node.tick(mesh)


# ── Convergence (satellite: N ∈ {16, 64, 256}) ──


@pytest.mark.parametrize("n", [16, 64, 256])
def test_all_to_all_convergence_within_log_rounds(n):
    """Every host announces its own xorb; the fleet must agree on all
    N entries within O(log N) sweeps. The bound is 2·⌈log2 N⌉ —
    generous for push-pull with fanout ⌈log2 N⌉, but still O(log N):
    a linear-round regression (e.g. fanout accidentally 1-directional
    or deltas dropped) blows through it immediately."""
    mesh, nodes = _fleet(n)
    for i, node in enumerate(nodes):
        node.announce(_xh(i), 7000 + i)
    bound = 2 * math.ceil(math.log2(n))
    rounds = 0
    while rounds < bound:
        rounds += 1
        _sweep(mesh, nodes)
        if all(len(node.digest) == n for node in nodes):
            break
    assert all(len(node.digest) == n for node in nodes), (
        f"not converged after {rounds} rounds at N={n}: "
        f"{sorted(len(node.digest) for node in nodes)[:5]}...")
    assert rounds <= bound
    # Fanout really is O(log N).
    assert nodes[0].fanout() == math.ceil(math.log2(n))


def test_single_rumor_reaches_everyone(n=64):
    mesh, nodes = _fleet(n)
    nodes[17].announce(_xh(17), 7017)
    for _ in range(math.ceil(math.log2(n))):
        _sweep(mesh, nodes)
    holders = [len(node.digest.holders(KIND_XORB, _xh(17).hex()))
               for node in nodes]
    assert all(h == 1 for h in holders)
    # find_peers answers from the digest, excluding self.
    assert nodes[0].find_peers(_xh(17)) == [("host17", 7017)]
    assert nodes[17].find_peers(_xh(17)) == []


def test_reannounce_bumps_sequence_and_wins():
    """Merge keeps the max origin seq (CRDT): a re-announce with a new
    port replaces the old payload everywhere, in any merge order."""
    mesh, nodes = _fleet(4)
    nodes[1].announce(_xh(1), 7001)
    for _ in range(3):
        _sweep(mesh, nodes)
    nodes[1].announce(_xh(1), 9999)  # moved listen port
    for _ in range(3):
        _sweep(mesh, nodes)
    for node in nodes:
        holders = node.digest.holders(KIND_XORB, _xh(1).hex())
        assert holders[1]["port"] == 9999


# ── Bounded digest / eviction ──


def test_eviction_keeps_bound_and_prefers_foreign():
    d = GossipDigest(max_entries=8, own_origin=0)
    for s in range(4):  # own entries (origin 0)
        d.update(KIND_XORB, f"own{s}", 0, s + 1, {"port": 1})
    for o in range(1, 101):  # 100 foreign origins
        d.update(KIND_XORB, f"f{o}", o, 1, {"port": 1})
    assert len(d) == 8
    assert d.evicted == 96
    # The origin-0 (own) entries all survived — only foreign evicted.
    own = [ident for ident in d._entries if ident[2] == 0]
    assert len(own) == 4


def test_version_vector_survives_eviction():
    """An evicted entry must NOT be re-merged at the same seq (the vv
    remembers the origin reached it); a seq bump does re-enter."""
    d = GossipDigest(max_entries=2)
    d.update(KIND_XORB, "a", 1, 5, {"port": 1})
    d.update(KIND_XORB, "b", 2, 5, {"port": 1})
    d.update(KIND_XORB, "c", 3, 5, {"port": 1})  # evicts one
    assert len(d) == 2 and d.evicted == 1
    evicted_key = next(k for k in ("a", "b", "c") if not d.holders(
        KIND_XORB, k))
    origin = {"a": 1, "b": 2, "c": 3}[evicted_key]
    assert not d.update(KIND_XORB, evicted_key, origin, 5, {"port": 1})
    assert d.update(KIND_XORB, evicted_key, origin, 6, {"port": 2})


def test_digest_memory_bound_at_1024_hosts():
    """Acceptance: digest memory stays under the configured bound at
    1024 hosts. 1024 origins × 64 announces each against a 4096-entry
    bound — entries never exceed the bound and the byte estimate stays
    under bound × a conservative per-entry ceiling."""
    d = GossipDigest(max_entries=4096)
    for origin in range(1024):
        for s in range(64):
            d.update(KIND_XORB, _xh(origin * 64 + s).hex(), origin,
                     s + 1, {"port": 7000 + origin})
    assert len(d) <= 4096
    assert d.evicted == 1024 * 64 - 4096
    per_entry_ceiling = 64 + len("xorb") + 64 + 32  # ident + payload
    assert d.memory_bytes() <= 4096 * per_entry_ceiling
    assert len(d.vv) == 1024  # vectors survive eviction


def test_delta_is_capped():
    d = GossipDigest()
    for s in range(MAX_DELTA_ENTRIES + 100):
        d.update(KIND_XORB, f"k{s}", 0, s + 1, {"port": 1})
    rows = d.delta_since({})
    assert len(rows) == MAX_DELTA_ENTRIES
    # Oldest-seq first: repeated capped rounds drain monotonically.
    seqs = [r[3] for r in rows]
    assert seqs == sorted(seqs) and seqs[0] == 1


# ── Partition then heal (satellite) ──


class _PartitionedMesh(LoopbackMesh):
    def __init__(self, split: int):
        super().__init__()
        self.split = split
        self.healed = False

    def exchange(self, peer, payload):
        if not self.healed:
            sender = payload.get("host", 0)
            if (sender < self.split) != (peer < self.split):
                return None  # WAN partition: exchange times out
        return super().exchange(peer, payload)


def test_partition_then_heal_reconverges():
    n, split = 32, 16
    mesh = _PartitionedMesh(split)
    book = {i: (f"host{i}", 7000 + i) for i in range(n)}
    nodes = [GossipNode(i, n, book) for i in range(n)]
    for node in nodes:
        mesh.register(node)
    nodes[2].announce(_xh(2), 7002)    # left half
    nodes[20].announce(_xh(20), 7020)  # right half
    for _ in range(8):
        _sweep(mesh, nodes)
    # Each side converged on its own rumor, neither crossed the cut.
    assert all(nodes[i].digest.holders(KIND_XORB, _xh(2).hex())
               for i in range(split))
    assert not any(nodes[i].digest.holders(KIND_XORB, _xh(2).hex())
                   for i in range(split, n))
    assert not any(nodes[i].digest.holders(KIND_XORB, _xh(20).hex())
                   for i in range(split))
    mesh.healed = True
    for _ in range(2 * math.ceil(math.log2(n))):
        _sweep(mesh, nodes)
    for node in nodes:
        assert node.digest.holders(KIND_XORB, _xh(2).hex())
        assert node.digest.holders(KIND_XORB, _xh(20).hex())


# ── Content-aware routing: link costs + nearest-first (tentpole c) ──


def test_link_cost_table():
    topo = (0, 0, 1, 1)
    pods = (0, 0, 0, 1)
    assert link_cost(0, 1, topo, pods) == COST_ICI
    assert link_cost(0, 2, topo, pods) == COST_DCN
    assert link_cost(2, 3, topo, pods) == COST_WAN  # pod beats slice
    # Missing maps degrade conservatively.
    assert link_cost(0, 1, None, None) == COST_DCN
    assert link_cost(0, 1, topo, None) == COST_ICI


def test_find_peers_orders_by_link_cost():
    """A cold host's candidate list tries ICI, then DCN, then WAN —
    the routing rule that sends a cold pod to the nearest warm pod."""
    n = 8
    topo = (0, 0, 1, 1, 0, 0, 1, 1)
    pods = (0, 0, 0, 0, 1, 1, 1, 1)
    mesh = LoopbackMesh()
    book = {i: (f"host{i}", 7000 + i) for i in range(n)}
    nodes = [GossipNode(i, n, book, topology=topo, pods=pods)
             for i in range(n)]
    for node in nodes:
        mesh.register(node)
    for holder in (6, 2, 1):  # WAN, DCN, ICI holders from host 0's view
        nodes[holder].announce(_xh(42), 7000 + holder)
    for _ in range(6):
        _sweep(mesh, nodes)
    assert nodes[0].who_has(_xh(42)) == [1, 2, 6]
    assert nodes[0].find_peers(_xh(42)) == [
        ("host1", 7001), ("host2", 7002), ("host6", 7006)]
    # From inside the other pod the same holders sort WAN-last too.
    assert nodes[7].who_has(_xh(42)) == [6, 1, 2]


def test_seeder_state_spreads():
    mesh, nodes = _fleet(4)
    nodes[3].set_seeder_state("draining", until=123)
    for _ in range(3):
        _sweep(mesh, nodes)
    st = nodes[0].digest.holders(KIND_SEEDER, "3")
    assert st[3]["state"] == "draining" and st[3]["until"] == 123


# ── DCN piggyback (tentpole a: no new listener, no new port) ──


def test_gossip_over_real_dcn_wire(tmp_path):
    from zest_tpu.transfer.dcn import DcnPool, DcnServer

    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                 dcn_port=0)
    server_node = GossipNode(1, 2, {})
    server_node.announce(_xh(9), 7555)
    srv = DcnServer(cfg)
    srv.attach_gossip(server_node)
    port = srv.start()
    pool = DcnPool()
    try:
        client = GossipNode(0, 2, {1: ("127.0.0.1", port)})
        transport = DcnGossipTransport(pool, {1: ("127.0.0.1", port)})
        fresh = client.tick(transport)
        assert fresh == 1
        assert client.find_peers(_xh(9)) == [("127.0.0.1", 7555)]
        # Push half: the server learned the client's announcements too.
        client.announce(_xh(10), 7010)
        client.tick(transport)
        assert server_node.digest.holders(KIND_XORB, _xh(10).hex())
    finally:
        pool.close()
        srv.shutdown()


def test_pre_gossip_server_is_unavailable_not_fatal(tmp_path):
    """A server with no node attached answers GOSSIP with the legacy
    ERROR — the transport treats the peer as gossip-unavailable while
    chunk RPCs keep working (mixed-fleet rollout)."""
    from zest_tpu.transfer.dcn import DcnPool, DcnServer, GossipUnavailable

    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                 dcn_port=0)
    srv = DcnServer(cfg)  # no attach_gossip
    port = srv.start()
    pool = DcnPool()
    try:
        with pytest.raises(GossipUnavailable):
            pool.gossip_exchange("127.0.0.1", port,
                                 {"host": 0, "vv": {}, "delta": []})
        node = GossipNode(0, 2, {1: ("127.0.0.1", port)})
        transport = DcnGossipTransport(pool, {1: ("127.0.0.1", port)})
        assert node.tick(transport) == 0  # best-effort, no raise
    finally:
        pool.close()
        srv.shutdown()


# ── Wiring gate (acceptance: ZEST_GOSSIP=0 bit-for-bit) ──


def test_node_from_config_gossip_off(tmp_path):
    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest")
    cfg.gossip_enabled = False
    assert node_from_config(cfg, 0, 4, None) is None


def test_node_from_config_carries_knobs(tmp_path):
    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest")
    cfg.coop_topology = (0, 0, 1, 1)
    cfg.coop_pods = (0, 0, 1, 1)
    cfg.gossip_fanout = 3
    cfg.gossip_max_entries = 128
    node = node_from_config(cfg, 2, 4, {i: ("h", 1) for i in range(4)})
    assert node is not None
    assert node.fanout() == 3
    assert node.digest.max_entries == 128
    assert node.cost_to(3) == COST_ICI
    assert node.cost_to(0) == COST_WAN


def test_swarm_announce_is_bootstrap_only_with_gossip(tmp_path):
    """With a node attached, the tracker sees ONE announce per swarm
    (the bootstrap seed); refreshes ride the digest. Detached
    (ZEST_GOSSIP=0) the tracker sees every announce — bit-for-bit the
    old behavior — and the stats schema carries no gossip key."""
    from zest_tpu.transfer.swarm import SwarmDownloader

    class RecordingSource:
        def __init__(self):
            self.announces = []

        def find_peers(self, info_hash):
            return []

        def announce(self, info_hash, port):
            self.announces.append(info_hash)

    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest")
    xorb = _xh(5)

    tracker = RecordingSource()
    plain = SwarmDownloader(cfg, peer_sources=[tracker])
    for _ in range(3):
        plain.announce_available(xorb, xorb.hex())
    assert len(tracker.announces) == 3  # tracker-only: every announce
    assert "gossip" not in plain.summary()
    plain.close()

    from zest_tpu.p2p.peer_id import compute_info_hash

    tracker = RecordingSource()
    node = GossipNode(0, 2, {})
    sw = SwarmDownloader(cfg, peer_sources=[tracker])
    sw.attach_gossip(node)
    assert sw.peer_sources[0] is node  # primary discovery source
    for _ in range(3):
        sw.announce_available(xorb, xorb.hex())
    assert len(tracker.announces) == 1  # bootstrap seed only
    assert node.digest.holders(KIND_XORB,
                               compute_info_hash(xorb).hex())
    assert sw.summary()["gossip"]["entries"] == 1
    sw.close()
