"""Xorb frame-stream tests: roundtrip, range slicing, verification, hostile input."""

import os
import struct

import pytest

from zest_tpu.cas import hashing, xorb
from zest_tpu.cas.xorb import XorbBuilder, XorbFormatError, XorbReader


def _build(chunks):
    b = XorbBuilder()
    for c in chunks:
        b.add_chunk(c)
    return b


class TestRoundtrip:
    def test_single_chunk(self):
        b = _build([b"hello world" * 100])
        r = XorbReader(b.serialize())
        assert r.extract_chunk(0) == b"hello world" * 100
        assert r.xorb_hash() == b.xorb_hash()

    def test_many_chunks_range_extraction(self):
        chunks = [os.urandom(1000 + i * 37) for i in range(20)]
        r = XorbReader(_build(chunks).serialize())
        assert len(r) == 20
        assert r.extract_chunk_range(0, 20) == b"".join(chunks)
        assert r.extract_chunk_range(5, 8) == b"".join(chunks[5:8])
        assert r.extract_chunk_range(19, 20) == chunks[19]

    def test_byte_slice_is_parseable_blob(self):
        # The property the whole transfer economy relies on: a chunk-range
        # byte slice is itself a valid xorb blob.
        chunks = [os.urandom(2000) for _ in range(10)]
        b = _build(chunks)
        blob = b.serialize()
        offs = b.frame_offsets()
        sub = blob[offs[3] : offs[7]]
        assert sub == XorbReader(blob).slice_range(3, 7)
        r = XorbReader(sub)
        assert len(r) == 4
        assert r.extract_chunk_range(0, 4) == b"".join(chunks[3:7])

    def test_compressible_chunks_shrink(self):
        chunks = [b"wwww" * 8000 for _ in range(4)]
        blob = _build(chunks).serialize()
        assert len(blob) < sum(len(c) for c in chunks) // 4

    def test_cdc_convenience(self):
        data = os.urandom(300_000)
        xh, blob, chunk_hashes = xorb.build_from_data(data)
        r = XorbReader(blob)
        assert r.extract_chunk_range(0, len(r)) == data
        assert r.xorb_hash() == xh
        assert r.chunk_hashes() == chunk_hashes

    def test_identity_independent_of_compression(self):
        data = b"model weights " * 1000
        h = hashing.chunk_hash(data)
        b = _build([data])
        assert b.chunk_hashes()[0][0] == h

    def test_empty_blob(self):
        r = XorbReader(b"")
        assert len(r) == 0


class TestHostileInput:
    def test_truncated_frame_header(self):
        blob = _build([b"x" * 100]).serialize()
        with pytest.raises(XorbFormatError):
            XorbReader(blob[:10])

    def test_payload_extends_past_end(self):
        blob = _build([b"y" * 5000]).serialize()
        with pytest.raises(XorbFormatError):
            XorbReader(blob[:-10])

    def test_unknown_scheme_rejected(self):
        blob = bytearray(_build([b"z" * 100]).serialize())
        blob[0] = 0xEE  # scheme byte
        with pytest.raises(XorbFormatError):
            XorbReader(bytes(blob))

    def test_corrupted_chunk_fails_verification(self):
        # Full artifact (footer carries hashes): payload corruption is
        # caught at extraction.
        chunks = [os.urandom(5000)]
        blob = bytearray(_build(chunks).serialize_full())
        blob[100] ^= 0xFF  # inside the single chunk's payload
        r = XorbReader(bytes(blob))
        with pytest.raises(Exception):  # hash mismatch or decode error
            r.extract_chunk(0)

    def test_corruption_skippable_without_verify(self):
        chunks = [os.urandom(5000)]
        r = XorbReader(_build(chunks).serialize())
        assert r.extract_chunk(0, verify=False) == chunks[0]

    def test_tampered_hash_detected(self):
        b = _build([b"q" * 3000])
        blob = bytearray(b.serialize_full())
        # Flip a byte of the chunk hash inside the footer's XBLBHSH section:
        # frames end at serialize() length; hash 0 starts 52 bytes into the
        # footer (ident+version+xorb hash+section ident+count).
        blob[len(b.serialize()) + 52] ^= 0x01
        r = XorbReader(bytes(blob))
        with pytest.raises(XorbFormatError, match="hash mismatch"):
            r.extract_chunk(0)

    def test_absurd_uncompressed_len_rejected(self):
        # Untrusted frame header must not dictate allocations: claim the
        # u24 max (16 MiB), over the 4 MiB decode cap.
        frame = bytearray(_build([b"x" * 100]).serialize())
        frame[5:8] = b"\xff\xff\xff"
        with pytest.raises(XorbFormatError, match="claims"):
            XorbReader(bytes(frame))

    def test_oversized_chunk_rejected_at_build(self):
        from zest_tpu.cas.xorb import MAX_CHUNK_BYTES, encode_frame

        with pytest.raises(XorbFormatError):
            encode_frame(b"\x00" * (MAX_CHUNK_BYTES + 1))

    def test_serialized_size_respects_wire_cap(self):
        from zest_tpu.cas.xorb import MAX_XORB_BYTES
        from zest_tpu.p2p import wire

        b = XorbBuilder()
        piece = os.urandom(1024 * 1024)
        while not b.would_overflow(len(piece)):
            b.add_chunk(piece)
        blob = b.serialize()
        assert len(blob) <= MAX_XORB_BYTES
        # A full xorb must always fit in one BEP XET response frame.
        framed = wire.encode_extended(3, b"\x02" + b"\x00" * 12 + blob)
        assert len(framed) - 4 - 1 <= wire.MAX_MESSAGE_SIZE

    def test_range_bounds_checked(self):
        r = XorbReader(_build([b"a" * 100]).serialize())
        for start, end in [(-1, 1), (0, 2), (1, 1), (2, 1)]:
            with pytest.raises(XorbFormatError):
                r.extract_chunk_range(start, end)


def test_extract_range_into_matches_extract_chunk_range():
    """The in-place decode (landing fast path) must be byte-identical to
    the allocating path for stored and compressed chunks, with and
    without a verifying footer, and reject wrong-size buffers."""
    import numpy as np

    from zest_tpu.cas.xorb import XorbBuilder, XorbFormatError, XorbReader

    rng = np.random.default_rng(42)
    builder = XorbBuilder()
    chunks = [
        rng.integers(0, 256, 70_000, dtype=np.uint8).tobytes(),  # stored
        b"compress me " * 5000,                                  # LZ4
        rng.integers(0, 256, 1024, dtype=np.uint8).tobytes(),
        b"\x00" * 50_000,
    ]
    for c in chunks:
        builder.add_chunk(c)
    for blob in (builder.serialize(), builder.serialize_full()):
        reader = XorbReader(blob)
        for s, e in [(0, 4), (1, 3), (0, 1), (3, 4)]:
            want = reader.extract_chunk_range(s, e)
            out = bytearray(len(want))
            n = reader.extract_range_into(s, e, out)
            assert n == len(want)
            assert bytes(out) == want, (s, e)
        with pytest.raises(XorbFormatError, match="out buffer"):
            reader.extract_range_into(0, 2, bytearray(3))
