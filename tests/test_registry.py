"""Model-family registry: config.json model_type → landing shard rules,
and the pull path applying them so landed tensors arrive TP-placed.

Reference analog: none — the reference hands files to torch and never
needs to know the family (SURVEY.md §3.1); the TPU build shards at
landing time, so family dispatch is part of the pull."""

import json

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tests.fixtures import FixtureHub, FixtureRepo, gpt2_checkpoint_files
from zest_tpu.config import Config, MeshConfig
from zest_tpu.models.registry import (
    detect_model_type,
    shard_rules_for_model_type,
    shard_rules_for_snapshot,
)


def test_detect_model_type(tmp_path):
    (tmp_path / "config.json").write_text(json.dumps(
        {"model_type": "llama", "hidden_size": 4096}
    ))
    assert detect_model_type(tmp_path) == "llama"


def test_detect_missing_or_bad_config(tmp_path):
    assert detect_model_type(tmp_path) is None
    (tmp_path / "config.json").write_text("{not json")
    assert detect_model_type(tmp_path) is None
    # Valid JSON that isn't an object must degrade to None, not raise.
    (tmp_path / "config.json").write_text("[1, 2, 3]")
    assert detect_model_type(tmp_path) is None


def test_rule_specs_degrade_on_mismatched_mesh():
    """Family rules on a mesh missing their axes (or with indivisible
    dims) must fall back to infer_spec, not break HBM landing."""
    import jax
    from zest_tpu.models.loader import spec_for
    from zest_tpu.parallel.mesh import model_mesh

    mesh = model_mesh({"data": 2, "model": 4})
    moe_rules = shard_rules_for_model_type("mixtral")
    # 'expert' axis doesn't exist here → generic largest-divisible-dim.
    spec = spec_for("model.layers.0.self_attn.q_proj.weight", (64, 64),
                    mesh, moe_rules)
    assert spec == P("model", None)
    # Rule dim indivisible (65 % 4): the rule P(None, 'model') is unusable;
    # infer_spec shards the divisible dim 0 instead.
    gpt2_rules = shard_rules_for_model_type("gpt2")
    spec = spec_for("h.0.attn.c_attn.weight", (64, 65), mesh, gpt2_rules)
    assert spec == P("model", None)
    # Fitting rule still wins.
    spec = spec_for("h.0.attn.c_attn.weight", (64, 192), mesh, gpt2_rules)
    assert spec == P(None, "model")


@pytest.mark.parametrize("family,sample", [
    ("gpt2", "h.0.attn.c_attn.weight"),
    ("llama", "model.layers.0.self_attn.q_proj.weight"),
    ("mistral", "model.layers.0.self_attn.q_proj.weight"),
    ("qwen2", "model.layers.0.self_attn.q_proj.weight"),
    ("mixtral", "model.layers.0.block_sparse_moe.experts.0.w1.weight"),
])
def test_families_have_rules(family, sample):
    import re

    rules = shard_rules_for_model_type(family)
    assert rules, family
    assert any(re.search(pat, sample) for pat, _ in rules), family


def test_unknown_family_returns_none():
    assert shard_rules_for_model_type("rwkv") is None
    assert shard_rules_for_model_type(None) is None


def test_shard_rules_for_snapshot(tmp_path):
    (tmp_path / "config.json").write_text('{"model_type": "gpt2"}')
    assert shard_rules_for_snapshot(tmp_path)
    (tmp_path / "config.json").write_text('{"model_type": "unknown"}')
    assert shard_rules_for_snapshot(tmp_path) is None


def test_mixtral_rules_cover_expert_tensors():
    import re

    rules = shard_rules_for_model_type("mixtral")
    hits = {
        "model.layers.0.self_attn.q_proj.weight": P("expert", None),
        "model.layers.0.block_sparse_moe.experts.3.w1.weight":
            P("expert", None),
        "model.layers.0.block_sparse_moe.experts.3.w2.weight":
            P(None, "expert"),
        "model.layers.0.block_sparse_moe.gate.weight": P(),
    }
    for name, want in hits.items():
        got = next(
            (spec for pat, spec in rules if re.search(pat, name)), None
        )
        assert got == want, name


def test_resolve_dtype():
    import jax.numpy as jnp
    import pytest as _pytest

    from zest_tpu.models.loader import resolve_dtype

    assert resolve_dtype(None) is None
    assert resolve_dtype("bf16") == jnp.bfloat16
    assert resolve_dtype("BFLOAT16") == jnp.bfloat16
    assert resolve_dtype("f32") == jnp.float32
    with _pytest.raises(ValueError, match="int8"):
        resolve_dtype("int8")


def test_pull_rejects_bad_dtype_before_network(tmp_path):
    """A landing-dtype typo fails fast — before resolving the repo —
    but only the TPU path consumes it (plain pulls ignore it)."""
    from zest_tpu.transfer.pull import pull_model

    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                 hf_token="hf_test", endpoint="http://127.0.0.1:9",
                 land_dtype="fp16")
    with pytest.raises(ValueError, match="fp16"):
        pull_model(cfg, "any/repo", no_p2p=True, device="tpu")
    # Non-TPU pull never touches land_dtype: it fails on the (closed)
    # endpoint instead, proving dtype validation didn't abort it.
    with pytest.raises(Exception) as ei:
        pull_model(cfg, "any/repo", no_p2p=True)
    assert not isinstance(ei.value, ValueError) or "fp16" not in str(ei.value)


def test_commit_tensors_dtype_skips_integers():
    """--dtype casts floats only; integer buffers keep their dtype."""
    import jax.numpy as jnp

    from zest_tpu.models.loader import commit_tensors

    host = {"w": np.ones((4, 4), np.float32),
            "ids": np.arange(4, dtype=np.int64)}
    out = commit_tensors(host, dtype=jnp.bfloat16)
    assert str(out["w"].dtype) == "bfloat16"
    assert str(out["ids"].dtype) in ("int64", "int32")  # x64-dependent
    np.testing.assert_array_equal(np.asarray(out["ids"]), host["ids"])
    # ml_dtypes sources (bf16 checkpoints) are NOT np.floating subtypes
    # but must still cast — e.g. upcasting a bf16 checkpoint to f32.
    import ml_dtypes

    host = {"w": np.ones((2, 2), ml_dtypes.bfloat16)}
    out = commit_tensors(host, dtype=jnp.float32)
    assert str(out["w"].dtype) == "float32"


def test_commit_tensors_coalesced_float64_values_survive():
    """A small-tensor float64 group must commit value-correct.

    The coalesced bit-pattern carrier is uint64; with jax in default
    (x64-off) mode device_put VALUE-casts uint64 → uint32, truncating
    every 8-byte pattern — the group came back all zeros. 8-byte dtypes
    must skip the carrier unless x64 is on (the plain per-group path
    downcasts f64 → f32, which is value-correct)."""
    from zest_tpu.models.loader import commit_tensors

    host = {"a": np.arange(8, dtype=np.float64),
            "b": np.arange(8, 16, dtype=np.float64)}
    out = commit_tensors(host)
    np.testing.assert_allclose(np.asarray(out["a"]), host["a"])
    np.testing.assert_allclose(np.asarray(out["b"]), host["b"])


def test_commit_tensors_coalesced_sub_byte_group():
    """Sub-byte dtypes (int4 quantized exports) must not get a byte
    carrier: itemsize says 1 but the type is 4 bits wide, and the
    on-device bitcast back (uint8 → int4) is rejected by jax — the
    group must coalesce raw, as it did before the carrier existed."""
    import ml_dtypes

    from zest_tpu.models.loader import commit_tensors

    host = {"a": np.array([1, 2, 3, 4], dtype=ml_dtypes.int4),
            "b": np.array([5, 6, 7, 1], dtype=ml_dtypes.int4)}
    out = commit_tensors(host)
    np.testing.assert_array_equal(
        np.asarray(out["a"]).astype(np.int8), [1, 2, 3, 4])
    np.testing.assert_array_equal(
        np.asarray(out["b"]).astype(np.int8), [5, 6, 7, 1])


@pytest.mark.slow
def test_pull_lands_bf16(tmp_path):
    """--dtype bf16 halves landed bytes on both the direct path and the
    disk-resume path."""
    from zest_tpu.transfer.pull import pull_model

    files = gpt2_checkpoint_files(n_embd=64, n_layer=2)
    repo = FixtureRepo("acme/bf16-gpt2", files, chunks_per_xorb=4)
    with FixtureHub(repo) as hub:
        cfg = Config(
            hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
            hf_token="hf_test", endpoint=hub.url, land_dtype="bf16",
        )
        res = pull_model(cfg, "acme/bf16-gpt2", no_p2p=True, device="tpu")
        assert res.stats["hbm"]["direct"] is True
        arr = res.params["h.0.attn.c_attn.weight"]
        assert str(arr.dtype) == "bfloat16"
        res.params = None
        res2 = pull_model(cfg, "acme/bf16-gpt2", no_p2p=True, device="tpu")
        assert res2.stats["hbm"]["direct"] is False
        assert str(res2.params["h.0.attn.c_attn.weight"].dtype) == "bfloat16"


# ── End-to-end: pull --device=tpu applies family rules ──


def test_pull_lands_with_family_rules(tmp_path):
    """A gpt2-typed repo pulled onto a {data, model} mesh must land its
    attention weights sharded per gpt2.checkpoint_shard_rules — both on
    the direct path (cold) and the disk path (resume)."""
    from zest_tpu.transfer.pull import pull_model

    files = gpt2_checkpoint_files(n_embd=64, n_layer=2)
    repo = FixtureRepo("acme/tiny-gpt2", files, chunks_per_xorb=4)
    with FixtureHub(repo) as hub:
        cfg = Config(
            hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
            hf_token="hf_test", endpoint=hub.url,
            mesh=MeshConfig(mesh_axes={"data": 2, "model": 4}),
        )
        res = pull_model(cfg, "acme/tiny-gpt2", no_p2p=True, device="tpu")
        assert res.stats["hbm"]["direct"] is True
        qkv = res.params["h.0.attn.c_attn.weight"]
        assert qkv.sharding.spec == P(None, "model")
        res.params = None

        # Resume: disk staging must apply the same family rules.
        res2 = pull_model(cfg, "acme/tiny-gpt2", no_p2p=True, device="tpu")
        assert res2.stats["hbm"]["direct"] is False
        qkv2 = res2.params["h.0.attn.c_attn.weight"]
        assert qkv2.sharding.spec == P(None, "model")
        np.testing.assert_array_equal(
            np.asarray(qkv2).view(np.uint8).reshape(-1),
            files_tensor(files, "h.0.attn.c_attn.weight"),
        )


def files_tensor(files: dict, name: str) -> np.ndarray:
    """Reference bytes of one tensor from the fixture checkpoint."""
    from zest_tpu.models.safetensors_io import parse_header

    blob = files["model.safetensors"]
    header = parse_header(blob)
    info = header.tensors[name]
    start, end = info.data_offsets
    return np.frombuffer(
        blob[header.data_start + start:header.data_start + end], np.uint8
    )
