"""Golden interop tests: the content-addressing stack vs the official client.

Two distinct guarantees are enforced here, and the distinction matters:

1. **Production interop (external oracle).** The installed official
   ``hf_xet`` client (xet-core, Rust) recomputes file hashes for the same
   inputs. Equality pins the ENTIRE addressing pipeline — GearHash table +
   mask + min/max chunk limits, BLAKE3 chunk/node domain keys, merkle
   grouping rule, file salt, and the little-endian-u64 hex convention —
   because a single wrong bit in any of them changes the final hex. These
   hashes are real HF CAS addresses. (Reference analog: zig-xet's formats
   are pinned by the live-CDN integrity gate,
   /root/reference/test/local/verify-model.sh:90-147.)

2. **Format freeze (regression guard).** The XETBLOB xorb layout, the LZ4
   frame encoder output, and the BG4/bitslice transforms are pinned to
   frozen fixture bytes under tests/golden/ (provenance:
   scripts/gen_golden_fixtures.py, deterministic inputs). The golden files
   guard against silent format drift — any diff means previously-cached
   xorbs stop parsing. The LZ4 *decoder* additionally gets spec-derived
   hand-built vectors, which ARE an independent check of the block/frame
   semantics.

3. **Container interop (external oracle, §1b).** The official client's
   *download* path is pointed at the loopback fixture hub, so the
   production Rust code reconstructs files from xorbs OUR XorbBuilder
   serialized (reconstruction JSON, ranged xorb fetches, frame parsing,
   all three compression schemes). This closes the gap the freeze alone
   leaves open: a self-consistent wrong layout passes its own golden
   bytes, but not an independent consumer.
"""

from __future__ import annotations

import json
import pathlib
import struct

import numpy as np
import pytest

from zest_tpu.cas import compression as comp
from zest_tpu.cas import xorb as xorbmod
from zest_tpu.cas.chunking import (
    MAX_CHUNK,
    MIN_CHUNK,
    _cut_points_py,
    chunk_stream,
    cut_points,
)
from zest_tpu.cas.hashing import (
    chunk_hash,
    file_hash,
    hash_to_hex,
    hex_to_hash,
)
from zest_tpu.cas.xorb import XorbBuilder, XorbReader, parse_footer

GOLDEN = pathlib.Path(__file__).parent / "golden"


def _our_file_hash_hex(data: bytes) -> str:
    leaves = [(chunk_hash(c), len(c)) for _meta, c in chunk_stream(data)]
    return hash_to_hex(file_hash(leaves))


def _official_file_hash_hex(tmp_path, data: bytes) -> str:
    # Only the cross-check tests need the official client; the frozen
    # format-freeze tests below must keep running where hf_xet has no
    # wheel — they are the regression guard for OUR layouts.
    hf_xet = pytest.importorskip(
        "hf_xet", reason="official client not installed"
    )
    p = tmp_path / "input.bin"
    p.write_bytes(data)
    (info,) = hf_xet.hash_files([str(p)])
    return info.hash


def _payload(name: str) -> bytes:
    """Deterministic test payloads; seeded PCG64, no ambient randomness
    (zlib.crc32 seed — str hash() is randomized per process)."""
    import zlib

    rng = np.random.default_rng(zlib.crc32(name.encode()))

    def rand(n):
        return rng.integers(0, 256, n, dtype=np.uint8).tobytes()

    return {
        "empty": b"",
        "tiny": b"hello world",
        "sub_min_chunk": rand(100),
        "min_minus_1": rand(MIN_CHUNK - 1),
        "min_exact": rand(MIN_CHUNK),
        "one_target": rand(64 * 1024),
        "multi_chunk": rand(300_003),
        "one_mib": rand(1024 * 1024),
        "five_mib": rand(5 * 1024 * 1024),
        "zeros": bytes(2 * 1024 * 1024),
        "low_entropy": (b"layer.%04d.weight " * 40000)[: 1024 * 1024],
        # Smooth fp32 tensor bytes: byte-grouping (BG4) beats plain LZ4,
        # so compress_auto picks BG4_LZ4 (asserted where it's used).
        "fp32_smooth": np.sin(np.linspace(0, 2000, 256 * 1024))
        .astype(np.float32).tobytes(),
    }[name]


# ── 1. Official-client cross-checks ──


@pytest.mark.parametrize(
    "name",
    [
        "empty",
        "tiny",
        "sub_min_chunk",
        "min_minus_1",
        "min_exact",
        "one_target",
        "multi_chunk",
        "one_mib",
        "five_mib",
        "zeros",
        "low_entropy",
    ],
)
def test_file_hash_matches_official_client(tmp_path, name):
    data = _payload(name)
    assert _our_file_hash_hex(data) == _official_file_hash_hex(tmp_path, data)


def test_multi_xorb_scale_matches_official_client(tmp_path):
    """~70 MiB random: >1000 chunks, past the one-xorb cap — exercises
    deep merkle aggregation (multiple interior levels, forced k==9
    closes) on a production-scale input."""
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, 70 * 1024 * 1024, dtype=np.uint8).tobytes()
    n_chunks = len(cut_points(data))  # cut_points yields chunk END offsets
    assert n_chunks > 1024  # spans multiple xorbs when packed
    assert _our_file_hash_hex(data) == _official_file_hash_hex(tmp_path, data)


def test_empty_file_is_zero_hash(tmp_path):
    """Official-client convention: an empty file's address is all zeros,
    not a salted empty merkle root."""
    official = _official_file_hash_hex(tmp_path, b"")
    assert official == "0" * 64
    assert _our_file_hash_hex(b"") == official
    assert file_hash([]) == bytes(32)


# ── 1b. Official-client CONTAINER cross-validation ──
#
# The file-hash checks above pin the *addressing* pipeline; these pin the
# *artifact* pipeline. The official Rust client's download path
# (XetSession → XetFileDownloadGroup) is pointed at the loopback fixture
# hub, whose xorbs OUR XorbBuilder serialized and whose reconstruction
# metadata OUR recon.to_json produced. The client resolves
# /v{1,2}/reconstructions/{file_hex}, issues ranged GETs against
# /xorbs/{hex}, parses our frame stream (chunk headers +
# NONE/LZ4/BG4-LZ4 bodies), and reassembles the file. Byte equality
# means an independent production consumer accepts our container — the
# cross-implementation check a self-consistent-but-wrong golden freeze
# could never provide. (Reference analog: container correctness proven
# by an independent consumer in the live-CDN gate,
# /root/reference/test/local/verify-model.sh:90-147.)

_FIXTURE_TOKEN = ("fixture-access-token", 4102444800)


def _official_pull_via_hub(tmp_path, monkeypatch, repo_files: dict,
                           chunks_per_xorb: int = 0) -> dict:
    """Serve ``repo_files`` from a FixtureRepo and download every xet
    file with the official client; returns {path: downloaded_bytes} and
    asserts the bytes actually crossed the hub (no warm-cache pass)."""
    hf_xet = pytest.importorskip(
        "hf_xet", reason="official client not installed"
    )
    from tests.fixtures import FixtureHub, FixtureRepo

    # The Rust client keeps a chunk cache under HF_HOME/xet; an earlier
    # test's cache would let it skip the hub entirely, voiding the
    # cross-check. Point it at this test's tmp dir (read at session
    # creation) and assert below that xorb GETs were observed.
    monkeypatch.setenv("HF_HOME", str(tmp_path / "hf_home"))
    monkeypatch.setenv("HF_XET_CACHE", str(tmp_path / "hf_home" / "xet"))

    repo = FixtureRepo("acme/oracle", repo_files,
                       chunks_per_xorb=chunks_per_xorb)
    out: dict[str, bytes] = {}
    with FixtureHub(repo) as hub:
        session = hf_xet.XetSession()
        with session.new_file_download_group(
            endpoint=hub.url,
            token=_FIXTURE_TOKEN[0],
            token_expiry_unix_secs=_FIXTURE_TOKEN[1],
        ) as group:
            dests = {}
            for path, f in repo.files.items():
                if f.xet_hash is None:
                    continue
                dest = tmp_path / "out" / path
                dest.parent.mkdir(parents=True, exist_ok=True)
                group.start_download_file(
                    hf_xet.XetFileInfo(f.xet_hash, len(f.data)), str(dest)
                )
                dests[path] = dest
        for path, dest in dests.items():
            out[path] = dest.read_bytes()
        assert any(r.startswith("GET /xorbs/") for r in hub.requests_seen), \
            hub.requests_seen
    return out


@pytest.mark.parametrize(
    "name, scheme",
    [
        ("one_mib", comp.Scheme.NONE),        # incompressible frames
        ("zeros", comp.Scheme.LZ4),           # maximally compressible
        ("low_entropy", comp.Scheme.LZ4),     # repetitive text
        ("fp32_smooth", comp.Scheme.BG4_LZ4), # byte-grouped fp32 tensor
    ],
)
def test_official_client_downloads_our_xorbs(tmp_path, monkeypatch,
                                             name, scheme):
    """Per-compression-scheme container interop: the official client
    must decode OUR encoder's frames for every auto-selected scheme."""
    data = _payload(name)
    # Self-check the payload really exercises the claimed scheme.
    first = next(c for _m, c in chunk_stream(data))
    assert comp.compress_auto(first)[0] == scheme
    got = _official_pull_via_hub(
        tmp_path, monkeypatch, {"model.safetensors": data}
    )
    assert got["model.safetensors"] == data


def test_official_client_downloads_multi_xorb_repo(tmp_path, monkeypatch):
    """Multi-file, multi-xorb repo with sub-xorb terms
    (chunks_per_xorb=3): the official client reassembles every file from
    several xorbs of OUR serialization, mixed schemes in one group."""
    files = {
        "model-00001-of-00002.safetensors":
            _payload("multi_chunk") + _payload("zeros")[:300_000],
        "model-00002-of-00002.safetensors":
            _payload("fp32_smooth") + _payload("one_mib")[:200_000],
    }
    got = _official_pull_via_hub(tmp_path, monkeypatch, files,
                                 chunks_per_xorb=3)
    assert got == files


def test_chunk_boundaries_within_limits():
    data = _payload("five_mib")
    cuts = cut_points(data)  # END offset of each chunk, covering data exactly
    assert cuts[-1] == len(data)
    sizes = [b - a for a, b in zip([0] + cuts, cuts)]
    assert all(MIN_CHUNK <= s <= MAX_CHUNK for s in sizes[:-1])
    assert 0 < sizes[-1] <= MAX_CHUNK


def test_native_and_python_chunkers_agree():
    """The pure-Python scanner is the correctness anchor; the native C++
    hot path must produce identical boundaries."""
    data = _payload("one_mib") + _payload("low_entropy")
    assert cut_points(data) == _cut_points_py(memoryview(data))


def test_hex_convention_le_u64():
    """MerkleHash hex = 4 little-endian u64 groups, each %016x — NOT the
    plain byte hex (reference: src/server.zig:201-204)."""
    h = bytes(range(32))
    expect = (
        "0706050403020100"
        "0f0e0d0c0b0a0908"
        "1716151413121110"
        "1f1e1d1c1b1a1918"
    )
    assert hash_to_hex(h) == expect
    assert hex_to_hash(expect) == h


# ── 2. Frozen XETBLOB layout ──


@pytest.fixture(scope="module")
def golden_xorb():
    blob = (GOLDEN / "xorb_mixed.bin").read_bytes()
    meta = json.loads((GOLDEN / "xorb_mixed.json").read_text())
    return blob, meta


def test_golden_xorb_parses(golden_xorb):
    blob, meta = golden_xorb
    reader = XorbReader(blob)
    assert len(reader) == meta["n_chunks"]
    assert hash_to_hex(reader.xorb_hash()) == meta["xorb_hash"]
    for i, cm in enumerate(meta["chunks"]):
        assert hash_to_hex(reader.chunk_hashes()[i][0]) == cm["chunk_hash"]
        data = reader.extract_chunk(i, verify=True)
        assert len(data) == cm["uncompressed_len"]


def test_golden_xorb_footer_fields(golden_xorb):
    blob, meta = golden_xorb
    frames_end, xh, hashes = parse_footer(blob)
    assert frames_end == meta["frames_len"]
    assert hash_to_hex(xh) == meta["xorb_hash"]
    assert [hash_to_hex(h) for h in hashes] == [
        c["chunk_hash"] for c in meta["chunks"]
    ]
    (footer_len,) = struct.unpack("<I", blob[-4:])
    assert footer_len == 40 * meta["n_chunks"] + 92
    assert len(blob) == meta["full_len"]


def test_golden_xorb_schemes_cover_auto_set(golden_xorb):
    _blob, meta = golden_xorb
    schemes = {c["scheme_name"] for c in meta["chunks"]}
    assert {"NONE", "LZ4", "BG4_LZ4"} <= schemes


def test_golden_xorb_rebuild_is_bit_identical(golden_xorb):
    """Extract every chunk and rebuild: serialize_full() must reproduce
    the frozen bytes exactly — pins frame headers, scheme auto-selection,
    and the footer layout in one assertion."""
    blob, meta = golden_xorb
    reader = XorbReader(blob)
    builder = XorbBuilder()
    for i in range(len(reader)):
        builder.add_chunk(reader.extract_chunk(i))
    assert builder.serialize_full() == blob
    offs = builder.frame_offsets()  # N starts + end sentinel
    assert offs[:-1] == [c["frame_offset"] for c in meta["chunks"]]
    assert offs[-1] == meta["frames_len"]


def test_golden_xorb_range_slices(golden_xorb):
    """Any chunk range is a contiguous frame byte range; a sliced blob is
    itself a parseable (footerless) xorb — the property every transfer
    tier relies on (CDN url_range, partial cache entries, BEP XET)."""
    blob, meta = golden_xorb
    reader = XorbReader(blob)
    offs = [c["frame_offset"] for c in meta["chunks"]] + [meta["frames_len"]]
    part = reader.slice_range(1, 4)
    assert part == blob[offs[1] : offs[4]]
    sub = XorbReader(part)
    assert len(sub) == 3
    for local, absolute in enumerate(range(1, 4)):
        assert sub.extract_chunk(local) == reader.extract_chunk(absolute)


def test_golden_file_hash(golden_xorb):
    blob, meta = golden_xorb
    reader = XorbReader(blob)
    assert hash_to_hex(file_hash(reader.chunk_hashes())) == meta["file_hash"]


# ── 3. LZ4: frozen encoder frames + spec-derived decoder vectors ──


@pytest.fixture(scope="module")
def lz4_golden():
    return json.loads((GOLDEN / "lz4_frames.json").read_text())


def test_lz4_encoder_frames_frozen(lz4_golden):
    for name, case in lz4_golden.items():
        if name.startswith("_"):
            continue
        payload = comp.lz4_frame_decompress(
            bytes.fromhex(case["frame_hex"]), case["payload_len"]
        )
        assert comp.lz4_frame_compress(payload).hex() == case["frame_hex"], name


def _frame(flg: int, descriptor_extra: bytes, body: bytes) -> bytes:
    """Hand-assemble an LZ4 frame: magic, FLG, BD(256KiB), extras, HC=0
    (decoder skips it), body, end mark."""
    return (
        struct.pack("<I", 0x184D2204)
        + bytes([flg, 0x50])
        + descriptor_extra
        + b"\x00"
        + body
        + struct.pack("<I", 0)
    )


def _stored(payload: bytes) -> bytes:
    return struct.pack("<I", 0x80000000 | len(payload)) + payload


def test_spec_stored_block_roundtrip():
    payload = b"stored, not compressed"
    frame = _frame(0x60, b"", _stored(payload))
    assert comp.lz4_frame_decompress(frame, len(payload)) == payload


def test_spec_compressed_block_literals_only():
    # token high nibble = literal count (8), no match (last sequence).
    block = bytes([0x80]) + b"ABCDEFGH"
    frame = _frame(0x60, b"", struct.pack("<I", len(block)) + block)
    assert comp.lz4_frame_decompress(frame, 8) == b"ABCDEFGH"


def test_spec_compressed_block_overlapping_match():
    # seq1: 1 literal 'A', offset-1 match of length 6 (overlap copy) → 7×A;
    # final sequence: 5 literals. Decoded = 12×A.
    block = bytes([0x12]) + b"A" + struct.pack("<H", 1)
    block += bytes([0x50]) + b"AAAAA"
    frame = _frame(0x60, b"", struct.pack("<I", len(block)) + block)
    assert comp.lz4_frame_decompress(frame, 12) == b"A" * 12


def test_spec_varlen_literal_extension():
    # token literal nibble 15 + extension byte 5 → 20 literals.
    lits = bytes(range(20))
    block = bytes([0xF0, 0x05]) + lits
    frame = _frame(0x60, b"", struct.pack("<I", len(block)) + block)
    assert comp.lz4_frame_decompress(frame, 20) == lits


def test_spec_dictid_flag_skips_4_bytes():
    # FLG bit 0 = DictID: 4 extra descriptor bytes before HC.
    payload = b"dictionary-flagged frame"
    frame = _frame(0x61, struct.pack("<I", 0xDEADBEEF), _stored(payload))
    assert comp.lz4_frame_decompress(frame, len(payload)) == payload


def test_spec_content_size_flag_skips_8_bytes():
    payload = b"content-size-flagged frame"
    frame = _frame(0x68, struct.pack("<Q", len(payload)), _stored(payload))
    assert comp.lz4_frame_decompress(frame, len(payload)) == payload


def test_spec_block_checksum_flag_skips_4_bytes():
    payload = b"block-checksummed frame"
    body = _stored(payload) + struct.pack("<I", 0)  # checksum ignored
    frame = _frame(0x70, b"", body)
    assert comp.lz4_frame_decompress(frame, len(payload)) == payload


@pytest.mark.parametrize(
    "mutant",
    [
        b"",
        b"\x00\x00\x00\x00",
        struct.pack("<I", 0x184D2204),  # magic only
        struct.pack("<I", 0x184D2204) + bytes([0x00, 0x50, 0x00]),  # bad ver
        struct.pack("<I", 0x184D2204) + bytes([0x60, 0x50, 0x00])
        + struct.pack("<I", 100),  # block past end
    ],
)
def test_spec_malformed_frames_rejected(mutant):
    with pytest.raises(comp.CompressionError):
        comp.lz4_frame_decompress(mutant, 10)


def test_xxh32_published_vector():
    # xxHash reference: XXH32("", seed=0) = 0x02CC5D05.
    assert comp.xxh32(b"") == 0x02CC5D05


def test_frame_header_checksum_matches_spec_rule():
    # HC = (xxh32(descriptor) >> 8) & 0xFF over FLG..descriptor end.
    frame = comp.lz4_frame_compress(b"x" * 100)
    descriptor = frame[4:6]
    assert frame[6] == (comp.xxh32(descriptor) >> 8) & 0xFF


# ── 4. BG4 / bitslice transforms ──


def test_bg4_layout_frozen(lz4_golden):
    t = lz4_golden["_transforms"]
    fixed = bytes.fromhex(t["input_hex"])
    assert comp._bg4(fixed).hex() == t["bg4_hex"]
    assert comp._bitslice(fixed).hex() == t["bitslice_hex"]


def test_bg4_plane_layout_hand_vector():
    # byte k of every 4-byte group lands in plane k.
    assert comp._bg4(b"abcdefgh") == b"aebfcgdh"
    assert comp._bg4_inverse(b"aebfcgdh") == b"abcdefgh"


def test_all_schemes_roundtrip_tensorlike():
    data = np.cos(np.linspace(0, 31, 2048)).astype(np.float32).tobytes()
    for scheme in comp.Scheme:
        enc = comp.compress(data, scheme)
        assert comp.decompress(enc, scheme, len(data)) == data
