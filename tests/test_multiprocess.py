"""True multi-process jax.distributed test — the launcher side.

Spawns two REAL jax processes (tests/_mp_pod_worker.py) against one
coordinator: separate caches, separate device sets (4 virtual CPU
devices each, one global 8-device mesh), KV-store peer discovery via
CoordinatorRegistry, BT-wire transfer between the processes, then a
distributed pod_round over the global mesh. De-simulates the
monkeypatched process counts used by the in-process tests
(test_hierarchy.py, test_direct_landing.py) — here jax.process_count()
really is 2 in every worker.

Reference analog: the Docker 2-node gate
(test/local/p2p-docker-test.sh:204-218) — fail unless bytes moved peer
to peer. Shell twin: scripts/multiprocess-pod-test.sh (CI job).
"""

from __future__ import annotations

import json
import pathlib
import socket
import subprocess
import sys

import numpy as np
import pytest

from tests.fixtures import FixtureHub, FixtureRepo

REPO_ID = "acme/mp-model"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(scope="module")
def hub():
    rng = np.random.default_rng(321)
    files = {
        "config.json": b'{"model_type": "gpt2"}',
        "model.safetensors": rng.integers(
            0, 256, 768 * 1024, dtype=np.uint8
        ).tobytes(),
    }
    with FixtureHub(FixtureRepo(REPO_ID, files, chunks_per_xorb=2)) as h:
        yield h


@pytest.mark.slow
def test_two_process_distribution(hub, tmp_path):
    nprocs = 2
    coord = f"127.0.0.1:{_free_port()}"
    script = pathlib.Path(__file__).parent / "_mp_pod_worker.py"
    # Per-worker log files, not PIPEs: the workers are barrier-coupled,
    # so an unread pipe filling up in one would deadlock the other.
    logs = [open(tmp_path / f"worker_{pid}.log", "w+") for pid in
            range(nprocs)]
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(nprocs), coord,
             hub.url, str(tmp_path), REPO_ID],
            stdout=logs[pid], stderr=subprocess.STDOUT, text=True,
            # sitecustomize imports jax at interpreter start, so the CPU
            # platform + virtual device count must already be in the env
            # when the worker is spawned.
            env={
                **__import__("os").environ,
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            },
        )
        for pid in range(nprocs)
    ]

    def read_log(pid):
        logs[pid].flush()
        logs[pid].seek(0)
        return logs[pid].read()

    try:
        for p in procs:
            try:
                # Generous: the two workers alone finish in ~2 min, but
                # under a full-suite run on a 1-vCPU box the spawned
                # jax.distributed children contend with the suite itself
                # and 300 s has proven flaky.
                p.wait(timeout=600)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail("multi-process workers timed out:\n"
                            + "\n".join(read_log(i) for i in range(nprocs)))
        for pid, p in enumerate(procs):
            assert p.returncode == 0, \
                f"worker {pid} failed:\n{read_log(pid)}"
    finally:
        for f in logs:
            f.close()

    s0 = json.loads((tmp_path / "stats_0.json").read_text())
    s1 = json.loads((tmp_path / "stats_1.json").read_text())
    # the Docker-gate criterion: real bytes moved process-to-process
    assert s1["phase_b_peer_bytes"] > 0
    assert s1["phase_b_cdn_bytes"] == 0
    assert s0["announced"] > 0
    # the distributed pod round saw the full global mesh in BOTH workers
    assert s0["pod"]["slots"] == s1["pod"]["slots"] == 8
    assert s0["verified_files"] == s1["verified_files"] == 1
    # hierarchical round: pod axis == process boundary, every unit
    # verified byte-for-byte out of the cross-process gathered pool
    for s in (s0, s1):
        assert s["hier"]["pods"] == 2
        assert s["hier"]["verified_units"] > 0
        assert s["hier"]["stage_seconds"]["dcn"] > 0
