"""Models layer: safetensors format, checkpoint landing, flagship GPT-2.

The correctness anchor mirrors the reference's verify-model.sh (load pulled
weights with transformers and check behavior, test/local/verify-model.sh:
90-147) — but cross-implementation: the same random checkpoint must produce
the same logits from torch/transformers' GPT2 and from our pure-JAX
forward.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zest_tpu.models import gpt2
from zest_tpu.models.loader import infer_spec, load_checkpoint, spec_for
from zest_tpu.models.safetensors_io import (
    SafetensorsFile,
    parse_header,
    write_safetensors,
)


# ── safetensors_io ──


def test_safetensors_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    tensors = {
        "a": rng.standard_normal((3, 5)).astype(np.float32),
        "b": rng.integers(0, 255, size=(7,), dtype=np.uint8),
        "c.nested.name": rng.standard_normal((2, 2, 2)).astype(np.float16),
    }
    path = tmp_path / "m.safetensors"
    write_safetensors(path, tensors, metadata={"format": "pt"})
    with SafetensorsFile(path) as sf:
        assert sorted(sf.names()) == sorted(tensors)
        assert sf.header.metadata == {"format": "pt"}
        for name, want in tensors.items():
            np.testing.assert_array_equal(sf.tensor(name), want)


def test_safetensors_bf16_roundtrip(tmp_path):
    import ml_dtypes

    arr = np.arange(16, dtype=np.float32).astype(ml_dtypes.bfloat16)
    path = tmp_path / "m.safetensors"
    write_safetensors(path, {"x": arr})
    with SafetensorsFile(path) as sf:
        assert sf.info("x").dtype == "BF16"
        np.testing.assert_array_equal(sf.tensor("x"), arr)


def test_safetensors_upstream_compat(tmp_path):
    """Our writer's files parse with the upstream safetensors package and
    vice versa."""
    st = pytest.importorskip("safetensors.numpy")
    ours = tmp_path / "ours.safetensors"
    theirs = tmp_path / "theirs.safetensors"
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    write_safetensors(ours, {"x": x})
    np.testing.assert_array_equal(st.load_file(str(ours))["x"], x)
    st.save_file({"x": x}, str(theirs))
    with SafetensorsFile(theirs) as sf:
        np.testing.assert_array_equal(sf.tensor("x"), x)


def test_safetensors_rejects_bad_header():
    with pytest.raises(ValueError):
        parse_header(b"\x00" * 4)
    huge = (10**12).to_bytes(8, "little") + b"{}"
    with pytest.raises(ValueError):
        parse_header(huge)


def test_safetensors_rejects_overlapping_and_oob_offsets():
    import struct

    def hdr(doc, data: bytes) -> bytes:
        raw = json.dumps(doc).encode()
        return struct.pack("<Q", len(raw)) + raw + data

    # overlapping ranges: two tensors aliasing the same bytes
    with pytest.raises(ValueError, match="overlap"):
        parse_header(hdr({
            "a": {"dtype": "F32", "shape": [2], "data_offsets": [0, 8]},
            "b": {"dtype": "F32", "shape": [2], "data_offsets": [4, 12]},
        }, b"\x00" * 12))
    # out of bounds / reversed
    with pytest.raises(ValueError, match="out of bounds"):
        parse_header(hdr({
            "a": {"dtype": "U8", "shape": [4], "data_offsets": [0, 4]},
        }, b"\x00" * 2))
    with pytest.raises(ValueError, match="out of bounds"):
        parse_header(hdr({
            "a": {"dtype": "U8", "shape": [0], "data_offsets": [4, 0]},
        }, b"\x00" * 8))


def test_safetensors_rejects_offset_shape_mismatch(tmp_path):
    import json
    import struct

    hdr = json.dumps({
        "x": {"dtype": "F32", "shape": [4], "data_offsets": [0, 8]}
    }).encode()
    with pytest.raises(ValueError, match="span"):
        parse_header(struct.pack("<Q", len(hdr)) + hdr + b"\x00" * 8)


# ── loader ──


def _mesh8():
    return Mesh(np.asarray(jax.devices()[:8]), ("model",))


def test_infer_spec_picks_largest_divisible_axis():
    mesh = _mesh8()
    assert infer_spec((16, 6), mesh, "model") == P("model", None)
    assert infer_spec((6, 32), mesh, "model") == P(None, "model")
    assert infer_spec((3, 5), mesh, "model") == P()  # indivisible
    assert infer_spec((), mesh, "model") == P()


def test_spec_rules_first_match_wins():
    mesh = _mesh8()
    rules = [(r"bias$", P()), (r"weight$", P("model", None))]
    assert spec_for("h.0.weight", (16, 16), mesh, rules) == P("model", None)
    assert spec_for("h.0.bias", (16,), mesh, rules) == P()
    # no rule match → inferred
    assert spec_for("other", (16, 4), mesh, rules) == P("model", None)


def test_load_checkpoint_sharded(tmp_path):
    rng = np.random.default_rng(1)
    tensors = {
        "w": rng.standard_normal((16, 4)).astype(np.float32),
        "b": rng.standard_normal((5,)).astype(np.float32),
    }
    write_safetensors(tmp_path / "model.safetensors", tensors)
    mesh = _mesh8()
    params = load_checkpoint(tmp_path, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(params["w"]), tensors["w"])
    np.testing.assert_array_equal(np.asarray(params["b"]), tensors["b"])
    w_spec = params["w"].sharding.spec
    assert w_spec == P("model", None)       # 16 divisible by 8
    assert params["b"].sharding.spec == P()  # 5 indivisible → replicated


def test_stage_snapshot_to_hbm_stats(tmp_path):
    tensors = {"w": np.ones((8, 8), np.float32)}
    write_safetensors(tmp_path / "model.safetensors", tensors)
    from zest_tpu.models.loader import stage_snapshot_to_hbm

    params, stats = stage_snapshot_to_hbm(tmp_path)
    assert stats["tensors"] == 1
    assert stats["bytes"] == 8 * 8 * 4
    assert stats["direct"] is False
    assert "w" in params  # the caller owns the staged tree


# ── gpt2 flagship ──


def test_gpt2_forward_shapes_and_jit():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(jax.random.key(0), cfg)
    ids = jnp.zeros((2, 9), jnp.int32)
    logits = jax.jit(lambda p, x: gpt2.forward(p, x, cfg))(params, ids)
    assert logits.shape == (2, 9, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_gpt2_matches_transformers():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.GPT2Config(
        vocab_size=96, n_positions=32, n_embd=48, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        activation_function="gelu_new",
    )
    torch.manual_seed(0)
    model = transformers.GPT2LMHeadModel(hf_cfg).eval()
    state = {k: v.numpy() for k, v in model.state_dict().items()}

    cfg = gpt2.GPT2Config(vocab_size=96, n_ctx=32, n_embd=48,
                          n_layer=2, n_head=4)
    params = gpt2.params_from_hf(state, cfg)

    ids = np.array([[5, 17, 2, 90, 41, 7, 0, 33]], dtype=np.int64)
    with torch.no_grad():
        want = model(torch.from_numpy(ids)).logits.numpy()
    got = np.asarray(gpt2.forward(params, jnp.asarray(ids, jnp.int32), cfg))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_gpt2_train_step_reduces_loss():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(jax.random.key(1), cfg)
    batch = jax.random.randint(jax.random.key(2), (4, 17), 0,
                               cfg.vocab_size, jnp.int32)
    import functools
    step = jax.jit(functools.partial(gpt2.train_step, cfg=cfg, lr=1e-2))
    params, loss0 = step(params, batch)
    for _ in range(5):
        params, loss = step(params, batch)
    assert float(loss) < float(loss0)


def test_gpt2_sharded_train_step():
    """The dryrun path: params sharded per param_specs over data×model."""
    cfg = gpt2.GPT2Config.tiny()
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4),
                ("data", "model"))
    params = gpt2.init_params(jax.random.key(0), cfg)
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, gpt2.param_specs(cfg),
    )
    batch = jax.device_put(
        jnp.zeros((4, 17), jnp.int32),
        NamedSharding(mesh, P("data", None)),
    )
    import functools
    step = jax.jit(functools.partial(gpt2.train_step, cfg=cfg))
    new_params, loss = step(params, batch)
    jax.block_until_ready(loss)
    assert np.isfinite(float(loss))
    # sharding survived the step
    qkv = new_params["blocks"]["attn"]["qkv_w"]
    assert qkv.sharding.spec == P(None, None, "model")


def test_gpt2_generate_greedy_is_causal_consistent():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(jax.random.key(3), cfg)
    out = gpt2.generate_greedy(params, cfg, [1, 2, 3], steps=4)
    assert out.shape == (7,)
    assert list(np.asarray(out[:3])) == [1, 2, 3]
    # determinism
    out2 = gpt2.generate_greedy(params, cfg, [1, 2, 3], steps=4)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


# ── driver entry points ──


def test_graft_entry_single_chip():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[1].shape[0]
    assert bool(jnp.all(jnp.isfinite(out.astype(jnp.float32))))


def test_gpt2_generate_rejects_context_overflow():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(jax.random.key(3), cfg)
    with pytest.raises(ValueError, match="n_ctx"):
        gpt2.generate_greedy(params, cfg, list(range(60)), steps=10)


def test_real_gpt2_vocab_lands_on_mesh(tmp_path):
    """Regression: vocab 50257 divides no axis — wte must land replicated
    or embedding-dim-sharded, never raise."""
    mesh = _mesh8()
    spec = spec_for("wte.weight", (50257, 768), mesh,
                    gpt2.checkpoint_shard_rules())
    assert spec in (P(), P(None, "model"))
    arr = np.zeros((50257, 16), np.float32)
    landed = jax.device_put(arr, NamedSharding(mesh, spec))
    assert landed.shape == arr.shape


@pytest.mark.slow
def test_graft_entry_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)
    ge.dryrun_multichip(4)  # subset of local devices must also work
