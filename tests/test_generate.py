"""`zest-tpu generate`: pull + family-model greedy decode — the
reference's verify loop (test/local/verify-model.sh:103-147) as a native
command over the pure-JAX models."""

import json

import numpy as np
import pytest

from tests.fixtures import FixtureHub, FixtureRepo, gpt2_checkpoint_files
from zest_tpu.models.generate import (
    UnsupportedModelError,
    load_generator,
    try_tokenizer,
)


def write_gpt2_snapshot(root):
    files = gpt2_checkpoint_files(n_embd=64, n_layer=2)
    root.mkdir(parents=True, exist_ok=True)
    for name, blob in files.items():
        (root / name).write_bytes(blob)
    return root


def test_load_generator_gpt2(tmp_path):
    snap = write_gpt2_snapshot(tmp_path / "snap")
    model_type, generate = load_generator(snap)
    assert model_type == "gpt2"
    out = generate([1, 2, 3], 5)
    assert out.shape == (8,)
    assert list(out[:3]) == [1, 2, 3]
    # Deterministic
    np.testing.assert_array_equal(out, generate([1, 2, 3], 5))


def test_load_generator_llama(tmp_path):
    import jax

    from zest_tpu.models import llama
    from zest_tpu.models.safetensors_io import write_safetensors

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    # Round-trip through HF-style names: build a state dict the mapper
    # understands (transpose back to [out, in]).
    tensors = {
        "model.embed_tokens.weight": np.asarray(params["wte"]),
        "model.norm.weight": np.asarray(params["ln_f"]["g"]),
        "lm_head.weight": np.asarray(params["lm_head"]).T,
    }
    b = params["blocks"]
    for layer in range(cfg.n_layer):
        pre = f"model.layers.{layer}."
        tensors[pre + "input_layernorm.weight"] = \
            np.asarray(b["ln_attn"]["g"][layer])
        tensors[pre + "post_attention_layernorm.weight"] = \
            np.asarray(b["ln_mlp"]["g"][layer])
        for hf, leaf in [("self_attn.q_proj", "q_w"),
                         ("self_attn.k_proj", "k_w"),
                         ("self_attn.v_proj", "v_w"),
                         ("self_attn.o_proj", "o_w")]:
            tensors[pre + hf + ".weight"] = \
                np.asarray(b["attn"][leaf][layer]).T
        for hf, leaf in [("mlp.gate_proj", "gate_w"),
                         ("mlp.up_proj", "up_w"),
                         ("mlp.down_proj", "down_w")]:
            tensors[pre + hf + ".weight"] = \
                np.asarray(b["mlp"][leaf][layer]).T
    snap = tmp_path / "snap"
    snap.mkdir()
    write_safetensors(snap / "model.safetensors", tensors)
    (snap / "config.json").write_text(json.dumps(dict(
        model_type="llama", vocab_size=cfg.vocab_size, hidden_size=64,
        intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0,
    )))
    model_type, generate = load_generator(snap)
    assert model_type == "llama"
    out = generate([5, 6], 4)
    want = llama.generate_cached(params, llama.LlamaConfig.from_hf(
        json.loads((snap / "config.json").read_text())), [5, 6], 4)
    np.testing.assert_array_equal(out, np.asarray(want))


def test_load_generator_unsupported(tmp_path):
    (tmp_path / "config.json").write_text('{"model_type": "rwkv"}')
    with pytest.raises(UnsupportedModelError, match="rwkv"):
        load_generator(tmp_path)


def test_load_generator_missing_weights(tmp_path):
    (tmp_path / "config.json").write_text('{"model_type": "gpt2"}')
    with pytest.raises(FileNotFoundError, match="safetensors"):
        load_generator(tmp_path)


def test_try_tokenizer_absent(tmp_path):
    assert try_tokenizer(tmp_path) is None


def test_cli_generate_end_to_end(tmp_path, monkeypatch, capsys):
    """Full loop through the CLI: fixture hub → pull → decode → ids out."""
    from zest_tpu import cli

    files = gpt2_checkpoint_files(n_embd=64, n_layer=2)
    repo = FixtureRepo("acme/gen-gpt2", files, chunks_per_xorb=4)
    with FixtureHub(repo) as hub:
        monkeypatch.setenv("HF_HOME", str(tmp_path / "hf"))
        monkeypatch.setenv("ZEST_CACHE_DIR", str(tmp_path / "zest"))
        monkeypatch.setenv("HF_TOKEN", "hf_test")
        monkeypatch.setenv("HF_ENDPOINT", hub.url)
        rc = cli.main(["generate", "acme/gen-gpt2",
                       "--ids", "1,2,3", "--steps", "4", "--no-p2p"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "[gpt2] 3 prompt + 4 new tokens" in out
    last = out.strip().splitlines()[-1]
    ids = [int(t) for t in last.split(",")]
    assert len(ids) == 7 and ids[:3] == [1, 2, 3]


def test_cli_generate_requires_prompt_or_ids(tmp_path, monkeypatch, capsys):
    from zest_tpu import cli

    files = gpt2_checkpoint_files(n_embd=64, n_layer=2)
    repo = FixtureRepo("acme/gen2", files, chunks_per_xorb=4)
    with FixtureHub(repo) as hub:
        monkeypatch.setenv("HF_HOME", str(tmp_path / "hf"))
        monkeypatch.setenv("ZEST_CACHE_DIR", str(tmp_path / "zest"))
        monkeypatch.setenv("HF_TOKEN", "hf_test")
        monkeypatch.setenv("HF_ENDPOINT", hub.url)
        rc = cli.main(["generate", "acme/gen2", "--no-p2p"])
        assert rc == 2
        rc = cli.main(["generate", "acme/gen2", "--no-p2p",
                       "--ids", "1,x"])
        assert rc == 2
        # No tokenizer in the fixture snapshot → --prompt must fail clean.
        rc = cli.main(["generate", "acme/gen2", "--no-p2p",
                       "--prompt", "hello"])
        assert rc == 2
        # Context overflow: clean error, not a traceback (n_ctx=64).
        rc = cli.main(["generate", "acme/gen2", "--no-p2p",
                       "--ids", "1,2", "--steps", "100"])
        assert rc == 1
        # Non-positive steps rejected before any pull.
        rc = cli.main(["generate", "acme/gen2", "--no-p2p",
                       "--ids", "1", "--steps", "0"])
        assert rc == 2
    err = capsys.readouterr().err
    assert "required" in err and "tokenizer" in err
    assert "exceeds" in err and "positive" in err