"""`zest-tpu generate`: pull + family-model greedy decode — the
reference's verify loop (test/local/verify-model.sh:103-147) as a native
command over the pure-JAX models."""

import json

import numpy as np
import pytest

from tests.fixtures import FixtureHub, FixtureRepo, gpt2_checkpoint_files
from zest_tpu.models.generate import (
    UnsupportedModelError,
    load_generator,
    try_tokenizer,
)


def write_gpt2_snapshot(root):
    files = gpt2_checkpoint_files(n_embd=64, n_layer=2)
    root.mkdir(parents=True, exist_ok=True)
    for name, blob in files.items():
        (root / name).write_bytes(blob)
    return root


def test_load_generator_gpt2(tmp_path):
    snap = write_gpt2_snapshot(tmp_path / "snap")
    model_type, generate = load_generator(snap)
    assert model_type == "gpt2"
    out = generate([1, 2, 3], 5)
    assert out.shape == (8,)
    assert list(out[:3]) == [1, 2, 3]
    # Deterministic
    np.testing.assert_array_equal(out, generate([1, 2, 3], 5))


def test_load_generator_llama(tmp_path):
    import jax

    from zest_tpu.models import llama
    from zest_tpu.models.safetensors_io import write_safetensors

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    # Round-trip through HF-style names: build a state dict the mapper
    # understands (transpose back to [out, in]).
    tensors = {
        "model.embed_tokens.weight": np.asarray(params["wte"]),
        "model.norm.weight": np.asarray(params["ln_f"]["g"]),
        "lm_head.weight": np.asarray(params["lm_head"]).T,
    }
    b = params["blocks"]
    for layer in range(cfg.n_layer):
        pre = f"model.layers.{layer}."
        tensors[pre + "input_layernorm.weight"] = \
            np.asarray(b["ln_attn"]["g"][layer])
        tensors[pre + "post_attention_layernorm.weight"] = \
            np.asarray(b["ln_mlp"]["g"][layer])
        for hf, leaf in [("self_attn.q_proj", "q_w"),
                         ("self_attn.k_proj", "k_w"),
                         ("self_attn.v_proj", "v_w"),
                         ("self_attn.o_proj", "o_w")]:
            tensors[pre + hf + ".weight"] = \
                np.asarray(b["attn"][leaf][layer]).T
        for hf, leaf in [("mlp.gate_proj", "gate_w"),
                         ("mlp.up_proj", "up_w"),
                         ("mlp.down_proj", "down_w")]:
            tensors[pre + hf + ".weight"] = \
                np.asarray(b["mlp"][leaf][layer]).T
    snap = tmp_path / "snap"
    snap.mkdir()
    write_safetensors(snap / "model.safetensors", tensors)
    (snap / "config.json").write_text(json.dumps(dict(
        model_type="llama", vocab_size=cfg.vocab_size, hidden_size=64,
        intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0,
    )))
    model_type, generate = load_generator(snap)
    assert model_type == "llama"
    out = generate([5, 6], 4)
    want = llama.generate_cached(params, llama.LlamaConfig.from_hf(
        json.loads((snap / "config.json").read_text())), [5, 6], 4)
    np.testing.assert_array_equal(out, np.asarray(want))


def test_sampling_semantics():
    """temperature=0 and top_k=1 are greedy; temperature>0 is seeded and
    deterministic per seed, varied across seeds."""
    import jax
    import jax.numpy as jnp

    from zest_tpu.models import llama
    from zest_tpu.models.sampling import sample_token

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    prompt = [3, 7, 1]
    greedy = llama.generate_cached(params, cfg, prompt, 8)
    np.testing.assert_array_equal(
        np.asarray(llama.generate_cached(params, cfg, prompt, 8,
                                         temperature=1.0, top_k=1)),
        np.asarray(greedy),
    )
    s1 = llama.generate_cached(params, cfg, prompt, 8, temperature=1.0,
                               rng=jax.random.key(1))
    s1b = llama.generate_cached(params, cfg, prompt, 8, temperature=1.0,
                                rng=jax.random.key(1))
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s1b))
    # A sampled draw differs from greedy for SOME seed (vocab is wide).
    diffs = [
        not np.array_equal(
            np.asarray(llama.generate_cached(
                params, cfg, prompt, 8, temperature=2.0,
                rng=jax.random.key(s))),
            np.asarray(greedy))
        for s in range(4)
    ]
    assert any(diffs)
    # top_k masks everything outside the k best.
    logits = jnp.asarray([0.0, 5.0, 4.0, -1.0])
    for s in range(8):
        tok = int(sample_token(logits, jax.random.key(s),
                               temperature=5.0, top_k=2))
        assert tok in (1, 2)
    # top_k beyond the vocab means "no restriction", not a top_k error.
    tok = int(sample_token(logits, jax.random.key(0),
                           temperature=1.0, top_k=100000))
    assert 0 <= tok < 4


def test_nucleus_sampling():
    """top_p keeps the smallest prefix of the sorted distribution whose
    mass reaches p (the crossing token included, HF semantics); a tiny p
    degenerates to greedy; p>=1 is unrestricted; composes with top_k and
    is jit-safe (static shapes throughout)."""
    import jax
    import jax.numpy as jnp

    from zest_tpu.models.sampling import sample_token

    # probs ~ [0.643, 0.237, 0.087, 0.032, 0.00059] over tokens 3,0,2,4,1
    logits = jnp.asarray([4.0, -2.0, 3.0, 5.0, 2.0])
    for s in range(16):
        # p=0.7: mass before token 0 is 0.643 < 0.7, before token 2 is
        # 0.88 >= 0.7 — nucleus is exactly {3, 0}.
        tok = int(sample_token(logits, jax.random.key(s),
                               temperature=1.0, top_p=0.7))
        assert tok in (0, 3), tok
        # tiny p: only the argmax survives.
        tok = int(sample_token(logits, jax.random.key(s),
                               temperature=3.0, top_p=1e-6))
        assert tok == 3
        # top_k=3 ∩ top_p=0.7 is still {3, 0}.
        tok = int(sample_token(logits, jax.random.key(s),
                               temperature=1.0, top_k=3, top_p=0.7))
        assert tok in (0, 3)
    # p >= 1 imposes no restriction (and must not mask the tail away).
    seen = {int(sample_token(logits, jax.random.key(s),
                             temperature=50.0, top_p=1.0))
            for s in range(64)}
    assert len(seen) >= 4
    # Jit-compatible (the decode loop jits the whole scan around it).
    jitted = jax.jit(lambda l, k: sample_token(l, k, 1.0, None, 0.7))
    assert int(jitted(logits, jax.random.key(0))) in (0, 3)
    # Out-of-range p is an error, not a silent no-restriction.
    for bad in (0.0, -0.5, 1.5):
        with pytest.raises(ValueError, match="top_p"):
            sample_token(logits, jax.random.key(0), 1.0, None, bad)
    # Ties at the nucleus boundary don't widen it: with ALL logits
    # equal, a tiny p must still degenerate to one token (the stable
    # argsort keeps the earliest index, as HF's sorted-gather does).
    flat = jnp.zeros((16,))
    for s in range(8):
        assert int(sample_token(flat, jax.random.key(s),
                                temperature=5.0, top_p=1e-6)) == 0


def test_eos_stop_and_trim(tmp_path):
    """A generated eos_token_id freezes the row and the generator trims
    just past it; stop_at_eos=False keeps the full buffer; prompt
    occurrences of the EOS id don't stop anything."""
    snap = write_gpt2_snapshot(tmp_path / "snap")
    _, generate = load_generator(snap)
    base = generate([1, 2], 8)          # no eos_token_id in config: full
    assert base.shape == (10,)
    eos = int(base[4])                  # the 3rd generated token
    # The tiny model may repeat tokens: the stop happens at the FIRST
    # generated occurrence, wherever that is.
    first = 2 + next(i for i, t in enumerate(base[2:]) if t == eos)
    cfg = json.loads((snap / "config.json").read_text())
    cfg["eos_token_id"] = eos
    (snap / "config.json").write_text(json.dumps(cfg))
    _, generate = load_generator(snap)
    assert generate.eos_ids == (eos,)
    out = generate([1, 2], 8)
    np.testing.assert_array_equal(out, base[:first + 1])
    assert int(out[-1]) == eos
    # Full buffer on request; the frozen tail repeats EOS.
    full = generate([1, 2], 8, stop_at_eos=False)
    assert full.shape == (10,)
    np.testing.assert_array_equal(full, base)
    # EOS in the *prompt* doesn't count as a stop.
    out = generate([1, eos, 2], 8)
    assert len(out) > 3
    # eos_token_id as a list (HF allows several, e.g. Llama-3's two
    # ids): ALL entries stop generation, not just the first. Put the
    # observed stop token in the SECOND slot — generation must still
    # stop at it, and the frozen tail pads with the FIRST id.
    cfg["eos_token_id"] = [999, eos]
    (snap / "config.json").write_text(json.dumps(cfg))
    _, generate = load_generator(snap)
    assert generate.eos_ids == (999, eos)
    out = generate([1, 2], 8)
    np.testing.assert_array_equal(out, base[:first + 1])
    assert int(out[-1]) == eos


def test_eos_freezes_rows_independently():
    """Batched decode: a row that generates EOS pads the rest of its row
    with EOS without disturbing the other rows."""
    import jax

    from zest_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    prompts = np.asarray([[3, 7, 1], [5, 2, 9]])
    base = np.asarray(llama.generate_cached(params, cfg, prompts, 8))
    eos = int(base[0, 4])
    if eos == int(base[1, 4]):  # want the rows to stop at different times
        eos = int(base[0, 5])
    out = np.asarray(llama.generate_cached(params, cfg, prompts, 8,
                                           eos_id=eos))
    row0 = list(base[0]).index(eos, 3)
    assert set(out[0, row0:].tolist()) == {eos}
    # A tuple of stop ids: stops on the SECOND listed id (999 is out of
    # the tiny vocab so only `eos` can fire) and the frozen tail pads
    # with the FIRST listed id — Llama-3-style multi-EOS semantics.
    out2 = np.asarray(llama.generate_cached(params, cfg, prompts, 8,
                                            eos_id=(999, eos)))
    np.testing.assert_array_equal(out2[0, :row0 + 1], base[0, :row0 + 1])
    assert set(out2[0, row0 + 1:].tolist()) <= {999}
    np.testing.assert_array_equal(out[0, :row0 + 1], base[0, :row0 + 1])
    # Row 1 is untouched up to its own first generated EOS (if any).
    hits = [i for i, t in enumerate(base[1]) if t == eos and i >= 3]
    end1 = hits[0] + 1 if hits else base.shape[1]
    np.testing.assert_array_equal(out[1, :end1], base[1, :end1])


def test_on_token_streams_every_position():
    """The ordered io_callback reports, in order, every *generated*
    position on the prefill path (family generate_cached) and every
    written position on the sequential path; streamed tokens agree
    with the returned buffer either way."""
    import jax

    from zest_tpu.models import llama
    from zest_tpu.models.sampling import cached_decode_loop

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    seen = []
    out = np.asarray(llama.generate_cached(
        params, cfg, [3, 7, 1], 6,
        on_token=lambda pos, toks: seen.append(
            (int(pos), int(np.asarray(toks).ravel()[0]))),
    ))
    # No effects_barrier: the loop's per-request sentinel drain
    # guarantees every callback has been delivered before it returns.
    assert [p for p, _ in seen] == list(range(3, 9))  # generated only
    for pos, tid in seen:
        assert out[pos] == tid
    seen_seq = []
    out_seq = np.asarray(cached_decode_loop(
        llama.init_kv_cache, llama.decode_step, params, cfg, [3, 7, 1], 6,
        on_token=lambda pos, toks: seen_seq.append(
            (int(pos), int(np.asarray(toks).ravel()[0]))),
    ))
    assert [p for p, _ in seen_seq] == list(range(1, 9))  # all written
    for pos, tid in seen_seq:
        assert out_seq[pos] == tid


def test_generate_top_p_threading(tmp_path):
    snap = write_gpt2_snapshot(tmp_path / "snap")
    _, generate = load_generator(snap)
    g = generate([1, 2], 5)
    # A degenerate nucleus is greedy regardless of temperature.
    s = generate([1, 2], 5, temperature=2.0, top_p=1e-6)
    np.testing.assert_array_equal(g, s)
    s2 = generate([1, 2], 5, temperature=1.5, top_p=0.9, seed=3)
    assert s2.shape == (7,)


def test_gpt2_sampling_matches_greedy_at_topk1(tmp_path):
    snap = write_gpt2_snapshot(tmp_path / "snap")
    _, generate = load_generator(snap)
    g = generate([1, 2], 5)
    s = generate([1, 2], 5, temperature=0.7, top_k=1)
    np.testing.assert_array_equal(g, s)
    s2 = generate([1, 2], 5, temperature=1.5, seed=3)
    assert s2.shape == (7,)


def test_load_generator_unsupported(tmp_path):
    (tmp_path / "config.json").write_text('{"model_type": "rwkv"}')
    with pytest.raises(UnsupportedModelError, match="rwkv"):
        load_generator(tmp_path)


def test_load_generator_missing_weights(tmp_path):
    (tmp_path / "config.json").write_text('{"model_type": "gpt2"}')
    with pytest.raises(FileNotFoundError, match="safetensors"):
        load_generator(tmp_path)


def test_try_tokenizer_absent(tmp_path):
    assert try_tokenizer(tmp_path) is None


def test_cli_generate_end_to_end(tmp_path, monkeypatch, capsys):
    """Full loop through the CLI: fixture hub → pull → decode → ids out."""
    from zest_tpu import cli

    files = gpt2_checkpoint_files(n_embd=64, n_layer=2)
    repo = FixtureRepo("acme/gen-gpt2", files, chunks_per_xorb=4)
    with FixtureHub(repo) as hub:
        monkeypatch.setenv("HF_HOME", str(tmp_path / "hf"))
        monkeypatch.setenv("ZEST_CACHE_DIR", str(tmp_path / "zest"))
        monkeypatch.setenv("HF_TOKEN", "hf_test")
        monkeypatch.setenv("HF_ENDPOINT", hub.url)
        rc = cli.main(["generate", "acme/gen-gpt2",
                       "--ids", "1,2,3", "--steps", "4", "--no-p2p"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "[gpt2] 3 prompt + 4 new tokens" in out
    last = out.strip().splitlines()[-1]
    ids = [int(t) for t in last.split(",")]
    assert len(ids) == 7 and ids[:3] == [1, 2, 3]


def test_http_generate_endpoint(tmp_path):
    """POST /v1/generate: pull + decode streamed as SSE, ids in `done`."""
    import requests

    from zest_tpu.api.http_api import HttpApi
    from zest_tpu.config import Config

    files = gpt2_checkpoint_files(n_embd=64, n_layer=2)
    repo = FixtureRepo("acme/api-gen", files, chunks_per_xorb=4)
    with FixtureHub(repo) as hub:
        cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                     hf_token="hf_test", endpoint=hub.url, http_port=0)
        api = HttpApi(cfg)
        port = api.start()
        try:
            r = requests.post(
                f"http://127.0.0.1:{port}/v1/generate",
                json={"repo_id": "acme/api-gen", "ids": [1, 2, 3],
                      "steps": 4},
                timeout=120, stream=True,
            )
            events = [json.loads(line[len("data: "):])
                      for line in r.iter_lines(decode_unicode=True)
                      if line.startswith("data: ")]
        finally:
            api.close()
    kinds = [e["event"] for e in events]
    assert kinds[0] == "start" and "pulled" in kinds
    done = events[-1]
    assert done["event"] == "done", events
    assert done["model_type"] == "gpt2"
    assert done["ids"][:3] == [1, 2, 3] and len(done["ids"]) == 7


def test_generator_cache_single_flight_and_lru(tmp_config, monkeypatch):
    """Concurrent first requests share one load; hits refresh LRU order."""
    import threading
    import time

    import zest_tpu.models.generate as gen_mod
    from zest_tpu.api.http_api import HttpApi

    api = HttpApi(tmp_config)
    calls = []

    def slow_load(snapshot_dir):
        calls.append(str(snapshot_dir))
        time.sleep(0.2)
        return ("fake", lambda *a, **k: None)

    monkeypatch.setattr(gen_mod, "load_generator", slow_load)
    results = []
    threads = [
        threading.Thread(
            target=lambda: results.append(api._generator_for("/snap/a"))
        )
        for _ in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(calls) == 1          # single flight
    assert all(r == ("fake", results[0][1]) for r in results)
    # LRU: fill exactly to the bound (a,b,c,d), HIT a to refresh its
    # recency, then overflow — the eviction must take b, not a.
    for name in ("b", "c", "d"):
        api._generator_for(f"/snap/{name}")
    assert len(calls) == 4             # a,b,c,d each loaded once
    api._generator_for("/snap/a")      # cache hit → move-to-end
    assert len(calls) == 4             # ...and not a reload
    api._generator_for("/snap/e")      # overflow evicts b (oldest)
    assert "/snap/a" in api._generators
    assert "/snap/b" not in api._generators


def test_http_generate_rejects_bad_body(tmp_config):
    import requests

    from zest_tpu.api.http_api import HttpApi

    tmp_config.http_port = 0
    # Hermeticity: the missing-prompt request below drives a pull; point
    # the hub at a closed local port so failure is immediate, not a live
    # huggingface.co dependency.
    tmp_config.endpoint = "http://127.0.0.1:9"
    api = HttpApi(tmp_config)
    port = api.start()
    try:
        r = requests.post(f"http://127.0.0.1:{port}/v1/generate",
                          data=b"not json", timeout=5)
        assert r.status_code == 400
        # Valid JSON that isn't an object must also 400, not crash.
        r = requests.post(f"http://127.0.0.1:{port}/v1/generate",
                          data=b"[1, 2]", timeout=5)
        assert r.status_code == 400
        r = requests.post(f"http://127.0.0.1:{port}/v1/pull",
                          data=b"123", timeout=5)
        assert r.status_code == 400
        # Missing prompt/ids surfaces as an SSE error event, not a crash.
        r = requests.post(f"http://127.0.0.1:{port}/v1/generate",
                          json={"repo_id": "no/such"}, timeout=30)
        events = [json.loads(line[len("data: "):])
                  for line in r.text.splitlines()
                  if line.startswith("data: ")]
        assert events[-1]["event"] == "error"
    finally:
        api.close()


def test_cli_pull_profile_writes_trace(tmp_path, monkeypatch, capsys):
    """--profile wraps the pull in jax.profiler.trace and produces a
    TensorBoard-consumable trace directory."""
    from zest_tpu import cli

    files = gpt2_checkpoint_files(n_embd=64, n_layer=1)
    repo = FixtureRepo("acme/prof-cli", files, chunks_per_xorb=4)
    with FixtureHub(repo) as hub:
        monkeypatch.setenv("HF_HOME", str(tmp_path / "hf"))
        monkeypatch.setenv("ZEST_CACHE_DIR", str(tmp_path / "zest"))
        monkeypatch.setenv("HF_TOKEN", "hf_test")
        monkeypatch.setenv("HF_ENDPOINT", hub.url)
        trace = tmp_path / "trace"
        rc = cli.main(["pull", "acme/prof-cli", "--no-p2p", "--no-seed",
                       "--profile", str(trace)])
    assert rc == 0
    assert "profiler trace written" in capsys.readouterr().out
    assert any(p.is_file() for p in trace.rglob("*"))


def test_cli_generate_requires_prompt_or_ids(tmp_path, monkeypatch, capsys):
    from zest_tpu import cli

    files = gpt2_checkpoint_files(n_embd=64, n_layer=2)
    repo = FixtureRepo("acme/gen2", files, chunks_per_xorb=4)
    with FixtureHub(repo) as hub:
        monkeypatch.setenv("HF_HOME", str(tmp_path / "hf"))
        monkeypatch.setenv("ZEST_CACHE_DIR", str(tmp_path / "zest"))
        monkeypatch.setenv("HF_TOKEN", "hf_test")
        monkeypatch.setenv("HF_ENDPOINT", hub.url)
        rc = cli.main(["generate", "acme/gen2", "--no-p2p"])
        assert rc == 2
        rc = cli.main(["generate", "acme/gen2", "--no-p2p",
                       "--ids", "1,x"])
        assert rc == 2
        # No tokenizer in the fixture snapshot → --prompt must fail clean.
        rc = cli.main(["generate", "acme/gen2", "--no-p2p",
                       "--prompt", "hello"])
        assert rc == 2
        # Context overflow: clean error, not a traceback (n_ctx=64).
        rc = cli.main(["generate", "acme/gen2", "--no-p2p",
                       "--ids", "1,2", "--steps", "100"])
        assert rc == 1
        # Non-positive steps rejected before any pull.
        rc = cli.main(["generate", "acme/gen2", "--no-p2p",
                       "--ids", "1", "--steps", "0"])
        assert rc == 2
    err = capsys.readouterr().err
    assert "required" in err and "tokenizer" in err
    assert "exceeds" in err and "positive" in err

def test_http_generate_streams_tokens(tmp_path):
    """POST /v1/generate with stream:true: one `token` SSE event per
    generated position (prompt prefill filtered out), consistent with
    the final `done` ids."""
    import requests

    from zest_tpu.api.http_api import HttpApi
    from zest_tpu.config import Config

    files = gpt2_checkpoint_files(n_embd=64, n_layer=2)
    repo = FixtureRepo("acme/api-stream", files, chunks_per_xorb=4)
    with FixtureHub(repo) as hub:
        cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                     hf_token="hf_test", endpoint=hub.url, http_port=0)
        api = HttpApi(cfg)
        port = api.start()
        try:
            r = requests.post(
                f"http://127.0.0.1:{port}/v1/generate",
                json={"repo_id": "acme/api-stream", "ids": [1, 2, 3],
                      "steps": 4, "stream": True},
                timeout=120, stream=True,
            )
            events = [json.loads(line[len("data: "):])
                      for line in r.iter_lines(decode_unicode=True)
                      if line.startswith("data: ")]
        finally:
            api.close()
    done = events[-1]
    assert done["event"] == "done", events
    tokens = [e for e in events if e["event"] == "token"]
    assert [t["pos"] for t in tokens] == [3, 4, 5, 6]
    for t in tokens:
        assert done["ids"][t["pos"]] == t["id"]


def test_http_generate_memoizes_pull(tmp_path):
    """Warm /v1/generate requests skip the hub entirely: the resolved
    snapshot is memoized for a short TTL, so the second request makes
    ZERO hub round-trips (the pull idempotence re-check was the bulk of
    warm-request latency)."""
    import requests

    from zest_tpu.api.http_api import HttpApi
    from zest_tpu.config import Config

    files = gpt2_checkpoint_files(n_embd=64, n_layer=2)
    repo = FixtureRepo("acme/api-memo", files, chunks_per_xorb=4)
    with FixtureHub(repo) as hub:
        cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                     hf_token="hf_test", endpoint=hub.url, http_port=0)
        api = HttpApi(cfg)
        port = api.start()
        try:
            body = {"repo_id": "acme/api-memo", "ids": [1, 2], "steps": 3}

            def request():
                r = requests.post(
                    f"http://127.0.0.1:{port}/v1/generate", json=body,
                    timeout=120, stream=True)
                evs = [json.loads(l[len("data: "):])
                       for l in r.iter_lines(decode_unicode=True)
                       if l.startswith("data: ")]
                assert evs[-1]["event"] == "done", evs[-1]
                return evs[-1]

            first = request()
            n_before = len(hub.requests_seen)
            second = request()
            assert len(hub.requests_seen) == n_before  # memo hit: no hub
            assert second["ids"] == first["ids"]
        finally:
            api.close()


@pytest.mark.slow
def test_prefill_matches_sequential_decode():
    """The batched prefill (family decode_window) must be token-identical
    to the sequential replay path, greedy and sampled, single and
    batched, for every family — same per-position keys, same cache
    contents, same logits."""
    import jax

    from zest_tpu.models import gpt2, llama, moe
    from zest_tpu.models.sampling import cached_decode_loop

    cases = [
        (gpt2, gpt2.GPT2Config.tiny()),
        (llama, llama.LlamaConfig.tiny()),
        (moe, moe.MoEConfig.tiny()),
    ]
    for fam, cfg in cases:
        params = fam.init_params(jax.random.key(0), cfg)
        for prompt in ([3, 7, 1, 4, 2], [[3, 7, 1], [5, 2, 9]]):
            for kw in (dict(),
                       dict(temperature=1.3, top_p=0.9,
                            rng=jax.random.key(5))):
                seq = cached_decode_loop(
                    fam.init_kv_cache, fam.decode_step, params, cfg,
                    prompt, 6, **kw)              # no prefill_step
                pre = fam.generate_cached(params, cfg, prompt, 6, **kw)
                np.testing.assert_array_equal(
                    np.asarray(pre), np.asarray(seq),
                    err_msg=f"{fam.__name__} prompt={prompt} kw={kw}")


def test_prefill_respects_eos():
    """EOS freezing is identical on the prefill path — including an EOS
    sampled as the very first generated token (the prefill's sample)."""
    import jax

    from zest_tpu.models import llama
    from zest_tpu.models.sampling import cached_decode_loop

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    base = np.asarray(llama.generate_cached(params, cfg, [3, 7, 1], 8))
    first_gen = int(base[3])
    out = np.asarray(llama.generate_cached(params, cfg, [3, 7, 1], 8,
                                           eos_id=first_gen))
    assert set(out[3:].tolist()) == {first_gen}
    seq = cached_decode_loop(
        llama.init_kv_cache, llama.decode_step, params, cfg,
        [3, 7, 1], 8, eos_id=first_gen)
    np.testing.assert_array_equal(out, np.asarray(seq))


@pytest.mark.slow
def test_concurrent_streams_do_not_serialize():
    """A short streamed decode must complete while a long one is still
    in flight. Under the old global jax.effects_barrier() drain, the
    short request's return blocked on the long request's ENTIRE decode
    (so by the time it returned, the long stream had delivered all its
    tokens); the per-request pos=-1 sentinel drains only the caller's
    own callbacks."""
    import threading

    import jax

    from zest_tpu.models import llama

    # DETERMINISTIC gate (no wall-clock window): the long stream's
    # callback BLOCKS on `release` after its first token. Ordered
    # io_callbacks serialize within one computation, so the long decode
    # provably cannot advance past token 1 — and `release` is only set
    # AFTER the short stream returns. If the short stream's drain used a
    # global barrier (the old bug), it would wait on the long stream's
    # wedged callback queue and deadlock here (caught by the callback's
    # own timeout → loud failure), never falsely pass. Callbacks of
    # DIFFERENT computations run independently (verified: the short
    # stream's relay is not behind the long stream's blocked one).
    cfg = llama.LlamaConfig.tiny(n_ctx=64)
    params = llama.init_params(jax.random.key(0), cfg)
    long_steps, short_steps = 8, 4

    # Pre-compile BOTH streamed signatures so the gated phase exercises
    # decode, not tracing.
    llama.generate_cached(params, cfg, [1, 2], short_steps,
                          on_token=lambda *a: None)
    llama.generate_cached(params, cfg, [1, 2], long_steps,
                          on_token=lambda *a: None)

    release = threading.Event()
    first_token = threading.Event()
    release_was_set_first = []
    long_tokens: list[int] = []

    def long_cb(pos, toks):
        long_tokens.append(int(pos))
        first_token.set()
        # Block the long stream's ordered-callback chain until the test
        # releases it. The timeout turns a global-barrier deadlock into
        # a loud assertion instead of a hung suite.
        release_was_set_first.append(release.wait(120.0))

    long_done = threading.Event()

    def run_long():
        llama.generate_cached(params, cfg, [1, 2], long_steps,
                              on_token=long_cb)
        long_done.set()

    t = threading.Thread(target=run_long, daemon=True)
    t.start()
    assert first_token.wait(60.0), "long stream produced no tokens"

    short_seen: list[int] = []
    llama.generate_cached(params, cfg, [3, 4], short_steps,
                          on_token=lambda pos, toks: short_seen.append(
                              int(pos)))
    # The short stream is fully drained (its own sentinel) while the
    # long stream is PROVABLY incomplete — its callback chain is still
    # blocked on `release`, which nothing has set yet.
    assert len(short_seen) == short_steps
    assert not long_done.is_set(), (
        "long stream completed while its callback was blocked — the "
        "blocking gate is broken"
    )
    assert len(long_tokens) <= 2, (
        f"ordered callbacks ran past the block "
        f"({len(long_tokens)}/{long_steps} delivered)"
    )
    release.set()
    t.join(120.0)
    assert not t.is_alive()
    assert long_done.is_set()
    assert len(long_tokens) == long_steps
    assert all(release_was_set_first), (
        "a long-stream callback timed out waiting for release: the "
        "short stream's drain serialized behind the long stream"
    )
