"""Seeding-tier upload policy tests (ISSUE 12).

The serving half of "the package IS the seeder": rate shaping through
the shared token bucket, choke/unchoke reciprocity over the health
registry's served-bytes book, per-request deadlines with serving-side
strike attribution, quarantine-aware content refusal, graceful drain,
and the chaos fault sites that exercise all of it.
"""

import os
import threading
import time

import pytest

from zest_tpu import faults, storage
from zest_tpu.cas import hashing
from zest_tpu.config import Config
from zest_tpu.p2p import peer_id as peer_id_mod
from zest_tpu.p2p.health import PROVENANCE, ContentProvenance, HealthRegistry
from zest_tpu.p2p.peer import (
    BtPeer,
    ContentRefusedError,
    PeerChokedError,
)
from zest_tpu.shaping import TokenBucket
from zest_tpu.transfer.pull import pull_model
from zest_tpu.transfer.server import BtServer, _ChokeBook
from zest_tpu.transfer.swarm import SwarmDownloader

from fixtures import FixtureHub, FixtureRepo

FILES = {
    "config.json": b'{"model_type": "seedtest"}',
    "model.safetensors": os.urandom(1_500_000),
}


@pytest.fixture(scope="module")
def hub():
    # chunks_per_xorb high enough that the checkpoint lands as ONE big
    # xorb (~1.5 MB): the shaping/drain tests need a transfer long
    # enough to time, and the single-xorb shape is the worst case for
    # fairness anyway.
    repo = FixtureRepo("acme/seed-model", FILES, chunks_per_xorb=64)
    with FixtureHub(repo) as h:
        yield h


@pytest.fixture(autouse=True)
def _clean_slate():
    faults.install(None)
    PROVENANCE.reset()
    yield
    faults.install(None)
    PROVENANCE.reset()


def _cfg(hub, root, **kw):
    return Config(
        hf_home=root / "hf",
        cache_dir=root / "zest",
        hf_token="hf_test",
        endpoint=hub.url,
        listen_port=0,
        **kw,
    )


def _warm_seeder(hub, root, **cfg_kw):
    cfg = _cfg(hub, root, **cfg_kw)
    pull_model(cfg, "acme/seed-model", no_p2p=True)
    return cfg


def _largest_cached_xorb(cfg):
    cache = storage.XorbCache(cfg)
    best, best_len = None, -1
    for key in storage.list_cached_xorbs(cfg):
        blob = cache.get(key)
        if blob is not None and len(blob) > best_len:
            best, best_len = key, len(blob)
    return best


# ── shaping.TokenBucket (promoted from tests/fixtures) ──


def test_token_bucket_enforces_rate():
    bucket = TokenBucket(1_000_000, capacity=50_000)
    t0 = time.monotonic()
    sent = 0
    while sent < 400_000:
        assert bucket.acquire(50_000)
        sent += 50_000
    elapsed = time.monotonic() - t0
    # 400 KB minus the 50 KB burst at 1 MB/s >= ~0.35 s.
    assert elapsed >= 0.25, f"rate not enforced: {elapsed:.3f}s"
    assert elapsed < 2.0


def test_token_bucket_give_up_rolls_back():
    bucket = TokenBucket(10_000, capacity=1_000)
    assert bucket.acquire(1_000)  # drain the burst
    # 100k tokens at 10kB/s = 10s wait; a 50ms deadline must refuse...
    assert not bucket.acquire(100_000,
                              give_up_at=time.monotonic() + 0.05)
    # ...and roll the debit back: a small acquire is near-instant again.
    t0 = time.monotonic()
    assert bucket.acquire(500)
    assert time.monotonic() - t0 < 1.0


def test_fixtures_reexport_is_the_shared_bucket():
    import fixtures

    assert fixtures._TokenBucket is TokenBucket


# ── _ChokeBook (reciprocity ranking + optimistic rotation) ──


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_choke_book_all_unchoked_under_capacity():
    book = _ChokeBook(slots=4, health=None)
    for i in range(5):  # slots + 1
        book.register(i, ("h", i))
    assert all(book.slot(i) == "reciprocal" for i in range(5))
    assert book.counts() == (5, 0)


def test_choke_book_reciprocity_ranks_by_served_bytes():
    clock = _Clock()
    health = HealthRegistry(time_fn=clock)
    book = _ChokeBook(slots=2, health=health, rechoke_s=10.0,
                      time_fn=clock)
    for i in range(5):
        book.register(i, ("h", i))
    health.record_success(("h", 3), nbytes=5_000_000)
    health.record_success(("h", 1), nbytes=2_000_000)
    clock.t += 11  # force a re-rank
    assert book.slot(3) == "reciprocal"
    assert book.slot(1) == "reciprocal"
    unchoked, choked = book.counts()
    assert (unchoked, choked) == (3, 2)  # 2 reciprocal + 1 optimistic
    optimistic = [i for i in (0, 2, 4) if book.slot(i) == "optimistic"]
    assert len(optimistic) == 1


def test_choke_book_optimistic_slot_rotates():
    clock = _Clock()
    health = HealthRegistry(time_fn=clock)
    book = _ChokeBook(slots=1, health=health, rechoke_s=5.0,
                      time_fn=clock)
    for i in range(4):
        book.register(i, ("h", i))
    health.record_success(("h", 0), nbytes=1_000_000)  # permanent winner
    seen = set()
    for _ in range(6):
        clock.t += 6
        for i in (1, 2, 3):
            if book.slot(i) == "optimistic":
                seen.add(i)
    assert seen == {1, 2, 3}, f"rotation stuck: only {seen} got the slot"


def test_choke_book_unregister_reranks():
    book = _ChokeBook(slots=1, health=None)
    for i in range(4):
        book.register(i, ("h", i))
    choked = [i for i in range(4) if book.slot(i) is None]
    assert choked
    for i in choked:
        book.unregister(i)
    remaining = [i for i in range(4) if i not in choked]
    assert all(book.slot(i) is not None for i in remaining)


# ── ContentProvenance ──


def test_provenance_record_clear_and_bound():
    book = ContentProvenance(capacity=3)
    for i in range(5):
        book.record(f"x{i}", ("peer", i))
    assert len(book) == 3
    assert book.source("x0") is None  # oldest aged out
    assert book.source("x4") == ("peer", 4)
    book.clear("x4")
    assert book.source("x4") is None
    book.record("y", None)  # no source, no entry
    assert book.source("y") is None


# ── Server integration (loopback) ──


def test_default_knobs_preserve_loopback_pull(hub, tmp_path):
    """Acceptance pin: with every seed knob unset the serving path is
    behaviorally identical to the pre-policy server — a leecher pull is
    all-peer, zero CDN xorbs, bytes exact."""
    seeder_cfg = _warm_seeder(hub, tmp_path / "seeder")
    server = BtServer(seeder_cfg)
    port = server.start()
    try:
        leech = _cfg(hub, tmp_path / "leech")
        swarm = SwarmDownloader(leech)
        swarm.add_direct_peer("127.0.0.1", port)
        try:
            result = pull_model(leech, "acme/seed-model", swarm=swarm)
        finally:
            swarm.close()
        for name, want in FILES.items():
            assert (result.snapshot_dir / name).read_bytes() == want
        assert result.stats["fetch"]["xorbs"]["cdn"] == 0
        assert result.stats["fetch"]["bytes"]["peer"] > 0
        # No seeding keys leak into PULL stats (serving economics are
        # server-side state, surfaced via /v1/status).
        assert "seeding" not in result.stats
        st = server.get_stats()
        assert st.chunks_served > 0
        assert st.bytes_served > 0
        assert st.refused_quarantined == 0
        assert st.uploads_expired == 0
    finally:
        server.shutdown()


def test_upload_rate_enforced_within_20pct(hub, tmp_path):
    seeder_cfg = _warm_seeder(hub, tmp_path / "seeder")
    seeder_cfg.seed_rate_bps = 1_500_000
    server = BtServer(seeder_cfg)
    port = server.start()
    try:
        key = _largest_cached_xorb(seeder_cfg)
        blob = storage.XorbCache(seeder_cfg).get(key)
        assert len(blob) > 1_000_000, "fixture xorb too small to time"
        from zest_tpu.cas.xorb import XorbReader

        n = len(XorbReader(blob))
        xorb_hash = hashing.hex_to_hash(key)
        peer = BtPeer.connect(
            "127.0.0.1", port,
            peer_id_mod.compute_info_hash(xorb_hash),
            peer_id_mod.generate(),
        )
        try:
            t0 = time.monotonic()
            result = peer.request_chunk(xorb_hash, 0, n)
            elapsed = time.monotonic() - t0
        finally:
            peer.close()
        assert result.data == blob
        # Burst capacity is rate/4; the remainder must flow at the knob.
        floor = (len(blob) - seeder_cfg.seed_rate_bps / 4) \
            / seeder_cfg.seed_rate_bps
        assert elapsed >= 0.8 * floor, (
            f"shaping not enforced: {len(blob)}B in {elapsed:.3f}s "
            f"(expected >= {floor:.3f}s)")
    finally:
        server.shutdown()


def test_choke_flap_pull_survives_without_strikes(hub, tmp_path):
    """A seeder that chokes every request (seeder_choke_flap at 1.0)
    must cost the leecher nothing but a tier change: the pull completes
    via CDN, the choked denials are counted distinctly, and the seeder
    is NOT struck or quarantined — choking is policy, not failure."""
    seeder_cfg = _warm_seeder(hub, tmp_path / "seeder")
    server = BtServer(seeder_cfg)
    port = server.start()
    faults.install("seeder_choke_flap:1.0")
    try:
        leech = _cfg(hub, tmp_path / "leech")
        swarm = SwarmDownloader(leech)
        swarm.add_direct_peer("127.0.0.1", port)
        try:
            result = pull_model(leech, "acme/seed-model", swarm=swarm)
        finally:
            swarm.close()
        for name, want in FILES.items():
            assert (result.snapshot_dir / name).read_bytes() == want
        assert result.stats["swarm"]["peer_choked"] > 0
        assert result.stats["swarm"]["peers_quarantined"] == 0
        assert result.stats["fetch"]["bytes"]["cdn"] > 0
        addr = ("127.0.0.1", port)
        assert not swarm.health.is_quarantined(addr)
        detail = {r["peer"]: r for r in swarm.health.detail()}
        row = detail.get(f"127.0.0.1:{port}")
        assert row is None or row["strikes"] == 0
        assert faults.counters().get("seeder_choke_flap", 0) > 0
    finally:
        faults.install(None)
        server.shutdown()


def test_seeder_stall_expires_without_blaming_reader(hub, tmp_path):
    """seeder_stall past the request deadline: the upload slot frees
    and the connection drops — but the reader is NOT struck, because
    the stall was the server's own (an injected fault / its queue), not
    the reader's. Misattribution here would quarantine healthy leechers
    whenever the seeder itself is congested."""
    seeder_cfg = _warm_seeder(hub, tmp_path / "seeder")
    seeder_cfg.seed_request_deadline_s = 0.2
    server = BtServer(seeder_cfg)
    port = server.start()
    faults.install("seeder_stall:1.0@0.6")
    try:
        key = storage.list_cached_xorbs(seeder_cfg)[0]
        xorb_hash = hashing.hex_to_hash(key)
        peer = BtPeer.connect(
            "127.0.0.1", port,
            peer_id_mod.compute_info_hash(xorb_hash),
            peer_id_mod.generate(),
            listen_port=7777,  # our serving identity, for attribution
        )
        try:
            with pytest.raises(Exception):  # conn dropped mid-protocol
                peer.request_chunk(xorb_hash, 0, 1)
        finally:
            peer.close()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if server.get_stats().uploads_expired:
                break
            time.sleep(0.02)
        st = server.get_stats()
        assert st.uploads_expired >= 1
        rows = {r["peer"]: r for r in server.health.detail()}
        assert "127.0.0.1:7777" not in rows, (
            f"reader blamed for the server's own stall: {rows}")
        assert faults.counters().get("seeder_stall", 0) >= 1
    finally:
        faults.install(None)
        server.shutdown()


def test_stalled_reader_struck_with_distinct_kind(hub, tmp_path):
    """A reader that stops draining its socket mid-upload (tiny RCVBUF,
    never recv()s) times the send out at the request deadline: the
    upload expires AND the reader is struck with ``stalled_reader`` —
    the genuinely-their-fault case, visible in health.detail()."""
    import socket as _socket

    from zest_tpu.p2p import wire

    seeder_cfg = _warm_seeder(hub, tmp_path / "seeder")
    seeder_cfg.seed_request_deadline_s = 0.5
    server = BtServer(seeder_cfg)
    port = server.start()
    try:
        key = _largest_cached_xorb(seeder_cfg)
        blob = storage.XorbCache(seeder_cfg).get(key)
        from zest_tpu.cas.xorb import XorbReader

        n = len(XorbReader(blob))
        xorb_hash = hashing.hex_to_hash(key)
        sock = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        # A few KB of receive window: the ~1.5 MB response must block
        # the server's send once our window + its buffer fill.
        sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_RCVBUF, 4096)
        sock.connect(("127.0.0.1", port))
        stream = wire.SocketStream(sock)
        try:
            from zest_tpu.p2p import bep_xet
            from zest_tpu.p2p.peer import LOCAL_UT_XET_ID

            info_hash = peer_id_mod.compute_info_hash(xorb_hash)
            stream.send_handshake(info_hash, peer_id_mod.generate())
            stream.recv_handshake()
            stream.send_raw(wire.encode_extended(
                0, bep_xet.make_ext_handshake(LOCAL_UT_XET_ID, 7778)))
            # Pipeline enough requests that the aggregate response
            # exceeds any autotuned send buffer (tcp_wmem caps at
            # ~4 MB): one ~1.5 MB response alone can be absorbed
            # whole by the kernel, and then the send never blocks.
            for rid in range(1, 7):
                stream.send_raw(bep_xet.encode_framed(
                    LOCAL_UT_XET_ID,
                    bep_xet.ChunkRequest(rid, xorb_hash, 0, n)))
            # ...and never read: the server's send must hit its
            # deadline and attribute the stall to US.
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if server.get_stats().uploads_expired:
                    break
                time.sleep(0.05)
        finally:
            stream.close()
        assert server.get_stats().uploads_expired >= 1
        rows = {r["peer"]: r for r in server.health.detail()}
        row = rows.get("127.0.0.1:7778")
        assert row is not None, f"no stalled-reader strike: {rows}"
        assert row["strike_kinds"].get("stalled_reader", 0) >= 1
    finally:
        server.shutdown()


def test_upload_corrupt_detected_healed_never_admitted(hub, tmp_path):
    """Serving-side corruption (upload_corrupt at 1.0): every peer
    response is poisoned — the leecher's verify tiers must reject at
    the trust boundary, strike/quarantine the seeder, heal via CDN,
    and land byte-exact files. corrupt-bytes-admitted == 0 is THE
    seeding-tier invariant."""
    seeder_cfg = _warm_seeder(hub, tmp_path / "seeder")
    server = BtServer(seeder_cfg)
    port = server.start()
    faults.install("upload_corrupt:1.0")
    try:
        leech = _cfg(hub, tmp_path / "leech")
        swarm = SwarmDownloader(leech)
        swarm.add_direct_peer("127.0.0.1", port)
        try:
            result = pull_model(leech, "acme/seed-model", swarm=swarm)
        finally:
            swarm.close()
        for name, want in FILES.items():
            got = (result.snapshot_dir / name).read_bytes()
            assert got == want, f"{name}: corrupt bytes admitted"
        detected = (
            result.stats["swarm"]["corrupt_from_peer"]
            + result.stats["fetch"]["resilience"]["corrupt_from_peer"])
        assert detected > 0, "corruption was never even detected"
        assert faults.counters().get("upload_corrupt", 0) > 0
    finally:
        faults.install(None)
        server.shutdown()


def test_quarantined_source_content_refused(hub, tmp_path):
    seeder_cfg = _warm_seeder(hub, tmp_path / "seeder")
    server = BtServer(seeder_cfg)
    port = server.start()
    try:
        keys = storage.list_cached_xorbs(seeder_cfg)
        suspect, clean = keys[0], keys[1] if len(keys) > 1 else None
        bad_peer = ("10.0.0.9", 6881)
        PROVENANCE.record(suspect, bad_peer)
        for _ in range(3):
            server.health.record_failure(bad_peer, kind="corrupt")
        assert server.health.is_quarantined(bad_peer)

        xorb_hash = hashing.hex_to_hash(suspect)
        peer = BtPeer.connect(
            "127.0.0.1", port,
            peer_id_mod.compute_info_hash(xorb_hash),
            peer_id_mod.generate(),
        )
        try:
            with pytest.raises(ContentRefusedError):
                peer.request_chunk(xorb_hash, 0, 1)
            if clean is not None:
                # Unsuspected content still serves on the same conn.
                from zest_tpu.cas.xorb import XorbReader

                blob = storage.XorbCache(seeder_cfg).get(clean)
                n = len(XorbReader(blob))
                res = peer.request_chunk(hashing.hex_to_hash(clean), 0, n)
                assert res.data == blob
        finally:
            peer.close()
        assert server.get_stats().refused_quarantined == 1
    finally:
        server.shutdown()


def test_refusal_degrades_to_cdn_in_full_pull(hub, tmp_path):
    seeder_cfg = _warm_seeder(hub, tmp_path / "seeder")
    server = BtServer(seeder_cfg)
    port = server.start()
    try:
        bad_peer = ("10.0.0.9", 6881)
        for key in storage.list_cached_xorbs(seeder_cfg):
            PROVENANCE.record(key, bad_peer)
        for _ in range(3):
            server.health.record_failure(bad_peer, kind="corrupt")

        leech = _cfg(hub, tmp_path / "leech")
        swarm = SwarmDownloader(leech)
        swarm.add_direct_peer("127.0.0.1", port)
        try:
            result = pull_model(leech, "acme/seed-model", swarm=swarm)
        finally:
            swarm.close()
        for name, want in FILES.items():
            assert (result.snapshot_dir / name).read_bytes() == want
        assert result.stats["swarm"]["peer_refusals"] > 0
        assert result.stats["fetch"]["bytes"]["cdn"] > 0
        # A deliberate refusal is not a failure: the seeder stays clean.
        assert not swarm.health.is_quarantined(("127.0.0.1", port))
    finally:
        server.shutdown()


def test_graceful_drain_completes_inflight_upload(hub, tmp_path):
    """Shutdown mid-upload: the in-flight response finishes whole
    within the drain window — never a truncated-but-accepted blob."""
    seeder_cfg = _warm_seeder(hub, tmp_path / "seeder")
    seeder_cfg.seed_rate_bps = 1_500_000  # ~1s transfer: shutdown lands mid-flight
    server = BtServer(seeder_cfg)
    port = server.start()
    key = _largest_cached_xorb(seeder_cfg)
    blob = storage.XorbCache(seeder_cfg).get(key)
    from zest_tpu.cas.xorb import XorbReader

    n = len(XorbReader(blob))
    xorb_hash = hashing.hex_to_hash(key)
    peer = BtPeer.connect(
        "127.0.0.1", port,
        peer_id_mod.compute_info_hash(xorb_hash), peer_id_mod.generate(),
    )
    got: list = [None]
    err: list = [None]

    def fetch():
        try:
            got[0] = peer.request_chunk(xorb_hash, 0, n)
        except Exception as exc:  # noqa: BLE001 - asserted below
            err[0] = exc

    t = threading.Thread(target=fetch)
    t.start()
    time.sleep(0.25)  # the shaped upload is now mid-frame
    server.shutdown(drain_s=10.0)
    t.join(timeout=15)
    peer.close()
    assert not t.is_alive()
    assert err[0] is None, f"drained upload failed: {err[0]!r}"
    assert got[0].data == blob, "drained upload delivered wrong bytes"
    # And the listener really is closed.
    import socket as _socket

    with pytest.raises(OSError):
        s = _socket.create_connection(("127.0.0.1", port), timeout=0.5)
        s.close()
        raise OSError("port still accepting")  # reached only if connect worked


def test_abrupt_shutdown_never_truncates_accepted(hub, tmp_path):
    """Even with drain_s=0 (abort), a cut upload surfaces as a WIRE
    error at the puller, never as short-but-accepted data: the frame
    length prefix makes truncation loud."""
    seeder_cfg = _warm_seeder(hub, tmp_path / "seeder")
    seeder_cfg.seed_rate_bps = 300_000  # slow enough to cut mid-frame
    server = BtServer(seeder_cfg)
    port = server.start()
    key = _largest_cached_xorb(seeder_cfg)
    blob = storage.XorbCache(seeder_cfg).get(key)
    from zest_tpu.cas.xorb import XorbReader

    n = len(XorbReader(blob))
    xorb_hash = hashing.hex_to_hash(key)
    peer = BtPeer.connect(
        "127.0.0.1", port,
        peer_id_mod.compute_info_hash(xorb_hash), peer_id_mod.generate(),
    )
    got: list = [None]
    err: list = [None]

    def fetch():
        try:
            got[0] = peer.request_chunk(xorb_hash, 0, n)
        except Exception as exc:  # noqa: BLE001 - asserted below
            err[0] = exc

    t = threading.Thread(target=fetch)
    t.start()
    time.sleep(0.3)
    server.shutdown(drain_s=0.0)
    t.join(timeout=15)
    peer.close()
    assert not t.is_alive()
    if got[0] is not None:  # the send won the race: must be whole
        assert got[0].data == blob
    else:
        assert err[0] is not None  # loud failure, not silent truncation


# ── Surfaces ──


def test_status_payload_seeding_block(hub, tmp_path):
    from zest_tpu.api.http_api import HttpApi

    seeder_cfg = _warm_seeder(hub, tmp_path / "seeder")
    seeder_cfg.seed_rate_bps = 123_000
    server = BtServer(seeder_cfg)
    server.start()
    try:
        api = HttpApi(seeder_cfg, bt_server=server)
        payload = api.status_payload()
        seeding = payload["seeding"]
        assert seeding["rate_bps"] == 123_000
        assert seeding["slots"] == seeder_cfg.seed_slots
        for field in ("active_leechers", "unchoked", "choked",
                      "chunks_served", "bytes_served", "choke_events",
                      "refused_quarantined", "uploads_expired"):
            assert field in seeding
    finally:
        server.shutdown()


def test_stats_watch_renders_seed_line():
    from zest_tpu.cli import _stats_watch_lines

    lines = _stats_watch_lines({}, {
        "version": "t", "seeding": {
            "active_leechers": 2, "unchoked": 2, "choked": 1,
            "chunks_served": 7, "bytes_served": 12345,
            "choke_events": 3, "refused_quarantined": 1,
            "uploads_expired": 2, "rate_bps": 1000,
        }})
    seed = [ln for ln in lines if ln.startswith("seed:")]
    assert seed, lines
    assert "12345B in 7 chunks" in seed[0]
    assert "unchoked=2/3" in seed[0]
    assert "refused=1" in seed[0]
    assert "rate=1000B/s" in seed[0]


def test_seed_env_knobs_parse_and_raise():
    env = {"ZEST_SEED_RATE_BPS": "1000000", "ZEST_SEED_PEER_BPS": "2000",
           "ZEST_SEED_SLOTS": "3", "ZEST_SEED_DEADLINE_S": "1.5",
           "ZEST_SEED_DRAIN_S": "2"}
    cfg = Config.load(env)
    assert cfg.seed_rate_bps == 1_000_000
    assert cfg.seed_peer_bps == 2_000
    assert cfg.seed_slots == 3
    assert cfg.seed_request_deadline_s == 1.5
    assert cfg.seed_drain_s == 2.0
    # Unset = policy off / defaults.
    cfg = Config.load({})
    assert cfg.seed_rate_bps == 0
    assert cfg.seed_peer_bps == 0
    with pytest.raises(ValueError):
        Config.load({"ZEST_SEED_RATE_BPS": "fast"})
    with pytest.raises(ValueError):
        Config.load({"ZEST_SEED_SLOTS": "many"})
    with pytest.raises(ValueError):
        Config.load({"ZEST_SEED_DEADLINE_S": "soon"})
    # A sign slip must raise, never silently mean "unshaped"/"tiny".
    with pytest.raises(ValueError):
        Config.load({"ZEST_SEED_RATE_BPS": "-25000000"})
    with pytest.raises(ValueError):
        Config.load({"ZEST_SEED_PEER_BPS": "-1"})
    with pytest.raises(ValueError):
        Config.load({"ZEST_SEED_SLOTS": "0"})
    with pytest.raises(ValueError):
        Config.load({"ZEST_SEED_DEADLINE_S": "-3"})
    with pytest.raises(ValueError):
        Config.load({"ZEST_SEED_DRAIN_S": "-1"})


def test_tracker_uploaded_counter_reads_seed_metric():
    """The announce's ``uploaded`` counter is live seeding economics:
    TrackerClient reads zest_seed_bytes_total from the process registry
    (the counter BtServer bumps per upload) with no extra plumbing."""
    from zest_tpu import telemetry
    from zest_tpu.p2p.tracker import TrackerClient

    client = TrackerClient("http://tracker.invalid/announce", b"p" * 20)
    base = client.uploaded_total()
    telemetry.counter(
        "zest_seed_bytes_total",
        "Payload bytes served by the seeding tier, by unchoke slot kind",
        ("peer_state",)).inc(4321, peer_state="reciprocal")
    assert client.uploaded_total() == base + 4321
    client.uploaded = 79  # out-of-process base stays additive
    assert client.uploaded_total() == base + 4321 + 79


def test_bench_swarm_tiny_end_to_end():
    """The capacity model at toy scale: M=2 × K=2, fault mix on, shaped
    seeders — swarm-wide ratio, fairness skew, zero corrupt admitted,
    every fault fired."""
    from zest_tpu.bench_scale import bench_swarm

    r = bench_swarm(gb=0.008, m_pullers=2, k_seeders=2, scale=2,
                    chunks_per_xorb=16,
                    fault_spec="upload_corrupt:0.02,seeder_choke_flap:0.1",
                    fault_seed=7)
    assert r["pulls_completed"] == 2
    assert r["corrupt_bytes_admitted"] == 0
    assert r["peer_served_ratio"] is not None
    assert r["peer_served_ratio"] >= 0.5
    assert r["faults_fired"].get("seeder_choke_flap", 0) > 0
    assert r["upload_fairness"]["skew"] is not None
    assert r["pull_latency_s"]["p50"] is not None


def test_token_bucket_refund_restores_tokens():
    bucket = TokenBucket(10_000, capacity=1_000)
    assert bucket.acquire(1_000)   # drain the burst
    bucket.refund(1_000)
    t0 = time.monotonic()
    assert bucket.acquire(1_000)   # refunded: immediate again
    assert time.monotonic() - t0 < 0.05
    bucket.refund(10_000_000)      # clamped at capacity, never above
    assert bucket.tokens <= bucket.capacity


def test_provenance_multi_source_any_quarantined_refuses():
    """One key can carry several unproven contributors; a later
    recording must not displace an earlier peer's attribution, and
    the refusal check is 'ANY source quarantined'."""
    book = ContentProvenance()
    book.record("xx", ("p1", 1))
    book.record("xx", ("p2", 2))
    book.record("xx", ("p1", 1))  # dedup: no growth
    assert book.sources("xx") == (("p1", 1), ("p2", 2))
    assert book.source("xx") == ("p2", 2)  # latest
    h = HealthRegistry(strikes_to_quarantine=1)
    h.record_failure(("p1", 1))
    assert any(h.is_quarantined(s) for s in book.sources("xx"))
