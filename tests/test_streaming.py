"""Streaming landing contracts (ISSUE 8 tentpole).

The ``--device=tpu`` landing flows fetch → decode → verify →
``device_put`` at tensor granularity through a fixed ring of reusable
host staging buffers (models.loader.HostRing), committing in layer
order so the first-token-capable set (embedding + layer 0) is resident
while later layers are still on the wire. These tests pin:

- byte identity of the streamed HBM tree (``params_digest``) and the
  materialized files against the non-streaming path;
- the ring's byte bound under an adversarially tiny budget, and the
  oversized-alone admission (one tensor larger than the whole ring
  lands serially, never deadlocks);
- chaos: ``chunk_corrupt`` through the streaming path still attributes
  corruption at the trust boundary and self-heals from CDN;
- knob-off (``ZEST_LAND_STREAM=0``) restores the PR-1 shard-level
  double buffer's stats schema bit-for-bit;
- the deterministic layer-priority key: registry ordering, per-unit
  priorities/covers from content-addressed metadata, and the coop
  round's plan fingerprint UNCHANGED by priority ordering;
- ring-knob env parsing (malformed values raise, like every landing
  knob).
"""

import threading

import pytest

from fixtures import FixtureHub, FixtureRepo

from zest_tpu.bench_scale import llama_checkpoint_files
from zest_tpu.config import Config
from zest_tpu.models.loader import HostRing, RingClosed, params_digest
from zest_tpu.models.registry import (
    first_layer_names,
    layer_priority,
    order_names,
)
from zest_tpu.transfer.pull import pull_model

FILES = llama_checkpoint_files(0.012, shard_bytes=3 * 1024 * 1024,
                               scale=8)
SHARDS = sorted(n for n in FILES if n.endswith(".safetensors"))


@pytest.fixture(scope="module")
def hub():
    repo = FixtureRepo("acme/streaming", FILES, chunks_per_xorb=8)
    with FixtureHub(repo) as h:
        yield h


def _cfg(hub, root, **kw):
    return Config(hf_home=root / "hf", cache_dir=root / "zest",
                  hf_token="hf_test", endpoint=hub.url, **kw)


def _quiet(*a, **k):
    pass


def _pull(hub, root, **cfg_kw):
    return pull_model(_cfg(hub, root, **cfg_kw), "acme/streaming",
                      device="tpu", no_p2p=True, log=_quiet)


def _assert_files_exact(res):
    for name, data in FILES.items():
        assert (res.snapshot_dir / name).read_bytes() == data, name


# ── Layer-priority ordering (models.registry) ──


def test_layer_priority_groups():
    assert layer_priority("model.embed_tokens.weight") == (0, 0)
    assert layer_priority("transformer.wte.weight") == (0, 0)
    assert layer_priority("model.layers.0.mlp.up_proj.weight") == (1, 0)
    assert layer_priority("model.layers.17.input_layernorm.weight") \
        == (1, 17)
    assert layer_priority("h.3.attn.c_attn.weight") == (1, 3)
    assert layer_priority("blocks.2.norm.weight") == (1, 2)
    assert layer_priority("lm_head.weight") == (2, 0)
    assert layer_priority("model.norm.weight") == (2, 0)
    assert layer_priority("totally.unknown.tensor") == (2, 0)


def test_order_names_stable_and_layered():
    names = ["lm_head.weight", "model.layers.1.a", "model.layers.0.b",
             "model.embed_tokens.weight", "model.layers.0.a",
             "model.norm.weight"]
    out = order_names(names)
    assert out[0] == "model.embed_tokens.weight"
    assert out[1:3] == ["model.layers.0.b", "model.layers.0.a"]  # stable
    assert out[3] == "model.layers.1.a"
    assert out[4:] == ["lm_head.weight", "model.norm.weight"]  # stable


def test_first_layer_names():
    names = ["model.embed_tokens.weight", "model.layers.2.a",
             "model.layers.5.a", "model.norm.weight"]
    # Lowest layer PRESENT (2 — a sharded landing may not start at 0).
    assert first_layer_names(names) == frozenset(
        {"model.embed_tokens.weight", "model.layers.2.a"})
    # No recognizable layer structure: the honest answer is the whole
    # set — first-layer-usable then coincides with the full landing.
    flat = ["alpha.weight", "beta.weight"]
    assert first_layer_names(flat) == frozenset(flat)


def test_unit_priorities_and_covers(hub, tmp_path):
    from zest_tpu.models.direct import (
        tensor_unit_keys,
        unit_layer_priorities,
    )
    from zest_tpu.parallel.plan import collect_units
    from zest_tpu.transfer.bridge import XetBridge
    from zest_tpu.transfer.pod import fetch_file_header

    cfg = _cfg(hub, tmp_path)
    bridge = XetBridge(cfg)
    bridge.authenticate("acme/streaming")
    repo = hub.repos["acme/streaming"]
    rwh = [(repo.reconstructions[repo.files[n].xet_hash],
            fetch_file_header(
                bridge, repo.reconstructions[repo.files[n].xet_hash]))
           for n in SHARDS]
    prio = unit_layer_priorities(rwh)
    all_keys = {k for k, _fi in collect_units([r for r, _h in rwh])}
    # Every unit of every shard got a priority, and they are a pure
    # function of content-addressed metadata: rebuild == original.
    assert set(prio) == all_keys
    assert unit_layer_priorities(rwh) == prio
    # Units serving the embedding (file head) rank first-group.
    best = min(prio.values())
    assert best == (0, 0)
    # Per-tensor unit covers: non-empty, subsets of the shard's units,
    # and the embedding's cover is exactly the (0, 0)-priority units
    # it touches.
    rec0, header0 = rwh[0]
    covers = tensor_unit_keys(rec0, header0)
    shard0_keys = {k for k, _fi in collect_units([rec0])}
    assert set(covers) == set(header0.tensors)
    for name, keys in covers.items():
        assert keys and keys <= shard0_keys, name
    for key in covers["model.embed_tokens.weight"]:
        assert prio[key] == (0, 0)
    bridge.close()


# ── End-to-end: identity + schema ──


def test_streamed_pull_identical_and_first_layer_early(hub, tmp_path):
    on = _pull(hub, tmp_path / "on")
    off = _pull(hub, tmp_path / "off", land_stream=False)
    try:
        # Byte identity both places the bytes can land.
        assert params_digest(on.params) == params_digest(off.params)
        _assert_files_exact(on)
        _assert_files_exact(off)

        # Streaming evidence: ring accounting, the headline stat, and
        # the first-layer stage interval agreeing with it.
        hbm = on.stats["hbm"]
        assert hbm["streamed"] is True
        ring = hbm["ring"]
        assert ring["buffers_allocated"] > 0
        assert ring["peak_bytes"] <= ring["budget_bytes"]
        tfl = on.stats["time_to_first_layer_s"]
        tth = on.stats["time_to_hbm_s"]
        assert 0 < tfl < tth
        assert on.stats["stages"]["first_layer"] == pytest.approx(
            tfl, abs=0.05)

        # Knob-off restores the PR-1 schema bit-for-bit: same stats
        # keys minus the streaming headline, no streamed/ring keys, no
        # first_layer stage.
        assert "time_to_first_layer_s" not in off.stats
        assert set(off.stats) == set(on.stats) - {"time_to_first_layer_s"}
        assert "streamed" not in off.stats["hbm"]
        assert "ring" not in off.stats["hbm"]
        assert "first_layer" not in off.stats["stages"]
        assert off.stats["hbm"]["decode_ahead"] is True
        # The write-behind lane engaged in BOTH modes (stream: ring
        # slots retained by the sink; off: shard-level host dict).
        for res in (on, off):
            assert res.stats["files_pipeline"]["lane_bytes"].get(
                "tensors", 0) > 0
    finally:
        on.params = None
        off.params = None


def test_tiny_ring_budget_bound_holds(hub, tmp_path):
    """Adversarially tiny ring: the landing must still complete, byte-
    identical, with in-flight staging bounded by max(budget, largest
    single READ) — the oversized-alone admission's bound, where a read
    is a tensor run rounded OUT to term boundaries (each boundary term
    decodes in place instead of riding the per-term memo), so the
    largest read can exceed the largest tensor by up to two terms."""
    largest = 512 * 1024  # << several tensors in the fixture
    res = _pull(hub, tmp_path, land_ring_bytes=largest, land_ring_slots=2)
    try:
        ring = res.stats["hbm"]["ring"]
        biggest_tensor = max(
            int(a.nbytes) for a in res.params.values())
        repo = hub.repos["acme/streaming"]
        max_term = max(
            t.unpacked_length
            for n in SHARDS
            for t in repo.reconstructions[
                repo.files[n].xet_hash].terms)
        assert ring["budget_bytes"] == largest
        assert ring["peak_bytes"] <= max(
            largest, biggest_tensor + 2 * max_term)
        assert ring["oversized"] > 0  # the big projections exceeded it
        _assert_files_exact(res)
    finally:
        res.params = None


def test_oversized_alone_never_deadlocks(hub, tmp_path):
    """A ring smaller than EVERY tensor: fully serial admission, still
    terminates with identical bytes (mirrors ByteBudget's rule)."""
    res = _pull(hub, tmp_path, land_ring_bytes=1, land_ring_slots=1)
    try:
        ring = res.stats["hbm"]["ring"]
        assert ring["oversized"] > 0
        assert res.stats["hbm"]["streamed"] is True
        _assert_files_exact(res)
    finally:
        res.params = None


# ── HostRing unit behavior ──


def test_hostring_close_wakes_blocked_acquire():
    ring = HostRing(100, 1)
    slot = ring.acquire(100)
    errors: list = []

    def blocked():
        try:
            ring.acquire(100)
        except RingClosed as exc:
            errors.append(exc)

    t = threading.Thread(target=blocked, daemon=True)
    t.start()
    import time as _time

    _time.sleep(0.15)  # let it stall (counted)
    ring.close()
    t.join(timeout=5)
    assert not t.is_alive()
    assert errors and isinstance(errors[0], RingClosed)
    assert ring.stalls >= 1
    slot.release()


def test_hostring_reuse_and_detach_accounting():
    ring = HostRing(1000, 8)
    a = ring.acquire(400)
    a.release()
    b = ring.acquire(300)  # smallest-fit reuse of the 400-byte buffer
    assert ring.reuses == 1 and ring.allocs == 1
    # Detach surrenders the accounting: a second large acquire fits.
    b.addref()
    b.detach()
    c = ring.acquire(900)
    assert ring.peak_bytes <= 1000 + 400  # detached bytes left the bound
    b.release()
    b.release()
    assert ring.detached == 1
    c.release()
    ring.close()


# ── Chaos: corruption through the streaming path ──


@pytest.mark.chaos
def test_chunk_corrupt_streaming_attributed_and_healed(tmp_path):
    """A peer serving flipped bytes under the STREAMING landing: the
    corruption is attributed at the trust boundary (peer strike), the
    unit heals from CDN, and both the HBM tree and the materialized
    files come out byte-exact — the ring changed the unit of
    buffering, never the trust model."""
    from zest_tpu import faults
    from zest_tpu.transfer.server import BtServer
    from zest_tpu.transfer.swarm import SwarmDownloader

    chaos_files = llama_checkpoint_files(0.003,
                                         shard_bytes=1024 * 1024,
                                         scale=8)
    repo = FixtureRepo("acme/streaming-chaos", chaos_files,
                       chunks_per_xorb=1)
    faults.reset()
    with FixtureHub(repo) as hub:
        def cfg_for(name):
            return Config(hf_home=tmp_path / name / "hf",
                          cache_dir=tmp_path / name / "zest",
                          hf_token="hf_test", endpoint=hub.url)

        seed_cfg = cfg_for("seeder")
        pull_model(seed_cfg, "acme/streaming-chaos", no_p2p=True,
                   log=_quiet)
        server = BtServer(seed_cfg)
        port = server.start()
        try:
            faults.install(f"chunk_corrupt:1.0@127.0.0.1:{port}",
                           seed=1337)
            cfg = cfg_for("leecher")
            swarm = SwarmDownloader(cfg)
            swarm.add_direct_peer("127.0.0.1", port)
            # Capture the pull log: if the streaming landing ever falls
            # back ("direct HBM landing unavailable (...)"), the assert
            # below must show WHY, not die with a bare KeyError.
            log_lines: list[str] = []

            def log_capture(*a, **k):
                log_lines.append(" ".join(str(x) for x in a))

            try:
                result = pull_model(cfg, "acme/streaming-chaos",
                                    swarm=swarm, device="tpu", pod=False,
                                    log=log_capture)
            finally:
                swarm.close()
        finally:
            server.shutdown()
            faults.reset()

    assert result.stats["hbm"].get("streamed") is True, (
        f"streaming landing fell back: hbm={result.stats['hbm']!r} "
        f"log={log_lines!r}")
    for name, data in chaos_files.items():
        assert (result.snapshot_dir / name).read_bytes() == data
    assert result.stats["faults"]["chunk_corrupt"] >= 1
    assert result.stats["swarm"]["corrupt_from_peer"] >= 1
    assert result.stats["fetch"]["bytes"]["cdn"] > 0
    result.params = None


# ── Coop interop: priority ordering leaves the plan untouched ──


def test_coop_fingerprint_unchanged_by_priorities(hub, tmp_path):
    from zest_tpu.cas.hub import HubClient
    from zest_tpu.models.direct import unit_layer_priorities
    from zest_tpu.transfer.bridge import XetBridge
    from zest_tpu.transfer.coop import coop_round
    from zest_tpu.transfer.dcn import DcnServer
    from zest_tpu.transfer.pod import fetch_file_header

    def run_pair(sub, priorities_for):
        """2 in-process hosts, one coop round; returns host 0's stats."""
        bridges, servers, addrs = [], [], {}
        for i in range(2):
            cfg = Config(hf_home=tmp_path / sub / f"h{i}" / "hf",
                         cache_dir=tmp_path / sub / f"h{i}" / "zest",
                         hf_token="hf_test", endpoint=hub.url,
                         dcn_port=0)
            b = XetBridge(cfg)
            b.authenticate("acme/streaming")
            bridges.append(b)
            s = DcnServer(b.cfg, b.cache)
            addrs[i] = ("127.0.0.1", s.start())
            servers.append(s)
        recs = [bridges[0].get_reconstruction(e.xet_hash)
                for e in HubClient(bridges[0].cfg).list_files(
                    "acme/streaming")
                if e.is_xet]
        results: list = [None, None]
        errors: list = []

        def run(i):
            try:
                results[i] = coop_round(
                    bridges[i], recs, i, 2, addrs, server=servers[i],
                    priorities=priorities_for(bridges[i], recs))
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        ts = [threading.Thread(target=run, args=(i,), daemon=True)
              for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        for s in servers:
            s.shutdown()
        for b in bridges:
            b.close()
        assert not errors, errors
        return results

    def with_prio(bridge, recs):
        repo = hub.repos["acme/streaming"]
        rwh = [(repo.reconstructions[repo.files[n].xet_hash],
                fetch_file_header(
                    bridge,
                    repo.reconstructions[repo.files[n].xet_hash]))
               for n in SHARDS]
        return unit_layer_priorities(rwh)

    plain = run_pair("plain", lambda b, r: None)
    ordered = run_pair("ordered", with_prio)
    # The ownership plan — and with it the cross-host agreement every
    # exchange depends on — is byte-identical with ordering on or off.
    fp = {r["plan"]["fingerprint"] for r in plain + ordered}
    assert len(fp) == 1
    for r in ordered:
        assert r["exchange"]["units"] > 0  # the round actually exchanged


# ── Config: ring knobs through the env, uniformly ──


def test_config_ring_env_parsing():
    base = {"HF_HOME": "/tmp/x", "ZEST_CACHE_DIR": "/tmp/y"}
    cfg = Config.load({**base, "ZEST_LAND_STREAM": "1",
                       "ZEST_LAND_RING_BYTES": "8388608",
                       "ZEST_LAND_RING_SLOTS": "7"})
    assert cfg.land_stream is True
    assert cfg.land_ring_bytes == 8 * 1024 * 1024
    assert cfg.land_ring_slots == 7
    off = Config.load({**base, "ZEST_LAND_STREAM": "0"})
    assert off.land_stream is False
    defaults = Config.load(base)
    assert defaults.land_stream is True
    assert defaults.land_ring_bytes == 512 * 1024 * 1024
    assert defaults.land_ring_slots == 64
    # Malformed values raise (like ZEST_COOP_ADDRS), never silently
    # fall back to a default ring.
    with pytest.raises(ValueError):
        Config.load({**base, "ZEST_LAND_RING_BYTES": "256mb"})
    with pytest.raises(ValueError):
        Config.load({**base, "ZEST_LAND_RING_SLOTS": "many"})
    # The rollback knob parses STRICTLY: "false"/"off"/a typo must
    # raise, never silently keep streaming on.
    with pytest.raises(ValueError):
        Config.load({**base, "ZEST_LAND_STREAM": "false"})


def test_stats_watch_landing_line():
    from zest_tpu.cli import _stats_watch_lines

    lines = _stats_watch_lines(
        {"landing": {"first_layer_s": 1.2, "time_to_hbm_s": 6.0,
                     "first_layer_ratio": 0.2, "ring_stalls": 3}},
        {"version": "x"})
    landing = [ln for ln in lines if ln.startswith("landing:")]
    assert landing and "first_layer=1.2s" in landing[0]
    assert "hbm=6.0s" in landing[0]
    assert "20% of hbm" in landing[0]
    assert "ring_stalls=3" in landing[0]
