"""Self-healing control plane (ISSUE 17): the remediation policy
engine over the PR-14 anomaly stream, its bounded/rate-limited/
reversible actions through the existing recovery paths, and the
satellite fixes riding along.

The contract under test: every decision is gated (action mask → token
bucket → dry-run) and recorded (log entry + metric + flight event with
before/after timeline snapshots) whatever the outcome; actions only
ever drive *injected* targets (no telemetry → transfer imports);
``ZEST_REMEDIATE=0`` restores the pure-observer process bit-for-bit
(no subscription, no targets, identical pull stats schema); the tuner
never leaves its rails and never oscillates within one observation
window; and a demotion never creates a strike against a healthy peer.
"""

from __future__ import annotations

import json
import time
from types import SimpleNamespace

import pytest

from zest_tpu import telemetry
from zest_tpu.telemetry import recorder
from zest_tpu.telemetry import remediate
from zest_tpu.telemetry import session as session_mod
from zest_tpu.telemetry import timeline
from zest_tpu.transfer import tenancy

from fixtures import FixtureHub, FixtureRepo


@pytest.fixture(autouse=True)
def clean(monkeypatch):
    # The engine reads ZEST_REMEDIATE_* from the live environment;
    # scrub any ambient settings so every test starts from defaults.
    for name in ("ZEST_REMEDIATE", "ZEST_REMEDIATE_ACTIONS",
                 "ZEST_REMEDIATE_DRY", "ZEST_REMEDIATE_RATE_S",
                 "ZEST_REMEDIATE_BURST", "ZEST_REMEDIATE_PATIENCE",
                 "ZEST_REMEDIATE_BURN_MAX", "ZEST_REMEDIATE_OBSERVE_S",
                 "ZEST_TIMELINE", "ZEST_TELEMETRY"):
        monkeypatch.delenv(name, raising=False)
    telemetry.reset_all()
    tenancy.reset()
    yield
    telemetry.reset_all()
    tenancy.reset()


def _engine() -> remediate.RemediationEngine:
    assert remediate.ensure_started()
    return remediate.ENGINE


def _counts(action: str) -> dict:
    return remediate.payload()["counts"].get(action, {})


# ── Enable gate + pure-observer contract ──


class TestEnableGate:
    def test_default_on(self):
        assert remediate.enabled() is True

    def test_env_off(self, monkeypatch):
        monkeypatch.setenv("ZEST_REMEDIATE", "0")
        assert remediate.enabled() is False
        assert remediate.ensure_started() is False

    def test_timeline_off_implies_off(self, monkeypatch):
        monkeypatch.setenv("ZEST_TIMELINE", "0")
        timeline.reset()
        assert remediate.enabled() is False

    def test_off_register_target_is_noop(self, monkeypatch):
        monkeypatch.setenv("ZEST_REMEDIATE", "0")
        assert remediate.register_target("hedge:x", lambda r: None) \
            is False
        assert remediate.ENGINE is None  # no engine even built

    def test_off_payload_stub(self, monkeypatch):
        monkeypatch.setenv("ZEST_REMEDIATE", "0")
        doc = remediate.payload()
        assert doc["enabled"] is False
        assert doc["counts"] == {} and doc["recent"] == []

    def test_parse_actions_lenient(self):
        assert remediate.parse_actions(None) \
            == frozenset(remediate.ACTIONS)
        assert remediate.parse_actions("all") \
            == frozenset(remediate.ACTIONS)
        assert remediate.parse_actions("hedge, demote") \
            == frozenset({"hedge", "demote"})
        # Unknown names are dropped, never raised, on the engine side.
        assert remediate.parse_actions("hedge,typo") \
            == frozenset({"hedge"})


# ── The decision spine: mask → bucket → dry-run → execute ──


class TestDecisionSpine:
    def test_stall_anomaly_arms_hedge_through_listener(self):
        _engine()
        sess = session_mod.begin("acme/m", "main")
        calls: list[str] = []
        remediate.register_target(
            f"hedge:{sess.id}",
            lambda reason: calls.append(reason) or {"armed": True})
        timeline.STORE.detector._fire(
            timeline.ANOMALY_STALL, session=sess, phase="fetch",
            bytes_done=7)
        assert calls == ["anomaly:stall"]
        assert _counts("hedge") == {"success": 1}
        session_mod.finish(sess, "ok")

    def test_collapse_maps_to_hedge_too(self):
        eng = _engine()
        sess = session_mod.begin("acme/m", "main")
        calls = []
        remediate.register_target(f"hedge:{sess.id}",
                                  lambda reason: calls.append(reason))
        eng.on_anomaly(timeline.ANOMALY_COLLAPSE, sess, {})
        assert calls == ["anomaly:throughput_collapse"]
        session_mod.finish(sess, "ok")

    def test_hedge_without_target_is_silent(self):
        eng = _engine()
        sess = session_mod.begin("acme/m", "main")
        eng.on_anomaly(timeline.ANOMALY_STALL, sess, {})
        # Not fetch-bound: no decision logged at all (not a no_target
        # per stall of an unrelated phase).
        assert remediate.payload()["recent"] == []
        session_mod.finish(sess, "ok")

    def test_token_bucket_rate_limit(self):
        eng = _engine()
        sess = session_mod.begin("acme/m", "main")
        remediate.register_target(f"hedge:{sess.id}", lambda r: {})
        for _ in range(eng.burst + 2):
            eng.on_anomaly(timeline.ANOMALY_STALL, sess, {})
        c = _counts("hedge")
        assert c["success"] == eng.burst
        assert c["rate_limited"] == 2
        session_mod.finish(sess, "ok")

    def test_token_bucket_refills(self):
        b = remediate._TokenBucket(capacity=1, refill_s=10.0)
        t0 = b.last_t
        assert b.take(t0) is True
        assert b.take(t0 + 1.0) is False
        assert b.take(t0 + 10.5) is True  # one token back after refill_s

    def test_action_mask_disables(self, monkeypatch):
        monkeypatch.setenv("ZEST_REMEDIATE_ACTIONS", "strike,shed")
        eng = _engine()
        sess = session_mod.begin("acme/m", "main")
        calls = []
        remediate.register_target(f"hedge:{sess.id}",
                                  lambda r: calls.append(r))
        eng.on_anomaly(timeline.ANOMALY_STALL, sess, {})
        assert calls == []
        assert _counts("hedge") == {"disabled": 1}
        session_mod.finish(sess, "ok")

    def test_dry_run_records_but_does_not_execute(self, monkeypatch):
        monkeypatch.setenv("ZEST_REMEDIATE_DRY", "1")
        eng = _engine()
        assert eng.dry_run is True
        sess = session_mod.begin("acme/m", "main")
        calls = []
        remediate.register_target(f"hedge:{sess.id}",
                                  lambda r: calls.append(r))
        eng.on_anomaly(timeline.ANOMALY_STALL, sess, {})
        assert calls == []
        assert _counts("hedge") == {"dry_run": 1}
        (entry,) = remediate.payload()["recent"]
        assert entry["outcome"] == "dry_run" and entry["dry_run"]
        session_mod.finish(sess, "ok")

    def test_failing_target_records_failed(self):
        eng = _engine()
        sess = session_mod.begin("acme/m", "main")

        def boom(reason):
            raise RuntimeError("target exploded")

        remediate.register_target(f"hedge:{sess.id}", boom)
        eng.on_anomaly(timeline.ANOMALY_STALL, sess, {})  # must not raise
        assert _counts("hedge") == {"failed": 1}
        (entry,) = remediate.payload()["recent"]
        assert "target exploded" in entry["detail"]["error"]
        session_mod.finish(sess, "ok")

    def test_decision_carries_before_after_snapshots(self):
        _engine()
        timeline.STORE._append("fetch.cdn_bps", 5.0, "rate", 1.0)
        sess = session_mod.begin("acme/m", "main")
        remediate.register_target(f"hedge:{sess.id}", lambda r: {})
        timeline.STORE.detector._fire(timeline.ANOMALY_STALL,
                                      session=sess, phase="fetch")
        (entry,) = remediate.payload()["recent"]
        assert entry["before"]["fetch.cdn_bps"] == [[1.0, 5.0]]
        assert "after" in entry
        evs = [e for e in recorder.tail() if e["kind"] == "remediation"]
        assert evs and evs[0]["before"]["fetch.cdn_bps"] == [[1.0, 5.0]]
        # The flight event is JSON-clean end to end.
        json.dumps(evs[0])
        session_mod.finish(sess, "ok")

    def test_unregister_target_is_identity_checked(self):
        eng = _engine()
        a, b = (lambda r: "a"), (lambda r: "b")
        remediate.register_target("hedge:x", a)
        remediate.register_target("hedge:x", b)  # replace semantics
        remediate.unregister_target("hedge:x", a)  # stale unregister
        assert eng._targets.get("hedge:x") is b
        remediate.unregister_target("hedge:x", b)
        assert "hedge:x" not in eng._targets


# ── Straggler strike / abort patience ──


class TestStraggler:
    def test_strike_then_abort_past_patience(self):
        eng = _engine()
        calls = []
        remediate.register_target(
            "collective", lambda cmd, p: calls.append((cmd, p)) or {})
        for _ in range(3):
            eng.on_anomaly(timeline.ANOMALY_STRAGGLER, None,
                           {"partner": 3, "barrier_wait_s": 2.0})
        assert calls[0] == ("strike", 3)
        assert calls[1] == ("abort", 3)   # patience default 2
        assert calls[2] == ("abort", 3)

    def test_collective_registration_resets_patience(self):
        eng = _engine()
        calls = []
        remediate.register_target(
            "collective", lambda cmd, p: calls.append(cmd) or {})
        eng.on_anomaly(timeline.ANOMALY_STRAGGLER, None, {"partner": 1})
        eng.on_anomaly(timeline.ANOMALY_STRAGGLER, None, {"partner": 1})
        assert calls == ["strike", "abort"]
        # A new round registers a fresh target: patience starts over.
        remediate.register_target(
            "collective", lambda cmd, p: calls.append(cmd) or {})
        eng.on_anomaly(timeline.ANOMALY_STRAGGLER, None, {"partner": 1})
        assert calls[-1] == "strike"

    def test_straggler_without_partner_is_silent(self):
        eng = _engine()
        remediate.register_target("collective", lambda cmd, p: {})
        eng.on_anomaly(timeline.ANOMALY_STRAGGLER, None, {})
        assert remediate.payload()["recent"] == []

    def test_collective_abort_flag_drains_to_ladder(self):
        """The wired side: run_collective's injected target sets the
        abort flag the barrier-retry loop checks (exercised end-to-end
        by the MTTR bench; here the target contract)."""
        from zest_tpu.p2p.health import HealthRegistry

        health = HealthRegistry(strikes_to_quarantine=3)
        _engine()
        # Mimic run_collective's registration.
        abort_req: dict = {}
        peers = {2: ("127.0.0.1", 9999)}

        def cmd_fn(cmd, partner):
            if cmd == "strike":
                health.record_failure(peers[partner], kind="straggler")
                return {"cmd": "strike"}
            abort_req["partner"] = partner
            return {"cmd": "abort"}

        remediate.register_target("collective", cmd_fn)
        eng = remediate.ENGINE
        eng.on_anomaly(timeline.ANOMALY_STRAGGLER, None, {"partner": 2})
        assert health.detail()[0]["strike_kinds"] == {"straggler": 1}
        assert not abort_req
        eng.on_anomaly(timeline.ANOMALY_STRAGGLER, None, {"partner": 2})
        assert abort_req == {"partner": 2}


# ── Shed: queue_stuck + SLO burn, and the recovery leg ──


class TestShed:
    def test_skipped_without_burn(self):
        eng = _engine()
        calls = []
        remediate.register_target("shed",
                                  lambda cmd: calls.append(cmd) or {})
        eng.on_anomaly(timeline.ANOMALY_QUEUE, None, {"depth": 9})
        assert calls == []
        (entry,) = remediate.payload()["recent"]
        assert entry["detail"]["cmd"] == "none"

    def test_fires_with_burn(self, monkeypatch):
        eng = _engine()
        monkeypatch.setattr(remediate, "_worst_burn", lambda: 0.5)
        calls = []
        remediate.register_target("shed",
                                  lambda cmd: calls.append(cmd) or {})
        eng.on_anomaly(timeline.ANOMALY_QUEUE, None, {"depth": 9})
        assert calls == ["shed"]
        assert remediate.payload()["shedding"] is True

    def test_recovery_is_ungated(self, monkeypatch):
        eng = _engine()
        monkeypatch.setattr(remediate, "_worst_burn", lambda: 0.5)
        calls = []
        remediate.register_target("shed",
                                  lambda cmd: calls.append(cmd) or {})
        eng.on_anomaly(timeline.ANOMALY_QUEUE, None, {})
        # Exhaust the shed bucket entirely — recovery must still run.
        b = eng._bucket("shed")
        while b.take(time.monotonic()):
            pass
        monkeypatch.setattr(remediate, "_worst_burn", lambda: 0.0)
        eng._maybe_recover_shed()
        assert calls == ["shed", "recover"]
        assert remediate.payload()["shedding"] is False

    def test_recovery_waits_for_half_burn(self, monkeypatch):
        eng = _engine()
        monkeypatch.setattr(remediate, "_worst_burn", lambda: 0.5)
        calls = []
        remediate.register_target("shed",
                                  lambda cmd: calls.append(cmd) or {})
        eng.on_anomaly(timeline.ANOMALY_QUEUE, None, {})
        monkeypatch.setattr(remediate, "_worst_burn",
                            lambda: eng.burn_max * 0.75)
        eng._maybe_recover_shed()  # above burn_max/2: still shedding
        assert calls == ["shed"]
        assert remediate.payload()["shedding"] is True

    def test_admission_controller_shed_evicts_lowest_deficit(self):
        import threading

        ctrl = tenancy.AdmissionController(max_pulls=1, max_queue=8)
        ctrl.acquire("a")  # holds the only slot
        errors: dict[str, BaseException] = {}

        def queued(tenant):
            try:
                ctrl.acquire(tenant)
            except BaseException as exc:  # noqa: BLE001
                errors[tenant] = exc

        t = threading.Thread(target=queued, args=("b",))
        t.start()
        deadline = time.monotonic() + 5
        while ctrl.summary()["queued"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        out = ctrl.shed()
        t.join(timeout=5)
        assert out["tenant"] == "b" and out["shed"] == 1
        assert isinstance(errors["b"], tenancy.AdmissionRejected)
        assert errors["b"].retry_after_s >= 1.0
        s = ctrl.summary()
        assert s["shedding"] is True and s["shed_total"] == 1
        assert s["queued"] == 0

    def test_shedding_rejects_new_queuers_until_recover(self):
        ctrl = tenancy.AdmissionController(max_pulls=1, max_queue=8)
        ctrl.acquire("a")
        ctrl.shed()
        with pytest.raises(tenancy.AdmissionRejected):
            ctrl.acquire("c")
        rejected, retry = ctrl.probe_reject()
        assert rejected and retry >= 1.0
        ctrl.recover()
        assert ctrl.summary()["shedding"] is False
        ok, _ = ctrl.probe_reject()
        assert ok is False  # back to "would queue, not rejected"

    def test_admitted_sessions_survive_shed(self):
        ctrl = tenancy.AdmissionController(max_pulls=2, max_queue=8)
        ctrl.acquire("a")
        ctrl.acquire("b")
        ctrl.shed()
        assert ctrl.summary()["active"] == 2  # never touched
        ctrl.release()
        ctrl.release()


# ── Demote: the proactive seeder scan ──


def _peer_row(addr="10.0.0.1:7000", strikes=0, kinds=None, served=0.0,
              quarantined_for=0.0):
    return {"peer": addr, "strikes": strikes,
            "strike_kinds": kinds or {}, "successes": 0,
            "failures": strikes, "corruptions": 0, "quarantines": 0,
            "quarantined_for_s": quarantined_for,
            "served_bytes_recent": served}


class TestDemote:
    def _wire(self, rows, budget=3):
        eng = _engine()
        demoted = []
        remediate.register_target(
            "peer_health",
            lambda: {"rows": rows, "strike_budget": budget})
        remediate.register_target(
            "demote", lambda addr: demoted.append(addr) or
            {"window_s": 15.0})
        return eng, demoted

    def test_near_budget_strikes_demote(self):
        eng, demoted = self._wire([_peer_row(strikes=2)], budget=3)
        eng._scan_seeders(now=100.0)
        assert demoted == [("10.0.0.1", 7000)]
        assert _counts("demote") == {"success": 1}

    def test_bad_kind_strikes_demote(self):
        rows = [_peer_row(strikes=2, kinds={"corrupt": 2})]
        eng, demoted = self._wire(rows, budget=9)  # nowhere near budget
        eng._scan_seeders(now=100.0)
        assert demoted == [("10.0.0.1", 7000)]

    def test_served_collapse_demotes_with_a_strike(self):
        eng, demoted = self._wire(
            [_peer_row(strikes=1, served=8 << 20)], budget=9)
        eng._scan_seeders(now=100.0)       # records the 8 MiB peak
        assert demoted == []
        eng._peers["10.0.0.1:7000"]["demoted_t"] = None
        row = _peer_row(strikes=1, served=100.0)  # collapsed vs peak
        remediate.register_target(
            "peer_health",
            lambda: {"rows": [row], "strike_budget": 9})
        eng._scan_seeders(now=200.0)
        assert demoted == [("10.0.0.1", 7000)]

    def test_healthy_peer_never_demoted(self):
        eng, demoted = self._wire(
            [_peer_row(strikes=0, served=8 << 20)], budget=3)
        eng._scan_seeders(now=100.0)
        assert demoted == []
        assert remediate.payload()["recent"] == []

    def test_quarantined_peer_skipped(self):
        eng, demoted = self._wire(
            [_peer_row(strikes=2, quarantined_for=9.0)], budget=3)
        eng._scan_seeders(now=100.0)
        assert demoted == []

    def test_demote_cooldown_per_peer(self):
        eng, demoted = self._wire([_peer_row(strikes=2)], budget=3)
        eng._scan_seeders(now=100.0)
        eng._scan_seeders(now=100.0 + eng.observe_s / 2)
        assert len(demoted) == 1  # within the observe window
        eng._scan_seeders(now=101.0 + eng.observe_s)
        assert len(demoted) == 2

    def test_health_demote_never_creates_a_strike(self):
        """The failure-semantics rule (SCALING.md §15): demotion
        quarantines WITHOUT touching strikes/strike_kinds/quarantines,
        and the peer re-enters through the normal probation path."""
        from zest_tpu.p2p.health import HealthRegistry

        clock = [100.0]
        h = HealthRegistry(strikes_to_quarantine=3,
                           time_fn=lambda: clock[0])
        events = []
        h.subscribe(lambda ev, addr: events.append((ev, addr)))
        addr = ("10.0.0.9", 7000)
        h.record_failure(addr, kind="seed_stall")
        before = h.detail()[0]
        window = h.demote(addr)
        assert window > 0
        after = h.detail()[0]
        assert after["strikes"] == before["strikes"] == 1
        assert after["strike_kinds"] == {"seed_stall": 1}
        assert after["quarantines"] == 0  # a demotion is NOT a breaker trip
        assert h.is_quarantined(addr) is True
        assert ("demoted", addr) in events
        assert h.summary()["demotions"] == 1
        # Re-entry through probation at expiry, record intact.
        clock[0] += window + 1
        assert h.is_quarantined(addr) is False


# ── The ring-knob auto-tuner ──


class TestTuner:
    def _stall(self, v):
        timeline.STORE._append("ring.stalls", float(v), "gauge",
                               time.monotonic())

    def test_up_nudge_on_stall_growth(self):
        eng = _engine()
        base = 64 << 20
        remediate.set_knob_base("land_ring_bytes", base)
        assert remediate.knob_override("land_ring_bytes") is None
        self._stall(1)
        eng._tune_ring(timeline.STORE, now=10.0)   # primes last sample
        self._stall(3)
        eng._tune_ring(timeline.STORE, now=20.0)
        assert remediate.knob_override("land_ring_bytes") == base * 2
        assert _counts("tune") == {"success": 1}

    def test_rails_cap_at_8x_base(self):
        eng = _engine()
        base = 1 << 20
        remediate.set_knob_base("land_ring_bytes", base)
        now, v = 10.0, 0
        for i in range(12):
            v += 1
            self._stall(v)
            now += eng.observe_s + 1
            eng._tune_ring(timeline.STORE, now=now)
        assert remediate.knob_override("land_ring_bytes") == base * 8
        assert eng._knobs["land_ring_bytes"]["max"] == base * 8

    def test_oscillation_damping_one_direction_per_window(self):
        """Satellite: an up-nudge must not be followed by a down-nudge
        within the same observation window, however quiet the series
        goes."""
        eng = _engine()
        base = 64 << 20
        remediate.set_knob_base("land_ring_bytes", base)
        self._stall(1)
        eng._tune_ring(timeline.STORE, now=10.0)
        self._stall(5)
        eng._tune_ring(timeline.STORE, now=11.0)   # up ×2
        assert remediate.knob_override("land_ring_bytes") == base * 2
        self._stall(5)                              # quiet now
        eng._tune_ring(timeline.STORE, now=11.5)
        eng._tune_ring(timeline.STORE, now=11.0 + eng.observe_s - 0.5)
        assert remediate.knob_override("land_ring_bytes") == base * 2

    def test_down_nudge_after_quiet_window(self):
        eng = _engine()
        base = 64 << 20
        remediate.set_knob_base("land_ring_bytes", base)
        self._stall(1)
        eng._tune_ring(timeline.STORE, now=10.0)
        self._stall(5)
        eng._tune_ring(timeline.STORE, now=11.0)   # up ×2
        self._stall(5)                              # quiet
        eng._tune_ring(timeline.STORE, now=12.0 + eng.observe_s)
        assert remediate.knob_override("land_ring_bytes") is None  # back at base

    def test_up_nudges_respect_their_own_window(self):
        eng = _engine()
        base = 64 << 20
        remediate.set_knob_base("land_ring_bytes", base)
        self._stall(1)
        eng._tune_ring(timeline.STORE, now=10.0)
        self._stall(2)
        eng._tune_ring(timeline.STORE, now=11.0)   # ×2
        self._stall(3)
        eng._tune_ring(timeline.STORE, now=12.0)   # within window: no-op
        assert remediate.knob_override("land_ring_bytes") == base * 2

    def test_never_tunes_without_a_base(self):
        eng = _engine()
        self._stall(1)
        eng._tune_ring(timeline.STORE, now=10.0)
        self._stall(9)
        eng._tune_ring(timeline.STORE, now=20.0)
        assert remediate.payload()["knobs"] == {}
        assert _counts("tune") == {}


# ── Satellite 1: evidence-armed hedges share the deadline counters ──


class TestHedgeAccounting:
    def _bridge(self, tmp_path, monkeypatch):
        from zest_tpu.config import Config
        from zest_tpu.transfer import bridge as bridge_mod
        from zest_tpu.transfer.bridge import XetBridge

        monkeypatch.setattr(bridge_mod, "_HEDGE_EVIDENCE_WAIT_S", 0.05)
        cfg = Config(hf_home=tmp_path / "hf",
                     cache_dir=tmp_path / "zest")
        br = XetBridge(cfg)
        br.cas = object()  # authenticated enough for the hedge path
        term = SimpleNamespace(xorb_hash=b"\x00" * 32,
                               range=SimpleNamespace(start=0, end=4))
        fi = SimpleNamespace(range=SimpleNamespace(start=0, end=4))
        return br, term, fi

    def test_evidence_hedge_win_bumps_shared_counters(self, tmp_path,
                                                      monkeypatch):
        br, term, fi = self._bridge(tmp_path, monkeypatch)
        br.swarm = SimpleNamespace(
            try_peer_download=lambda *a, **k: time.sleep(0.5))
        sentinel = object()
        monkeypatch.setattr(
            br, "_cdn_fetch_for_term",
            lambda *a, **k: sentinel, raising=False)
        out = br.arm_hedge("anomaly:stall")
        assert out == {"armed": True, "already": False,
                       "reason": "anomaly:stall"}
        assert br.arm_hedge()["already"] is True
        try:
            got = br._peer_tier(term, None, fi, "00" * 32)
        finally:
            br.close()
        assert got is sentinel
        assert br.stats.hedges == 1
        assert br.stats.hedges_won == 1
        assert br.stats.hedges_lost == 0
        # The regression: these flow into stats.fetch.resilience.
        res = br.stats.summary()["resilience"]
        assert res["hedges"] == 1 and res["hedges_won"] == 1

    def test_evidence_hedge_lost_waits_peer_out(self, tmp_path,
                                                monkeypatch):
        br, term, fi = self._bridge(tmp_path, monkeypatch)
        blob = object()
        br.swarm = SimpleNamespace(
            try_peer_download=lambda *a, **k: time.sleep(0.2) or blob)

        def cdn_fail(*a, **k):
            raise OSError("cdn down")

        monkeypatch.setattr(br, "_cdn_fetch_for_term", cdn_fail,
                            raising=False)
        br.arm_hedge()
        try:
            got = br._peer_tier(term, None, fi, "00" * 32)
        finally:
            br.close()
        assert got is blob
        assert br.stats.hedges == 1
        assert br.stats.hedges_lost == 1
        assert br.stats.hedges_won == 0

    def test_unarmed_without_deadline_never_hedges(self, tmp_path,
                                                   monkeypatch):
        br, term, fi = self._bridge(tmp_path, monkeypatch)
        blob = object()
        br.swarm = SimpleNamespace(
            try_peer_download=lambda *a, **k: blob)
        try:
            got = br._peer_tier(term, None, fi, "00" * 32)
        finally:
            br.close()
        assert got is blob
        assert br.stats.hedges == 0


# ── Satellite 2: session eviction clears detector episode state ──


class TestEpisodeEviction:
    def test_finish_drops_detector_row(self):
        timeline.ensure_started()
        det = timeline.STORE.detector
        sess = session_mod.begin("acme/m", "main")
        det.observe_session(
            SimpleNamespace(id=sess.id, phase="fetch", _fetch=None),
            now=1.0)
        assert sess.id in det._sessions
        session_mod.finish(sess, "ok")
        assert sess.id not in det._sessions

    def test_one_stall_firing_per_distinct_session(self):
        """Two sessions that each stall each get their own firing —
        the first session's terminal eviction must not leave an
        armed-off episode row suppressing the second's."""
        _engine()
        det = timeline.STORE.detector
        fired = []
        timeline.add_anomaly_listener(
            lambda kind, sess, fields: fired.append(
                (kind, getattr(sess, "id", None))))
        for _ in range(2):
            sess = session_mod.begin("acme/m", "main")
            det._fire(timeline.ANOMALY_STALL, session=sess)
            det._sessions.setdefault(
                sess.id, {"fired": set()})["fired"] = {
                    timeline.ANOMALY_STALL}
            session_mod.finish(sess, "ok")
            assert sess.id not in det._sessions
        kinds = [k for k, _sid in fired if k == timeline.ANOMALY_STALL]
        assert len(kinds) == 2
        assert len({sid for _k, sid in fired}) == 2


# ── Config mirror + strict action mask ──


class TestConfig:
    def _load(self, **env):
        from zest_tpu.config import Config

        base = {"HF_HOME": "/tmp/hf", "ZEST_CACHE_DIR": "/tmp/zc"}
        base.update(env)
        return Config.load(base)

    def test_defaults(self):
        cfg = self._load()
        assert cfg.remediate_enabled is True
        assert cfg.remediate_actions is None
        assert cfg.remediate_dry_run is False
        assert cfg.remediate_rate_s == 10.0
        assert cfg.remediate_burst == 3

    def test_mirrors_env(self):
        cfg = self._load(ZEST_REMEDIATE="0",
                         ZEST_REMEDIATE_ACTIONS="hedge,demote",
                         ZEST_REMEDIATE_DRY="1",
                         ZEST_REMEDIATE_RATE_S="2.5",
                         ZEST_REMEDIATE_BURST="7")
        assert cfg.remediate_enabled is False
        assert cfg.remediate_actions == ("hedge", "demote")
        assert cfg.remediate_dry_run is True
        assert cfg.remediate_rate_s == 2.5
        assert cfg.remediate_burst == 7

    def test_unknown_action_raises(self):
        with pytest.raises(ValueError, match="typo"):
            self._load(ZEST_REMEDIATE_ACTIONS="hedge,typo")

    def test_all_is_every_action(self):
        assert self._load(
            ZEST_REMEDIATE_ACTIONS="all").remediate_actions is None


# ── Surfaces: /v1/remediations + zest heal ──


@pytest.fixture
def api(tmp_config, monkeypatch):
    from zest_tpu.api.http_api import HttpApi

    requests = pytest.importorskip("requests")
    monkeypatch.setenv(timeline.ENV_HZ, "0.02")
    timeline.reset()
    tmp_config.http_port = 0
    a = HttpApi(tmp_config)
    port = a.start()
    yield a, requests, f"http://127.0.0.1:{port}"
    a.close()


class TestSurfaces:
    def test_http_remediations_payload(self, api):
        _a, requests, base = api
        _engine()
        sess = session_mod.begin("acme/m", "main")
        remediate.register_target(f"hedge:{sess.id}", lambda r: {})
        remediate.ENGINE.on_anomaly(timeline.ANOMALY_STALL, sess, {})
        doc = requests.get(f"{base}/v1/remediations", timeout=5).json()
        assert doc["enabled"] is True
        assert doc["counts"]["hedge"]["success"] == 1
        assert doc["recent"][-1]["action"] == "hedge"
        assert f"hedge:{sess.id}" in doc["targets"]
        session_mod.finish(sess, "ok")

    def test_http_dry_run_toggle(self, api):
        _a, requests, base = api
        _engine()
        r = requests.post(f"{base}/v1/remediations",
                          json={"dry_run": True}, timeout=5)
        assert r.json() == {"dry_run": True}
        assert remediate.ENGINE.dry_run is True
        r = requests.post(f"{base}/v1/remediations",
                          json={"dry_run": False}, timeout=5)
        assert r.json() == {"dry_run": False}
        bad = requests.post(f"{base}/v1/remediations",
                            data=b"not json", timeout=5)
        assert bad.status_code == 400

    def test_heal_lines_render(self):
        from zest_tpu.cli import _heal_lines

        doc = {"enabled": True, "dry_run": False,
               "actions": ["demote", "hedge"], "rate_s": 10.0,
               "burst": 3, "shedding": True,
               "knobs": {"land_ring_bytes": {
                   "base": 64, "value": 128, "min": 64, "max": 512}},
               "counts": {"hedge": {"success": 2, "rate_limited": 1}},
               "recent": [{"t": 1700000000.0, "action": "hedge",
                           "outcome": "success",
                           "reason": "stall in phase fetch",
                           "session": "p0001-aa"}]}
        frame = "\n".join(_heal_lines(doc))
        assert "LOAD SHEDDING ACTIVE" in frame
        assert "knob land_ring_bytes: 128 (base 64" in frame
        assert "success=2" in frame and "rate_limited=1" in frame
        assert "session=p0001-aa" in frame

    def test_heal_lines_disabled(self):
        from zest_tpu.cli import _heal_lines

        (line,) = _heal_lines({"enabled": False})
        assert "pure observer" in line

    def test_cmd_heal(self, api, monkeypatch, capsys):
        from zest_tpu import cli

        _a, _requests, base = api
        monkeypatch.setenv("ZEST_HTTP_PORT", base.rsplit(":", 1)[1])
        _engine()
        assert cli.main(["heal"]) == 0
        out = capsys.readouterr().out
        assert "self-healing: live" in out
        assert cli.main(["heal", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["enabled"] is True
        assert cli.main(["heal", "--dry-run", "on"]) == 0
        assert remediate.ENGINE.dry_run is True
        assert cli.main(["heal", "--dry-run", "off"]) == 0
        assert remediate.ENGINE.dry_run is False


# ── Knob-off identity: ZEST_REMEDIATE=0 is a pure observer ──


class TestKnobOffIdentity:
    def test_pull_stats_schema_identical(self, tmp_path, monkeypatch):
        from zest_tpu.config import Config
        from zest_tpu.transfer.pull import pull_model

        files = {"config.json": b'{"model_type": "heal"}',
                 "model.safetensors": bytes(range(256)) * 400}
        repo = FixtureRepo("acme/heal-model", files, chunks_per_xorb=3)

        def cfg(hub, root):
            return Config(hf_home=root / "hf", cache_dir=root / "zest",
                          hf_token="hf_test", endpoint=hub.url)

        with FixtureHub(repo) as hub:
            on = pull_model(cfg(hub, tmp_path / "on"),
                            "acme/heal-model", no_p2p=True,
                            log=lambda *a, **k: None)
            assert remediate.ENGINE is not None  # pull started it
            telemetry.reset_all()
            tenancy.reset()
            monkeypatch.setenv("ZEST_REMEDIATE", "0")
            off = pull_model(cfg(hub, tmp_path / "off"),
                             "acme/heal-model", no_p2p=True,
                             log=lambda *a, **k: None)
            assert remediate.ENGINE is None   # never built
            assert sorted(on.stats) == sorted(off.stats)
            for name in files:
                assert (on.snapshot_dir / name).read_bytes() \
                    == (off.snapshot_dir / name).read_bytes()

    def test_reset_tears_everything_down(self):
        _engine()
        remediate.register_target("hedge:x", lambda r: {})
        telemetry.reset_all()
        assert remediate.ENGINE is None
        assert timeline._anomaly_listeners == []
        assert timeline._tick_listeners == []
