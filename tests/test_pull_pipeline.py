"""Pipelined-pull contracts: bounded-memory file pipeline, stage-clock
overlap accounting, interrupt/resume idempotence, and the CPU guard
keeping the concurrency knobs deadlock-free for the fast suite.

The tentpole under test (ISSUE 1): `files` reassembly runs on a worker
pool bounded by a byte budget, overlapping the direct HBM landing —
bytes must stay identical to the sequential path, in-flight memory must
respect the budget, and a mid-pull failure must leave a resumable
snapshot (the ``_is_complete`` contract).
"""

import threading
import time

import numpy as np
import pytest

from zest_tpu.bench_scale import llama_checkpoint_files
from zest_tpu.config import Config
from zest_tpu.transfer.pull import (
    ByteBudget,
    StageClock,
    pull_model,
)

from fixtures import FixtureHub, FixtureRepo

# Multi-shard llama-shaped repo (~15 MB over ~4 shards): small enough
# for the fast suite, sharded enough that the file pipeline and the
# landing's decode-ahead both actually pipeline.
FILES = llama_checkpoint_files(0.012, shard_bytes=3 * 1024 * 1024,
                               scale=8)
SHARDS = sorted(n for n in FILES if n.endswith(".safetensors"))


@pytest.fixture(scope="module")
def hub():
    repo = FixtureRepo("acme/pipelined", FILES, chunks_per_xorb=8)
    with FixtureHub(repo) as h:
        yield h


def _cfg(hub, root, **kw):
    return Config(hf_home=root / "hf", cache_dir=root / "zest",
                  hf_token="hf_test", endpoint=hub.url, **kw)


# ── ByteBudget ──


def test_byte_budget_blocks_at_cap_and_tracks_peak():
    budget = ByteBudget(100)
    budget.acquire(60)
    budget.acquire(40)  # exactly at cap
    state = {"acquired": False}

    def blocked():
        budget.acquire(10)
        state["acquired"] = True
        budget.release(10)

    t = threading.Thread(target=blocked)
    t.start()
    time.sleep(0.05)
    assert not state["acquired"], "acquire must block past the budget"
    budget.release(60)
    budget.release(40)
    t.join(timeout=5)
    assert state["acquired"]
    assert budget.peak_bytes == 100


def test_byte_budget_admits_oversized_item_alone():
    budget = ByteBudget(10)
    # An item larger than the whole budget must not deadlock: it is
    # admitted when nothing else is in flight, and runs alone.
    budget.acquire(50)
    state = {"acquired": False}

    def second():
        budget.acquire(5)
        state["acquired"] = True

    t = threading.Thread(target=second)
    t.start()
    time.sleep(0.05)
    assert not state["acquired"], "oversized item must run alone"
    budget.release(50)
    t.join(timeout=5)
    assert state["acquired"]


# ── StageClock: busy vs wall vs span ──


def test_stage_clock_busy_exceeds_wall_under_concurrency():
    clock = StageClock()
    barrier = threading.Barrier(2)

    def worker():
        with clock("files"):
            barrier.wait(timeout=5)
            time.sleep(0.08)

    threads = [threading.Thread(target=worker) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = clock.summary()["files"]
    busy = clock.busy_summary()["files"]
    # Two workers inside the stage simultaneously: busy ~= 2x wall.
    assert busy >= wall * 1.5
    # summary() rounds to 4 decimals; span() is exact.
    assert clock.span("files") == pytest.approx(wall, abs=1e-3)


def test_stage_clock_span_unions_disjoint_stages():
    clock = StageClock()
    with clock("a"):
        time.sleep(0.03)
    with clock("b"):
        time.sleep(0.03)
    s = clock.summary()
    combined = clock.span("a", "b")
    # Disjoint stages: the union span equals the sum of the walls.
    assert combined == pytest.approx(s["a"] + s["b"], abs=5e-3)


def test_stage_clock_gbps_and_ensure():
    clock = StageClock()
    clock.ensure("files")
    assert clock.summary()["files"] == 0.0
    with clock("hbm_commit"):
        time.sleep(0.02)
    clock.note_bytes("hbm_commit", 10_000_000)
    gbps = clock.gbps_summary()
    assert "hbm_commit" in gbps and gbps["hbm_commit"] > 0
    assert "files" not in gbps  # no bytes noted, no rate invented


# ── The pipeline itself ──


def test_pipelined_bytes_identical_to_sequential(hub, tmp_path):
    seq = pull_model(
        _cfg(hub, tmp_path / "seq", pull_pipeline_width=1),
        "acme/pipelined", no_p2p=True)
    par = pull_model(
        _cfg(hub, tmp_path / "par", pull_pipeline_width=4),
        "acme/pipelined", no_p2p=True)
    for name, data in FILES.items():
        a = (seq.snapshot_dir / name).read_bytes()
        b = (par.snapshot_dir / name).read_bytes()
        assert a == data and b == data, f"{name} corrupt"
    assert par.stats["files_downloaded"] == len(FILES)
    assert par.stats["files_pipeline"]["width"] == 4


def test_inflight_bytes_stay_under_budget(hub, tmp_path):
    # Budget sized to the largest shard: wide pipeline, but only one
    # shard's bytes may be in flight at a time — the acceptance bound.
    budget = max(len(b) for b in FILES.values()) + 1024
    res = pull_model(
        _cfg(hub, tmp_path, pull_pipeline_width=4,
             pull_inflight_bytes=budget),
        "acme/pipelined", no_p2p=True)
    pipe = res.stats["files_pipeline"]
    assert pipe["budget_bytes"] == budget
    assert 0 < pipe["inflight_peak_bytes"] <= budget
    for name, data in FILES.items():
        assert (res.snapshot_dir / name).read_bytes() == data


def test_tiny_budget_serializes_but_never_deadlocks(hub, tmp_path):
    # Every file is "oversized" for a 1-byte budget: the pipeline must
    # degrade to one-file-at-a-time, not deadlock the suite.
    res = pull_model(
        _cfg(hub, tmp_path, pull_pipeline_width=4,
             pull_inflight_bytes=1),
        "acme/pipelined", no_p2p=True)
    assert res.stats["files_downloaded"] == len(FILES)
    # Oversized admissions run alone: peak is one file, not a pile-up.
    assert (res.stats["files_pipeline"]["inflight_peak_bytes"]
            <= max(len(b) for b in FILES.values()))


def test_mid_pull_failure_resumes_idempotently(hub, tmp_path, monkeypatch):
    """First error cancels the pipeline; completed files survive as
    complete (atomic rename), the victim is absent, and a re-pull
    resumes via ``_is_complete`` — downloading only what's missing."""
    import zest_tpu.transfer.pull as pull_mod

    victim = SHARDS[-1]
    orig = pull_mod._pull_xet_file

    def sabotaged(bridge, par, hub_, cfg, repo_id, revision, entry, dest,
                  log, **kw):
        if entry.path == victim:
            raise RuntimeError("injected mid-pull failure")
        return orig(bridge, par, hub_, cfg, repo_id, revision, entry,
                    dest, log, **kw)

    monkeypatch.setattr(pull_mod, "_pull_xet_file", sabotaged)
    cfg = _cfg(hub, tmp_path, pull_pipeline_width=2)
    with pytest.raises(RuntimeError, match="injected mid-pull failure"):
        pull_model(cfg, "acme/pipelined", no_p2p=True)

    snap_root = cfg.model_cache_dir("acme/pipelined") / "snapshots"
    snap = next(snap_root.iterdir())
    assert not (snap / victim).exists(), "failed file must not be partial"
    # No half-written tmp litter survives the cancellation.
    assert not list(snap.glob(".tmp-*"))
    done_before = {p.name for p in snap.iterdir()}
    for name in done_before:
        assert (snap / name).read_bytes() == FILES[name]

    monkeypatch.setattr(pull_mod, "_pull_xet_file", orig)
    res = pull_model(cfg, "acme/pipelined", no_p2p=True)
    assert res.stats["files_skipped"] == len(done_before)
    assert res.stats["files_downloaded"] == len(FILES) - len(done_before)
    for name, data in FILES.items():
        assert (res.snapshot_dir / name).read_bytes() == data


def test_prepared_budget_holder_cannot_deadlock_blocked_workers():
    """Regression: a write-behind job acquires budget at enqueue time.
    If it shared the worker pool, it could queue behind workers blocked
    in acquire() on the very bytes it holds — a deadlock. The dedicated
    writer thread guarantees the budget holder always runs."""
    from types import SimpleNamespace

    from zest_tpu.transfer.pull import _FilePipeline

    clock = StageClock()
    release_prepared = threading.Event()

    def slow_prepared(entry):
        release_prepared.wait(timeout=5)
        return "downloaded"

    pipe = _FilePipeline(1, 100, clock, work=lambda e: "downloaded")
    # Prepared B holds 60 of 100 budget and occupies the writer...
    pipe.submit_prepared(SimpleNamespace(path="b", size=60), slow_prepared)
    # ...while plain A (80 bytes) blocks its only worker in acquire().
    pipe.submit(SimpleNamespace(path="a", size=80))
    time.sleep(0.1)
    release_prepared.set()
    joiner = threading.Thread(target=pipe.join, daemon=True)
    joiner.start()
    joiner.join(timeout=10)
    assert not joiner.is_alive(), "pipeline deadlocked"
    assert pipe.downloaded == 2


def test_abort_releases_budget_of_cancelled_prepared_jobs():
    """A queued write-behind job holds pre-acquired budget bytes; if
    abort() cancels it before it runs, those bytes must be released —
    a leak would park future acquirers forever."""
    from types import SimpleNamespace

    from zest_tpu.transfer.pull import _FilePipeline

    clock = StageClock()
    gate = threading.Event()
    pipe = _FilePipeline(1, 100, clock, work=lambda e: "downloaded")
    # First prepared job occupies the single writer thread...
    pipe.submit_prepared(SimpleNamespace(path="a", size=10),
                         lambda e: gate.wait(timeout=5) or "downloaded")
    # ...second one queues behind it, holding 50 budget bytes.
    pipe.submit_prepared(SimpleNamespace(path="b", size=50),
                         lambda e: "downloaded")
    # Abort while `a` is mid-write: `b` is still queued, so abort
    # CANCELS it — its 50 bytes must be released by the done-callback.
    threading.Timer(0.2, gate.set).start()
    pipe.abort()
    assert pipe.budget._inflight == 0, "cancelled prepared job leaked budget"


# ── Overlap with the HBM landing (device="tpu") ──


def test_tpu_pull_overlap_schema_and_decode_ahead(hub, tmp_path):
    res = pull_model(_cfg(hub, tmp_path), "acme/pipelined",
                     no_p2p=True, device="tpu")
    stats = res.stats
    assert stats["hbm"]["direct"] is True
    # Multi-shard landing: the decode-ahead staging thread engaged.
    assert stats["hbm"]["decode_ahead"] is True
    assert stats["time_to_hbm_s"] <= stats["elapsed_s"] + 0.05
    # Overlap accounting present and coherent: busy >= wall per stage,
    # and the files∪hbm span never exceeds the whole pull.
    assert stats["files_hbm_span_s"] <= stats["elapsed_s"] + 0.05
    for stage, wall in stats["stages"].items():
        assert stats["stages_busy"][stage] >= wall - 0.05
    assert stats["stages_gbps"].get("files", 0) >= 0


def test_decode_ahead_lands_identical_params(hub, tmp_path):
    serial = pull_model(
        _cfg(hub, tmp_path / "s", land_decode_ahead=0),
        "acme/pipelined", no_p2p=True, device="tpu")
    ahead = pull_model(
        _cfg(hub, tmp_path / "a", land_decode_ahead=1),
        "acme/pipelined", no_p2p=True, device="tpu")
    assert serial.stats["hbm"]["decode_ahead"] is False
    assert ahead.stats["hbm"]["decode_ahead"] is True
    assert set(serial.params) == set(ahead.params)
    for name in serial.params:
        # Bitwise compare: random bf16 fixtures contain NaN patterns,
        # and NaN != NaN would flag identical bytes as a mismatch.
        a = np.asarray(serial.params[name]).view(np.uint16)
        b = np.asarray(ahead.params[name]).view(np.uint16)
        np.testing.assert_array_equal(a, b, err_msg=name)


# ── CI guard: the knobs must default sanely on CPU ──


def test_pipeline_knobs_default_sane_for_cpu_suite():
    """Tier-1 deadlock guard: defaults must yield a live pipeline
    (width >= 1, positive byte budget, at least one decode worker) so
    the fast CPU suite can never stall on a zero-width pool or a
    zero-byte budget."""
    from zest_tpu.models.direct import resolve_decode_workers

    cfg = Config.load({})
    assert cfg.pull_pipeline_width >= 1
    assert cfg.pull_inflight_bytes >= 64 << 20
    assert cfg.land_decode_ahead >= 0
    assert resolve_decode_workers(cfg.decode_workers) >= 1
    # Env overrides cannot configure a dead pipeline either.
    floor = Config.load({"ZEST_PULL_WIDTH": "0",
                         "ZEST_PULL_INFLIGHT": "0"})
    assert floor.pull_pipeline_width >= 1
    assert floor.pull_inflight_bytes >= 1
