"""The fault-injection registry itself: spec grammar, seeded
determinism, scoping, and the disabled fast path."""

import pytest

from zest_tpu import faults


@pytest.fixture(autouse=True)
def _clean():
    faults.reset()
    yield
    faults.reset()


def _pattern(inj, name, n=64):
    return [bool(inj.roll(name)) for _ in range(n)]


class TestSpecGrammar:
    def test_parse_basic(self):
        specs = faults.parse_spec("peer_timeout:0.1,cdn_503:0.25")
        assert specs["peer_timeout"].prob == 0.1
        assert specs["cdn_503"].prob == 0.25

    def test_parse_args(self):
        specs = faults.parse_spec("peer_slow:1.0@2.5@127.0.0.1:7001")
        spec = specs["peer_slow"]
        assert spec.float_arg(1.0) == 2.5
        assert spec.scope() == "127.0.0.1:7001"

    def test_scope_only_arg(self):
        spec = faults.parse_spec("chunk_corrupt:1.0@10.0.0.2:6881")[
            "chunk_corrupt"]
        assert spec.scope() == "10.0.0.2:6881"
        assert spec.float_arg(3.0) == 3.0  # no numeric arg -> default

    def test_malformed_specs_fail_loud(self):
        for bad in ("peer_timeout", "x:notanumber", "x:1.5", ":0.1"):
            with pytest.raises(faults.FaultSpecError):
                faults.parse_spec(bad)

    def test_empty_clauses_ignored(self):
        assert faults.parse_spec(" , ,cdn_503:1.0,").keys() == {"cdn_503"}


class TestDeterminism:
    def test_same_seed_same_pattern(self):
        a = faults.FaultInjector(faults.parse_spec("f:0.3"), seed=7)
        b = faults.FaultInjector(faults.parse_spec("f:0.3"), seed=7)
        assert _pattern(a, "f") == _pattern(b, "f")

    def test_different_seed_different_pattern(self):
        a = faults.FaultInjector(faults.parse_spec("f:0.5"), seed=1)
        b = faults.FaultInjector(faults.parse_spec("f:0.5"), seed=2)
        assert _pattern(a, "f", 128) != _pattern(b, "f", 128)

    def test_faults_draw_independent_trials(self):
        """Two faults never perturb each other's sequence: interleaving
        draws of g between draws of f leaves f's pattern unchanged."""
        spec = "f:0.4,g:0.4"
        a = faults.FaultInjector(faults.parse_spec(spec), seed=3)
        solo = _pattern(a, "f")
        b = faults.FaultInjector(faults.parse_spec(spec), seed=3)
        mixed = []
        for _ in range(64):
            b.roll("g")
            mixed.append(bool(b.roll("f")))
        assert mixed == solo

    def test_prob_extremes(self):
        inj = faults.FaultInjector(
            faults.parse_spec("always:1.0,never:0.0"), seed=0)
        assert all(_pattern(inj, "always"))
        assert not any(_pattern(inj, "never"))
        assert inj.counters() == {"always": 64}


class TestScoping:
    def test_scoped_fault_only_fires_on_matching_key(self):
        inj = faults.FaultInjector(
            faults.parse_spec("f:1.0@10.0.0.2:6881"), seed=0)
        assert inj.roll("f", key="10.0.0.2:6881") is not None
        assert inj.roll("f", key="10.0.0.3:6881") is None
        assert inj.roll("f") is None  # site passes no key -> no fire

    def test_non_matching_key_consumes_no_trial(self):
        inj = faults.FaultInjector(faults.parse_spec("f:1.0@peerA"), seed=0)
        for _ in range(10):
            inj.roll("f", key="peerB")
        assert inj._trials.get("f", 0) == 0


class TestModuleSwitchboard:
    def test_disabled_by_default(self):
        assert faults.fire("anything") is None
        assert faults.counters() == {}

    def test_env_configuration(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_SPEC, "f:1.0")
        monkeypatch.setenv(faults.ENV_SEED, "9")
        faults.reset()
        assert faults.fire("f") is not None
        assert faults.active().seed == 9

    def test_install_and_reset(self):
        faults.install("f:1.0", seed=1)
        assert faults.fire("f") is not None
        faults.install(None)
        assert faults.fire("f") is None

    def test_sleep_if_returns_slept_seconds(self):
        faults.install("slow:1.0@0.01", seed=0)
        assert faults.sleep_if("slow") == pytest.approx(0.01)
        faults.install(None)
        assert faults.sleep_if("slow") == 0.0


class TestCorrupt:
    def test_deterministic_single_byte_flip(self):
        data = bytes(range(256))
        bad = faults.corrupt(data)
        assert bad != data and len(bad) == len(data)
        assert faults.corrupt(data) == bad
        diff = [i for i in range(256) if bad[i] != data[i]]
        assert diff == [128]

    def test_empty_payload_passthrough(self):
        assert faults.corrupt(b"") == b""
