"""Delta-pull contracts (ISSUE 10 tentpole).

A pull of revision B over a locally-evidenced revision A moves only
changed bytes (chunk-level DeltaPlan over the content-addressed cache),
short-circuits decode + verify + device_put for tensors whose chunk
cover is unchanged, and hot-swaps a resident rev-A param tree in place.
These tests pin:

- the multi-revision fixture's chunk-level dedup (revision B references
  revision A's xorbs; only changed chunks enter new xorbs);
- manifest save/load and base-revision resolution;
- per-tensor fingerprints: equal covers ⇒ equal fingerprints, and the
  unchanged-name set is exactly what the mutation left untouched;
- DeltaPlan classification is a pure function of the two revisions —
  cache warmth never enters ``changed_keys`` (the cross-host coop
  agreement), and the cooperative ownership plan over the changed set
  fingerprint-agrees regardless of input order;
- byte identity (``params_digest``) of the delta pull against a cold
  pull of B — streamed and non-streamed, in-place hot-swap and
  fresh-mesh — with the changed-bytes-only fetch asserted from
  FetchStats;
- mid-delta interrupt → resume idempotence, chaos ``chunk_corrupt``
  through a delta fetch (attribution + heal), ``ZEST_DELTA=0`` knob-off
  with the pre-delta stats schema, malformed env parsing raising;
- the ``zest diff`` dry run: correct totals, zero payload fetches.
"""

import json

import pytest

from fixtures import FixtureHub, FixtureRepo

from zest_tpu.bench_scale import llama_checkpoint_files
from zest_tpu.config import Config
from zest_tpu.models.loader import params_digest
from zest_tpu.transfer import delta
from zest_tpu.transfer.pull import pull_model

FILES_A = llama_checkpoint_files(0.012, shard_bytes=3 * 1024 * 1024,
                                 scale=8)
FILES_B = llama_checkpoint_files(0.012, shard_bytes=3 * 1024 * 1024,
                                 scale=8, mutate_fraction=0.01)
SHARDS = sorted(n for n in FILES_A if n.endswith(".safetensors"))
TOTAL_B = sum(len(b) for b in FILES_B.values())
SHA_B = "b" * 40


def _make_repo() -> FixtureRepo:
    repo = FixtureRepo("acme/delta", FILES_A, chunks_per_xorb=8)
    repo.add_revision(FILES_B, commit_sha=SHA_B)
    return repo


@pytest.fixture(scope="module")
def hub():
    with FixtureHub(_make_repo()) as h:
        yield h


def _cfg(hub, root, **kw):
    return Config(hf_home=root / "hf", cache_dir=root / "zest",
                  hf_token="hf_test", endpoint=hub.url, **kw)


def _quiet(*a, **k):
    pass


SHA_A = "f1x7ure5ha" + "0" * 30  # FixtureRepo's default commit sha


def _pull(hub, root, revision, **kw):
    cfg_kw = kw.pop("cfg_kw", {})
    return pull_model(_cfg(hub, root, **cfg_kw), "acme/delta",
                      revision=revision, no_p2p=True, log=_quiet, **kw)


# ── Fixture: multi-revision chunk dedup ──


def test_fixture_revision_dedup_and_exact_bytes():
    repo = _make_repo()
    # Revision B's reconstructions reference mostly revision-A xorbs:
    # the NEW xorb bytes the mutation introduced are a small fraction.
    a_xorbs = {t.hash_hex
               for f in repo.revisions[repo.commit_sha].values()
               if f.xet_hash
               for t in repo.reconstructions[f.xet_hash].terms}
    b_terms = [t for f in repo.revisions[SHA_B].values() if f.xet_hash
               for t in repo.reconstructions[f.xet_hash].terms]
    new_bytes = sum(t.unpacked_length for t in b_terms
                    if t.hash_hex not in a_xorbs)
    total = sum(t.unpacked_length for t in b_terms)
    assert 0 < new_bytes < 0.06 * total
    # The revision-aware hub surface: exact sha wins, "main" = latest.
    assert repo.sha_for(SHA_B) == SHA_B
    assert repo.sha_for("main") == SHA_B
    assert repo.sha_for(repo.commit_sha) == repo.commit_sha
    assert set(repo.files_for(repo.commit_sha)) == set(FILES_A)


# ── Manifests + fingerprints ──


def test_manifest_roundtrip_and_base_resolution(tmp_path):
    repo = _make_repo()
    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                 hf_token="hf_test")
    ff = repo.revisions[repo.commit_sha][SHARDS[0]]
    rec = repo.reconstructions[ff.xet_hash]

    class E:
        path, size, xet_hash, is_xet = SHARDS[0], len(ff.data), \
            ff.xet_hash, True

    assert delta.save_manifest(cfg, "acme/delta", SHA_A, [E],
                               lambda e: rec)
    man = delta.load_manifest(cfg, "acme/delta", SHA_A)
    assert man and man["revision"] == SHA_A
    assert man["files"][SHARDS[0]]["terms"] == [
        [t.hash_hex, t.range.start, t.range.end, t.unpacked_length]
        for t in rec.terms]
    # find_base: explicit sha, then newest-other; same-sha excluded.
    assert delta.find_base_manifest(cfg, "acme/delta", SHA_B,
                                    SHA_A) is not None
    assert delta.find_base_manifest(cfg, "acme/delta", SHA_B) is not None
    assert delta.find_base_manifest(cfg, "acme/delta", SHA_A) is None
    # Incomplete evidence declines to write.
    assert not delta.save_manifest(cfg, "acme/delta", "x" * 40, [E],
                                   lambda e: None)
    assert delta.load_manifest(cfg, "acme/delta", "x" * 40) is None


# ── Base selection with MULTIPLE cached revisions (ISSUE 19): the
# parent chain decides — closest ancestor wins, a descendant (newer
# revision derived from the target) is never handed back as base, and
# lineage-free manifests keep the historical newest-mtime order. ──


def _write_manifest(cfg, repo, sha, parent=None, mtime=None):
    doc = {"format": delta.MANIFEST_FORMAT, "repo": repo,
           "revision": sha, "saved_at": 0.0,
           "files": {"model.safetensors":
                     {"size": 4, "xet_hash": "ab" * 32, "terms": []}}}
    if parent:
        doc["parent"] = parent
    path = delta.manifest_path(cfg, repo, sha)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(doc))
    if mtime is not None:
        import os

        os.utime(path, (mtime, mtime))


def test_find_base_prefers_closest_ancestor_over_newest(tmp_path):
    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                 hf_token="hf_test")
    repo = "acme/lineage"
    A, B, C, D = ("a" * 40), ("b" * 40), ("c" * 40), ("d" * 40)
    # Chain A <- B <- C <- D; A has the NEWEST mtime. Pulling/pushing D
    # must pick C (the closest ancestor), never mtime-king A.
    _write_manifest(cfg, repo, A, parent=None, mtime=1_000_300)
    _write_manifest(cfg, repo, B, parent=A, mtime=1_000_010)
    _write_manifest(cfg, repo, C, parent=B, mtime=1_000_020)
    _write_manifest(cfg, repo, D, parent=C, mtime=1_000_030)
    man = delta.find_base_manifest(cfg, repo, D)
    assert man and man["revision"] == C
    # First hop's manifest gone: its parent link is unknowable, so the
    # chain walk ends and selection falls back to the newest
    # non-descendant (A) rather than guessing at B.
    delta.manifest_path(cfg, repo, C).unlink()
    man = delta.find_base_manifest(cfg, repo, D)
    assert man and man["revision"] == A


def test_find_base_never_selects_a_descendant(tmp_path):
    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                 hf_token="hf_test")
    repo = "acme/lineage"
    A, B, C = ("a" * 40), ("b" * 40), ("c" * 40)
    # Pulling B on a node that cached A (old) and C (C.parent == B — a
    # NEWER revision derived from B). B itself has no local manifest
    # (it is the revision being pulled). The descendant C must lose to
    # the older A: a descendant base would let the plan "reuse" chunks
    # the target revision predates.
    _write_manifest(cfg, repo, A, parent=None, mtime=1_000_000)
    _write_manifest(cfg, repo, C, parent=B, mtime=1_000_500)
    man = delta.find_base_manifest(cfg, repo, B)
    assert man and man["revision"] == A
    # Only the descendant cached: no eligible base at all.
    delta.manifest_path(cfg, repo, A).unlink()
    assert delta.find_base_manifest(cfg, repo, B) is None


def test_find_base_without_lineage_keeps_newest_mtime_order(tmp_path):
    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                 hf_token="hf_test")
    repo = "acme/lineage"
    X, Y, Z = ("1" * 40), ("2" * 40), ("9" * 40)
    _write_manifest(cfg, repo, X, mtime=1_000_000)
    _write_manifest(cfg, repo, Y, mtime=1_000_100)
    man = delta.find_base_manifest(cfg, repo, Z)
    assert man and man["revision"] == Y  # newest wins, pre-lineage rule
    # A cyclic/corrupt parent chain must not hang or crash selection.
    _write_manifest(cfg, repo, X, parent=Y, mtime=1_000_000)
    _write_manifest(cfg, repo, Y, parent=X, mtime=1_000_100)
    man = delta.find_base_manifest(cfg, repo, Z)
    assert man and man["revision"] == Y


def test_tensor_fingerprints_detect_exactly_the_changed_tensors():
    from zest_tpu.models.safetensors_io import parse_header_prefix

    repo = _make_repo()
    changed_names: set[str] = set()
    unchanged_names: set[str] = set()
    for shard in SHARDS:
        fa = repo.revisions[repo.commit_sha][shard]
        fb = repo.revisions[SHA_B][shard]
        rec_a = repo.reconstructions[fa.xet_hash]
        rec_b = repo.reconstructions[fb.xet_hash]
        header = parse_header_prefix(fb.data)
        got = delta.unchanged_tensor_names(delta.terms_of(rec_a), rec_b,
                                           header)
        # Ground truth from the raw file bytes: a tensor whose span's
        # bytes are identical MAY be reused; one whose bytes differ
        # must NEVER be.
        for name, info in header.tensors.items():
            lo, hi = info.file_range(header.data_start)
            same = fa.data[lo:hi] == fb.data[lo:hi]
            if not same:
                assert name not in got, name
                changed_names.add(name)
            elif name in got:
                unchanged_names.add(name)
    assert changed_names, "the mutation changed no tensor?"
    assert unchanged_names, "the fingerprint reused no tensor?"
    # Identical revisions fingerprint identically, everywhere.
    fa = repo.revisions[repo.commit_sha][SHARDS[0]]
    rec = repo.reconstructions[fa.xet_hash]
    header = parse_header_prefix(fa.data)
    assert delta.unchanged_tensor_names(
        delta.terms_of(rec), rec, header) == set(header.tensors)


def test_plan_classification_is_cache_independent(hub, tmp_path):
    from zest_tpu.parallel.plan import collect_units
    from zest_tpu.storage import XorbCache
    from zest_tpu.transfer.bridge import XetBridge

    repo = hub.repos["acme/delta"]
    base_files = {}
    for shard in SHARDS:
        fa = repo.revisions[SHA_A][shard]
        base_files[shard] = {
            "terms": delta.terms_of(repo.reconstructions[fa.xet_hash])}
    base_man = {"format": 1, "repo": "acme/delta", "revision": SHA_A,
                "files": base_files}
    recs_b = [repo.reconstructions[repo.revisions[SHA_B][s].xet_hash]
              for s in SHARDS]
    files_terms = [(s, delta.terms_of(r))
                   for s, r in zip(SHARDS, recs_b)]
    units = [(hh, fi) for (hh, _s), fi in collect_units(recs_b)]

    cold = delta.build_plan(base_man, files_terms, units=units)
    # Warm cache: pull revision A first, then rebuild the plan against
    # that cache — classification must be IDENTICAL (stale accounting
    # may differ; changed_keys may not).
    res = _pull(hub, tmp_path, SHA_A)
    bridge_cfg = _cfg(hub, tmp_path)
    warm = delta.build_plan(base_man, files_terms, units=units,
                            cache=XorbCache(bridge_cfg))
    assert cold.changed_keys == warm.changed_keys
    assert cold.changed_bytes == warm.changed_bytes
    assert 0 < cold.delta_bytes_ratio < 0.10
    assert set(cold.per_file) == set(SHARDS)
    assert cold.total_bytes == sum(
        r.total_bytes for r in recs_b)
    # Warm A cache holds every unchanged unit: nothing is stale.
    assert warm.stale_units == 0
    # Deterministic changed-unit order.
    assert cold.changed_units == sorted(
        cold.changed_units, key=lambda u: (u[0], u[1].range.start))
    del res
    # XetBridge import kept honest (plan never needed one).
    assert XetBridge is not None


def test_coop_plan_over_changed_units_fingerprint_agrees(hub):
    import random

    from zest_tpu.parallel.plan import collect_units
    from zest_tpu.transfer.coop import CoopPlan

    repo = hub.repos["acme/delta"]
    base_files = {
        s: {"terms": delta.terms_of(
            repo.reconstructions[repo.revisions[SHA_A][s].xet_hash])}
        for s in SHARDS}
    base_man = {"format": 1, "repo": "acme/delta", "revision": SHA_A,
                "files": base_files}
    recs_b = [repo.reconstructions[repo.revisions[SHA_B][s].xet_hash]
              for s in SHARDS]
    units = [(hh, fi) for (hh, _s), fi in collect_units(recs_b)]
    plan = delta.build_plan(
        base_man, [(s, delta.terms_of(r))
                   for s, r in zip(SHARDS, recs_b)], units=units)
    assert plan.changed_units

    p1 = CoopPlan.build(recs_b, 4, units=plan.changed_units)
    shuffled = list(plan.changed_units)
    random.Random(7).shuffle(shuffled)
    p2 = CoopPlan.build(list(reversed(recs_b)), 4, units=shuffled)
    # The satellite: hosts with differently-warm caches (and any input
    # order) agree byte-for-byte on the changed-set ownership plan.
    assert p1.fingerprint() == p2.fingerprint()
    assert len(p1.units) == len(plan.changed_units)
    # And it is NOT the full-set plan: unchanged units never shard.
    assert p1.fingerprint() != CoopPlan.build(recs_b, 4).fingerprint()


def test_changed_units_order_through_shared_priority_key(hub):
    """The delta subset inherits the ONE shared landing-priority sort:
    coop's ``_layer_order`` over changed units puts first-layer-serving
    units first — same key the solo warm sorts with."""
    from zest_tpu.models.direct import (
        unit_layer_priorities,
        unit_priority_sort_key,
    )
    from zest_tpu.models.safetensors_io import parse_header_prefix
    from zest_tpu.parallel.plan import collect_units
    from zest_tpu.transfer.coop import _layer_order

    repo = hub.repos["acme/delta"]
    rwh = [(repo.reconstructions[repo.revisions[SHA_B][s].xet_hash],
            parse_header_prefix(repo.revisions[SHA_B][s].data))
           for s in SHARDS]
    prio = unit_layer_priorities(rwh)
    recs_b = [r for r, _h in rwh]
    base_files = {
        s: {"terms": delta.terms_of(
            repo.reconstructions[repo.revisions[SHA_A][s].xet_hash])}
        for s in SHARDS}
    plan = delta.build_plan(
        {"format": 1, "repo": "acme/delta", "revision": SHA_A,
         "files": base_files},
        [(s, delta.terms_of(r)) for s, r in zip(SHARDS, recs_b)],
        units=[(hh, fi) for (hh, _s), fi in collect_units(recs_b)])
    ordered = _layer_order(plan.changed_units, prio)
    key = unit_priority_sort_key(prio)
    assert ordered == sorted(plan.changed_units, key=key)
    assert len(ordered) == len(plan.changed_units)


# ── End-to-end: identity + schema ──


def test_hot_swap_digest_identical_and_changed_bytes_only(hub, tmp_path):
    res_a = _pull(hub, tmp_path / "d", SHA_A, device="tpu")
    base = res_a.params
    res_b = _pull(hub, tmp_path / "d", SHA_B, device="tpu",
                  base_params=base, base_revision=SHA_A)
    cold = _pull(hub, tmp_path / "cold", SHA_B, device="tpu")
    try:
        d = res_b.stats["delta"]
        assert d["base_revision"] == SHA_A
        # Changed-bytes-only fetch, asserted from FetchStats: the
        # network moved only the changed units' (compressed) bytes.
        fetched = res_b.stats["fetch"]["bytes"]["cdn"]
        assert fetched <= d["changed_bytes"] * 1.1
        assert fetched < 0.10 * TOTAL_B
        assert d["fetched_bytes"] == fetched
        assert 0 < d["delta_bytes_ratio"] < 0.10
        # Hot swap: headline + evidence + consumed base.
        assert res_b.stats["time_to_swap_s"] == \
            res_b.stats["time_to_hbm_s"]
        swap = res_b.stats["hbm"]["swap"]
        assert swap["reused_tensors"] > 0
        assert swap["reused_tensors"] == d["tensors"]["reused"]
        assert not base, "base params must be consumed"
        # Byte identity with a cold pull of B, both places bytes land.
        assert params_digest(res_b.params) == params_digest(cold.params)
        for name, data in FILES_B.items():
            assert (res_b.snapshot_dir / name).read_bytes() == data, name
        # Cold pull of B in a fresh cache grew no delta keys (no base
        # evidence there).
        assert "delta" not in cold.stats
        assert "time_to_swap_s" not in cold.stats
    finally:
        res_a.params = None
        res_b.params = None
        cold.params = None


def test_non_streamed_hot_swap_identical(hub, tmp_path):
    kw = {"cfg_kw": {"land_stream": False}}
    res_a = _pull(hub, tmp_path / "d", SHA_A, device="tpu", **kw)
    base = res_a.params
    res_b = _pull(hub, tmp_path / "d", SHA_B, device="tpu",
                  base_params=base, base_revision=SHA_A, **kw)
    cold = _pull(hub, tmp_path / "cold", SHA_B, device="tpu", **kw)
    try:
        assert res_b.stats["hbm"]["swap"]["reused_tensors"] > 0
        assert not base
        assert res_b.stats["time_to_swap_s"] is not None
        assert params_digest(res_b.params) == params_digest(cold.params)
    finally:
        res_a.params = None
        res_b.params = None
        cold.params = None


def test_base_params_without_base_revision_raises(hub, tmp_path):
    """Tensor reuse is judged against the named revision's manifest —
    guessing (newest manifest) could diff against a revision the
    resident tree does not hold and silently reuse wrong bytes."""
    with pytest.raises(ValueError, match="base_revision"):
        _pull(hub, tmp_path, SHA_B, device="tpu", base_params={})


def test_dtype_mismatch_reuses_nothing_but_stays_correct(hub, tmp_path):
    """A delta pull landing at a different --dtype than the base tree
    must not mix dtypes: nothing short-circuits, and the result is
    byte-identical to a cold pull at the new dtype."""
    res_a = _pull(hub, tmp_path / "d", SHA_A, device="tpu")  # bf16 tree
    base = res_a.params
    kw = {"cfg_kw": {"land_dtype": "f32"}}
    res_b = _pull(hub, tmp_path / "d", SHA_B, device="tpu",
                  base_params=base, base_revision=SHA_A, **kw)
    cold = _pull(hub, tmp_path / "cold", SHA_B, device="tpu", **kw)
    try:
        # The dtype guard re-landed EVERYTHING: still a swap (the base
        # tree was superseded and consumed), but zero tensors reused —
        # and the result matches a cold pull at the new dtype exactly.
        swap = res_b.stats["hbm"]["swap"]
        assert swap["reused_tensors"] == 0
        assert not base, "superseded base tree must still be consumed"
        assert res_b.stats["time_to_swap_s"] is not None
        assert params_digest(res_b.params) == params_digest(cold.params)
    finally:
        res_a.params = None
        res_b.params = None
        cold.params = None


def test_fresh_mesh_delta_identical(hub, tmp_path):
    """No resident base tree: the delta still plans (network moves only
    changed bytes) but every tensor lands fresh — no swap keys."""
    res_a = _pull(hub, tmp_path / "d", SHA_A, device="tpu")
    res_a.params = None  # the mesh "lost" the tree; cache remains
    res_b = _pull(hub, tmp_path / "d", SHA_B, device="tpu")
    cold = _pull(hub, tmp_path / "cold", SHA_B, device="tpu")
    try:
        d = res_b.stats["delta"]
        assert res_b.stats["fetch"]["bytes"]["cdn"] < 0.10 * TOTAL_B
        assert "tensors" not in d
        assert "time_to_swap_s" not in res_b.stats
        assert "swap" not in res_b.stats["hbm"]
        assert params_digest(res_b.params) == params_digest(cold.params)
    finally:
        res_b.params = None
        cold.params = None


def test_plain_pull_delta_stats_and_resume_after_interrupt(
        hub, tmp_path, monkeypatch):
    """A non-device delta pull: the plan still gates the network, and a
    mid-delta failure leaves a resumable state — the re-pull converges
    byte-exact (idempotence over the content-addressed cache)."""
    import zest_tpu.transfer.pull as pull_mod

    _pull(hub, tmp_path, SHA_A)
    victim = SHARDS[-1]
    orig = pull_mod._pull_xet_file

    def sabotaged(bridge, par, hub_, cfg, repo_id, revision, entry, dest,
                  log, **kw):
        if entry.path == victim and revision == SHA_B:
            raise RuntimeError("injected mid-delta failure")
        return orig(bridge, par, hub_, cfg, repo_id, revision, entry,
                    dest, log, **kw)

    monkeypatch.setattr(pull_mod, "_pull_xet_file", sabotaged)
    with pytest.raises(RuntimeError, match="injected mid-delta"):
        _pull(hub, tmp_path, SHA_B)
    monkeypatch.setattr(pull_mod, "_pull_xet_file", orig)
    res = _pull(hub, tmp_path, SHA_B)
    assert "delta" in res.stats
    for name, data in FILES_B.items():
        assert (res.snapshot_dir / name).read_bytes() == data, name
    # Both revisions' manifests persist for the NEXT delta.
    cfg = _cfg(hub, tmp_path)
    assert delta.load_manifest(cfg, "acme/delta", SHA_A)
    assert delta.load_manifest(cfg, "acme/delta", SHA_B)


def test_missing_base_evidence_degrades_with_flight_event(hub, tmp_path):
    from zest_tpu import telemetry

    telemetry.recorder.reset()
    res_a = _pull(hub, tmp_path, SHA_A, device="tpu")
    base = res_a.params
    # Wipe the manifests: the rev-A evidence is gone.
    import shutil

    shutil.rmtree(delta.manifest_dir(_cfg(hub, tmp_path)))
    res_b = _pull(hub, tmp_path, SHA_B, device="tpu",
                  base_params=base, base_revision=SHA_A)
    try:
        assert "delta" not in res_b.stats
        assert "time_to_swap_s" not in res_b.stats
        assert base, "degraded pull must leave the base tree alone"
        kinds = [e["kind"] for e in telemetry.recorder.tail()]
        assert "delta_degraded" in kinds
    finally:
        res_a.params = None
        res_b.params = None
        base.clear()


def test_complete_snapshot_hot_swap_degrades_loudly(hub, tmp_path):
    """Both snapshots fully materialized: the direct landing defers to
    disk staging, so the short-circuit can't run — the pull must SAY so
    (flight event + log) and leave the base tree alone, not silently
    return a second full tree."""
    from zest_tpu import telemetry

    res_a = _pull(hub, tmp_path, SHA_A, device="tpu")
    res_b1 = _pull(hub, tmp_path, SHA_B, device="tpu")  # materializes B
    res_b1.params = None
    telemetry.recorder.reset()
    base = res_a.params
    res_b2 = _pull(hub, tmp_path, SHA_B, device="tpu",
                   base_params=base, base_revision=SHA_A)
    try:
        assert base, "base tree must be left untouched"
        assert "time_to_swap_s" not in res_b2.stats
        events = [e for e in telemetry.recorder.tail()
                  if e["kind"] == "delta_degraded"]
        assert events and events[0]["reason"] == \
            "snapshot already complete"
        assert params_digest(res_b2.params) is not None
    finally:
        res_a.params = None
        res_b2.params = None
        base.clear()


# ── Chaos: corruption through a delta fetch ──


@pytest.mark.chaos
def test_chunk_corrupt_through_delta_attributed_and_healed(tmp_path):
    """A peer serving flipped bytes for the CHANGED units of a delta
    pull: corruption is attributed at the trust boundary, the unit
    heals from CDN, and the landed tree + files come out byte-exact —
    the delta changed what is fetched, never the trust model."""
    from zest_tpu import faults
    from zest_tpu.transfer.server import BtServer
    from zest_tpu.transfer.swarm import SwarmDownloader

    repo = _make_repo()
    faults.reset()
    with FixtureHub(repo) as hub:
        def cfg_for(name):
            return Config(hf_home=tmp_path / name / "hf",
                          cache_dir=tmp_path / name / "zest",
                          hf_token="hf_test", endpoint=hub.url)

        seed_cfg = cfg_for("seeder")
        pull_model(seed_cfg, "acme/delta", revision=SHA_B, no_p2p=True,
                   log=_quiet)
        server = BtServer(seed_cfg)
        port = server.start()
        try:
            cfg = cfg_for("leecher")
            pull_model(cfg, "acme/delta", revision=SHA_A, no_p2p=True,
                       log=_quiet)
            faults.install(f"chunk_corrupt:1.0@127.0.0.1:{port}",
                           seed=1337)
            swarm = SwarmDownloader(cfg)
            swarm.add_direct_peer("127.0.0.1", port)
            try:
                result = pull_model(cfg, "acme/delta", revision=SHA_B,
                                    swarm=swarm, log=_quiet)
            finally:
                swarm.close()
        finally:
            server.shutdown()
            faults.reset()

    assert "delta" in result.stats
    for name, data in FILES_B.items():
        assert (result.snapshot_dir / name).read_bytes() == data, name
    assert result.stats["faults"]["chunk_corrupt"] >= 1
    assert result.stats["swarm"]["corrupt_from_peer"] >= 1
    assert result.stats["fetch"]["bytes"]["cdn"] > 0


# ── Knob-off + env parsing ──


def test_knob_off_restores_schema_and_writes_no_manifest(hub, tmp_path):
    kw = {"cfg_kw": {"delta_pull": False}}
    _pull(hub, tmp_path, SHA_A, device="tpu", **kw).params = None
    res_off = _pull(hub, tmp_path, SHA_B, device="tpu", **kw)
    base_line = _pull(hub, tmp_path / "ref", SHA_B, device="tpu", **kw)
    try:
        assert "delta" not in res_off.stats
        assert "time_to_swap_s" not in res_off.stats
        assert "swap" not in res_off.stats["hbm"]
        # Schema identical to a pre-delta pull of the same shape.
        assert set(res_off.stats) == set(base_line.stats)
        assert not delta.manifest_dir(_cfg(hub, tmp_path)).exists()
        for name, data in FILES_B.items():
            assert (res_off.snapshot_dir / name).read_bytes() == data
    finally:
        res_off.params = None
        base_line.params = None


def test_config_delta_env_parsing():
    base = {"HF_HOME": "/tmp/x", "ZEST_CACHE_DIR": "/tmp/y"}
    assert Config.load(base).delta_pull is True
    assert Config.load({**base, "ZEST_DELTA": "0"}).delta_pull is False
    assert Config.load({**base, "ZEST_DELTA": "1"}).delta_pull is True
    # The rollback knob parses STRICTLY: a typo must raise, never
    # silently keep deltas on.
    with pytest.raises(ValueError):
        Config.load({**base, "ZEST_DELTA": "false"})
    with pytest.raises(ValueError):
        Config.load({**base, "ZEST_DELTA": "off"})


# ── zest diff (dry run) ──


def test_diff_cli_dry_run_no_payload_fetch(hub, tmp_path, monkeypatch,
                                           capsys):
    from zest_tpu import cli

    monkeypatch.setenv("HF_HOME", str(tmp_path / "hf"))
    monkeypatch.setenv("ZEST_CACHE_DIR", str(tmp_path / "zest"))
    monkeypatch.setenv("HF_TOKEN", "hf_test")
    monkeypatch.setenv("HF_ENDPOINT", hub.url)
    seen_before = len(hub.requests_seen)
    rc = cli.main(["diff", f"acme/delta@{SHA_A}",
                   f"acme/delta@{SHA_B}"])
    out = capsys.readouterr().out
    assert rc == 0
    assert f"delta acme/delta@{SHA_A} -> acme/delta@{SHA_B}" in out
    assert "bytes changed" in out
    # Dry run: metadata only — not one payload byte moved.
    new_requests = hub.requests_seen[seen_before:]
    assert not any("/xorbs/" in r for r in new_requests), new_requests
    assert any("/v1/reconstructions/" in r for r in new_requests)
    # --json round-trips the plan summary.
    rc = cli.main(["diff", f"acme/delta@{SHA_A}",
                   f"acme/delta@{SHA_B}", "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert 0 < doc["delta_bytes_ratio"] < 0.10
    assert set(doc["files"]) == set(SHARDS)


def test_stats_watch_delta_line():
    from zest_tpu.cli import _stats_watch_lines

    lines = _stats_watch_lines(
        {"landing": {"first_layer_s": 1.2, "time_to_hbm_s": 6.0,
                     "delta_ratio": 0.021, "swap_s": 0.8}},
        {"version": "x"})
    dline = [ln for ln in lines if ln.startswith("delta:")]
    assert dline and "fetched=2.1% of bytes" in dline[0]
    assert "swap=0.8s" in dline[0]
