"""Child process for the two-process federated-round test.

Runs as a REAL separate process (no monkeypatched process indices): pod 0
fetches its owned units from the fixture hub's CDN, serves them on an
ephemeral DCN port, and stays up until the parent signals done. The
parent process (pytest) is pod 1 and pulls pod-0-owned units over the DCN
chunk RPC — real bytes over a real socket between two OS processes.

Usage: python tests/_federated_child.py HUB_URL ROOT_DIR REPO_ID
Writes: ROOT_DIR/port       (the DCN port, once serving)
        ROOT_DIR/stats.json (pod 0's federated_round stats)
Exits when ROOT_DIR/done appears (rc 0) or after 60s (rc 3).
"""

import json
import pathlib
import sys
import time


def main() -> int:
    hub_url, root, repo_id = sys.argv[1], pathlib.Path(sys.argv[2]), sys.argv[3]
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

    from zest_tpu.cas.hub import HubClient
    from zest_tpu.config import Config
    from zest_tpu.transfer.bridge import XetBridge
    from zest_tpu.transfer.dcn import DcnServer
    from zest_tpu.transfer.federated import federated_round

    cfg = Config(
        hf_home=root / "hf",
        cache_dir=root / "zest",
        hf_token="hf_test",
        endpoint=hub_url,
        dcn_port=0,  # ephemeral
    )
    bridge = XetBridge(cfg)
    bridge.authenticate(repo_id)
    recs = [
        bridge.get_reconstruction(e.xet_hash)
        for e in HubClient(cfg).list_files(repo_id)
        if e.is_xet
    ]

    # Pod 0 of 2, no peers: fetch own units from CDN, CDN-degrade nothing
    # (foreign units are pod 1's business).
    stats = federated_round(bridge, recs, 0, 2, pod_addrs={})
    (root / "stats.json").write_text(json.dumps(stats))

    server = DcnServer(cfg, bridge.cache)
    port = server.start()
    (root / "port").write_text(str(port))

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if (root / "done").exists():
            server.shutdown()
            return 0
        time.sleep(0.1)
    server.shutdown()
    return 3


if __name__ == "__main__":
    sys.exit(main())
