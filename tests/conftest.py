"""Test harness configuration.

Tests run hermetically on CPU with a virtual 8-device mesh so multi-chip
sharding paths are exercised without TPU hardware (the reference's analog is
its Docker 2-node harness, test/local/p2p-docker-test.sh). Must run before
any jax import, hence module-level in conftest.
"""

import os

# Force CPU even when a real TPU is attached: unit tests are hermetic; only
# bench.py and the driver's compile checks run on hardware. The env vars
# alone are not enough — sitecustomize may import jax before this module
# runs, freezing its config defaults — so set both env and jax.config.
os.environ["JAX_PLATFORMS"] = "cpu"
# Hermeticity: the serving path arms jax's persistent compile cache
# under ~/.cache/zest by default (models.generate.enable_compile_cache);
# tests must not write to — or get warm-start artifacts from — the
# developer's home.
os.environ.setdefault("ZEST_JIT_CACHE", "0")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    # Newer jax spells the virtual-device count as a config option; older
    # builds (<=0.4.x) only honor the XLA_FLAGS form set above.
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass

import pytest  # noqa: E402


@pytest.fixture
def tmp_config(tmp_path):
    """Hermetic Config rooted in a tempdir (reference: injected environ,
    src/config.zig:160-166)."""
    from zest_tpu.config import Config

    return Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest")
