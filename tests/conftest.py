"""Test harness configuration.

Tests run hermetically on CPU with a virtual 8-device mesh so multi-chip
sharding paths are exercised without TPU hardware (the reference's analog is
its Docker 2-node harness, test/local/p2p-docker-test.sh). Must run before
any jax import, hence module-level in conftest.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture
def tmp_config(tmp_path):
    """Hermetic Config rooted in a tempdir (reference: injected environ,
    src/config.zig:160-166)."""
    from zest_tpu.config import Config

    return Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest")
