"""BT wire + BEP XET codec tests, fixed-buffer roundtrip style
(parity: reference bt_wire.zig:160-233, bep_xet.zig:240-332)."""

import os

import pytest

from zest_tpu.p2p import bep_xet, wire
from zest_tpu.p2p.bep_xet import (
    ChunkError,
    ChunkNotFound,
    ChunkRequest,
    ChunkResponse,
)


class TestHandshake:
    def test_roundtrip(self):
        ih, pid = os.urandom(20), os.urandom(20)
        buf = wire.encode_handshake(ih, pid)
        assert len(buf) == 68
        hs = wire.decode_handshake(buf)
        assert hs.info_hash == ih and hs.peer_id == pid
        assert hs.supports_bep10

    def test_wire_layout(self):
        buf = wire.encode_handshake(b"\x01" * 20, b"\x02" * 20)
        assert buf[0] == 19
        assert buf[1:20] == b"BitTorrent protocol"
        assert buf[25] == 0x10  # reserved byte 5: BEP 10 bit

    def test_bad_protocol_string_rejected(self):
        buf = bytearray(wire.encode_handshake(b"\x01" * 20, b"\x02" * 20))
        buf[5] ^= 0xFF
        with pytest.raises(wire.WireError):
            wire.decode_handshake(bytes(buf))

    def test_bad_length_rejected(self):
        with pytest.raises(wire.WireError):
            wire.decode_handshake(b"short")
        with pytest.raises(wire.WireError):
            wire.encode_handshake(b"short", b"\x02" * 20)


class TestFraming:
    def test_message_layout(self):
        buf = wire.encode_message(wire.MessageId.UNCHOKE)
        assert buf == b"\x00\x00\x00\x01\x01"

    def test_extended_layout(self):
        buf = wire.encode_extended(3, b"payload")
        # [len=2+7][20][3]payload
        assert buf[:4] == (2 + 7).to_bytes(4, "big")
        assert buf[4] == 20 and buf[5] == 3
        assert buf[6:] == b"payload"

    def test_keepalive(self):
        assert wire.encode_keepalive() == b"\x00" * 4

    def test_size_cap(self):
        with pytest.raises(wire.WireError):
            wire.decode_message_header((wire.MAX_MESSAGE_SIZE + 1).to_bytes(4, "big"))

    def test_parse_extended(self):
        ext_id, payload = wire.parse_extended(b"\x07hello")
        assert ext_id == 7 and payload == b"hello"
        with pytest.raises(wire.WireError):
            wire.parse_extended(b"")


class TestXetMessages:
    def test_chunk_request_45_bytes(self):
        h = os.urandom(32)
        buf = bep_xet.encode(ChunkRequest(7, h, 3, 9))
        assert len(buf) == 45 and buf[0] == 0x01
        msg = bep_xet.decode(buf)
        assert msg == ChunkRequest(7, h, 3, 9)

    def test_chunk_response_roundtrip(self):
        data = os.urandom(5000)
        buf = bep_xet.encode(ChunkResponse(9, 12, data))
        msg = bep_xet.decode(buf)
        assert msg == ChunkResponse(9, 12, data)
        assert buf[0] == 0x02 and len(buf) == 13 + len(data)

    def test_chunk_not_found_37_bytes(self):
        h = os.urandom(32)
        buf = bep_xet.encode(ChunkNotFound(4, h))
        assert len(buf) == 37 and buf[0] == 0x03
        assert bep_xet.decode(buf) == ChunkNotFound(4, h)

    def test_chunk_error_roundtrip(self):
        buf = bep_xet.encode(ChunkError(2, 500, b"boom"))
        assert buf[0] == 0x04
        assert bep_xet.decode(buf) == ChunkError(2, 500, b"boom")

    def test_length_field_mismatch_rejected(self):
        buf = bytearray(bep_xet.encode(ChunkResponse(1, 0, b"abc")))
        buf += b"EXTRA"
        with pytest.raises(bep_xet.XetMessageError):
            bep_xet.decode(bytes(buf))

    def test_unknown_type_rejected(self):
        with pytest.raises(bep_xet.XetMessageError):
            bep_xet.decode(b"\x99" + b"\x00" * 44)

    def test_truncated_rejected(self):
        for bad in [b"", b"\x01short", b"\x02\x00\x00"]:
            with pytest.raises(bep_xet.XetMessageError):
                bep_xet.decode(bad)


class TestExtHandshake:
    def test_roundtrip(self):
        buf = bep_xet.make_ext_handshake(3, listen_port=6881)
        caps = bep_xet.parse_ext_handshake(buf)
        assert caps.ut_xet_id == 3
        assert caps.listen_port == 6881
        assert caps.client and caps.client.startswith(b"zest-tpu/")

    def test_no_ut_xet(self):
        from zest_tpu.p2p import bencode

        caps = bep_xet.parse_ext_handshake(
            bencode.encode({b"m": {b"ut_other": 1}, b"v": b"x"})
        )
        assert caps.ut_xet_id is None

    def test_garbage_rejected(self):
        with pytest.raises(bep_xet.XetMessageError):
            bep_xet.parse_ext_handshake(b"not bencode at all \xff")


# ── native one-pass framer parity (zest_tpu/native/wire.cc) ──


def test_encode_framed_matches_pure_concat():
    """The native framer must be byte-identical to the pure chain
    wire.encode_extended(ext, bep_xet.encode(msg)) for every message kind
    it accelerates — and decode back to the original message."""
    from zest_tpu.native import lib
    from zest_tpu.p2p import bep_xet, wire

    h = bytes(range(32))
    msgs = [
        bep_xet.ChunkRequest(0xABCDEF01, h, 3, 900),
        bep_xet.ChunkResponse(7, 12, b"\x00\x01" * 40_000),
        bep_xet.ChunkResponse(8, 0, b""),
        bep_xet.ChunkNotFound(0xFFFFFFFF, h),
    ]
    for m in msgs:
        pure = wire.encode_extended(9, bep_xet.encode(m))
        framed = bep_xet.encode_framed(9, m)
        assert framed == pure, type(m).__name__
        # roundtrip through the decoders
        length = wire.decode_message_header(framed[:4])
        assert length == len(framed) - 4
        ext_id, sub = wire.parse_extended(framed[5:])
        assert ext_id == 9
        assert bep_xet.decode(sub) == m
    assert lib.available(), "native lib should compile in this image"


def test_encode_framed_error_falls_back_to_pure():
    from zest_tpu.p2p import bep_xet, wire

    m = bep_xet.ChunkError(3, 42, b"boom")
    assert bep_xet.encode_framed(5, m) == wire.encode_extended(
        5, bep_xet.encode(m)
    )


def test_encode_framed_validates_hash_length():
    import pytest

    from zest_tpu.p2p import bep_xet

    with pytest.raises(bep_xet.XetMessageError):
        bep_xet.encode_framed(1, bep_xet.ChunkRequest(1, b"short", 0, 1))


def test_encode_framed_rejects_out_of_range_fields():
    """ctypes would silently truncate (c_uint8(300) → 44) where the pure
    path raises — the framed encoder must fail loudly first."""
    import pytest

    from zest_tpu.p2p import bep_xet, wire

    h = bytes(32)
    with pytest.raises(bep_xet.XetMessageError, match="ext_id"):
        bep_xet.encode_framed(300, bep_xet.ChunkNotFound(1, h))
    with pytest.raises(bep_xet.XetMessageError, match="request_id"):
        bep_xet.encode_framed(1, bep_xet.ChunkNotFound(-1, h))
    with pytest.raises(bep_xet.XetMessageError, match="request_id"):
        bep_xet.encode_framed(1, bep_xet.ChunkNotFound(1 << 32, h))
    with pytest.raises(bep_xet.XetMessageError, match="chunk_offset"):
        bep_xet.encode_framed(1, bep_xet.ChunkResponse(1, -5, b"x"))
    with pytest.raises(bep_xet.XetMessageError, match="range"):
        bep_xet.encode_framed(1, bep_xet.ChunkRequest(1, h, 0, 1 << 33))


def test_encode_framed_enforces_message_cap(monkeypatch):
    import pytest

    from zest_tpu.p2p import bep_xet, wire

    # Shrink the cap rather than allocating 64 MiB in a unit test.
    monkeypatch.setattr(wire, "MAX_MESSAGE_SIZE", 1024)
    with pytest.raises(wire.WireError, match="too large"):
        bep_xet.encode_framed(1, bep_xet.ChunkResponse(1, 0, b"x" * 2048))
