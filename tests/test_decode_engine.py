"""ISSUE 3 decode engine: native batch decode, columnar reader, fused
Pallas decode→verify, and the satellites that rode along.

Byte identity is the contract everywhere: the native engine, the pure-
Python fallback, and the per-chunk legacy path must be indistinguishable
to every caller — the engine is a performance tier, never a new trust
model."""

import hashlib
import os

import numpy as np
import pytest

from zest_tpu.cas import compression as comp
from zest_tpu.cas import hashing
from zest_tpu.cas.compression import CompressionError, Scheme
from zest_tpu.cas.xorb import XorbBuilder, XorbFormatError, XorbReader


def _native_available() -> bool:
    return comp.native_batch_available()


def _chunk(rng, n, compressible=False):
    if compressible:
        return bytes(np.repeat(
            rng.integers(0, 256, n // 4 + 1, dtype=np.uint8), 4)[:n])
    return bytes(rng.integers(0, 256, n, dtype=np.uint8))


# Odd tails matter: BG4 planes of a length-n chunk are (n-k+3)//4 bytes,
# bitslice planes (n+7)//8 — every boundary case below exercises a
# different tail shape.
ODD_LENGTHS = (1, 2, 3, 5, 7, 17, 1001, 65537)


class TestBatchDecodeIdentity:
    """decode_batch_into: native vs pure Python, all schemes."""

    def _cases(self):
        rng = np.random.default_rng(7)
        cases = []
        for n in ODD_LENGTHS:
            for compressible in (False, True):
                data = _chunk(rng, n, compressible)
                for scheme in Scheme:
                    cases.append((data, scheme,
                                  comp.compress(data, scheme)))
        return cases

    @pytest.mark.parametrize("use_native", [False, True])
    def test_all_schemes_byte_identity(self, use_native):
        if use_native and not _native_available():
            pytest.skip("native lib unavailable")
        cases = self._cases()
        src = bytearray()
        descs = []
        pos = 0
        for data, scheme, payload in cases:
            descs.append((None, len(src), len(payload), int(scheme),
                          pos, len(data)))
            src += payload
            pos += len(data)
        src = bytes(src)
        descs = [(src, *d[1:]) for d in descs]
        out = bytearray(pos)
        wrote = comp.decode_batch_into(descs, out, workers=3,
                                       use_native=use_native)
        assert wrote == pos
        cursor = 0
        for data, scheme, _payload in cases:
            assert bytes(out[cursor:cursor + len(data)]) == data, \
                (len(data), scheme, use_native)
            cursor += len(data)

    def test_empty_batch_is_a_noop(self):
        assert comp.decode_batch_into([], bytearray(0)) == 0
        assert comp.decode_columns_into([], bytearray(4)) == 0

    def test_overlapping_dst_ranges_rejected(self):
        payload = b"abcd"
        descs = [(payload, 0, 4, int(Scheme.NONE), 0, 4),
                 (payload, 0, 4, int(Scheme.NONE), 2, 4)]
        with pytest.raises(CompressionError, match="overlap"):
            comp.decode_batch_into(descs, bytearray(8))

    def test_dst_out_of_bounds_rejected(self):
        descs = [(b"abcd", 0, 4, int(Scheme.NONE), 6, 4)]
        with pytest.raises(CompressionError):
            comp.decode_batch_into(descs, bytearray(8))

    def test_src_out_of_bounds_rejected(self):
        descs = [(b"ab", 0, 4, int(Scheme.NONE), 0, 4)]
        with pytest.raises(CompressionError):
            comp.decode_batch_into(descs, bytearray(4), use_native=False)

    def test_readonly_destination_rejected(self):
        with pytest.raises(CompressionError, match="read-only"):
            comp.decode_batch_into(
                [(b"ab", 0, 2, int(Scheme.NONE), 0, 2)], b"\x00\x00")

    def test_corrupt_payload_raises_precise_error(self):
        # A malformed LZ4 frame must raise CompressionError through BOTH
        # paths (the native engine falls back to the pure loop for the
        # precise error).
        descs = [(b"\xff" * 16, 0, 16, int(Scheme.LZ4), 0, 100)]
        for use_native in (False, True):
            if use_native and not _native_available():
                continue
            with pytest.raises(CompressionError):
                comp.decode_batch_into(descs, bytearray(100),
                                       use_native=use_native)

    @pytest.mark.parametrize("use_native", [False, True])
    def test_columnar_identity(self, use_native):
        if use_native and not _native_available():
            pytest.skip("native lib unavailable")
        cases = self._cases()
        src = bytearray()
        rows = []
        pos = 0
        for data, scheme, payload in cases:
            rows.append((len(src), len(payload), int(scheme), pos,
                         len(data)))
            src += payload
            pos += len(data)
        src = bytes(src)
        group = (src,
                 np.asarray([r[0] for r in rows], dtype=np.uint64),
                 np.asarray([r[1] for r in rows], dtype=np.uint64),
                 np.asarray([r[2] for r in rows], dtype=np.uint8),
                 np.asarray([r[3] for r in rows], dtype=np.uint64),
                 np.asarray([r[4] for r in rows], dtype=np.uint64))
        out = bytearray(pos)
        wrote = comp.decode_columns_into([group], out, workers=2,
                                         use_native=use_native)
        assert wrote == pos
        cursor = 0
        for data, _scheme, _payload in cases:
            assert bytes(out[cursor:cursor + len(data)]) == data
            cursor += len(data)

    def test_columnar_overlap_rejected(self):
        group = (b"abcdefgh",
                 np.asarray([0, 0], dtype=np.uint64),
                 np.asarray([4, 4], dtype=np.uint64),
                 np.asarray([0, 0], dtype=np.uint8),
                 np.asarray([0, 2], dtype=np.uint64),
                 np.asarray([4, 4], dtype=np.uint64))
        with pytest.raises(CompressionError, match="overlap"):
            comp.decode_columns_into([group], bytearray(8))


class TestReaderColumnarCore:
    """XorbReader's columnar chunk table and range decode."""

    def _build(self, n_chunks=9, seed=5):
        rng = np.random.default_rng(seed)
        b = XorbBuilder()
        originals = []
        for i in range(n_chunks):
            data = _chunk(rng, 900 + 257 * i, compressible=i % 3 == 0)
            b.add_chunk(data)
            originals.append(data)
        return b, originals

    def test_extract_range_into_matches_extract_chunk_range(self):
        b, originals = self._build()
        reader = XorbReader(b.serialize())
        want = b"".join(originals)
        for workers in (1, 3):
            out = bytearray(len(want))
            n = reader.extract_range_into(0, len(reader), out,
                                          workers=workers)
            assert n == len(want)
            assert bytes(out) == want
        assert reader.extract_chunk_range(0, len(reader)) == want

    def test_subrange_decode(self):
        b, originals = self._build()
        reader = XorbReader(b.serialize())
        want = b"".join(originals[2:5])
        out = bytearray(len(want))
        reader.extract_range_into(2, 5, out)
        assert bytes(out) == want

    def test_entries_object_view_matches_columns(self):
        b, _ = self._build()
        reader = XorbReader(b.serialize())
        entries = reader.entries
        assert len(entries) == len(reader)
        for i, e in enumerate(entries):
            assert e.frame_offset == int(reader._frame_offs[i])
            assert e.compressed_len == int(reader._comp_lens[i])
            assert e.uncompressed_len == int(reader._unc_lens[i])
            assert int(e.scheme) == int(reader._schemes[i])

    def test_native_and_python_parse_agree(self):
        if not _native_available():
            pytest.skip("native lib unavailable")
        from zest_tpu.cas.xorb import _parse_frames_py
        from zest_tpu.native import lib

        b, _ = self._build(n_chunks=17)
        blob = b.serialize()
        native_cols = lib.parse_frames(memoryview(blob), len(blob),
                                       8 * 1024)
        py_cols = _parse_frames_py(memoryview(blob), len(blob))
        for a, c in zip(native_cols, py_cols):
            assert np.array_equal(a, c)

    def test_footer_blob_still_verifies_per_chunk(self):
        b, originals = self._build(n_chunks=4)
        full = bytearray(b.serialize_full())
        reader = XorbReader(bytes(full))
        assert reader.decode_columns(0, len(reader)) is None
        out = bytearray(sum(len(o) for o in originals))
        reader.extract_range_into(0, len(reader), out)
        assert bytes(out) == b"".join(originals)
        # Corrupt one payload byte: the footer-hash verify must fire.
        payload_off = int(reader._frame_offs[1]) + 8
        full[payload_off] ^= 0x01
        bad = XorbReader(bytes(full))
        with pytest.raises(XorbFormatError, match="hash mismatch"):
            bad.extract_range_into(0, len(bad), out)

    def test_hostile_stored_chunk_raises_format_error(self):
        data = os.urandom(64)
        frame = (bytes([0]) + len(data).to_bytes(3, "little")
                 + bytes([0]) + (len(data) + 9).to_bytes(3, "little")
                 + data)
        reader = XorbReader(frame)
        out = bytearray(len(data) + 9)
        with pytest.raises(XorbFormatError, match="claims"):
            reader.extract_range_into(0, 1, out)


class TestCachedReaderBatchLane:
    """The landing-side whole-read batch: entry-read amortization and
    self-heal through the new lane."""

    def _fixture(self, tmp_path):
        from zest_tpu.cas import reconstruction as recon

        rng = np.random.default_rng(11)
        b = XorbBuilder()
        data = b"".join(
            _chunk(rng, 2048, compressible=i % 2 == 0) for i in range(16))
        chunk_hashes = b.add_data(data)
        blob = b.serialize()
        xorb_hash = b.xorb_hash()
        hash_hex = hashing.hash_to_hex(xorb_hash)
        n = len(chunk_hashes)
        offs = b.frame_offsets()
        doc = {
            "terms": [
                {"hash": hash_hex, "unpacked_length": len(data),
                 "range": {"start": 0, "end": n}},
            ],
            "fetch_info": {
                hash_hex: [
                    {"range": {"start": 0, "end": n},
                     "url": "http://unused.invalid/x",
                     "url_range": {"start": offs[0], "end": offs[-1] - 1}},
                ]
            },
        }
        rec = recon.from_json(hashing.hash_to_hex(
            hashing.blake3_hash(data)), doc)
        return rec, hash_hex, blob, data

    class _CountingCache:
        def __init__(self, blob, hash_hex):
            self.blob = blob
            self.hash_hex = hash_hex
            self.reads = 0

        def get_with_range(self, hash_hex, range_start):
            from zest_tpu.storage import CacheResult

            assert hash_hex == self.hash_hex
            self.reads += 1
            return CacheResult(self.blob, 0)

    def test_whole_read_decodes_and_amortizes_entry_reads(self, tmp_path):
        from zest_tpu.models.direct import CachedFileReader

        rec, hash_hex, blob, data = self._fixture(tmp_path)
        cache = self._CountingCache(blob, hash_hex)
        reader = CachedFileReader(cache, rec, workers=2)
        out = bytearray(len(data))
        assert reader.read_into(0, len(data), out) == len(data)
        assert bytes(out) == data
        # One entry read total — not one per term/tensor read.
        assert cache.reads == 1
        out2 = bytearray(1000)
        reader.read_into(512, 1512, out2)
        assert bytes(out2) == data[512:1512]
        assert cache.reads == 1

    def test_corrupt_entry_falls_back_and_heals(self, tmp_path):
        from zest_tpu.models.direct import CachedFileReader

        rec, hash_hex, blob, data = self._fixture(tmp_path)
        # Corrupt a compressed chunk's payload so the batch decode
        # fails; the reader must fall back per term, refetch through the
        # bridge, and still produce exact bytes.
        bad = bytearray(blob)
        bad[int(XorbReader(blob)._frame_offs[0]) + 8] ^= 0xFF
        cache = self._CountingCache(bytes(bad), hash_hex)

        class _Bridge:
            fetched = 0

            def fetch_term(self, term, rec):
                _Bridge.fetched += 1
                return data

        reader = CachedFileReader(cache, rec, bridge=_Bridge(), workers=2)
        out = bytearray(len(data))
        reader.read_into(0, len(data), out)
        assert bytes(out) == data
        assert _Bridge.fetched == 1


class TestFusedPallasDecodeVerify:
    """BG4 regroup + BLAKE3 fused on device (interpret mode on CPU) vs
    the host reference — the ISSUE 3 device-front acceptance test."""

    def test_identity_vs_host_reference(self):
        from zest_tpu.ops.decode_pallas import FusedBg4Verifier

        rng = np.random.default_rng(3)
        chunks = [
            _chunk(rng, n, compressible=n % 2 == 0)
            for n in (1, 2, 3, 5, 17, 1000, 1023, 1024, 1025, 2048, 3000)
        ]
        payloads = [comp._bg4(c) for c in chunks]
        v = FusedBg4Verifier(hashing.CHUNK_KEY, interpret=True)
        got = v.hash_planar_batch(payloads, [len(c) for c in chunks])
        want = [hashing.chunk_hash(c) for c in chunks]
        assert got == want

    def test_pod_verify_uses_fused_lane_and_rejects_corruption(self):
        from zest_tpu.ops import DeviceHasher, FusedBg4Verifier
        from zest_tpu.transfer.pod import _device_verify_full_xorb

        rng = np.random.default_rng(0)
        b = XorbBuilder()
        for i in range(3):
            b.add_chunk(_chunk(rng, 3000 + 7 * i, compressible=True))
        b.add_chunk(_chunk(rng, 5000))
        blob = b.serialize()
        assert any(int(s) == int(Scheme.BG4_LZ4)
                   for s in XorbReader(blob)._schemes), \
            "fixture lost its BG4 chunks"
        hh = hashing.hash_to_hex(b.xorb_hash())
        hasher = DeviceHasher(hashing.CHUNK_KEY)
        fused = FusedBg4Verifier(hashing.CHUNK_KEY, interpret=True)
        assert _device_verify_full_xorb(blob, hh, hasher, fused=fused)
        bad = bytearray(blob)
        bad[40] ^= 0x01
        assert not _device_verify_full_xorb(bytes(bad), hh, hasher,
                                            fused=fused)

    def test_planar_length_mismatch_rejected(self):
        from zest_tpu.ops.decode_pallas import FusedBg4Verifier

        v = FusedBg4Verifier(interpret=True)
        with pytest.raises(ValueError, match="planar"):
            v.hash_planar_batch([b"abc"], [100])


class TestSatellites:
    def test_warm_summary_sums_only_allowlisted_counters(self):
        from zest_tpu.transfer.pull import _PipelinedWarm

        warm = _PipelinedWarm.__new__(_PipelinedWarm)
        warm.threads = {0: object(), 1: object()}
        warm.stats = [
            {"units": 3, "bytes": 100, "failed": 0, "retried": 1,
             "gbps": 1.5, "started_at": 1721212121.0},
            {"units": 2, "bytes": 50, "failed": 1, "gbps": 2.5},
        ]
        out = warm.summary()
        assert out["units"] == 5 and out["bytes"] == 150
        assert out["failed"] == 1 and out["retried"] == 1
        # Non-counter numerics are surfaced, never summed.
        assert "gbps" not in out and "started_at" not in out
        assert out["unsummed_keys"] == ["gbps", "started_at"]

    def test_evidence_incomplete_forces_partial_cache_keys(self, tmp_path):
        from zest_tpu.cas import reconstruction as recon
        from zest_tpu.config import Config
        from zest_tpu.transfer.bridge import XetBridge

        cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest")
        bridge = XetBridge(cfg)
        hash_hex = "ab" * 32
        rec = _evidence_rec(hash_hex)
        entries = rec.fetch_info[hash_hex]
        assert bridge.whole_xorb_provable(entries, 0)
        bridge._cache_fetched(rec, hash_hex, 0, b"blob-bytes")
        assert bridge.cache.get(hash_hex) == b"blob-bytes"

        bridge2 = XetBridge(Config(hf_home=tmp_path / "hf2",
                                   cache_dir=tmp_path / "zest2"))
        bridge2.mark_evidence_incomplete()
        assert not bridge2.whole_xorb_provable(entries, 0)
        bridge2._cache_fetched(rec, hash_hex, 0, b"blob-bytes")
        assert bridge2.cache.get(hash_hex) is None
        assert bridge2.cache.get_with_range(hash_hex, 0).data \
            == b"blob-bytes"


def _evidence_rec(hash_hex):
    from zest_tpu.cas import reconstruction as recon

    return recon.from_json(
        "cd" * 32,
        {"terms": [{"hash": hash_hex, "unpacked_length": 10,
                    "range": {"start": 0, "end": 4}}],
         "fetch_info": {hash_hex: [
             {"range": {"start": 0, "end": 4},
              "url": "http://unused.invalid/x",
              "url_range": {"start": 0, "end": 99}}]}},
    )


# ── Chaos: corruption attribution through the NEW decode path ──

_RNG_BYTES = b"".join(
    hashlib.blake2b(i.to_bytes(4, "little"), digest_size=64).digest()
    for i in range(16384)
)
_FILES = {
    "config.json": b'{"model_type": "chaos"}',
    "model.safetensors": _RNG_BYTES,
}


@pytest.mark.chaos
def test_chunk_corrupt_attribution_through_batch_decode(tmp_path):
    """A peer serving flipped bytes, pulled through the rewired decode
    path (columnar batch + mmap readers): corruption must still be
    attributed to the serving peer and healed from CDN, with the final
    bytes exact — proof the engine changed no trust boundary."""
    from fixtures import FixtureHub, FixtureRepo
    from zest_tpu import faults
    from zest_tpu.config import Config
    from zest_tpu.transfer.pull import pull_model
    from zest_tpu.transfer.server import BtServer
    from zest_tpu.transfer.swarm import SwarmDownloader

    repo = FixtureRepo("acme/decode-chaos", _FILES, chunks_per_xorb=1)
    faults.reset()
    with FixtureHub(repo) as hub:
        def cfg_for(name):
            return Config(hf_home=tmp_path / name / "hf",
                          cache_dir=tmp_path / name / "zest",
                          hf_token="hf_test", endpoint=hub.url,
                          listen_port=0)

        seed_cfg = cfg_for("seeder")
        pull_model(seed_cfg, "acme/decode-chaos", no_p2p=True,
                   log=lambda *a, **k: None)
        server = BtServer(seed_cfg)
        port = server.start()
        try:
            faults.install(f"chunk_corrupt:1.0@127.0.0.1:{port}",
                           seed=1337)
            cfg = cfg_for("leecher")
            swarm = SwarmDownloader(cfg)
            swarm.add_direct_peer("127.0.0.1", port)
            try:
                result = pull_model(cfg, "acme/decode-chaos", swarm=swarm,
                                    log=lambda *a, **k: None)
            finally:
                swarm.close()
        finally:
            server.shutdown()
            faults.reset()

    for name, data in _FILES.items():
        assert (result.snapshot_dir / name).read_bytes() == data
    res = result.stats["fetch"]["resilience"]
    assert result.stats["swarm"]["corrupt_from_peer"] >= 1
    assert res["corrupt_from_peer"] >= 1
    assert result.stats["fetch"]["bytes"]["cdn"] > 0
