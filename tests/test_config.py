"""Config tests (parity: reference src/config.zig:160-183)."""

from pathlib import Path

import pytest

from zest_tpu.config import Config, MeshConfig


def test_defaults_from_empty_env(tmp_path):
    cfg = Config.load(env={"HF_HOME": str(tmp_path / "hf"),
                           "ZEST_CACHE_DIR": str(tmp_path / "zest")})
    assert cfg.listen_port == 6881
    assert cfg.http_port == 9847
    assert cfg.max_peers == 50
    assert cfg.max_concurrent_downloads == 16
    assert cfg.hf_token is None


def test_env_overrides(tmp_path):
    cfg = Config.load(env={
        "HF_HOME": str(tmp_path),
        "ZEST_CACHE_DIR": str(tmp_path),
        "ZEST_HTTP_PORT": "1234",
        "ZEST_MAX_PEERS": "7",
        "HF_TOKEN": "hf_secret",
    })
    assert cfg.http_port == 1234
    assert cfg.max_peers == 7
    assert cfg.hf_token == "hf_secret"


def test_token_file_fallback(tmp_path):
    (tmp_path / "hf").mkdir()
    (tmp_path / "hf" / "token").write_text("hf_from_file\n")
    cfg = Config.load(env={"HF_HOME": str(tmp_path / "hf"),
                           "ZEST_CACHE_DIR": str(tmp_path)})
    assert cfg.hf_token == "hf_from_file"


def test_snapshot_dir_layout(tmp_config):
    d = tmp_config.model_snapshot_dir("openai-community/gpt2", "abc123")
    assert d == tmp_config.hf_home / "hub" / "models--openai-community--gpt2" / "snapshots" / "abc123"


def test_invalid_repo_id_rejected(tmp_config):
    with pytest.raises(ValueError):
        tmp_config.model_cache_dir("no-slash")
    with pytest.raises(ValueError):
        tmp_config.model_cache_dir("../../etc/passwd")


def test_xorb_and_chunk_cache_paths(tmp_config):
    h = "deadbeef" + "0" * 56
    assert tmp_config.xorb_cache_path(h) == tmp_config.cache_dir / "xorbs" / "de" / h
    assert tmp_config.chunk_cache_path(h) == tmp_config.cache_dir / "chunks" / "de" / h


def test_mesh_config_from_env():
    m = MeshConfig.from_env({
        "ZEST_TPU_MESH": "data=2,model=4",
        "ZEST_TPU_COORDINATOR": "10.0.0.1:8476",
        "ZEST_TPU_PROCESS_ID": "3",
        "ZEST_TPU_NUM_PROCESSES": "8",
    })
    assert m.mesh_axes == {"data": 2, "model": 4}
    assert m.coordinator == "10.0.0.1:8476"
    assert m.process_id == 3 and m.num_processes == 8
    assert m.is_distributed


def test_mesh_config_defaults():
    m = MeshConfig.from_env({})
    assert not m.is_distributed and m.mesh_axes == {}
