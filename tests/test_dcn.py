"""DCN chunk-RPC transport: codecs, loopback serving, pipelining, and the
two-process federated round.

Covers the reference's BEP XET semantics carried over the lean DCN
framing (reference: src/bep_xet.zig:66-124) and the cross-pod waterfall
tier (cache → owner pod over DCN → CDN). The two-process test is the
"real bytes between two processes" gate: pod 0 runs as an actual child
process serving its cache over a TCP socket; pod 1 (this process) pulls
pod-0-owned units through it.
"""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys
import time

import numpy as np
import pytest

from tests.fixtures import FixtureHub, FixtureRepo
from zest_tpu.cas import hashing
from zest_tpu.cas.hub import HubClient
from zest_tpu.cas.xorb import XorbBuilder, XorbReader
from zest_tpu.config import Config
from zest_tpu.storage import XorbCache, write_chunk
from zest_tpu.transfer import dcn
from zest_tpu.transfer.bridge import XetBridge
from zest_tpu.transfer.federated import federated_round, pod_owned_units


def _model_bytes(n_kib: int = 1024) -> bytes:
    rng = np.random.default_rng(1234)
    return rng.integers(0, 256, n_kib * 1024, dtype=np.uint8).tobytes()


REPO_ID = "acme/fed-model"
FILES = {
    "config.json": b'{"model_type": "gpt2"}',
    "model.safetensors": _model_bytes(),
}


# ── Codec (fixed-buffer roundtrip style, SURVEY.md §4) ──


def _roundtrip(msg):
    encoded = dcn.encode_message(msg)
    return dcn.decode_message(encoded[: dcn._HEADER.size],
                              encoded[dcn._HEADER.size :])


def test_codec_roundtrips():
    h = bytes(range(32))
    assert _roundtrip(dcn.DcnRequest(7, h, 3, 9)) == \
        dcn.DcnRequest(7, h, 3, 9)
    assert _roundtrip(dcn.DcnResponse(8, 2, b"framebytes")) == \
        dcn.DcnResponse(8, 2, b"framebytes")
    assert _roundtrip(dcn.DcnNotFound(9, h)) == dcn.DcnNotFound(9, h)
    assert _roundtrip(dcn.DcnError(10, "nope")) == dcn.DcnError(10, "nope")


def test_codec_rejects_malformed():
    good = dcn.encode_message(dcn.DcnRequest(1, bytes(32), 0, 4))
    header, body = good[: dcn._HEADER.size], good[dcn._HEADER.size :]
    with pytest.raises(dcn.DcnProtocolError):
        dcn.decode_message(header, body[:-1])  # length mismatch
    with pytest.raises(dcn.DcnProtocolError):
        dcn.decode_message(bytes([99]) + header[1:], body)  # unknown type
    bad_nf = dcn.encode_message(dcn.DcnNotFound(1, bytes(32)))
    with pytest.raises(dcn.DcnProtocolError):
        dcn.decode_message(
            bad_nf[: dcn._HEADER.size - 4] + (20).to_bytes(4, "little"),
            bad_nf[dcn._HEADER.size :][:20],  # truncated hash
        )
    with pytest.raises(dcn.DcnProtocolError):
        dcn.encode_message(
            dcn.DcnResponse(1, 0, bytes(dcn.MAX_MESSAGE_SIZE + 1))
        )


# ── Loopback server + channel ──


@pytest.fixture()
def served_cache(tmp_path):
    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                 dcn_port=0)
    cache = XorbCache(cfg)
    rng = np.random.default_rng(5)
    builder = XorbBuilder()
    chunks = [rng.integers(0, 256, 9000, dtype=np.uint8).tobytes()
              for _ in range(6)]
    for c in chunks:
        builder.add_chunk(c)
    xh_hex = hashing.hash_to_hex(builder.xorb_hash())
    cache.put(xh_hex, builder.serialize_full())
    server = dcn.DcnServer(cfg, cache)
    port = server.start()
    try:
        yield cfg, server, port, builder, chunks, xh_hex
    finally:
        server.shutdown()


def test_full_range_served(served_cache):
    _cfg, _server, port, builder, chunks, xh_hex = served_cache
    ch = dcn.DcnChannel("127.0.0.1", port)
    try:
        reply = ch.request(hashing.hex_to_hash(xh_hex), 0, len(chunks))
        assert isinstance(reply, dcn.DcnResponse)
        assert reply.chunk_offset == 0
        reader = XorbReader(reply.data)
        for i, c in enumerate(chunks):
            assert reader.extract_chunk(i) == c
    finally:
        ch.close()


def test_subrange_served_and_rebased(served_cache):
    _cfg, _server, port, builder, chunks, xh_hex = served_cache
    ch = dcn.DcnChannel("127.0.0.1", port)
    try:
        reply = ch.request(hashing.hex_to_hash(xh_hex), 2, 5)
        assert isinstance(reply, dcn.DcnResponse)
        assert reply.chunk_offset == 2
        reader = XorbReader(reply.data)
        assert len(reader) == 3
        assert reader.extract_chunk(0) == chunks[2]
        assert reader.extract_chunk(2) == chunks[4]
    finally:
        ch.close()


def test_chunk_cache_tier_served(served_cache):
    cfg, _server, port, *_ = served_cache
    payload = b"single chunk payload" * 100
    ch_hash = hashing.chunk_hash(payload)
    write_chunk(cfg, ch_hash, payload)
    ch = dcn.DcnChannel("127.0.0.1", port)
    try:
        reply = ch.request(ch_hash, 0, 1)
        assert isinstance(reply, dcn.DcnResponse)
        assert XorbReader(reply.data).extract_chunk(0) == payload
    finally:
        ch.close()


def test_not_found_and_error(served_cache):
    _cfg, server, port, _b, _c, xh_hex = served_cache
    ch = dcn.DcnChannel("127.0.0.1", port)
    try:
        miss = ch.request(b"\xab" * 32, 0, 1)
        assert miss == dcn.DcnNotFound(miss.request_id, b"\xab" * 32)
        bad = ch.request(hashing.hex_to_hash(xh_hex), 5, 5)  # empty range
        assert isinstance(bad, dcn.DcnError)
        assert "invalid range" in bad.message
    finally:
        ch.close()
    assert server.stats.not_found == 1


def test_pipelined_batch_order_and_stats(served_cache):
    _cfg, server, port, builder, chunks, xh_hex = served_cache
    xh = hashing.hex_to_hash(xh_hex)
    ch = dcn.DcnChannel("127.0.0.1", port)
    try:
        wants = [(xh, i, i + 1) for i in range(len(chunks))]
        wants.insert(3, (b"\xcd" * 32, 0, 1))  # a miss mid-pipeline
        replies = ch.request_many(wants)
        assert isinstance(replies[3], dcn.DcnNotFound)
        hits = replies[:3] + replies[4:]
        for i, reply in enumerate(hits):
            assert isinstance(reply, dcn.DcnResponse), i
            assert reply.chunk_offset == i
            assert XorbReader(reply.data).extract_chunk(0) == chunks[i]
    finally:
        ch.close()
    assert server.stats.chunks_served == len(chunks)


def test_large_blob_served_intact(served_cache):
    """A multi-megabyte response exercises the scatter-gather send path
    (partial sendmsg resumption) end-to-end."""
    cfg, _server, port, *_ = served_cache
    cache = XorbCache(cfg)
    rng = np.random.default_rng(13)
    builder = XorbBuilder()
    while builder.uncompressed_total < 8 * 1024 * 1024:
        builder.add_chunk(rng.integers(0, 256, 64 * 1024,
                                       dtype=np.uint8).tobytes())
    n = len(builder.chunk_hashes())
    xh_hex = hashing.hash_to_hex(builder.xorb_hash())
    cache.put(xh_hex, builder.serialize_full())
    ch = dcn.DcnChannel("127.0.0.1", port)
    try:
        reply = ch.request(hashing.hex_to_hash(xh_hex), 0, n)
        assert isinstance(reply, dcn.DcnResponse)
        reader = XorbReader(reply.data)
        assert len(reader) == n
        reader.extract_chunk(0, verify=True)
        reader.extract_chunk(n - 1, verify=True)
    finally:
        ch.close()


def test_pool_reconnects_dead_channels(served_cache):
    """A server-side close (idle timeout, restart) marks the channel dead;
    the pool must hand out a fresh connection, not the corpse."""
    import time

    _cfg, server, port, _b, _c, xh_hex = served_cache
    pool = dcn.DcnPool(timeout=5.0)
    try:
        ch = pool.channel("127.0.0.1", port)
        assert isinstance(
            ch.request(hashing.hex_to_hash(xh_hex), 0, 1), dcn.DcnResponse
        )
        # serverectomy: close the remote end of the live channel
        server.shutdown()
        # keep poking until the reader thread observes the EOF (a sendall
        # EPIPE raises before dead is set; don't stop on it)
        deadline = time.monotonic() + 5
        while not ch.dead and time.monotonic() < deadline:
            try:
                ch.request(hashing.hex_to_hash(xh_hex), 0, 1)
            except (ConnectionError, TimeoutError):
                pass
            time.sleep(0.05)
        assert ch.dead, "channel never noticed the server went away"
        # restart on the same port; the pool must replace the dead channel
        server2 = dcn.DcnServer(_cfg, server.cache)
        server2.cfg.dcn_port = port
        try:
            deadline = time.monotonic() + 5
            while True:  # old listener may need a beat to release the port
                try:
                    server2.start()
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.1)
            ch2 = pool.channel("127.0.0.1", port)
            assert ch2 is not ch
            reply = ch2.request(hashing.hex_to_hash(xh_hex), 0, 1)
            assert isinstance(reply, dcn.DcnResponse)
        finally:
            server2.shutdown()
    finally:
        pool.close()


def test_pool_reuses_channels(served_cache):
    _cfg, server, port, *_ = served_cache
    pool = dcn.DcnPool()
    try:
        a = pool.channel("127.0.0.1", port)
        b = pool.channel("127.0.0.1", port)
        assert a is b
        pool.drop("127.0.0.1", port)
        c = pool.channel("127.0.0.1", port)
        assert c is not a
    finally:
        pool.close()
    assert server.stats.connections == 2


def test_pool_request_many_retries_idle_closed_channel(served_cache):
    """A pooled channel the server idle-closed looks live until the
    first request fails; DcnPool.request_many must reconnect and retry
    the window once, transparently — the caller never sees the corpse."""
    _cfg, _server, port, _builder, chunks, xh_hex = served_cache
    pool = dcn.DcnPool(timeout=5.0)
    wants = [(hashing.hex_to_hash(xh_hex), 0, len(chunks))]
    try:
        stale = pool.channel("127.0.0.1", port)
        calls = []

        def dies_once(w):
            calls.append(w)
            raise ConnectionError("server idle-closed this channel")

        stale.request_many = dies_once  # instance shadow: fails once
        replies = pool.request_many("127.0.0.1", port, wants)
        assert calls, "stale channel was never tried"
        assert isinstance(replies[0], dcn.DcnResponse)
        assert XorbReader(replies[0].data).extract_chunk(0) == chunks[0]
        fresh = pool.channel("127.0.0.1", port)
        assert fresh is not stale, "dead channel must have been replaced"
    finally:
        pool.close()


def test_pool_request_many_recovers_injected_dcn_reset(served_cache):
    """The chaos hook end-to-end: an injected dcn_reset kills the pooled
    channel mid-send; the pool's reconnect-retry absorbs it."""
    from zest_tpu import faults

    def fires(seed, trial):
        inj = faults.FaultInjector(faults.parse_spec("dcn_reset:0.5"), seed)
        return inj._fires("dcn_reset", trial, 0.5)

    # A seed whose pattern opens fire-then-clear: the pooled channel's
    # send dies, the retried fresh channel's send survives.
    seed = next(s for s in range(200) if fires(s, 0) and not fires(s, 1))
    _cfg, _server, port, _builder, chunks, xh_hex = served_cache
    pool = dcn.DcnPool(timeout=5.0)
    faults.install("dcn_reset:0.5", seed=seed)
    try:
        stale = pool.channel("127.0.0.1", port)
        replies = pool.request_many(
            "127.0.0.1", port, [(hashing.hex_to_hash(xh_hex), 0, 1)])
        assert isinstance(replies[0], dcn.DcnResponse)
        assert stale.dead, "injected reset never hit the pooled channel"
    finally:
        faults.reset()
        pool.close()


def test_pool_request_many_fresh_failure_propagates(tmp_path):
    """A fresh connection failing is a real peer problem — no silent
    retry loop against a dead host."""
    pool = dcn.DcnPool(timeout=0.5)
    try:
        with pytest.raises((ConnectionError, OSError)):
            pool.request_many("127.0.0.1", 1, [(b"h" * 32, 0, 1)])
    finally:
        pool.close()


# ── Federated round, single process (ownership + fallback paths) ──


@pytest.fixture(scope="module")
def hub():
    repo = FixtureRepo(REPO_ID, FILES, chunks_per_xorb=2)
    with FixtureHub(repo) as h:
        yield h


def _bridge(hub, root):
    cfg = Config(hf_home=root / "hf", cache_dir=root / "zest",
                 hf_token="hf_test", endpoint=hub.url, dcn_port=0)
    bridge = XetBridge(cfg)
    bridge.authenticate(REPO_ID)
    return bridge


def _recs(hub, bridge):
    return [
        bridge.get_reconstruction(e.xet_hash)
        for e in HubClient(bridge.cfg).list_files(REPO_ID)
        if e.is_xet
    ]


def test_ownership_splits_units(hub, tmp_path):
    bridge = _bridge(hub, tmp_path)
    recs = _recs(hub, bridge)
    mine0, theirs0 = pod_owned_units(recs, 0, 2)
    mine1, theirs1 = pod_owned_units(recs, 1, 2)
    assert mine0 and mine1, "fixture must give both pods units"
    # complementary views: pod 0's own units are exactly what pod 1 sees
    # as pod-0-owned, and vice versa (every process computes the same
    # owner map with no coordination)
    key = lambda units: {(hh, fi.range.start) for hh, fi in units}
    assert key(mine0) == key(theirs1.get(0, []))
    assert key(mine1) == key(theirs0.get(1, []))
    assert key(mine0).isdisjoint(key(mine1))


def test_federated_round_in_process(hub, tmp_path):
    """Pod 0 fetches + serves; pod 1 (same process, separate caches)
    pulls pod-0 units over a real socket; both end fully cached."""
    b0 = _bridge(hub, tmp_path / "pod0")
    recs0 = _recs(hub, b0)
    s0 = federated_round(b0, recs0, 0, 2, pod_addrs={})
    assert s0["own_units"] > 0 and s0["dcn_units"] == 0

    server = dcn.DcnServer(b0.cfg, b0.cache)
    port = server.start()
    try:
        b1 = _bridge(hub, tmp_path / "pod1")
        recs1 = _recs(hub, b1)
        s1 = federated_round(
            b1, recs1, 1, 2, pod_addrs={0: ("127.0.0.1", port)}
        )
        assert s1["dcn_units"] == s0["own_units"]
        assert s1["dcn_bytes"] > 0
        assert s1["fallback_units"] == 0
        assert s1["failed_units"] == 0
        # every unit now locally cached: full reconstruction without CDN
        cdn_before = b1.stats.bytes_from_cdn
        for e in HubClient(b1.cfg).list_files(REPO_ID):
            if e.is_xet:
                out = tmp_path / "pod1" / "out.bin"
                b1.reconstruct_to_file(e.xet_hash, out)
                assert out.read_bytes() == FILES[e.path]
        assert b1.stats.bytes_from_cdn == cdn_before
    finally:
        server.shutdown()
    assert server.stats.bytes_served == s1["dcn_bytes"]


def test_federated_round_degrades_to_cdn(hub, tmp_path):
    """Unreachable owner pod: its units fall back to CDN — the waterfall
    safety net (SURVEY.md §5)."""
    b1 = _bridge(hub, tmp_path)
    recs = _recs(hub, b1)
    _mine, theirs = pod_owned_units(recs, 1, 2)
    foreign = sum(len(u) for u in theirs.values())
    s = federated_round(
        b1, recs, 1, 2, pod_addrs={0: ("127.0.0.1", 1)}  # nothing listens
    )
    assert s["dcn_units"] == 0
    assert s["fallback_units"] == foreign
    assert s["failed_units"] == 0


def test_federated_round_never_narrows_cached_entries(hub, tmp_path):
    """A unit answered by a cache hit must not be re-put: a full cached
    xorb answering a narrow [0,n) unit would otherwise be overwritten by
    its own slice, evicting the chunks past n."""
    from zest_tpu.cas.reconstruction import (
        ChunkRange, FetchInfo, Reconstruction, Term,
    )

    b = _bridge(hub, tmp_path)
    rng = np.random.default_rng(77)
    builder = XorbBuilder()
    chunks = [rng.integers(0, 256, 9000, dtype=np.uint8).tobytes()
              for _ in range(6)]
    for c in chunks:
        builder.add_chunk(c)
    xh = builder.xorb_hash()
    xh_hex = hashing.hash_to_hex(xh)
    b.cache.put(xh_hex, builder.serialize_full())
    full_before = b.cache.get(xh_hex)

    # a file needing only chunks [0,3) of that xorb, single fetch entry
    offs = builder.frame_offsets()
    rec = Reconstruction(
        file_hash=bytes(32),
        terms=[Term(xh, ChunkRange(0, 3),
                    sum(len(c) for c in chunks[:3]))],
        fetch_info={xh_hex: [FetchInfo("/nowhere", 0, offs[3],
                                       ChunkRange(0, 3))]},
    )
    for pod_index in (0, 1):  # whoever owns it, the entry must survive
        s = federated_round(b, [rec], pod_index, 2, pod_addrs={})
        assert s["failed_units"] == 0
    assert b.cache.get(xh_hex) == full_before
    assert len(XorbReader(b.cache.get(xh_hex))) == 6


def test_federated_pull_cli_flags(hub, tmp_path, capsys, monkeypatch):
    """The product surface: `pull --pods/--pod-index/--pod-addr` runs the
    cross-pod stage inside pull_model and reports it in the stats."""
    import re

    import zest_tpu.cli as cli

    def set_pod_env(i):
        monkeypatch.setenv("HF_HOME", str(tmp_path / f"pod{i}/hf"))
        monkeypatch.setenv("ZEST_CACHE_DIR", str(tmp_path / f"pod{i}/zest"))
        monkeypatch.setenv("HF_TOKEN", "hf_test")
        monkeypatch.setenv("HF_ENDPOINT", hub.url)

    # pod 0: CDN pull via the CLI, then serve its cache over DCN
    set_pod_env(0)
    rc = cli.main(["pull", REPO_ID, "--no-p2p", "--no-seed",
                   "--pods", "2", "--pod-index", "0"])
    assert rc == 0
    assert "Federated:  pod 0/2" in capsys.readouterr().out
    cfg0 = Config(hf_home=tmp_path / "pod0/hf",
                  cache_dir=tmp_path / "pod0/zest",
                  hf_token="hf_test", endpoint=hub.url, dcn_port=0)
    server = dcn.DcnServer(cfg0, XorbCache(cfg0))
    port = server.start()
    try:
        # half-specified federated config is a usage error, not silence
        assert cli.main(["pull", REPO_ID, "--no-p2p", "--no-seed",
                         "--pods", "2"]) == 2
        assert cli.main(["pull", REPO_ID, "--no-p2p", "--no-seed",
                         "--pods", "2", "--pod-index", "1",
                         "--pod-addr", "127.0.0.1:9"]) == 2

        # pod 1: pulls with the DCN endpoint; foreign units ride the RPC
        set_pod_env(1)
        rc = cli.main(["pull", REPO_ID, "--no-p2p", "--no-seed",
                       "--pods", "2", "--pod-index", "1",
                       "--pod-addr", f"0=127.0.0.1:{port}"])
        assert rc == 0
        out1 = capsys.readouterr().out
        assert "Federated:  pod 1/2" in out1
        assert "0 CDN-fallback" in out1
        m = re.search(r"(\d+) over DCN \((\d+) bytes\)", out1)
        assert m and int(m.group(1)) > 0 and int(m.group(2)) > 0
    finally:
        server.shutdown()


# ── The two-process gate ──


def test_federated_round_two_processes(hub, tmp_path):
    """Real bytes between two OS processes over the DCN chunk RPC —
    the reference's Docker-2-node analog for the cross-pod tier
    (test/local/p2p-docker-test.sh:204-218: fail unless >0 from peers)."""
    child_root = tmp_path / "child"
    child_root.mkdir()
    script = pathlib.Path(__file__).parent / "_federated_child.py"
    proc = subprocess.Popen(
        [sys.executable, str(script), hub.url, str(child_root), REPO_ID],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        port_file = child_root / "port"
        deadline = time.monotonic() + 30
        while not port_file.exists() and time.monotonic() < deadline:
            if proc.poll() is not None:
                pytest.fail(f"child died:\n{proc.stdout.read()}")
            time.sleep(0.1)
        assert port_file.exists(), "child never started serving"
        port = int(port_file.read_text())

        b1 = _bridge(hub, tmp_path / "parent")
        recs = _recs(hub, b1)
        s1 = federated_round(
            b1, recs, 1, 2, pod_addrs={0: ("127.0.0.1", port)}
        )
        child_stats = json.loads((child_root / "stats.json").read_text())
        assert s1["dcn_units"] == child_stats["own_units"] > 0
        assert s1["dcn_bytes"] > 0
        assert s1["failed_units"] == 0
        # integrity: reconstruct every file from the now-warm cache
        for e in HubClient(b1.cfg).list_files(REPO_ID):
            if e.is_xet:
                out = tmp_path / "parent" / "out.bin"
                b1.reconstruct_to_file(e.xet_hash, out)
                assert out.read_bytes() == FILES[e.path]
    finally:
        (child_root / "done").write_text("1")
        try:
            rc = proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            rc = -1
    assert rc == 0, f"child exit {rc}:\n{proc.stdout.read()}"


def test_bench_dcn_fetch_runs():
    """The synthetic-suite DCN stage (SURVEY §2.1 row 17 "DCN fetch")
    moves every payload byte over a real loopback socket and reports a
    positive rate."""
    from zest_tpu.bench_suite import bench_dcn_fetch

    r = bench_dcn_fetch(n_chunks=8, window=4, repeats=2)
    assert r.name == "dcn_fetch_pipelined"
    assert r.bytes_per_iter == 8 * 64 * 1024
    assert r.mb_per_s > 0
