"""Cooperative pod-scale pull (transfer.coop; ROADMAP item 1).

Covers the ISSUE-6 acceptance surface:

- ownership-plan determinism: byte-for-byte identical plans from the
  same reconstruction set regardless of input order, skew bounded by
  1.15x mean bytes/host, and quarantine re-shard covering 100% of the
  units exactly once;
- the round end-to-end over real loopback DCN sockets: every host ends
  fully cached with compressed frames on the wire and the expected
  peer-served ratio;
- degradation: a dead exchange host and injected ``dcn_reset`` /
  ``peer_timeout`` faults inside the exchange phase complete the pull
  via per-host CDN fallback (counted, never a hang, never a corrupt
  landing), and a corrupt owner blob is rejected at the trust boundary
  then healed from CDN;
- the ByteBudget bound on exchange staging;
- ``pull_model`` integration (stats["coop"], peer_served_ratio).
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from fixtures import FixtureHub, FixtureRepo

from zest_tpu import faults
from zest_tpu.cas.hub import HubClient
from zest_tpu.config import Config
from zest_tpu.transfer.bridge import XetBridge
from zest_tpu.transfer.coop import (
    CoopPlan,
    CoopUnavailable,
    coop_round,
)
from zest_tpu.transfer.dcn import DcnServer

REPO_ID = "acme/coop-model"

# Compressible payload (low-entropy bytes): the compressed-on-the-wire
# evidence (wire < unpacked) must be visible, as on real checkpoints.
_PAYLOAD = np.random.default_rng(5).integers(
    0, 4, 1_500_000, dtype=np.uint8).tobytes()
FILES = {
    "config.json": b'{"model_type": "coop"}',
    "model.safetensors": _PAYLOAD,
}


@pytest.fixture(scope="module")
def hub():
    repo = FixtureRepo(REPO_ID, FILES, chunks_per_xorb=2)
    with FixtureHub(repo) as h:
        yield h


@pytest.fixture(autouse=True)
def _no_faults():
    faults.reset()
    yield
    faults.reset()


def _bridge(hub, root):
    cfg = Config(hf_home=root / "hf", cache_dir=root / "zest",
                 hf_token="hf_test", endpoint=hub.url, dcn_port=0)
    b = XetBridge(cfg)
    b.authenticate(REPO_ID)
    return b


def _recs(bridge):
    return [bridge.get_reconstruction(e.xet_hash)
            for e in HubClient(bridge.cfg).list_files(REPO_ID)
            if e.is_xet]


def _run_hosts(hub, tmp_path, n, round_kwargs=None, skip=(),
               collective=True):
    """n concurrent in-process hosts, each with its own cache + DCN
    server (the MULTICHIP-dryrun multi-host shape); returns (bridges,
    results). Hosts in ``skip`` get an addr map entry pointing at a
    dead port but run no round (the dead-host scenario).
    ``collective=False`` pins the PR-6 point-to-point exchange (the
    ZEST_COOP_COLLECTIVE=0 ladder)."""
    bridges, servers, addrs = [], [], {}
    for i in range(n):
        b = _bridge(hub, tmp_path / f"h{i}")
        b.cfg.coop_collective = collective
        bridges.append(b)
        if i in skip:
            addrs[i] = ("127.0.0.1", 1)  # nothing listens
            servers.append(None)
        else:
            s = DcnServer(b.cfg, b.cache)
            addrs[i] = ("127.0.0.1", s.start())
            servers.append(s)
    results: list = [None] * n
    errors: list = []

    def run(i):
        try:
            results[i] = coop_round(bridges[i], _recs(bridges[i]), i, n,
                                    addrs, server=servers[i],
                                    **(round_kwargs or {}))
        except Exception as exc:  # noqa: BLE001 - surfaced below
            errors.append((i, exc))

    threads = [threading.Thread(target=run, args=(i,), daemon=True)
               for i in range(n) if i not in skip]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for s in servers:
        if s is not None:
            s.shutdown()
    assert not errors, errors
    return bridges, results


def _assert_fully_cached(bridge, root):
    """Every xet file reconstructs byte-exactly with zero CDN traffic."""
    before = bridge.stats.bytes_from_cdn
    for e in HubClient(bridge.cfg).list_files(REPO_ID):
        if e.is_xet:
            out = root / "check.bin"
            bridge.reconstruct_to_file(e.xet_hash, out)
            assert out.read_bytes() == FILES[e.path]
    assert bridge.stats.bytes_from_cdn == before, \
        "reconstruction hit CDN: cache incomplete after the round"


# ── Ownership plan ──


def test_plan_identical_regardless_of_input_order(hub, tmp_path):
    b = _bridge(hub, tmp_path)
    recs = _recs(b)
    plan = CoopPlan.build(recs, 4)
    again = CoopPlan.build(recs, 4)
    reversed_in = CoopPlan.build(list(reversed(recs)), 4)
    assert plan.fingerprint() == again.fingerprint()
    assert plan.fingerprint() == reversed_in.fingerprint()
    assert plan.owners == reversed_in.owners
    # every unit owned exactly once, all owners alive
    assert set(plan.owners) == {k for k, _fi in plan.units}
    assert set(plan.owners.values()) <= set(plan.alive)


def test_plan_skew_bound(hub, tmp_path):
    """Byte balance: max bytes/host <= 1.15x mean bytes/host (the LPT
    bound the ISSUE pins) for a checkpoint-shaped unit population."""
    b = _bridge(hub, tmp_path)
    recs = _recs(b)
    for n in (2, 3, 4, 8):
        plan = CoopPlan.build(recs, n)
        # Only meaningful while units comfortably outnumber hosts (the
        # fixture has ~12 units; at 8 hosts the discrete bound is
        # mean + largest_unit instead).
        if len(plan.units) >= 2 * n:
            assert plan.skew() <= 1.15, (n, plan.summary())
        per = plan.bytes_per_host()
        mean = plan.total_bytes / len(plan.alive)
        largest = max(fi.url_range_end - fi.url_range_start
                      for _k, fi in plan.units)
        assert max(per.values()) <= mean + largest + 1  # LPT guarantee


def test_plan_reshard_covers_every_unit_exactly_once(hub, tmp_path):
    b = _bridge(hub, tmp_path)
    recs = _recs(b)
    full = CoopPlan.build(recs, 4)
    reshard = CoopPlan.build(recs, 4, quarantined={2})
    assert 2 not in set(reshard.owners.values())
    assert reshard.for_host(2) == []
    # 100% of units assigned exactly once across the alive hosts
    seen: list = []
    for h in range(4):
        seen.extend((hh, fi.range.start) for hh, fi in reshard.for_host(h))
    assert sorted(seen) == sorted(k for k, _fi in full.units)
    assert len(seen) == len(set(seen)) == len(full.units)
    # and the reshard is itself deterministic
    assert reshard.fingerprint() == CoopPlan.build(
        recs, 4, quarantined={2}).fingerprint()


def test_plan_all_quarantined_raises(hub, tmp_path):
    b = _bridge(hub, tmp_path)
    with pytest.raises(CoopUnavailable):
        CoopPlan.build(_recs(b), 2, quarantined={0, 1})


# ── The round, end to end ──


def test_coop_round_end_to_end(hub, tmp_path):
    n = 4
    bridges, results = _run_hosts(hub, tmp_path, n)
    for i, (b, r) in enumerate(zip(bridges, results)):
        assert r["fallbacks"] == 0, r
        assert r["exchange"]["units"] > 0
        # compressed frames crossed the wire, not expanded bytes
        assert 0 < r["exchange"]["wire_bytes"] \
            < r["exchange"]["unpacked_bytes"]
        # N=4: ~3/4 of served bytes came from peers
        assert r["peer_served_ratio"] >= 0.6, r
        _assert_fully_cached(b, tmp_path / f"h{i}")
    # the fetch shares were disjoint: total CDN bytes across hosts ~1x
    # the deduped unit set (each unit left the CDN once)
    total_cdn = sum(b.stats.bytes_from_cdn for b in bridges)
    one_copy = results[0]["plan"]["total_bytes"]
    assert total_cdn <= one_copy * 1.05


def test_coop_round_no_peers_raises(hub, tmp_path):
    b = _bridge(hub, tmp_path)
    with pytest.raises(CoopUnavailable):
        coop_round(b, _recs(b), 0, 4, host_addrs={})


def test_coop_round_single_host_skips(hub, tmp_path):
    b = _bridge(hub, tmp_path)
    assert coop_round(b, _recs(b), 0, 1)["skipped"] is True


def test_coop_dead_host_degrades_to_cdn(hub, tmp_path):
    """Point-to-point ladder (ZEST_COOP_COLLECTIVE=0 semantics): host 2
    is in the addr map but dead — its units degrade to the per-host CDN
    fallback on every other host; the round completes and every live
    host still ends fully cached. (The collective-mode dead-host story
    — a live host can receive the dead host's share FORWARDED by a peer
    that already healed it — is covered in test_collective.py.)"""
    n = 3
    bridges, results = _run_hosts(hub, tmp_path, n, skip={2},
                                  collective=False)
    for i in (0, 1):
        r = results[i]
        assert r["fallbacks"] > 0, r
        assert 2 in r["exchange"].get("dead_hosts", []), r
        _assert_fully_cached(bridges[i], tmp_path / f"h{i}")


def test_coop_quarantined_host_resharded_upfront(hub, tmp_path):
    """An up-front quarantined host is excluded from the plan: nobody
    dials it (zero fallbacks, zero dead hosts — unlike the dead-host
    case, which pays timeouts), and its share re-shards."""
    n = 3
    bridges, results = _run_hosts(hub, tmp_path, n, skip={2},
                                  round_kwargs={"quarantined": {2}})
    for i in (0, 1):
        r = results[i]
        assert r["fallbacks"] == 0, r
        assert r["exchange"].get("dead_hosts") is None, r
        assert r["plan"]["alive"] == 2
        _assert_fully_cached(bridges[i], tmp_path / f"h{i}")


def test_coop_budget_bounds_exchange_staging(hub, tmp_path):
    """The exchange honors the ByteBudget: in-flight staged wire bytes
    never exceed the budget (when the budget admits the largest unit —
    the oversized-alone admission otherwise applies)."""
    budget = 256 * 1024
    bridges, results = _run_hosts(
        hub, tmp_path, 2, round_kwargs={"budget_bytes": budget})
    largest = max(fi.url_range_end - fi.url_range_start
                  for _k, fi in CoopPlan.build(_recs(bridges[0]), 2).units)
    cap = max(budget, largest)
    for r in results:
        assert r["exchange"]["budget_bytes"] == budget
        assert 0 < r["exchange"]["inflight_peak_bytes"] <= cap, r


def test_coop_corrupt_owner_blob_rejected_and_healed(hub, tmp_path):
    """A byte-flipped blob in the owner's cache fails the receiver's
    whole-xorb verification at the trust boundary (the fused-kernel
    path on TPU, native host hashing here), is never cached, and the
    unit heals from CDN — the corrupt landing the ISSUE forbids."""
    b0 = _bridge(hub, tmp_path / "owner")
    recs0 = _recs(b0)
    plan = CoopPlan.build(recs0, 2)
    owned = plan.for_host(0)
    assert owned
    # Owner fetches honestly, then its cache entry is poisoned.
    from zest_tpu.transfer.federated import warm_units_parallel

    warm_units_parallel(b0, recs0, units=owned)
    hh, fi = owned[0]
    entry = b0.cache.get_with_range(hh, fi.range.start)
    bad = bytearray(entry.data)
    bad[len(bad) // 2] ^= 0xFF
    b0.cache.put(hh, bytes(bad))

    server = DcnServer(b0.cfg, b0.cache)
    port = server.start()
    try:
        b1 = _bridge(hub, tmp_path / "puller")
        r = coop_round(b1, _recs(b1), 1, 2,
                       {0: ("127.0.0.1", port)})
        assert r["exchange"]["verify_rejected"] >= 1, r
        assert r["fallbacks"] >= 1, r
        _assert_fully_cached(b1, tmp_path / "puller")
    finally:
        server.shutdown()


# ── Chaos inside the exchange phase ──


@pytest.mark.chaos
@pytest.mark.parametrize("fault", ["dcn_reset:1.0", "peer_timeout:1.0"])
def test_coop_chaos_exchange_faults_degrade_to_cdn(hub, tmp_path, fault):
    """``dcn_reset`` / ``peer_timeout`` fired inside the exchange must
    degrade to the per-host CDN fallback — the pull completes, the
    fallbacks are counted, the fault counter proves the fault FIRED,
    and the landing is byte-exact. Never a hang (join bounded), never
    corruption."""
    faults.install(fault, seed=1337)
    name = fault.split(":", 1)[0]
    bridges, results = _run_hosts(hub, tmp_path, 2)
    assert faults.counters().get(name, 0) > 0, "fault never fired"
    for i, (b, r) in enumerate(zip(bridges, results)):
        assert r["fallbacks"] > 0, r
        assert r["exchange"]["units"] == 0, r
        _assert_fully_cached(b, tmp_path / f"h{i}")


# ── pull_model integration ──


def test_pull_model_coop_integration(hub, tmp_path):
    """The product surface: ``pull_model(coop=True, ...)`` runs the
    round (stats["coop"] + headline peer_served_ratio) and the files on
    disk are byte-exact; the peer host serves through a plain DCN
    server over its own warmed cache."""
    from zest_tpu.transfer.federated import warm_units_parallel
    from zest_tpu.transfer.pull import pull_model

    peer = _bridge(hub, tmp_path / "peer")
    recs = _recs(peer)
    plan = CoopPlan.build(recs, 2)
    warm_units_parallel(peer, recs, units=plan.for_host(1))
    server = DcnServer(peer.cfg, peer.cache)
    port = server.start()
    try:
        cfg = Config(hf_home=tmp_path / "p0/hf",
                     cache_dir=tmp_path / "p0/zest",
                     hf_token="hf_test", endpoint=hub.url, dcn_port=0)
        res = pull_model(cfg, REPO_ID, no_p2p=True, coop=True,
                         coop_hosts=2, coop_index=0,
                         coop_addrs={1: ("127.0.0.1", port)},
                         log=lambda *a, **k: None)
        coop = res.stats.get("coop")
        assert coop and not coop.get("skipped"), res.stats
        assert res.stats["peer_served_ratio"] == \
            coop["peer_served_ratio"] >= 0.4
        assert coop["fallbacks"] == 0, coop
        for name, data in FILES.items():
            assert (res.snapshot_dir / name).read_bytes() == data
    finally:
        server.shutdown()


def test_pull_model_coop_auto_off_without_topology(hub, tmp_path):
    """No coop args, no ZEST_COOP*, single process: the pull must not
    attempt (or report) a cooperative round."""
    from zest_tpu.transfer.pull import pull_model

    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                 hf_token="hf_test", endpoint=hub.url)
    res = pull_model(cfg, REPO_ID, no_p2p=True,
                     log=lambda *a, **k: None)
    assert "coop" not in res.stats
    assert "peer_served_ratio" not in res.stats


def test_config_coop_env_parsing():
    cfg = Config.load({
        "HF_HOME": "/tmp/x", "ZEST_CACHE_DIR": "/tmp/y",
        "ZEST_COOP": "1", "ZEST_COOP_HOSTS": "4",
        "ZEST_COOP_INDEX": "2",
        "ZEST_COOP_ADDRS": "0=h0:6991, 1=h1:6991,3=h3:7001",
        "ZEST_COOP_INFLIGHT": "123456",
    })
    assert cfg.coop_pull is True
    assert cfg.coop_hosts == 4 and cfg.coop_index == 2
    assert cfg.coop_addrs == {0: ("h0", 6991), 1: ("h1", 6991),
                              3: ("h3", 7001)}
    assert cfg.coop_inflight_bytes == 123456
    with pytest.raises(ValueError):
        Config.load({"HF_HOME": "/tmp/x", "ZEST_CACHE_DIR": "/tmp/y",
                     "ZEST_COOP_ADDRS": "nonsense"})
    off = Config.load({"HF_HOME": "/tmp/x", "ZEST_CACHE_DIR": "/tmp/y",
                       "ZEST_COOP": "0"})
    assert off.coop_pull is False
    unset = Config.load({"HF_HOME": "/tmp/x", "ZEST_CACHE_DIR": "/tmp/y"})
    assert unset.coop_pull is None
