"""Opt-in real-network e2e: the production-CAS integrity gate.

Everything else in tests/ runs against the loopback fixture hub; this
file is the one place the full client stack — hub listing, xet-read-token
exchange, CAS reconstruction, CDN xorb fetch, chunk extraction, file
reassembly, transformers load — is exercised against huggingface.co
itself (reference analog: test/local/verify-model.sh:103-147).

Gated on ZEST_E2E_REAL=1 because it needs network egress and downloads a
real model; CI environments without egress skip it cleanly. The shell
twin (scripts/verify-model.sh) additionally records a JSON report.
"""

from __future__ import annotations

import os

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("ZEST_E2E_REAL") != "1",
    reason="real-network e2e is opt-in: set ZEST_E2E_REAL=1 (needs egress)",
)

REPO = os.environ.get("ZEST_E2E_REPO", "openai-community/gpt2")


@pytest.fixture(scope="module")
def cfg(tmp_path_factory):
    from zest_tpu.config import Config

    root = tmp_path_factory.mktemp("real_e2e")
    return Config(
        hf_home=root / "hf",
        cache_dir=root / "zest",
        hf_token=os.environ.get("HF_TOKEN"),
    )


def test_real_pull_hashes_and_loads(cfg, monkeypatch):
    from zest_tpu.cas.chunking import chunk_stream
    from zest_tpu.cas.hashing import chunk_hash, file_hash, hash_to_hex
    from zest_tpu.cas.hub import HubClient
    from zest_tpu.transfer.pull import pull_model

    result = pull_model(cfg, REPO, no_p2p=True)
    snapshot = result.snapshot_dir

    # Every xet-backed file's bytes must hash back to the hub-advertised
    # address — the strongest possible integrity check: it re-derives the
    # production CAS address from the reassembled bytes.
    entries = HubClient(cfg).list_files(REPO)
    n_xet = 0
    for entry in entries:
        if not entry.is_xet:
            continue
        n_xet += 1
        data = (snapshot / entry.path).read_bytes()
        leaves = [(chunk_hash(c), len(c)) for _m, c in chunk_stream(data)]
        assert hash_to_hex(file_hash(leaves)) == entry.xet_hash, entry.path
    assert n_xet > 0, "expected at least one xet-backed file"

    # The reference's bar: transformers loads it offline, >100M params,
    # greedy generation echoes the prompt. monkeypatch restores whatever
    # offline-mode values the environment already had.
    monkeypatch.setenv("HF_HUB_OFFLINE", "1")
    monkeypatch.setenv("TRANSFORMERS_OFFLINE", "1")
    from transformers import AutoModelForCausalLM, AutoTokenizer

    model = AutoModelForCausalLM.from_pretrained(
        REPO, cache_dir=cfg.hf_home / "hub"
    )
    tok = AutoTokenizer.from_pretrained(REPO, cache_dir=cfg.hf_home / "hub")
    assert sum(p.numel() for p in model.parameters()) > 100_000_000
    ids = tok("The quick brown fox", return_tensors="pt").input_ids
    out = model.generate(ids, max_new_tokens=8, do_sample=False)
    assert tok.decode(out[0], skip_special_tokens=True).startswith(
        "The quick brown fox"
    )
