"""Tests for the TPU distribution plane (zest_tpu.parallel).

Runs on the virtual 8-device CPU mesh from conftest — the analog of the
reference's Docker 2-node harness (test/local/p2p-docker-test.sh): multi-
host semantics exercised without hardware.
"""

import jax
import numpy as np
import pytest

from tests.fixtures import FixtureRepo
from zest_tpu.cas import hashing
from zest_tpu.config import MeshConfig
from zest_tpu.parallel import (
    DistributionPlan,
    HbmStagingCache,
    InMemoryRegistry,
    PodDistributor,
    PoolLayout,
    TieredCache,
    mesh_from_config,
    model_mesh,
    owner_host,
    pod_mesh,
)
from zest_tpu.storage import XorbCache


def _repo(n_files=3, chunks_per_xorb=2, size=40_000):
    rng = np.random.default_rng(7)
    files = {
        f"model-{i}.safetensors": rng.bytes(size + i * 1111)
        for i in range(n_files)
    }
    return FixtureRepo("acme/tiny", files, chunks_per_xorb=chunks_per_xorb)


# ── mesh ──


def test_pod_mesh_spans_all_devices():
    mesh = pod_mesh()
    assert mesh.shape["pod"] == len(jax.devices()) == 8


def test_model_mesh_axes_and_mismatch():
    mesh = model_mesh({"data": 2, "model": 4})
    assert mesh.shape == {"data": 2, "model": 4}
    with pytest.raises(ValueError):
        model_mesh({"data": 3})


def test_mesh_from_config_roundtrip():
    cfg = MeshConfig.from_env({"ZEST_TPU_MESH": "data=2,model=4"})
    assert mesh_from_config(cfg).shape == {"data": 2, "model": 4}
    assert mesh_from_config(MeshConfig()).shape == {"pod": 8}


# ── ownership plan ──


def test_owner_host_deterministic_and_in_range():
    h = hashing.blake3_hash(b"some xorb")
    owners = [owner_host(h, 0, 8) for _ in range(3)]
    assert len(set(owners)) == 1
    assert 0 <= owners[0] < 8
    assert owner_host(h, 0, 1) == 0
    # Different ranges of the same xorb may land on different owners.
    assert isinstance(owner_host(h, 1024, 8), int)


def test_owner_host_balance():
    """HRW should spread many xorbs roughly evenly (no host starved)."""
    counts = [0] * 8
    for i in range(400):
        counts[owner_host(hashing.blake3_hash(f"x{i}".encode()), 0, 8)] += 1
    assert min(counts) > 20  # E[x]=50; extreme skew means a broken hash


def test_owner_stability_under_host_removal():
    """Dropping one host only remaps that host's units (HRW property)."""
    hashes = [hashing.blake3_hash(f"h{i}".encode()) for i in range(200)]
    before = {h: owner_host(h, 0, 8) for h in hashes}
    after = {h: owner_host(h, 0, 7) for h in hashes}
    moved = [h for h in hashes if before[h] != after[h]]
    # Only units owned by the removed host (index 7) may move.
    assert all(before[h] == 7 for h in moved)


def test_distribution_plan_dedup_and_partition():
    repo = _repo()
    recs = list(repo.reconstructions.values())
    # Duplicate a reconstruction: shared xorbs must be planned once.
    plan = DistributionPlan.build(recs + [recs[0]], num_hosts=8)
    keys = [(a.hash_hex, a.fetch_info.range.start) for a in plan.assignments]
    assert len(keys) == len(set(keys))
    assert sum(len(plan.for_host(h)) for h in range(8)) == len(plan.assignments)
    s = plan.summary()
    assert s["total_bytes"] == plan.total_bytes > 0
    assert 0 < s["balance"] <= 1.0


def test_plan_identical_regardless_of_input_order():
    repo = _repo()
    recs = list(repo.reconstructions.values())
    a = DistributionPlan.build(recs, 8)
    b = DistributionPlan.build(list(reversed(recs)), 8)
    assert [(x.hash_hex, x.owner) for x in a.assignments] == [
        (x.hash_hex, x.owner) for x in b.assignments
    ]


# ── HBM staging tier ──


def test_hbm_cache_roundtrip_and_offset():
    hbm = HbmStagingCache(budget_bytes=1 << 20)
    hbm.put("a" * 64, b"full blob")
    hbm.put_partial("b" * 64, 5, b"partial blob")
    got = hbm.get_with_range("a" * 64, 0)
    assert got.data == b"full blob" and got.chunk_offset == 0
    got = hbm.get_with_range("b" * 64, 5)
    assert got.data == b"partial blob" and got.chunk_offset == 5
    assert hbm.get_with_range("b" * 64, 6) is None
    assert hbm.summary()["hits"] == 2


def test_hbm_cache_lru_eviction():
    hbm = HbmStagingCache(budget_bytes=1000)
    hbm.put("a" * 64, b"x" * 400)
    hbm.put("b" * 64, b"y" * 400)
    assert hbm.get_with_range("a" * 64, 0) is not None  # refresh a
    hbm.put("c" * 64, b"z" * 400)  # evicts b (LRU)
    assert hbm.has("a" * 64) and hbm.has("c" * 64)
    assert not hbm.has("b" * 64)
    assert hbm.summary()["evictions"] == 1
    assert hbm.used_bytes <= 1000


def test_hbm_cache_oversized_blob_skipped():
    hbm = HbmStagingCache(budget_bytes=10)
    hbm.put("a" * 64, b"x" * 100)
    assert not hbm.has("a" * 64)


def test_hbm_cache_counters_count_every_get_once():
    """hits + misses == number of gets, across BOTH get paths — the
    counters are bumped inside the same lock acquisition as the probe,
    so concurrent-pipeline stats can't drift."""
    hbm = HbmStagingCache(budget_bytes=1 << 20)
    hbm.put("a" * 64, b"full")
    hbm.put_partial("b" * 64, 7, b"part")
    assert hbm.get_with_range("a" * 64, 0) is not None   # hit
    assert hbm.get_with_range("b" * 64, 7) is not None   # partial hit
    assert hbm.get_with_range("b" * 64, 9) is None       # one miss, not two
    assert hbm.get_device("a" * 64) is not None          # hit (counted too)
    assert hbm.get_device("c" * 64) is None              # miss
    s = hbm.summary()
    assert (s["hits"], s["misses"]) == (3, 2)


def test_tiered_cache_promotion(tmp_config):
    disk = XorbCache(tmp_config)
    hbm = HbmStagingCache(budget_bytes=1 << 20)
    tiered = TieredCache(disk, hbm)
    disk.put("d" * 64, b"cold data")
    got = tiered.get_with_range("d" * 64, 0)
    assert got.data == b"cold data"
    assert hbm.has("d" * 64)  # promoted
    tiered.put("e" * 64, b"warm")
    assert disk.has("e" * 64) and hbm.has("e" * 64)


# ── collectives: the ICI all-gather round ──


def _fetchers_for(repo, plan):
    def fetch(a):
        return repo.xorbs[a.hash_hex].blob

    shards = {
        h: {
            (a.hash_hex, a.fetch_info.range.start): repo.xorbs[a.hash_hex].blob
            for a in plan.for_host(h)
        }
        for h in range(plan.num_hosts)
    }
    return fetch, shards


def test_pool_layout_rows_disjoint_and_aligned():
    repo = _repo()
    plan = DistributionPlan.build(list(repo.reconstructions.values()), 8)
    layout = PoolLayout.from_plan(plan)
    rows = [r for r, _ in layout.index.values()]
    assert len(rows) == len(set(rows))
    assert layout.row_len % 128 == 0
    assert layout.total_rows == 8 * layout.rows_per_host


def test_distribute_all_blobs_reach_every_slot(tmp_config):
    repo = _repo(n_files=4, chunks_per_xorb=2)
    plan = DistributionPlan.build(list(repo.reconstructions.values()), 8)
    fetch, shards = _fetchers_for(repo, plan)
    pool = PodDistributor(pod_mesh()).distribute(
        plan, fetch, host=0, local_shards=shards
    )
    for a in plan.assignments:
        got = pool.blob(a.hash_hex, a.fetch_info.range.start)
        assert got is not None
        data, offset = got
        assert data == repo.xorbs[a.hash_hex].blob
        assert offset == a.fetch_info.range.start
    # Gathered pool is replicated: one shard per device, all identical.
    assert pool.pool.sharding.is_fully_replicated


def test_distribute_missing_unit_leaves_zero_row(tmp_config):
    repo = _repo(n_files=2)
    plan = DistributionPlan.build(list(repo.reconstructions.values()), 8)
    fetch, shards = _fetchers_for(repo, plan)
    victim = plan.assignments[0]
    vkey = (victim.hash_hex, victim.fetch_info.range.start)
    shards[victim.owner].pop(vkey)

    def failing_fetch(a):
        if (a.hash_hex, a.fetch_info.range.start) == vkey:
            raise IOError("CDN down for this unit")
        return repo.xorbs[a.hash_hex].blob

    pool = PodDistributor(pod_mesh()).distribute(
        plan, failing_fetch, host=victim.owner, local_shards=shards
    )
    assert pool.blob(*vkey) is None  # falls through to CDN downstream
    others = [
        a for a in plan.assignments
        if (a.hash_hex, a.fetch_info.range.start) != vkey
    ]
    assert all(
        pool.blob(a.hash_hex, a.fetch_info.range.start) is not None
        for a in others
    )


def test_distribute_fill_cache_feeds_waterfall(tmp_config):
    """After one gather round the disk cache serves every planned unit —
    the in-pod equivalent of the Docker test's '100% from peers' check."""
    repo = _repo(n_files=3, chunks_per_xorb=2)
    plan = DistributionPlan.build(list(repo.reconstructions.values()), 8)
    fetch, shards = _fetchers_for(repo, plan)
    pool = PodDistributor(pod_mesh()).distribute(
        plan, fetch, host=0, local_shards=shards
    )
    cache = XorbCache(tmp_config)
    assert pool.fill_cache(cache) == (len(plan.assignments), 0)
    for a in plan.assignments:
        got = cache.get_with_range(a.hash_hex, a.fetch_info.range.start)
        assert got is not None and got.data == repo.xorbs[a.hash_hex].blob


def test_plan_mesh_size_mismatch_raises():
    repo = _repo(n_files=1)
    plan = DistributionPlan.build(list(repo.reconstructions.values()), 4)
    with pytest.raises(ValueError):
        PodDistributor(pod_mesh()).distribute(plan, lambda a: b"")


# ── windowed waves: HBM-budgeted rounds ──


def _unit(i, size, owner):
    from zest_tpu.cas.reconstruction import ChunkRange, FetchInfo
    from zest_tpu.parallel.plan import FetchAssignment

    return FetchAssignment(
        hash_hex=f"{i:064x}",
        fetch_info=FetchInfo("/u", 0, size, ChunkRange(0, 1)),
        owner=owner,
    )


def test_split_waves_bounds_pool_to_budget():
    from zest_tpu.parallel import split_waves

    plan = DistributionPlan(8, [_unit(i, 100_000, i % 8) for i in range(64)])
    budget = 2 << 20
    assert PoolLayout.from_plan(plan).pool_bytes > budget
    waves = split_waves(plan, budget)
    assert len(waves) > 1
    got = []
    for w in waves:
        assert PoolLayout.from_plan(w).pool_bytes <= budget
        got += [(a.hash_hex, a.fetch_info.range.start) for a in w.assignments]
    # every unit appears in exactly one wave
    want = [(a.hash_hex, a.fetch_info.range.start) for a in plan.assignments]
    assert sorted(got) == sorted(want)


def test_split_waves_buckets_mixed_sizes():
    """One big unit among many small ones must not set the row capacity
    for all of them (the ~600x pool inflation failure mode)."""
    from zest_tpu.parallel import split_waves

    units = [_unit(0, 8 << 20, 0)] + [
        _unit(i + 1, 4096, i % 8) for i in range(80)
    ]
    plan = DistributionPlan(8, units)
    waves = split_waves(plan, budget_bytes=64 << 20)
    assert len(waves) == 2  # big unit isolated, small ones together
    total = sum(PoolLayout.from_plan(w).pool_bytes for w in waves)
    assert total < PoolLayout.from_plan(plan).pool_bytes / 10


def test_split_waves_budget_zero_disables_windowing():
    from zest_tpu.parallel import split_waves

    plan = DistributionPlan(8, [_unit(i, 1000, i % 8) for i in range(10)])
    assert split_waves(plan, 0) == [plan]


def test_split_waves_oversized_unit_gets_own_wave():
    from zest_tpu.parallel import split_waves

    plan = DistributionPlan(8, [_unit(i, 1 << 20, i % 8) for i in range(4)])
    waves = split_waves(plan, budget_bytes=1024)
    assert len(waves) == 4
    assert all(len(w.assignments) == 1 for w in waves)


def test_split_waves_deterministic():
    from zest_tpu.parallel import split_waves

    units = [_unit(i, 1000 + 97 * (i % 7), i % 8) for i in range(40)]
    a = split_waves(DistributionPlan(8, units), 1 << 20)
    b = split_waves(DistributionPlan(8, list(reversed(units))), 1 << 20)
    key = lambda w: [(x.hash_hex, x.owner) for x in w.assignments]  # noqa: E731
    assert [key(w) for w in a] == [key(w) for w in b]


# ── coordinator discovery ──


def test_in_memory_registry_protocol():
    reg = InMemoryRegistry()
    ih = b"\x01" * 20
    assert reg.find_peers(ih) == []
    reg.self_addr = ("10.0.0.1", 6881)
    reg.announce(ih, 6881)
    # Own announce is filtered out of discovery.
    assert reg.find_peers(ih) == []
    reg.add(ih, "10.0.0.2", 6881)
    assert ("10.0.0.2", 6881) in reg.find_peers(ih)
