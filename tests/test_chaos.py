"""The chaos matrix: deterministic fault injection against full pulls.

Acceptance for the resilience layer (ISSUE 2): under injected peer
timeouts, corrupt chunks, CDN 503s, connection resets, and a slow peer,
``pull_model`` must complete with bytes identical to the fault-free
path, wall time bounded by the configured deadline (no legacy 60 s
single-peer stall), and a peer that serves corrupt chunks must be
quarantined after K strikes while its traffic shifts to healthy tiers.

Every scenario pins the injection seed (``SEED``), so the firing
sequence of each fault is reproducible run-to-run — a chaos failure
replays exactly.
"""

import hashlib
import time

import pytest

from zest_tpu import faults
from zest_tpu.config import Config
from zest_tpu.transfer.pull import pull_model
from zest_tpu.transfer.server import BtServer
from zest_tpu.transfer.swarm import SwarmDownloader

from fixtures import FixtureHub, FixtureRepo

pytestmark = pytest.mark.chaos

SEED = 1337

# Deterministic, NON-periodic payload (a repeating pattern would dedup
# into one xorb and starve the matrix of requests to inject into).
_RNG_BYTES = b"".join(
    hashlib.blake2b(i.to_bytes(4, "little"), digest_size=64).digest()
    for i in range(16384)
)  # 1 MiB -> ~8 distinct chunks -> ~8 xorbs at chunks_per_xorb=1
FILES = {
    "config.json": b'{"model_type": "chaos"}',
    "model.safetensors": _RNG_BYTES,
    "tokenizer.json": b'{"tok": 1}' * 40,
}


@pytest.fixture(scope="module")
def hub():
    # One chunk per xorb: the ~600 KB model splits into 5 xorbs, so a
    # pull makes enough peer/CDN requests to accumulate K strikes and
    # to give the pinned fault sequences trials to fire on.
    repo = FixtureRepo("acme/chaos-model", FILES, chunks_per_xorb=1)
    with FixtureHub(repo) as h:
        yield h


@pytest.fixture(autouse=True)
def _pinned_faults():
    faults.reset()
    yield
    faults.reset()


def _cfg(hub, root, **kw):
    return Config(hf_home=root / "hf", cache_dir=root / "zest",
                  hf_token="hf_test", endpoint=hub.url, **kw)


@pytest.fixture(scope="module")
def seeder(hub, tmp_path_factory):
    """A warm host serving its cache over the BT wire."""
    cfg = _cfg(hub, tmp_path_factory.mktemp("seeder"), listen_port=0)
    pull_model(cfg, "acme/chaos-model", no_p2p=True)
    server = BtServer(cfg)
    port = server.start()
    yield port
    server.shutdown()


def _pull_with_peer(cfg, seeder_port):
    swarm = SwarmDownloader(cfg)
    swarm.add_direct_peer("127.0.0.1", seeder_port)
    try:
        result = pull_model(cfg, "acme/chaos-model", swarm=swarm,
                            log=lambda *a, **k: None)
    finally:
        swarm.close()
    return result


def _assert_bytes_identical(result):
    for name, data in FILES.items():
        assert (result.snapshot_dir / name).read_bytes() == data, \
            f"{name} differs from the fault-free bytes"


def test_peer_timeouts_bounded_quarantined_healed(hub, seeder, tmp_path):
    """Every connect to the (only) peer times out: the pull must fall
    to CDN without stalling, and the dead peer must be quarantined so
    later xorbs stop paying for it at all."""
    faults.install(f"peer_timeout:1.0@127.0.0.1:{seeder}", seed=SEED)
    t0 = time.monotonic()
    result = _pull_with_peer(_cfg(hub, tmp_path), seeder)
    elapsed = time.monotonic() - t0

    _assert_bytes_identical(result)
    swarm_stats = result.stats["swarm"]
    assert result.stats["fetch"]["bytes"]["cdn"] > 0
    assert result.stats["fetch"]["bytes"]["peer"] == 0
    assert swarm_stats["peer_failures"] > 0
    # K strikes (default 3) quarantine the dead peer; the repo has more
    # xorbs than that, so attempts stop short of one-per-xorb.
    assert swarm_stats["peers_quarantined"] >= 1
    assert swarm_stats["health"]["quarantined_now"] >= 1
    # Injected timeouts fail instantly; the bound proves no tier ever
    # waited out a legacy 5 s connect / 60 s IO timeout per xorb.
    assert elapsed < 30.0


def test_corrupt_peer_attributed_quarantined_healed(hub, seeder, tmp_path):
    """The seeder answers every chunk request with a flipped byte: the
    bridge must attribute the corruption to that peer (strikes →
    quarantine), refetch from CDN, and still produce exact bytes —
    including healing any poisoned cache entry."""
    faults.install(f"chunk_corrupt:1.0@127.0.0.1:{seeder}", seed=SEED)
    result = _pull_with_peer(_cfg(hub, tmp_path), seeder)

    _assert_bytes_identical(result)
    swarm_stats = result.stats["swarm"]
    res = result.stats["fetch"]["resilience"]
    assert swarm_stats["corrupt_from_peer"] >= 1, "corruption unattributed"
    assert res["corrupt_from_peer"] >= 1
    # Traffic shifted to the healthy tier (CDN) after quarantine.
    assert swarm_stats["peers_quarantined"] >= 1
    assert result.stats["fetch"]["bytes"]["cdn"] > 0


def _serial_cfg(hub, root, **kw):
    """Single-threaded pull: the fault trial sequence maps to requests
    deterministically, so the pinned seed replays exactly."""
    return _cfg(hub, root, pull_pipeline_width=1,
                max_concurrent_downloads=1, decode_workers=1, **kw)


def test_cdn_503s_retried(hub, tmp_path):
    faults.install("cdn_503:0.4", seed=SEED)
    result = pull_model(_serial_cfg(hub, tmp_path), "acme/chaos-model",
                        no_p2p=True, log=lambda *a, **k: None)
    _assert_bytes_identical(result)
    assert result.stats["fetch"]["resilience"]["cdn_retries"] >= 1


def test_cdn_connection_resets_retried(hub, tmp_path):
    faults.install("cdn_reset:0.4", seed=SEED)
    result = pull_model(_serial_cfg(hub, tmp_path), "acme/chaos-model",
                        no_p2p=True, log=lambda *a, **k: None)
    _assert_bytes_identical(result)
    assert result.stats["fetch"]["resilience"]["cdn_retries"] >= 1


def test_slow_peer_hedged_under_deadline(hub, seeder, tmp_path):
    """The peer serves correct bytes but sleeps 4 s per request; with an
    8 s pull deadline the bridge must hedge to CDN instead of waiting —
    the wall time stays inside the deadline, nowhere near the legacy
    60 s per-xorb stall."""
    faults.install(f"peer_slow:1.0@4.0@127.0.0.1:{seeder}", seed=SEED)
    deadline_s = 8.0
    cfg = _cfg(hub, tmp_path, pull_deadline_s=deadline_s)
    t0 = time.monotonic()
    result = _pull_with_peer(cfg, seeder)
    elapsed = time.monotonic() - t0

    _assert_bytes_identical(result)
    res = result.stats["fetch"]["resilience"]
    assert res["hedges"] >= 1, "deadline at risk but no hedge fired"
    assert res["hedges_won"] >= 1, "CDN racer never beat the slow peer"
    assert elapsed < deadline_s + 2.0, (
        f"pull took {elapsed:.1f}s against a {deadline_s}s deadline"
    )
    assert result.stats["deadline"]["budget_s"] == deadline_s


def test_full_matrix_combined(hub, seeder, tmp_path, monkeypatch):
    """Everything at once — flaky connects, corrupt chunks, CDN
    hiccups, a sluggish peer — under a deadline. The pull still lands
    exact bytes inside the budget."""
    import zest_tpu.cas.client as cas_client

    # Generous retry budget: overlapping fault streams can stack more
    # consecutive CDN failures onto one request than the default 3.
    monkeypatch.setattr(cas_client, "DEFAULT_RETRIES", 8)
    faults.install(
        f"peer_timeout:0.3@127.0.0.1:{seeder},"
        f"chunk_corrupt:0.3@127.0.0.1:{seeder},"
        f"peer_slow:0.3@1.0@127.0.0.1:{seeder},"
        "cdn_503:0.1,cdn_reset:0.1",
        seed=SEED,
    )
    deadline_s = 25.0
    cfg = _cfg(hub, tmp_path, pull_deadline_s=deadline_s)
    t0 = time.monotonic()
    result = _pull_with_peer(cfg, seeder)
    elapsed = time.monotonic() - t0

    _assert_bytes_identical(result)
    assert elapsed < deadline_s + 2.0
    fired = faults.counters()
    assert fired, "matrix ran but nothing injected"


def test_faultfree_pull_records_zero_resilience_events(hub, seeder,
                                                      tmp_path):
    """Control arm: with injection disabled the resilience layer is
    silent — no retries, no hedges, no strikes — and the peer tier
    serves the bytes as before."""
    result = _pull_with_peer(_cfg(hub, tmp_path), seeder)
    _assert_bytes_identical(result)
    res = result.stats["fetch"]["resilience"]
    assert res == {k: 0 for k in res}
    swarm_stats = result.stats["swarm"]
    assert swarm_stats["peers_quarantined"] == 0
    assert swarm_stats["corrupt_from_peer"] == 0
    assert result.stats["fetch"]["bytes"]["peer"] > 0
