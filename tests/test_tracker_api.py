"""Tracker client + HTTP control plane + CLI surface.

Tracker tests follow the reference's built-then-parsed style
(bt_tracker.zig:208-242) plus a live loopback announce against a canned
HTTP server. API tests drive the real ThreadingHTTPServer over loopback —
including the SSE ``/v1/pull`` the reference never implemented.
"""

import json
import socket
import struct
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest
import requests

from zest_tpu.p2p import bencode
from zest_tpu.p2p.tracker import (
    AnnounceResponse,
    Event,
    TrackerClient,
    TrackerError,
    build_announce_url,
    parse_announce_response,
)


# ── Tracker ──


def _compact(peers):
    return b"".join(
        socket.inet_aton(ip) + struct.pack(">H", port) for ip, port in peers
    )


def test_parse_announce_response_roundtrip():
    body = bencode.encode({
        b"interval": 900,
        b"peers": _compact([("10.0.0.1", 6881), ("10.0.0.2", 6882)]),
    })
    resp = parse_announce_response(body)
    assert resp == AnnounceResponse(
        900, [("10.0.0.1", 6881), ("10.0.0.2", 6882)]
    )


def test_parse_announce_failure_reason():
    body = bencode.encode({b"failure reason": b"unregistered torrent"})
    with pytest.raises(TrackerError, match="unregistered"):
        parse_announce_response(body)


def test_parse_announce_rejects_misaligned_peers():
    body = bencode.encode({b"interval": 1, b"peers": b"x" * 7})
    with pytest.raises(TrackerError, match="6-aligned"):
        parse_announce_response(body)


def test_build_announce_url_percent_encodes_binary():
    url = build_announce_url(
        "http://t.example/announce", bytes(range(20)),
        b"-ZE0200-abcdefghijkl", 6881, event=Event.STARTED,
    )
    assert "info_hash=%00%01%02" in url
    assert "event=started" in url and "compact=1" in url
    # '?' already present → '&' separator
    url2 = build_announce_url("http://t.example/a?k=1", b"\xff" * 20,
                              b"p" * 20, 1)
    assert "?k=1&info_hash=%FF" in url2


@pytest.fixture
def fake_tracker():
    """Canned tracker that records request paths."""
    seen = []

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            seen.append(self.path)
            body = bencode.encode({
                b"interval": 60,
                b"peers": _compact([("127.0.0.1", 7777)]),
            })
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}/announce", seen
    httpd.shutdown()
    httpd.server_close()


def test_tracker_client_live_announce(fake_tracker):
    url, seen = fake_tracker
    client = TrackerClient(url, b"-ZE0200-abcdefghijkl")
    resp = client.announce_event(b"\xab" * 20, 6881, Event.STARTED)
    assert resp.peers == [("127.0.0.1", 7777)]
    assert client.last_interval == 60
    assert "info_hash=%AB" in seen[0]
    # PeerSource protocol surface
    assert client.find_peers(b"\xab" * 20) == [("127.0.0.1", 7777)]
    client.announce(b"\xab" * 20, 6881)
    assert len(seen) == 3


def test_tracker_client_swallows_network_errors():
    client = TrackerClient("http://127.0.0.1:1/announce", b"p" * 20,
                           timeout=0.2)
    assert client.find_peers(b"\x01" * 20) == []
    client.announce(b"\x01" * 20, 1)  # must not raise


# ── HTTP control plane ──


@pytest.fixture
def api(tmp_config):
    from zest_tpu.api.http_api import HttpApi

    tmp_config.http_port = 0
    a = HttpApi(tmp_config)
    port = a.start()
    yield a, f"http://127.0.0.1:{port}"
    a.close()


def test_health_status_models_routes(api, tmp_config):
    a, base = api
    assert requests.get(f"{base}/v1/health", timeout=5).json() == {
        "status": "ok"
    }
    status = requests.get(f"{base}/v1/status", timeout=5).json()
    assert status["bt_peers"] == 0 and status["xorbs_cached"] == 0
    assert status["http_requests"] >= 1

    # Seed a fake cached model and see it in /v1/models.
    snap = (tmp_config.hf_home / "hub" / "models--org--name" /
            "snapshots" / "abc123")
    snap.mkdir(parents=True)
    (snap / "config.json").write_text("{}")
    models = requests.get(f"{base}/v1/models", timeout=5).json()
    assert models["models"] == [
        {"repo_id": "org/name", "revision": "abc123", "files": 1}
    ]

    assert requests.get(f"{base}/nope", timeout=5).status_code == 404
    assert "zest-tpu" in requests.get(f"{base}/", timeout=5).text


def test_stop_route_triggers_shutdown(api):
    a, base = api
    assert not a.shutdown_event.is_set()
    requests.post(f"{base}/v1/stop", timeout=5)
    assert a.shutdown_event.wait(timeout=2)


def test_pull_route_streams_sse_errors(api, monkeypatch):
    """A bad repo id must stream start → error, not 500 or hang."""
    a, base = api
    r = requests.post(f"{base}/v1/pull", json={"repo_id": "nosuch/repo"},
                      stream=True, timeout=30)
    assert r.status_code == 200
    events = []
    for line in r.iter_lines():
        if line.startswith(b"data: "):
            events.append(json.loads(line[6:]))
    assert events[0]["event"] == "start"
    assert events[-1]["event"] == "error"


def test_pull_route_rejects_bad_body(api):
    _a, base = api
    r = requests.post(f"{base}/v1/pull", data=b"not json", timeout=5)
    assert r.status_code == 400


# ── CLI ──


def test_cli_version_and_help(capsys):
    from zest_tpu.cli import main

    assert main(["version"]) == 0
    out = capsys.readouterr().out
    assert "zest-tpu" in out
    assert main([]) == 0
    assert "pull" in capsys.readouterr().out


def test_cli_bench_host_only(capsys):
    from zest_tpu.cli import main

    assert main(["bench", "--no-device", "--json"]) == 0
    results = json.loads(capsys.readouterr().out)
    names = {r["name"] for r in results}
    assert {"bencode_encode", "bencode_decode", "blake3_64kb",
            "sha1_info_hash", "bt_wire_frame"} <= names
    assert all(r["mb_per_s"] > 0 for r in results)


def test_cmd_start_prints_dashboard_url(monkeypatch, capsys):
    """VERDICT r5 item 8: `start` must surface the dashboard URL once
    health passes, and ZEST_OPEN_DASHBOARD=1 opens the browser."""
    import webbrowser

    from zest_tpu import cli

    health = iter([False, True])
    monkeypatch.setattr(cli, "_server_running",
                        lambda cfg: next(health, True))
    monkeypatch.setattr(cli, "auto_start_server", lambda cfg: True)
    monkeypatch.setenv("ZEST_HTTP_PORT", "9848")
    opened = []
    monkeypatch.setattr(webbrowser, "open",
                        lambda url: opened.append(url) or True)

    monkeypatch.delenv("ZEST_OPEN_DASHBOARD", raising=False)
    assert cli.main(["start"]) == 0
    out = capsys.readouterr().out
    assert "dashboard: http://127.0.0.1:9848/" in out
    assert opened == []  # opt-in only: headless CI must not spawn a browser

    monkeypatch.setenv("ZEST_OPEN_DASHBOARD", "1")
    monkeypatch.setattr(cli, "_server_running", lambda cfg: True)
    assert cli.main(["start"]) == 0
    out = capsys.readouterr().out
    assert "already running" in out
    assert "dashboard: http://127.0.0.1:9848/" in out
    assert opened == ["http://127.0.0.1:9848/"]
