"""BLAKE3 correctness: official vectors, structural invariants, and
pure-Python vs native C++ cross-checks.

Test style follows the reference's fixed-buffer roundtrip approach
(SURVEY.md §4) — no network, no mocks, exact expected bytes.
"""

import os
import random
import struct

import pytest

from zest_tpu.cas import blake3 as b3
from zest_tpu.cas import hashing

# Official test vectors (github.com/BLAKE3-team/BLAKE3 test_vectors.json):
# input is bytes(i % 251), these are the first 32 bytes of output.
OFFICIAL_VECTORS = {
    0: "af1349b9f5f9a1a6a0404dea36dcc9499bcb25c9adc112b7cc9a93cae41f3262",
    1: "2d3adedff11b61f14c886e35afa036736dcd87a74d27b5c1510225d0f592e213",
}


def _pattern(n: int) -> bytes:
    return bytes(i % 251 for i in range(n))


class TestOfficialVectors:
    @pytest.mark.parametrize("n,expected", sorted(OFFICIAL_VECTORS.items()))
    def test_hash(self, n, expected):
        assert b3.blake3(_pattern(n)).hex() == expected

    def test_xof_prefix_property(self):
        # XOF output must extend the 32-byte digest.
        long = b3.blake3(b"zest", 128)
        assert long[:32] == b3.blake3(b"zest")


class TestStructure:
    def test_two_chunk_tree_matches_manual_parent(self):
        # 2048 bytes = exactly two chunks; root = parent(cv0, cv1) with ROOT.
        data = _pattern(2048)
        cv = []
        for idx in (0, 1):
            chunk = b3._ChunkState(b3.IV, idx, 0)
            chunk.update(memoryview(data[idx * 1024 : (idx + 1) * 1024]))
            cv.append(chunk.output().chaining_value())
        root = b3._Output(
            b3.IV, cv[0] + cv[1], 0, b3.BLOCK_LEN, b3.PARENT
        ).root_bytes(32)
        assert root == b3.blake3(data)

    def test_incremental_equals_oneshot(self):
        data = _pattern(5000)
        h = b3.Hasher()
        for i in range(0, len(data), 37):  # awkward split sizes
            h.update(data[i : i + 37])
        assert h.digest() == b3.blake3(data)

    @pytest.mark.parametrize("n", [63, 64, 65, 1023, 1024, 1025, 3072, 4097])
    def test_boundary_lengths_incremental(self, n):
        data = _pattern(n)
        h = b3.Hasher()
        for byte in data[: min(n, 200)]:
            h.update(bytes([byte]))
        h.update(data[min(n, 200):])
        assert h.digest() == b3.blake3(data)

    def test_keyed_differs_from_plain(self):
        key = bytes(range(32))
        assert b3.blake3_keyed(key, b"data") != b3.blake3(b"data")
        assert b3.blake3_keyed(key, b"data") != b3.blake3_keyed(
            bytes(32), b"data"
        )

    def test_derive_key_deterministic(self):
        a = b3.blake3_derive_key("ctx", b"material")
        b = b3.blake3_derive_key("ctx", b"material")
        c = b3.blake3_derive_key("ctx2", b"material")
        assert a == b and a != c


class TestNativeCrossCheck:
    """Native C++ backend must agree bit-for-bit with pure Python."""

    @pytest.fixture(scope="class")
    def native(self):
        from zest_tpu.native import lib

        if not lib.available():
            pytest.skip("native lib unavailable (no g++?)")
        return lib

    # Exact multiples of 8/16 KiB pin the wide cores' have_final tails
    # (a group whose last lane IS the final chunk, pushed N-1 + promoted);
    # >256 KiB (n_chunks > 256) exercises the heap-allocation branch and
    # the >128-chunk level-order tree shapes the SIMD fold rewrote —
    # 524_288 is an exact 512-chunk tree, the others odd-promote.
    @pytest.mark.parametrize(
        "n", [0, 1, 31, 64, 65, 1023, 1024, 1025, 2048, 4096, 8192,
              10_000, 16_384, 24_576, 32_768, 70_000, 131_072,
              300_001, 524_288, 1_048_577]
    )
    def test_lengths(self, native, n):
        data = _pattern(n)
        assert native.blake3(data) == b3.blake3(data)

    def test_random_inputs(self, native):
        rng = random.Random(1234)
        for _ in range(30):
            n = rng.randrange(0, 9000)
            data = rng.randbytes(n)
            assert native.blake3(data) == b3.blake3(data)

    def test_keyed(self, native):
        key = os.urandom(32)
        for n in (0, 100, 1024, 5000):
            data = _pattern(n)
            assert native.blake3_keyed(key, data) == b3.blake3_keyed(key, data)

    def test_batch(self, native):
        item = 1024
        count = 8
        data = os.urandom(item * count)
        out = native.blake3_batch(data, count, item)
        for i in range(count):
            assert out[i * 32 : (i + 1) * 32] == b3.blake3(
                data[i * item : (i + 1) * item]
            )


class TestXetConventions:
    def test_hex_roundtrip(self):
        h = os.urandom(32)
        assert hashing.hex_to_hash(hashing.hash_to_hex(h)) == h

    def test_hex_is_le_u64_convention(self):
        # First 8 bytes 01..08 -> u64 LE 0x0807060504030201.
        h = bytes(range(1, 33))
        assert hashing.hash_to_hex(h).startswith("0807060504030201")
        assert hashing.hash_to_hex(h) != h.hex()

    def test_single_chunk_xorb_hash_is_chunk_hash(self):
        ch = hashing.chunk_hash(b"chunk")
        assert hashing.xorb_hash([(ch, 5)]) == ch

    def test_merkle_root_changes_with_order(self):
        a = (hashing.chunk_hash(b"a"), 1)
        b = (hashing.chunk_hash(b"b"), 1)
        assert hashing.merkle_root([a, b]) != hashing.merkle_root([b, a])

    def test_merkle_matches_documented_grouping(self):
        """Independent re-derivation of the production tree rule (group
        closes at child k>=3 when last u64 LE % 4 == 0, or at k == 9;
        parent = node_hash of the group)."""
        import struct as _struct

        leaves = [(hashing.chunk_hash(bytes([i])), 1) for i in range(23)]

        def ref_root(nodes):
            if len(nodes) == 1:
                return nodes[0]
            groups, cur = [], []
            for nd in nodes:
                cur.append(nd)
                last = _struct.unpack("<Q", nd[0][24:32])[0]
                if (len(cur) >= 3 and last % 4 == 0) or len(cur) == 9:
                    groups.append(cur)
                    cur = []
            if cur:
                groups.append(cur)
            return ref_root([
                (hashing.node_hash(g), sum(s for _, s in g)) for g in groups
            ])
        root, total = hashing.merkle_root(leaves)
        assert (root, total) == ref_root(leaves)
        assert total == 23
        # single leaf is its own root
        assert hashing.merkle_root(leaves[:1]) == leaves[0]

    def test_chunk_domain_separation(self):
        data = b"same bytes"
        assert hashing.chunk_hash(data) != hashing.blake3_hash(data)
        assert hashing.chunk_hash(data) != hashing.blake3_keyed(
            hashing.NODE_KEY, data
        )

    def test_dispatch_agrees_with_pure(self):
        data = os.urandom(3000)
        assert hashing.blake3_hash(data) == b3.blake3(data)
