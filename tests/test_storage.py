"""Storage tier tests: refs, chunk cache, xorb cache range semantics, registry."""

import os

from zest_tpu import storage
from zest_tpu.storage import XorbCache, XorbRegistry


def test_atomic_write_creates_parents(tmp_config):
    p = tmp_config.cache_dir / "a" / "b" / "c.bin"
    storage.atomic_write(p, b"data")
    assert p.read_bytes() == b"data"
    assert not list(p.parent.glob(".tmp-*"))


def test_refs_roundtrip(tmp_config):
    storage.write_ref(tmp_config, "org/model", "main", "abc123")
    assert storage.read_ref(tmp_config, "org/model", "main") == "abc123"
    assert storage.read_ref(tmp_config, "org/model", "missing") is None


def test_chunk_cache_roundtrip(tmp_config):
    h = os.urandom(32)
    assert storage.read_chunk(tmp_config, h) is None
    storage.write_chunk(tmp_config, h, b"chunk bytes")
    assert storage.read_chunk(tmp_config, h) == b"chunk bytes"


class TestXorbCache:
    def test_full_entry(self, tmp_config):
        cache = XorbCache(tmp_config)
        hex_key = "ab" * 32
        assert not cache.has(hex_key)
        assert cache.get_with_range(hex_key, 0) is None
        cache.put(hex_key, b"full xorb")
        assert cache.has(hex_key)
        result = cache.get_with_range(hex_key, 5)
        assert result.data == b"full xorb" and result.chunk_offset == 0

    def test_partial_entry(self, tmp_config):
        cache = XorbCache(tmp_config)
        hex_key = "cd" * 32
        cache.put_partial(hex_key, 7, b"partial blob")
        # Full lookup misses, exact partial hits with rebase offset.
        assert cache.get(hex_key) is None
        result = cache.get_with_range(hex_key, 7)
        assert result.data == b"partial blob" and result.chunk_offset == 7
        # Different range start misses (exact-match semantics,
        # reference swarm.zig:81-95).
        assert cache.get_with_range(hex_key, 6) is None

    def test_full_preferred_over_partial(self, tmp_config):
        cache = XorbCache(tmp_config)
        hex_key = "ef" * 32
        cache.put_partial(hex_key, 3, b"part")
        cache.put(hex_key, b"whole")
        assert cache.get_with_range(hex_key, 3).chunk_offset == 0


def test_list_cached_xorbs_excludes_partials(tmp_config):
    cache = XorbCache(tmp_config)
    cache.put("11" * 32, b"x")
    cache.put("22" * 32, b"y")
    cache.put_partial("33" * 32, 4, b"z")
    assert storage.list_cached_xorbs(tmp_config) == ["11" * 32, "22" * 32]


class TestRegistry:
    def test_scan(self, tmp_config):
        cache = XorbCache(tmp_config)
        cache.put("aa" * 32, b"full blob")
        cache.put_partial("bb" * 32, 12, b"part blob")
        reg = XorbRegistry()
        assert reg.scan(tmp_config) == 2
        assert reg.has("aa" * 32)
        assert reg.get("aa" * 32).size == 9
        assert reg.get("bb" * 32).partial_starts == (12,)

    def test_add_merges_partials(self):
        reg = XorbRegistry()
        reg.add("cc" * 32, 100, (3,))
        reg.add("cc" * 32, 100, (9,))
        assert reg.get("cc" * 32).partial_starts == (3, 9)
        assert len(reg) == 1

    def test_scan_ignores_tmp_files(self, tmp_config):
        d = tmp_config.xorb_cache_dir() / "aa"
        d.mkdir(parents=True)
        (d / ".tmp-partial").write_bytes(b"junk")
        reg = XorbRegistry()
        assert reg.scan(tmp_config) == 0


def test_list_models_ignores_stray_snapshot_files(tmp_path):
    """Cache introspection (storage.list_models — /v1/models and the
    ``models`` CLI): one row per models--*/ dir, revision = newest
    snapshots/ DIRECTORY; stray files dropped next to snapshots (e.g.
    an exported safetensors) must not masquerade as a revision."""
    import time

    from zest_tpu.config import Config
    from zest_tpu.storage import list_models

    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                 hf_token="hf_test")
    snap = cfg.model_snapshot_dir("acme/m", "shaAAA")
    snap.mkdir(parents=True)
    (snap / "config.json").write_text("{}")
    (snap / "model.safetensors").write_bytes(b"x")
    time.sleep(0.01)
    stray = snap.parent / "finetuned.safetensors"
    stray.write_bytes(b"y")  # newer mtime than the revision dir

    models = list_models(cfg)
    assert models == [
        {"repo_id": "acme/m", "revision": "shaAAA", "files": 2}
    ]
