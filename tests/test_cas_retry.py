"""CasClient resilience: idempotent retries with backoff, mid-stream
resume via adjusted Range headers, xet-token refresh on 401/403, and
deadline-capped retry budgets."""

import pytest
import requests

from zest_tpu.cas.client import CasClient, CasError
from zest_tpu.resilience import Deadline, DeadlineExceeded


class FakeResp:
    def __init__(self, status, body=b"", doc=None, die_after=None):
        self.status_code = status
        self._body = body
        self._doc = doc
        self._die_after = die_after  # bytes to yield before "reset"
        self.closed = False

    def json(self):
        return self._doc

    def iter_content(self, chunk_size):
        body = self._body
        sent = 0
        for i in range(0, len(body), chunk_size):
            piece = body[i : i + chunk_size]
            if self._die_after is not None \
                    and sent + len(piece) > self._die_after:
                keep = self._die_after - sent
                if keep > 0:
                    yield piece[:keep]
                raise requests.exceptions.ChunkedEncodingError(
                    "connection reset mid-body")
            sent += len(piece)
            yield piece

    def close(self):
        self.closed = True


class FakeSession:
    """Pops one scripted outcome per GET; records (url, headers)."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = []

    def get(self, url, headers=None, timeout=None, stream=False):
        self.calls.append((url, dict(headers or {})))
        step = self.script.pop(0)
        if isinstance(step, BaseException):
            raise step
        return step


def _client(script, **kw):
    kw.setdefault("backoff_base_s", 0.001)
    session = FakeSession(script)
    return CasClient("http://cas.test", "tok0", session=session, **kw), \
        session


BODY = bytes(range(256)) * 64  # 16 KiB


class TestFetchRetries:
    def test_5xx_then_success(self):
        events = []
        client, session = _client(
            [FakeResp(503), FakeResp(200, BODY)], on_event=events.append)
        assert client.fetch_xorb_from_url("http://cdn.test/x") == BODY
        assert events == ["cdn_retries"]

    def test_connection_error_then_success(self):
        client, _ = _client([
            requests.exceptions.ConnectionError("reset"),
            FakeResp(200, BODY),
        ])
        assert client.fetch_xorb_from_url("http://cdn.test/x") == BODY

    def test_retries_exhausted_raises(self):
        client, session = _client([FakeResp(503)] * 3, retries=2)
        with pytest.raises(CasError, match="after 3 attempts"):
            client.fetch_xorb_from_url("http://cdn.test/x")
        assert len(session.calls) == 3

    def test_non_retryable_status_fails_fast(self):
        client, session = _client([FakeResp(418)])
        with pytest.raises(CasError, match="418"):
            client.fetch_xorb_from_url("http://cdn.test/x")
        assert len(session.calls) == 1

    def test_mid_stream_reset_resumes_from_offset(self):
        """A reset after N bytes re-requests bytes N.. — the consumer
        sees one seamless, byte-exact stream."""
        cut = 5000
        client, session = _client([
            FakeResp(206, BODY[:8192], die_after=cut),
            FakeResp(206, BODY[cut:]),
        ])
        got = client.fetch_xorb_from_url("http://cdn.test/x",
                                         byte_range=(0, len(BODY)))
        assert got == BODY
        assert session.calls[0][1]["Range"] == f"bytes=0-{len(BODY) - 1}"
        assert session.calls[1][1]["Range"] == f"bytes={cut}-{len(BODY) - 1}"

    def test_unranged_fetch_resumes_with_range_header(self):
        cut = 1024
        client, session = _client([
            FakeResp(200, BODY, die_after=cut),
            FakeResp(206, BODY[cut:]),
        ])
        assert client.fetch_xorb_from_url("http://cdn.test/x") == BODY
        assert "Range" not in session.calls[0][1]
        assert session.calls[1][1]["Range"] == f"bytes={cut}-"

    def test_resume_when_origin_ignores_range(self):
        """Second attempt answers 200-whole-body despite the resume
        Range; the client must trim the already-delivered prefix."""
        cut = 3000
        client, _ = _client([
            FakeResp(200, BODY, die_after=cut),
            FakeResp(200, BODY),
        ])
        assert client.fetch_xorb_from_url("http://cdn.test/x") == BODY


class TestTokenRefresh:
    def test_401_refreshes_once_and_retries(self):
        events = []
        client, session = _client(
            [FakeResp(401), FakeResp(200, BODY)],
            token_refresher=lambda: ("http://cas.test", "tok1"),
            on_event=events.append,
        )
        assert client.fetch_xorb_from_url("http://cas.test/v1/x") == BODY
        assert session.calls[0][1]["Authorization"] == "Bearer tok0"
        assert session.calls[1][1]["Authorization"] == "Bearer tok1"
        assert events == ["token_refreshes"]

    def test_second_401_is_fatal(self):
        client, _ = _client(
            [FakeResp(401), FakeResp(401)],
            token_refresher=lambda: ("http://cas.test", "tok1"),
        )
        with pytest.raises(CasError, match="401"):
            client.fetch_xorb_from_url("http://cas.test/v1/x")

    def test_403_without_refresher_is_fatal(self):
        client, session = _client([FakeResp(403)])
        with pytest.raises(CasError, match="403"):
            client.fetch_xorb_from_url("http://cas.test/v1/x")
        assert len(session.calls) == 1

    def test_presigned_url_403_not_refreshed(self):
        """Off-origin (presigned) URLs don't carry our bearer token, so
        a 403 there is not a token problem — fail, don't refresh."""
        called = []
        client, _ = _client(
            [FakeResp(403)],
            token_refresher=lambda: called.append(1) or ("", "t"),
        )
        with pytest.raises(CasError, match="403"):
            client.fetch_xorb_from_url("http://cdn.elsewhere/x")
        assert not called

    def test_reconstruction_retries_and_refreshes(self):
        doc = {"terms": [], "fetch_info": {}}
        client, session = _client(
            [FakeResp(503), FakeResp(401), FakeResp(200, doc=doc)],
            token_refresher=lambda: ("http://cas.test", "tok1"),
        )
        rec = client.get_reconstruction("ab" * 32)
        assert rec.terms == []
        assert session.calls[-1][1]["Authorization"] == "Bearer tok1"

    def test_reconstruction_404_fails_fast(self):
        client, session = _client([FakeResp(404)])
        with pytest.raises(CasError, match="no reconstruction"):
            client.get_reconstruction("ab" * 32)
        assert len(session.calls) == 1


class TestDeadline:
    def test_expired_deadline_stops_retrying(self):
        client, session = _client([FakeResp(503)] * 10, retries=9,
                                  deadline=Deadline(0.05))
        with pytest.raises((DeadlineExceeded, CasError)):
            client.fetch_xorb_from_url("http://cdn.test/x")
        assert len(session.calls) < 10

    def test_deadline_caps_request_timeout(self):
        captured = {}

        class TimeoutSession(FakeSession):
            def get(self, url, headers=None, timeout=None, stream=False):
                captured["timeout"] = timeout
                return super().get(url, headers=headers, timeout=timeout,
                                   stream=stream)

        session = TimeoutSession([FakeResp(200, BODY)])
        client = CasClient("http://cas.test", session=session,
                           deadline=Deadline(5.0))
        client.fetch_xorb_from_url("http://cdn.test/x")
        assert captured["timeout"] <= 5.0
