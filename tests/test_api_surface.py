"""L6 Python-package surface: ZestClient, the hf monkey-patch, SSE pull.

The reference's python/zest/* contract (SURVEY.md §2.3): `pull` returns
the snapshot dir, `enable()`'s patched snapshot_download is transparent
and falls back to the original on ANY zest failure, and the REST pull
streams progress. All against the loopback fixture hub.
"""

from __future__ import annotations

import json

import numpy as np
import pytest
import requests

from tests.fixtures import FixtureHub, FixtureRepo
from zest_tpu.api import hf_backend
from zest_tpu.api.client import ZestClient
from zest_tpu.config import Config

REPO_ID = "acme/api-model"
FILES = {
    "config.json": b'{"model_type": "gpt2"}',
    "model.safetensors": np.random.default_rng(9).integers(
        0, 256, 300_000, dtype=np.uint8
    ).tobytes(),
}


@pytest.fixture(scope="module")
def hub():
    with FixtureHub(FixtureRepo(REPO_ID, FILES, chunks_per_xorb=2)) as h:
        yield h


@pytest.fixture()
def cfg(hub, tmp_path):
    return Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                  hf_token="hf_test", endpoint=hub.url)


def test_client_pull_returns_snapshot(cfg):
    import os
    import pathlib

    res = ZestClient(cfg).pull(REPO_ID)
    # PullResult is os.PathLike (the reference contract: pull hands back
    # the snapshot dir) and additionally carries the stats block.
    snap = pathlib.Path(os.fspath(res))
    for name, data in FILES.items():
        assert (snap / name).read_bytes() == data
    assert res.stats["fetch"]["bytes"]["cdn"] > 0


def test_hf_patch_pulls_through_zest(cfg):
    import huggingface_hub

    original = huggingface_hub.snapshot_download
    hf_backend.patch_hf_hub(ZestClient(cfg))
    try:
        assert huggingface_hub.snapshot_download is not original
        out = huggingface_hub.snapshot_download(REPO_ID)
        assert (
            __import__("pathlib").Path(out) / "model.safetensors"
        ).read_bytes() == FILES["model.safetensors"]
        # idempotent: re-patching keeps the original recoverable
        hf_backend.patch_hf_hub(ZestClient(cfg))
    finally:
        hf_backend.unpatch_hf_hub()
    assert huggingface_hub.snapshot_download is original


def test_hf_patch_falls_back_on_zest_failure(cfg, monkeypatch):
    """zest must never make a download fail that would otherwise
    succeed: a broken client degrades to the original downloader."""
    import huggingface_hub

    sentinel = object()
    monkeypatch.setattr(huggingface_hub, "snapshot_download",
                        lambda repo_id, *a, **k: sentinel)

    class BrokenClient:
        def pull(self, repo_id, revision="main"):
            raise RuntimeError("zest exploded")

    hf_backend.patch_hf_hub(BrokenClient())
    try:
        assert huggingface_hub.snapshot_download(REPO_ID) is sentinel
    finally:
        hf_backend.unpatch_hf_hub()


def test_sse_pull_streams_progress_and_completes(cfg):
    from zest_tpu.api.http_api import HttpApi

    cfg.http_port = 0
    api = HttpApi(cfg)
    port = api.start()
    try:
        r = requests.post(
            f"http://127.0.0.1:{port}/v1/pull",
            json={"repo_id": REPO_ID}, stream=True, timeout=60,
        )
        assert r.status_code == 200
        assert "text/event-stream" in r.headers["Content-Type"]
        events = []
        for line in r.iter_lines():
            if line.startswith(b"data: "):
                events.append(json.loads(line[6:]))
        assert events[0]["event"] == "start"
        assert events[-1]["event"] == "done"
        assert events[-1]["stats"]["files_downloaded"] == len(FILES)
        snap = __import__("pathlib").Path(events[-1]["snapshot_dir"])
        for name, data in FILES.items():
            assert (snap / name).read_bytes() == data
    finally:
        api.close()


def test_effective_http_port_resolves_ephemeral_daemon(tmp_path):
    """A daemon started with http_port=0 binds an ephemeral port and
    records it next to its pid file; status/stop/DaemonClient resolve it
    via Config.effective_http_port. Regression: status used to dial
    literal port 0 and report a live daemon as not running."""
    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                 hf_token="hf_test", http_port=0)
    # No daemon, no recorded port: the configured port is the answer.
    assert cfg.effective_http_port() == 0

    cfg.cache_dir.mkdir(parents=True, exist_ok=True)
    cfg.http_port_file().write_text("41513")
    assert cfg.effective_http_port() == 41513

    from zest_tpu.api.daemon import ZestServer

    assert ZestServer(cfg)._base.endswith(":41513")

    # Garbage degrades to the configured port (pid-file staleness model).
    cfg.http_port_file().write_text("not-a-port")
    assert cfg.effective_http_port() == 0

    # A CONCRETE configured port always wins: the record file must never
    # shadow an explicit --http-port/ZEST_HTTP_PORT (documented
    # precedence), even when a stale record from a crashed ephemeral
    # daemon survives in the same cache dir.
    cfg.http_port_file().write_text("41513")
    cfg2 = Config(hf_home=cfg.hf_home, cache_dir=cfg.cache_dir,
                  hf_token="hf_test", http_port=5000)
    assert cfg2.effective_http_port() == 5000
