"""Ring attention correctness: exactness vs dense attention, causality,
GQA, and differentiability (the ring-backward), on the virtual 8-device
mesh (tests/conftest.py). Reference analog: none — SURVEY.md §5 records
long-context as absent from the reference; this is a brief-mandated
first-class TPU component."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zest_tpu.parallel.ring import ring_attention, ring_self_attention


def dense_reference(q, k, v, causal):
    """Straightforward f32 attention over (B, T, H, D) with GQA repeat."""
    B, T, H, D = q.shape
    Hkv = k.shape[2]
    if Hkv != H:
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    qf = q.astype(jnp.float32).transpose(0, 2, 1, 3)
    kf = k.astype(jnp.float32).transpose(0, 2, 1, 3)
    vf = v.astype(jnp.float32).transpose(0, 2, 1, 3)
    scores = qf @ kf.transpose(0, 1, 3, 2) / np.sqrt(D)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        scores = jnp.where(mask, scores, -jnp.inf)
    att = jax.nn.softmax(scores, axis=-1)
    return (att @ vf).transpose(0, 2, 1, 3).astype(q.dtype)


def make_qkv(seed=0, B=2, T=32, H=4, Hkv=2, D=8, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), dtype)
    k = jnp.asarray(rng.standard_normal((B, T, Hkv, D)), dtype)
    v = jnp.asarray(rng.standard_normal((B, T, Hkv, D)), dtype)
    return q, k, v


def seq_mesh(n=8):
    return Mesh(np.asarray(jax.devices()[:n]), ("seq",))


@pytest.mark.slow
@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(causal):
    q, k, v = make_qkv()
    mesh = seq_mesh()
    got = ring_attention(q, k, v, mesh, causal=causal)
    want = dense_reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_ring_full_heads_no_gqa():
    q, k, v = make_qkv(seed=3, H=4, Hkv=4)
    mesh = seq_mesh()
    got = ring_attention(q, k, v, mesh, causal=True)
    want = dense_reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.slow
def test_ring_smaller_axis_and_uneven_heads():
    """4-device ring, 1 kv head, bf16 inputs (f32 accumulation inside)."""
    q, k, v = make_qkv(seed=5, T=16, H=4, Hkv=1, D=16, dtype=jnp.bfloat16)
    mesh = seq_mesh(4)
    got = ring_attention(q, k, v, mesh, causal=True)
    want = dense_reference(q, k, v, True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=3e-2, rtol=3e-2,
    )


@pytest.mark.slow
def test_ring_gradients_match_dense():
    """The scan/ppermute recurrence must transpose to the same gradients
    the dense formulation produces (ring-backward correctness)."""
    q, k, v = make_qkv(seed=7, T=16)
    mesh = seq_mesh(4)

    def ring_sum(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    def dense_sum(q, k, v):
        return jnp.sum(dense_reference(q, k, v, True) ** 2)

    g_ring = jax.grad(ring_sum, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_sum, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   atol=1e-5, rtol=1e-4)


def test_ring_rejects_bad_head_ratio():
    q, k, v = make_qkv(H=4, Hkv=3)

    with pytest.raises(ValueError, match="multiple"):
        mesh = seq_mesh(4)
        jax.shard_map(
            lambda q, k, v: ring_self_attention(q, k, v, "seq"),
            mesh=mesh,
            in_specs=(P(None, "seq"),) * 3, out_specs=P(None, "seq"),
        )(q, k, v)


def test_ring_under_jit_with_sharded_inputs():
    """jit + explicitly sharded operands: the deployment shape."""
    q, k, v = make_qkv(seed=11)
    mesh = seq_mesh()
    sh = NamedSharding(mesh, P(None, "seq"))
    q, k, v = (jax.device_put(t, sh) for t in (q, k, v))
    fn = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, causal=True))
    got = fn(q, k, v)
    want = dense_reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
