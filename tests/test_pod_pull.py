"""Pod-native pull: the collective pre-pass wired into ``pull_model``.

BASELINE config #3's shape on the virtual 8-device mesh: the round plans
ownership, owners fetch through the waterfall, the ICI all-gather fills
the cache, full xorbs are device-verified, and the per-file
reconstruction that follows never touches the CDN again.
"""

import numpy as np
import pytest

from tests.fixtures import FixtureHub, FixtureRepo
from zest_tpu.config import Config
from zest_tpu.transfer.bridge import XetBridge
from zest_tpu.transfer.pod import _device_verify_full_xorb, pod_round
from zest_tpu.transfer.pull import pull_model

FILES = {
    "config.json": b'{"model_type": "podtest"}',
    "model.safetensors": np.random.default_rng(5).bytes(600_000),
    "extra.safetensors": np.random.default_rng(6).bytes(200_000),
}


@pytest.fixture(scope="module")
def hub():
    repo = FixtureRepo("acme/pod-model", FILES, chunks_per_xorb=2)
    with FixtureHub(repo) as h:
        yield h


def _cfg(hub, root):
    return Config(
        hf_home=root / "hf", cache_dir=root / "zest",
        hf_token="hf_test", endpoint=hub.url,
    )


def _authed_bridge(hub, cfg, repo_id="acme/pod-model"):
    bridge = XetBridge(cfg)
    bridge.authenticate(repo_id)
    return bridge


def _recs(hub, bridge):
    repo = hub.repos["acme/pod-model"]
    return [
        repo.reconstructions[f.xet_hash]
        for f in repo.files.values() if f.xet_hash
    ]


@pytest.mark.slow
def test_pod_round_fills_cache_and_verifies(hub, tmp_path):
    cfg = _cfg(hub, tmp_path)
    bridge = _authed_bridge(hub, cfg)
    recs = _recs(hub, bridge)
    stats = pod_round(bridge, recs)
    assert stats["slots"] == 8
    assert stats["filled"] == stats["units"] > 0
    assert stats["verify_rejected"] == 0
    # every planned unit now hits tier 1
    for rec in recs:
        for term in rec.terms:
            fi = rec.find_fetch_info(term)
            assert bridge.cache.get_with_range(
                term.hash_hex, fi.range.start
            ) is not None


def test_pod_round_single_slot_skips(hub, tmp_path):
    import jax

    from zest_tpu.parallel.mesh import pod_mesh

    cfg = _cfg(hub, tmp_path)
    bridge = _authed_bridge(hub, cfg)
    stats = pod_round(bridge, _recs(hub, bridge),
                      mesh=pod_mesh(jax.devices()[:1]))
    assert stats.get("skipped")


def test_pull_with_pod_round_end_to_end(hub, tmp_path):
    cfg = _cfg(hub, tmp_path)
    res = pull_model(cfg, "acme/pod-model", no_p2p=True, pod=True)
    assert res.stats["pod"]["filled"] == res.stats["pod"]["units"] > 0
    # reconstruction after the round is all cache hits: CDN bytes equal
    # exactly what the round's owners fetched (no per-file refetch)
    fetch = res.stats["fetch"]
    assert fetch["xorbs"]["cache"] >= res.stats["pod"]["units"]
    for name, data in FILES.items():
        assert (res.snapshot_dir / name).read_bytes() == data


def test_pull_pod_files_identical_to_plain_pull(hub, tmp_path):
    plain = pull_model(_cfg(hub, tmp_path / "plain"), "acme/pod-model",
                       no_p2p=True, pod=False)
    podded = pull_model(_cfg(hub, tmp_path / "pod"), "acme/pod-model",
                        no_p2p=True, pod=True)
    assert "pod" not in plain.stats
    for name in FILES:
        assert (plain.snapshot_dir / name).read_bytes() == \
            (podded.snapshot_dir / name).read_bytes()


def test_device_verify_rejects_corrupt_xorb(hub, tmp_path):
    from zest_tpu.cas import hashing
    from zest_tpu.ops import best_hasher

    repo = hub.repos["acme/pod-model"]
    hash_hex, xf = next(iter(repo.xorbs.items()))
    hasher = best_hasher(hashing.CHUNK_KEY)
    assert _device_verify_full_xorb(xf.blob, hash_hex, hasher)
    bad = bytearray(xf.blob)
    bad[len(bad) // 2] ^= 0xFF
    assert not _device_verify_full_xorb(bytes(bad), hash_hex, hasher)
    assert not _device_verify_full_xorb(b"garbage", hash_hex, hasher)


def test_pod_round_windowed_waves_match_single_gather(hub, tmp_path):
    """A budget far below the plan's pool forces multiple waves; each
    wave's pool stays within budget and the cache ends up identical to
    the single-gather round (the reference's bounded 128-term batching,
    parallel_download.zig:117-131, as a collective)."""
    from zest_tpu.parallel.collectives import PoolLayout
    from zest_tpu.parallel.plan import DistributionPlan

    cfg = _cfg(hub, tmp_path / "win")
    bridge = _authed_bridge(hub, cfg)
    recs = _recs(hub, bridge)
    plan = DistributionPlan.build(recs, 8)
    full_pool = PoolLayout.from_plan(plan).pool_bytes
    biggest = max(
        PoolLayout.from_plan(DistributionPlan(8, [a])).pool_bytes
        for a in plan.assignments
    )
    budget = max(biggest, full_pool // 3)
    assert budget < full_pool
    stats = pod_round(bridge, recs, budget_bytes=budget)
    assert stats["waves"] > 1
    assert stats["pool_bytes"] <= budget
    assert stats["filled"] == stats["units"]
    assert stats["budget_bytes"] == budget

    ref = _authed_bridge(hub, _cfg(hub, tmp_path / "one"))
    ref_stats = pod_round(ref, _recs(hub, ref), budget_bytes=0)
    assert ref_stats["waves"] == 1
    for a in plan.assignments:
        x = bridge.cache.get_with_range(a.hash_hex, a.fetch_info.range.start)
        y = ref.cache.get_with_range(a.hash_hex, a.fetch_info.range.start)
        assert x is not None and y is not None and x.data == y.data


def test_device_verify_oversized_chunk_rejected_not_raised(hub):
    """A peer-supplied blob with a chunk above the device hasher's leaf
    cap (128 KiB) must count as a verify failure, not abort the round."""
    from zest_tpu.cas import hashing
    from zest_tpu.cas.xorb import XorbBuilder
    from zest_tpu.ops import best_hasher

    b = XorbBuilder()
    b.add_chunk(bytes(200 * 1024))  # XorbReader-legal, hasher-illegal
    blob = b.serialize()
    hh = hashing.hash_to_hex(b.xorb_hash())
    hasher = best_hasher(hashing.CHUNK_KEY)
    assert _device_verify_full_xorb(blob, hh, hasher) is False


def test_fetch_unit_slices_overwide_cached_blob(hub, tmp_path):
    """A cached full xorb wider than a prefix unit is re-framed to the
    unit's exact range — a wider blob would overflow its pool row and be
    zero-rowed (refetching from CDN despite the local hit)."""
    from zest_tpu.cas.reconstruction import ChunkRange, FetchInfo
    from zest_tpu.cas.xorb import XorbReader

    cfg = _cfg(hub, tmp_path)
    bridge = XetBridge(cfg)  # no CAS auth: a CDN fallthrough would raise
    repo = hub.repos["acme/pod-model"]
    hash_hex, xf = next(
        (h, x) for h, x in repo.xorbs.items()
        if len(XorbReader(x.blob)) >= 2
    )
    bridge.cache.put(hash_hex, xf.blob)
    fi = FetchInfo("/unused", 0, len(xf.blob), ChunkRange(0, 1))
    got = bridge.fetch_unit(hash_hex, fi)
    assert got == XorbReader(xf.blob).slice_range(0, 1)
    assert len(got) < len(xf.blob)
    assert bridge.stats.bytes_from_cache == len(got)


def test_pod_round_failed_fetch_degrades(hub, tmp_path):
    """An owner whose fetch fails leaves a zero row; the following
    reconstruction falls through to CDN — no aborts."""
    cfg = _cfg(hub, tmp_path)
    bridge = _authed_bridge(hub, cfg)
    recs = _recs(hub, bridge)
    real_fetch = bridge.fetch_unit
    calls = {"n": 0}

    def flaky(hash_hex, fi):
        calls["n"] += 1
        if calls["n"] % 2:
            raise IOError("cdn hiccup")
        return real_fetch(hash_hex, fi)

    bridge.fetch_unit = flaky
    stats = pod_round(bridge, recs)
    assert 0 < stats["filled"] < stats["units"]
    bridge.fetch_unit = real_fetch
    # files still reconstruct (cache partial + CDN for the rest)
    from zest_tpu.transfer.parallel import ParallelDownloader

    par = ParallelDownloader(bridge)
    repo = hub.repos["acme/pod-model"]
    f = repo.files["model.safetensors"]
    out = tmp_path / "out.safetensors"
    par.reconstruct_to_file(f.xet_hash, out)
    assert out.read_bytes() == FILES["model.safetensors"]


def test_fetch_unit_slices_cached_full_xorb(hub, tmp_path):
    """An owner holding the full xorb re-frames a sub-range unit from
    disk instead of re-downloading it."""
    from zest_tpu.cas.reconstruction import ChunkRange, FetchInfo
    from zest_tpu.cas.xorb import XorbReader

    cfg = _cfg(hub, tmp_path)
    bridge = XetBridge(cfg)  # no CAS auth: a CDN fallthrough would raise
    repo = hub.repos["acme/pod-model"]
    hash_hex, xf = next(
        (h, x) for h, x in repo.xorbs.items()
        if len(XorbReader(x.blob)) >= 2
    )
    bridge.cache.put(hash_hex, xf.blob)
    fi = FetchInfo(url="/unused", url_range_start=0,
                   url_range_end=len(xf.blob), range=ChunkRange(1, 2))
    got = bridge.fetch_unit(hash_hex, fi)
    assert got == XorbReader(xf.blob).slice_range(1, 2)
    assert bridge.stats.xorbs_from_cache == 1
    assert bridge.stats.xorbs_from_cdn == 0


def test_get_reconstruction_memoized(hub, tmp_path):
    cfg = _cfg(hub, tmp_path)
    bridge = _authed_bridge(hub, cfg)
    repo = hub.repos["acme/pod-model"]
    fhash = repo.files["model.safetensors"].xet_hash
    before = len(hub.requests_seen)
    r1 = bridge.get_reconstruction(fhash)
    mid = len(hub.requests_seen)
    r2 = bridge.get_reconstruction(fhash)
    assert r1 is r2
    assert len(hub.requests_seen) == mid > before  # second call: no HTTP


@pytest.mark.slow
def test_expert_routed_pull_end_to_end(tmp_path):
    """BASELINE config #4 through the production entry point: a
    Mixtral-family ``pull_model(device="tpu")`` must dispatch to the
    expert-routed round — expert-private xorbs fetched only by their
    owner host, never all-gathered — and still produce a byte-identical
    snapshot. The reference replicates every file to every asker
    (src/swarm.zig:279-314); this is the behavior that beats it."""
    import json

    from tests.test_moe import _hf_mixtral_tensors
    from zest_tpu.models import moe
    from zest_tpu.models.safetensors_io import write_safetensors

    cfg_m = moe.MoEConfig.tiny(n_layer=1, n_experts=4, n_embd=64,
                               d_ff=512, vocab_size=64)
    path = tmp_path / "model.safetensors"
    write_safetensors(path, _hf_mixtral_tensors(cfg_m))
    ckpt = path.read_bytes()
    config = {"model_type": "mixtral", "num_local_experts": 4}
    repo = FixtureRepo(
        "acme/tiny-mixtral",
        {"config.json": json.dumps(config).encode(),
         "model.safetensors": ckpt},
        chunks_per_xorb=2,
    )
    with FixtureHub(repo) as hub:
        cfg = _cfg(hub, tmp_path)
        res = pull_model(cfg, "acme/tiny-mixtral", device="tpu",
                         no_p2p=True, log=lambda *a, **k: None)
    pod = res.stats["pod"]
    assert pod["expert_routed"] is True
    assert pod["n_experts"] == 4
    assert pod["expert_units_fetched"] > 0
    assert pod["expert_units_failed"] == 0
    # The gather moved strictly less than the checkpoint: expert bytes
    # stayed private to their owners (the saving the plan promises).
    assert pod["expert_bytes"] > 0
    assert pod["ici_bytes_saved"] >= pod["expert_bytes"] * 7  # 8 slots
    assert pod["shared"]["planned_bytes"] < len(ckpt)
    # End-to-end integrity is unchanged by the routing split.
    out = res.snapshot_dir / "model.safetensors"
    assert out.read_bytes() == ckpt


@pytest.mark.slow
def test_dense_pull_takes_plain_round(hub, tmp_path):
    """A non-MoE repo through the same dispatch must take the plain
    all-gather round (no expert fields in stats)."""
    cfg = _cfg(hub, tmp_path)
    res = pull_model(cfg, "acme/pod-model", device="tpu", no_p2p=True,
                     log=lambda *a, **k: None)
    pod = res.stats["pod"]
    assert "expert_routed" not in pod
    assert pod["filled"] > 0
