"""One process of the true multi-process distribution test.

Run N of these (process_id 0..N-1) against one jax.distributed
coordinator: each is a REAL separate jax process — no monkeypatched
process counts — with its own cache, BT seeding server, and 4 virtual
CPU devices, forming one global 4N-device mesh.

Four phases, KV-barriered:

  A. process 0 fetches every unit from the fixture CDN and announces
     each xorb on the CoordinatorRegistry (the jax.distributed KV store).
  B. every other process pulls ALL units through the waterfall with the
     registry as its only peer source: discovery must come from the KV
     prefix, bytes must come from process 0 over BT wire, CDN must see
     nothing. Process 0 meanwhile asserts find_peers never returns
     itself.
  C. all processes run one pod_round over the global mesh — the
     multi-process make_array_from_process_local_data branch + the
     cross-process all-gather — then verify every file reassembles
     bit-identically (hash re-derived through the CAS stack).
  D. a hierarchical (pods, hosts) round with the pod axis ON the process
     boundary: stage 1's cross-pod gather is a real cross-process
     collective, every unit verified byte-for-byte out of the pool.

Usage: _mp_pod_worker.py PROCESS_ID NUM_PROCS COORD_ADDR HUB_URL ROOT REPO_ID
Writes ROOT/stats_{pid}.json on success.
"""

import json
import os
import pathlib
import sys


def main() -> int:
    pid, nprocs = int(sys.argv[1]), int(sys.argv[2])
    coord, hub_url = sys.argv[3], sys.argv[4]
    root, repo_id = pathlib.Path(sys.argv[5]), sys.argv[6]
    devices_per_proc = 4

    # CPU backend with 4 virtual devices. The launcher already exports
    # JAX_PLATFORMS/XLA_FLAGS, but sitecustomize may have imported jax
    # before this line with the ambient (TPU) platform — set both env
    # and jax.config, exactly like tests/conftest.py.
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices_per_proc}"
    )
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=coord, num_processes=nprocs, process_id=pid
    )
    assert jax.process_count() == nprocs
    assert jax.device_count() == devices_per_proc * nprocs

    from zest_tpu.cas.chunking import chunk_stream
    from zest_tpu.cas.hashing import chunk_hash, file_hash, hash_to_hex
    from zest_tpu.cas.hub import HubClient
    from zest_tpu.config import Config
    from zest_tpu.parallel.coordinator import CoordinatorRegistry
    from zest_tpu.parallel.mesh import pod_mesh
    from zest_tpu.transfer.bridge import XetBridge
    from zest_tpu.transfer.pod import pod_round
    from zest_tpu.transfer.server import BtServer
    from zest_tpu.transfer.swarm import SwarmDownloader

    cfg = Config(
        hf_home=root / f"p{pid}" / "hf",
        cache_dir=root / f"p{pid}" / "zest",
        hf_token="hf_test",
        endpoint=hub_url,
        listen_port=0,
    )
    registry = CoordinatorRegistry("127.0.0.1", process_id=pid)
    swarm = SwarmDownloader(cfg, peer_sources=[registry])
    bridge = XetBridge(cfg, swarm=swarm)
    bridge.authenticate(repo_id)
    recs = [
        bridge.get_reconstruction(e.xet_hash)
        for e in HubClient(cfg).list_files(repo_id)
        if e.is_xet
    ]
    from zest_tpu.parallel.plan import collect_units

    units = collect_units(recs)
    assert units, "fixture repo must have xet units"

    stats = {"pid": pid, "phase_b_peer_bytes": 0, "phase_b_cdn_bytes": 0}

    from zest_tpu.transfer.federated import (
        _already_cached,
        _cache_unit,
        _entries_by_hash,
    )

    entries_map = _entries_by_hash(recs)

    def warm(units):
        """fetch_unit + persist under the bridge's full-vs-partial cache
        rule (fetch_unit leaves caching to its callers)."""
        for (hash_hex, _start), fi in units:
            if _already_cached(bridge, hash_hex, fi):
                continue
            data = bridge.fetch_unit(hash_hex, fi)
            _cache_unit(bridge, entries_map, hash_hex, fi,
                        fi.range.start, data)

    # Phase A: process 0 warms its cache from CDN and announces.
    server = BtServer(cfg, bridge.cache)
    bt_port = server.start()
    if pid == 0:
        warm(units)
        from zest_tpu.cas import hashing as _h
        from zest_tpu.p2p import peer_id as peer_id_mod

        for (hash_hex, _start), _fi in units:
            registry.announce(
                peer_id_mod.compute_info_hash(_h.hex_to_hash(hash_hex)),
                bt_port,
            )
            # self-exclusion: our own announce must be invisible to us
            assert registry.find_peers(
                peer_id_mod.compute_info_hash(_h.hex_to_hash(hash_hex))
            ) == []
        stats["announced"] = len(units)
    registry.barrier("phase-a", 120)

    # Phase B: other processes pull through KV-discovered BT peers only.
    if pid != 0:
        cdn_before = bridge.stats.bytes_from_cdn
        warm(units)
        stats["phase_b_peer_bytes"] = bridge.stats.bytes_from_peer
        stats["phase_b_cdn_bytes"] = bridge.stats.bytes_from_cdn - cdn_before
        assert stats["phase_b_peer_bytes"] > 0, "no bytes over BT wire"
        assert stats["phase_b_cdn_bytes"] == 0, "waterfall leaked to CDN"
    registry.barrier("phase-b", 120)

    # Phase C: the distributed pod round over the global mesh. Caches are
    # warm, so owners serve their slots from cache and the cross-process
    # all-gather replicates every band.
    mesh = pod_mesh()  # 1-D axis over all 4N global devices
    pod_stats = pod_round(bridge, recs, mesh=mesh)
    assert pod_stats["slots"] == devices_per_proc * nprocs
    assert pod_stats["filled"] > 0 or pod_stats["units"] == 0
    stats["pod"] = {
        k: pod_stats[k] for k in ("slots", "units", "filled", "waves")
    }

    # Integrity: every file reassembles to its advertised CAS address.
    for e in HubClient(cfg).list_files(repo_id):
        if not e.is_xet:
            continue
        out = root / f"p{pid}" / f"out-{e.path.replace('/', '_')}"
        bridge.reconstruct_to_file(e.xet_hash, out)
        data = out.read_bytes()
        leaves = [(chunk_hash(c), len(c)) for _m, c in chunk_stream(data)]
        assert hash_to_hex(file_hash(leaves)) == e.xet_hash, e.path
    stats["verified_files"] = sum(
        1 for e in HubClient(cfg).list_files(repo_id) if e.is_xet
    )

    registry.barrier("phase-c", 120)

    # Phase D: a hierarchical (pods, hosts) round where the pod axis
    # crosses the PROCESS boundary — process i is pod i, so stage 1's
    # cross-pod all-gather is a real cross-process collective (the
    # de-simulation of test_hierarchy's monkeypatched multiprocess
    # branch). Caches are warm, so fetch_fn serves from disk.
    from zest_tpu.parallel.hierarchy import (
        HierarchicalDistributor,
        HierarchicalPlan,
        hier_mesh,
    )

    hmesh = hier_mesh(nprocs, devices_per_proc)
    hplan = HierarchicalPlan.build(recs, nprocs, devices_per_proc)
    dist = HierarchicalDistributor(hmesh)
    pool = dist.distribute(
        hplan,
        lambda a: bridge.fetch_unit(a.hash_hex, a.fetch_info),
    )
    verified_units = 0
    for a in hplan.flat.assignments:
        got = pool.blob(a.hash_hex, a.fetch_info.range.start)
        assert got is not None, (pid, a.hash_hex)
        want = bridge.fetch_unit(a.hash_hex, a.fetch_info)
        assert got[0] == want, (pid, a.hash_hex)
        verified_units += 1
    stats["hier"] = {
        "pods": nprocs,
        "hosts_per_pod": devices_per_proc,
        "verified_units": verified_units,
        "stage_seconds": dist.stage_seconds,
    }

    registry.barrier("phase-d", 120)
    server.shutdown()
    (root / f"stats_{pid}.json").write_text(json.dumps(stats))
    return 0


if __name__ == "__main__":
    sys.exit(main())
