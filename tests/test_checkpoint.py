"""Training-state checkpoint/restore (orbax) and HF export round-trip."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zest_tpu.models import llama
from zest_tpu.models.checkpoint import (
    export_hf_safetensors,
    restore_train_state,
    save_train_state,
)
from zest_tpu.models.training import adamw, create_state, make_train_step


@pytest.mark.slow
def test_save_restore_round_trip(tmp_path):
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    tx = adamw(warmup_steps=1, total_steps=10)
    step = make_train_step(tx, functools.partial(llama.loss_fn, cfg=cfg))
    rng = np.random.default_rng(0)
    batch = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 9)), jnp.int32)
    state, _ = step(create_state(params, tx), batch)

    save_train_state(tmp_path / "step_1", state)
    like = create_state(llama.init_params(jax.random.key(9), cfg), tx)
    restored = restore_train_state(tmp_path / "step_1", like)

    assert int(restored.step) == int(state.step) == 1
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Resume: one more step from the restored state runs and advances.
    resumed, loss = step(restored, batch)
    assert int(resumed.step) == 2 and np.isfinite(float(loss))


def test_save_restore_sharded(tmp_path):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(1), cfg)
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("data", "model"))
    specs = llama.param_specs(cfg)
    sharded = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda v: isinstance(v, P),
    )
    tx = adamw()
    state = create_state(sharded, tx)
    save_train_state(tmp_path / "s", state)
    restored = restore_train_state(tmp_path / "s", state)
    qw = restored.params["blocks"]["attn"]["q_w"]
    assert qw.sharding.spec == P(None, None, "model")
    np.testing.assert_array_equal(
        np.asarray(qw), np.asarray(state.params["blocks"]["attn"]["q_w"])
    )


def test_export_hf_round_trip(tmp_path):
    """Exported safetensors re-import bit-identically through
    params_from_hf."""
    from zest_tpu.models.safetensors_io import SafetensorsFile

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(2), cfg)
    path = tmp_path / "model.safetensors"
    export_hf_safetensors(path, params, cfg)
    with SafetensorsFile(path) as sf:
        tensors = {n: sf.tensor(n) for n in sf.names()}
    back = llama.params_from_hf(tensors, cfg)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_export_loads_in_transformers(tmp_path):
    """The full interchange oracle: exported file → torch state_dict →
    transformers forward must match the JAX forward."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    safetensors_torch = pytest.importorskip("safetensors.torch")

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(3), cfg)
    path = tmp_path / "model.safetensors"
    export_hf_safetensors(path, params, cfg)

    hf_cfg = transformers.LlamaConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.n_embd,
        intermediate_size=cfg.d_ff, num_hidden_layers=cfg.n_layer,
        num_attention_heads=cfg.n_head, num_key_value_heads=cfg.n_kv_head,
        max_position_embeddings=cfg.n_ctx, rms_norm_eps=cfg.rms_eps,
        rope_theta=cfg.rope_theta, tie_word_embeddings=False,
        attention_bias=False, mlp_bias=False,
    )
    model = transformers.LlamaForCausalLM(hf_cfg)
    state = safetensors_torch.load_file(str(path))
    missing, unexpected = model.load_state_dict(state, strict=False)
    assert not unexpected, unexpected
    # rotary buffers may report missing; no real weights may.
    assert not [m for m in missing if "rotary" not in m], missing
    model.eval()

    rng = np.random.default_rng(4)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 12))
    got = np.asarray(llama.forward(params, jnp.asarray(ids, jnp.int32), cfg))
    with torch.no_grad():
        want = model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)


def test_qwen2_export_includes_biases(tmp_path):
    cfg = llama.LlamaConfig.tiny(attn_bias=True)
    params = llama.init_params(jax.random.key(5), cfg)
    hf = llama.params_to_hf(params, cfg)
    assert "model.layers.0.self_attn.q_proj.bias" in hf
    assert "model.layers.0.self_attn.o_proj.bias" not in hf
