"""Tests for hierarchical DCN+ICI distribution (BASELINE config #5).

The virtual 8-device mesh models 2 pods × 4 hosts; assertions check the
two-level ownership balance, the stage decomposition (each stage is a
single-axis collective), and byte-exact delivery with the flat
distributor's waterfall semantics preserved.
"""

import numpy as np
import pytest

from tests.fixtures import FixtureRepo
from zest_tpu.cas import hashing
from zest_tpu.parallel import (
    HierarchicalDistributor,
    HierarchicalPlan,
    hier_mesh,
    owner_pod_host,
)


def _repo(n_files=4, size=60_000):
    rng = np.random.default_rng(11)
    files = {
        f"w-{i}.safetensors": rng.bytes(size + i * 777)
        for i in range(n_files)
    }
    return FixtureRepo("acme/hier", files, chunks_per_xorb=2)


def _plan(repo, n_pods=2, hosts_per_pod=4):
    recs = [
        repo.reconstructions[f.xet_hash]
        for f in repo.files.values() if f.xet_hash
    ]
    return HierarchicalPlan.build(recs, n_pods, hosts_per_pod)


def _fetch_fn(repo):
    def fetch(a):
        xf = repo.xorbs[a.hash_hex]
        return xf.blob[a.fetch_info.url_range_start:a.fetch_info.url_range_end]
    return fetch


def test_hier_mesh_shape_and_mismatch():
    mesh = hier_mesh(2, 4)
    assert mesh.shape == {"pods": 2, "hosts": 4}
    with pytest.raises(ValueError):
        hier_mesh(3, 3)


def test_owner_pod_host_deterministic_in_range():
    h = hashing.blake3_hash(b"unit")
    pod, host = owner_pod_host(h, 0, 4, 16)
    assert (pod, host) == owner_pod_host(h, 0, 4, 16)
    assert 0 <= pod < 4 and 0 <= host < 16
    # range_start participates in the draw: some other start must land
    # elsewhere (64 draws of 1/64 chance of all-equal by accident)
    assert any(
        owner_pod_host(h, s, 4, 16) != (pod, host)
        for s in range(64, 64 * 65, 64)
    )


def test_pod_and_host_draws_independent():
    """Pod-level and host-level rendezvous must be independent draws —
    otherwise host load within a pod correlates with pod choice."""
    pods, hosts = [], []
    for i in range(256):
        h = hashing.blake3_hash(f"unit-{i}".encode())
        p, s = owner_pod_host(h, 0, 2, 2)
        pods.append(p)
        hosts.append(s)
    both = sum(1 for p, s in zip(pods, hosts) if p == s)
    # independence → p==s about half the time; perfectly correlated draws
    # would give ~all or ~none
    assert 64 < both < 192


def test_plan_balances_pod_ingress():
    plan = _plan(_repo(n_files=8, size=120_000))
    s = plan.summary()
    assert s["pods"] == 2
    assert sum(s["bytes_per_pod"]) == s["total_bytes"]
    assert s["pod_balance"] > 0.5  # HRW keeps pods within 2× of each other


def test_distribute_round_trips_all_blobs(tmp_config):
    repo = _repo()
    plan = _plan(repo)
    mesh = hier_mesh(2, 4)
    dist = HierarchicalDistributor(mesh)
    fetch = _fetch_fn(repo)
    shards = {
        s: {(a.hash_hex, a.fetch_info.range.start): fetch(a)
            for a in plan.flat.for_host(s)}
        for s in range(plan.flat.num_hosts)
    }
    pool = dist.distribute(plan, fetch, slot=0, local_shards=shards)
    for a in plan.flat.assignments:
        got = pool.blob(a.hash_hex, a.fetch_info.range.start)
        assert got is not None
        want = fetch(a)
        assert got[0] == want
    # both stages ran and were timed; byte basis is the padded pool the
    # collectives actually carry, not the plan's compressed sum
    assert set(dist.stage_seconds) == {"dcn", "ici"}
    from zest_tpu.parallel import PoolLayout

    pool_bytes = PoolLayout.from_plan(plan.flat).pool_bytes
    stats = dist.stage_stats()
    assert stats["pool_bytes"] == pool_bytes >= plan.flat.total_bytes
    assert stats["dcn_bytes"] == pool_bytes          # (P-1) = 1
    assert stats["ici_bytes"] == pool_bytes * 2 * 3  # P·(H-1)
    assert stats["dcn_gbps"] > 0 and stats["ici_gbps"] > 0


def test_distribute_failed_fetch_leaves_zero_row(tmp_config):
    repo = _repo(n_files=2)
    plan = _plan(repo)
    mesh = hier_mesh(2, 4)
    dist = HierarchicalDistributor(mesh)
    fetch = _fetch_fn(repo)
    owned = plan.flat.for_host(0)

    def failing(a):
        raise IOError("cdn down")

    pool = dist.distribute(plan, failing, slot=0)
    for a in owned:
        assert pool.blob(a.hash_hex, a.fetch_info.range.start) is None


def test_multiprocess_branch_builds_per_device_shards(monkeypatch):
    """Drive the multi-process packing path: every device is addressable
    in a single-process run, so faking process_count exercises the
    per-device shard construction end-to-end and must produce the same
    pool as the global path."""
    import zest_tpu.parallel.hierarchy as hier

    repo = _repo(n_files=2)
    plan = _plan(repo)
    fetch = _fetch_fn(repo)
    mesh = hier_mesh(2, 4)

    monkeypatch.setattr(hier.jax, "process_count", lambda: 2)
    pool = HierarchicalDistributor(mesh).distribute(plan, fetch)
    for a in plan.flat.assignments:
        got = pool.blob(a.hash_hex, a.fetch_info.range.start)
        assert got is not None and got[0] == fetch(a)


def test_plan_mesh_mismatch_raises():
    plan = _plan(_repo(n_files=1), n_pods=4, hosts_per_pod=2)
    dist = HierarchicalDistributor(hier_mesh(2, 4))
    with pytest.raises(ValueError, match="4×2"):
        dist.distribute(plan, lambda a: b"")


def test_hier_owners_match_two_level_draw():
    plan = _plan(_repo())
    for a in plan.flat.assignments:
        pod, host = owner_pod_host(
            hashing.hex_to_hash(a.hash_hex),
            a.fetch_info.range.start, 2, 4,
        )
        assert a.owner == pod * 4 + host
