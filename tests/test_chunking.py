"""GearHash CDC tests: determinism, bounds, shift-invariance, native parity."""

import os
import random

import pytest

from zest_tpu.cas import chunking
from zest_tpu.cas.chunking import MAX_CHUNK, MIN_CHUNK, _cut_points_py, cut_points


def test_empty():
    assert cut_points(b"") == []


def test_small_input_single_chunk():
    data = os.urandom(1000)
    assert cut_points(data) == [1000]


def test_chunks_cover_input_exactly():
    data = os.urandom(1_000_000)
    cuts = cut_points(data)
    assert cuts[-1] == len(data)
    assert cuts == sorted(set(cuts))
    prev = 0
    for c in cuts[:-1]:
        assert MIN_CHUNK <= c - prev <= MAX_CHUNK
        prev = c
    assert c if cuts else True


def test_deterministic():
    data = os.urandom(500_000)
    assert cut_points(data) == cut_points(data)


def test_average_chunk_size_near_target():
    rng = random.Random(7)
    data = rng.randbytes(8 * 1024 * 1024)
    cuts = cut_points(data)
    avg = len(data) / len(cuts)
    # CDC average should be within 2x of target either way.
    assert chunking.TARGET_CHUNK / 2 < avg < chunking.TARGET_CHUNK * 2


def test_content_defined_boundaries_survive_prefix_shift():
    # Insert bytes at the front: boundaries must re-align after ~1 chunk,
    # which is the entire point of CDC dedup.
    rng = random.Random(42)
    data = rng.randbytes(1_000_000)
    cuts_a = set(cut_points(data))
    shifted = rng.randbytes(777) + data
    cuts_b = {c - 777 for c in cut_points(shifted)}
    late_a = {c for c in cuts_a if c > 300_000}
    assert late_a and late_a.issubset(cuts_b | {len(data)})


def test_native_matches_python():
    from zest_tpu.native import lib

    if not lib.available():
        pytest.skip("native lib unavailable")
    rng = random.Random(3)
    for n in (0, 100, MIN_CHUNK, 300_000, 1_000_000):
        data = rng.randbytes(n)
        assert lib.gear_cut_points(
            data, MIN_CHUNK, MAX_CHUNK, chunking.MASK
        ) == _cut_points_py(memoryview(data))


def test_chunk_stream_reassembles():
    data = os.urandom(400_000)
    pieces = list(chunking.chunk_stream(data))
    assert b"".join(p for _, p in pieces) == data
    assert all(ch.length == len(p) for ch, p in pieces)
