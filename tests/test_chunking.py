"""GearHash CDC tests: determinism, bounds, shift-invariance, native parity."""

import os
import random

import pytest

from zest_tpu.cas import chunking
from zest_tpu.cas.chunking import MAX_CHUNK, MIN_CHUNK, _cut_points_py, cut_points


def test_empty():
    assert cut_points(b"") == []


def test_small_input_single_chunk():
    data = os.urandom(1000)
    assert cut_points(data) == [1000]


def test_chunks_cover_input_exactly():
    data = os.urandom(1_000_000)
    cuts = cut_points(data)
    assert cuts[-1] == len(data)
    assert cuts == sorted(set(cuts))
    prev = 0
    for c in cuts[:-1]:
        assert MIN_CHUNK <= c - prev <= MAX_CHUNK
        prev = c
    assert c if cuts else True


def test_deterministic():
    data = os.urandom(500_000)
    assert cut_points(data) == cut_points(data)


def test_average_chunk_size_near_target():
    rng = random.Random(7)
    data = rng.randbytes(8 * 1024 * 1024)
    cuts = cut_points(data)
    avg = len(data) / len(cuts)
    # CDC average should be within 2x of target either way.
    assert chunking.TARGET_CHUNK / 2 < avg < chunking.TARGET_CHUNK * 2


def test_content_defined_boundaries_survive_prefix_shift():
    # Insert bytes at the front: boundaries must re-align after ~1 chunk,
    # which is the entire point of CDC dedup.
    rng = random.Random(42)
    data = rng.randbytes(1_000_000)
    cuts_a = set(cut_points(data))
    shifted = rng.randbytes(777) + data
    cuts_b = {c - 777 for c in cut_points(shifted)}
    late_a = {c for c in cuts_a if c > 300_000}
    assert late_a and late_a.issubset(cuts_b | {len(data)})


def test_native_matches_python():
    from zest_tpu.native import lib

    if not lib.available():
        pytest.skip("native lib unavailable")
    rng = random.Random(3)
    for n in (0, 100, MIN_CHUNK, 300_000, 1_000_000):
        data = rng.randbytes(n)
        assert lib.gear_cut_points(
            data, MIN_CHUNK, MAX_CHUNK, chunking.MASK
        ) == _cut_points_py(memoryview(data))


def _both_paths(data: bytes) -> list[list[int]]:
    """cut_points results from every available implementation path."""
    results = [_cut_points_py(memoryview(data)), cut_points(data)]
    from zest_tpu.native import lib

    if lib.available() and len(data) > 0:
        results.append(
            lib.gear_cut_points(data, MIN_CHUNK, MAX_CHUNK, chunking.MASK))
    return results


def test_edge_empty_input_both_paths():
    # Contract: the empty stream has no chunks — [] on EVERY path, and
    # chunk_stream yields nothing (no zero-length Chunk).
    for cuts in _both_paths(b""):
        assert cuts == []
    assert list(chunking.chunk_stream(b"")) == []


def test_edge_shorter_than_min_chunk_both_paths():
    # Below MIN_CHUNK no mask cut can fire (the min-size skip), so the
    # whole input is exactly one final chunk — on every path.
    rng = random.Random(11)
    for n in (1, 2, MIN_CHUNK - 1, MIN_CHUNK):
        data = rng.randbytes(n)
        py, dispatch, *native = _both_paths(data)
        assert py == dispatch, f"n={n}"
        for cuts in native:
            assert cuts == py, f"n={n}"
        assert py[-1] == n and py == sorted(set(py))
        if n < MIN_CHUNK:
            assert py == [n]
        pieces = list(chunking.chunk_stream(data))
        assert b"".join(p for _, p in pieces) == data


def test_edge_exact_boundary_final_chunk_both_paths():
    # Truncate a buffer exactly AT an interior cut: the final chunk's
    # boundary lands on len(data) and must be emitted once — no
    # trailing zero-length cut — and the cut list must be the exact
    # prefix of the full buffer's (the CDC prefix property the dedup
    # index relies on). Pinned identical across paths.
    rng = random.Random(12)
    data = rng.randbytes(1_000_000)
    cuts = cut_points(data)
    assert len(cuts) >= 3, "fixture buffer did not chunk"
    boundary = cuts[1]  # interior mask/max cut, not the tail
    trunc = data[:boundary]
    expect = [c for c in cuts if c <= boundary]
    for got in _both_paths(trunc):
        assert got == expect
        assert got[-1] == len(trunc)
        assert got == sorted(set(got))  # no duplicate/zero-length tail
    # MAX_CHUNK-boundary flavour: a max-size cut landing exactly on the
    # end of input (constant bytes never satisfy the mask, so every cut
    # is a MAX_CHUNK truncation).
    flat = b"\x00" * (2 * MAX_CHUNK)
    for got in _both_paths(flat):
        assert got == [MAX_CHUNK, 2 * MAX_CHUNK]


def test_chunk_stream_reassembles():
    data = os.urandom(400_000)
    pieces = list(chunking.chunk_stream(data))
    assert b"".join(p for _, p in pieces) == data
    assert all(ch.length == len(p) for ch, p in pieces)
