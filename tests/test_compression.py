"""LZ4 + transform scheme tests: roundtrips, cross-backend, hostile input."""

import os
import random

import numpy as np
import pytest

from zest_tpu.cas import compression as comp
from zest_tpu.cas.compression import (
    CompressionError,
    Scheme,
    _lz4_compress_py,
    _lz4_decompress_py,
)


def _native():
    from zest_tpu.native import lib

    return lib if lib.available() else None


CASES = [
    b"",
    b"a",
    b"abcd" * 100,
    os.urandom(100),
    b"\x00" * 10_000,
    bytes(range(256)) * 300,
    os.urandom(70_000),
    b"The quick brown fox " * 5000,
]


class TestLZ4Python:
    @pytest.mark.parametrize("i", range(len(CASES)))
    def test_roundtrip(self, i):
        data = CASES[i]
        c = _lz4_compress_py(data)
        assert _lz4_decompress_py(c, len(data)) == data

    def test_compresses_repetitive(self):
        data = b"x" * 100_000
        assert len(_lz4_compress_py(data)) < 1000

    def test_overlapping_match(self):
        # offset 1 run replication — the classic RLE-via-LZ4 case
        data = b"ab" + b"a" * 1000
        c = _lz4_compress_py(data)
        assert _lz4_decompress_py(c, len(data)) == data

    def test_truncated_input_rejected(self):
        c = _lz4_compress_py(b"hello world, hello world, hello world")
        for cut in (1, len(c) // 2, len(c) - 1):
            with pytest.raises(CompressionError):
                _lz4_decompress_py(c[:cut], 37)

    def test_bad_offset_rejected(self):
        # token: 0 literals + match len 4, offset 5 with empty history
        with pytest.raises(CompressionError):
            _lz4_decompress_py(b"\x00\x05\x00", 4)

    def test_wrong_expected_len_rejected(self):
        c = _lz4_compress_py(b"abcdef")
        with pytest.raises(CompressionError):
            _lz4_decompress_py(c, 7)


class TestLZ4NativeCross:
    @pytest.fixture(scope="class")
    def native(self):
        lib = _native()
        if lib is None:
            pytest.skip("native lib unavailable")
        return lib

    @pytest.mark.parametrize("i", range(len(CASES)))
    def test_native_roundtrip(self, native, i):
        data = CASES[i]
        c = native.lz4_compress(data)
        assert native.lz4_decompress(c, len(data)) == data

    @pytest.mark.parametrize("i", range(len(CASES)))
    def test_py_compress_native_decompress(self, native, i):
        data = CASES[i]
        assert native.lz4_decompress(_lz4_compress_py(data), len(data)) == data

    @pytest.mark.parametrize("i", range(len(CASES)))
    def test_native_compress_py_decompress(self, native, i):
        data = CASES[i]
        assert _lz4_decompress_py(native.lz4_compress(data), len(data)) == data

    def test_native_rejects_garbage(self, native):
        with pytest.raises(CompressionError):
            native.lz4_decompress(b"\xff\xff\xff\xff", 100)

    def test_native_rejects_garbage_for_zero_expected(self, native):
        # Regression: n==0 return is ambiguous with expected_len==0.
        with pytest.raises(CompressionError):
            native.lz4_decompress(b"\xff\xff", 0)

    def test_random_fuzz_cross(self, native):
        rng = random.Random(99)
        for _ in range(25):
            n = rng.randrange(0, 5000)
            data = rng.randbytes(n) if rng.random() < 0.5 else bytes(
                rng.choices(b"abcab", k=n)
            )
            c1, c2 = _lz4_compress_py(data), native.lz4_compress(data)
            assert native.lz4_decompress(c1, n) == data
            assert _lz4_decompress_py(c2, n) == data


class TestSchemes:
    @pytest.mark.parametrize("scheme", list(Scheme))
    def test_roundtrip_all_schemes(self, scheme):
        data = np.arange(4096, dtype=np.float32).tobytes()
        c = comp.compress(data, scheme)
        assert comp.decompress(c, scheme, len(data)) == data

    @pytest.mark.parametrize("scheme", list(Scheme))
    @pytest.mark.parametrize("n", [0, 1, 3, 7, 1021])
    def test_awkward_lengths(self, scheme, n):
        data = os.urandom(n)
        c = comp.compress(data, scheme)
        assert comp.decompress(c, scheme, n) == data

    def test_bg4_beats_plain_on_float_data(self):
        # fp32 weights: planar regrouping should compress much better.
        rng = np.random.default_rng(0)
        data = (rng.standard_normal(16384) * 0.02).astype(np.float32).tobytes()
        plain = comp.compress(data, Scheme.LZ4)
        bg4 = comp.compress(data, Scheme.BG4_LZ4)
        assert len(bg4) < len(plain)

    def test_auto_picks_none_for_random(self):
        scheme, payload = comp.compress_auto(os.urandom(4096))
        assert scheme == Scheme.NONE and len(payload) == 4096

    def test_auto_picks_compressed_for_text(self):
        scheme, payload = comp.compress_auto(b"weights " * 1000)
        assert scheme != Scheme.NONE and len(payload) < 8000
