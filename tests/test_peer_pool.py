"""PeerPool eviction discipline: true LRU among idle connections.

Pins the satellite fix for the `max_peers` cap: eviction used to drop
an *arbitrary* (insertion-ordered) idle entry, throwing away hot peers
while week-old idle sockets survived. Every pool access now touches its
key, and eviction walks least-recently-used first.
"""

import threading

import pytest

from zest_tpu.p2p.pool import PeerPool


class FakePeer:
    def __init__(self):
        self.lock = threading.Lock()
        self.closed = False

    def close(self):
        self.closed = True


@pytest.fixture
def pool(monkeypatch):
    import zest_tpu.p2p.pool as pool_mod

    monkeypatch.setattr(
        pool_mod.BtPeer, "connect",
        staticmethod(lambda *a, **k: FakePeer()),
    )
    return PeerPool(max_peers=2)


def _get(pool, host):
    return pool.get_or_connect(host, 6881, b"i" * 20, b"p" * 20)


def test_eviction_drops_least_recently_used(pool):
    a = _get(pool, "a")
    b = _get(pool, "b")
    assert _get(pool, "a") is a  # touch refreshes recency
    c = _get(pool, "c")  # at cap: evicts b (LRU), never a (just touched)
    assert len(pool) == 2
    assert b.closed and not a.closed and not c.closed
    assert _get(pool, "a") is a  # a survived
    assert _get(pool, "c") is c


def test_eviction_skips_busy_peer_even_if_lru(pool):
    a = _get(pool, "a")
    b = _get(pool, "b")
    assert _get(pool, "a") is a  # b is now LRU...
    with b.lock:  # ...but mid-request: closing it would kill a transfer
        _get(pool, "c")
    assert not b.closed
    assert a.closed  # the next-least-recent idle peer went instead


def test_all_busy_soft_caps_instead_of_closing(pool):
    a = _get(pool, "a")
    b = _get(pool, "b")
    with a.lock, b.lock:
        c = _get(pool, "c")  # admitted over the cap; nothing closed
    assert len(pool) == 3
    assert not a.closed and not b.closed and not c.closed


def test_remove_and_reconnect(pool):
    a = _get(pool, "a")
    pool.remove("a", 6881)
    assert a.closed and len(pool) == 0
    assert _get(pool, "a") is not a  # fresh connection after removal


def _lease(pool, host):
    return pool.lease(host, 6881, b"i" * 20, b"p" * 20)


def test_lease_reports_reuse(pool):
    a, reused = _lease(pool, "a")
    assert not reused  # fresh connect
    a2, reused2 = _lease(pool, "a")
    assert a2 is a and reused2  # pooled


def test_eviction_race_closes_leased_but_unlocked_peer(pool):
    """The race _evict_one_locked concedes: a thread that leased a peer
    but hasn't taken its stream lock yet can lose the connection to an
    eviction. The contract is (1) the evicted socket is observably
    closed — the victim's request fails rather than hanging — and
    (2) the lease carried reused=True, which is exactly the signal the
    swarm uses to retry once on a fresh connection instead of failing
    the pull (pinned end-to-end by
    test_swarm_health.test_stale_pooled_socket_gets_one_reconnect_retry).
    """
    a = _get(pool, "a")
    _get(pool, "b")
    leased, reused = _lease(pool, "a")  # victim thread's lease...
    assert leased is a and reused
    # ...then, before the victim locks, a third connect evicts at cap.
    # The lease touched `a`, so LRU order protects it — hold b's lock to
    # force the eviction onto `a` (the leased-but-unlocked peer).
    b2, _ = _lease(pool, "b")
    with b2.lock:
        _get(pool, "c")
    assert leased.closed, "evicted peer must be closed, not leaked"
    # The victim's request on the closed peer now fails fast; the swarm
    # turns (reused=True, IO error) into exactly one reconnect retry.
    fresh, fresh_reused = _lease(pool, "a")
    assert fresh is not leased and not fresh_reused
