"""Pipeline parallelism: the shard_map GPipe program must be semantically
identical to running ``lax.scan`` over the stacked layers unsharded —
forward, gradients, and with real transformer blocks."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from zest_tpu.models import gpt2
from zest_tpu.parallel.pipeline import (
    microbatch, pipeline_blocks, unmicrobatch,
)


def pipe_mesh(n=4):
    return Mesh(np.asarray(jax.devices()[:n]), ("pipe",))


def linear_block(x, p):
    """Toy layer: x @ w + b, the scan-body signature models use."""
    return jnp.tanh(x @ p["w"] + p["b"]), None


def make_stack(L=8, E=16, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(rng.standard_normal((L, E, E)) * 0.3, jnp.float32),
        "b": jnp.asarray(rng.standard_normal((L, E)) * 0.1, jnp.float32),
    }


def test_microbatch_roundtrip():
    x = jnp.arange(24.0).reshape(12, 2)
    mb = microbatch(x, 4)
    assert mb.shape == (4, 3, 2)
    np.testing.assert_array_equal(np.asarray(unmicrobatch(mb)),
                                  np.asarray(x))
    with pytest.raises(ValueError, match="divisible"):
        microbatch(x, 5)


@pytest.mark.parametrize("stages,microbatches", [(4, 4), (4, 8), (2, 2)])
def test_pipeline_matches_scan(stages, microbatches):
    params = make_stack()
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
    want, _ = jax.lax.scan(linear_block, x, params)
    got = pipeline_blocks(
        linear_block, params, x, pipe_mesh(stages), microbatches
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-6, rtol=1e-6)


def test_pipeline_single_stage_degenerates_to_scan():
    params = make_stack(L=4)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
    want, _ = jax.lax.scan(linear_block, x, params)
    got = pipeline_blocks(linear_block, params, x, pipe_mesh(1), 2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-6, rtol=1e-6)


@pytest.mark.slow
def test_pipeline_gradients_match_scan():
    """Reverse-mode must recover the unsharded gradients (the backward
    pipeline schedule falls out of scan/ppermute transposition)."""
    params = make_stack(L=4, E=8)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)
    mesh = pipe_mesh(4)

    def pipe_loss(params, x):
        return jnp.sum(pipeline_blocks(linear_block, params, x, mesh, 2) ** 2)

    def scan_loss(params, x):
        out, _ = jax.lax.scan(linear_block, x, params)
        return jnp.sum(out ** 2)

    gp = jax.grad(pipe_loss)(params, x)
    gs = jax.grad(scan_loss)(params, x)
    for leaf_p, leaf_s in zip(jax.tree.leaves(gp), jax.tree.leaves(gs)):
        np.testing.assert_allclose(np.asarray(leaf_p), np.asarray(leaf_s),
                                   atol=1e-5, rtol=1e-4)


def test_pipeline_runs_gpt2_blocks():
    """The composition contract: models' stacked-block scan bodies drop
    straight into the pipeline (same signature, same stacked layout)."""
    cfg = gpt2.GPT2Config.tiny(n_layer=4)
    params = gpt2.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(4)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)), jnp.int32)
    x = params["wte"][ids] + params["wpe"][:16]

    def block(x, lp):
        return gpt2._block(x, lp, cfg), None

    want, _ = jax.lax.scan(block, x, params["blocks"])
    got = pipeline_blocks(block, params["blocks"], x, pipe_mesh(4), 2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_spmd_composes_with_data_axis():
    """pipeline_spmd inside a multi-axis shard_map ({data, pipe}): the
    carry initializers must be varying over every mesh axis the operands
    vary over, not just pipe (regression: VMA carry-type mismatch)."""
    from jax.sharding import PartitionSpec as P

    from zest_tpu.parallel.pipeline import pipeline_spmd

    params = make_stack(L=4)
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("data", "pipe"))

    def mapped(p, xs):
        out = pipeline_spmd(linear_block, p, xs)
        return jax.lax.psum(out, "pipe")

    fn = jax.shard_map(
        mapped, mesh=mesh,
        in_specs=(P("pipe"), P(None, "data")),
        out_specs=P(None, "data"),
    )
    got = unmicrobatch(fn(params, microbatch(x, 2)))
    want, _ = jax.lax.scan(linear_block, x, params)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-6, rtol=1e-6)


def test_pipeline_rejects_indivisible_layers():
    params = make_stack(L=6)
    x = jnp.zeros((4, 16), jnp.float32)
    with pytest.raises(Exception):  # shard_map divisibility error
        pipeline_blocks(linear_block, params, x, pipe_mesh(4), 2)
