"""HBM serving pool (ISSUE 18): multi-model residency, scale-to-zero
re-landing, first-layer-commit decode start, and lazy MoE expert
paging.

The contract under test: the pool admits/evicts against the
``ZEST_HBM_POOL_BYTES`` watermark and NEVER evicts a pinned tree; an
evict → re-land cycle reproduces the exact bytes a cold pull landed
(``loader.params_digest`` identity); a cold generate starts decoding
at first-layer commit, before the land finishes; the gated decoders
are bit-identical to the family paths (greedy AND sampled); a Mixtral
entry serves with expert residency bounded by the pager budget, every
page-in digest-verified; an aborted landing strands zero HBM bytes
(satellite 1, pool and loader side); and ``ZEST_HBM_POOL=0`` restores
the single-model serving path bit-for-bit, payload schemas included.
"""

from __future__ import annotations

import json
import threading
import time

import jax
import numpy as np
import pytest

from fixtures import (
    FixtureHub,
    FixtureRepo,
    llama_checkpoint_files,
    mixtral_checkpoint_files,
)
from zest_tpu import telemetry
from zest_tpu.config import Config
from zest_tpu.models import hbm_pool
from zest_tpu.telemetry import remediate, timeline


@pytest.fixture(autouse=True)
def clean(monkeypatch):
    for name in ("ZEST_HBM_POOL", "ZEST_HBM_POOL_BYTES",
                 "ZEST_SLO_TTFT_S", "ZEST_TIMELINE", "ZEST_TELEMETRY",
                 "ZEST_REMEDIATE", "ZEST_TENANCY"):
        monkeypatch.delenv(name, raising=False)
    hbm_pool.reset()
    telemetry.reset_all()
    yield
    hbm_pool.reset()
    telemetry.reset_all()


def _snap(root, files, name="snap"):
    d = root / name
    d.mkdir(parents=True, exist_ok=True)
    for fname, data in files.items():
        if not isinstance(data, bytes):
            data = data.encode()
        (d / fname).write_bytes(data)
    return d


def _cfg(root, **kw) -> Config:
    return Config(hf_home=root / "hf", cache_dir=root / "zest",
                  hf_token="hf_test", **kw)


@pytest.fixture
def make_pool(tmp_path):
    pools: list[hbm_pool.HbmPool] = []

    def make(**kw) -> hbm_pool.HbmPool:
        p = hbm_pool.HbmPool(_cfg(tmp_path, **kw))
        pools.append(p)
        return p

    yield make
    for p in pools:
        p.close()


def _wait_state(entry, want, timeout=30.0):
    t0 = time.monotonic()
    while entry.state != want:
        assert time.monotonic() - t0 < timeout, \
            f"entry stuck in {entry.state!r}, wanted {want!r}"
        time.sleep(0.01)


def _samples(name: str) -> list:
    for m in telemetry.REGISTRY.metrics():
        if m.name == name:
            return m.samples()
    return []


# ── Config knobs (strict env parsing) ──


class TestKnobs:
    def test_defaults(self):
        cfg = Config.load({})
        assert cfg.hbm_pool_enabled is True
        assert cfg.hbm_pool_bytes == 2 << 30
        assert cfg.slo_ttft_s is None

    def test_pool_off(self):
        assert Config.load({"ZEST_HBM_POOL": "0"}).hbm_pool_enabled \
            is False

    @pytest.mark.parametrize("bad", ["false", "yes", "2", ""])
    def test_pool_knob_strict(self, bad):
        with pytest.raises(ValueError):
            Config.load({"ZEST_HBM_POOL": bad})

    def test_pool_bytes(self):
        cfg = Config.load({"ZEST_HBM_POOL_BYTES": "1048576"})
        assert cfg.hbm_pool_bytes == 1048576
        assert Config.load(
            {"ZEST_HBM_POOL_BYTES": "0"}).hbm_pool_bytes == 0

    @pytest.mark.parametrize("bad", ["2GB", "-1", "1.5"])
    def test_pool_bytes_strict(self, bad):
        with pytest.raises(ValueError):
            Config.load({"ZEST_HBM_POOL_BYTES": bad})

    def test_slo_ttft(self):
        assert Config.load({"ZEST_SLO_TTFT_S": "1.5"}).slo_ttft_s == 1.5
        assert Config.load({"ZEST_SLO_TTFT_S": "0"}).slo_ttft_s is None
        assert Config.load({"ZEST_SLO_TTFT_S": ""}).slo_ttft_s is None

    @pytest.mark.parametrize("bad", ["-1", "soon"])
    def test_slo_ttft_strict(self, bad):
        with pytest.raises(ValueError):
            Config.load({"ZEST_SLO_TTFT_S": bad})


# ── Admission / eviction / pinning ──


class TestAdmission:
    def test_acquire_miss_then_hit(self, make_pool, tmp_path):
        snap = _snap(tmp_path, llama_checkpoint_files())
        pool = make_pool()
        entry, hot = pool.acquire(snap, "acme/a")
        assert hot is False and entry.pins == 2  # caller + land thread
        _wait_state(entry, "resident")
        pool.release(entry)
        entry2, hot2 = pool.acquire(snap, "acme/a")
        assert entry2 is entry and hot2 is True
        pool.release(entry2)
        assert pool.hits == 1 and pool.misses == 1
        assert entry.bytes == entry.reserved > 0
        assert pool.used_bytes() == entry.bytes

    def test_unsupported_family_rejected(self, make_pool, tmp_path):
        snap = _snap(tmp_path, {
            "config.json": json.dumps({"model_type": "gpt2"})})
        pool = make_pool()
        with pytest.raises(ValueError, match="not pool-served"):
            pool.acquire(snap, "acme/gpt2")
        assert pool.supports("gpt2") is False
        assert pool.supports("llama") is True

    def test_missing_checkpoint_unpins(self, make_pool, tmp_path):
        snap = _snap(tmp_path, {
            "config.json": json.dumps({"model_type": "llama"})})
        pool = make_pool()
        with pytest.raises(FileNotFoundError):
            pool.acquire(snap, "acme/empty")
        # The failed admission must not leak its pin.
        assert pool._entries[str(snap.resolve())].pins == 0

    def test_pressure_evicts_lru_not_pinned(self, make_pool, tmp_path):
        files = llama_checkpoint_files()
        snap_a = _snap(tmp_path, files, "a")
        snap_b = _snap(tmp_path, llama_checkpoint_files(seed=1), "b")
        snap_c = _snap(tmp_path, llama_checkpoint_files(seed=2), "c")
        pool = make_pool()
        ea, _ = pool.acquire(snap_a, "acme/a")
        _wait_state(ea, "resident")
        pool.release(ea)
        # Budget: room for ~two trees, not three.
        pool.budget = int(ea.reserved * 2.5)

        eb, _ = pool.acquire(snap_b, "acme/b")
        _wait_state(eb, "resident")
        # B stays pinned while C admits: A (LRU, unpinned) must be the
        # victim; B must survive.
        ec, _ = pool.acquire(snap_c, "acme/c")
        _wait_state(ec, "resident")
        assert ea.state == "evicted" and ea.bytes == 0
        assert eb.state == "resident"
        assert pool.evictions == 1
        evs = {lbl.get("reason"): v
               for lbl, v in _samples("zest_hbm_pool_evictions_total")}
        assert evs.get("pressure") == 1
        pool.release(eb)
        pool.release(ec)

    def test_all_pinned_survives_over_budget(self, make_pool, tmp_path):
        snap_a = _snap(tmp_path, llama_checkpoint_files(), "a")
        snap_b = _snap(tmp_path, llama_checkpoint_files(seed=1), "b")
        pool = make_pool()
        ea, _ = pool.acquire(snap_a, "acme/a")
        _wait_state(ea, "resident")
        pool.budget = ea.reserved + 1  # no room for a second tree
        eb, _ = pool.acquire(snap_b, "acme/b")  # A still pinned
        _wait_state(eb, "resident")
        # Zero pinned-model evictions under pressure — the pool runs
        # over budget rather than break an active decode.
        assert ea.state == "resident"
        assert pool.evictions == 0
        assert pool.pinned_survivals >= 1
        assert pool.used_bytes() > pool.budget
        pool.release(ea)
        pool.release(eb)

    def test_manual_evict_refuses_pinned(self, make_pool, tmp_path):
        snap = _snap(tmp_path, llama_checkpoint_files())
        pool = make_pool()
        entry, _ = pool.acquire(snap, "acme/a")
        _wait_state(entry, "resident")
        assert pool.evict(snap) is False          # pinned
        assert entry.state == "resident"
        pool.release(entry)
        assert pool.evict(snap) is True
        assert entry.state == "evicted"

    def test_shed_coldest_picks_lru(self, make_pool, tmp_path):
        snap_a = _snap(tmp_path, llama_checkpoint_files(), "a")
        snap_b = _snap(tmp_path, llama_checkpoint_files(seed=1), "b")
        pool = make_pool()
        for snap, repo in ((snap_a, "acme/a"), (snap_b, "acme/b")):
            e, _ = pool.acquire(snap, repo)
            _wait_state(e, "resident")
            pool.release(e)
        # Touch B so A is coldest.
        eb, _ = pool.acquire(snap_b, "acme/b")
        pool.release(eb)
        assert pool.shed_coldest() == "acme/a"
        assert pool.shed_coldest() == "acme/b"
        assert pool.shed_coldest() is None


# ── Scale-to-zero re-landing ──


class TestReLand:
    def test_evict_reland_digest_identity(self, make_pool, tmp_path):
        from zest_tpu.models.generate import snapshot_tensors
        from zest_tpu.models.loader import params_digest

        snap = _snap(tmp_path, llama_checkpoint_files())
        pool = make_pool()
        out1, info1 = pool.generate_for(snap, "acme/a", [1, 2, 3], 4)
        assert info1["temp"] == "cold"
        d_cold = pool.digest(snap)
        assert d_cold is not None
        # The on-disk truth: digest over the snapshot's host tensors.
        d_disk = params_digest(snapshot_tensors(snap))
        assert d_cold == d_disk

        assert pool.evict(snap) is True
        assert pool.digest(snap) is None          # evicted: no tree
        out2, info2 = pool.generate_for(snap, "acme/a", [1, 2, 3], 4)
        assert info2["temp"] == "cold"
        # Byte-identical tree after the round trip, identical tokens.
        assert pool.digest(snap) == d_disk
        np.testing.assert_array_equal(np.asarray(out1),
                                      np.asarray(out2))

    def test_decode_starts_before_land_end(self, make_pool, tmp_path):
        snap = _snap(tmp_path, llama_checkpoint_files(n_layer=4))
        pool = make_pool()
        pool.group_bytes = 4096      # flush per layer boundary
        pool.land_delay_s = 0.05     # stretch the landing tail
        out, info = pool.generate_for(snap, "acme/a", [1, 2, 3], 2)
        entry = pool._entries[str(snap.resolve())]
        assert info["temp"] == "cold"
        assert info["decode_start_before_land_end"] is True
        assert entry.t_decode_start < entry.t_land_end
        # The decode really waited on gates rather than a full tree.
        assert entry.t_first_layer < entry.t_land_end
        assert info["ttft_s"] > 0

    def test_concurrent_hot_and_cold(self, make_pool, tmp_path):
        snap_a = _snap(tmp_path, llama_checkpoint_files(), "a")
        snap_b = _snap(tmp_path, llama_checkpoint_files(seed=1), "b")
        pool = make_pool()
        warm, _ = pool.generate_for(snap_a, "acme/a", [1, 2, 3], 4)
        pool.land_delay_s = 0.02
        results: dict = {}

        def hot():
            results["hot"] = pool.generate_for(
                snap_a, "acme/a", [1, 2, 3], 4)

        t = threading.Thread(target=hot)
        t.start()
        results["cold"] = pool.generate_for(
            snap_b, "acme/b", [1, 2, 3], 4)
        t.join(timeout=60)
        assert not t.is_alive()
        out_hot, info_hot = results["hot"]
        out_cold, info_cold = results["cold"]
        assert info_hot["temp"] == "hot"
        assert info_cold["temp"] == "cold"
        # The hot decode is undisturbed by the concurrent landing.
        np.testing.assert_array_equal(np.asarray(out_hot),
                                      np.asarray(warm))
        assert not np.array_equal(np.asarray(out_cold),
                                  np.asarray(out_hot))

    def test_land_abort_strands_no_bytes(self, make_pool, tmp_path,
                                         monkeypatch):
        """Satellite 1, pool side: a landing that dies mid-flight
        releases every array it already committed, reports state
        'error' at the gates, and a later acquire retries cleanly."""
        import zest_tpu.models.loader as loader_mod

        snap = _snap(tmp_path, llama_checkpoint_files(n_layer=4))
        pool = make_pool()
        pool.group_bytes = 4096
        real = loader_mod.commit_tensors
        calls = {"n": 0}

        def flaky(batch, *a, **kw):
            calls["n"] += 1
            if calls["n"] > 1:
                raise RuntimeError("injected mid-land fault")
            return real(batch, *a, **kw)

        with pytest.MonkeyPatch.context() as mp:
            mp.setattr(loader_mod, "commit_tensors", flaky)
            entry, _ = pool.acquire(snap, "acme/a")
            _wait_state(entry, "error")
            with pytest.raises(RuntimeError, match="landing .* failed"):
                entry.wait_for(entry.first_layer)
            pool.release(entry)
        assert calls["n"] > 1            # fault really fired mid-land
        assert entry.params == {} and entry.bytes == 0
        assert entry.committed == set()
        # Recovery: the next acquire re-lands from scratch.
        entry2, hot = pool.acquire(snap, "acme/a")
        assert entry2 is entry and hot is False
        _wait_state(entry, "resident")
        assert entry.bytes == entry.reserved
        pool.release(entry)


# ── Decode parity with the family paths ──


class TestParity:
    def test_llama_matches_family(self, make_pool, tmp_path):
        from zest_tpu.models.generate import load_generator

        snap = _snap(tmp_path, llama_checkpoint_files())
        _mt, family = load_generator(snap)
        pool = make_pool()
        for kwargs in (
            dict(),
            dict(temperature=0.8, top_k=20, seed=3),
        ):
            want = family([1, 2, 3], 6, **kwargs)
            got, _info = pool.generate_for(snap, "acme/a", [1, 2, 3], 6,
                                           **kwargs)
            np.testing.assert_array_equal(
                np.asarray(got), np.asarray(want)), kwargs

    def test_mixtral_matches_family(self, make_pool, tmp_path):
        from zest_tpu.models.generate import load_generator

        snap = _snap(tmp_path, mixtral_checkpoint_files())
        _mt, family = load_generator(snap)
        want = family([1, 2, 3], 5)
        pool = make_pool()
        got, info = pool.generate_for(snap, "acme/moe", [1, 2, 3], 5)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want))
        # Paged experts, same logits: the dense core landed, experts
        # paged on demand, and residency stayed under the 50% bound.
        ex = info["experts"]
        assert 0 < ex["residency"] < 0.5
        assert ex["page_ins"] > 0 and ex["verified"] > 0


# ── Lazy MoE expert paging ──


def _fake_expert_store(n_layer=2, n_expert=4, dim=8, seed=0):
    rng = np.random.default_rng(seed)
    store = {}
    for layer in range(n_layer):
        for e in range(n_expert):
            pre = (f"model.layers.{layer}.block_sparse_moe."
                   f"experts.{e}.")
            for leaf in ("w1", "w3", "w2"):
                store[pre + leaf + ".weight"] = rng.normal(
                    size=(dim, dim)).astype(np.float32)
    return store


class TestExpertPager:
    GROUP = 3 * 8 * 8 * 4  # three dim×dim f32 tensors

    def _pager(self, store, groups: float):
        pager = hbm_pool.ExpertPager(lambda n: store[n],
                                     int(self.GROUP * groups))
        pager.total_expert_bytes = self.GROUP * 8
        return pager

    def test_lru_bound_and_eviction(self):
        store = _fake_expert_store()
        pager = self._pager(store, 2)
        for e in range(3):
            pager.get(0, e)
        assert pager.bytes <= pager.budget_bytes
        assert pager.evictions == 1 and pager.page_ins == 3
        # (0,0) was evicted — a re-get is a page-in, not a hit.
        pager.get(0, 0)
        assert pager.page_ins == 4 and pager.hits == 0

    def test_hit_refreshes_lru(self):
        store = _fake_expert_store()
        pager = self._pager(store, 2)
        pager.get(0, 0)
        pager.get(0, 1)
        pager.get(0, 0)                 # refresh: 1 is now LRU
        assert pager.hits == 1
        pager.get(0, 2)                 # evicts (0,1), not (0,0)
        pager.get(0, 0)
        assert pager.hits == 2

    def test_single_over_budget_group_serves(self):
        store = _fake_expert_store()
        pager = self._pager(store, 0.5)  # budget < one group
        grp = pager.get(0, 0)
        assert set(grp) == {"w1", "w3", "w2"}
        assert pager.bytes == self.GROUP  # admitted despite overshoot
        assert pager.stats()["residency"] == pytest.approx(1 / 8)

    def test_corrupt_page_in_refused(self):
        store = _fake_expert_store()
        pager = self._pager(store, 2)
        grp = pager.get(0, 0)
        np.testing.assert_array_equal(
            np.asarray(grp["w1"]),
            store["model.layers.0.block_sparse_moe.experts.0"
                  ".w1.weight"])
        # Flip bytes on "disk", then force a re-read (evict the group).
        store["model.layers.0.block_sparse_moe.experts.0"
              ".w1.weight"][0, 0] += 1.0
        pager.clear()
        with pytest.raises(RuntimeError, match="changed on disk"):
            pager.get(0, 0)
        corrupt = {lbl.get("outcome"): v for lbl, v in _samples(
            "zest_hbm_pool_expert_pages_total")}
        assert corrupt.get("corrupt") == 1

    def test_routed_miss_pages_in_through_pool(self, make_pool,
                                               tmp_path):
        snap = _snap(tmp_path, mixtral_checkpoint_files())
        pool = make_pool()
        _out, info = pool.generate_for(snap, "acme/moe", [1, 2, 3], 4)
        entry = pool._entries[str(snap.resolve())]
        pager = entry.pager
        assert pager is not None
        assert pager.bytes <= pager.budget_bytes
        assert pager.stats()["residency"] < 0.5
        # Expert bytes count against the pool, dense core excluded
        # from expected.
        assert entry.hbm_bytes == entry.bytes + pager.bytes
        assert not any(hbm_pool._is_expert_name(n)
                       for n in entry.expected)
        outcomes = {lbl.get("outcome"): v for lbl, v in _samples(
            "zest_hbm_pool_expert_pages_total")}
        assert outcomes.get("miss", 0) == pager.page_ins > 0


# ── Knob-off: bit-for-bit single-model behavior ──


class TestKnobOff:
    def test_pool_none_when_disabled(self, tmp_path):
        cfg = _cfg(tmp_path, hbm_pool_enabled=False)
        assert hbm_pool.pool(cfg) is None

    def test_http_payload_schema_identity(self, tmp_path):
        from zest_tpu.api.http_api import HttpApi

        api_on = HttpApi(_cfg(tmp_path))
        api_off = HttpApi(_cfg(tmp_path, hbm_pool_enabled=False))
        try:
            on, off = api_on.status_payload(), api_off.status_payload()
            assert "hbm_pool" in on and "hbm_pool" not in off
            assert set(on) - {"hbm_pool"} == set(off)
            mon, moff = (api_on.models_payload(),
                         api_off.models_payload())
            assert "resident" in mon and set(moff) == {"models"}
        finally:
            api_on.close()
            api_off.close()

    def test_generate_path_bit_identical(self, tmp_path):
        from zest_tpu.api.http_api import HttpApi

        snap = _snap(tmp_path, llama_checkpoint_files())
        api_on = HttpApi(_cfg(tmp_path))
        api_off = HttpApi(_cfg(tmp_path, hbm_pool_enabled=False))
        try:
            mt_on, gen_on, info = api_on._decode_path(snap, "acme/a")
            mt_off, gen_off, none = api_off._decode_path(snap, "acme/a")
            assert mt_on == mt_off == "llama"
            assert info is not None and none is None
            out_on = gen_on([1, 2, 3], 6)
            out_off = gen_off([1, 2, 3], 6)
            np.testing.assert_array_equal(np.asarray(out_on),
                                          np.asarray(out_off))
            assert info["temp"] == "cold"
        finally:
            api_on.close()
            api_off.close()

    def test_streamed_done_carries_pool_info(self, tmp_path):
        from zest_tpu.api.http_api import HttpApi

        snap = _snap(tmp_path, llama_checkpoint_files())
        api = HttpApi(_cfg(tmp_path))
        try:
            mt, gen, info = api._decode_path(snap, "acme/a")
            kwargs = dict(temperature=0.0, top_k=None, top_p=None,
                          seed=0, stop_at_eos=True)
            evs = list(api._streamed_decode(gen, mt, [1, 2, 3], 4,
                                            None, kwargs,
                                            pool_info=info))
            assert [e["event"] for e in evs] == ["token"] * 4 + ["done"]
            assert evs[-1]["pool"]["temp"] == "cold"
        finally:
            api.close()


# ── Observability: metrics, SLO, CLI ──


class TestObservability:
    def test_metrics_and_timeline(self, make_pool, tmp_path):
        snap = _snap(tmp_path, llama_checkpoint_files())
        pool = make_pool()
        pool.generate_for(snap, "acme/a", [1, 2, 3], 3)
        states = {lbl.get("state"): v
                  for lbl, v in _samples("zest_hbm_pool_bytes")}
        assert set(states) == {"pinned", "resident"}
        assert states["pinned"] == 0       # decode finished, unpinned
        assert states["resident"] > 0
        ttft = _samples("zest_ttft_seconds")
        assert any(lbl.get("temp") == "cold" for lbl, _v in ttft)
        # Timeline probes registered by the pool (replace semantics).
        assert timeline.STORE is not None
        row = pool.summary()
        assert row["models"][0]["state"] == "resident"
        assert row["enabled"] is True

    def test_ttft_slo_breach(self, make_pool, tmp_path):
        snap = _snap(tmp_path, llama_checkpoint_files())
        pool = make_pool(slo_ttft_s=1e-6)   # impossible budget
        pool.generate_for(snap, "acme/a", [1, 2, 3], 2)
        breaches = {lbl.get("slo"): v
                    for lbl, v in _samples("zest_slo_breaches_total")}
        assert breaches.get("ttft") == 1
        burn = telemetry.session.SESSIONS.slo_burn()
        assert burn["ttft"]["breaches"] == 1
        assert burn["ttft"]["burn"] == 1.0

    def test_cli_models_resident(self, make_pool, tmp_path,
                                 monkeypatch, capsys):
        from types import SimpleNamespace

        from zest_tpu import cli

        rows = [{"repo": "acme/a", "state": "resident",
                 "bytes": 1048576, "pins": 0, "lands": 1,
                 "gate_stall_s": 0.0,
                 "experts": {"residency": 0.375}}]
        monkeypatch.setattr(
            cli, "_daemon_get",
            lambda cfg, path, timeout=2.0: {"models": [],
                                            "resident": rows})
        rc = cli.cmd_models(SimpleNamespace(json=False, resident=True))
        out = capsys.readouterr().out
        assert rc == 0
        assert "acme/a" in out and "resident" in out
        assert "experts 38%" in out

        rc = cli.cmd_models(SimpleNamespace(json=True, resident=True))
        assert rc == 0
        assert json.loads(capsys.readouterr().out) == {"resident": rows}

    def test_cli_models_resident_no_daemon(self, monkeypatch, capsys):
        from types import SimpleNamespace

        from zest_tpu import cli

        monkeypatch.setattr(cli, "_daemon_get",
                            lambda cfg, path, timeout=2.0: None)
        rc = cli.cmd_models(SimpleNamespace(json=False, resident=True))
        assert rc == 1
        assert "no HBM pool state" in capsys.readouterr().err


# ── Remediation rules (pool thrash → shed, gate stall → rush) ──


class TestRemediation:
    def _engine(self):
        assert remediate.ensure_started()
        return remediate.ENGINE

    def test_stall_growth_arms_rush(self):
        eng = self._engine()
        fired = []
        remediate.register_target("pool_land",
                                  lambda cmd: fired.append(cmd) or True)
        timeline.post("hbm_pool.gate_stall_s", 0.5)
        timeline.post("hbm_pool.landing", 1.0)
        timeline.STORE.tick()
        eng._pool_rules(timeline.STORE, time.monotonic())
        assert fired == []                   # first tick: baseline only
        timeline.post("hbm_pool.gate_stall_s", 2.0)
        timeline.STORE.tick()
        eng._pool_rules(timeline.STORE, time.monotonic())
        assert fired == ["rush"]
        counts = remediate.payload()["counts"].get("hedge", {})
        assert counts.get("success", 0) == 1

    def test_eviction_growth_sheds(self):
        eng = self._engine()
        fired = []
        remediate.register_target("pool_shed",
                                  lambda cmd: fired.append(cmd) or True)
        timeline.post("hbm_pool.evictions", 1.0)
        timeline.STORE.tick()
        eng._pool_rules(timeline.STORE, time.monotonic())
        timeline.post("hbm_pool.evictions", 3.0)
        timeline.STORE.tick()
        eng._pool_rules(timeline.STORE, time.monotonic())
        assert fired == ["shed_coldest"]
        counts = remediate.payload()["counts"].get("shed", {})
        assert counts.get("success", 0) == 1

    def test_steady_state_no_action(self):
        eng = self._engine()
        fired = []
        remediate.register_target("pool_land",
                                  lambda cmd: fired.append(cmd) or True)
        remediate.register_target("pool_shed",
                                  lambda cmd: fired.append(cmd) or True)
        for _ in range(3):
            timeline.post("hbm_pool.gate_stall_s", 1.0)
            timeline.post("hbm_pool.evictions", 2.0)
            timeline.post("hbm_pool.landing", 0.0)
            timeline.STORE.tick()
            eng._pool_rules(timeline.STORE, time.monotonic())
        assert fired == []

    def test_pool_rush_target(self, make_pool):
        pool = make_pool()
        assert pool._land_cmd("rush") is True
        assert pool._rush.is_set()
        assert pool._land_cmd("unknown") is False
        assert pool._shed_cmd("shed_coldest") is False  # empty pool


# ── Satellite 1, loader side: aborted streaming landing cleanup ──


class TestLoaderAbortCleanup:
    def test_aborted_streaming_land_releases_arrays(self, tmp_path):
        from zest_tpu.models.loader import stage_cached_to_hbm
        from zest_tpu.transfer.bridge import XetBridge
        from zest_tpu.transfer.pod import fetch_file_header, pod_round

        files = llama_checkpoint_files(n_layer=4)
        repo = FixtureRepo("acme/tiny-llama", files, chunks_per_xorb=2)
        with FixtureHub(repo) as hub:
            cfg = Config(hf_home=tmp_path / "hf",
                         cache_dir=tmp_path / "zest",
                         hf_token="hf_test", endpoint=hub.url)
            bridge = XetBridge(cfg)
            bridge.authenticate("acme/tiny-llama")
            frepo = hub.repos["acme/tiny-llama"]
            rec = frepo.reconstructions[
                frepo.files["model.safetensors"].xet_hash]
            pod_round(bridge, [rec])
            header = fetch_file_header(bridge, rec)

            def gate(_i, name, _cancel):
                if name.startswith("model.layers.2."):
                    raise RuntimeError("injected abort at layer 2")

            base = sum(int(a.nbytes) for a in jax.live_arrays())
            with pytest.raises(RuntimeError, match="injected abort"):
                stage_cached_to_hbm(bridge, [(rec, header)],
                                    stream=True, tensor_gate=gate)
            # The committed prefix (embeddings + early layers) was
            # deleted by the abort path — no stranded partial tree.
            after = sum(int(a.nbytes) for a in jax.live_arrays())
            assert after - base < 64 * 1024, \
                f"stranded {after - base} HBM bytes after abort"

            # The cache is intact: a clean landing still round-trips.
            params, stats = stage_cached_to_hbm(bridge, [(rec, header)],
                                                stream=True)
            assert stats["streamed"] is True
            emb = np.frombuffer(
                np.asarray(params["model.embed_tokens.weight"])
                .tobytes(), np.float32)
            assert emb.size == 256 * 64
            for arr in params.values():
                arr.delete()
