"""End-to-end pull tests against the loopback fixture hub.

Tier-1 integration (the reference's verify-model.sh analog): pull a repo
CDN-only into an isolated HF_HOME, verify bytes, verify refs, verify
idempotent re-pull, and verify every cached xorb is seedable.
"""

import os

import pytest

from zest_tpu import storage
from zest_tpu.config import Config
from zest_tpu.transfer.pull import pull_model

from fixtures import FixtureHub, FixtureRepo

FILES = {
    "config.json": b'{"architectures": ["TestModel"], "model_type": "test"}',
    "model.safetensors": os.urandom(700_000),
    "tokenizer.json": b'{"tok": 1}' * 50,
}


@pytest.fixture(scope="module")
def hub():
    repo = FixtureRepo("acme/e2e-model", FILES, chunks_per_xorb=3)
    with FixtureHub(repo) as h:
        yield h


@pytest.fixture
def cfg(hub, tmp_path):
    return Config(
        hf_home=tmp_path / "hf",
        cache_dir=tmp_path / "zest",
        hf_token="hf_test",
        endpoint=hub.url,
    )


def test_cdn_only_pull(cfg, hub):
    result = pull_model(cfg, "acme/e2e-model", no_p2p=True)
    snap = result.snapshot_dir
    for name, data in FILES.items():
        assert (snap / name).read_bytes() == data, f"{name} corrupt"
    # refs written for offline from_pretrained resolution
    assert storage.read_ref(cfg, "acme/e2e-model", "main") == \
        hub.repos["acme/e2e-model"].commit_sha
    # all bytes came from CDN, none from peers
    assert result.stats["fetch"]["bytes"]["cdn"] > 0
    assert result.stats["fetch"]["bytes"]["peer"] == 0
    assert result.stats["files_downloaded"] == len(FILES)
    # per-stage tracing: the plain pull times resolve + file writes, and
    # the stage sum never exceeds the total (stages are non-overlapping
    # sections of the one pull thread)
    stages = result.stats["stages"]
    assert stages["resolve"] >= 0 and stages["files"] >= 0
    assert sum(stages.values()) <= result.stats["elapsed_s"] + 0.05


def test_repull_skips_and_hits_cache(cfg):
    pull_model(cfg, "acme/e2e-model", no_p2p=True)
    again = pull_model(cfg, "acme/e2e-model", no_p2p=True)
    assert again.stats["files_downloaded"] == 0
    assert again.stats["files_skipped"] == len(FILES)
    assert again.stats["fetch"]["bytes"]["cdn"] == 0


def test_corrupt_local_file_repulled(cfg):
    first = pull_model(cfg, "acme/e2e-model", no_p2p=True)
    target = first.snapshot_dir / "model.safetensors"
    target.write_bytes(b"truncated garbage")  # wrong size -> not skipped
    result = pull_model(cfg, "acme/e2e-model", no_p2p=True)
    assert target.read_bytes() == FILES["model.safetensors"]
    assert result.stats["files_downloaded"] == 1


def test_every_cached_xorb_is_seedable(cfg):
    """After a pull, the xorb cache must hold parseable blobs covering the
    model — the 'package IS the seeder' invariant."""
    from zest_tpu.cas.xorb import XorbReader
    from zest_tpu.cas import hashing

    pull_model(cfg, "acme/e2e-model", no_p2p=True)
    cached = storage.list_cached_xorbs(cfg)
    assert cached, "nothing cached for seeding"
    cache = storage.XorbCache(cfg)
    for hex_key in cached:
        reader = XorbReader(cache.get(hex_key))
        assert len(reader) > 0
        assert hashing.hash_to_hex(reader.xorb_hash()) == hex_key


def test_pull_unknown_repo_raises(cfg):
    from zest_tpu.cas.hub import HubError

    with pytest.raises(HubError):
        pull_model(cfg, "acme/does-not-exist", no_p2p=True)


def test_sequential_fallback_when_parallel_breaks(cfg, monkeypatch):
    """Break the parallel downloader; the 3-deep chain must still deliver
    correct bytes via the sequential bridge (reference: main.zig:232-256)."""
    from zest_tpu.transfer.parallel import ParallelDownloader

    def explode(self, *a, **k):
        raise RuntimeError("injected parallel failure")

    monkeypatch.setattr(ParallelDownloader, "reconstruct_to_file", explode)
    logged = []
    result = pull_model(cfg, "acme/e2e-model", no_p2p=True,
                        log=lambda *a, **k: logged.append(a))
    snap = result.snapshot_dir
    assert (snap / "model.safetensors").read_bytes() == FILES["model.safetensors"]
    assert any("injected parallel failure" in str(line) for line in logged)


def test_cache_direct_file_write(cfg, hub):
    """The files-stage fast lane: with every unit cached (post-warm
    state), the file is decoded straight from the cache into an mmapped
    destination — byte-exact, counted as cache-tier bytes; with a cold
    cache it reports False and leaves nothing behind."""
    from zest_tpu.cas.hub import HubClient
    from zest_tpu.transfer.bridge import XetBridge
    from zest_tpu.transfer.federated import warm_units_parallel
    from zest_tpu.transfer.pull import _write_file_from_cache

    bridge = XetBridge(cfg)
    bridge.authenticate("acme/e2e-model")
    entry = next(e for e in HubClient(cfg).list_files("acme/e2e-model")
                 if e.path == "model.safetensors")
    dest = cfg.hf_home / "out.safetensors"

    # Cold cache: clean miss, no artifact, no exception.
    assert _write_file_from_cache(bridge, entry.xet_hash, dest) is False
    assert not dest.exists()
    assert not list(dest.parent.glob(".tmp-*"))

    rec = bridge.get_reconstruction(entry.xet_hash)
    warm_units_parallel(bridge, [rec])
    before_cache = bridge.stats.xorbs_from_cache
    assert _write_file_from_cache(bridge, entry.xet_hash, dest) is True
    assert dest.read_bytes() == FILES["model.safetensors"]
    assert bridge.stats.xorbs_from_cache > before_cache


def test_warm_pull_takes_cache_direct_lane(cfg, hub, monkeypatch):
    """A device=tpu pull (warm stage fills the cache first) must write
    its files through the fast lane — the parallel downloader is never
    invoked — and still produce a byte-exact snapshot."""
    import zest_tpu.transfer.parallel as par_mod

    def boom(*a, **k):
        raise AssertionError("waterfall chain ran despite warm cache")

    monkeypatch.setattr(par_mod.ParallelDownloader,
                        "reconstruct_to_file", boom)
    result = pull_model(cfg, "acme/e2e-model", device="tpu", no_p2p=True,
                        log=lambda *a, **k: None)
    for name, data in FILES.items():
        assert (result.snapshot_dir / name).read_bytes() == data
