"""End-to-end pull tests against the loopback fixture hub.

Tier-1 integration (the reference's verify-model.sh analog): pull a repo
CDN-only into an isolated HF_HOME, verify bytes, verify refs, verify
idempotent re-pull, and verify every cached xorb is seedable.
"""

import os

import pytest

from zest_tpu import storage
from zest_tpu.config import Config
from zest_tpu.transfer.pull import pull_model

from fixtures import FixtureHub, FixtureRepo

FILES = {
    "config.json": b'{"architectures": ["TestModel"], "model_type": "test"}',
    "model.safetensors": os.urandom(700_000),
    "tokenizer.json": b'{"tok": 1}' * 50,
}


@pytest.fixture(scope="module")
def hub():
    repo = FixtureRepo("acme/e2e-model", FILES, chunks_per_xorb=3)
    with FixtureHub(repo) as h:
        yield h


@pytest.fixture
def cfg(hub, tmp_path):
    return Config(
        hf_home=tmp_path / "hf",
        cache_dir=tmp_path / "zest",
        hf_token="hf_test",
        endpoint=hub.url,
    )


def test_cdn_only_pull(cfg, hub):
    result = pull_model(cfg, "acme/e2e-model", no_p2p=True)
    snap = result.snapshot_dir
    for name, data in FILES.items():
        assert (snap / name).read_bytes() == data, f"{name} corrupt"
    # refs written for offline from_pretrained resolution
    assert storage.read_ref(cfg, "acme/e2e-model", "main") == \
        hub.repos["acme/e2e-model"].commit_sha
    # all bytes came from CDN, none from peers
    assert result.stats["fetch"]["bytes"]["cdn"] > 0
    assert result.stats["fetch"]["bytes"]["peer"] == 0
    assert result.stats["files_downloaded"] == len(FILES)
    # per-stage tracing: the plain pull times resolve + file writes, and
    # the stage sum never exceeds the total (stages are non-overlapping
    # sections of the one pull thread)
    stages = result.stats["stages"]
    assert stages["resolve"] >= 0 and stages["files"] >= 0
    assert sum(stages.values()) <= result.stats["elapsed_s"] + 0.05


def test_repull_skips_and_hits_cache(cfg):
    pull_model(cfg, "acme/e2e-model", no_p2p=True)
    again = pull_model(cfg, "acme/e2e-model", no_p2p=True)
    assert again.stats["files_downloaded"] == 0
    assert again.stats["files_skipped"] == len(FILES)
    assert again.stats["fetch"]["bytes"]["cdn"] == 0


def test_corrupt_local_file_repulled(cfg):
    first = pull_model(cfg, "acme/e2e-model", no_p2p=True)
    target = first.snapshot_dir / "model.safetensors"
    target.write_bytes(b"truncated garbage")  # wrong size -> not skipped
    result = pull_model(cfg, "acme/e2e-model", no_p2p=True)
    assert target.read_bytes() == FILES["model.safetensors"]
    assert result.stats["files_downloaded"] == 1


def test_every_cached_xorb_is_seedable(cfg):
    """After a pull, the xorb cache must hold parseable blobs covering the
    model — the 'package IS the seeder' invariant."""
    from zest_tpu.cas.xorb import XorbReader
    from zest_tpu.cas import hashing

    pull_model(cfg, "acme/e2e-model", no_p2p=True)
    cached = storage.list_cached_xorbs(cfg)
    assert cached, "nothing cached for seeding"
    cache = storage.XorbCache(cfg)
    for hex_key in cached:
        reader = XorbReader(cache.get(hex_key))
        assert len(reader) > 0
        assert hashing.hash_to_hex(reader.xorb_hash()) == hex_key


def test_pull_unknown_repo_raises(cfg):
    from zest_tpu.cas.hub import HubError

    with pytest.raises(HubError):
        pull_model(cfg, "acme/does-not-exist", no_p2p=True)


def test_sequential_fallback_when_parallel_breaks(cfg, monkeypatch):
    """Break the parallel downloader; the 3-deep chain must still deliver
    correct bytes via the sequential bridge (reference: main.zig:232-256)."""
    from zest_tpu.transfer.parallel import ParallelDownloader

    def explode(self, *a, **k):
        raise RuntimeError("injected parallel failure")

    monkeypatch.setattr(ParallelDownloader, "reconstruct_to_file", explode)
    logged = []
    result = pull_model(cfg, "acme/e2e-model", no_p2p=True,
                        log=lambda *a, **k: logged.append(a))
    snap = result.snapshot_dir
    assert (snap / "model.safetensors").read_bytes() == FILES["model.safetensors"]
    assert any("injected parallel failure" in str(line) for line in logged)


def test_cache_direct_file_write(cfg, hub):
    """The files-stage fast lane: with every unit cached (post-warm
    state), the file is decoded straight from the cache into an mmapped
    destination — byte-exact, counted as cache-tier bytes; with a cold
    cache it reports False and leaves nothing behind."""
    from zest_tpu.cas.hub import HubClient
    from zest_tpu.transfer.bridge import XetBridge
    from zest_tpu.transfer.federated import warm_units_parallel
    from zest_tpu.transfer.pull import _write_file_from_cache

    bridge = XetBridge(cfg)
    bridge.authenticate("acme/e2e-model")
    entry = next(e for e in HubClient(cfg).list_files("acme/e2e-model")
                 if e.path == "model.safetensors")
    dest = cfg.hf_home / "out.safetensors"

    # Cold cache: clean miss, no artifact, no exception.
    assert _write_file_from_cache(bridge, entry.xet_hash, dest) is False
    assert not dest.exists()
    assert not list(dest.parent.glob(".tmp-*"))

    rec = bridge.get_reconstruction(entry.xet_hash)
    warm_units_parallel(bridge, [rec])
    before_cache = bridge.stats.xorbs_from_cache
    assert _write_file_from_cache(bridge, entry.xet_hash, dest) is True
    assert dest.read_bytes() == FILES["model.safetensors"]
    assert bridge.stats.xorbs_from_cache > before_cache


def test_warm_pull_takes_cache_direct_lane(cfg, hub, monkeypatch):
    """A device=tpu pull (warm stage fills the cache first) must write
    its files through the fast lane — the parallel downloader is never
    invoked — and still produce a byte-exact snapshot."""
    import zest_tpu.transfer.parallel as par_mod

    def boom(*a, **k):
        raise AssertionError("waterfall chain ran despite warm cache")

    monkeypatch.setattr(par_mod.ParallelDownloader,
                        "reconstruct_to_file", boom)
    result = pull_model(cfg, "acme/e2e-model", device="tpu", no_p2p=True,
                        log=lambda *a, **k: None)
    for name, data in FILES.items():
        assert (result.snapshot_dir / name).read_bytes() == data


def test_direct_landing_pipelines_shards(tmp_path):
    """Multi-shard direct landing: shard i+1's warm fetch overlaps
    shard i's decode+commit (one-shard lookahead), every shard still
    lands and writes byte-exact."""
    import numpy as np

    from zest_tpu.models.safetensors_io import write_safetensors

    rng = np.random.default_rng(9)
    shard_files = {}
    for i in (1, 2, 3):
        p = tmp_path / f"s{i}.safetensors"
        # Big enough that each shard spans several xorbs — the header
        # fetch caches only the head term, leaving real work for the
        # pipelined warm fetch (tiny shards are fully cached by the
        # header fetch and warm bytes is rightly 0).
        write_safetensors(p, {f"t{i}.weight":
                              rng.standard_normal((512, 512)).astype("f4")})
        shard_files[f"model-{i:05d}-of-00003.safetensors"] = p.read_bytes()
    repo = FixtureRepo("acme/sharded", {
        "config.json": b'{"model_type": "test"}', **shard_files,
    }, chunks_per_xorb=3)
    with FixtureHub(repo) as hub:
        cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                     hf_token="hf_test", endpoint=hub.url)
        # pod=False: skip the collective pre-pass so the pipelined warm
        # fetch is what actually moves the bytes (with the pod round on,
        # everything is already cached and warm bytes is rightly 0).
        res = pull_model(cfg, "acme/sharded", device="tpu", pod=False,
                         no_p2p=True, log=lambda *a, **k: None)
    warm = res.stats["hbm"]["warm"]
    assert warm["pipelined_shards"] == 3
    assert warm["failed"] == 0 and warm["bytes"] > 0
    assert res.stats["hbm"]["direct"] is True
    for name, data in shard_files.items():
        assert (res.snapshot_dir / name).read_bytes() == data


def test_cross_shard_dedup_keeps_partial_key(tmp_path):
    """A xorb deduped across shards, warmed from the shard that covers
    only its head chunks, must be cached under a PARTIAL key — a
    truncated blob under the full key would shadow other shards'
    entries and be announced as a seedable complete xorb.

    The fixture encoder only emits whole-xorb references, so the
    cross-shard topology (one shard's fetch_info = a head chunk range
    of a xorb another shard reads past) is hand-built here, the way the
    production CAS emits it for deduped prefixes."""
    import numpy as np

    from fixtures import _XorbFixture
    from zest_tpu.cas import hashing, reconstruction as recon
    from zest_tpu.cas.xorb import XorbBuilder
    from zest_tpu.transfer.bridge import XetBridge
    from zest_tpu.transfer.federated import _entries_by_hash, warm_units_parallel

    repo = FixtureRepo("acme/dedup-shards", {"f.bin": b"x" * 1000})
    builder = XorbBuilder()
    rng = np.random.default_rng(3)
    chunks = [rng.integers(0, 256, 30_000, dtype=np.uint8).tobytes()
              for _ in range(6)]
    for c in chunks:
        builder.add_chunk(c)
    xh = builder.xorb_hash()
    xh_hex = hashing.hash_to_hex(xh)
    offs = builder.frame_offsets()

    def rec_for(n_chunks, salt):
        fh = hashing.blake3_hash(salt)
        return recon.Reconstruction(
            file_hash=fh,
            terms=[recon.Term(xorb_hash=xh,
                              range=recon.ChunkRange(0, n_chunks),
                              unpacked_length=sum(
                                  len(c) for c in chunks[:n_chunks]))],
            fetch_info={xh_hex: [recon.FetchInfo(
                url=f"/xorbs/{xh_hex}", url_range_start=0,
                url_range_end=offs[n_chunks],
                range=recon.ChunkRange(0, n_chunks))]},
        )

    rec_pre, rec_full = rec_for(3, b"pre"), rec_for(6, b"full")
    with FixtureHub(repo) as hub:
        hub.repos["acme/dedup-shards"].xorbs[xh_hex] = _XorbFixture(
            xh_hex, builder.serialize(), offs, builder.serialize_full())
        cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                     hf_token="hf_test", endpoint=hub.url)
        bridge = XetBridge(cfg)
        bridge.authenticate("acme/dedup-shards")

        # Warm ONLY the prefix shard — per-shard, as the pipelined
        # landing does — with whole-checkpoint evidence: X has two
        # entries there, so the 3-chunk blob must take a partial key.
        evidence = _entries_by_hash([rec_full, rec_pre])
        warm_units_parallel(bridge, [rec_pre], entries_map=evidence)
        assert not bridge.cache.has(xh_hex), \
            "truncated blob cached under the full xorb key"
        assert bridge.cache.get(f"{xh_hex}.0") is not None

        # The full shard still fetches its 6 chunks and both shards
        # extract byte-exact afterwards.
        warm_units_parallel(bridge, [rec_full], entries_map=evidence)
        got_pre = bridge.fetch_unit(xh_hex, rec_pre.fetch_info[xh_hex][0])
        got_full = bridge.fetch_unit(xh_hex, rec_full.fetch_info[xh_hex][0])
        from zest_tpu.cas.xorb import XorbReader

        assert XorbReader(got_pre).extract_chunk_range(0, 3) == \
            b"".join(chunks[:3])
        assert XorbReader(got_full).extract_chunk_range(0, 6) == \
            b"".join(chunks)


def test_bridge_fallback_uses_cross_file_evidence(tmp_path):
    """The per-term waterfall (the landing's designated fallback when a
    shard's warm prefetch fails) must judge full-vs-partial against
    every reconstruction the bridge has resolved, not just the term's
    own file: a xorb deduped across files looks whole from the prefix
    file's fetch_info (single entry at chunk 0) while another file
    reads past it. Companion to test_cross_shard_dedup_keeps_partial_key,
    which covers the warm path."""
    import numpy as np

    from fixtures import _XorbFixture
    from zest_tpu.cas import hashing, reconstruction as recon
    from zest_tpu.cas.xorb import XorbBuilder
    from zest_tpu.transfer.bridge import XetBridge

    repo = FixtureRepo("acme/dedup-fallback", {"f.bin": b"x" * 1000})
    builder = XorbBuilder()
    rng = np.random.default_rng(5)
    chunks = [rng.integers(0, 256, 30_000, dtype=np.uint8).tobytes()
              for _ in range(6)]
    for c in chunks:
        builder.add_chunk(c)
    xh = builder.xorb_hash()
    xh_hex = hashing.hash_to_hex(xh)
    offs = builder.frame_offsets()

    def rec_for(start, end, salt):
        fh = hashing.blake3_hash(salt)
        return recon.Reconstruction(
            file_hash=fh,
            terms=[recon.Term(xorb_hash=xh,
                              range=recon.ChunkRange(start, end),
                              unpacked_length=sum(
                                  len(c) for c in chunks[start:end]))],
            fetch_info={xh_hex: [recon.FetchInfo(
                url=f"/xorbs/{xh_hex}", url_range_start=offs[start],
                url_range_end=offs[end],
                range=recon.ChunkRange(start, end))]},
        )

    rec_pre = rec_for(0, 3, b"pre")
    rec_tail = rec_for(3, 6, b"tail")
    with FixtureHub(repo) as hub:
        hub.repos["acme/dedup-fallback"].xorbs[xh_hex] = _XorbFixture(
            xh_hex, builder.serialize(), offs, builder.serialize_full())
        cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest",
                     hf_token="hf_test", endpoint=hub.url)
        bridge = XetBridge(cfg)
        bridge.authenticate("acme/dedup-fallback")
        # The pull resolves every file's reconstruction up front (memoized
        # in get_reconstruction); model that state directly.
        bridge._recons[hashing.hash_to_hex(rec_tail.file_hash)] = rec_tail

        data = bridge.fetch_term(rec_pre.terms[0], rec_pre)
        assert data == b"".join(chunks[:3])
        assert not bridge.cache.has(xh_hex), \
            "truncated blob cached under the full xorb key"
        assert bridge.cache.get(f"{xh_hex}.0") is not None


def test_provably_whole_dedupes_identical_references():
    """Two files referencing the SAME whole-xorb range must still count
    as whole-xorb evidence (the merged cross-file entry list holds two
    identical ranges; a naive len(entries)==1 check would wrongly
    downgrade the blob to a partial key and break seeding)."""
    from zest_tpu.cas import reconstruction as recon
    from zest_tpu.transfer.bridge import provably_whole

    whole = recon.FetchInfo(url="/x", url_range_start=0, url_range_end=100,
                            range=recon.ChunkRange(0, 6))
    dup = recon.FetchInfo(url="/x", url_range_start=0, url_range_end=100,
                          range=recon.ChunkRange(0, 6))
    tail = recon.FetchInfo(url="/x", url_range_start=50, url_range_end=100,
                           range=recon.ChunkRange(3, 6))
    assert provably_whole([whole, dup], chunk_offset=0)
    assert not provably_whole([whole, tail], chunk_offset=0)
    assert not provably_whole([whole], chunk_offset=3)
    assert not provably_whole([], chunk_offset=0)
