"""Transport/schedule split + lossy tier (transfer.transport,
transfer.lossy; ISSUE 20).

Covers the ISSUE-20 acceptance surface:

- the shared transport-conformance suite, run against all three
  backends (dcn wire, in-process loopback, jax ICI lanes): tagged
  window round-trip with byte-identical payloads, NOT_FOUND for
  unknown hashes, abort on a mid-phase ``dcn_reset``, clock-offset
  reporting, and plan-fingerprint lane agreement for the jax backend;
- the ``ZEST_COLLECTIVE_BACKEND=dcn`` restore-pre-split pin: the
  round stats schema is bit-for-bit PR-13's (no ``backend`` key, the
  exact exchange key set) and every window the transport issues hits
  ``DcnPool.request_many`` with exactly the pre-split argument shape
  (no ``flags`` kwarg) — plus a golden-bytes pin on the default
  REQUEST wire encoding;
- strict env parsing for both knobs (typos raise, never fall back);
- the ZQLS lossy codec: bounded per-block quantization error,
  declines on non-float/already-byte-cheap blobs, exact_len
  round-trip;
- the lossy serving tier: byte-exact by default, quantizes fresh
  cache data only when invited (FLAG_QUANT_OK), forwards a staged
  container only to a requester that opted in (FLAG_LOSSY_OK);
- lossy end-to-end: a cross-slice round lands quantized payloads
  HBM-only (staging populated, not one ZQLS byte in the xorb cache),
  reports ``lossy_bytes``/``bits_saved_ratio``, bounds the landed
  float error, and byte-exact needs refetch through the verified
  waterfall;
- the preadv cold-read lane: batched stored-scheme reads land bytes
  identical to the decode path, and the lane actually engages.
"""

from __future__ import annotations

import struct
import threading

import numpy as np
import pytest

from fixtures import FixtureHub, FixtureRepo

from zest_tpu import faults
from zest_tpu.cas import hashing
from zest_tpu.cas.hub import HubClient
from zest_tpu.config import Config
from zest_tpu.models.direct import CachedFileReader, DirectLandingError
from zest_tpu.transfer import lossy
from zest_tpu.transfer.coop import CoopPlan, coop_round
from zest_tpu.transfer.dcn import (
    FLAG_LOSSY,
    FLAG_LOSSY_OK,
    FLAG_QUANT_OK,
    DcnNotFound,
    DcnPool,
    DcnRequest,
    DcnResponse,
    DcnServer,
    encode_message,
    serve_chunk_range,
)
from zest_tpu.transfer.federated import warm_units_parallel
from zest_tpu.transfer.transport import (
    LINK_ICI,
    TransportUnavailable,
    make_transport,
    register_loopback,
    reset_loopback,
)

REPO_ID = "acme/transport-model"

# weights.bin: random-normal float32 — BG4-compressible, the shape the
# lossy tier targets. blob.bin: incompressible bytes — every chunk
# lands stored-scheme (Scheme.NONE), the shape the preadv lane
# targets. config.json: the tiny non-float file that must always ship
# byte-exact.
_RNG = np.random.default_rng(11)
_FLOATS = _RNG.standard_normal(300_000).astype("<f4")
FILES = {
    "config.json": b'{"model_type": "transport"}',
    "weights.bin": _FLOATS.tobytes(),
    "blob.bin": _RNG.bytes(1_200_000),
}

BACKENDS = ("dcn", "loopback", "jax")

# The PR-6/PR-13 pinned stats schema (test_collective pins the
# knob-off variant; the dcn-backend pin below must match it exactly).
_TOP_KEYS = {"host", "hosts", "trace_id", "plan", "fetch", "exchange",
             "fallbacks", "own_server", "peer_served_ratio",
             "elapsed_s", "clock_offsets"}
_EX_KEYS = {"units", "wire_bytes", "unpacked_bytes", "fallback_units",
            "fallback_bytes", "verify_rejected", "retries",
            "budget_bytes", "inflight_peak_bytes"}


@pytest.fixture(scope="module")
def hub():
    repo = FixtureRepo(REPO_ID, FILES, chunks_per_xorb=2)
    with FixtureHub(repo) as h:
        yield h


@pytest.fixture(autouse=True)
def _clean_state():
    faults.reset()
    reset_loopback()
    lossy.reset_stagings()
    yield
    faults.reset()
    reset_loopback()
    lossy.reset_stagings()


def _bridge(hub, root, **cfg_kwargs):
    from zest_tpu.transfer.bridge import XetBridge

    cfg = Config(hf_home=root / "hf", cache_dir=root / "zest",
                 hf_token="hf_test", endpoint=hub.url, dcn_port=0,
                 **cfg_kwargs)
    b = XetBridge(cfg)
    b.authenticate(REPO_ID)
    return b


def _recs(bridge):
    return [bridge.get_reconstruction(e.xet_hash)
            for e in HubClient(bridge.cfg).list_files(REPO_ID)
            if e.is_xet]


def _rec_for(bridge, path):
    for e in HubClient(bridge.cfg).list_files(REPO_ID):
        if e.is_xet and e.path == path:
            return bridge.get_reconstruction(e.xet_hash)
    raise AssertionError(f"no xet file {path}")


def _units(rec):
    out = []
    for hh, entries in rec.fetch_info.items():
        for fi in entries:
            out.append((hh, fi))
    return out


# ── Shared conformance fixture: one fully-warmed owner host, served
# over a real DCN socket AND registered in the loopback fabric under
# the same address, so every backend answers the same windows. ──


@pytest.fixture
def owner(hub, tmp_path):
    b = _bridge(hub, tmp_path / "owner")
    recs = _recs(b)
    warm_units_parallel(b, recs)
    plan = CoopPlan.build(recs, 2)
    server = DcnServer(b.cfg, b.cache)
    addr = ("127.0.0.1", server.start())
    register_loopback(addr, b.cfg, b.cache)
    yield b, recs, plan, addr
    server.shutdown()
    b.close()


def _wants(bridge, rec, k=3):
    """(hash, start, end) triples for ``rec``'s first ``k`` units,
    with the expected byte-exact serve for each."""
    wants, expect = [], []
    for hh, fi in _units(rec)[:k]:
        wants.append((hashing.hex_to_hash(hh), fi.range.start,
                      fi.range.end))
        found = serve_chunk_range(bridge.cfg, bridge.cache,
                                  hashing.hex_to_hash(hh),
                                  fi.range.start, fi.range.end)
        assert found is not None, "owner cache must be warm"
        expect.append(found)
    return wants, expect


# ── Transport conformance (one suite, all three backends) ──


@pytest.mark.parametrize("backend", BACKENDS)
def test_tagged_window_roundtrip(hub, owner, backend):
    b, _recs_, plan, addr = owner
    pool = DcnPool()
    try:
        t = make_transport(backend, pool, plan=plan)
        assert t.name == backend
        wants, expect = _wants(b, _rec_for(b, "weights.bin"))
        wants.append((b"\xab" * 32, 0, 1))  # unknown hash → NOT_FOUND
        link = LINK_ICI if backend == "jax" else "dcn"
        tag = t.window_tag()
        assert 0 < tag <= 0xFFFF
        replies = t.request_window(0, addr, wants, timeout=10.0,
                                   tag=tag, link=link)
        assert len(replies) == len(wants)
        for reply, (off, blob, flags) in zip(replies, expect):
            assert isinstance(reply, DcnResponse), reply
            assert reply.chunk_offset == off
            assert reply.data == blob, "payload must survive the lane"
            assert reply.flags == flags == 0
        assert isinstance(replies[-1], DcnNotFound)
        c = t.counters
        assert c["tagged_windows"] >= 1
        assert c["untagged_windows"] == 0
        assert c["requests"] >= len(wants)
        if backend == "jax":
            assert c["ici_windows"] == 1
            assert c["ici_lane_bytes"] > 0
            assert c["ici_lane_bytes"] % t.lane_bytes == 0
            assert c["lane_overflows"] == 0
    finally:
        pool.close()


@pytest.mark.parametrize("backend", BACKENDS)
def test_abort_mid_phase_raises_connection_error(hub, owner, backend):
    b, _recs_, plan, addr = owner
    faults.install("dcn_reset:1.0", seed=1)
    pool = DcnPool()
    try:
        t = make_transport(backend, pool, plan=plan)
        wants, _ = _wants(b, _rec_for(b, "weights.bin"), k=1)
        link = LINK_ICI if backend == "jax" else "dcn"
        with pytest.raises((ConnectionError, TimeoutError, OSError)):
            t.request_window(0, addr, wants, timeout=5.0,
                             tag=t.window_tag(), link=link)
    finally:
        pool.close()
    assert faults.counters().get("dcn_reset", 0) > 0


@pytest.mark.parametrize("backend", BACKENDS)
def test_clock_offsets_shape(hub, owner, backend):
    b, _recs_, plan, addr = owner
    pool = DcnPool()
    try:
        t = make_transport(backend, pool, plan=plan)
        wants, _ = _wants(b, _rec_for(b, "weights.bin"), k=1)
        t.request_window(0, addr, wants, timeout=10.0,
                         tag=t.window_tag(),
                         link="dcn" if backend != "jax" else LINK_ICI)
        offs = t.clock_offsets()
        assert isinstance(offs, dict)
        if backend == "dcn":
            # the wire backend dialed a v2 channel → one offset sample
            assert offs, "dcn backend must report peer clock offsets"
            for row in offs.values():
                assert isinstance(row["offset_s"], float)
                assert isinstance(row["rtt_s"], float)
    finally:
        pool.close()


def test_jax_lane_width_agrees_across_hosts(hub, owner):
    """The lane width is a pure function of the fingerprint-identical
    plan: two hosts building plans from independently-ordered recs
    compile the same lane shape with zero negotiation."""
    b, recs, _plan, _addr = owner
    pool = DcnPool()
    try:
        t1 = make_transport("jax", pool, plan=CoopPlan.build(recs, 4))
        t2 = make_transport(
            "jax", pool, plan=CoopPlan.build(list(reversed(recs)), 4))
        assert t1.lane_bytes == t2.lane_bytes
        assert t1.lane_bytes % (64 * 1024) == 0
        biggest = max(fi.url_range_end - fi.url_range_start
                      for _k, fi in CoopPlan.build(recs, 4).units)
        assert t1.lane_bytes >= biggest
    finally:
        pool.close()


def test_unknown_backend_raises():
    with pytest.raises(TransportUnavailable):
        make_transport("carrier-pigeon", None)


# ── End-to-end rounds per backend ──


def _run_hosts(hub, tmp_path, n, pools=None, fabric=True, **cfg_kwargs):
    """n concurrent in-process hosts, each with its own cache, DCN
    server, and (when ``fabric``) a loopback registration under the
    same address — so dcn/loopback/jax backends all resolve."""
    bridges, servers, addrs = [], [], {}
    for i in range(n):
        b = _bridge(hub, tmp_path / f"h{i}", **cfg_kwargs)
        bridges.append(b)
        s = DcnServer(b.cfg, b.cache)
        addrs[i] = ("127.0.0.1", s.start())
        servers.append(s)
        if fabric:
            register_loopback(addrs[i], b.cfg, b.cache)
    results: list = [None] * n
    errors: list = []

    def run(i):
        try:
            kwargs = {}
            if pools and i in pools:
                kwargs["dcn_pool"] = pools[i]
            results[i] = coop_round(bridges[i], _recs(bridges[i]), i, n,
                                    addrs, server=servers[i], **kwargs)
        except Exception as exc:  # noqa: BLE001 - surfaced below
            errors.append((i, exc))

    threads = [threading.Thread(target=run, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for s in servers:
        s.shutdown()
    assert not errors, errors
    return bridges, results


def _assert_fully_cached(bridge, root):
    before = bridge.stats.bytes_from_cdn
    for e in HubClient(bridge.cfg).list_files(REPO_ID):
        if e.is_xet:
            out = root / "check.bin"
            bridge.reconstruct_to_file(e.xet_hash, out)
            assert out.read_bytes() == FILES[e.path]
    assert bridge.stats.bytes_from_cdn == before, \
        "reconstruction hit CDN: cache incomplete after the round"


@pytest.mark.parametrize("backend", ["loopback", "jax"])
def test_collective_round_end_to_end_per_backend(hub, tmp_path, backend):
    bridges, results = _run_hosts(hub, tmp_path, 4,
                                  collective_backend=backend)
    for i, (b, r) in enumerate(zip(bridges, results)):
        cx = r.get("collective")
        assert cx, r
        assert cx["backend"] == backend, cx
        assert "aborted" not in cx, cx
        assert "lossy" not in cx
        assert r["fallbacks"] == 0, r
        assert r["exchange"]["units"] > 0
        assert sum(cx["link_bytes"].values()) \
            == r["exchange"]["wire_bytes"]
        _assert_fully_cached(b, tmp_path / f"h{i}")


# ── The restore-pre-split pin (ZEST_COLLECTIVE_BACKEND=dcn) ──


class _SpyPool(DcnPool):
    """Records the exact keyword shape of every window call — the
    pre-split transport called ``request_many(host, port, wants,
    timeout=..., tag=...)`` and nothing else; any extra kwarg (flags)
    would change wire bytes for default-mode rounds."""

    def __init__(self):
        super().__init__()
        self.window_kwargs: list[dict] = []

    def request_many(self, host, port, wants, **kwargs):
        self.window_kwargs.append(dict(kwargs))
        return super().request_many(host, port, wants, **kwargs)


def test_dcn_backend_restores_pre_split_exchange(hub, tmp_path):
    """Default backend: stats schema bit-for-bit PR-13's (no backend
    or lossy keys anywhere, exact key sets) and every collective
    window reaches the pool with exactly the pre-split call shape."""
    spies = {i: _SpyPool() for i in range(2)}
    try:
        bridges, results = _run_hosts(hub, tmp_path, 2, pools=spies,
                                      fabric=False)
        for i, (b, r) in enumerate(zip(bridges, results)):
            assert set(r) == _TOP_KEYS | {"collective"}, sorted(r)
            assert set(r["exchange"]) == _EX_KEYS, sorted(r["exchange"])
            cx = r["collective"]
            assert "backend" not in cx, cx
            assert "lossy" not in cx, cx
            assert "aborted" not in cx, cx
            _assert_fully_cached(b, tmp_path / f"h{i}")
        for i, spy in spies.items():
            assert spy.window_kwargs, f"host {i} issued no windows"
            for kw in spy.window_kwargs:
                assert set(kw) == {"timeout", "tag"}, kw
                assert kw["tag"], "pre-split windows were all tagged"
    finally:
        for spy in spies.values():
            spy.close()


def test_default_request_wire_bytes_pinned():
    """Golden bytes: a default (flags=0) REQUEST encodes identically
    to the pre-ISSUE-20 header — the u8 the flag bits ride stays 0."""
    h = bytes(range(32))
    req = DcnRequest(7, h, 3, 9, tag=5)
    body = struct.pack("<32sQQ", h, 3, 9)
    assert encode_message(req) == \
        struct.pack("<BBHII", 1, 0, 5, 7, len(body)) + body
    resp = DcnResponse(7, 42, b"abc")
    assert encode_message(resp) == \
        struct.pack("<BBHII", 2, 0, 0, 7, 8 + 3) \
        + struct.pack("<Q", 42) + b"abc"


# ── Strict env parsing (satellite: typos raise) ──


def _env(tmp_path, **extra):
    base = {"HF_HOME": str(tmp_path / "hf"),
            "ZEST_CACHE_DIR": str(tmp_path / "zest")}
    base.update(extra)
    return base


def test_collective_env_defaults(tmp_path):
    cfg = Config.load(env=_env(tmp_path))
    assert cfg.collective_backend == "dcn"
    assert cfg.collective_lossy == "0"


@pytest.mark.parametrize("value", ["dcn", "jax", "loopback"])
def test_collective_backend_env_values(tmp_path, value):
    cfg = Config.load(env=_env(tmp_path,
                               ZEST_COLLECTIVE_BACKEND=value))
    assert cfg.collective_backend == value


@pytest.mark.parametrize("value", ["0", "dcn", "wan"])
def test_collective_lossy_env_values(tmp_path, value):
    cfg = Config.load(env=_env(tmp_path, ZEST_COLLECTIVE_LOSSY=value))
    assert cfg.collective_lossy == value


@pytest.mark.parametrize("knob,bad", [
    ("ZEST_COLLECTIVE_BACKEND", "jxa"),
    ("ZEST_COLLECTIVE_BACKEND", "DCN"),
    ("ZEST_COLLECTIVE_BACKEND", "1"),
    ("ZEST_COLLECTIVE_LOSSY", "yes"),
    ("ZEST_COLLECTIVE_LOSSY", "dcn,wan"),
    ("ZEST_COLLECTIVE_LOSSY", "lossy"),
])
def test_collective_env_typos_raise(tmp_path, knob, bad):
    with pytest.raises(ValueError):
        Config.load(env=_env(tmp_path, **{knob: bad}))


# ── ZQLS codec ──


def _float_frames(n_chunks=3, chunk_vals=16384, seed=3):
    from zest_tpu.cas.xorb import encode_frame

    rng = np.random.default_rng(seed)
    frames, raws = [], []
    for _ in range(n_chunks):
        raw = rng.standard_normal(chunk_vals).astype("<f4").tobytes()
        frame, _h = encode_frame(raw)
        frames.append(frame)
        raws.append(raw)
    return b"".join(frames), raws


def test_quantize_roundtrip_bounded_error():
    from zest_tpu.cas.xorb import XorbReader

    blob, raws = _float_frames()
    container = lossy.quantize_blob(blob)
    assert container is not None
    assert lossy.is_lossy_container(container)
    assert not lossy.is_lossy_container(blob)
    assert len(container) < len(blob) * 0.5, \
        "int8+scales must beat BG4 on random floats by ~2x+"
    assert lossy.exact_len(container) == len(blob)

    out = lossy.dequantize_blob(container)
    reader = XorbReader(out)
    assert len(reader) == len(raws)
    for i, raw in enumerate(raws):
        got = np.frombuffer(reader.extract_chunk(i, verify=False),
                            dtype="<f4")
        want = np.frombuffer(raw, dtype="<f4")
        assert got.shape == want.shape
        # per-block bound: chunks start block-aligned, so each
        # 256-value block's error is <= absmax(block)/127
        for s in range(0, want.size, lossy.BLOCK_VALUES):
            blk = slice(s, s + lossy.BLOCK_VALUES)
            bound = np.max(np.abs(want[blk])) / 127.0 + 1e-6
            assert np.max(np.abs(got[blk] - want[blk])) <= bound


def test_quantize_declines_non_float_blobs():
    from zest_tpu.cas.xorb import encode_frame

    # stored-scheme chunk (incompressible bytes): nothing to quantize
    frame, _h = encode_frame(np.random.default_rng(5).bytes(100_000))
    assert lossy.quantize_blob(frame) is None
    # LZ4 text chunk: compressible but not BG4 → decline
    frame2, _h2 = encode_frame(b'{"k": 1}' * 20_000)
    assert lossy.quantize_blob(frame2) is None
    # garbage that doesn't parse as frames
    assert lossy.quantize_blob(b"\xff" * 64) is None
    with pytest.raises(ValueError):
        lossy.dequantize_blob(b"not a container")


def test_staging_registry_and_rebase(tmp_path):
    st = lossy.staging_for(tmp_path / "zest")
    assert st is lossy.staging_for(tmp_path / "zest")
    assert st is not lossy.staging_for(tmp_path / "other")
    blob, _raws = _float_frames(n_chunks=1)
    container = lossy.quantize_blob(blob)
    st.put("ab" * 32, 4, container)
    assert st.units() == 1 and st.total_bytes() == len(container)
    got = st.get_with_range("ab" * 32, 6)  # rebase: offset 4 covers 6
    assert got == (container, 4)
    assert st.get_with_range("ab" * 32, 2) is None
    lossy.reset_stagings()
    assert lossy.staging_for(tmp_path / "zest").units() == 0


# ── Lossy serving tier (serve_chunk_range decision tree) ──


def test_serve_byte_exact_by_default_and_quantizes_on_invite(hub, owner):
    b, _recs_, _plan, _addr = owner
    hh, fi = _units(_rec_for(b, "weights.bin"))[0]
    h = hashing.hex_to_hash(hh)
    off, blob, flags = serve_chunk_range(
        b.cfg, b.cache, h, fi.range.start, fi.range.end)
    assert flags == 0 and not lossy.is_lossy_container(blob)
    # LOSSY_OK alone must NOT quantize fresh cache data
    off2, blob2, flags2 = serve_chunk_range(
        b.cfg, b.cache, h, fi.range.start, fi.range.end, FLAG_LOSSY_OK)
    assert (off2, blob2, flags2) == (off, blob, 0)
    # QUANT_OK invites quantization of the byte-exact cache hit
    off3, blob3, flags3 = serve_chunk_range(
        b.cfg, b.cache, h, fi.range.start, fi.range.end,
        FLAG_LOSSY_OK | FLAG_QUANT_OK)
    assert off3 == off
    assert flags3 & FLAG_LOSSY
    assert lossy.is_lossy_container(blob3)
    assert len(blob3) < len(blob)
    assert lossy.exact_len(blob3) == len(blob)
    # non-float payloads stay byte-exact even when invited
    ch, cfi = _units(_rec_for(b, "blob.bin"))[0]
    _o, cblob, cflags = serve_chunk_range(
        b.cfg, b.cache, hashing.hex_to_hash(ch), cfi.range.start,
        cfi.range.end, FLAG_LOSSY_OK | FLAG_QUANT_OK)
    assert cflags == 0 and not lossy.is_lossy_container(cblob)


def test_serve_forwards_staged_container_only_on_opt_in(hub, owner,
                                                       tmp_path):
    """Store-and-forward: a host holding only a staged (lossy) copy
    serves the container VERBATIM — no re-quantization compounding —
    and only to a requester that advertised FLAG_LOSSY_OK."""
    b, _recs_, _plan, _addr = owner
    hh, fi = _units(_rec_for(b, "weights.bin"))[0]
    h = hashing.hex_to_hash(hh)
    off, container, flags = serve_chunk_range(
        b.cfg, b.cache, h, fi.range.start, fi.range.end,
        FLAG_LOSSY_OK | FLAG_QUANT_OK)
    assert flags & FLAG_LOSSY

    puller = _bridge(hub, tmp_path / "staged-only")
    try:
        lossy.staging_for(puller.cfg.cache_dir).put(hh, off, container)
        # cache miss + no opt-in → NOT_FOUND (never a surprise lossy)
        assert serve_chunk_range(puller.cfg, puller.cache, h,
                                 fi.range.start, fi.range.end) is None
        got = serve_chunk_range(puller.cfg, puller.cache, h,
                                fi.range.start, fi.range.end,
                                FLAG_LOSSY_OK)
        assert got is not None
        g_off, g_blob, g_flags = got
        assert g_flags & FLAG_LOSSY
        assert g_off == off and g_blob == container, \
            "staged containers must forward byte-verbatim"
    finally:
        puller.close()


# ── Lossy end-to-end (cross-slice round, HBM-only admission) ──


def test_lossy_round_lands_hbm_only(hub, tmp_path):
    """2 hosts in different slices (every exchange link is dcn) with
    ZEST_COLLECTIVE_LOSSY=dcn: float payloads cross quantized and land
    in the staging overlay only; the xorb cache stays merkle-pure; the
    stats ledger reports the saved bits; landed floats are within the
    quantization bound; byte-exact needs heal through the waterfall."""
    bridges, results = _run_hosts(hub, tmp_path, 2, fabric=False,
                                  coop_topology=(0, 1),
                                  collective_lossy="dcn")
    want = np.frombuffer(FILES["weights.bin"], dtype="<f4")
    for i, (b, r) in enumerate(zip(bridges, results)):
        cx = r["collective"]
        assert cx["lossy"] == "dcn", cx
        assert "aborted" not in cx, cx
        ex = r["exchange"]
        assert set(ex) == _EX_KEYS | {"lossy_bytes",
                                      "bits_saved_ratio"}, sorted(ex)
        assert ex["lossy_bytes"] > 0
        assert 0.0 < ex["bits_saved_ratio"] < 1.0
        # lossy payloads landed in the staging overlay...
        st = lossy.staging_for(b.cfg.cache_dir)
        assert st.units() > 0 and st.total_bytes() > 0
        # ...and not one ZQLS byte entered the merkle-verified cache
        xorb_dir = b.cfg.cache_dir / "xorbs"
        cached = [p for p in xorb_dir.rglob("*") if p.is_file()]
        assert cached, "own share must still be cached byte-exact"
        for p in cached:
            assert not lossy.is_lossy_container(p.read_bytes()), p

        # HBM-landing view: the lossy overlay serves the foreign share
        # within the per-block quantization bound
        rec = _rec_for(b, "weights.bin")
        reader = CachedFileReader(b.cache, rec, allow_lossy=True)
        got = np.frombuffer(reader.read(0, len(FILES["weights.bin"])),
                            dtype="<f4")
        assert got.shape == want.shape
        err = np.abs(got - want)
        assert np.max(err) <= np.max(np.abs(want)) / 127.0 + 1e-6
        assert np.any(err > 0), "the lossy tier never engaged"

        # without the overlay the foreign share is simply not there —
        # a byte-exact read must go through the verified waterfall
        strict = CachedFileReader(b.cache, rec)
        with pytest.raises(DirectLandingError):
            strict.read(0, len(FILES["weights.bin"]))
        before = b.stats.bytes_from_cdn
        healed = CachedFileReader(b.cache, rec, bridge=b)
        assert healed.read(0, len(FILES["weights.bin"])) \
            == FILES["weights.bin"]
        assert b.stats.bytes_from_cdn > before, \
            "byte-exact heal must refetch, not trust lossy bytes"
        # non-float files crossed byte-exact, no heal needed
        blob_reader = CachedFileReader(b.cache,
                                       _rec_for(b, "blob.bin"))
        assert blob_reader.read(0, len(FILES["blob.bin"])) \
            == FILES["blob.bin"]


# ── preadv cold-read lane ──


def test_preadv_lane_identity_and_engagement(hub, tmp_path):
    b = _bridge(hub, tmp_path)
    try:
        warm_units_parallel(b, _recs(b))
        rec = _rec_for(b, "blob.bin")
        want = FILES["blob.bin"]

        r1 = CachedFileReader(b.cache, rec)
        got = r1.read(0, len(want))
        assert got == want
        assert r1.preadv_stats["terms"] > 0, \
            "stored-scheme cold reads must take the preadv lane"
        assert r1.preadv_stats["bytes"] > 0
        assert r1.preadv_stats["syscalls"] >= 1

        r2 = CachedFileReader(b.cache, rec, use_preadv=False)
        assert r2.read(0, len(want)) == want
        assert r2.preadv_stats == {"terms": 0, "bytes": 0,
                                   "syscalls": 0}

        # unaligned interior slice: both lanes byte-identical
        a, z = 1234, len(want) - 777
        assert CachedFileReader(b.cache, rec).read(a, z) == want[a:z]
        assert CachedFileReader(b.cache, rec,
                                use_preadv=False).read(a, z) \
            == want[a:z]
    finally:
        b.close()


def test_lossy_overlay_reader_gate(hub, tmp_path):
    """The decode overlay honors the same trust boundary as the wire:
    a staged container is readable only with allow_lossy=True (within
    the quantization bound); the default reader refuses."""
    owner_b = _bridge(hub, tmp_path / "o")
    puller = _bridge(hub, tmp_path / "p")
    try:
        from zest_tpu.cas.xorb import XorbReader

        warm_units_parallel(owner_b, _recs(owner_b))
        rec = _rec_for(puller, "weights.bin")
        st = lossy.staging_for(puller.cfg.cache_dir)
        staged = 0
        for hh, fi in _units(rec):
            entry = owner_b.cache.get_with_range(hh, fi.range.start)
            assert entry is not None
            # re-slice so the blob starts exactly at the unit's chunk
            # offset — partial cache entries are keyed by it
            lo = fi.range.start - entry.chunk_offset
            hi = fi.range.end - entry.chunk_offset
            blob = XorbReader(entry.data).slice_range(lo, hi)
            container = lossy.quantize_blob(blob)
            if container is not None:
                st.put(hh, fi.range.start, container)
                staged += 1
            else:
                puller.cache.put_partial(hh, fi.range.start, blob)
        assert staged > 0, "no weights unit quantized"
        want = np.frombuffer(FILES["weights.bin"], dtype="<f4")
        reader = CachedFileReader(puller.cache, rec, allow_lossy=True)
        got = np.frombuffer(
            reader.read(0, len(FILES["weights.bin"])), dtype="<f4")
        assert np.max(np.abs(got - want)) \
            <= np.max(np.abs(want)) / 127.0 + 1e-6
        strict = CachedFileReader(puller.cache, rec)
        with pytest.raises(DirectLandingError):
            strict.read(0, len(FILES["weights.bin"]))
    finally:
        owner_b.close()
        puller.close()
