"""``zest push`` / cas.publish contracts (ISSUE 19).

The write path promoted out of the test fixtures into production:

- :class:`cas.publish.Publisher` — CDC chunk → dedup-index → xorb-pack
  encoding: seeded base xorbs dedup byte-for-byte, minted xorbs drain
  exactly once, referencing terms point into base xorbs at builder-
  parity frame offsets;
- :func:`transfer.push.push_checkpoint` — manifest + parent lineage +
  refs bump + cache writes; content-defined revision ids (idempotent
  re-push); dedup ratio ≥ 0.9 at a contiguous 1 %-changed checkpoint;
  preview mode writes NOTHING;
- the publisher daemon surface: a second node's unmodified
  ``pull_model``, pointed at the daemon as its endpoint, reassembles
  the pushed revision byte-identically; ``POST /v1/watch`` streams the
  ``/v1/push`` notification (and 404s when ``ZEST_WATCH=0``).
"""

import json
import threading
import time

import numpy as np
import pytest

from zest_tpu.api.http_api import HttpApi, WatchHub
from zest_tpu.cas.publish import Publisher, is_xet_path
from zest_tpu.cas.xorb import XorbReader
from zest_tpu.config import Config
from zest_tpu.transfer import delta
from zest_tpu.transfer import push as push_mod
from zest_tpu import storage

REPO = "acme/push"


def _cfg(root, **kw):
    return Config(hf_home=root / "hf", cache_dir=root / "zest",
                  hf_token="hf_test", **kw)


def _quiet(*a, **k):
    pass


def _weights(n=4_000_000, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()


def _mutated(data: bytes, fraction=0.01, at=1_000_000) -> bytes:
    """A contiguous ``fraction`` of bytes flipped — the shape real
    1 %-changed checkpoints have (whole tensors change; scattered
    single-byte noise would dirty every CDC chunk by construction)."""
    buf = bytearray(data)
    for i in range(at, at + int(len(buf) * fraction)):
        buf[i] ^= 0xFF
    return bytes(buf)


def _checkpoint(root, name, weights):
    d = root / name
    d.mkdir()
    (d / "model.safetensors").write_bytes(weights)
    (d / "config.json").write_text(json.dumps({"hidden": 64}))
    return d


# ── Publisher (the promoted encoder) ──


def test_publisher_seeded_base_dedups_everything():
    w = _weights(1_500_000)
    first = Publisher()
    pf = first.publish_file("model.safetensors", w)
    minted = first.drain_new_xorbs()
    assert minted and first.drain_new_xorbs() == []  # drain-once
    # Second encoder seeded with the first's xorbs: identical bytes
    # become 100% referencing terms — zero new xorbs.
    second = Publisher()
    for px in minted:
        r = XorbReader(px.blob)
        second.seed_xorb(px.hash_hex, r.frame_offsets(), r.chunk_hashes())
    pf2 = second.publish_file("model.safetensors", w)
    assert second.drain_new_xorbs() == []
    assert pf2.new_bytes == 0 and pf2.reused_bytes == len(w)
    assert pf2.dedup_ratio == 1.0
    assert pf2.xet_hash == pf.xet_hash  # same content, same identity
    # Referencing terms point into the SEEDED xorbs at builder-parity
    # frame offsets (what fetch_info byte ranges are built from).
    seeded = {px.hash_hex for px in minted}
    assert {t.hash_hex for t in pf2.reconstruction.terms} <= seeded


def test_is_xet_path_suffixes():
    assert is_xet_path("model.safetensors")
    assert is_xet_path("sub/dir/weights.bin")
    assert not is_xet_path("config.json")
    assert not is_xet_path("tokenizer.model")


# ── push_checkpoint: durable writes + lineage + idempotence ──


def test_push_first_revision_lands_everything(tmp_path):
    cfg = _cfg(tmp_path)
    ckpt = _checkpoint(tmp_path, "ckpt", _weights())
    res = push_mod.push_checkpoint(cfg, REPO, ckpt, notify=False,
                                   log=_quiet)
    assert res.parent is None and len(res.revision) == 40
    assert res.manifest_written
    assert res.new_xorbs >= 1 and res.xorb_digests
    # Ref, manifest, snapshot, and cache all agree.
    assert storage.read_ref(cfg, REPO, "main") == res.revision
    man = delta.load_manifest(cfg, REPO, res.revision)
    assert man and "model.safetensors" in man["files"]
    assert "parent" not in man
    snap = cfg.model_snapshot_dir(REPO, res.revision)
    assert (snap / "model.safetensors").stat().st_size == 4_000_000
    cache = storage.XorbCache(cfg)
    for hex_ in res.xorb_digests:
        assert cache.has(hex_)


def test_push_dedups_against_base_and_is_idempotent(tmp_path):
    cfg = _cfg(tmp_path)
    w = _weights()
    a = push_mod.push_checkpoint(
        cfg, REPO, _checkpoint(tmp_path, "a", w), notify=False,
        log=_quiet)
    ckpt_b = _checkpoint(tmp_path, "b", _mutated(w))
    b = push_mod.push_checkpoint(cfg, REPO, ckpt_b, notify=False,
                                 log=_quiet)
    assert b.parent == a.revision
    assert b.seeded_base_xorbs >= 1
    assert b.dedup_ratio >= 0.90  # the ISSUE 19 headline gate
    man = delta.load_manifest(cfg, REPO, b.revision)
    assert man["parent"] == a.revision
    # Content-defined revision id: re-pushing the same bytes over the
    # same parent is the SAME revision (trainer retry safety)...
    b2 = push_mod.push_checkpoint(cfg, REPO, ckpt_b, notify=False,
                                  log=_quiet)
    assert b2.revision == b.revision
    # ...and with the base now cached, every chunk dedups.
    assert b2.new_xorbs == 0 and b2.dedup_ratio == 1.0
    # The next publish's base selection walks the lineage to B.
    assert delta.find_base_manifest(
        cfg, REPO, "f" * 40)["revision"] == b.revision


def test_preview_writes_nothing(tmp_path):
    cfg = _cfg(tmp_path)
    ckpt = _checkpoint(tmp_path, "ckpt", _weights(1_000_000))
    out = push_mod.preview_push(cfg, REPO, ckpt)
    assert out["preview"] and out["new_xorbs"] >= 1
    assert not delta.manifest_dir(cfg).exists() or \
        not list(delta.manifest_dir(cfg).iterdir())
    assert storage.read_ref(cfg, REPO, "main") is None
    assert storage.list_cached_xorbs(cfg) == []


# ── The publisher daemon surface + fan-out ──


@pytest.fixture()
def served(tmp_path):
    cfg = _cfg(tmp_path, http_port=0)
    api = HttpApi(cfg)
    port = api.start()
    cfg.http_port_file().parent.mkdir(parents=True, exist_ok=True)
    cfg.http_port_file().write_text(str(port))
    try:
        yield cfg, api, f"http://127.0.0.1:{port}", tmp_path
    finally:
        api.close()


def test_second_node_pull_reassembles_pushed_revision(served):
    from zest_tpu.transfer.pull import pull_model

    cfg, api, url, root = served
    w = _weights()
    push_mod.push_checkpoint(cfg, REPO, _checkpoint(root, "a", w),
                             notify=False, log=_quiet)
    w_b = _mutated(w)
    b = push_mod.push_checkpoint(cfg, REPO, _checkpoint(root, "b", w_b),
                                 notify=False, log=_quiet)
    cfg2 = Config(hf_home=root / "hf2", cache_dir=root / "zest2",
                  hf_token="hf_test", endpoint=url)
    res = pull_model(cfg2, REPO, revision="main", no_p2p=True, log=_quiet)
    snap = res.snapshot_dir
    assert (snap / "model.safetensors").read_bytes() == w_b
    assert json.loads((snap / "config.json").read_text()) == {"hidden": 64}
    assert res.stats["revision"] == b.revision


def test_watch_stream_delivers_push_notification(served):
    cfg, api, url, root = served
    events: list[dict] = []

    def subscriber():
        for ev in push_mod.watch_events(url, repos=[REPO], timeout_s=30):
            events.append(ev)
            if ev.get("event") == "revision":
                return

    t = threading.Thread(target=subscriber, daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    while api.watch_hub.watchers() == 0 and time.monotonic() < deadline:
        time.sleep(0.02)
    res = push_mod.push_checkpoint(
        cfg, REPO, _checkpoint(root, "ckpt", _weights(1_000_000)),
        log=_quiet)
    assert res.notified and res.notified["delivered"] == 1
    t.join(timeout=10)
    assert [e["event"] for e in events] == ["hello", "revision"]
    ev = events[-1]
    assert ev["revision"] == res.revision
    assert ev["repo"] == REPO and isinstance(ev["pushed_at"], float)


def test_watch_hub_filters_by_repo():
    hub = WatchHub()
    got: list[dict] = []

    def run():
        for ev in hub.subscribe(repos=["acme/wanted"], ping_s=30):
            got.append(ev)
            if len(got) >= 2:
                return

    t = threading.Thread(target=run, daemon=True)
    t.start()
    deadline = time.monotonic() + 5
    while hub.watchers() == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert hub.notify({"event": "revision", "repo": "acme/other"}) == 0
    assert hub.notify({"event": "revision", "repo": "acme/wanted"}) == 1
    t.join(timeout=5)
    assert got[0]["event"] == "hello"
    assert got[1]["repo"] == "acme/wanted"
    deadline = time.monotonic() + 5
    while hub.watchers() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert hub.watchers() == 0  # subscriber unregistered on exit


def test_watch_disabled_answers_404(tmp_path):
    import urllib.error
    import urllib.request

    cfg = _cfg(tmp_path, http_port=0, watch_enabled=False)
    api = HttpApi(cfg)
    port = api.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/watch", data=b"{}",
            headers={"Content-Type": "application/json"}, method="POST")
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=10)
        assert exc_info.value.code == 404
    finally:
        api.close()
